#!/usr/bin/env python
"""ResNeXt-50 example (reference examples/cpp/resnext50)."""

from common import parse_config, train_synthetic

from flexflow_tpu.models import ResNeXtConfig, create_resnext50


def main():
    cfg = parse_config()
    rc = ResNeXtConfig(
        batch_size=cfg.batch_size if cfg.batch_size_explicit else 16)
    cfg.batch_size = rc.batch_size
    ff = create_resnext50(rc, cfg)
    train_synthetic(ff, cfg, [((3, rc.image_size, rc.image_size), "float32", 0)],
                    (1,), classes=rc.num_classes)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""XDL example (reference examples/cpp/XDL)."""

from common import parse_config, train_synthetic

from flexflow_tpu.models import XDLConfig, create_xdl


def main():
    cfg = parse_config()
    xc = XDLConfig(batch_size=cfg.batch_size)
    ff = create_xdl(xc, cfg)
    specs = [((xc.embedding_bag_size,), "int32", v) for v in xc.embedding_size]
    train_synthetic(ff, cfg, specs, (1,), classes=2)


if __name__ == "__main__":
    main()

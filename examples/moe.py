#!/usr/bin/env python
"""Mixture-of-Experts example (reference examples/cpp/mixture_of_experts)."""

from common import parse_config, train_synthetic

from flexflow_tpu import AdamOptimizer
from flexflow_tpu.models import MoEConfig, create_moe


def main():
    cfg = parse_config()
    mc = MoEConfig(batch_size=cfg.batch_size)
    ff = create_moe(mc, cfg)
    train_synthetic(ff, cfg, [((mc.input_dim,), "float32", 0)], (1,),
                    classes=mc.num_classes,
                    optimizer=AdamOptimizer(alpha=1e-3))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""CANDLE-Uno example (reference examples/cpp/candle_uno)."""

from common import parse_config, train_synthetic

from flexflow_tpu import LossType, MetricsType
from flexflow_tpu.models import CandleUnoConfig, create_candle_uno


def main():
    cfg = parse_config()
    cc = CandleUnoConfig(batch_size=cfg.batch_size)
    ff = create_candle_uno(cc, cfg)
    specs = [((d,), "float32", 0) for d in cc.input_features.values()]
    train_synthetic(ff, cfg, specs, (1,),
                    loss=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
                    metrics=(MetricsType.MEAN_SQUARED_ERROR,))


if __name__ == "__main__":
    main()

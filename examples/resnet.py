#!/usr/bin/env python
"""ResNet-50 example (reference examples/cpp/ResNet)."""

from common import parse_config, train_synthetic

from flexflow_tpu.models import ResNetConfig, create_resnet


def main():
    cfg = parse_config()
    rc = ResNetConfig(batch_size=cfg.batch_size)
    ff = create_resnet(rc, cfg)
    train_synthetic(ff, cfg, [((3, rc.image_size, rc.image_size), "float32", 0)],
                    (1,), classes=rc.num_classes)


if __name__ == "__main__":
    main()

"""Shared driver glue for the example programs.

Mirrors the reference examples' top_level_task pattern (e.g.
examples/cpp/Transformer/transformer.cc:105-211): parse FFConfig flags,
build the model, generate synthetic data, run the epochs/iterations loop,
print `ELAPSED TIME = .. THROUGHPUT = .. samples/s` (the metric the
osdi22ae scripts grep)."""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu import (AdamOptimizer, FFConfig, LossType, MetricsType,
                          SGDOptimizer)


def parse_config(argv=None) -> FFConfig:
    cfg = FFConfig()
    rest = cfg.parse_args(argv if argv is not None else sys.argv[1:])
    cfg._rest = rest
    return cfg


def train_synthetic(ff, cfg: FFConfig, input_specs, label_shape,
                    loss=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                    metrics=(MetricsType.ACCURACY,), classes=None,
                    optimizer=None, iterations=None):
    """input_specs: list of (shape_without_batch, dtype, high) tuples."""
    ff.compile(optimizer or SGDOptimizer(lr=cfg.learning_rate), loss,
               list(metrics))
    axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
    print(f"mesh: {axes}" + (
        f"  search: predicted {ff.search_info['predicted_time'] * 1e3:.3f} ms"
        if ff.search_info else "  (data-parallel default)"))
    bs = ff.input_tensors[0].shape[0]
    iters = iterations or max(cfg.iterations, 4)
    rs = np.random.RandomState(cfg.seed)
    xs = []
    for shape, dtype, high in input_specs:
        if np.issubdtype(np.dtype(dtype), np.integer):
            xs.append(rs.randint(0, high, (bs,) + tuple(shape)).astype(dtype))
        else:
            xs.append(rs.randn(bs, *shape).astype(dtype))
    if classes:
        y = rs.randint(0, classes, label_shape and (bs,) + tuple(label_shape)
                       or (bs, 1)).astype(np.int32)
    else:
        y = rs.randn(bs, *label_shape).astype(np.float32)

    ff.set_batch(xs if len(xs) > 1 else xs[0], y)
    ff.forward(); ff.backward(); ff.update()  # warmup / compile
    start = time.time()
    for _ in range(iters):
        ff.forward()
        ff.zero_gradients()
        ff.backward()
        ff.update()
    float(ff._last_loss)  # sync
    elapsed = time.time() - start
    thr = bs * iters / elapsed
    print(f"ELAPSED TIME = {elapsed:.4f}s, THROUGHPUT = {thr:.2f} samples/s")
    return thr

#!/usr/bin/env python
"""Inception-v3 example (reference examples/cpp/InceptionV3)."""

from common import parse_config, train_synthetic

from flexflow_tpu.models import InceptionConfig, create_inception_v3


def main():
    cfg = parse_config()
    ic = InceptionConfig(batch_size=cfg.batch_size)
    ff = create_inception_v3(ic, cfg)
    train_synthetic(ff, cfg, [((3, ic.image_size, ic.image_size), "float32", 0)],
                    (1,), classes=ic.num_classes)


if __name__ == "__main__":
    main()

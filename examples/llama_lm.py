#!/usr/bin/env python
"""Llama-family decoder LM example (new scope vs the reference zoo):
RMSNorm + RoPE + grouped-query attention + SwiGLU, token-level CE.

Run tiny:   python examples/llama_lm.py -b 8 --budget 3 --enable-parameter-parallel
Llama-3-8B shapes (compile-scale check): --llama3-8b
"""

import sys

from common import parse_config, train_synthetic

from flexflow_tpu import LossType, MetricsType
from flexflow_tpu.models import LlamaModelConfig, create_llama


def main():
    cfg = parse_config()
    if "--llama3-8b" in cfg._rest:
        mcfg = LlamaModelConfig(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32,
            num_key_value_heads=8, rope_theta=500000.0,
            batch_size=cfg.batch_size, seq_length=512)
    else:
        mcfg = LlamaModelConfig(vocab_size=512, hidden_size=128,
                                intermediate_size=256, num_hidden_layers=4,
                                num_attention_heads=8, num_key_value_heads=4,
                                batch_size=cfg.batch_size, seq_length=64)
    ff = create_llama(mcfg, cfg)
    train_synthetic(
        ff, cfg, [((mcfg.seq_length,), "int32", mcfg.vocab_size)],
        (mcfg.seq_length,), loss=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=(), classes=mcfg.vocab_size)


if __name__ == "__main__":
    main()

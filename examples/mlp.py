#!/usr/bin/env python
"""MLP example (reference examples/cpp/MLP_Unify): deep wide MLP —
the column/row-parallel showcase."""

from common import parse_config, train_synthetic

from flexflow_tpu import LossType, MetricsType
from flexflow_tpu.models import create_mlp


def main():
    cfg = parse_config()
    hidden = [4096, 4096, 4096, 4096]
    ff = create_mlp(cfg.batch_size, 1024, hidden, 10, ff_config=cfg)
    train_synthetic(ff, cfg, [((1024,), "float32", 0)], (1,), classes=10)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""DLRM example (reference examples/cpp/DLRM): parameter-parallel
embedding tables + bottom/top MLPs."""

import numpy as np

from common import parse_config, train_synthetic

from flexflow_tpu import LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models import DLRMConfig, create_dlrm


def main():
    cfg = parse_config()
    dc = DLRMConfig(batch_size=cfg.batch_size)
    ff = create_dlrm(dc, cfg)
    specs = [((dc.indices_per_feature,), "int32", dc.vocab_size)
             for _ in range(dc.num_sparse_features)]
    specs.append(((dc.dense_dim,), "float32", 0))
    train_synthetic(ff, cfg, specs, (1,),
                    loss=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
                    metrics=(MetricsType.MEAN_SQUARED_ERROR,),
                    optimizer=SGDOptimizer(lr=0.01))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Multi-host (multi-controller) training example.

Analog of the reference's multinode launch (mpirun -np N with GASNet
conduits, tests/multinode_helpers/mpi_wrapper1.sh): launch ONE driver
process per host, each pointing at the same coordinator —

    # host 0                                           # host 1
    python -m flexflow_tpu.driver --nodes 2 \\
        --coordinator-address host0:9876 --node-rank 0 \\   ... --node-rank 1 \\
        examples/multihost_train.py

(on a real TPU pod, `--nodes`/`--coordinator-address`/`--node-rank` are
auto-detected and may be omitted). Every process executes the same
program over one global mesh spanning all hosts; each feeds only its own
batch rows — `fit(x, y)` takes the PROCESS-LOCAL shard.

Local 2-process demo without hardware (4 virtual CPU devices per
process, gloo collectives):

    FLEXFLOW_DEMO_CPU=1 FLEXFLOW_NUM_NODES=2 FLEXFLOW_NODE_RANK=0 \\
        FLEXFLOW_COORDINATOR=localhost:9876 python examples/multihost_train.py &
    FLEXFLOW_DEMO_CPU=1 FLEXFLOW_NUM_NODES=2 FLEXFLOW_NODE_RANK=1 \\
        FLEXFLOW_COORDINATOR=localhost:9876 python examples/multihost_train.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    if os.environ.get("FLEXFLOW_DEMO_CPU"):
        os.environ.pop("JAX_PLATFORMS", None)
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 4)
    import jax

    from flexflow_tpu import (FFConfig, LossType, MetricsType, SGDOptimizer,
                              distributed)
    from flexflow_tpu.models import TransformerConfig, create_transformer

    # rendezvous (no-op when the driver already initialized, or when the
    # run is single-process)
    distributed.initialize_from_config(FFConfig())
    n_proc, rank = distributed.process_count(), distributed.process_index()
    n_dev = jax.device_count()
    print(f"[host {rank}/{n_proc}] global devices: {n_dev}")

    global_batch = 4 * n_dev
    tc = TransformerConfig(num_layers=2, hidden_size=64, num_heads=4,
                           seq_length=32, batch_size=global_batch)
    ff = create_transformer(tc, FFConfig(batch_size=global_batch))
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               [MetricsType.MEAN_SQUARED_ERROR])

    # each host generates ITS rows of the (synthetic) global dataset
    rows = global_batch // n_proc * 4  # 4 batches worth per host
    rs = np.random.RandomState(rank)
    x = rs.randn(rows, tc.seq_length, tc.hidden_size).astype(np.float32)
    y = rs.randn(rows, tc.seq_length, 1).astype(np.float32)
    ff.fit(x, y, epochs=2, verbose=(rank == 0))
    if rank == 0:
        print(f"multihost training ok: {n_proc} hosts x "
              f"{n_dev // max(n_proc, 1)} devices, loss {ff._last_loss:.5f}")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""AlexNet example (reference examples/cpp/AlexNet)."""

from common import parse_config, train_synthetic

from flexflow_tpu.models import create_alexnet


def main():
    cfg = parse_config()
    ff = create_alexnet(cfg.batch_size, ff_config=cfg)
    shape = ff.input_tensors[0].shape[1:]
    train_synthetic(ff, cfg, [(shape, "float32", 0)], (1,), classes=10)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""BERT-proxy Transformer example (reference examples/cpp/Transformer).

Reference config: 12 layers, hidden 1024, 16 heads, seq 512, batch 8
(transformer.cc:79-84). Usage:
    python examples/transformer.py --budget 30 [-b 8] [--epochs 1]
    python examples/transformer.py --only-data-parallel
"""

from common import parse_config, train_synthetic

from flexflow_tpu import AdamOptimizer, LossType, MetricsType
from flexflow_tpu.models import TransformerConfig, create_transformer


def main():
    cfg = parse_config()
    tc = TransformerConfig(
        batch_size=cfg.batch_size if cfg.batch_size_explicit else 8)
    cfg.batch_size = tc.batch_size
    ff = create_transformer(tc, cfg)
    train_synthetic(
        ff, cfg,
        [((tc.seq_length, tc.hidden_size), "float32", 0)],
        (tc.seq_length, 1),
        loss=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=(MetricsType.MEAN_SQUARED_ERROR,),
        optimizer=AdamOptimizer(alpha=1e-4),
    )


if __name__ == "__main__":
    main()

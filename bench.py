#!/usr/bin/env python
"""Benchmark zoo: training throughput on the chip for 5 workload families.

Headline metric follows the reference's OSDI'22 AE BERT benchmark
(scripts/osdi22ae/bert.sh + examples/cpp/Transformer/transformer.cc:79-84):
12 layers, hidden 1024, 16 heads, seq 512, batch 8 per chip; metric is
training samples/s (fwd+bwd+update, jitted). Three more mirror the
rest of the AE protocol on one chip (scripts/osdi22ae/{inception,dlrm}.sh
+ examples/cpp/mixture_of_experts): a conv family, an embedding-heavy
recsys model, and a MoE; the fifth is a pipelined transformer on a
pipe x data mesh (PipelineGraphExecutor — on CPU via 8 virtual host
devices) — so executor changes can't regress a family unnoticed
(VERDICT r4 Missing #2). Prints ONE JSON line.

vs_baseline: ratio against the recorded best from previous rounds
(bench_history.json, keyed per workload), 1.0 on first run — the
reference repo publishes no absolute numbers (BASELINE.md).
"""

import dataclasses
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))


def ensure_virtual_host_devices(n: int = 8) -> None:
    """Give the CPU backend ``n`` virtual host devices BEFORE jax
    initializes (harmless on TPU — the flag only affects the host
    platform). The ONE bootstrap shared by bench main/serve and
    scripts/serve_bench.py; call before the first ``import jax``."""
    if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}")


def single_device_mesh_on_cpu(on_cpu):
    """Explicit 1-device mesh for the legacy workload families on CPU:
    main() forces 8 virtual host devices so the pipeline workload has a
    pipe x data mesh, but the single-device CPU protocol (census = 0 B,
    unsharded HBM peak) is what their ratchet history records — the
    virtual devices must not silently turn them data-parallel. On TPU
    (None) they keep using every visible chip as before."""
    if not on_cpu:
        return None
    from flexflow_tpu.machine import make_mesh
    return make_mesh(1, {"data": 1})


def time_train(ff, xs, y, iters, windows, tracer=None, capture=None):
    """Steady-state training samples/s: jitted fwd+bwd+update loop.

    Plain per-step dispatch, NOT lax.scan — measured r3 (30 iters, v5e):
    async dispatch pipelines better than the fused scan (160.35 vs
    156.46 samples/s), so the plain loop is both the honest protocol and
    the faster one. float(loss) forces a device->host sync — on the
    tunneled TPU backend block_until_ready alone does not. Best-of-N
    windows because the tunnel occasionally stalls for hundreds of ms.

    ``tracer`` (an active obs StepTracer) wraps each step in a span
    WITHOUT per-step fencing — the protocol's async pipelining is the
    thing being measured, so spans record dispatch cadence, and the
    window's host fetch is the only sync. None (the default) leaves the
    loop untouched.

    ``capture`` (an obs DeviceTraceCapture) wraps the WARMUP steps only
    — the windowed profiler session runs on post-compile warmup steps
    (window "1:3"), so the device-time attribution (exposed_comms_frac,
    the overlap direction's coordinate) is measured without perturbing
    the throughput windows.

    Returns ``(samples_per_s, step_samples)`` where ``step_samples`` are
    the per-step dispatch intervals (perf_counter deltas) of every
    measured window — in the steady state the async pipeline backs up on
    the device queue, so their distribution tracks device step time;
    main() reports their p50/p99 next to the throughput number
    (informational, no ratchet).
    """
    import jax
    import jax.random as jrandom

    train_step = ff.executor.make_train_step()
    inputs = ff._stage_inputs(xs)
    labels = ff._shard_batch(y)

    def step(params, opt_state, state, rng):
        rng, sub = jrandom.split(rng)
        params, opt_state, state, loss, _ = train_step(
            params, opt_state, state, inputs, labels, sub)
        return params, opt_state, state, rng, loss

    if tracer is not None and tracer.active:
        _raw_step = step

        def step(params, opt_state, state, rng):
            with tracer.step():
                with tracer.phase("dispatch"):
                    return _raw_step(params, opt_state, state, rng)

    params, opt_state, state = ff.params, ff.opt_state, ff.state
    rng = jrandom.PRNGKey(0)
    # warmup (compile; a second round catches the donation-aliased recompile)
    for i in range(3):
        if capture is not None:
            with capture.step(i):
                params, opt_state, state, rng, loss = step(
                    params, opt_state, state, rng)
                jax.block_until_ready(loss)  # device spans inside window
        else:
            params, opt_state, state, rng, loss = step(params, opt_state,
                                                       state, rng)
    float(loss)
    bs = ff.input_tensors[0].shape[0]
    best_dt = None
    final_loss = None
    step_samples = []
    for _ in range(windows):
        t0 = time.perf_counter()
        prev = t0
        for _ in range(iters):
            params, opt_state, state, rng, loss = step(params, opt_state,
                                                       state, rng)
            now = time.perf_counter()
            step_samples.append(now - prev)
            prev = now
        final_loss = float(loss)  # sync: depends on the whole step chain
        dt = time.perf_counter() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)
    assert np.isfinite(final_loss), f"training diverged: loss={final_loss}"
    return bs * iters / best_dt, step_samples


# ---------------------------------------------------------------------------
# workload builders: name -> (ff, xs, y, config_dict)


def build_bert_proxy(on_cpu):
    import jax.numpy as jnp

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType, MetricsType
    from flexflow_tpu.models.transformer import (TransformerConfig,
                                                 create_transformer)
    from flexflow_tpu.optimizers import AdamOptimizer

    cfg = (TransformerConfig(num_layers=2, hidden_size=128, num_heads=4,
                             seq_length=64, batch_size=8)
           if on_cpu else TransformerConfig())  # reference config on TPU
    # TPU-native optimizer configuration: bf16 m/v storage (update math is
    # f32 — optimizers.py). The update phase is HBM-bound (measured r4,
    # scripts/measure_bw.py: ~620 GB/s marginal, so bytes are the lever);
    # bf16 state cuts its traffic 29%. Convergence parity with f32 state is
    # asserted by tests/test_model_training.py::test_adam_bf16_state.
    ff = create_transformer(cfg, FFConfig(batch_size=cfg.batch_size))
    ff.compile(AdamOptimizer(alpha=1e-4, state_dtype=jnp.bfloat16),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               [MetricsType.MEAN_SQUARED_ERROR],
               mesh=single_device_mesh_on_cpu(on_cpu))
    rs = np.random.RandomState(0)
    x = rs.randn(cfg.batch_size, cfg.seq_length,
                 cfg.hidden_size).astype(np.float32)
    y = rs.randn(cfg.batch_size, cfg.seq_length, 1).astype(np.float32)
    return ff, [x], y, dataclasses.asdict(cfg)


def build_inception_proxy(on_cpu):
    import jax.numpy as jnp

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType
    from flexflow_tpu.models.inception import (InceptionConfig,
                                               create_inception_v3)
    from flexflow_tpu.optimizers import AdamOptimizer

    # reference AE: batch 64 across 4 GPUs (scripts/osdi22ae/inception.sh);
    # one-chip proxy keeps the full v3 topology at batch 16
    cfg = (InceptionConfig(batch_size=2, image_size=75, num_classes=10,
                           reduced=True)
           if on_cpu else
           InceptionConfig(batch_size=16, image_size=299, num_classes=1000))
    ff = create_inception_v3(cfg, FFConfig(batch_size=cfg.batch_size))
    ff.compile(AdamOptimizer(alpha=1e-4, state_dtype=jnp.bfloat16),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [],
               mesh=single_device_mesh_on_cpu(on_cpu))
    rs = np.random.RandomState(0)
    x = rs.randn(cfg.batch_size, 3, cfg.image_size,
                 cfg.image_size).astype(np.float32)
    y = rs.randint(0, cfg.num_classes,
                   (cfg.batch_size, 1)).astype(np.int32)
    return ff, [x], y, dataclasses.asdict(cfg)


def build_dlrm(on_cpu):
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType
    from flexflow_tpu.models.dlrm import DLRMConfig, create_dlrm
    from flexflow_tpu.optimizers import SGDOptimizer

    # reference AE config family (examples/cpp/DLRM/dlrm.cc defaults,
    # run_random.sh: sparse-feature-size 64, embedding-bag-size 1):
    # embedding-table traffic dominates — the parameter-parallel showcase
    cfg = (DLRMConfig(batch_size=32, num_sparse_features=4,
                      vocab_size=1000, embedding_dim=16)
           if on_cpu else
           DLRMConfig(batch_size=2048, num_sparse_features=8,
                      vocab_size=1000000, embedding_dim=64))
    ff = create_dlrm(cfg, FFConfig(batch_size=cfg.batch_size))
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
               mesh=single_device_mesh_on_cpu(on_cpu))
    rs = np.random.RandomState(0)
    xs = []
    for name in ff.executor.input_names:
        if name.startswith("sparse"):
            xs.append(rs.randint(0, cfg.vocab_size,
                                 (cfg.batch_size,
                                  cfg.indices_per_feature)).astype(np.int32))
        else:
            xs.append(rs.randn(cfg.batch_size,
                               cfg.dense_dim).astype(np.float32))
    y = rs.randint(0, 2, (cfg.batch_size, 1)).astype(np.float32)
    return ff, xs, y, dataclasses.asdict(cfg)


def build_moe(on_cpu):
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType
    from flexflow_tpu.models.moe_model import MoEConfig, create_moe
    from flexflow_tpu.optimizers import SGDOptimizer

    # reference moe.cc defaults scaled to saturate one chip: top-2 of 16
    # experts over a 1024-wide hidden
    cfg = (MoEConfig(batch_size=32, input_dim=64, num_exp=4, num_select=2,
                     hidden_size=32)
           if on_cpu else
           MoEConfig(batch_size=1024, input_dim=1024, num_exp=16,
                     num_select=2, hidden_size=1024, num_classes=1000))
    ff = create_moe(cfg, FFConfig(batch_size=cfg.batch_size))
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [],
               mesh=single_device_mesh_on_cpu(on_cpu))
    rs = np.random.RandomState(0)
    x = rs.randn(cfg.batch_size, cfg.input_dim).astype(np.float32)
    y = rs.randint(0, cfg.num_classes, (cfg.batch_size, 1)).astype(np.int32)
    return ff, [x], y, dataclasses.asdict(cfg)


def build_pipeline_transformer(on_cpu):
    """Pipelined transformer (pp >= 2): the only workload exercising
    PipelineGraphExecutor, so the hbm_peak_bytes / collective_bytes
    ratchets cover the pipeline path (sharded microbatch queue, circular
    schedule, WUS at pp > 1). On CPU the 8 virtual host devices (main()
    sets --xla_force_host_platform_device_count before jax initializes)
    provide the pipe x data mesh; on a real slice the physical chips do."""
    import jax
    import jax.numpy as jnp

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType
    from flexflow_tpu.machine import make_mesh
    from flexflow_tpu.models.transformer import (TransformerConfig,
                                                 create_transformer)
    from flexflow_tpu.optimizers import AdamOptimizer

    ndev = len(jax.devices())
    if ndev < 2:
        raise RuntimeError(
            f"pipeline workload needs >= 2 devices, have {ndev}")
    pp = 4 if ndev >= 8 else 2
    dp = 2 if ndev >= 2 * pp else 1
    mesh = make_mesh(pp * dp, {"pipe": pp, "data": dp})
    cfg = (TransformerConfig(num_layers=2 * pp, hidden_size=64, num_heads=4,
                             seq_length=32, batch_size=8 * dp * pp)
           if on_cpu else
           TransformerConfig(num_layers=4 * pp, hidden_size=1024,
                             num_heads=16, seq_length=512,
                             batch_size=8 * dp * pp))
    c = FFConfig(batch_size=cfg.batch_size)
    c.pipeline_microbatches = 2 * pp
    ff = create_transformer(cfg, c)
    ff.compile(AdamOptimizer(alpha=1e-4, state_dtype=jnp.bfloat16),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [], mesh=mesh)
    # block-level rematerialization (ISSUE 20): the searched pipeline
    # 'remat' bit, engaged here so the family's hbm_peak_bytes ratchet
    # records the remat footprint (measured 36% of the remat-less peak
    # on the CPU config — the backward holds ONE block interior instead
    # of every in-flight microbatch's). Step values stay in the last-ulp
    # parity class of the remat-less step (XLA re-fuses the recomputed
    # interior; tests/test_remat.py::test_pipeline_body_remat_parity_-
    # at_pp2 bounds the drift); FFS_NO_REMAT opts out bit-identically,
    # mirroring the search-side switch.
    if not os.environ.get("FFS_NO_REMAT"):
        ff.executor.body_remat = True
    rs = np.random.RandomState(0)
    x = rs.randn(cfg.batch_size, cfg.seq_length,
                 cfg.hidden_size).astype(np.float32)
    y = rs.randn(cfg.batch_size, cfg.seq_length, 1).astype(np.float32)
    out_cfg = dataclasses.asdict(cfg)
    out_cfg.update(pipe=pp, data=dp, microbatches=c.pipeline_microbatches,
                   schedule=ff.executor.schedule,
                   body_remat=ff.executor.body_remat)
    return ff, [x], y, out_cfg


def build_longcontext_transformer(on_cpu):
    """Long-context attention at seq 2048 (ISSUE 20), DEVICELESS: the
    workload is never timed — its coordinates are the compile-determined
    ratchets (hbm_peak_bytes, dispatch_count, collective_bytes) from
    XLA's memory analysis, so it runs in seconds even though an
    interpret-mode flash step would take minutes on CPU. It pins the
    winning remat x kernel composition for long contexts, the lattice
    point ``_k:flash_r``: flash never materializes the O(seq^2) score
    interior, and remat then frees the boundary activations too —
    remat of the EINSUM attention alone cannot cut the peak (the
    recompute re-materializes the same interior at backward time;
    tests/test_remat.py::test_long_context_attention_hbm_peak_at_seq_2k
    asserts the same composition). FFS_NO_REMAT leaves the flash
    lowering but drops the checkpoint, exactly like the executor's
    opt-out."""
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType
    from flexflow_tpu.model import FFModel
    from flexflow_tpu.optimizers import SGDOptimizer

    if on_cpu:
        # the pallas flash kernel needs the interpreter off-TPU; on a
        # real chip the compiled kernel runs as-is
        os.environ.setdefault("FLEXFLOW_TPU_PALLAS", "interpret")
    seq, hidden, layers = 2048, 32, 2
    cfg = FFConfig(batch_size=2, seed=42)
    ff = FFModel(cfg)
    x = ff.create_tensor((2, seq, hidden), name="x")
    t = x
    for i in range(layers):
        t = ff.multihead_attention(t, t, t, hidden, 2, name=f"attn{i}")
    ff.dense(t, hidden, name="fc")
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
               mesh=single_device_mesh_on_cpu(on_cpu))
    attn = {f"attn{i}" for i in range(layers)}
    for n in ff.executor.nodes:
        if n.op.name in attn:
            n.op.kernel_impl = "flash"
    if not os.environ.get("FFS_NO_REMAT"):
        ff.executor.remat_ops = attn
    rs = np.random.RandomState(0)
    xv = rs.randn(2, seq, hidden).astype(np.float32)
    y = rs.randn(2, seq, hidden).astype(np.float32)
    cfg_dict = dict(seq_length=seq, hidden_size=hidden, num_layers=layers,
                    batch_size=2, kernel="flash",
                    remat=not os.environ.get("FFS_NO_REMAT"))
    return ff, [xv], y, cfg_dict


def build_multislice_transformer(on_cpu):
    """Multi-slice transformer (2 slices x 4 chips), deviceless on CPU:
    the 8 virtual host devices stand in for two DCN-connected slices.
    ``--slices 2`` splits the flat data mesh into ('slice', 'data') in
    model.compile, so the gradient sync crosses the slice boundary and
    the fabric-split census (collectives_by_fabric) attributes its bytes
    to DCN — the ``dcn_bytes`` coordinate this workload records. On a
    real multi-slice deployment the physical DCN carries the same
    collectives; here the numbers are compile-determined, not timed."""
    import jax
    import jax.numpy as jnp

    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType
    from flexflow_tpu.machine import make_mesh
    from flexflow_tpu.models.transformer import (TransformerConfig,
                                                 create_transformer)
    from flexflow_tpu.optimizers import AdamOptimizer

    ndev = len(jax.devices())
    if ndev < 8:
        raise RuntimeError(
            f"multislice workload needs >= 8 devices, have {ndev}")
    cfg = (TransformerConfig(num_layers=2, hidden_size=128, num_heads=4,
                             seq_length=64, batch_size=32)
           if on_cpu else
           TransformerConfig(num_layers=8, hidden_size=1024, num_heads=16,
                             seq_length=512, batch_size=64))
    c = FFConfig(batch_size=cfg.batch_size)
    c.slices = 2
    ff = create_transformer(cfg, c)
    ff.compile(AdamOptimizer(alpha=1e-4, state_dtype=jnp.bfloat16),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
               mesh=make_mesh(8, {"data": 8}))
    assert "slice" in ff.mesh.axis_names, ff.mesh.axis_names
    rs = np.random.RandomState(0)
    x = rs.randn(cfg.batch_size, cfg.seq_length,
                 cfg.hidden_size).astype(np.float32)
    y = rs.randn(cfg.batch_size, cfg.seq_length, 1).astype(np.float32)
    out_cfg = dataclasses.asdict(cfg)
    out_cfg.update(slices=2, mesh=dict(zip(ff.mesh.axis_names,
                                           ff.mesh.devices.shape)))
    return ff, [x], y, out_cfg


WORKLOADS = [
    ("bert_proxy", build_bert_proxy, 30),
    ("inception_proxy", build_inception_proxy, 10),
    ("dlrm", build_dlrm, 30),
    ("moe", build_moe, 30),
    ("pipeline_transformer", build_pipeline_transformer, 10),
    ("multislice_transformer", build_multislice_transformer, 10),
    # iters=0 marks a DEVICELESS family: never timed, only the
    # compile-determined ratchets engage (hbm_peak_bytes,
    # dispatch_count, collective_bytes)
    ("longcontext_transformer", build_longcontext_transformer, 0),
]


def load_history():
    path = os.path.join(REPO, "bench_history.json")
    hist = {}
    if os.path.exists(path):
        try:
            hist = json.load(open(path))
        except Exception:
            hist = {}
    if "samples_per_s" in hist:
        # migrate the r1/r2 flat format; those rounds were recorded on the
        # TPU by the driver, so the number belongs to the tpu key
        hist = {"bert_proxy:tpu": {"samples_per_s": hist["samples_per_s"]}}
    return path, hist


def save_history(path, hist):
    """Atomic write-temp-then-rename: a bench crash mid-dump must never
    truncate the ratchet history every later round compares against."""
    from flexflow_tpu.obs.artifacts import atomic_write_text
    atomic_write_text(path, json.dumps(hist))


def ratchet(hist, key, samples_per_s, config, protocol):
    """Best-ever per workload key. The key is protocol name + platform
    ONLY — never the config dict (a schema change must not reset the
    ratchet; r2 lesson). `protocol` records the actual windows x iters
    measured (e.g. "best3x30") so a drifted protocol is flagged, not
    silently compared. Returns (vs_baseline, best_ever,
    old_protocol_or_None) — best_ever is reported beside each run's
    number because the tunneled chip swings up to ~2.3x run-to-run
    (BENCH_NOTES.md): a sub-1 vs_baseline on one run is usually chip
    weather, and the framework's demonstrated capability is the best."""
    entry = hist.get(key)
    if not isinstance(entry, dict):
        # first run of a new workload family (key absent), or a legacy /
        # hand-edited bare-number entry: both must ratchet cleanly
        entry = ({"samples_per_s": float(entry)}
                 if isinstance(entry, (int, float)) else {})
    baseline = entry.get("samples_per_s")
    vs = samples_per_s / baseline if baseline else 1.0
    old = entry.get("protocol", protocol) if entry else protocol
    if samples_per_s >= (baseline or 0.0):
        # merge over the old entry: sibling ratchets (collective_bytes,
        # census_ratchet below) live in the same dict and must survive a
        # new throughput best
        hist[key] = dict(entry, samples_per_s=samples_per_s,
                         protocol=protocol, config=config)
    # else: keep the stored best AND its provenance untouched
    return vs, max(samples_per_s, baseline or 0.0), \
        (old if old != protocol else None)


def _low_water_ratchet(hist, key, field, value, tol, abs_tol=0.0,
                       skip=False, max_drop=None):
    """Shared downward ratchet (census bytes, HBM peak, exposed-comms
    fraction): lower is better; a new low updates ``field`` in the
    workload's history entry, anything more than ``tol`` relative plus
    ``abs_tol`` absolute above the recorded best is a regression.
    ``skip`` suppresses the flag (the low-water value still records).
    For MEASURED metrics ``max_drop`` bounds how far one run can tighten
    the baseline (e.g. 0.5 = at most halve it per round): a single
    outlier-low capture window must not set a floor typical runs can
    never meet again, while sustained genuine improvement still
    converges geometrically. Returns (regression, baseline)."""
    entry = hist.get(key)
    if not isinstance(entry, dict):
        # legacy bare-number entry: preserve it as the samples/s baseline
        # (exactly as ratchet() does) instead of clobbering the record
        entry = ({"samples_per_s": float(entry)}
                 if isinstance(entry, (int, float)) else {})
        hist[key] = entry
    baseline = entry.get(field)
    regression = (not skip and baseline is not None
                  and value > baseline * (1.0 + tol) + abs_tol)
    if baseline is None:
        entry[field] = float(value)
    elif value < baseline:
        floor = baseline * max_drop if max_drop else 0.0
        entry[field] = float(max(value, floor))
    return regression, baseline


def census_ratchet(hist, key, total_bytes, tol=0.01):
    """Collective BYTE-VOLUME ratchet per workload family (ROADMAP
    trace-regression gate): unlike samples/s the census is a property of
    the compiled program — chip weather cannot hide a strategy
    regression that adds comms. Best (lowest) per-device bytes per step
    live under ``collective_bytes`` in the same history entry the
    throughput ratchet uses."""
    return _low_water_ratchet(hist, key, "collective_bytes", total_bytes,
                              tol)


def emit_obs_artifacts(name, ff, tracer):
    """Per-workload observability emission (only when --trace-dir is
    set): export the step trace, write the compiled-step summary
    artifact, and print ONE census line — to stderr, because the driver
    parses stdout as the single bench JSON line. Returns the summary
    (reused by the census byte ratchet) or None."""
    import traceback

    try:
        from flexflow_tpu.obs import export_step_summary
        tracer.export()
        summary = export_step_summary(ff, tracer)
        census = summary.get("collectives") or {}
        total = summary.get("collectives_total") or {}
        print(f"[obs] {name} collectives: "
              + json.dumps(dict(per_kind=census, total=total)),
              file=sys.stderr)
        return summary
    except Exception:
        print(f"[obs] {name}: artifact emission failed:\n"
              + traceback.format_exc(), file=sys.stderr)
        return None


def step_summary_for(name, ff, summary):
    """The compiled-step summary (collective census + XLA memory
    analysis), computed at most once per workload. Reuses a summary
    already computed for --trace-dir; otherwise pays one AOT
    lower+compile of the train step. FFS_SKIP_CENSUS=1 opts out (e.g. a
    time-boxed tunnel run). Returns None when unavailable — the byte and
    HBM ratchets then simply don't engage."""
    if summary is None and not os.environ.get("FFS_SKIP_CENSUS"):
        try:
            from flexflow_tpu.obs import inspect_model_step
            summary = inspect_model_step(ff)
        except Exception as e:
            print(f"[obs] {name}: census inspection failed: {e!r}",
                  file=sys.stderr)
            return None
    return summary


def census_bytes_of(summary):
    """Per-device collective bytes the compiled step moves (census
    total), or None."""
    total = (summary or {}).get("collectives_total") or {}
    b = total.get("bytes")
    return float(b) if b is not None else None


def dcn_bytes_of(summary):
    """Per-device CROSS-SLICE collective bytes the compiled step moves
    (the fabric-split census's DCN bucket — only present on a
    ('slice', ...) mesh), or None. Informational this round: recorded
    per workload alongside collective_bytes, not yet ratcheted —
    BENCH_NOTES documents the attribution methodology; the ratchet
    lands once a chip-validated multi-slice baseline exists."""
    fab = (summary or {}).get("collectives_by_fabric") or {}
    dcn = fab.get("dcn") or {}
    b = dcn.get("bytes")
    return float(b) if b is not None else None


def hbm_peak_of(summary):
    """Per-device HBM peak the compiled step needs (XLA compiled memory
    analysis: live arguments + temp), or None."""
    mem = (summary or {}).get("memory") or {}
    b = mem.get("peak_bytes")
    return float(b) if b else None


def dispatch_count_of(summary):
    """Kernel launches per compiled step (HLO fusion census: fusions +
    custom calls + collectives), or None. The dispatch-bound hot path's
    coordinate — the one the searched kernel dimension (ISSUE 15)
    moves."""
    f = (summary or {}).get("fusions") or {}
    d = f.get("dispatches")
    return int(d) if d else None


def dispatch_ratchet(hist, key, dispatches, tol=0.05):
    """Downward ratchet on the per-step dispatch count, alongside
    ``collective_bytes``/``hbm_peak_bytes``: a change that un-fuses the
    hot path (more kernel launches) fails the bench even when wall
    clock hides it. Compile-determined but XLA-version-sensitive, so a
    slightly wider tolerance than the byte ratchets plus 2 launches of
    absolute slack. FFS_SKIP_CENSUS=1 opts out upstream (no summary ->
    no engagement)."""
    return _low_water_ratchet(hist, key, "dispatch_count",
                              float(dispatches), tol, abs_tol=2.0)


def step_time_stats(step_samples, iters):
    """p50/p99 of the steady-state per-step dispatch intervals: the
    first window (index < iters) still fills the async pipeline, so it
    is dropped whenever a later window exists. Returns (p50, p99) or
    (None, None)."""
    from flexflow_tpu.obs.registry import percentile
    s = step_samples[iters:] if len(step_samples) > iters else step_samples
    if not s:
        return None, None
    s = sorted(s)
    return percentile(s, 0.5), percentile(s, 0.99)


def mfu_of(ff, step_s):
    """Model-FLOPs utilization at the measured step time: analytic
    fwd+bwd FLOPs per step / chips / step seconds / chip peak
    (obs.devtrace.train_step_flops — same convention as the traced-run
    MFU gauge). None when unavailable."""
    try:
        from flexflow_tpu.obs.devtrace import train_step_flops
        spec = ff.machine_spec
        if not (spec and step_s):
            return None
        n_chips = int(ff.mesh.devices.size)
        return train_step_flops(ff) / n_chips / step_s / float(spec.flops)
    except Exception:
        return None


def sim_accuracy_of(name, ff, p50, sps, cfg_dict):
    """Predicted/measured step-time ratio for one workload: the native
    simulator's replay of the compiled strategy (learned cost table
    engaged per the usual discovery — FFS_NO_LEARNED_COSTS opts out)
    over the measured steady-state step. Measured = the dispatch p50
    when the window captured one, else batch/samples-per-s. None when
    either side is unavailable; never raises (a simulator failure must
    not cost a bench round)."""
    try:
        from flexflow_tpu.search.validate import simulate_strategy
        pred_s = simulate_strategy(ff).get("iteration_time")
        meas_s = p50
        if not meas_s and sps:
            bs = cfg_dict.get("batch_size")
            meas_s = float(bs) / sps if bs else None
        if not (pred_s and meas_s):
            return None
        return round(float(pred_s) / float(meas_s), 4)
    except Exception as e:
        print(f"[obs] {name}: sim-accuracy replay failed: {e!r}",
              file=sys.stderr)
        return None


def exposed_ratchet(hist, key, frac, tol=0.25, abs_tol=0.01):
    """Downward ratchet on the measured exposed-comms fraction (ISSUE 9:
    promoted from informational — overlap wins must not silently
    regress). The fraction comes from the warmup-window device capture,
    which is noisier than the compile-determined ratchets, so the guard
    allows ``tol`` relative plus ``abs_tol`` absolute slack (a
    zero-comms single-device family must not flag on measurement dust)
    and a new low can tighten the baseline by at most half per round
    (one lucky capture window must not set an unreachable floor).
    Mirrors the census ratchet's opt-out: FFS_SKIP_EXPOSED=1 skips the
    guard (the low-water value still records). Returns
    (regression, baseline)."""
    return _low_water_ratchet(
        hist, key, "exposed_comms_frac", frac, tol, abs_tol=abs_tol,
        skip=bool(os.environ.get("FFS_SKIP_EXPOSED")), max_drop=0.5)


def hbm_ratchet(hist, key, peak_bytes, tol=0.02):
    """HBM-peak ratchet per workload family, the memory sibling of
    ``census_ratchet``: XLA's compiled memory analysis is also a
    property of the program, so a regression that bloats optimizer
    state or loses buffer donation fails the bench even when chip
    weather hides the samples/s cost. Best peak lives under
    ``hbm_peak_bytes``."""
    return _low_water_ratchet(hist, key, "hbm_peak_bytes", peak_bytes, tol)


def latency_ratchet(hist, key, field, value_s, tol=0.5, max_drop=0.5):
    """Downward ratchet on a measured request-latency percentile
    (BENCH_NOTES r14): lower is better; generous relative tolerance
    because closed-loop CPU/tunnel latency is far noisier than the
    compile-determined ratchets, and one outlier-fast round may tighten
    the baseline by at most half. FFS_SKIP_LATENCY=1 opts out (the
    low-water value still records)."""
    return _low_water_ratchet(
        hist, key, field, value_s, tol, abs_tol=0.001,
        skip=bool(os.environ.get("FFS_SKIP_LATENCY")), max_drop=max_drop)


def serve_main(argv):
    """`bench.py serve`: closed-loop inference-serving latency bench —
    the latency sibling of the training-throughput families. Drives the
    flexflow_tpu/serve engine (continuous batching + latency-searched
    bucket executors) with the BENCH_NOTES r14 protocol (per-bucket
    warmup excluded, closed-loop clients) and ratchets p50/p99 request
    latency downward in the same bench_history.json the throughput
    ratchets live in. Prints ONE JSON line."""
    ensure_virtual_host_devices()
    import jax

    sys.path.insert(0, REPO)
    on_cpu = jax.devices()[0].platform == "cpu"
    platform = "cpu" if on_cpu else "tpu"
    hist_path, hist = load_history()
    models = [a for a in argv if not a.startswith("-")] or ["transformer"]
    trace_dir = os.environ.get("FFS_TRACE_DIR") or None

    from flexflow_tpu.serve.loadgen import (build_serve_model,
                                            run_serve_workload)

    result = {"metric": "serve_request_latency", "unit": "s",
              "workloads": {}}
    regressions = []
    for name in models:
        try:
            # fresh registry per workload: the serve/* series (latency
            # reservoir, occupancy) are process-global — without a reset
            # the second model's report would blend in the first's
            from flexflow_tpu.obs.registry import get_registry
            get_registry().reset()
            ff, make_request, cfg_dict = build_serve_model(name, on_cpu)
            report = run_serve_workload(
                ff, make_request,
                num_requests=(24 if on_cpu else 200),
                concurrency=4, search_budget=4, trace_dir=trace_dir)
        except Exception as e:
            result["workloads"][name] = {
                "error": f"{type(e).__name__}: {e}"}
            continue
        loop = report["closed_loop"]
        key = f"serve_{name}:{platform}"
        wl = dict(
            p50_s=round(loop.get("p50_s", 0.0), 6),
            p99_s=round(loop.get("p99_s", 0.0), 6),
            throughput_rps=round(loop.get("throughput_rps", 0.0), 2),
            num_measured=loop.get("num_measured"),
            buckets={b: dict(objective=e["objective"],
                             differs=e["strategy_differs_from_training"])
                     for b, e in report["buckets"].items()},
        )
        occ = report.get("registry", {}).get("occupancy_mean")
        if occ is not None:
            wl["occupancy_mean"] = round(occ, 4)
        fields = ("request_latency_p50_s", "request_latency_p99_s")
        prev = dict(hist.get(key) or {}) if isinstance(hist.get(key),
                                                       dict) else {}
        for field, v in zip(fields, (loop.get("p50_s"),
                                     loop.get("p99_s"))):
            if v is None:
                continue
            reg, base = latency_ratchet(hist, key, field, v)
            if reg:
                regressions.append(
                    f"{name}: {field} {v:.6f}s vs recorded best "
                    f"{base:.6f}s")
        ent = hist.get(key)
        if isinstance(ent, dict):
            # provenance follows the RECORDED BEST, not the latest run
            # (the ratchet() discipline): protocol/config update only
            # when this run actually lowered a baseline
            improved = any(ent.get(f) != prev.get(f) for f in fields)
            if improved or "protocol" not in ent:
                ent.update(
                    protocol="closed4x" + str(loop.get("num_measured")),
                    config=cfg_dict,
                    throughput_rps=wl["throughput_rps"])
        result["workloads"][name] = wl
        del ff
    try:
        save_history(hist_path, hist)
    except Exception:
        pass
    if regressions:
        result["latency_regressions"] = regressions
    print(json.dumps(result))


def main():
    # the pipeline workload needs a pipe x data mesh
    ensure_virtual_host_devices()
    import jax

    sys.path.insert(0, REPO)
    on_cpu = jax.devices()[0].platform == "cpu"
    platform = "cpu" if on_cpu else "tpu"
    hist_path, hist = load_history()
    trace_dir = os.environ.get("FFS_TRACE_DIR") or None
    if "--trace-dir" in sys.argv:
        i = sys.argv.index("--trace-dir")
        if i + 1 >= len(sys.argv) or sys.argv[i + 1].startswith("-"):
            print("bench.py: --trace-dir requires a directory argument",
                  file=sys.stderr)
            sys.exit(2)
        trace_dir = sys.argv[i + 1]

    result = {}
    workloads_out = {}
    protocol_notes = []
    census_regressions = []
    memory_regressions = []
    exposed_regressions = []
    for name, build, iters in WORKLOADS:
        compile_only = iters == 0
        iters = iters if compile_only else (5 if on_cpu else iters)
        windows = 1 if on_cpu else 3
        protocol = ("compile_only" if compile_only
                    else f"best{windows}x{iters}")
        ff = None
        tracer = None
        try:
            ff, xs, y, cfg_dict = build(on_cpu)
            capture = None
            devrep = None
            summary = None
            if compile_only:
                # deviceless family: no training loop — every recorded
                # coordinate is a property of the compiled program
                sps, step_samples = None, []
            else:
                if trace_dir:
                    from flexflow_tpu.obs import make_capture, make_tracer
                    tracer = make_tracer(trace_dir, run_name=name)
                    # windowed device capture over the post-compile
                    # warmup steps: exposed_comms_frac (the overlap
                    # direction's ratchet coordinate) without perturbing
                    # the measurement
                    if tracer.active:
                        capture = make_capture(tracer, "1:3")
                sps, step_samples = time_train(ff, xs, y, iters=iters,
                                               windows=windows,
                                               tracer=tracer,
                                               capture=capture)
                if capture is not None and capture.active:
                    try:
                        devrep = capture.finalize(ff, tracer)
                    except Exception as e:
                        print(f"[obs] {name}: devtrace attribution "
                              f"failed: {e!r}", file=sys.stderr)
                if tracer is not None and tracer.active:
                    summary = emit_obs_artifacts(name, ff, tracer)
            summary = step_summary_for(name, ff, summary)
            cbytes = census_bytes_of(summary)
            hbm_peak = hbm_peak_of(summary)
        except Exception as e:
            if name == "bert_proxy":
                raise  # the headline metric must never be silently absent
            # a broken secondary family is a visible per-workload error,
            # not a lost bench run (the driver parses the ONE JSON line);
            # drop the failed model so its HBM frees before the next build
            ff = None
            workloads_out[name] = {"error": f"{type(e).__name__}: {e}"}
            continue
        key = f"{name}:{platform}"
        if compile_only:
            # no throughput to ratchet; record provenance so the entry
            # still says what was compiled
            vs = best = old_protocol = None
            ent = hist.get(key)
            if not isinstance(ent, dict):
                ent = {}
                hist[key] = ent
            ent.update(protocol=protocol, config=cfg_dict)
        else:
            vs, best, old_protocol = ratchet(hist, key, sps, cfg_dict,
                                             protocol)
        wl = {}
        if cbytes is not None:
            # the trace-regression gate (ROADMAP): a strategy change that
            # adds comms fails LOUDLY here even when chip weather hides
            # the samples/s slowdown — the census is compile-determined
            reg, byte_base = census_ratchet(hist, key, cbytes)
            wl["collective_bytes"] = round(cbytes, 1)
            if reg:
                census_regressions.append(
                    f"{name}: {cbytes:.0f} B/step vs recorded best "
                    f"{byte_base:.0f}")
        dcn = dcn_bytes_of(summary)
        if dcn is not None:
            # fabric attribution (multi-slice meshes only): cross-slice
            # byte volume per step — informational this round, the DCN
            # ratchet follows once a chip-validated baseline exists
            wl["dcn_bytes"] = round(dcn, 1)
        if hbm_peak is not None:
            # memory sibling of the census gate: per-device HBM peak from
            # XLA's compiled memory analysis (the metric weight-update
            # sharding moves) ratchets alongside throughput
            mreg, peak_base = hbm_ratchet(hist, key, hbm_peak)
            wl["hbm_peak_bytes"] = round(hbm_peak, 1)
            if mreg:
                memory_regressions.append(
                    f"{name}: {hbm_peak:.0f} B peak vs recorded best "
                    f"{peak_base:.0f}")
        dispatches = dispatch_count_of(summary)
        if dispatches is not None:
            # dispatch-count sibling (ISSUE 15): the kernel-search
            # dimension's coordinate — un-fusing the hot path (more
            # launches per step) fails loudly even when wall clock
            # doesn't move on this round's hardware
            dreg, dbase = dispatch_ratchet(hist, key, dispatches)
            wl["dispatch_count"] = dispatches
            if dreg:
                census_regressions.append(
                    f"{name}: {dispatches} dispatches/step vs recorded "
                    f"best {dbase:.0f}")
        # per-op kernel choices (provenance, informational): which impls
        # this round's strategy executed — seeded into the history entry
        # so cross-round diffs show kernel-choice flips. Searched models
        # record the "_k:" choices; heuristic workloads record the
        # attention dispatch (selected_impl) so the column never goes
        # silently absent.
        kc = dict(getattr(ff, "kernel_choices", None) or {})
        if not kc:
            from flexflow_tpu.search.unity import executed_kernel_choices
            axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
            kc = executed_kernel_choices(ff.executor.nodes, ff.strategy,
                                         axes, training=True)
        if kc:
            wl["kernel_choices"] = dict(sorted(kc.items()))
        # informational observability fields (ISSUE 6): step-time
        # distribution + MFU next to the ratchets — recorded into the
        # history entry for cross-round comparison, but NOT gated (chip
        # weather swings dispatch cadence far more than compiled bytes)
        p50, p99 = step_time_stats(step_samples, iters)
        mfu = mfu_of(ff, p50)
        if p50 is not None:
            wl["step_time_p50"] = round(p50, 6)
            wl["step_time_p99"] = round(p99, 6)
        if mfu is not None:
            wl["mfu"] = round(mfu, 8)
        # simulator accuracy as a tracked metric (ISSUE 14 / SCALE-Sim
        # methodology): replay the compiled strategy through the native
        # simulator — learned cost table engaged exactly as the search
        # had it — and record predicted/measured step time next to
        # throughput. Informational (no ratchet: the simulator predicts
        # chip behavior, so a CPU round's ratio is a smoke value, and
        # chip rounds swing with tunnel weather).
        sim_ratio = (None if compile_only
                     else sim_accuracy_of(name, ff, p50, sps, cfg_dict))
        if sim_ratio is not None:
            wl["sim_accuracy_ratio"] = sim_ratio
        # measured exposed-comms fraction from the warmup-window device
        # capture: since ISSUE 9 a downward-ratcheting GUARD (the
        # overlap direction's coordinate — a strategy/executor change
        # that re-exposes hidden comms fails the bench even when chip
        # weather hides the samples/s cost). FFS_SKIP_EXPOSED=1 opts
        # out, mirroring the census ratchet.
        tot = (devrep or {}).get("totals") or {}
        if tot.get("wall_s"):
            frac = round(tot.get("exposed_comms_s", 0.0) / tot["wall_s"], 4)
            wl["exposed_comms_frac"] = frac
            ereg, ebase = exposed_ratchet(hist, key, frac)
            if ereg:
                exposed_regressions.append(
                    f"{name}: exposed_comms_frac {frac:.4f} vs recorded "
                    f"best {ebase:.4f}")
        ent = hist.get(key)
        if isinstance(ent, dict):
            ent.update({k: wl[k] for k in
                        ("step_time_p50", "step_time_p99", "mfu",
                         "sim_accuracy_ratio", "kernel_choices")
                        if k in wl})
            if "sim_accuracy_ratio" not in wl:
                # a failed replay must not leave a PREVIOUS round's
                # ratio sitting next to this round's step times
                ent.pop("sim_accuracy_ratio", None)
            if "kernel_choices" not in wl:
                # same stale-field discipline: a round that records no
                # kernel choices must not inherit a previous round's
                ent.pop("kernel_choices", None)
        if name == "bert_proxy":
            result.update({
                "metric": "bert_proxy_train_throughput",
                "value": round(sps, 3),
                "unit": "samples/s",
                "vs_baseline": round(vs, 4),
                "best_recorded": round(best, 3),
            })
            result.update(wl)
        elif compile_only:
            workloads_out[name] = dict({"compile_only": True}, **wl)
        else:
            workloads_out[name] = dict(
                {"value": round(sps, 3),
                 "vs_baseline": round(vs, 4),
                 "best_recorded": round(best, 3)}, **wl)
        if old_protocol:
            protocol_notes.append(f"{name}: {old_protocol} -> {protocol}")
        del ff
    try:
        save_history(hist_path, hist)
    except Exception:
        pass
    result["workloads"] = workloads_out
    if census_regressions:
        result["census_regressions"] = census_regressions
    if memory_regressions:
        result["memory_regressions"] = memory_regressions
    if exposed_regressions:
        result["exposed_regressions"] = exposed_regressions
    if protocol_notes:
        result["protocol_change"] = ("vs_baseline spans protocols — " +
                                     "; ".join(protocol_notes))
    ratio = searched_vs_dp_ratio(on_cpu)
    if ratio is not None:
        # BASELINE.md north star: predicted searched/DP throughput on a
        # simulated v4-32 (the OSDI'22 AE protocol's headline comparison)
        result.update(ratio)
    print(json.dumps(result))


def searched_vs_dp_ratio(on_cpu):
    """Unity-search vs --only-data-parallel predicted iteration time for
    BERT-large (24 layers, hidden 1024, 16 heads, seq 512 — the
    BASELINE.md north-star model) on a simulated TPU v4-32.

    Protocol mirrors the reference's OSDI'22 AE comparison
    (scripts/osdi22ae/bert.sh: global batch 8 on 4 GPUs — *strong*
    scaling, ~1-2 samples per device, plain SGD): global batch = n_chips,
    where DP's per-parameter gradient sync cannot amortize and a hybrid
    strategy wins. At large per-chip batch DP is genuinely near-optimal
    on TPU (sync hides under backward) and the honest ratio approaches 1.
    Collectives are priced at the protocol's f32 payload
    (comm_bytes_factor 1.0, matching the reference's f32 training);
    r1-r4 measured the 12-layer proxy here — the r5 history in
    BENCH_NOTES.md tracks the change.
    """
    try:
        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.ffconst import LossType
        from flexflow_tpu.machine import MachineSpec
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     create_transformer)
        from flexflow_tpu.optimizers import SGDOptimizer
        from flexflow_tpu.search.native import available, native_optimize
        from flexflow_tpu.search.unity import machine_to_json, serialize_graph

        if not available():
            return None
        n_chips = 32
        mcfg = (TransformerConfig(num_layers=2, hidden_size=128, num_heads=4,
                                  seq_length=64, batch_size=n_chips)
                if on_cpu else
                TransformerConfig(num_layers=24, batch_size=n_chips))
        ff = create_transformer(
            mcfg, FFConfig(batch_size=mcfg.batch_size,
                           only_data_parallel=True, workers_per_node=1))
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        nodes = serialize_graph(ff.executor.nodes,
                                final_guid=ff.executor.final_ref[0])
        machine = machine_to_json(
            MachineSpec(chip="tpu-v4", chips_per_slice=n_chips), n_chips)
        base_cfg = dict(budget=8, alpha=0.05, training=True, overlap=True,
                        batch=mcfg.batch_size, opt_state_factor=0.0,
                        seed=42, rules=[])
        # the searched arm gets the full strategy space, including the r4
        # GPipe pipeline meshes (repeated-block metadata)
        search_req = dict(
            nodes=nodes, machine=machine, measured={},
            config=dict(base_cfg, enable_parameter_parallel=True))
        from flexflow_tpu.parallel.pipeline_detect import (
            detect_repeated_blocks, pipeline_meta_json)
        pb = detect_repeated_blocks(ff.executor.nodes)
        if pb is not None:
            search_req["pipeline"] = pipeline_meta_json(ff.executor.nodes, pb)
        searched = native_optimize(search_req)
        dp = native_optimize(dict(
            nodes=nodes, machine=machine, measured={},
            config=dict(base_cfg, only_data_parallel=True)))
        r = dp["predicted_time"] / searched["predicted_time"]
        mesh = {k: v for k, v in searched["mesh"].items() if v > 1}
        # the searched strategy's kernel choices (ISSUE 15): which
        # "_k:" impls the simulated v4-32 search committed to, keyed by
        # op — the per-workload kernel_choices record for strategies
        # that actually SEARCH (the CPU proxy workloads run heuristic
        # single-device strategies and record none)
        from flexflow_tpu.search.unity import kernel_choice_of
        by_guid = {n.op.guid: n.op.name for n in ff.executor.nodes}
        kchoices = {}
        for guid, oj in (searched.get("ops") or {}).items():
            impl = kernel_choice_of(oj.get("choice"))
            if impl is not None:
                kchoices[by_guid.get(int(guid), guid)] = impl
        out = {
            "searched_vs_dp_v4_32": round(r, 3),
            "searched_mesh_v4_32": mesh or {"data": 1},
            "north_star_model": ("transformer_tiny" if on_cpu
                                 else "bert_large_24L"),
        }
        if kchoices:
            out["searched_kernel_choices_v4_32"] = dict(sorted(
                kchoices.items()))
        if searched.get("pipeline"):
            out["searched_microbatches_v4_32"] = \
                searched["pipeline"]["microbatches"]
        return out
    except Exception:
        return None


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "serve":
        serve_main(sys.argv[2:])
    else:
        main()

#!/usr/bin/env python
"""Headline benchmark: BERT-proxy transformer training throughput.

Protocol follows the reference's OSDI'22 AE BERT benchmark
(scripts/osdi22ae/bert.sh + examples/cpp/Transformer/transformer.cc:79-84):
12 layers, hidden 1024, 16 heads, seq 512, batch 8 per chip; metric is
training samples/s (fwd+bwd+update, jitted). Prints ONE JSON line.

vs_baseline: ratio against the recorded best from previous rounds
(bench_history.json), 1.0 on first run — the reference repo publishes no
absolute numbers (BASELINE.md).
"""

import json
import os
import sys
import time

import numpy as np


def main():
    import jax

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType, MetricsType
    from flexflow_tpu.models.transformer import TransformerConfig, create_transformer
    from flexflow_tpu.optimizers import SGDOptimizer

    on_cpu = jax.devices()[0].platform == "cpu"
    cfg = (TransformerConfig(num_layers=2, hidden_size=128, num_heads=4,
                             seq_length=64, batch_size=8)
           if on_cpu else TransformerConfig())  # reference config on TPU

    from flexflow_tpu.optimizers import AdamOptimizer

    # TPU-native optimizer configuration: bf16 m/v storage (update math is
    # f32 — optimizers.py). The update phase is HBM-bound (measured r4,
    # scripts/measure_bw.py: ~620 GB/s marginal, so bytes are the lever);
    # bf16 state cuts its traffic 29%. Convergence parity with f32 state is
    # asserted by tests/test_model_training.py::test_adam_bf16_state.
    import jax.numpy as jnp
    ff = create_transformer(cfg, FFConfig(batch_size=cfg.batch_size))
    ff.compile(AdamOptimizer(alpha=1e-4, state_dtype=jnp.bfloat16),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               [MetricsType.MEAN_SQUARED_ERROR])

    rs = np.random.RandomState(0)
    x = rs.randn(cfg.batch_size, cfg.seq_length, cfg.hidden_size).astype(np.float32)
    y = rs.randn(cfg.batch_size, cfg.seq_length, 1).astype(np.float32)

    train_step = ff.executor.make_train_step()
    inputs = ff._stage_inputs([x])
    labels = ff._shard_batch(y)

    import jax.random as jrandom

    def step(params, opt_state, state, rng):
        rng, sub = jrandom.split(rng)
        params, opt_state, state, loss, _ = train_step(
            params, opt_state, state, inputs, labels, sub)
        return params, opt_state, state, rng, loss

    params, opt_state, state = ff.params, ff.opt_state, ff.state
    rng = jrandom.PRNGKey(0)
    # warmup (compile; a second round catches the donation-aliased
    # recompile); float() forces a real device->host sync — on the
    # tunneled TPU backend block_until_ready alone does not. Measured
    # (r3, 30 iters, v5e): plain loop 160.35 samples/s vs
    # make_multi_step lax.scan 156.46 — async per-step dispatch pipelines
    # better than the fused scan (scan serializes the donation chain), so
    # the plain loop is both the honest protocol and the faster one.
    for _ in range(3):
        params, opt_state, state, rng, loss = step(params, opt_state, state, rng)
    float(loss)

    # best of 3 full-length windows: the tunneled backend occasionally
    # stalls for hundreds of ms (observed: a 20x-slow outlier window on an
    # otherwise healthy chip), and steady-state throughput is the quantity
    # of interest. Window length stays at the r1/r2 protocol's 30 steps —
    # shorter windows under-report by amortizing the per-window host sync
    # over too few steps.
    iters = 10 if on_cpu else 30
    windows = 1 if on_cpu else 3
    best_dt = None
    final_loss = None
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, state, rng, loss = step(
                params, opt_state, state, rng)
        final_loss = float(loss)  # sync: depends on the whole step chain
        dt = time.perf_counter() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)
    assert np.isfinite(final_loss), f"training diverged: loss={final_loss}"
    samples_per_s = cfg.batch_size * iters / best_dt

    # ---- ratchet: best-ever per workload key --------------------------
    # The key is protocol name + platform ONLY — never the config dict.
    # (Round 2 masked a regression because a new config field invalidated
    # the recorded baseline; a schema change must not reset the ratchet.)
    workload = f"bert_proxy:{'cpu' if on_cpu else 'tpu'}"
    hist_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_history.json")
    hist = {}
    if os.path.exists(hist_path):
        try:
            hist = json.load(open(hist_path))
        except Exception:
            hist = {}
    if "samples_per_s" in hist:
        # migrate the r1/r2 flat format; those rounds were recorded on the
        # TPU by the driver, so the number belongs to the tpu key
        # regardless of where THIS run executes
        hist = {"bert_proxy:tpu": {"samples_per_s": hist["samples_per_s"]}}
    # protocol tag (advisor r3): vs_baseline is only meaningful
    # like-for-like. "best3x30" = best of 3 x 30-step windows (r3+);
    # entries without a tag predate r3 but the ratcheted max already
    # includes r3's best-of-3 run, so they are comparable going forward.
    PROTOCOL = "best3x30"
    entry = hist.get(workload) or {}
    baseline = entry.get("samples_per_s")
    vs_baseline = samples_per_s / baseline if baseline else 1.0
    protocol_changed = bool(entry) and entry.get("protocol",
                                                PROTOCOL) != PROTOCOL
    try:
        if samples_per_s >= (baseline or 0.0):
            hist[workload] = {
                "samples_per_s": samples_per_s,
                "protocol": PROTOCOL,
                "config": dataclass_dict(cfg),
            }
        # else: keep the stored best AND its provenance (protocol/config)
        # untouched — stamping the current tags onto an old best would
        # falsify the baseline's provenance
        json.dump(hist, open(hist_path, "w"))
    except Exception:
        pass

    result = {
        "metric": "bert_proxy_train_throughput",
        "value": round(samples_per_s, 3),
        "unit": "samples/s",
        "vs_baseline": round(vs_baseline, 4),
    }
    if protocol_changed:
        result["protocol_change"] = (
            f"{entry.get('protocol')} -> {PROTOCOL}: vs_baseline spans "
            f"protocols")
    ratio = searched_vs_dp_ratio(on_cpu)
    if ratio is not None:
        # BASELINE.md north star: predicted searched/DP throughput on a
        # simulated v4-32 (the OSDI'22 AE protocol's headline comparison)
        result.update(ratio)
    print(json.dumps(result))


def searched_vs_dp_ratio(on_cpu):
    """Unity-search vs --only-data-parallel predicted iteration time for
    the BERT-proxy on a simulated TPU v4-32.

    Protocol mirrors the reference's OSDI'22 AE comparison
    (scripts/osdi22ae/bert.sh: global batch 8 on 4 GPUs — *strong*
    scaling, ~1-2 samples per device, plain SGD): global batch = n_chips,
    where DP's per-parameter gradient sync cannot amortize and a hybrid
    strategy wins. At large per-chip batch DP is genuinely near-optimal
    on TPU (sync hides under backward) and the honest ratio approaches 1.
    """
    try:
        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.ffconst import LossType
        from flexflow_tpu.machine import MachineSpec
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     create_transformer)
        from flexflow_tpu.optimizers import SGDOptimizer
        from flexflow_tpu.search.native import available, native_optimize
        from flexflow_tpu.search.unity import machine_to_json, serialize_graph

        if not available():
            return None
        n_chips = 32
        mcfg = (TransformerConfig(num_layers=2, hidden_size=128, num_heads=4,
                                  seq_length=64, batch_size=n_chips)
                if on_cpu else
                TransformerConfig(batch_size=n_chips))
        ff = create_transformer(
            mcfg, FFConfig(batch_size=mcfg.batch_size,
                           only_data_parallel=True, workers_per_node=1))
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        nodes = serialize_graph(ff.executor.nodes)
        machine = machine_to_json(
            MachineSpec(chip="tpu-v4", chips_per_slice=n_chips), n_chips)
        base_cfg = dict(budget=8, alpha=0.05, training=True, overlap=True,
                        batch=mcfg.batch_size, opt_state_factor=0.0,
                        seed=42, rules=[])
        # the searched arm gets the full strategy space, including the r4
        # GPipe pipeline meshes (repeated-block metadata)
        search_req = dict(
            nodes=nodes, machine=machine, measured={},
            config=dict(base_cfg, enable_parameter_parallel=True))
        from flexflow_tpu.parallel.pipeline_detect import (
            detect_repeated_blocks, pipeline_meta_json)
        pb = detect_repeated_blocks(ff.executor.nodes)
        if pb is not None:
            search_req["pipeline"] = pipeline_meta_json(ff.executor.nodes, pb)
        searched = native_optimize(search_req)
        dp = native_optimize(dict(
            nodes=nodes, machine=machine, measured={},
            config=dict(base_cfg, only_data_parallel=True)))
        r = dp["predicted_time"] / searched["predicted_time"]
        mesh = {k: v for k, v in searched["mesh"].items() if v > 1}
        out = {
            "searched_vs_dp_v4_32": round(r, 3),
            "searched_mesh_v4_32": mesh or {"data": 1},
        }
        if searched.get("pipeline"):
            out["searched_microbatches_v4_32"] = \
                searched["pipeline"]["microbatches"]
        return out
    except Exception:
        return None


def dataclass_dict(cfg):
    import dataclasses
    return dataclasses.asdict(cfg)


if __name__ == "__main__":
    main()

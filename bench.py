#!/usr/bin/env python
"""Headline benchmark: BERT-proxy transformer training throughput.

Protocol follows the reference's OSDI'22 AE BERT benchmark
(scripts/osdi22ae/bert.sh + examples/cpp/Transformer/transformer.cc:79-84):
12 layers, hidden 1024, 16 heads, seq 512, batch 8 per chip; metric is
training samples/s (fwd+bwd+update, jitted). Prints ONE JSON line.

vs_baseline: ratio against the recorded best from previous rounds
(bench_history.json), 1.0 on first run — the reference repo publishes no
absolute numbers (BASELINE.md).
"""

import json
import os
import sys
import time

import numpy as np


def main():
    import jax

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType, MetricsType
    from flexflow_tpu.models.transformer import TransformerConfig, create_transformer
    from flexflow_tpu.optimizers import SGDOptimizer

    on_cpu = jax.devices()[0].platform == "cpu"
    cfg = (TransformerConfig(num_layers=2, hidden_size=128, num_heads=4,
                             seq_length=64, batch_size=8)
           if on_cpu else TransformerConfig())  # reference config on TPU

    from flexflow_tpu.optimizers import AdamOptimizer

    ff = create_transformer(cfg, FFConfig(batch_size=cfg.batch_size))
    ff.compile(AdamOptimizer(alpha=1e-4), LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               [MetricsType.MEAN_SQUARED_ERROR])

    rs = np.random.RandomState(0)
    x = rs.randn(cfg.batch_size, cfg.seq_length, cfg.hidden_size).astype(np.float32)
    y = rs.randn(cfg.batch_size, cfg.seq_length, 1).astype(np.float32)

    train_step = ff.executor.make_train_step()
    inputs = ff._stage_inputs([x])
    labels = ff._shard_batch(y)

    import jax.random as jrandom

    def step(params, opt_state, state, rng):
        rng, sub = jrandom.split(rng)
        params, opt_state, state, loss, _ = train_step(
            params, opt_state, state, inputs, labels, sub)
        return params, opt_state, state, rng, loss

    params, opt_state, state = ff.params, ff.opt_state, ff.state
    rng = jrandom.PRNGKey(0)
    # warmup (compile; a second round catches the donation-aliased
    # recompile); float() forces a real device->host sync — on the
    # tunneled TPU backend block_until_ready alone does not. Measured:
    # async per-step dispatch pipelines as well as a fused lax.scan loop
    # (make_multi_step), so the plain loop is the honest protocol.
    for _ in range(3):
        params, opt_state, state, rng, loss = step(params, opt_state, state, rng)
    float(loss)

    iters = 10 if on_cpu else 30
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, state, rng, loss = step(params, opt_state, state, rng)
    final_loss = float(loss)  # sync: depends on the whole step chain
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), f"training diverged: loss={final_loss}"
    samples_per_s = cfg.batch_size * iters / dt

    hist_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_history.json")
    baseline = None
    if os.path.exists(hist_path):
        try:
            baseline = json.load(open(hist_path)).get("samples_per_s")
        except Exception:
            baseline = None
    vs_baseline = samples_per_s / baseline if baseline else 1.0
    try:
        # record the best-known number so vs_baseline is vs best, not last
        json.dump({"samples_per_s": max(samples_per_s, baseline or 0.0),
                   "config": dataclass_dict(cfg)}, open(hist_path, "w"))
    except Exception:
        pass

    print(json.dumps({
        "metric": "bert_proxy_train_throughput",
        "value": round(samples_per_s, 3),
        "unit": "samples/s",
        "vs_baseline": round(vs_baseline, 4),
    }))


def dataclass_dict(cfg):
    import dataclasses
    return dataclasses.asdict(cfg)


if __name__ == "__main__":
    main()

// Graph-substitution engine: TASO/Unity-style pattern->replacement rewrites.
//
// Native analog of the reference's GraphXfer machinery: backtracking
// pattern match + apply (src/runtime/substitution.cc:596 GraphXfer::run),
// the hand-written substitution generators (:1726-1860), and the
// machine-generated rule corpus loader (src/runtime/substitution_loader.cc,
// substitutions/graph_subst_3_v2.json: 640 rules).
//
// A rule is a source pattern graph and a replacement graph over the same
// external inputs, with an output mapping. Matching binds pattern ops to
// graph nodes (types, edges, and parameter constraints must agree;
// parameters may be wildcards bound consistently across the pattern).
// Application splices the replacement in with fresh guids, re-inferring
// shapes locally — an application whose shapes don't check out is
// discarded, which also filters reference rules whose replica-dim
// conventions don't hold in this framework's explicit-shape form.
//
// The best-first search loop that drives rule application lives in
// ffs_search.cpp (analog of base_optimize, substitution.cc:2229).
#pragma once

#include <algorithm>
#include <array>
#include <cstdint>
#include <functional>
#include <map>
#include <optional>
#include <set>
#include <string>
#include <vector>

#include "ffs_graph.hpp"
#include "ffs_json.hpp"

namespace ffsearch {

// Parameter constraint value: >= 0 exact; WILDCARD_BASE - v = wildcard
// variable v (bound on first use, must agree everywhere it appears).
constexpr double kWildcardBase = -1000.0;
inline double wildcard(int var) { return kWildcardBase - var; }
inline bool is_wildcard(double v) { return v <= kWildcardBase; }
inline int wildcard_var(double v) { return static_cast<int>(kWildcardBase - v); }

struct SubstOp {
  std::string type;                              // repo OperatorType name
  std::vector<std::pair<int, int>> inputs;       // (opId, tsId); opId<0 ext
  std::map<std::string, double> para;            // PM_* -> value/wildcard
};

struct SubstRule {
  std::string name;
  std::vector<SubstOp> src, dst;
  // (srcOpId, srcTsId, dstOpId, dstTsId)
  std::vector<std::array<int, 4>> mapped;
  // semantics-gated rules (e.g. Conv+BatchNorm fold uses running stats):
  // only legal when the search runs in inference mode
  bool inference_only = false;
};

// ---- loaders --------------------------------------------------------------

inline std::string map_ref_op_type(const std::string& t) {
  // substitution_loader.cc op-type vocabulary -> repo OperatorType names
  if (t == "OP_LINEAR") return "LINEAR";
  if (t == "OP_RELU") return "RELU";
  if (t == "OP_EW_ADD") return "EW_ADD";
  if (t == "OP_EW_MUL") return "EW_MUL";
  if (t == "OP_CONCAT") return "CONCAT";
  if (t == "OP_SPLIT") return "SPLIT";
  if (t == "OP_PARTITION") return "REPARTITION";
  if (t == "OP_COMBINE") return "COMBINE";
  if (t == "OP_REPLICATE") return "REPLICATE";
  if (t == "OP_REDUCE") return "REDUCTION";
  if (t.rfind("OP_", 0) == 0) return t.substr(3);  // best-effort passthrough
  return t;
}

inline SubstOp parse_subst_op(const Json& oj, bool reference_format) {
  SubstOp op;
  std::string t = oj.get("type").as_string();
  op.type = reference_format ? map_ref_op_type(t) : t;
  for (const Json& in : oj.get("input").items())
    op.inputs.push_back({(int)in.get("opId").as_int(),
                         (int)in.get("tsId").as_int(0)});
  for (const Json& p : oj.get("para").items())
    op.para[p.get("key").as_string()] = p.get("value").as_double();
  return op;
}

// Parses both the reference corpus ({"rule": [...]}, substitution_loader.cc
// RuleCollection) and this repo's native list-of-rules format.
inline std::vector<SubstRule> parse_rules(const Json& j) {
  std::vector<SubstRule> rules;
  const Json& arr = j.get("rule").is_null() ? j : j.get("rule");
  for (const Json& rj : arr.items()) {
    SubstRule r;
    r.name = rj.get("name").as_string();
    bool ref = !rj.get("_t").is_null();  // reference serializer tags types
    for (const Json& oj : rj.get("srcOp").items())
      r.src.push_back(parse_subst_op(oj, ref));
    for (const Json& oj : rj.get("dstOp").items())
      r.dst.push_back(parse_subst_op(oj, ref));
    for (const Json& mj : rj.get("mappedOutput").items())
      r.mapped.push_back({(int)mj.get("srcOpId").as_int(),
                          (int)mj.get("srcTsId").as_int(0),
                          (int)mj.get("dstOpId").as_int(),
                          (int)mj.get("dstTsId").as_int(0)});
    r.inference_only = rj.get("inference_only").as_bool(false);
    rules.push_back(std::move(r));
  }
  return rules;
}

// Hand-written generator rules (analog of substitution.cc:1726-1860) in
// wildcard form: $0 = dim, $1 = degree, $2 = activation, ...
inline std::vector<SubstRule> builtin_rules() {
  std::vector<SubstRule> rules;
  auto pm = [](std::initializer_list<std::pair<const char*, double>> kv) {
    std::map<std::string, double> m;
    for (auto& p : kv) m[p.first] = p.second;
    return m;
  };
  {
    // eliminate inverse pair: Combine(d,k) -> Repartition(d,k) => identity
    SubstRule r;
    r.name = "eliminate_combine_repartition";
    r.src = {{"COMBINE", {{-1, 0}}, pm({{"PM_PARALLEL_DIM", wildcard(0)},
                                        {"PM_PARALLEL_DEGREE", wildcard(1)}})},
             {"REPARTITION", {{0, 0}}, pm({{"PM_PARALLEL_DIM", wildcard(0)},
                                           {"PM_PARALLEL_DEGREE", wildcard(1)}})}};
    // dst: a bare pass-through boundary (Combine of degree 1 == no-op is
    // not constructible, so use a REPLICATE-free identity: re-emit the
    // repartition alone, which restores the layout the pair started from)
    r.dst = {{"REPARTITION", {{-1, 0}}, pm({{"PM_PARALLEL_DIM", wildcard(0)},
                                            {"PM_PARALLEL_DEGREE", wildcard(1)}})}};
    r.mapped = {{1, 0, 0, 0}};
    rules.push_back(std::move(r));
  }
  {
    // eliminate inverse pair: Repartition(d,k) -> Combine(d,k) => identity
    SubstRule r;
    r.name = "eliminate_repartition_combine";
    r.src = {{"REPARTITION", {{-1, 0}}, pm({{"PM_PARALLEL_DIM", wildcard(0)},
                                            {"PM_PARALLEL_DEGREE", wildcard(1)}})},
             {"COMBINE", {{0, 0}}, pm({{"PM_PARALLEL_DIM", wildcard(0)},
                                       {"PM_PARALLEL_DEGREE", wildcard(1)}})}};
    r.dst = {{"IDENTITY", {{-1, 0}}, {}}};
    r.mapped = {{1, 0, 0, 0}};
    rules.push_back(std::move(r));
  }
  {
    // move a Combine past a unary op so downstream work stays sharded:
    // Combine(d,k) -> RELU  =>  RELU -> Combine(d,k)
    for (const char* u : {"RELU", "GELU", "SIGMOID", "TANH", "IDENTITY"}) {
      SubstRule r;
      r.name = std::string("move_combine_past_") + u;
      r.src = {{"COMBINE", {{-1, 0}}, pm({{"PM_PARALLEL_DIM", wildcard(0)},
                                          {"PM_PARALLEL_DEGREE", wildcard(1)}})},
               {u, {{0, 0}}, {}}};
      r.dst = {{u, {{-1, 0}}, {}},
               {"COMBINE", {{0, 0}}, pm({{"PM_PARALLEL_DIM", wildcard(0)},
                                         {"PM_PARALLEL_DEGREE", wildcard(1)}})}};
      r.mapped = {{1, 0, 1, 0}};
      rules.push_back(std::move(r));
    }
  }
  {
    // fuse two same-input Linears into one wide Linear + Split
    // (TASO's concat-of-linears; one big MXU matmul beats two small ones)
    SubstRule r;
    r.name = "fuse_parallel_linears";
    r.src = {{"LINEAR", {{-1, 0}}, pm({{"PM_ACTI", wildcard(2)}})},
             {"LINEAR", {{-1, 0}}, pm({{"PM_ACTI", wildcard(2)}})}};
    r.dst = {{"LINEAR", {{-1, 0}}, pm({{"PM_ACTI", wildcard(2)},
                                       {"PM_MERGE", 1.0}})},
             {"SPLIT", {{0, 0}}, pm({{"PM_NUM_OUTPUTS", 2.0}})}};
    r.mapped = {{0, 0, 1, 0}, {1, 0, 1, 1}};
    rules.push_back(std::move(r));
  }
  {
    // QKV-projection merge: THREE same-input Linears -> one wide Linear
    // + 3-way Split (r4 algebraic family; generalizes
    // fuse_parallel_linears — the transformer q/k/v pattern)
    SubstRule r;
    r.name = "fuse_parallel_linears3";
    r.src = {{"LINEAR", {{-1, 0}}, pm({{"PM_ACTI", wildcard(2)}})},
             {"LINEAR", {{-1, 0}}, pm({{"PM_ACTI", wildcard(2)}})},
             {"LINEAR", {{-1, 0}}, pm({{"PM_ACTI", wildcard(2)}})}};
    r.dst = {{"LINEAR", {{-1, 0}}, pm({{"PM_ACTI", wildcard(2)},
                                       {"PM_MERGE", 1.0}})},
             {"SPLIT", {{0, 0}}, pm({{"PM_NUM_OUTPUTS", 3.0}})}};
    r.mapped = {{0, 0, 1, 0}, {1, 0, 1, 1}, {2, 0, 1, 2}};
    rules.push_back(std::move(r));
  }
  {
    // activation-epilogue fusion: LINEAR(none) -> act  =>  LINEAR(act).
    // On TPU the activation runs in the matmul's epilogue fusion — the
    // standalone op's dispatch + HBM round-trip disappears (r4 family).
    struct ActKind { const char* op; double acti; };
    for (ActKind a : {ActKind{"RELU", 1.0}, ActKind{"SIGMOID", 2.0},
                      ActKind{"TANH", 3.0}, ActKind{"GELU", 4.0}}) {
      SubstRule r;
      r.name = std::string("fuse_linear_") + a.op;
      r.src = {{"LINEAR", {{-1, 0}}, pm({{"PM_ACTI", 0.0}})},
               {a.op, {{0, 0}}, {}}};
      r.dst = {{"LINEAR", {{-1, 0}}, pm({{"PM_ACTI", a.acti}})}};
      r.mapped = {{1, 0, 0, 0}};
      rules.push_back(std::move(r));
    }
  }
  {
    // fuse_parallel_ops (reference substitution.cc:1925): adjacent
    // parallel-op chains collapse into ONE FusedParallelOp boundary — a
    // single reshard instead of two sequential collectives.
    for (int d1 = 0; d1 < 3; ++d1) {
      for (int d2 = 0; d2 < 3; ++d2) {
        if (d1 == d2) continue;
        // Repartition(d1) -> Combine(d2): move shards between dims
        SubstRule r;
        r.name = "fuse_parallel_ops_part" + std::to_string(d1) + "_comb" +
                 std::to_string(d2);
        r.src = {{"REPARTITION", {{-1, 0}},
                  pm({{"PM_PARALLEL_DIM", (double)d1},
                      {"PM_PARALLEL_DEGREE", wildcard(1)}})},
                 {"COMBINE", {{0, 0}},
                  pm({{"PM_PARALLEL_DIM", (double)d2},
                      {"PM_PARALLEL_DEGREE", wildcard(3)}})}};
        r.dst = {{"FUSED_PARALLEL", {{-1, 0}}, {}}};
        r.mapped = {{1, 0, 0, 0}};
        rules.push_back(std::move(r));
      }
    }
    // Combine(d) -> Replicate: gather + broadcast in one boundary
    for (int d = 0; d < 3; ++d) {
      SubstRule r;
      r.name = "fuse_parallel_ops_comb" + std::to_string(d) + "_repl";
      r.src = {{"COMBINE", {{-1, 0}},
                pm({{"PM_PARALLEL_DIM", (double)d},
                    {"PM_PARALLEL_DEGREE", wildcard(1)}})},
               {"REPLICATE", {{0, 0}}, {}}};
      r.dst = {{"FUSED_PARALLEL", {{-1, 0}}, {}}};
      r.mapped = {{1, 0, 0, 0}};
      rules.push_back(std::move(r));
    }
  }
  {
    // move Combines past a binary op: Combine(a)+Combine(b) -> EW op
    // => EW op -> Combine — one all-gather instead of two, and the
    // elementwise work stays sharded (reference's partition rules around
    // element-wise chains, substitution.cc:1726)
    for (const char* b : {"EW_ADD", "EW_MUL"}) {
      SubstRule r;
      r.name = std::string("move_combines_past_") + b;
      r.src = {{"COMBINE", {{-1, 0}}, pm({{"PM_PARALLEL_DIM", wildcard(0)},
                                          {"PM_PARALLEL_DEGREE", wildcard(1)}})},
               {"COMBINE", {{-2, 0}}, pm({{"PM_PARALLEL_DIM", wildcard(0)},
                                          {"PM_PARALLEL_DEGREE", wildcard(1)}})},
               {b, {{0, 0}, {1, 0}}, {}}};
      r.dst = {{b, {{-1, 0}, {-2, 0}}, {}},
               {"COMBINE", {{0, 0}}, pm({{"PM_PARALLEL_DIM", wildcard(0)},
                                         {"PM_PARALLEL_DEGREE", wildcard(1)}})}};
      r.mapped = {{2, 0, 1, 0}};
      rules.push_back(std::move(r));
    }
  }
  {
    // move a batch-dim Combine past shape-preserving grid ops so the conv
    // work stays sharded (create_partition_conv2d_combine analog,
    // substitution.cc:1744): Combine(0,k) -> Conv/Pool/BN
    // => Conv/Pool/BN -> Combine(0,k)
    // BATCHNORM note: under GSPMD a Combine/Repartition is a layout
    // constraint, not data movement — BatchNorm's jnp.mean over the batch
    // dim always computes GLOBAL-batch statistics (XLA inserts the
    // cross-shard reduction when the dim is sharded), so this rewrite is
    // numerics-preserving here, unlike a runtime that would compute
    // per-shard local stats (advisor r3 finding: convention documented).
    for (const char* g : {"CONV2D", "POOL2D", "BATCHNORM", "LAYERNORM"}) {
      SubstRule r;
      r.name = std::string("move_combine_past_") + g;
      r.src = {{"COMBINE", {{-1, 0}}, pm({{"PM_PARALLEL_DIM", 0.0},
                                          {"PM_PARALLEL_DEGREE", wildcard(1)}})},
               {g, {{0, 0}}, {}}};
      r.dst = {{g, {{-1, 0}}, {}},
               {"COMBINE", {{0, 0}}, pm({{"PM_PARALLEL_DIM", 0.0},
                                         {"PM_PARALLEL_DEGREE", wildcard(1)}})}};
      r.mapped = {{1, 0, 1, 0}};
      rules.push_back(std::move(r));
    }
  }
  {
    // push a Repartition above a unary op: RELU -> Repartition(d,k)
    // => Repartition(d,k) -> RELU — the elementwise work runs sharded
    // (the reference's create_partition_relu_combine, substitution.cc:1726)
    for (const char* u : {"RELU", "GELU", "SIGMOID", "TANH"}) {
      SubstRule r;
      r.name = std::string("move_repartition_before_") + u;
      r.src = {{u, {{-1, 0}}, {}},
               {"REPARTITION", {{0, 0}}, pm({{"PM_PARALLEL_DIM", wildcard(0)},
                                             {"PM_PARALLEL_DEGREE", wildcard(1)}})}};
      r.dst = {{"REPARTITION", {{-1, 0}}, pm({{"PM_PARALLEL_DIM", wildcard(0)},
                                              {"PM_PARALLEL_DEGREE", wildcard(1)}})},
               {u, {{0, 0}}, {}}};
      r.mapped = {{1, 0, 1, 0}};
      rules.push_back(std::move(r));
    }
  }
  {
    // Concat of two same-degree Combines => Concat -> one Combine, when
    // the concat axis differs from the combine dim (same-dim case would
    // interleave shard groups — unsafe). (create_partition_concat_combine
    // analog, substitution.cc:1793.)
    for (int d = 0; d < 3; ++d) {
      for (int a = 0; a < 3; ++a) {
        if (a == d) continue;
        SubstRule r;
        r.name = "concat_of_combines_d" + std::to_string(d) + "_a" +
                 std::to_string(a);
        r.src = {{"COMBINE", {{-1, 0}}, pm({{"PM_PARALLEL_DIM", (double)d},
                                            {"PM_PARALLEL_DEGREE", wildcard(1)}})},
                 {"COMBINE", {{-2, 0}}, pm({{"PM_PARALLEL_DIM", (double)d},
                                            {"PM_PARALLEL_DEGREE", wildcard(1)}})},
                 {"CONCAT", {{0, 0}, {1, 0}}, pm({{"PM_AXIS", (double)a}})}};
        r.dst = {{"CONCAT", {{-1, 0}, {-2, 0}}, pm({{"PM_AXIS", (double)a}})},
                 {"COMBINE", {{0, 0}}, pm({{"PM_PARALLEL_DIM", (double)d},
                                           {"PM_PARALLEL_DEGREE", wildcard(1)}})}};
        r.mapped = {{2, 0, 1, 0}};
        rules.push_back(std::move(r));
      }
    }
  }
  return rules;
}

// ---- matching -------------------------------------------------------------

struct Match {
  std::vector<int> node_of;                       // pattern op -> node index
  std::map<int, std::pair<int64_t, int>> ext;     // ext id -> (guid, ts)
  std::map<int, double> vars;                     // wildcard bindings
};

namespace subst_detail {

// Graph-side value of a PM constraint key on a node.
inline std::optional<double> node_param(const Node& n, const std::string& key) {
  if (key == "PM_PARALLEL_DIM") {
    const Json& v = n.attrs.get("dim");
    if (!v.is_null()) return v.as_double();
    return std::nullopt;
  }
  if (key == "PM_PARALLEL_DEGREE") {
    const Json& v = n.attrs.get("degree");
    if (!v.is_null()) return v.as_double();
    return std::nullopt;
  }
  if (key == "PM_ACTI") {
    const Json& v = n.attrs.get("activation");
    if (!v.is_null()) return v.as_double();
    return 0.0;  // AC_MODE_NONE
  }
  if (key == "PM_AXIS") {
    const Json& v = n.attrs.get("axis");
    if (!v.is_null()) return v.as_double();
    return std::nullopt;
  }
  if (key == "PM_RELU") {
    const Json& v = n.attrs.get("relu");
    if (!v.is_null()) return v.as_double();
    return 0.0;
  }
  if (key == "PM_NUM_INPUTS") return (double)n.inputs.size();
  if (key == "PM_NUM_OUTPUTS") return (double)n.output_shapes.size();
  if (key == "PM_NUMDIM")
    return n.output_shapes.empty() ? 0.0 : (double)n.output_shapes[0].size();
  return std::nullopt;  // unknown key: cannot verify -> no match
}

inline bool check_params(const SubstOp& pop, const Node& n, Match& m) {
  for (const auto& kv : pop.para) {
    auto got = node_param(n, kv.first);
    if (!got) return false;
    if (is_wildcard(kv.second)) {
      int var = wildcard_var(kv.second);
      auto it = m.vars.find(var);
      if (it == m.vars.end())
        m.vars[var] = *got;
      else if (it->second != *got)
        return false;
    } else if (*got != kv.second) {
      return false;
    }
  }
  return true;
}

}  // namespace subst_detail

// All matches of `rule.src` in `g`. A matched internal tensor may not have
// consumers outside the match unless it is a mapped output (the reference's
// "no external uses of intermediates" check in GraphXfer::match).
inline std::vector<Match> find_matches(const Graph& g, const SubstRule& rule,
                                       size_t limit = 16) {
  std::vector<Match> out;
  const size_t P = rule.src.size();
  if (P == 0) return out;
  Match m;
  m.node_of.assign(P, -1);
  std::vector<bool> used(g.nodes.size(), false);

  // which (srcOp, ts) pairs escape via mappedOutput
  std::set<std::pair<int, int>> mapped_src;
  for (const auto& mo : rule.mapped) mapped_src.insert({mo[0], mo[1]});

  std::function<bool(size_t)> try_op = [&](size_t pi) -> bool {
    if (out.size() >= limit) return true;
    if (pi == P) {
      // verify intermediates have no external consumers
      std::set<int> in_match(m.node_of.begin(), m.node_of.end());
      for (size_t i = 0; i < P; ++i) {
        const Node& n = g.nodes[m.node_of[i]];
        for (size_t ts = 0; ts < n.output_shapes.size(); ++ts) {
          if (mapped_src.count({(int)i, (int)ts})) continue;
          auto it = g.consumers.find(n.guid);
          if (it == g.consumers.end()) continue;
          for (const auto& c : it->second) {
            // consumer must be inside the match and reference this ts
            const Node& cn = g.nodes[c.first];
            const EdgeRef& e = cn.inputs[c.second];
            if (e.src_idx == (int)ts && !in_match.count(c.first)) return false;
          }
        }
      }
      out.push_back(m);
      return out.size() >= limit;
    }
    const SubstOp& pop = rule.src[pi];
    for (size_t ni = 0; ni < g.nodes.size(); ++ni) {
      if (used[ni]) continue;
      const Node& n = g.nodes[ni];
      if (n.type != pop.type) continue;
      if (n.inputs.size() != pop.inputs.size()) continue;
      Match saved = m;
      bool ok = subst_detail::check_params(pop, n, m);
      // edge consistency
      for (size_t slot = 0; ok && slot < pop.inputs.size(); ++slot) {
        auto [src_op, src_ts] = pop.inputs[slot];
        const EdgeRef& e = n.inputs[slot];
        if (src_op >= 0) {
          // must come from already-matched pattern op (patterns are listed
          // in topological order in both formats)
          int mn = m.node_of[src_op];
          if (mn < 0 || e.src_guid != g.nodes[mn].guid || e.src_idx != src_ts)
            ok = false;
        } else {
          auto key = src_op * 1000 + src_ts;  // unique ext id
          auto it = m.ext.find(key);
          std::pair<int64_t, int> ref{e.src_guid, e.src_idx};
          if (it == m.ext.end())
            m.ext[key] = ref;
          else if (it->second != ref)
            ok = false;
        }
      }
      if (ok) {
        m.node_of[pi] = static_cast<int>(ni);
        used[ni] = true;
        if (try_op(pi + 1)) return true;
        used[ni] = false;
      }
      m = std::move(saved);
      m.node_of[pi] = -1;
    }
    return false;
  };
  try_op(0);
  return out;
}

// ---- application ----------------------------------------------------------

struct RewriteTraceEntry {
  std::string rule;
  std::vector<int64_t> removed;  // guids of removed nodes
  Json added = Json::array();    // node descriptors Python can rebuild
  // (old_guid, old_ts, new_guid, new_ts) for rule-mapped outputs, so the
  // caller can chase the model's final output through rewrites
  std::vector<std::array<int64_t, 4>> output_remap;
};

namespace subst_detail {

inline Json shape_json(const Shape& s) {
  Json a = Json::array();
  for (int64_t d : s) a.push_back(Json(d));
  return a;
}

}  // namespace subst_detail

// Apply `rule` at `match`. Returns the rewritten graph or nullopt when the
// replacement cannot be constructed (shape mismatch / non-inferable op).
inline std::optional<Graph> apply_rule(const Graph& g, const SubstRule& rule,
                                       const Match& match, int64_t* next_guid,
                                       RewriteTraceEntry* trace) {
  // resolve a pattern-side tensor ref to a (guid, ts) in the new graph
  std::set<int> removed_idx(match.node_of.begin(), match.node_of.end());

  // dst op j of type T inherits attrs/params from the j-th src op of type T
  std::map<std::string, std::vector<int>> src_of_type;
  for (size_t i = 0; i < rule.src.size(); ++i)
    src_of_type[rule.src[i].type].push_back(match.node_of[i]);
  std::map<std::string, size_t> taken;

  std::vector<Node> new_nodes;
  std::vector<std::pair<int64_t, int>> dst_out_ref(rule.dst.size() * 4,
                                                   {-1, 0});
  auto dst_ref = [&](int op, int ts) { return dst_out_ref[op * 4 + ts]; };

  // sentinel for "dst uses an external the src pattern never bound" — must
  // not collide with real graph-input ids (small negative guids)
  constexpr int64_t kUnbound = INT64_MIN;
  auto ext_ref = [&](int op_id, int ts_id) -> std::pair<int64_t, int> {
    auto it = match.ext.find(op_id * 1000 + ts_id);
    if (it != match.ext.end()) return it->second;
    return {kUnbound, 0};
  };

  auto para_val = [&](const SubstOp& op, const char* key,
                      double dflt) -> double {
    auto it = op.para.find(key);
    if (it == op.para.end()) return dflt;
    if (is_wildcard(it->second)) {
      auto vit = match.vars.find(wildcard_var(it->second));
      return vit == match.vars.end() ? dflt : vit->second;
    }
    return it->second;
  };

  // shape of a tensor ref (graph node / new node / graph input)
  auto shape_of = [&](std::pair<int64_t, int> ref) -> std::optional<Shape> {
    if (ref.first < 0) {
      // graph input: find a node consuming this exact external id
      for (const Node& n : g.nodes)
        for (size_t s = 0; s < n.inputs.size(); ++s)
          if (n.inputs[s].src_guid == ref.first &&
              s < n.input_shapes.size())
            return n.input_shapes[s];
      return std::nullopt;
    }
    auto it = g.index_of.find(ref.first);
    if (it != g.index_of.end())
      return g.nodes[it->second].output_shapes[ref.second];
    for (const Node& n : new_nodes)
      if (n.guid == ref.first) return n.output_shapes[ref.second];
    return std::nullopt;
  };

  for (size_t di = 0; di < rule.dst.size(); ++di) {
    const SubstOp& dop = rule.dst[di];
    Node n;
    n.guid = (*next_guid)++;
    n.type = dop.type;
    n.name = rule.name + "_" + std::to_string(n.guid);
    // inherit from positional same-type src op when available
    int inherit = -1;
    auto& avail = src_of_type[dop.type];
    size_t& k = taken[dop.type];
    if (k < avail.size()) inherit = avail[k++];
    const Node* base = inherit >= 0 ? &g.nodes[inherit] : nullptr;
    if (base) {
      n.attrs = base->attrs;
      n.params = base->params;
      n.dtype_size = base->dtype_size;
      n.fwd_flops = base->fwd_flops;
    } else {
      n.dtype_size = g.nodes[match.node_of[0]].dtype_size;
    }

    // wire inputs + collect input shapes
    std::vector<Shape> in_shapes;
    for (auto [op_id, ts_id] : dop.inputs) {
      std::pair<int64_t, int> ref =
          op_id >= 0 ? dst_ref(op_id, ts_id) : ext_ref(op_id, ts_id);
      if (ref.first == kUnbound) return std::nullopt;
      n.inputs.push_back({ref.first, ref.second});
      auto shp = shape_of(ref);
      if (!shp) return std::nullopt;
      in_shapes.push_back(*shp);
    }
    n.input_shapes = in_shapes;

    // local shape/attr inference per type
    const std::string& t = n.type;
    if (t == "REPARTITION" || t == "COMBINE" || t == "REPLICATE") {
      if (in_shapes.size() != 1) return std::nullopt;
      Json attrs = Json::object();
      attrs.set("dim", Json((int64_t)para_val(dop, "PM_PARALLEL_DIM", 0)));
      attrs.set("degree", Json((int64_t)para_val(dop, "PM_PARALLEL_DEGREE", 1)));
      n.attrs = attrs;
      n.output_shapes = {in_shapes[0]};
      int64_t dim = (int64_t)para_val(dop, "PM_PARALLEL_DIM", 0);
      int64_t deg = (int64_t)para_val(dop, "PM_PARALLEL_DEGREE", 1);
      if (t != "REPLICATE" &&
          (dim < 0 || dim >= (int64_t)in_shapes[0].size() ||
           deg <= 0 || in_shapes[0][dim] % deg))
        return std::nullopt;
      n.fwd_flops = 0;
    } else if (t == "REDUCTION") {
      // explicit-shape form: reduces groups along the dim — reference
      // replica-dim rules won't shape-check and are skipped here
      if (in_shapes.size() != 1) return std::nullopt;
      int64_t dim = (int64_t)para_val(dop, "PM_PARALLEL_DIM", 0);
      int64_t deg = (int64_t)para_val(dop, "PM_PARALLEL_DEGREE", 1);
      if (dim < 0 || dim >= (int64_t)in_shapes[0].size() || deg <= 0 ||
          in_shapes[0][dim] % deg)
        return std::nullopt;
      Shape s = in_shapes[0];
      s[dim] /= deg;
      Json attrs = Json::object();
      attrs.set("dim", Json(dim));
      attrs.set("degree", Json(deg));
      n.attrs = attrs;
      n.output_shapes = {s};
      n.fwd_flops = (double)shape_elems(in_shapes[0]);
    } else if (t == "IDENTITY" || t == "RELU" || t == "GELU" ||
               t == "SIGMOID" || t == "TANH" || t == "ELU" || t == "EXP" ||
               t == "SIN" || t == "COS" || t == "RSQRT" || t == "DROPOUT" ||
               t == "CAST" || t.rfind("SCALAR_", 0) == 0) {
      if (in_shapes.size() != 1) return std::nullopt;
      n.output_shapes = {in_shapes[0]};
      n.fwd_flops = (double)shape_elems(in_shapes[0]);
      n.params.clear();
    } else if (t == "CONV2D" || t == "POOL2D" || t == "BATCHNORM" ||
               t == "LAYERNORM") {
      // shape-preserving re-emission: the dst op must inherit from a
      // matched src op of the same type with identical input shape (rules
      // only move layout boundaries around these; nothing is resized)
      if (base == nullptr || in_shapes.empty() ||
          base->input_shapes.empty() || in_shapes[0] != base->input_shapes[0])
        return std::nullopt;
      n.output_shapes = base->output_shapes;
      n.fwd_flops = base->fwd_flops;
      n.params = base->params;
      // BN-fold overrides: the folded conv gains a bias and possibly the
      // BN's fused relu
      double acti = para_val(dop, "PM_ACTI", -1.0);
      double ub = para_val(dop, "PM_USE_BIAS", -1.0);
      if (acti >= 0 || ub >= 0) {
        Json attrs = n.attrs;
        if (acti >= 0) attrs.set("activation", Json(acti));
        if (ub >= 0) attrs.set("use_bias", Json((int64_t)ub));
        n.attrs = attrs;
        if (ub > 0 && !n.params.count("bias") && !n.output_shapes.empty() &&
            n.output_shapes[0].size() == 4)
          n.params["bias"] = {n.output_shapes[0][1]};  // NCHW channels
      }
    } else if (t == "FUSED_PARALLEL") {
      // fuse_parallel_ops: collapse the matched parallel-op chain into
      // one boundary. Steps come from the matched src ops in pattern
      // order; only non-REDUCTION steps are generated (shape-preserving).
      if (in_shapes.size() != 1) return std::nullopt;
      Json steps = Json::array();
      for (size_t si = 0; si < rule.src.size(); ++si) {
        const std::string& st_ = rule.src[si].type;
        if (st_ != "REPARTITION" && st_ != "COMBINE" && st_ != "REPLICATE")
          continue;
        const Node& sn = g.nodes[match.node_of[si]];
        int64_t dim = sn.attrs.get("dim").as_int(0);
        int64_t deg = sn.attrs.get("degree").as_int(1);
        Json step = Json::array();
        step.push_back(Json(st_));
        step.push_back(Json(dim));
        step.push_back(Json(deg));
        if (st_ == "REPARTITION" &&
            (dim < 0 || dim >= (int64_t)in_shapes[0].size() || deg <= 0 ||
             in_shapes[0][dim] % deg))
          return std::nullopt;
        steps.push_back(step);
      }
      if (steps.items().empty()) return std::nullopt;
      Json attrs = Json::object();
      attrs.set("ops", steps);
      n.attrs = attrs;
      n.output_shapes = {in_shapes[0]};
      n.fwd_flops = 0;
      n.params.clear();
    } else if (t.rfind("EW_", 0) == 0) {
      if (in_shapes.size() != 2) return std::nullopt;
      const Shape &a = in_shapes[0], &b = in_shapes[1];
      // Soundness: rules that move parallel ops across a binary assume
      // dim index i means the same logical axis in BOTH operands; under
      // rank-mismatched broadcast (e.g. bias [D] against [B,S,D]) dim 0
      // of the low-rank operand is a different axis and the rewrite
      // would shard operands inconsistently. Equal rank restores the
      // correspondence; size-1 broadcast dims stay safe because the
      // parallel-op emission's divisibility check (1 % deg) rejects
      // sharding them.
      if (a.size() != b.size()) return std::nullopt;
      size_t rank = std::max(a.size(), b.size());
      Shape o(rank, 1);
      for (size_t i = 0; i < rank; ++i) {
        int64_t da = i < rank - a.size() ? 1 : a[i - (rank - a.size())];
        int64_t db = i < rank - b.size() ? 1 : b[i - (rank - b.size())];
        if (da != db && da != 1 && db != 1) return std::nullopt;
        o[i] = std::max(da, db);
      }
      n.output_shapes = {o};
      n.fwd_flops = (double)shape_elems(o);
      n.params.clear();
    } else if (t == "LINEAR") {
      if (in_shapes.size() != 1 || in_shapes[0].empty()) return std::nullopt;
      int64_t in_dim = in_shapes[0].back();
      int64_t out_dim;
      if (para_val(dop, "PM_MERGE", 0.0) > 0) {
        // wide fusion: out = sum of all matched src linears' out dims
        out_dim = 0;
        for (int si : src_of_type["LINEAR"]) {
          const Node& sn = g.nodes[si];
          auto kit = sn.params.find("kernel");
          if (kit == sn.params.end() || kit->second.size() != 2 ||
              kit->second[0] != in_dim)
            return std::nullopt;
          out_dim += kit->second[1];
        }
      } else if (base) {
        auto kit = base->params.find("kernel");
        if (kit == base->params.end() || kit->second.size() != 2 ||
            kit->second[0] != in_dim)
          return std::nullopt;
        out_dim = kit->second[1];
      } else {
        return std::nullopt;  // no source to infer the weight from
      }
      Shape o = in_shapes[0];
      o.back() = out_dim;
      n.output_shapes = {o};
      n.params.clear();
      n.params["kernel"] = {in_dim, out_dim};
      n.params["bias"] = {out_dim};
      int64_t rows = 1;
      for (size_t i = 0; i + 1 < in_shapes[0].size(); ++i)
        rows *= in_shapes[0][i];
      n.fwd_flops = 2.0 * rows * in_dim * out_dim;
      Json attrs = base ? base->attrs : Json::object();
      attrs.set("out_dim", Json(out_dim));
      double acti = para_val(dop, "PM_ACTI", -1.0);
      if (acti >= 0) attrs.set("activation", Json(acti));
      n.attrs = attrs;
    } else if (t == "CONCAT") {
      if (in_shapes.empty()) return std::nullopt;
      int64_t axis = (int64_t)para_val(dop, "PM_AXIS", 0);
      if (axis < 0 || axis >= (int64_t)in_shapes[0].size()) return std::nullopt;
      Shape o = in_shapes[0];
      o[axis] = 0;
      for (const Shape& s : in_shapes) {
        if (s.size() != o.size()) return std::nullopt;
        for (size_t i = 0; i < s.size(); ++i)
          if ((int64_t)i != axis && s[i] != o[i]) return std::nullopt;
        o[axis] += s[axis];
      }
      Json attrs = Json::object();
      attrs.set("axis", Json(axis));
      n.attrs = attrs;
      n.output_shapes = {o};
      n.fwd_flops = 0;
      n.params.clear();
    } else if (t == "SPLIT") {
      if (in_shapes.size() != 1) return std::nullopt;
      // split the last dim back into the matched linears' out widths when
      // this is the fusion rule's tail; otherwise equal split via
      // PM_NUM_OUTPUTS on PM_AXIS
      int64_t axis = (int64_t)para_val(
          dop, "PM_AXIS", (double)(in_shapes[0].size() - 1));
      int64_t nout = (int64_t)para_val(dop, "PM_NUM_OUTPUTS", 2);
      if (axis < 0 || axis >= (int64_t)in_shapes[0].size() || nout <= 0)
        return std::nullopt;
      std::vector<int64_t> sizes;
      auto& lins = src_of_type["LINEAR"];
      if ((int64_t)lins.size() == nout) {
        for (int si : lins) {
          auto kit = g.nodes[si].params.find("kernel");
          if (kit == g.nodes[si].params.end()) return std::nullopt;
          sizes.push_back(kit->second[1]);
        }
      } else {
        if (in_shapes[0][axis] % nout) return std::nullopt;
        sizes.assign(nout, in_shapes[0][axis] / nout);
      }
      int64_t total = 0;
      for (int64_t s : sizes) total += s;
      if (total != in_shapes[0][axis]) return std::nullopt;
      for (int64_t sz : sizes) {
        Shape o = in_shapes[0];
        o[axis] = sz;
        n.output_shapes.push_back(o);
      }
      Json attrs = Json::object();
      attrs.set("axis", Json(axis));
      Json szs = Json::array();
      for (int64_t s : sizes) szs.push_back(Json(s));
      attrs.set("sizes", szs);
      n.attrs = attrs;
      n.fwd_flops = 0;
      n.params.clear();
    } else {
      return std::nullopt;  // unsupported dst op type
    }

    // roles: copy from inherited src, else sample+other
    if (base && !base->roles.empty() &&
        base->output_shapes.size() == n.output_shapes.size()) {
      n.roles = base->roles;
    } else {
      n.roles.clear();
      for (const Shape& s : n.output_shapes) {
        std::vector<Role> rr(s.size(), Role::Other);
        if (!rr.empty()) rr[0] = Role::Sample;
        n.roles.push_back(rr);
      }
    }

    for (size_t ts = 0; ts < n.output_shapes.size() && ts < 4; ++ts)
      dst_out_ref[di * 4 + ts] = {n.guid, (int)ts};
    new_nodes.push_back(std::move(n));
  }

  // output remap: (src guid, ts) -> (dst guid, ts)
  std::map<std::pair<int64_t, int>, std::pair<int64_t, int>> remap;
  for (const auto& mo : rule.mapped) {
    int64_t sg = g.nodes[match.node_of[mo[0]]].guid;
    remap[{sg, mo[1]}] = dst_ref(mo[2], mo[3]);
  }

  // splice: keep unmatched nodes, rewiring consumers of mapped outputs;
  // insert new nodes right where the first matched node stood (keeps
  // topological order because dst inputs are externals or earlier dst ops)
  Graph out;
  size_t insert_at = g.nodes.size();
  for (size_t i = 0; i < g.nodes.size(); ++i)
    if (removed_idx.count((int)i)) { insert_at = i; break; }

  std::set<std::pair<int64_t, int>> unmapped_removed;
  for (int ni : match.node_of) {
    const Node& n = g.nodes[ni];
    for (size_t ts = 0; ts < n.output_shapes.size(); ++ts)
      if (!remap.count({n.guid, (int)ts}))
        unmapped_removed.insert({n.guid, (int)ts});
  }

  for (size_t i = 0; i < g.nodes.size(); ++i) {
    if (i == insert_at)
      for (Node& nn : new_nodes) out.nodes.push_back(nn);
    if (removed_idx.count((int)i)) continue;
    Node n = g.nodes[i];
    for (EdgeRef& e : n.inputs) {
      auto it = remap.find({e.src_guid, e.src_idx});
      if (it != remap.end()) {
        e.src_guid = it->second.first;
        e.src_idx = it->second.second;
      } else if (unmapped_removed.count({e.src_guid, e.src_idx})) {
        return std::nullopt;  // consumer of an output the rule dropped
      }
    }
    out.nodes.push_back(std::move(n));
  }
  if (insert_at == g.nodes.size())
    for (Node& nn : new_nodes) out.nodes.push_back(nn);

  for (size_t i = 0; i < out.nodes.size(); ++i)
    out.index_of[out.nodes[i].guid] = static_cast<int>(i);
  for (size_t i = 0; i < out.nodes.size(); ++i)
    for (size_t slot = 0; slot < out.nodes[i].inputs.size(); ++slot) {
      const EdgeRef& r = out.nodes[i].inputs[slot];
      if (r.src_guid >= 0) {
        if (!out.index_of.count(r.src_guid)) return std::nullopt;
        out.consumers[r.src_guid].push_back({(int)i, (int)slot});
      }
    }

  if (trace) {
    trace->rule = rule.name;
    for (int ni : match.node_of) trace->removed.push_back(g.nodes[ni].guid);
    for (const auto& kv : remap)
      trace->output_remap.push_back({kv.first.first, (int64_t)kv.first.second,
                                     kv.second.first,
                                     (int64_t)kv.second.second});
    for (const Node& nn : new_nodes) {
      Json nd = Json::object();
      nd.set("guid", Json(nn.guid));
      nd.set("type", Json(nn.type));
      nd.set("name", Json(nn.name));
      Json ins = Json::array();
      for (const EdgeRef& e : nn.inputs) {
        Json pair = Json::array();
        pair.push_back(Json((int64_t)e.src_guid));
        pair.push_back(Json((int64_t)e.src_idx));
        ins.push_back(pair);
      }
      nd.set("inputs", ins);
      nd.set("attrs", nn.attrs);
      Json oshp = Json::array();
      for (const Shape& s : nn.output_shapes)
        oshp.push_back(subst_detail::shape_json(s));
      nd.set("output_shapes", oshp);
      trace->added.push_back(nd);
    }
  }
  return out;
}

// Structural hash for the seen-set of the best-first loop.
inline std::string graph_key(const Graph& g) {
  std::string k;
  for (const Node& n : g.nodes) {
    k += n.type;
    k += ':';
    for (const EdgeRef& e : n.inputs) {
      k += std::to_string(e.src_guid);
      k += '.';
      k += std::to_string(e.src_idx);
      k += ',';
    }
    for (const Shape& s : n.output_shapes)
      for (int64_t d : s) {
        k += std::to_string(d);
        k += 'x';
      }
    k += n.attrs.dump();
    k += ';';
  }
  return k;
}

}  // namespace ffsearch

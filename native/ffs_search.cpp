// Unity-style auto-parallelization search — native core.
//
// Re-implements the algorithms of the reference's search stack for the
// TPU/GSPMD target (SURVEY §2.5):
//
//  * frontier DP with memoized sharding states  ≙ SearchHelper's
//    find_optimal_{sequence,nonsequence}_graph_time (graph.h:170): at the
//    graph's bottleneck (post-dominator) nodes the live-tensor frontier
//    collapses to one spec, which is exactly where the reference memoizes
//    sequence splits; between bottlenecks the beam bounds the state set.
//  * alpha pruning + budget-scaled beam          ≙ base_optimize's
//    best-first queue with `cur > best*alpha` discard (substitution.cc:2229).
//  * memory-aware lambda binary search           ≙ try_one_lambda /
//    graph_optimize_with_memory (graph.cc:1883, substitution.cc:1960).
//  * MCMC simulated annealing refinement         ≙ FFModel::mcmc_optimize
//    (model.h:795): random re-choice proposals evaluated by the taskgraph
//    simulator, accepted with exp(-alpha*delta).
//  * outer mesh-shape enumeration                ≙ MachineView enumeration
//    (get_valid_machine_views): on TPU the view space is the set of
//    (data, model) mesh factorizations of the chip count.
//
// Input / output: JSON (see flexflow_tpu/search/unity.py for the schema).
#include <algorithm>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <queue>
#include <random>
#include <set>
#include <string>
#include <utility>
#include <vector>

#include "ffs_graph.hpp"
#include "ffs_json.hpp"
#include "ffs_machine.hpp"
#include "ffs_sim.hpp"
#include "ffs_strategy.hpp"
#include "ffs_subst.hpp"

namespace ffsearch {
namespace {

struct SearchConfig {
  int budget = 0;
  double alpha = 0.05;
  bool only_data_parallel = false;
  bool enable_parameter_parallel = true;
  bool overlap = true;
  bool training = true;
  double memory_threshold = 0;  // bytes; 0 = machine hbm_cap
  double opt_state_factor = 2.0;
  int beam = 0;  // 0 = auto from budget
  unsigned seed = 0;
  int64_t batch = 0;  // global batch size; dp must divide it (0 = unconstrained)
  bool enable_substitution = true;  // graph-rewrite outer loop
  bool enable_sample_parallel = true;  // 2-D batch partition (config.h:134)
  bool enable_pipeline_parallel = true;  // GPipe over a 'pipe' axis (r4)
  int pipeline_microbatches = 0;    // 0 = auto (sweep the divisor lattice
                                    // of batch/dp inside the pipe eval)
  std::string pipeline_schedule = "auto";  // auto | gpipe | circular
  bool pipeline_shard_queue = true;  // price the sharded microbatch queue
                                     // (--pipeline-replicated-queue = false)
  int subst_budget = 0;             // best-first expansions (0 = from budget)
  bool perform_fusion = true;       // fuse_parallel_ops rule family
                                    // (reference --disable-fusion)
  bool enable_wus = true;           // weight-update-sharding choice variants
                                    // (--weight-update-sharding != off)
  bool enable_overlap = true;       // comms-compute-overlap "_ovl" choice
                                    // variants (--overlap-bucket-mb != 0)
  bool enable_kernels = true;       // kernel-implementation "_k:<impl>"
                                    // choice twins (--kernel-search !=
                                    // off / FFS_NO_KERNEL_SEARCH unset)
  bool enable_remat = true;         // rematerialization "_r" choice twins
                                    // + the pipeline body_remat dimension
                                    // (--remat-search != off /
                                    // FFS_NO_REMAT unset)
  bool emit_trace = false;          // structured search-trace emission
                                    // (search provenance; explain.py /
                                    // obs .searchtrace.json artifact)
  std::map<std::string, std::vector<std::string>> allowed;  // op type -> choice names

  static SearchConfig from_json(const Json& j) {
    SearchConfig c;
    c.budget = (int)j.get("budget").as_int(0);
    c.alpha = j.get("alpha").as_double(0.05);
    c.only_data_parallel = j.get("only_data_parallel").as_bool(false);
    c.enable_parameter_parallel = j.get("enable_parameter_parallel").as_bool(true);
    c.overlap = j.get("overlap").as_bool(true);
    c.training = j.get("training").as_bool(true);
    c.memory_threshold = j.get("memory_threshold").as_double(0);
    c.opt_state_factor = j.get("opt_state_factor").as_double(2.0);
    c.beam = (int)j.get("beam").as_int(0);
    c.seed = (unsigned)j.get("seed").as_int(0);
    c.batch = j.get("batch").as_int(0);
    c.enable_substitution = j.get("enable_substitution").as_bool(true);
    c.enable_sample_parallel = j.get("enable_sample_parallel").as_bool(true);
    c.enable_pipeline_parallel = j.get("enable_pipeline_parallel").as_bool(true);
    c.pipeline_microbatches = (int)j.get("pipeline_microbatches").as_int(0);
    std::string sched = j.get("pipeline_schedule").as_string();
    if (!sched.empty()) c.pipeline_schedule = sched;
    c.pipeline_shard_queue = j.get("pipeline_shard_queue").as_bool(true);
    // best-first expansions scale with the user's budget (r5; the old
    // min(budget,16) cap could not exploit a 640-rule corpus)
    c.subst_budget = (int)j.get("subst_budget").as_int(
        std::max(1, std::min(4 * c.budget, 256)));
    c.perform_fusion = j.get("perform_fusion").as_bool(true);
    // "auto"/"on" enumerate the _wus twins (the DP picks per mesh);
    // "off" removes the dimension entirely
    c.enable_wus = j.get("weight_update_sharding").as_string() != "off";
    // "auto"/"on"/explicit-bucket enumerate the "_ovl" latency-hiding
    // twins (the DP picks per op); "off" removes the dimension
    c.enable_overlap = j.get("comm_overlap").as_string() != "off";
    // "auto" enumerates the "_k:<impl>" kernel twins (flash attention,
    // fused optimizer update, train-time Conv+BN — ffs_strategy.hpp);
    // "off" removes the dimension entirely (FFS_NO_KERNEL_SEARCH's
    // bit-identical pre-kernel-search escape hatch)
    c.enable_kernels = j.get("kernel_search").as_string() != "off";
    // "auto" spawns the "_r" remat twins + the pipeline body-remat
    // dimension; "off" removes the dimension entirely (FFS_NO_REMAT's
    // bit-identical pre-remat-search escape hatch)
    c.enable_remat = j.get("remat_search").as_string() != "off";
    c.emit_trace = j.get("emit_search_trace").as_bool(false);
    for (const Json& r : j.get("rules").items()) {
      std::vector<std::string> names;
      for (const Json& a : r.get("allow").items()) names.push_back(a.as_string());
      c.allowed[r.get("op_type").as_string()] = names;
    }
    return c;
  }
};

using Assignment = std::vector<int>;  // choice index per node

struct DPResult {
  Assignment assign;
  double cost = 1e30;
  double memory = 0;
  int64_t states = 0;
  bool ok = false;
};

// All sharding choices per node, pre-filtered by substitution rules.
std::vector<std::vector<Choice>> all_choices(const Graph& g, const MeshShape& mesh,
                                             const SearchConfig& cfg) {
  std::vector<std::vector<Choice>> out;
  for (const Node& n : g.nodes) {
    auto cs = enumerate_choices(n, mesh,
                                cfg.enable_parameter_parallel &&
                                    !cfg.only_data_parallel,
                                cfg.enable_sample_parallel &&
                                    !cfg.only_data_parallel,
                                // WUS twins exist on pipe meshes too: the
                                // pipeline executor reduce-scatters the
                                // stacked body grads over the data axes
                                cfg.enable_wus && cfg.training,
                                // "_ovl" latency-hiding twins: only
                                // meaningful in training (gradient sync)
                                cfg.enable_overlap && cfg.training,
                                // "_k:<impl>" kernel twins (flash applies
                                // at inference too; fused/conv_bn_fused
                                // gate on `training` inside). Not on pipe
                                // meshes: the pipeline executor has no
                                // per-op kernel plumbing yet — pricing a
                                // lowering it cannot deliver would
                                // misrank strategies (the _ovl lesson).
                                cfg.enable_kernels && mesh.pp == 1,
                                cfg.training,
                                // "_r" remat twins. Not on pipe meshes:
                                // body ops run through the stacked block
                                // template, which has no per-op
                                // checkpoint plumbing — pipe meshes get
                                // the block-level body_remat dimension
                                // (simulate_pipeline) instead.
                                cfg.enable_remat && cfg.training &&
                                    mesh.pp == 1);
    auto it = cfg.allowed.find(n.type);
    if (it != cfg.allowed.end()) {
      std::vector<Choice> kept;
      for (auto& c : cs)
        if (std::find(it->second.begin(), it->second.end(), c.name) !=
            it->second.end())
          kept.push_back(std::move(c));
      if (kept.empty())
        throw std::runtime_error(
            "substitution rule for " + n.type +
            " allows no legal choice on this mesh (check choice names)");
      cs = std::move(kept);
    }
    out.push_back(std::move(cs));
  }
  return out;
}

// ---- frontier DP ----------------------------------------------------------

struct DPState {
  // spec per live tensor, in live-list order
  std::vector<Spec> frontier;
  double cost = 0;
  double memory = 0;
  // liveness accounting (inference: activations free at last use, so the
  // metric is peak live + params — the bump-allocator role of reference
  // simulator.h:699-700; training keeps the saved-residual sum in
  // `memory` directly)
  double act_live = 0, act_peak = 0, param_mem = 0;
  Assignment assign;

  std::string key() const {
    std::string k;
    k.reserve(frontier.size() * 4);
    for (const Spec& s : frontier) {
      for (int8_t e : s) k += static_cast<char>(e + 2);
      k += '|';
    }
    return k;
  }
};

// `evo` (optional): per-node frontier-evolution rows for the search
// trace — how many states each layer expanded, how many survived the
// spec-key dedup (a duplicate key losing on cost = "dominated"), the
// alpha cut and the beam. One row per node keeps the trace O(N).
DPResult frontier_dp(const Graph& g, const std::vector<std::vector<Choice>>& choices,
                     const MeshShape& mesh, const MachineModel& m,
                     const SearchConfig& cfg, double lambda,
                     const MeasuredCosts* measured, Json* evo = nullptr) {
  const size_t N = g.nodes.size();
  // remaining-use counts per (guid, out_idx)
  std::map<std::pair<int64_t, int>, int> uses;
  for (const Node& n : g.nodes)
    for (const EdgeRef& e : n.inputs)
      if (e.src_guid >= 0) uses[{e.src_guid, e.src_idx}]++;

  int beam = cfg.beam > 0 ? cfg.beam
                          : std::min(2048, std::max(128, 32 * std::max(1, cfg.budget)));
  double reshard_factor = cfg.training ? 2.0 : 1.0;

  // live tensor list maintained in parallel across all states
  std::vector<std::pair<int64_t, int>> live;
  std::vector<DPState> states(1);
  DPResult res;

  for (size_t i = 0; i < N; ++i) {
    const Node& n = g.nodes[i];
    // positions of this node's inputs in the live list
    std::vector<int> in_pos(n.inputs.size(), -1);
    for (size_t slot = 0; slot < n.inputs.size(); ++slot) {
      const EdgeRef& e = n.inputs[slot];
      if (e.src_guid < 0) continue;
      for (size_t p = 0; p < live.size(); ++p)
        if (live[p].first == e.src_guid && live[p].second == e.src_idx) {
          in_pos[slot] = static_cast<int>(p);
          break;
        }
    }
    // next live list: drop fully-consumed, append new outputs w/ consumers
    std::vector<std::pair<int64_t, int>> next_live;
    std::vector<int> keep_pos;
    std::map<std::pair<int64_t, int>, int> uses_after = uses;
    for (const EdgeRef& e : n.inputs)
      if (e.src_guid >= 0) uses_after[{e.src_guid, e.src_idx}]--;
    for (size_t p = 0; p < live.size(); ++p)
      if (uses_after[live[p]] > 0) {
        keep_pos.push_back(static_cast<int>(p));
        next_live.push_back(live[p]);
      }
    std::vector<int> new_out;
    for (size_t oi = 0; oi < n.output_shapes.size(); ++oi)
      if (uses.count({n.guid, (int)oi}) && uses[{n.guid, (int)oi}] > 0) {
        new_out.push_back(static_cast<int>(oi));
        next_live.push_back({n.guid, (int)oi});
      }
    uses = std::move(uses_after);

    // keep-mask for the liveness free computation (per boundary, not per
    // choice): positions of `live` NOT carried into next_live
    std::vector<char> kept_mask(live.size(), 0);
    for (int p : keep_pos) kept_mask[p] = 1;

    std::map<std::string, DPState> next;
    double best_cost = 1e30;
    for (const DPState& st : states) {
      // bytes freed when this node consumes its inputs' last use —
      // depends on the state's frontier specs only, hoisted out of the
      // choice loop
      double st_dropped = 0;
      if (!cfg.training) {
        for (size_t p = 0; p < live.size(); ++p) {
          if (kept_mask[p]) continue;
          int pi2 = g.index_of.at(live[p].first);
          st_dropped += (double)g.nodes[pi2].output_bytes(live[p].second) /
                        shards_of(st.frontier[p], mesh);
        }
      }
      for (size_t ci = 0; ci < choices[i].size(); ++ci) {
        const Choice& c = choices[i][ci];
        double cost = st.cost;
        // input reshard costs
        for (size_t slot = 0; slot < n.inputs.size(); ++slot) {
          if (in_pos[slot] < 0) continue;
          const Spec& prod = st.frontier[in_pos[slot]];
          const Spec& need = slot < c.in.size() ? c.in[slot] : prod;
          int pi = g.index_of.at(n.inputs[slot].src_guid);
          cost += reshard_factor *
                  reshard_cost(prod, need,
                               (double)g.nodes[pi].output_bytes(n.inputs[slot].src_idx),
                               mesh, m);
        }
        NodeCost nc = node_cost(n, c, mesh, m, cfg.training, measured,
                                cfg.opt_state_factor);
        cost += nc.total();
        double pmem = node_param_memory(n, c, mesh, cfg.opt_state_factor);
        double amem = node_act_bytes(n, c, mesh);
        cost += lambda * (pmem + amem);
        DPState ns;
        ns.cost = cost;
        ns.assign = st.assign;
        ns.assign.push_back(static_cast<int>(ci));
        if (cfg.training) {
          // every activation is a saved residual: the sum is the peak
          ns.memory = st.memory + pmem + amem;
        } else {
          // inference: activations free at their last consumer
          ns.param_mem = st.param_mem + pmem;
          double live_b = st.act_live + amem;
          ns.act_peak = std::max(st.act_peak, live_b);
          ns.act_live = live_b - st_dropped;
          ns.memory = ns.param_mem + ns.act_peak;
        }
        ns.frontier.reserve(next_live.size());
        for (int p : keep_pos) ns.frontier.push_back(st.frontier[p]);
        for (int oi : new_out) ns.frontier.push_back(c.out[oi]);
        std::string key = ns.key();
        auto it = next.find(key);
        if (it == next.end() || it->second.cost > ns.cost)
          next[key] = std::move(ns);
        best_cost = std::min(best_cost, cost);
        res.states++;
      }
    }
    // alpha prune + beam prune
    std::vector<DPState> pruned;
    pruned.reserve(next.size());
    double alpha_cut = best_cost * (1.0 + std::max(0.0, cfg.alpha)) + 1e-12;
    for (auto& kv : next)
      if (kv.second.cost <= alpha_cut || next.size() <= 4)
        pruned.push_back(std::move(kv.second));
    size_t kept_alpha = pruned.size();
    if ((int)pruned.size() > beam) {
      std::nth_element(pruned.begin(), pruned.begin() + beam, pruned.end(),
                       [](const DPState& a, const DPState& b) {
                         return a.cost < b.cost;
                       });
      pruned.resize(beam);
    }
    if (evo != nullptr) {
      size_t expanded = states.size() * choices[i].size();
      Json row = Json::object();
      row.set("node", Json(n.guid));
      row.set("name", Json(n.name));
      row.set("choices", Json((int64_t)choices[i].size()));
      row.set("states_in", Json((int64_t)states.size()));
      row.set("expanded", Json((int64_t)expanded));
      row.set("unique_frontiers", Json((int64_t)next.size()));
      row.set("pruned_dominated", Json((int64_t)(expanded - next.size())));
      row.set("pruned_alpha", Json((int64_t)(next.size() - kept_alpha)));
      row.set("pruned_beam", Json((int64_t)(kept_alpha - pruned.size())));
      row.set("kept", Json((int64_t)pruned.size()));
      row.set("best_cost", Json(best_cost));
      evo->push_back(std::move(row));
    }
    states = std::move(pruned);
    live = std::move(next_live);
    if (states.empty()) return res;  // no feasible assignment
  }

  auto best = std::min_element(states.begin(), states.end(),
                               [](const DPState& a, const DPState& b) {
                                 return a.cost < b.cost;
                               });
  res.assign = best->assign;
  res.cost = best->cost;
  res.memory = best->memory;
  res.ok = true;
  return res;
}

// Memory-aware lambda binary search (graph.cc:1883 try_one_lambda loop).
DPResult dp_with_memory(const Graph& g, const std::vector<std::vector<Choice>>& choices,
                        const MeshShape& mesh, const MachineModel& m,
                        const SearchConfig& cfg, double threshold,
                        const MeasuredCosts* measured) {
  DPResult r0 = frontier_dp(g, choices, mesh, m, cfg, 0.0, measured);
  if (!r0.ok || threshold <= 0 || r0.memory <= threshold) return r0;
  // find a lambda that fits: double until feasible, then 10-iter bisect
  double lo = 0.0, hi = r0.cost / std::max(1.0, r0.memory);
  DPResult fit;
  for (int it = 0; it < 20; ++it) {
    fit = frontier_dp(g, choices, mesh, m, cfg, hi, measured);
    r0.states += fit.states;
    if (fit.ok && fit.memory <= threshold) break;
    lo = hi;
    hi *= 4.0;
  }
  if (!(fit.ok && fit.memory <= threshold)) { r0.ok = false; return r0; }
  for (int it = 0; it < 10; ++it) {
    double mid = 0.5 * (lo + hi);
    DPResult rm = frontier_dp(g, choices, mesh, m, cfg, mid, measured);
    r0.states += rm.states;
    if (rm.ok && rm.memory <= threshold) {
      hi = mid;
      fit = std::move(rm);
    } else {
      lo = mid;
    }
  }
  fit.states = r0.states;
  return fit;
}

// ---- MCMC refinement (FFModel::mcmc_optimize, model.cc:3174) -------------

struct MCMCStats {
  int iters = 0, accepted = 0;
};

Assignment mcmc_refine(const Graph& g, const std::vector<std::vector<Choice>>& choices,
                       const MeshShape& mesh, const MachineModel& m,
                       const SearchConfig& cfg, const TaskgraphSimulator& sim,
                       Assignment start, double threshold, MCMCStats* stats) {
  std::mt19937 rng(cfg.seed ? cfg.seed : 0x5eed);
  auto materialize = [&](const Assignment& a) {
    std::vector<Choice> cs;
    cs.reserve(a.size());
    for (size_t i = 0; i < a.size(); ++i) cs.push_back(choices[i][a[i]]);
    return cs;
  };
  auto eval = [&](const Assignment& a) {
    SimResult r = sim.simulate(materialize(a));
    double penalty = threshold > 0 && r.memory > threshold
                         ? (r.memory - threshold) * 1e-7
                         : 0.0;
    return r.iteration_time + penalty;
  };
  Assignment cur = start, best = start;
  double cur_cost = eval(cur), best_cost = cur_cost;
  int iters = std::max(0, cfg.budget) * 25;
  std::uniform_real_distribution<double> unif(0.0, 1.0);
  for (int it = 0; it < iters; ++it) {
    size_t node = rng() % g.nodes.size();
    if (choices[node].size() <= 1) continue;
    Assignment prop = cur;
    prop[node] = static_cast<int>(rng() % choices[node].size());
    if (prop[node] == cur[node]) continue;
    double c = eval(prop);
    stats->iters++;
    // simulated annealing acceptance: exp(-alpha * delta / temperature)
    double temp = 1.0 - static_cast<double>(it) / std::max(1, iters);
    double delta = (c - cur_cost) / std::max(1e-9, cur_cost);
    if (c < cur_cost || unif(rng) < std::exp(-delta / std::max(1e-3, 0.5 * temp))) {
      cur = std::move(prop);
      cur_cost = c;
      stats->accepted++;
      if (c < best_cost) {
        best = cur;
        best_cost = c;
      }
    }
  }
  return best;
}

// ---- per-graph evaluation (mesh loop + DP [+ MCMC]) -----------------------

PipelineMeta pipeline_meta_from_json(const Json& j) {
  PipelineMeta p;
  if (j.is_null()) return p;
  p.num_blocks = (int)j.get("num_blocks").as_int(0);
  if (p.num_blocks < 2) return p;
  for (const Json& v : j.get("body").items()) p.body.insert(v.as_int());
  for (const Json& v : j.get("head").items()) p.head.insert(v.as_int());
  for (const Json& v : j.get("tail").items()) p.tail.insert(v.as_int());
  p.block_out_bytes = j.get("block_out_bytes").as_double(0);
  p.batch = j.get("batch").as_int(0);
  p.present = p.num_blocks >= 2 && !p.body.empty();
  return p;
}

// Outer mesh-shape enumeration (MachineView enumeration analog) — N-D:
// every (data, model, seq, expert[, pipe]) factorization of the chip count
// legal for this graph's seq extent / expert count / repeated-block count.
// `rejects` (optional, search trace): factorizations of the chip count
// that failed a legality gate, with the gate's reason — the "illegal"
// rejection class of the search trace. Every firing is recorded (one
// per rejected factorization); build_search_trace aggregates them into
// one row per gate with a count, so the emitted trace stays bounded
// even at chip counts with thousands of factorizations.
std::vector<MeshShape> enumerate_meshes(
    const Graph& g, const MachineModel& m, const SearchConfig& cfg,
    const PipelineMeta& pipe = {},
    std::vector<std::pair<MeshShape, std::string>>* rejects = nullptr) {
  int64_t seq_extent = 0;
  int64_t num_experts = 0;
  // explicit REPARTITION ops pin an axis's extent: the Python applier
  // rejects a mesh whose matching axis exists with extent != degree
  // (parallel/strategy.py GSPMD legality — the check applies to
  // standalone Repartition only; Combine/Reduction/FusedParallel lower
  // without it), so such meshes must not be enumerated — the search
  // would pick a plan the executor refuses
  std::map<int8_t, std::set<int64_t>> pinned;  // axis -> required degrees
  for (const Node& n : g.nodes) {
    if (n.type == "EXPERTS")
      num_experts = std::max(num_experts, n.attrs.get("n_experts").as_int(0));
    if (n.type == "REPARTITION") {
      int64_t dim = n.attrs.get("dim").as_int(0);
      int64_t deg = n.attrs.get("degree").as_int(1);
      // the op may name its mesh axis explicitly (repartition(axis=...))
      int8_t ax = axis_from_name(n.attrs.get("mesh_axis").as_string(), dim);
      if (deg > 1) pinned[ax].insert(deg);
    }
    if (n.roles.empty()) continue;
    for (size_t d = 0; d < n.roles[0].size(); ++d)
      if (n.roles[0][d] == Role::Seq && d < n.output_shapes[0].size())
        seq_extent = std::max(seq_extent, n.output_shapes[0][d]);
  }
  auto axis_ok = [&](int8_t ax, int size) {
    // the executor requires EVERY standalone Repartition's degree to
    // equal its axis's extent, so an axis with conflicting pinned
    // degrees is only legal at extent 1 (constraints unrealizable)
    auto it = pinned.find(ax);
    if (it == pinned.end() || size == 1) return true;
    return it->second.size() == 1 && *it->second.begin() == (int64_t)size;
  };
  std::vector<MeshShape> meshes;
  int N = std::max(1, m.num_devices);
  auto reject = [&](const MeshShape& mesh, const char* why) {
    if (rejects != nullptr) rejects->push_back({mesh, why});
  };
  for (int mp = 1; mp <= N; ++mp) {
    if (N % mp) continue;
    if (mp > 1 && (cfg.only_data_parallel || !cfg.enable_parameter_parallel)) {
      reject({N / mp, mp, 1, 1, 1}, "parameter_parallel_disabled");
      continue;
    }
    for (int sp = 1; mp * sp <= N; ++sp) {
      if ((N / mp) % sp) continue;
      if (sp > 1 && (cfg.only_data_parallel || seq_extent % sp ||
                     seq_extent <= 1)) {
        reject({N / mp / sp, mp, sp, 1, 1},
               cfg.only_data_parallel ? "only_data_parallel"
               : seq_extent <= 1      ? "no_seq_dim"
                                      : "seq_extent_indivisible");
        continue;
      }
      for (int ep = 1; mp * sp * ep <= N; ++ep) {
        if ((N / mp / sp) % ep) continue;
        if (ep > 1 && (cfg.only_data_parallel || num_experts % ep ||
                       num_experts <= 1)) {
          reject({N / mp / sp / ep, mp, sp, ep, 1},
                 cfg.only_data_parallel ? "only_data_parallel"
                 : num_experts <= 1     ? "no_expert_ops"
                                        : "experts_indivisible");
          continue;
        }
        int rem = N / mp / sp / ep;
        // pipe axis: only on repeated-block graphs, composed with dp only
        // (the pipeline lowering runs stages under shard_map over
        // {pipe, data}; model/seq/expert inside a stage is future work)
        for (int pp = 1; pp <= rem; ++pp) {
          if (rem % pp) continue;
          if (pp > 1 &&
              (cfg.only_data_parallel || !cfg.enable_pipeline_parallel ||
               !pipe.present || pipe.num_blocks % pp ||
               mp * sp * ep != 1)) {
            reject({rem / pp, mp, sp, ep, pp},
                   !cfg.enable_pipeline_parallel ? "pipeline_disabled"
                   : cfg.only_data_parallel      ? "only_data_parallel"
                   : !pipe.present               ? "no_repeated_blocks"
                   : mp * sp * ep != 1 ? "pipe_composes_with_dp_only"
                                       : "blocks_indivisible_by_stages");
            continue;
          }
          int dp = rem / pp;
          // the host stages the batch sharded over 'data': dp must divide
          // it (under pipe: each microbatch shards over dp too)
          if (cfg.batch > 0 && dp > 1 && cfg.batch % dp) {
            reject({dp, mp, sp, ep, pp}, "batch_indivisible_by_dp");
            continue;
          }
          if (!axis_ok(kData, dp) || !axis_ok(kModel, mp) ||
              !axis_ok(kSeq, sp) || !axis_ok(kExpert, ep)) {
            reject({dp, mp, sp, ep, pp}, "pinned_axis_extent_mismatch");
            continue;
          }
          // multislice: model/seq/expert collectives are latency-bound and
          // must stay inside one ICI domain; only the data (gradient) axis
          // and the point-to-point pipe hops may cross slices
          if (m.num_slices > 1) {
            int inner = mp * sp * ep;
            if (inner > m.chips_per_slice() || m.chips_per_slice() % inner) {
              reject({dp, mp, sp, ep, pp}, "inner_axes_cross_slice");
              continue;
            }
          }
          meshes.push_back({dp, mp, sp, ep, pp});
        }
      }
    }
  }
  return meshes;
}

struct GraphEval {
  bool ok = false;
  double time = 1e30;
  MeshShape mesh{1, 1};
  Assignment assign;
  std::vector<std::vector<Choice>> choices;
  SimResult sim;
  int64_t states = 0;
  int pipe_microbatches = 0;      // > 0 when mesh.pp > 1
  std::string pipe_schedule;      // "gpipe"|"circular" when mesh.pp > 1
  bool pipe_remat = false;        // block-body rematerialization chosen
};

// Candidate microbatch counts for a pipe mesh: the explicit flag, or the
// divisor lattice of the per-data-replica batch (M must divide batch/dp
// for microbatches to tile the data-sharded batch). Multiples of pp keep
// the sharded microbatch queue; when none exist (tiny batches) every
// divisor stays in play against the replicated-queue fallback.
std::vector<int> microbatch_candidates(const SearchConfig& cfg,
                                       const PipelineMeta& pipe,
                                       const MeshShape& mesh) {
  std::vector<int> out;
  if (cfg.pipeline_microbatches > 0) {
    out.push_back(cfg.pipeline_microbatches);
    return out;
  }
  int64_t b = cfg.batch > 0 ? cfg.batch : pipe.batch;
  int dp = std::max(1, mesh.dp);
  if (b > 0 && b % dp == 0) {
    int64_t q = b / dp;
    for (int64_t M = 1; M <= q; ++M)
      if (q % M == 0 && M % mesh.pp == 0) out.push_back((int)M);
    if (out.empty())
      for (int64_t M = 1; M <= q; ++M)
        if (q % M == 0) out.push_back((int)M);
  } else {
    for (int f : {1, 2, 4, 8}) out.push_back(f * mesh.pp);
  }
  return out;
}

GraphEval eval_graph(const Graph& g, const MachineModel& m,
                     const SearchConfig& cfg, double threshold,
                     const MeasuredCosts& measured, bool refine,
                     MCMCStats* mcmc, const PipelineMeta& pipe = {}) {
  GraphEval ev;
  for (const MeshShape& mesh : enumerate_meshes(g, m, cfg, pipe)) {
    // per-axis torus pricing: embed THIS mesh's axes into the slice
    // torus so an axis mapped to a full torus dim prices a wrapped
    // ring while a sub-ring/fragmented mapping pays line penalties
    // (EnhancedMachineModel role, reference simulator.h:229-279)
    MachineModel mt = m;
    mt.assign_torus(mesh.dp, mesh.mp, mesh.sp, mesh.ep);
    auto choices = all_choices(g, mesh, cfg);
    // pp>1: the DP's memory model has no pipe axis (it would see every
    // chip holding all blocks and prune exactly the configs pipelining
    // exists to fit) — run unconstrained and let simulate_pipeline's
    // 1/pp-aware memory check enforce the threshold
    DPResult dp = mesh.pp > 1
        ? frontier_dp(g, choices, mesh, mt, cfg, 0.0, &measured)
        : dp_with_memory(g, choices, mesh, mt, cfg, threshold, &measured);
    ev.states += dp.states;
    if (!dp.ok) continue;
    std::vector<Choice> cs0;
    for (size_t i = 0; i < dp.assign.size(); ++i)
      cs0.push_back(choices[i][dp.assign[i]]);
    if (mesh.pp > 1) {
      // pipeline wrapper around the inner-mesh DP result; both the
      // microbatch count (more microbatches shrink the bubble but also
      // the per-tick tile efficiency, captured by the per-op floor) and
      // the schedule (GPipe vs circular) are priced dimensions
      int kblocks = pipe.num_blocks / mesh.pp;
      std::vector<bool> scheds;
      if (cfg.pipeline_schedule == "gpipe") {
        scheds = {false};
      } else if (cfg.pipeline_schedule == "circular") {
        scheds = {true};
      } else {
        scheds = {false};
        if (kblocks > 1) scheds.push_back(true);
      }
      for (int M : microbatch_candidates(cfg, pipe, mesh)) {
        if (M < 1) continue;
        int64_t b = cfg.batch > 0 ? cfg.batch : pipe.batch;
        if (b > 0 && (b % ((int64_t)M * std::max(1, mesh.dp)))) continue;
        for (bool circ : scheds) {
          // the circular runtime needs M >= stages (recirculation)
          if (circ && kblocks > 1 && M < mesh.pp) continue;
          // block-body rematerialization as a third pipe dimension:
          // remat strictly adds recompute time, so it wins only when
          // the non-remat twin misses the memory threshold
          for (int remat = 0;
               remat <= (cfg.enable_remat && cfg.training ? 1 : 0);
               ++remat) {
            SimResult sr = simulate_pipeline(
                g, mt, mesh, cs0, pipe, cfg.training, cfg.opt_state_factor,
                &measured, M, circ, cfg.pipeline_shard_queue, remat != 0);
            if (threshold > 0 && sr.memory > threshold) continue;
            if (sr.iteration_time < ev.time) {
              ev.time = sr.iteration_time;
              ev.mesh = mesh;
              ev.assign = dp.assign;
              ev.choices = choices;
              ev.sim = sr;
              ev.ok = true;
              ev.pipe_microbatches = M;
              ev.pipe_schedule = circ ? "circular" : "gpipe";
              ev.pipe_remat = remat != 0;
            }
          }
        }
      }
      continue;
    }
    TaskgraphSimulator sim(g, mt, mesh, cfg.training, cfg.overlap,
                           cfg.opt_state_factor, &measured);
    Assignment a = dp.assign;
    if (refine && cfg.budget > 0 && mcmc != nullptr)
      a = mcmc_refine(g, choices, mesh, mt, cfg, sim, a, threshold, mcmc);
    std::vector<Choice> cs;
    for (size_t i = 0; i < a.size(); ++i) cs.push_back(choices[i][a[i]]);
    SimResult sr = sim.simulate(cs);
    if (threshold > 0 && sr.memory > threshold) continue;
    if (sr.iteration_time < ev.time) {
      ev.time = sr.iteration_time;
      ev.mesh = mesh;
      ev.assign = a;
      ev.choices = choices;
      ev.sim = sr;
      ev.ok = true;
      ev.pipe_microbatches = 0;
    }
  }
  return ev;
}

// ---- search trace (provenance) --------------------------------------------
//
// A versioned, structured record of WHAT the search considered and WHY it
// rejected what it rejected (ISSUE 8): per-mesh candidate rows with
// rejection reasons (illegal / infeasible / over_budget / dominated), the
// frontier-DP evolution on the winning mesh, and a per-op candidate-choice
// cost table with each choice's cost decomposed into compute / collective /
// memory / opt-state terms plus the collectives it implies. Emission is
// opt-in (config.emit_search_trace) — the trace re-runs the per-mesh DP
// once, roughly doubling search cost, which an explain/trace run accepts.

constexpr int64_t kSearchTraceVersion = 1;

Json mesh_to_json(const MeshShape& mesh) {
  Json j = Json::object();
  j.set("data", Json((int64_t)mesh.dp));
  j.set("model", Json((int64_t)mesh.mp));
  j.set("seq", Json((int64_t)mesh.sp));
  j.set("expert", Json((int64_t)mesh.ep));
  j.set("pipe", Json((int64_t)mesh.pp));
  return j;
}

bool mesh_eq(const MeshShape& a, const MeshShape& b) {
  return a.dp == b.dp && a.mp == b.mp && a.sp == b.sp && a.ep == b.ep &&
         a.pp == b.pp;
}

// The collectives a choice statically implies (kind, global bytes, ring
// size, cause, fabric) — the "what would this cost on the wire" column of
// the explain table, mirroring the census records the simulators emit.
// `fabric` names the slowest fabric tier the ring crosses: "ici" inside
// one slice, "dcn" when the ring spans slices. Mesh legality keeps the
// inner (model/seq/expert) axes inside one ICI domain, so only the
// gradient-sync rows (data axis) can ever carry "dcn" — with the slice
// count the ring spans alongside.
Json choice_collectives_json(const Choice& c, bool training,
                             const MeshShape& mesh, const MachineModel& m) {
  Json arr = Json::array();
  int spans = slices_spanned(mesh, m);
  auto add = [&](const char* kind, double bytes, int k, const char* why,
                 bool data_axis) {
    Json o = Json::object();
    o.set("kind", Json(std::string(kind)));
    o.set("bytes", Json(bytes));
    o.set("ring", Json((int64_t)k));
    o.set("cause", Json(std::string(why)));
    bool dcn = data_axis && spans > 1;
    o.set("fabric", Json(std::string(dcn ? "dcn" : "ici")));
    if (dcn) o.set("slices", Json((int64_t)spans));
    arr.push_back(std::move(o));
  };
  if (c.psum_bytes > 0 && c.psum_k > 1)
    add("allreduce", c.psum_bytes, c.psum_k, "partial_sum", false);
  if (training && c.bwd_psum_bytes > 0 && c.psum_k > 1)
    add("allreduce", c.bwd_psum_bytes, c.psum_k, "backward_partial_sum",
        false);
  if (c.wgather_bytes > 0 && c.psum_k > 1)
    add("allgather", c.wgather_bytes, c.psum_k, "tiny_batch_weight_gather",
        false);
  if (c.gather_bytes > 0 && c.gather_k > 1)
    add("allgather", c.gather_bytes, c.gather_k, "combine_boundary", false);
  if (c.ring_bytes > 0 && c.ring_k > 1)
    add("ppermute", c.ring_bytes, c.ring_k, "ring_attention_rotation",
        false);
  if (training && c.gradsync_bytes > 0 && c.gradsync_k > 1) {
    if (c.wus) {
      add("allreduce", c.gradsync_bytes, c.gradsync_k,
          "grad_reduce_scatter", true);
      add("allgather", c.gradsync_bytes, c.gradsync_k,
          "wus_param_allgather", true);
    } else {
      add("allreduce", c.gradsync_bytes, c.gradsync_k, "grad_allreduce",
          true);
    }
  }
  return arr;
}

// One candidate-choice row: priced terms decomposed the way the frontier
// DP sees them. compute = fwd+bwd roofline; collective = per-op comms +
// gradient sync; opt_state = the update-triad HBM time WUS divides by the
// ring; memory = param / opt-state / activation bytes per device.
// `analytic_m` (optional): the machine with the learned table cleared,
// hoisted to the caller — it is invariant across the whole candidate
// loop and a per-candidate MachineModel copy would churn allocations.
Json choice_trace_json(const Node& n, const Choice& c, const MeshShape& mesh,
                       const MachineModel& m, const SearchConfig& cfg,
                       const MeasuredCosts* measured, bool chosen,
                       const MachineModel* analytic_m = nullptr) {
  NodeCost full = node_cost(n, c, mesh, m, cfg.training, measured,
                            cfg.opt_state_factor);
  NodeCost base = node_cost(n, c, mesh, m, cfg.training, measured);
  double update_s = full.gradsync - base.gradsync;
  double param_b = detail::sharded_param_bytes(n, c, mesh);
  double pmem = node_param_memory(n, c, mesh, cfg.opt_state_factor);
  Json cj = Json::object();
  cj.set("choice", Json(c.name));
  cj.set("chosen", Json(chosen));
  cj.set("work_div", Json(c.work_div));
  // which kernel implementation this candidate lowers to ("einsum" /
  // "flash" / "ring" / "conv" / "conv_bn_fused" / "triad" / "fused") —
  // the searched-kernel provenance column (ISSUE 15). Ops with no
  // registered alternatives carry no impl.
  {
    std::string impl = c.kernel.empty() ? kernel_default_impl(n, c)
                                        : c.kernel;
    if (!impl.empty()) cj.set("impl", Json(impl));
  }
  // which model priced this candidate's compute (learned vs analytic
  // vs measured) — the per-candidate provenance the costmodel loop
  // audits (ISSUE 14)
  cj.set("cost_source", Json(std::string(cost_source_name(base.src))));
  Json terms = Json::object();
  terms.set("fwd_s", Json(base.fwd));
  terms.set("bwd_s", Json(base.bwd));
  terms.set("compute_s", Json(base.fwd + base.bwd));
  if (!m.learned.empty() && analytic_m != nullptr &&
      base.src != SRC_MEASURED) {
    // learned-vs-analytic side by side: reprice the compute under the
    // analytic roofline alone (NO measured table — a measured override
    // here would label profile seconds "analytic" and fabricate
    // disagreements), and under the learned table when this (class,
    // features) is covered — explain.py's disagreement table flags ops
    // where the two models rank a different winner. Measured-priced
    // candidates skip the columns entirely: the DP used neither model.
    NodeCost an = node_cost(n, c, mesh, *analytic_m, cfg.training,
                            nullptr);
    terms.set("compute_analytic_s", Json(an.fwd + an.bwd));
    double lf = 0, lb = 0;
    if (learned_compute(n, c, m, &lf, &lb)) {
      double tf = std::max(lf, m.min_op_time);
      double tb = cfg.training ? std::max(lb, m.min_op_time) : 0.0;
      terms.set("compute_learned_s", Json(tf + tb));
    }
  }
  terms.set("comm_s", Json(base.comm));
  terms.set("gradsync_s", Json(base.gradsync));
  terms.set("collective_s", Json(base.comm + base.gradsync));
  terms.set("opt_state_s", Json(update_s));
  terms.set("total_s", Json(full.total()));
  if (c.ovl)
    // comm seconds the latency-hiding pricing hid under the op's
    // backward (+ optimizer tail) — the predicted-hidden column
    terms.set("hidden_s", Json(full.gradsync_hidden));
  cj.set("terms", terms);
  if (c.ovl) {
    // the bucket sweep behind the committed "_ovl" price: every
    // size-targeted candidate's exposed seconds, so the trace shows WHY
    // this bucket size won (ISSUE 9 satellite — sweep provenance)
    Json ov = Json::object();
    ov.set("bucket_mb", Json(full.ovl_bucket_mb));
    ov.set("buckets", Json((int64_t)full.ovl_buckets));
    ov.set("hidden_s", Json(full.gradsync_hidden));
    Json sweep = Json::array();
    {
      // reprice the sync + hiding window exactly as node_cost does
      Choice sync_c = c;
      sync_c.ovl = false;
      NodeCost base_sync = node_cost(n, sync_c, mesh, m, cfg.training,
                                     measured);
      double hide = base_sync.bwd +
                    update_triad_time(n, c, mesh, m, cfg.opt_state_factor);
      double wire = c.gradsync_bytes * m.comm_bytes_factor;
      for (int bi = 0; bi < kOvlBucketCount; ++bi) {
        double mb = kOvlBucketMB[bi];
        int B = std::max(
            1, (int)std::ceil(wire / (mb * 1e6)));
        double exp = std::max(base_sync.gradsync / B,
                              base_sync.gradsync - hide) +
                     B * m.collective_launch_overhead;
        Json row = Json::object();
        row.set("bucket_mb", Json(mb));
        row.set("buckets", Json((int64_t)B));
        row.set("exposed_s", Json(exp));
        sweep.push_back(std::move(row));
      }
    }
    ov.set("sweep", sweep);
    cj.set("overlap", ov);
  }
  Json mem = Json::object();
  mem.set("param_bytes", Json(param_b));
  mem.set("opt_state_bytes", Json(std::max(0.0, pmem - param_b)));
  mem.set("act_bytes", Json(node_act_bytes(n, c, mesh)));
  cj.set("memory", mem);
  if (c.remat) {
    // the "_r" tradeoff row explain.py renders: activation bytes the
    // checkpoint frees (the non-remat twin's residual) vs the forward
    // seconds backward re-spends recomputing the interior
    Choice base_c = c;
    base_c.remat = false;
    Json rj = Json::object();
    rj.set("freed_act_bytes", Json(node_act_bytes(n, base_c, mesh)));
    rj.set("recompute_s", Json(base.fwd));
    cj.set("remat", rj);
  }
  cj.set("collectives",
         choice_collectives_json(c, cfg.training, mesh, m));
  return cj;
}

// Per-op candidate table for an (assignment, mesh): every enumerated
// choice priced, the winner flagged — the rows scripts/explain.py turns
// into the chosen-vs-runner-up table, and (joined against measured per-op
// seconds) the learned-cost-model training corpus.
Json per_op_trace(const Graph& g,
                  const std::vector<std::vector<Choice>>& choices,
                  const Assignment& assign, const MeshShape& mesh,
                  const MachineModel& m, const SearchConfig& cfg,
                  const MeasuredCosts* measured) {
  Json ops = Json::array();
  MachineModel analytic;
  const MachineModel* analytic_m = nullptr;
  if (!m.learned.empty()) {
    analytic = m;
    analytic.learned.clear();
    analytic_m = &analytic;
  }
  for (size_t i = 0; i < g.nodes.size(); ++i) {
    const Node& n = g.nodes[i];
    Json oj = Json::object();
    oj.set("guid", Json(n.guid));
    oj.set("name", Json(n.name));
    oj.set("type", Json(n.type));
    oj.set("flops", Json(n.fwd_flops));
    oj.set("param_bytes", Json((double)n.param_bytes()));
    Json shp = Json::array();
    if (!n.output_shapes.empty())
      for (int64_t d : n.output_shapes[0]) shp.push_back(Json(d));
    oj.set("out_shape", shp);
    oj.set("chosen", Json(choices[i][assign[i]].name));
    // kernel alternatives the legality gates rejected for this op, with
    // the gate's named reason (e.g. the tiny t1 transformer's attention
    // rejects flash with seq_not_divisible_by_flash_tile_128) — the
    // per-op analog of the mesh rows' "illegal" class
    if (cfg.enable_kernels) {
      Json krej = Json::array();
      auto note = [&](const char* impl) {
        std::string why = kernel_gate(n, impl, cfg.training);
        if (why.empty()) return;
        Json r = Json::object();
        r.set("impl", Json(std::string(impl)));
        r.set("reason", Json(why));
        krej.push_back(std::move(r));
      };
      if (n.type == "MULTIHEAD_ATTENTION") note("flash");
      if (n.type == "CONV2D" && cfg.training) note("conv_bn_fused");
      if (!krej.items().empty()) oj.set("kernel_rejections", krej);
    }
    // "_r" twins the remat gate rejected for this op's CHOSEN lowering,
    // with the gate's named reason (e.g. interior_not_larger_than_boundary
    // on an elementwise op, dropout_interior on a dropout attention) —
    // the remat analog of kernel_rejections (ISSUE 20)
    if (cfg.enable_remat && cfg.training && mesh.pp == 1) {
      const Choice& chosen_c = choices[i][assign[i]];
      if (!chosen_c.remat) {
        std::string why = remat_gate(n, chosen_c, cfg.training);
        if (!why.empty()) {
          Json rrej = Json::array();
          Json r = Json::object();
          r.set("reason", Json(why));
          rrej.push_back(std::move(r));
          oj.set("remat_rejections", rrej);
        }
      }
    }
    Json cands = Json::array();
    for (size_t ci = 0; ci < choices[i].size(); ++ci)
      cands.push_back(choice_trace_json(n, choices[i][ci], mesh, m, cfg,
                                        measured, ci == (size_t)assign[i],
                                        analytic_m));
    oj.set("candidates", cands);
    ops.push_back(std::move(oj));
  }
  return ops;
}

// The whole trace: mesh candidates (including illegal factorizations and
// their gate), per-mesh DP outcome vs the winner, the winning mesh's
// frontier-DP evolution, and the winner's per-op candidate table.
Json build_search_trace(const Graph& g, const MachineModel& m,
                        const SearchConfig& cfg, double threshold,
                        const MeasuredCosts& measured, const GraphEval& best,
                        const PipelineMeta& pipe, bool graph_rewritten) {
  Json tr = Json::object();
  tr.set("schema_version", Json(kSearchTraceVersion));
  tr.set("graph", Json(std::string(graph_rewritten ? "rewritten"
                                                   : "original")));
  Json cfgj = Json::object();
  cfgj.set("budget", Json((int64_t)cfg.budget));
  cfgj.set("alpha", Json(cfg.alpha));
  cfgj.set("training", Json(cfg.training));
  cfgj.set("opt_state_factor", Json(cfg.opt_state_factor));
  cfgj.set("memory_threshold", Json(threshold));
  tr.set("config", cfgj);

  Json mrows = Json::array();
  std::vector<std::pair<MeshShape, std::string>> illegal;
  auto meshes = enumerate_meshes(g, m, cfg, pipe, &illegal);
  // one row per legality gate: the first rejected factorization as the
  // representative mesh plus a firing count — a 4096-chip machine has
  // thousands of rejected factorizations and the trace must not carry
  // one row each
  std::map<std::string, std::pair<MeshShape, int64_t>> by_gate;
  for (const auto& rej : illegal) {
    auto it = by_gate.find(rej.second);
    if (it == by_gate.end()) by_gate[rej.second] = {rej.first, 1};
    else it->second.second++;
  }
  for (const auto& kv : by_gate) {
    Json row = Json::object();
    row.set("mesh", mesh_to_json(kv.second.first));
    row.set("status", Json(std::string("illegal")));
    row.set("reason", Json(kv.first));
    row.set("count", Json(kv.second.second));
    mrows.push_back(std::move(row));
  }
  for (const MeshShape& mesh : meshes) {
    MachineModel mt = m;
    mt.assign_torus(mesh.dp, mesh.mp, mesh.sp, mesh.ep);
    Json row = Json::object();
    row.set("mesh", mesh_to_json(mesh));
    // multislice provenance: how many ICI slices this mesh's gradient
    // ring crosses — the rows a reviewer scans to see which candidates
    // paid DCN rates for their sync
    if (m.num_slices > 1)
      row.set("slices_spanned", Json((int64_t)slices_spanned(mesh, m)));
    auto choices = all_choices(g, mesh, cfg);
    DPResult dp = mesh.pp > 1
        ? frontier_dp(g, choices, mesh, mt, cfg, 0.0, &measured)
        : dp_with_memory(g, choices, mesh, mt, cfg, threshold, &measured);
    row.set("dp_states", Json(dp.states));
    if (!dp.ok) {
      row.set("status", Json(std::string("infeasible")));
      row.set("reason", Json(std::string(
          threshold > 0 ? "no_assignment_fits_memory_threshold"
                        : "no_feasible_assignment")));
      mrows.push_back(std::move(row));
      continue;
    }
    std::vector<Choice> cs;
    for (size_t i = 0; i < dp.assign.size(); ++i)
      cs.push_back(choices[i][dp.assign[i]]);
    if (mesh.pp > 1) {
      // pipe wrapper: every (microbatch count, schedule) candidate is a
      // priced sub-row; the mesh row carries the best of them
      int kblocks = pipe.num_blocks / mesh.pp;
      std::vector<bool> scheds;
      if (cfg.pipeline_schedule == "gpipe") scheds = {false};
      else if (cfg.pipeline_schedule == "circular") scheds = {true};
      else { scheds = {false}; if (kblocks > 1) scheds.push_back(true); }
      Json cand = Json::array();
      double best_t = 1e30;
      bool any_fit = false, any = false;
      for (int M : microbatch_candidates(cfg, pipe, mesh)) {
        if (M < 1) continue;
        int64_t b = cfg.batch > 0 ? cfg.batch : pipe.batch;
        if (b > 0 && (b % ((int64_t)M * std::max(1, mesh.dp)))) continue;
        for (bool circ : scheds) {
          if (circ && kblocks > 1 && M < mesh.pp) continue;
          for (int remat = 0;
               remat <= (cfg.enable_remat && cfg.training ? 1 : 0);
               ++remat) {
            SimResult sr = simulate_pipeline(
                g, mt, mesh, cs, pipe, cfg.training, cfg.opt_state_factor,
                &measured, M, circ, cfg.pipeline_shard_queue, remat != 0);
            any = true;
            Json pc = Json::object();
            pc.set("microbatches", Json((int64_t)M));
            pc.set("schedule",
                   Json(std::string(circ ? "circular" : "gpipe")));
            if (cfg.enable_remat && cfg.training)
              pc.set("remat", Json(remat != 0));
            pc.set("time_s", Json(sr.iteration_time));
            pc.set("memory_bytes", Json(sr.memory));
            bool fits = !(threshold > 0 && sr.memory > threshold);
            pc.set("fits_memory", Json(fits));
            cand.push_back(std::move(pc));
            if (fits) {
              any_fit = true;
              best_t = std::min(best_t, sr.iteration_time);
            }
          }
        }
      }
      row.set("pipeline_candidates", cand);
      if (!any || !any_fit) {
        row.set("status", Json(std::string(any ? "over_budget"
                                               : "infeasible")));
        row.set("reason", Json(std::string(
            any ? "all_microbatch_candidates_exceed_memory"
                : "no_legal_microbatch_count")));
        mrows.push_back(std::move(row));
        continue;
      }
      // the winner row reports the time the search actually committed
      // to (MCMC refinement may have improved on the DP assignment this
      // re-run reproduces) — keeps winner.time <= every dominated time
      bool won = mesh_eq(mesh, best.mesh);
      row.set("time_s", Json(won ? best.time : best_t));
      row.set("status", Json(std::string(won ? "winner" : "dominated")));
      if (!won)
        row.set("reason", Json(std::string("slower_than_winner")));
      mrows.push_back(std::move(row));
      continue;
    }
    TaskgraphSimulator sim(g, mt, mesh, cfg.training, cfg.overlap,
                           cfg.opt_state_factor, &measured);
    // the winner row reports the assignment the search COMMITTED to
    // (MCMC refinement may have improved on the DP assignment this
    // re-run reproduces; winner.time <= every dominated DP time holds
    // because refinement only ever lowers a mesh's time)
    bool won = mesh_eq(mesh, best.mesh);
    SimResult sr = won ? best.sim : sim.simulate(cs);
    row.set("time_s", Json(sr.iteration_time));
    row.set("memory_bytes", Json(sr.memory));
    Json bd = Json::object();
    bd.set("fwd_s", Json(sr.fwd_time));
    bd.set("bwd_s", Json(sr.bwd_time));
    bd.set("comm_s", Json(sr.comm_time));
    bd.set("gradsync_s", Json(sr.gradsync_time));
    row.set("sim_breakdown", bd);
    if (threshold > 0 && sr.memory > threshold) {
      row.set("status", Json(std::string("over_budget")));
      row.set("reason", Json(std::string("simulated_memory_exceeds_threshold")));
    } else if (won) {
      row.set("status", Json(std::string("winner")));
    } else {
      row.set("status", Json(std::string("dominated")));
      row.set("reason", Json(std::string("slower_than_winner")));
    }
    mrows.push_back(std::move(row));
  }
  tr.set("meshes", mrows);

  // frontier-DP evolution + per-op candidate table on the winning mesh
  // (evolution re-recorded at lambda = 0 — the memory-lambda refinement
  // reruns the same recursion with a nonzero price on bytes)
  if (best.ok) {
    MachineModel mt = m;
    mt.assign_torus(best.mesh.dp, best.mesh.mp, best.mesh.sp, best.mesh.ep);
    Json evo = Json::array();
    frontier_dp(g, best.choices, best.mesh, mt, cfg, 0.0, &measured, &evo);
    tr.set("dp_evolution", evo);
    tr.set("winner_mesh", mesh_to_json(best.mesh));
    if (best.mesh.pp > 1) {
      Json pj = Json::object();
      pj.set("microbatches", Json((int64_t)best.pipe_microbatches));
      pj.set("schedule", Json(best.pipe_schedule));
      pj.set("remat", Json(best.pipe_remat));
      tr.set("winner_pipeline", pj);
    }
    tr.set("ops", per_op_trace(g, best.choices, best.assign, best.mesh, mt,
                               cfg, &measured));
  }
  return tr;
}

// ---- driver ---------------------------------------------------------------

Json spec_to_json(const Spec& s) {
  Json arr = Json::array();
  for (int8_t e : s)
    arr.push_back(e == kData      ? Json("data")
                  : e == kModel   ? Json("model")
                  : e == kSeq     ? Json("seq")
                  : e == kExpert  ? Json("expert")
                  : e == kDataModel ? Json("data+model")
                                  : Json());
  return arr;
}

Json optimize(const Json& req) {
  Graph g0 = Graph::from_json(req.get("nodes"));
  MachineModel m = MachineModel::from_json(req.get("machine"));
  SearchConfig cfg = SearchConfig::from_json(req.get("config"));
  MeasuredCosts measured;
  for (const auto& kv : req.get("measured").fields())
    measured[kv.first] = kv.second.as_double();
  double threshold = cfg.memory_threshold > 0 ? cfg.memory_threshold : m.hbm_cap;

  // user-designated model output: rewrites must never drop it unmapped
  std::pair<int64_t, int> final_ref{-1, 0};
  const Json& fj = req.get("final");
  if (!fj.is_null())
    final_ref = {fj[0].as_int(-1), static_cast<int>(fj[1].as_int(0))};

  MCMCStats mcmc;
  // repeated-block pipeline metadata (pipe meshes are only legal on the
  // ORIGINAL graph: a rewrite inside the body would break block identity)
  PipelineMeta pipe = pipeline_meta_from_json(req.get("pipeline"));
  // "mesh shapes searched" means the original graph's candidate set; the
  // winning (possibly rewritten) graph may legalize a different set
  int64_t mesh_candidates = (int64_t)enumerate_meshes(g0, m, cfg, pipe).size();
  GraphEval best = eval_graph(g0, m, cfg, threshold, measured, false, nullptr,
                              pipe);
  int64_t total_states = best.states;
  Graph best_g = g0;
  std::vector<RewriteTraceEntry> best_trace;
  std::pair<int64_t, int> best_fin = final_ref;

  // ---- substitution best-first loop (base_optimize, substitution.cc:2229):
  // pop the cheapest graph, apply every rule at every match, keep children
  // within alpha of the incumbent. Rules = builtin generators
  // (substitution.cc:1726-1860 analogs) + the request's rule corpus
  // (reference substitutions/graph_subst_3_v2.json format supported).
  std::vector<SubstRule> rules;
  if (cfg.enable_substitution) {
    rules = builtin_rules();
    const Json& rj = req.get("subst_rules");
    if (!rj.is_null())
      for (SubstRule& r : parse_rules(rj)) rules.push_back(std::move(r));
    if (cfg.training)
      rules.erase(std::remove_if(rules.begin(), rules.end(),
                                 [](const SubstRule& r) {
                                   return r.inference_only;
                                 }),
                  rules.end());
    if (!cfg.perform_fusion)
      // --disable-fusion: drop the fuse_parallel_ops family (the only
      // explicit-fusion rewrites; kernel fusion itself belongs to XLA)
      rules.erase(std::remove_if(rules.begin(), rules.end(),
                                 [](const SubstRule& r) {
                                   return r.name.find("fuse_parallel_ops")
                                          != std::string::npos;
                                 }),
                  rules.end());
  }
  int graphs_evaluated = 1, expansions = 0;
  if (!rules.empty() && best.ok && !g0.nodes.empty()) {
    struct Cand {
      double cost;
      Graph g;
      std::vector<RewriteTraceEntry> trace;
      std::pair<int64_t, int> fin;
    };
    int64_t next_guid = 0;
    for (const Node& n : g0.nodes)
      next_guid = std::max(next_guid, n.guid + 1);
    auto cmp = [](const Cand& a, const Cand& b) { return a.cost > b.cost; };
    std::priority_queue<Cand, std::vector<Cand>, decltype(cmp)> pq(cmp);
    std::set<std::string> seen{graph_key(g0)};
    pq.push({best.time, g0, {}, final_ref});
    double alpha = 1.0 + std::max(0.0, cfg.alpha);
    while (!pq.empty() && expansions < cfg.subst_budget) {
      Cand cur = pq.top();
      pq.pop();
      if (cur.cost > best.time * alpha) break;
      ++expansions;
      for (const SubstRule& rule : rules) {
        int dbg_matches = 0, dbg_applied = 0;
        for (const Match& match : find_matches(cur.g, rule)) {
          ++dbg_matches;
          RewriteTraceEntry entry;
          auto ng = apply_rule(cur.g, rule, match, &next_guid, &entry);
          if (!ng) continue;
          ++dbg_applied;
          // chase the designated output through the rewrite; a rule that
          // drops it unmapped would train on the wrong tensor — reject
          std::pair<int64_t, int> fin = cur.fin;
          if (fin.first >= 0) {
            bool removed = std::find(entry.removed.begin(),
                                     entry.removed.end(),
                                     fin.first) != entry.removed.end();
            bool remapped = false;
            for (const auto& rm : entry.output_remap)
              if (rm[0] == fin.first && rm[1] == fin.second) {
                fin = {rm[2], static_cast<int>(rm[3])};
                remapped = true;
                break;
              }
            if (removed && !remapped) continue;
          }
          if (!seen.insert(graph_key(*ng)).second) continue;
          GraphEval ev;
          try {
            ev = eval_graph(*ng, m, cfg, threshold, measured, false, nullptr);
          } catch (const std::exception&) {
            continue;  // e.g. a choice filter unsatisfiable on the rewrite
          }
          ++graphs_evaluated;
          total_states += ev.states;
          if (!ev.ok) continue;
          std::vector<RewriteTraceEntry> trace = cur.trace;
          trace.push_back(entry);
          if (ev.time < best.time) {
            best = ev;
            best_g = *ng;
            best_trace = trace;
            best_fin = fin;
          }
          if (ev.time <= best.time * alpha && pq.size() < 256)
            pq.push({ev.time, std::move(*ng), std::move(trace), fin});
        }
        if (dbg_matches && getenv("FFS_DEBUG"))
          fprintf(stderr, "[ffs] rule %s: %d matches, %d applied\n",
                  rule.name.c_str(), dbg_matches, dbg_applied);
      }
    }
  }

  // MCMC refinement on the winning graph (FFModel::mcmc_optimize analog);
  // pipe meshes stay in play only for the unrewritten graph
  if (cfg.budget > 0 && best.ok) {
    GraphEval re = eval_graph(best_g, m, cfg, threshold, measured, true, &mcmc,
                              best_trace.empty() ? pipe : PipelineMeta{});
    total_states += re.states;
    if (re.ok && re.time <= best.time) best = re;
  }

  const Graph& g = best_g;
  Json out = Json::object();
  if (!best.ok && !g.nodes.empty()) {
    out.set("error", "no feasible strategy (memory threshold too low?)");
    return out;
  }
  Json meshj = Json::object();
  meshj.set("data", Json((int64_t)best.mesh.dp));
  meshj.set("model", Json((int64_t)best.mesh.mp));
  meshj.set("seq", Json((int64_t)best.mesh.sp));
  meshj.set("expert", Json((int64_t)best.mesh.ep));
  meshj.set("pipe", Json((int64_t)best.mesh.pp));
  out.set("mesh", meshj);
  // multislice: the winner's gradient ring crosses this many slices
  // (top-level, NOT inside "mesh" — decode_strategy reads mesh entries
  // as axis extents). 1 on single-slice machines.
  out.set("slices_spanned",
          Json((int64_t)slices_spanned(best.mesh, m)));
  if (best.mesh.pp > 1) {
    Json pj = Json::object();
    pj.set("microbatches", Json((int64_t)best.pipe_microbatches));
    pj.set("stages", Json((int64_t)best.mesh.pp));
    pj.set("schedule", Json(best.pipe_schedule.empty()
                                ? std::string("gpipe")
                                : best.pipe_schedule));
    // block-body rematerialization: the executor wraps the stage's block
    // template in jax.checkpoint when true (ISSUE 20)
    pj.set("remat", Json(best.pipe_remat));
    out.set("pipeline", pj);
  }
  Json ops = Json::object();
  for (size_t i = 0; i < g.nodes.size(); ++i) {
    const Choice& c = best.choices[i][best.assign[i]];
    Json oj = Json::object();
    oj.set("choice", Json(c.name));
    Json outs = Json::array();
    for (const Spec& s : c.out) outs.push_back(spec_to_json(s));
    oj.set("outputs", outs);
    Json ins = Json::array();
    for (const Spec& s : c.in) ins.push_back(spec_to_json(s));
    oj.set("inputs", ins);
    Json ps = Json::object();
    for (const auto& kv : c.param) ps.set(kv.first, spec_to_json(kv.second));
    oj.set("params", ps);
    ops.set(std::to_string(g.nodes[i].guid), oj);
  }
  out.set("ops", ops);
  // searched overlap summary: the byte-weighted winning bucket size
  // across the assignment's "_ovl" choices — the value the executor's
  // --overlap-bucket-mb 'auto' follows (per-op buckets agree in
  // practice; bytes break the tie when they don't)
  {
    MachineModel mt = m;
    mt.assign_torus(best.mesh.dp, best.mesh.mp, best.mesh.sp, best.mesh.ep);
    std::map<double, double> by_bucket;
    int ovl_ops = 0;
    for (size_t i = 0; i < g.nodes.size(); ++i) {
      const Choice& c = best.choices[i][best.assign[i]];
      if (!c.ovl || c.gradsync_bytes <= 0) continue;
      NodeCost nc = node_cost(g.nodes[i], c, best.mesh, mt, cfg.training,
                              &measured, cfg.opt_state_factor);
      by_bucket[nc.ovl_bucket_mb] += c.gradsync_bytes;
      ++ovl_ops;
    }
    if (ovl_ops > 0) {
      double top_mb = 0, top_bytes = -1;
      for (const auto& kv : by_bucket)
        if (kv.second > top_bytes) {
          top_bytes = kv.second;
          top_mb = kv.first;
        }
      Json ovj = Json::object();
      ovj.set("bucket_mb", Json(top_mb));
      ovj.set("ops", Json((int64_t)ovl_ops));
      out.set("overlap", ovj);
    }
  }
  // rewrite trace: Python replays this on its OpNode graph
  Json rewrites = Json::array();
  for (const RewriteTraceEntry& e : best_trace) {
    Json ej = Json::object();
    ej.set("rule", Json(e.rule));
    Json rm = Json::array();
    for (int64_t gd : e.removed) rm.push_back(Json(gd));
    ej.set("removed", rm);
    ej.set("added", e.added);
    Json remap = Json::array();
    for (const auto& r : e.output_remap) {
      Json q = Json::array();
      for (int64_t v : r) q.push_back(Json(v));
      remap.push_back(q);
    }
    ej.set("output_remap", remap);
    rewrites.push_back(ej);
  }
  out.set("rewrites", rewrites);
  if (final_ref.first >= 0) {
    Json fin = Json::array();
    fin.push_back(Json(best_fin.first));
    fin.push_back(Json((int64_t)best_fin.second));
    out.set("final", fin);
  }
  out.set("predicted_time", Json(best.sim.iteration_time));
  out.set("predicted_memory", Json(best.sim.memory));
  Json stats = Json::object();
  stats.set("states_explored", Json(total_states));
  stats.set("mesh_candidates", Json(mesh_candidates));
  stats.set("mcmc_iters", Json((int64_t)mcmc.iters));
  stats.set("mcmc_accepted", Json((int64_t)mcmc.accepted));
  stats.set("rules_loaded", Json((int64_t)rules.size()));
  stats.set("rewrites_applied", Json((int64_t)best_trace.size()));
  stats.set("graphs_evaluated", Json((int64_t)graphs_evaluated));
  stats.set("subst_expansions", Json((int64_t)expansions));
  stats.set("fwd_time", Json(best.sim.fwd_time));
  stats.set("bwd_time", Json(best.sim.bwd_time));
  stats.set("comm_time", Json(best.sim.comm_time));
  stats.set("gradsync_time", Json(best.sim.gradsync_time));
  out.set("stats", stats);
  if (cfg.emit_trace && best.ok) {
    // provenance, not the product: a trace failure must never void the
    // strategy the search already found
    try {
      out.set("search_trace",
              build_search_trace(best_g, m, cfg, threshold, measured, best,
                                 best_trace.empty() ? pipe : PipelineMeta{},
                                 !best_trace.empty()));
    } catch (const std::exception& e) {
      Json err = Json::object();
      err.set("schema_version", Json(kSearchTraceVersion));
      err.set("error", Json(std::string(e.what())));
      out.set("search_trace", err);
    }
  }
  return out;
}

// Simulate a given assignment (for tests / what-if queries / --taskgraph).
// A mesh with "pipe" > 1 routes through simulate_pipeline — the request's
// "pipeline" object supplies the repeated-block metadata plus the
// microbatch count and schedule to price, so searched pipe strategies
// replay through the same cost model the DP ranked them with.
Json simulate_only(const Json& req) {
  Graph g = Graph::from_json(req.get("nodes"));
  MachineModel m = MachineModel::from_json(req.get("machine"));
  SearchConfig cfg = SearchConfig::from_json(req.get("config"));
  MeshShape mesh{(int)req.get("mesh").get("data").as_int(1),
                 (int)req.get("mesh").get("model").as_int(1),
                 (int)req.get("mesh").get("seq").as_int(1),
                 (int)req.get("mesh").get("expert").as_int(1),
                 (int)req.get("mesh").get("pipe").as_int(1)};
  m.assign_torus(mesh.dp, mesh.mp, mesh.sp, mesh.ep);
  auto choices = all_choices(g, mesh, cfg);
  std::vector<Choice> cs;
  const Json& sel = req.get("assignment");
  for (size_t i = 0; i < g.nodes.size(); ++i) {
    std::string want = sel.get(std::to_string(g.nodes[i].guid)).as_string();
    auto find = [&](const std::string& name) -> const Choice* {
      for (const Choice& c : choices[i])
        if (c.name == name) return &c;
      return nullptr;
    };
    const Choice* pick = find(want);
    if (pick == nullptr) {
      // suffix fallback both ways for the "_wus"/"_ovl"/"_k:"/"_r"
      // twins: a heuristic replay may ask for a twin an op doesn't
      // spawn (no gradsync), and a stale strategy file may lack the
      // suffixes an enabled run expects. Canonical order is
      // base[+_wus][+_ovl][+_k:impl][+_r]. Candidates walk the suffix
      // lattice nearest the REQUESTED suffixes first: keep the "_r"
      // remat suffix and the "_k:" kernel suffix where twins carry
      // them, then drop them (a remat/kernel-search-off replay prices
      // the default lowering), toggling "_ovl" (a pure latency-hiding
      // pricing delta) before "_wus" (which also moves optimizer-state
      // memory and the update triad) — so e.g. a plain "dp_ovl" request
      // never silently picks up WUS pricing while "dp" is available.
      auto strip = [](std::string s, const char* sfx) {
        size_t n = strlen(sfx);
        if (s.size() > n && s.compare(s.size() - n, n, sfx) == 0)
          s.erase(s.size() - n);
        return s;
      };
      std::string base = want;
      // "_r" is the last suffix of the canonical order: strip it before
      // extracting the "_k:" kernel suffix
      std::string rsuffix;
      {
        std::string stripped = strip(base, "_r");
        if (stripped.size() != base.size()) {
          rsuffix = "_r";
          base = stripped;
        }
      }
      std::string ksuffix;
      size_t kp = base.find("_k:");
      if (kp != std::string::npos) {
        ksuffix = base.substr(kp);
        base.erase(kp);
      }
      base = strip(strip(base, "_ovl"), "_wus");
      const bool has_wus = want.find("_wus") != std::string::npos;
      const bool has_ovl = want.find("_ovl") != std::string::npos;
      auto name_of = [&](bool w, bool o) {
        return base + (w ? "_wus" : "") + (o ? "_ovl" : "");
      };
      const std::string lattice[] = {name_of(has_wus, has_ovl),
                                     name_of(has_wus, !has_ovl),
                                     name_of(!has_wus, has_ovl),
                                     name_of(!has_wus, !has_ovl)};
      for (const std::string& ln : lattice) {
        if (pick != nullptr) break;
        for (const std::string& cand :
             {ln + ksuffix + rsuffix, ln + ksuffix, ln + rsuffix, ln}) {
          if (cand == want) continue;
          pick = find(cand);
          if (pick != nullptr) break;
        }
      }
    }
    if (pick == nullptr)
      throw std::runtime_error("unknown/illegal choice '" + want +
                               "' for op " + std::to_string(g.nodes[i].guid));
    cs.push_back(*pick);
  }
  MeasuredCosts measured;
  for (const auto& kv : req.get("measured").fields())
    measured[kv.first] = kv.second.as_double();
  SimResult r;
  if (mesh.pp > 1) {
    PipelineMeta pipe = pipeline_meta_from_json(req.get("pipeline"));
    if (!pipe.present)
      throw std::runtime_error(
          "mesh has pipe > 1 but the request carries no repeated-block "
          "pipeline metadata");
    const Json& pj = req.get("pipeline");
    int M = (int)pj.get("microbatches").as_int(0);
    if (M <= 0) M = cfg.pipeline_microbatches;
    if (M <= 0) M = 2 * mesh.pp;
    std::string sched = pj.get("schedule").as_string();
    if (sched.empty()) sched = cfg.pipeline_schedule;
    int kblocks = pipe.num_blocks / mesh.pp;
    bool circ = sched == "circular" ||
                (sched != "gpipe" && kblocks > 1 && M >= mesh.pp);
    bool sq = pj.get("shard_queue").as_bool(cfg.pipeline_shard_queue);
    // block-body rematerialization replays through the same pricing the
    // search ranked it with (remat-search off forces it back off)
    bool remat = cfg.enable_remat && cfg.training &&
                 pj.get("remat").as_bool(false);
    r = simulate_pipeline(g, m, mesh, cs, pipe, cfg.training,
                          cfg.opt_state_factor, &measured, M, circ, sq,
                          remat);
  } else {
    TaskgraphSimulator sim(g, m, mesh, cfg.training, cfg.overlap,
                           cfg.opt_state_factor, &measured);
    r = sim.simulate(cs);
  }
  Json out = Json::object();
  out.set("iteration_time", Json(r.iteration_time));
  out.set("memory", Json(r.memory));
  out.set("fwd_time", Json(r.fwd_time));
  out.set("bwd_time", Json(r.bwd_time));
  out.set("comm_time", Json(r.comm_time));
  out.set("gradsync_time", Json(r.gradsync_time));
  // per-node compute pricing provenance (guid -> analytic | learned |
  // measured): the simtrace corpus rows record which model priced each
  // op so accuracy tracking can attribute drift to the right source
  {
    Json srcs = Json::object();
    for (size_t i = 0; i < g.nodes.size(); ++i) {
      NodeCost nc = node_cost(g.nodes[i], cs[i], mesh, m, cfg.training,
                              &measured);
      srcs.set(std::to_string(g.nodes[i].guid),
               Json(std::string(cost_source_name(nc.src))));
    }
    out.set("cost_sources", srcs);
  }
  // predicted comm seconds hidden under compute (the schedule's
  // overlapped intervals + the pipeline/"_ovl" analytic hidden terms) —
  // the predicted twin of devtrace's measured overlapped_comms_s
  out.set("hidden_comm_time", Json(r.hidden_comm_time));
  Json tasks = Json::array();
  for (const SimTask& t : r.tasks) {
    Json tj = Json::object();
    const char* kinds[] = {"fwd", "bwd", "comm", "gradsync", "update"};
    tj.set("kind", Json(kinds[(int)t.kind]));
    tj.set("node", Json((int64_t)t.node_idx));
    tj.set("start", Json(t.start));
    tj.set("finish", Json(t.finish));
    if (!t.collective.empty()) {
      tj.set("collective", Json(t.collective));
      tj.set("bytes", Json(t.bytes));
    }
    if (t.hidden > 0)
      tj.set("hidden_s", Json(t.hidden));
    tasks.push_back(tj);
  }
  out.set("tasks", tasks);
  return out;
}

// Offline rule audit (corpus verification harness): for each rule in
// `subst_rules`, count pattern matches on the given graph and how many of
// them apply_rule can structurally rewrite, and check every rewritten
// graph still admits a data-parallel pricing (shape/topology integrity).
// No cost gating — this answers "is the rule well-formed and applicable",
// not "is it profitable" (the best-first loop answers that at search
// time).
Json match_only(const Json& req) {
  Graph g = Graph::from_json(req.get("nodes"));
  std::vector<SubstRule> rules = parse_rules(req.get("subst_rules"));
  MachineModel m;
  m.num_devices = 8;
  SearchConfig cfg;
  cfg.enable_parameter_parallel = true;
  int64_t next_guid = 0;
  for (const Node& n : g.nodes) next_guid = std::max(next_guid, n.guid + 1);
  Json out = Json::object();
  for (const SubstRule& rule : rules) {
    auto matches = find_matches(g, rule, 64);
    int applied = 0, priced = 0;
    for (const Match& match : matches) {
      int64_t guid = next_guid;
      RewriteTraceEntry trace;
      auto g2 = apply_rule(g, rule, match, &guid, &trace);
      if (!g2) continue;
      ++applied;
      // integrity: the rewritten graph must still price under the DP
      MeshShape mesh;
      mesh.dp = 2;
      mesh.mp = 2;
      auto choices = all_choices(*g2, mesh, cfg);
      DPResult dp = frontier_dp(*g2, choices, mesh, m, cfg, 0.0, nullptr);
      if (dp.ok) ++priced;
    }
    Json rj = Json::object();
    rj.set("matches", Json((int64_t)matches.size()));
    rj.set("applied", Json((int64_t)applied));
    rj.set("priced", Json((int64_t)priced));
    out.set(rule.name, rj);
  }
  return out;
}

char* dup_string(const std::string& s) {
  char* p = static_cast<char*>(malloc(s.size() + 1));
  memcpy(p, s.c_str(), s.size() + 1);
  return p;
}

}  // namespace
}  // namespace ffsearch

extern "C" {

const char* ffs_version() { return "ffsearch 0.1 (tpu-native unity search)"; }

// Returns malloc'd JSON string; caller frees with ffs_free.
char* ffs_optimize(const char* request_json) {
  try {
    ffsearch::Json req = ffsearch::Json::parse(request_json);
    return ffsearch::dup_string(ffsearch::optimize(req).dump());
  } catch (const std::exception& e) {
    ffsearch::Json err = ffsearch::Json::object();
    err.set("error", ffsearch::Json(std::string(e.what())));
    return ffsearch::dup_string(err.dump());
  }
}

// Parse a substitution rule corpus (reference RuleCollection format,
// substitution_loader.cc, or this repo's native list) and report what
// loaded: {"count": N, "names": [...]}. Used by --substitution-json
// validation and tests.
char* ffs_list_rules(const char* rules_json) {
  try {
    ffsearch::Json rj = ffsearch::Json::parse(rules_json);
    std::vector<ffsearch::SubstRule> rules = ffsearch::parse_rules(rj);
    ffsearch::Json out = ffsearch::Json::object();
    out.set("count", ffsearch::Json((int64_t)rules.size()));
    ffsearch::Json names = ffsearch::Json::array();
    for (size_t i = 0; i < rules.size() && i < 64; ++i)
      names.push_back(ffsearch::Json(rules[i].name));
    out.set("names", names);
    return ffsearch::dup_string(out.dump());
  } catch (const std::exception& e) {
    ffsearch::Json err = ffsearch::Json::object();
    err.set("error", ffsearch::Json(std::string(e.what())));
    return ffsearch::dup_string(err.dump());
  }
}

char* ffs_simulate(const char* request_json) {
  try {
    ffsearch::Json req = ffsearch::Json::parse(request_json);
    return ffsearch::dup_string(ffsearch::simulate_only(req).dump());
  } catch (const std::exception& e) {
    ffsearch::Json err = ffsearch::Json::object();
    err.set("error", ffsearch::Json(std::string(e.what())));
    return ffsearch::dup_string(err.dump());
  }
}

// Offline rule audit: {"nodes": [...], "subst_rules": [...]} ->
// {rule_name: {matches, applied, priced}} (corpus-sweep harness).
char* ffs_match_rules(const char* request_json) {
  try {
    ffsearch::Json req = ffsearch::Json::parse(request_json);
    return ffsearch::dup_string(ffsearch::match_only(req).dump());
  } catch (const std::exception& e) {
    ffsearch::Json err = ffsearch::Json::object();
    err.set("error", ffsearch::Json(std::string(e.what())));
    return ffsearch::dup_string(err.dump());
  }
}

void ffs_free(char* p) { free(p); }

}  // extern "C"

// TPU machine model: analytic compute + collective cost functions.
//
// Replaces the reference's SimpleMachineModel / EnhancedMachineModel /
// NetworkedMachineModel hierarchy (include/flexflow/simulator.h:212-515)
// with the model that matches TPU hardware: per-chip peak FLOP/s and HBM
// bandwidth set the roofline for compute; the ICI torus sets ring-collective
// costs inside a slice; DCN connects slices. The reference's
// per-(op,machine-view) measured-cost cache (simulator.h:750) maps to the
// `measured` override table injected from Python profiles.
#pragma once

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <map>
#include <string>

#include "ffs_json.hpp"

namespace ffsearch {

struct MachineModel {
  int num_devices = 1;
  double flops = 197e12;       // bf16 peak FLOP/s per chip
  double hbm_bw = 0.82e12;     // bytes/s
  double hbm_cap = 16e9;       // bytes
  double ici_bw = 45e9;        // bytes/s per link direction
  double ici_latency = 1e-6;   // seconds per hop
  double dcn_bw = 25e9;        // bytes/s per slice pair
  double dcn_latency = 10e-6;
  int num_slices = 1;
  double mxu_efficiency = 0.55;  // achievable fraction of peak on real shapes
  double min_op_time = 5e-7;     // floor per fused op (dispatch overhead)
  // Collective payloads relative to the graph's nominal dtype: under the
  // r4 mixed-precision regime activations AND gradients move in bf16
  // while tensors are declared f32, so every collective's bytes halve
  // (0.5). Validated against emitted HLO (tests/test_collective_validation
  // runs f32/CPU where this stays 1.0).
  double comm_bytes_factor = 1.0;

  static MachineModel from_json(const Json& j) {
    MachineModel m;
    m.num_devices = static_cast<int>(j.get("num_devices").as_int(1));
    m.flops = j.get("flops").as_double(m.flops);
    m.hbm_bw = j.get("hbm_bw").as_double(m.hbm_bw);
    m.hbm_cap = j.get("hbm_cap").as_double(m.hbm_cap);
    m.ici_bw = j.get("ici_bw").as_double(m.ici_bw);
    m.ici_latency = j.get("ici_latency").as_double(m.ici_latency);
    m.dcn_bw = j.get("dcn_bw").as_double(m.dcn_bw);
    m.dcn_latency = j.get("dcn_latency").as_double(m.dcn_latency);
    m.num_slices = static_cast<int>(j.get("num_slices").as_int(1));
    m.mxu_efficiency = j.get("mxu_efficiency").as_double(m.mxu_efficiency);
    m.min_op_time = j.get("min_op_time").as_double(m.min_op_time);
    m.comm_bytes_factor =
        j.get("comm_bytes_factor").as_double(m.comm_bytes_factor);
    return m;
  }

  // Effective bidirectional ring bandwidth per chip.
  double ring_bw() const { return ici_bw * 2.0; }

  // Ring all-reduce of `bytes` over `k` chips: 2(k-1)/k * B / bw.
  double allreduce_time(double bytes, int k) const {
    bytes *= comm_bytes_factor;
    if (k <= 1 || bytes <= 0) return 0.0;
    return ici_latency * (k - 1) + 2.0 * (k - 1) / k * bytes / ring_bw();
  }

  // All-gather producing `bytes` full output on each of `k` chips.
  double allgather_time(double bytes, int k) const {
    bytes *= comm_bytes_factor;
    if (k <= 1 || bytes <= 0) return 0.0;
    return ici_latency * (k - 1) + (double)(k - 1) / k * bytes / ring_bw();
  }

  // Reduce-scatter of `bytes` over `k` chips.
  double reducescatter_time(double bytes, int k) const {
    bytes *= comm_bytes_factor;
    if (k <= 1 || bytes <= 0) return 0.0;
    return ici_latency * (k - 1) + (double)(k - 1) / k * bytes / ring_bw();
  }

  // One full ring rotation (ring attention K/V pass): `bytes` total sent
  // per chip over k-1 neighbor hops on one ICI link direction.
  double ring_time(double bytes, int k) const {
    bytes *= comm_bytes_factor;
    if (k <= 1 || bytes <= 0) return 0.0;
    return ici_latency * (k - 1) + bytes / ici_bw;
  }

  // All-to-all: each chip exchanges its (bytes/k) shard with k-1 peers.
  double alltoall_time(double bytes, int k) const {
    bytes *= comm_bytes_factor;
    if (k <= 1 || bytes <= 0) return 0.0;
    return ici_latency + bytes * (k - 1) / k / k / ring_bw();
  }

  // Cross-slice (DCN) all-reduce of `bytes` across num_slices.
  double dcn_allreduce_time(double bytes) const {
    bytes *= comm_bytes_factor;
    if (num_slices <= 1 || bytes <= 0) return 0.0;
    return dcn_latency * (num_slices - 1) +
           2.0 * (num_slices - 1) / num_slices * bytes / dcn_bw;
  }

  int chips_per_slice() const {
    return std::max(1, num_devices / std::max(1, num_slices));
  }

  // Hierarchical all-reduce of `bytes` over `k` chips spanning `slices`
  // ICI domains: reduce-scatter+all-gather inside each slice over ICI,
  // cross-slice all-reduce of each chip's 1/k_inner shard over DCN — the
  // standard multislice gradient sync (NetworkedMachineModel's role,
  // reference simulator.h:515, re-expressed for the TPU slice topology).
  double hier_allreduce_time(double bytes, int k, int slices) const {
    // NOTE: delegates to allreduce_time, which applies comm_bytes_factor —
    // only the DCN term scales locally (no double scaling)
    if (k <= 1 || bytes <= 0) return 0.0;
    slices = std::max(1, std::min(slices, num_slices));
    if (slices <= 1) return allreduce_time(bytes, k);
    int k_inner = std::max(1, k / slices);
    double t = allreduce_time(bytes, k_inner);
    double shard = bytes * comm_bytes_factor / k_inner;
    t += dcn_latency * (slices - 1) +
         2.0 * (slices - 1) / slices * shard / dcn_bw;
    return t;
  }

  // Roofline: time for `flop` FLOPs touching `bytes` of HBM on one chip.
  // `dtype_size` > 2 (f32) halves MXU throughput. `min_op_time` is charged
  // additively as per-kernel dispatch overhead — fusing two kernels into
  // one (e.g. two narrow matmuls into a wide one) saves a dispatch, which
  // the reference's measured per-op costs capture implicitly
  // (src/runtime/model.cu:38-74) and a pure roofline would miss.
  double compute_time(double flop, double bytes, int dtype_size = 2) const {
    double peak = flops * mxu_efficiency * (dtype_size <= 2 ? 1.0 : 0.5);
    return std::max(flop / peak, bytes / hbm_bw) + min_op_time;
  }
};

// Measured-cost override table: key = "<guid>:<choice>" or param-hash from
// Python-side profiling, value = seconds. Analog of the reference's
// hash_to_op_cost cache fed by real microbenchmarks (simulator.h:750-752).
using MeasuredCosts = std::map<std::string, double>;

}  // namespace ffsearch

// TPU machine model: analytic compute + collective cost functions.
//
// Replaces the reference's SimpleMachineModel / EnhancedMachineModel /
// NetworkedMachineModel hierarchy (include/flexflow/simulator.h:212-515)
// with the model that matches TPU hardware: per-chip peak FLOP/s and HBM
// bandwidth set the roofline for compute; the ICI torus sets ring-collective
// costs inside a slice; DCN connects slices. The reference's
// per-(op,machine-view) measured-cost cache (simulator.h:750) maps to the
// `measured` override table injected from Python profiles.
#pragma once

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <map>
#include <string>
#include <vector>

#include "ffs_json.hpp"

namespace ffsearch {

// Logical mesh-axis ids for per-axis torus pricing. Values match the
// Spec axis constants in ffs_strategy.hpp (kData..kExpert).
enum : int8_t { AX_DATA = 0, AX_MODEL = 1, AX_SEQ = 2, AX_EXPERT = 3 };

// ---- learned cost model (flexflow_tpu/costmodel) ---------------------------
//
// Per-op-class ridge regression over log-space features, trained by
// scripts/costmodel.py on the simtrace measurement corpus ("A Learned
// Performance Model for TPUs", PAPERS.md 2008.01040) and shipped to the
// search inside the machine JSON ("learned" key, machine_to_json). The
// feature vector MUST mirror flexflow_tpu/costmodel/corpus.py featurize()
// exactly — same order, same transforms — or the coefficients price a
// different space than they were trained in:
//   f0 = log1p(fwd_flops / work_div)
//   f1 = log1p(total_io_bytes / work_div)
//   f2 = log1p(param_bytes)
//   f3 = log(work_div)
constexpr int kLearnedFeatures = 4;

struct LearnedClass {
  std::vector<double> wf, wb;      // [intercept, w0..w3] fwd / bwd
  std::vector<double> fmin, fmax;  // training feature hull
  double err = 0;                  // held-out median |log(pred/actual)|
  int64_t n = 0;                   // training rows (coverage)
};

// Which model priced a node's compute terms (NodeCost.src /
// search-trace "cost_source": the per-candidate provenance column).
enum : int8_t { SRC_ANALYTIC = 0, SRC_LEARNED = 1, SRC_MEASURED = 2 };
inline const char* cost_source_name(int8_t s) {
  return s == SRC_LEARNED ? "learned"
       : s == SRC_MEASURED ? "measured" : "analytic";
}

struct MachineModel {
  int num_devices = 1;
  double flops = 197e12;       // bf16 peak FLOP/s per chip
  double hbm_bw = 0.82e12;     // bytes/s
  double hbm_cap = 16e9;       // bytes
  double ici_bw = 45e9;        // bytes/s per link direction
  double ici_latency = 1e-6;   // seconds per hop
  double dcn_bw = 25e9;        // bytes/s per slice pair
  double dcn_latency = 10e-6;
  int num_slices = 1;
  double mxu_efficiency = 0.55;  // achievable fraction of peak on real shapes
  // Per-op-class efficiency: convs do NOT reach matmul-grade MXU
  // utilization even channels-last (im2col padding, halo reads, ragged
  // spatial extents) — pricing them at mxu_efficiency made every conv
  // strategy the search ranked untrustworthy (ISSUE 2 motivation;
  // bench_history: inception_proxy ran ~7% MFU while the model assumed
  // 55%). Calibrate from scripts/roofline.py per-class aggregates;
  // measured per-op costs still override everything.
  double conv_efficiency = 0.35;
  double min_op_time = 5e-7;     // floor per fused op (dispatch overhead)
  // Per-collective launch cost of an async (bucketed) collective: the
  // start/done pair XLA schedules around a hidden collective still costs
  // a dispatch plus the ring's first-hop latency. The latency-hiding
  // "_ovl" pricing charges this once per bucket, which is what stops the
  // bucket sweep from degenerating to infinitely many tiny buckets.
  double collective_launch_overhead = 2e-6;
  // Collective payloads relative to the graph's nominal dtype: under the
  // r4 mixed-precision regime activations AND gradients move in bf16
  // while tensors are declared f32, so every collective's bytes halve
  // (0.5). Validated against emitted HLO (tests/test_collective_validation
  // runs f32/CPU where this stays 1.0).
  double comm_bytes_factor = 1.0;

  // ICI torus extents of ONE slice (e.g. [4, 2] for a v5e-8, [4, 4, 4]
  // for a v4-128 cube). Replaces the reference's Enhanced/Networked
  // machine-model link graphs (simulator.h:229-515) with the structure
  // TPU hardware actually has. Empty = flat (every axis prices alike).
  std::vector<int64_t> torus;

  // Explicit non-uniform inter-slice fabric: dcn_adj[a][b] = best direct
  // link bandwidth between slices a and b (0 = no direct link), built
  // from the spec's `dcn_links` triples. Empty = uniform fabric at
  // dcn_bw. With a fabric present, every DCN pricer resolves the
  // (bandwidth, latency) of the ring RESTRICTED to the slices a
  // collective actually spans (dcn_ring below) instead of the global
  // collapse MachineSpec.effective_dcn used to pre-bake — the
  // bottleneck-link rule, per span (ISSUE 20 satellite).
  std::vector<std::vector<double>> dcn_adj;

  // Learned per-op-class compute pricing (empty = analytic only; the
  // Python side omits the table under FFS_NO_LEARNED_COSTS or when no
  // trained COSTMODEL.json exists, so absence == pre-costmodel
  // behavior bit-for-bit). Class absent from the map = coverage gate:
  // that class keeps the analytic roofline.
  std::map<std::string, LearnedClass> learned;
  double learned_hull_margin = 0.7;

  // Learned per-chip (fwd, bwd) seconds for `type` at feature vector
  // `f` — false when the class is untrained or `f` falls outside the
  // trained hull (plus margin): extrapolation falls back to analytic.
  bool learned_predict(const std::string& type,
                       const double (&f)[kLearnedFeatures],
                       double* fwd, double* bwd) const {
    auto it = learned.find(type);
    if (it == learned.end()) return false;
    const LearnedClass& lc = it->second;
    if (lc.wf.size() != kLearnedFeatures + 1 ||
        lc.wb.size() != kLearnedFeatures + 1 ||
        lc.fmin.size() != kLearnedFeatures ||
        lc.fmax.size() != kLearnedFeatures)
      return false;
    for (int i = 0; i < kLearnedFeatures; ++i)
      if (f[i] < lc.fmin[i] - learned_hull_margin ||
          f[i] > lc.fmax[i] + learned_hull_margin)
        return false;
    double lf = lc.wf[0], lb = lc.wb[0];
    for (int i = 0; i < kLearnedFeatures; ++i) {
      lf += lc.wf[i + 1] * f[i];
      lb += lc.wb[i + 1] * f[i];
    }
    *fwd = std::exp(lf);
    *bwd = std::exp(lb);
    return true;
  }
  // Per-logical-axis multipliers from embedding the CURRENT mesh into
  // the torus (assign_torus): a mesh axis mapped to a full torus dim
  // keeps the wrapped-ring bandwidth (1.0); a sub-ring of a dim is a
  // line without wraparound (0.5); a fragmented axis also pays hops.
  double ax_bw[4] = {1.0, 1.0, 1.0, 1.0};
  double ax_lat[4] = {1.0, 1.0, 1.0, 1.0};

  double axbw(int8_t a) const {
    return (a >= 0 && a < 4) ? ax_bw[(int)a] : 1.0;
  }
  double axlat(int8_t a) const {
    return (a >= 0 && a < 4) ? ax_lat[(int)a] : 1.0;
  }

  // Embed a (dp, mp, sp, ep) mesh into the slice torus and set the
  // per-axis multipliers. Latency/bandwidth-critical axes get first
  // pick of the torus dims: the per-layer psum (model), then the
  // attention K/V ring (seq), then the MoE exchange (expert); the
  // gradient ring (data) overlaps with backward and takes the rest.
  void assign_torus(int dp, int mp, int sp, int ep) {
    for (int i = 0; i < 4; ++i) {
      ax_bw[i] = 1.0;
      ax_lat[i] = 1.0;
    }
    if (torus.size() < 2) return;  // flat or 1-D: nothing to distinguish
    int64_t tprod = 1;
    for (int64_t t : torus) tprod *= t;
    if (tprod != (int64_t)chips_per_slice()) return;  // stale description
    std::vector<int64_t> cap(torus.begin(), torus.end());
    auto place = [&](int8_t a, int64_t k) {
      if (k <= 1) return;
      // exact full dim: wrapped ring at full per-dim bandwidth
      for (size_t i = 0; i < cap.size(); ++i)
        if (cap[i] == torus[i] && torus[i] == k) {
          cap[i] = 1;
          return;
        }
      // exact product of two untouched dims: the ring embeds across
      // both with wraparound (Hamiltonian cycle on the sub-torus)
      for (size_t i = 0; i < cap.size(); ++i)
        for (size_t j = i + 1; j < cap.size(); ++j)
          if (cap[i] == torus[i] && cap[j] == torus[j] &&
              torus[i] * torus[j] == k) {
            cap[i] = cap[j] = 1;
            return;
          }
      // exact product of ALL untouched dims (e.g. 8 on a 2x2x2 cube)
      {
        int64_t prod = 1;
        for (size_t i = 0; i < cap.size(); ++i)
          prod *= (cap[i] == torus[i]) ? torus[i] : 1;
        if (prod == k) {
          for (size_t i = 0; i < cap.size(); ++i)
            if (cap[i] == torus[i]) cap[i] = 1;
          return;
        }
      }
      // sub-ring of one dim: a line, no wraparound link — half bw
      for (size_t i = 0; i < cap.size(); ++i)
        if (cap[i] >= k && cap[i] % k == 0) {
          cap[i] /= k;
          ax_bw[(int)a] = 0.5;
          return;
        }
      // fragmented across dims: half bandwidth and doubled hop count
      ax_bw[(int)a] = 0.5;
      ax_lat[(int)a] = 2.0;
    };
    place(AX_MODEL, mp);
    place(AX_SEQ, sp);
    place(AX_EXPERT, ep);
    if (dp > 1) {
      int64_t rem = 1;
      for (int64_t c : cap) rem *= c;
      // data axis consuming ALL remaining intra-slice chips rides every
      // leftover link (+ DCN across slices, priced by hier_allreduce)
      if (!((int64_t)dp == rem || (rem > 1 && dp % rem == 0)))
        place(AX_DATA, dp);
    }
  }

  static MachineModel from_json(const Json& j) {
    MachineModel m;
    m.num_devices = static_cast<int>(j.get("num_devices").as_int(1));
    m.flops = j.get("flops").as_double(m.flops);
    m.hbm_bw = j.get("hbm_bw").as_double(m.hbm_bw);
    m.hbm_cap = j.get("hbm_cap").as_double(m.hbm_cap);
    m.ici_bw = j.get("ici_bw").as_double(m.ici_bw);
    m.ici_latency = j.get("ici_latency").as_double(m.ici_latency);
    m.dcn_bw = j.get("dcn_bw").as_double(m.dcn_bw);
    m.dcn_latency = j.get("dcn_latency").as_double(m.dcn_latency);
    m.num_slices = static_cast<int>(j.get("num_slices").as_int(1));
    m.mxu_efficiency = j.get("mxu_efficiency").as_double(m.mxu_efficiency);
    m.conv_efficiency = j.get("conv_efficiency").as_double(m.conv_efficiency);
    m.min_op_time = j.get("min_op_time").as_double(m.min_op_time);
    m.collective_launch_overhead = j.get("collective_launch_overhead")
                                       .as_double(m.collective_launch_overhead);
    m.comm_bytes_factor =
        j.get("comm_bytes_factor").as_double(m.comm_bytes_factor);
    const Json& tj = j.get("torus");
    if (!tj.is_null())
      for (const Json& t : tj.items()) m.torus.push_back(t.as_int(1));
    const Json& dl = j.get("dcn_links");
    if (!dl.is_null() && m.num_slices > 1) {
      const int S = m.num_slices;
      std::vector<std::vector<double>> adj(S, std::vector<double>(S, 0.0));
      bool any = false;
      for (const Json& e : dl.items()) {
        int a = static_cast<int>(e[0].as_int(-1));
        int b = static_cast<int>(e[1].as_int(-1));
        double bw = e[2].as_double(0.0);
        if (a < 0 || b < 0 || a >= S || b >= S || a == b || bw <= 0)
          continue;
        adj[a][b] = std::max(adj[a][b], bw);
        adj[b][a] = std::max(adj[b][a], bw);
        any = true;
      }
      if (any) m.dcn_adj = std::move(adj);
    }
    const Json& lj = j.get("learned");
    if (!lj.is_null()) {
      m.learned_hull_margin =
          lj.get("hull_margin").as_double(m.learned_hull_margin);
      for (const auto& kv : lj.get("classes").fields()) {
        LearnedClass lc;
        auto fill = [](const Json& arr, std::vector<double>& out) {
          for (const Json& v : arr.items()) out.push_back(v.as_double());
        };
        fill(kv.second.get("wf"), lc.wf);
        fill(kv.second.get("wb"), lc.wb);
        fill(kv.second.get("fmin"), lc.fmin);
        fill(kv.second.get("fmax"), lc.fmax);
        lc.err = kv.second.get("err").as_double(0);
        lc.n = kv.second.get("n").as_int(0);
        m.learned[kv.first] = std::move(lc);
      }
    }
    return m;
  }

  // Effective bidirectional ring bandwidth per chip.
  double ring_bw() const { return ici_bw * 2.0; }

  // Ring all-reduce of `bytes` over `k` chips: 2(k-1)/k * B / bw.
  // `axis` selects the per-axis torus multipliers (AX_*, -1 = neutral).
  double allreduce_time(double bytes, int k, int8_t axis = -1) const {
    bytes *= comm_bytes_factor;
    if (k <= 1 || bytes <= 0) return 0.0;
    return ici_latency * axlat(axis) * (k - 1) +
           2.0 * (k - 1) / k * bytes / (ring_bw() * axbw(axis));
  }

  // All-gather producing `bytes` full output on each of `k` chips.
  double allgather_time(double bytes, int k, int8_t axis = -1) const {
    bytes *= comm_bytes_factor;
    if (k <= 1 || bytes <= 0) return 0.0;
    return ici_latency * axlat(axis) * (k - 1) +
           (double)(k - 1) / k * bytes / (ring_bw() * axbw(axis));
  }

  // Reduce-scatter of `bytes` over `k` chips.
  double reducescatter_time(double bytes, int k, int8_t axis = -1) const {
    bytes *= comm_bytes_factor;
    if (k <= 1 || bytes <= 0) return 0.0;
    return ici_latency * axlat(axis) * (k - 1) +
           (double)(k - 1) / k * bytes / (ring_bw() * axbw(axis));
  }

  // One full ring rotation (ring attention K/V pass): `bytes` total sent
  // per chip over k-1 neighbor hops on one ICI link direction.
  double ring_time(double bytes, int k, int8_t axis = -1) const {
    bytes *= comm_bytes_factor;
    if (k <= 1 || bytes <= 0) return 0.0;
    return ici_latency * axlat(axis) * (k - 1) +
           bytes / (ici_bw * axbw(axis));
  }

  // All-to-all: each chip exchanges its (bytes/k) shard with k-1 peers.
  double alltoall_time(double bytes, int k, int8_t axis = -1) const {
    bytes *= comm_bytes_factor;
    if (k <= 1 || bytes <= 0) return 0.0;
    return ici_latency * axlat(axis) +
           bytes * (k - 1) / k / k / (ring_bw() * axbw(axis));
  }

  // Hop-shortest, then widest-bottleneck route a->b over the explicit
  // inter-slice link graph — the native twin of
  // MachineSpec.effective_dcn's route() (Bellman-Ford relaxation).
  // Unreachable pairs fall back to the uniform dcn_bw with a 2-hop
  // penalty (the fabric must be connected through a spine).
  void dcn_route(int a, int b, int* hops, double* bw) const {
    const int S = num_slices;
    std::vector<int> h(S, -1);
    std::vector<double> w(S, 0.0);
    h[a] = 0;
    w[a] = 1e300;
    for (int it = 0; it < S; ++it) {
      bool changed = false;
      for (int u = 0; u < S; ++u) {
        if (h[u] < 0) continue;
        for (int v = 0; v < S; ++v) {
          double link = dcn_adj[u][v];
          if (link <= 0) continue;
          int ch = h[u] + 1;
          double cw = std::min(w[u], link);
          if (h[v] < 0 || ch < h[v] || (ch == h[v] && cw > w[v])) {
            h[v] = ch;
            w[v] = cw;
            changed = true;
          }
        }
      }
      if (!changed) break;
    }
    if (h[b] < 0 || w[b] >= 1e300) {
      *hops = 2;
      *bw = dcn_bw;
    } else {
      *hops = h[b];
      *bw = w[b];
    }
  }

  // (bandwidth, latency) of the cross-slice DCN ring restricted to the
  // `slices` consecutive slices a collective actually spans: the ring is
  // paced by its slowest routed pair and latency scales with the longest
  // routed path. Uniform fabric (no dcn_links) keeps (dcn_bw,
  // dcn_latency) — bit-identical to the pre-fabric model.
  void dcn_ring(int slices, double* bw, double* lat) const {
    *bw = dcn_bw;
    *lat = dcn_latency;
    if (dcn_adj.empty() || num_slices <= 1 || slices <= 1) return;
    slices = std::min(slices, num_slices);
    double worst_bw = 1e300;
    int worst_hops = 1;
    for (int i = 0; i < slices; ++i) {
      int a = i, b = (i + 1) % slices;
      if (a == b) continue;
      int hops;
      double bbw;
      dcn_route(a, b, &hops, &bbw);
      worst_bw = std::min(worst_bw, bbw);
      worst_hops = std::max(worst_hops, hops);
    }
    if (worst_bw >= 1e300) worst_bw = dcn_bw;
    *bw = worst_bw;
    *lat = dcn_latency * worst_hops;
  }

  // Cross-slice (DCN) all-reduce of `bytes` across num_slices.
  double dcn_allreduce_time(double bytes) const {
    bytes *= comm_bytes_factor;
    if (num_slices <= 1 || bytes <= 0) return 0.0;
    double bw, lat;
    dcn_ring(num_slices, &bw, &lat);
    return lat * (num_slices - 1) +
           2.0 * (num_slices - 1) / num_slices * bytes / bw;
  }

  int chips_per_slice() const {
    return std::max(1, num_devices / std::max(1, num_slices));
  }

  // Hierarchical all-reduce of `bytes` over `k` chips spanning `slices`
  // ICI domains: reduce-scatter+all-gather inside each slice over ICI,
  // cross-slice all-reduce of each chip's 1/k_inner shard over DCN — the
  // standard multislice gradient sync (NetworkedMachineModel's role,
  // reference simulator.h:515, re-expressed for the TPU slice topology).
  double hier_allreduce_time(double bytes, int k, int slices,
                             int8_t axis = -1) const {
    // NOTE: delegates to allreduce_time, which applies comm_bytes_factor —
    // only the DCN term scales locally (no double scaling)
    if (k <= 1 || bytes <= 0) return 0.0;
    slices = std::max(1, std::min(slices, num_slices));
    if (slices <= 1) return allreduce_time(bytes, k, axis);
    int k_inner = std::max(1, k / slices);
    double t = allreduce_time(bytes, k_inner, axis);
    double shard = bytes * comm_bytes_factor / k_inner;
    double bw, lat;
    dcn_ring(slices, &bw, &lat);
    t += lat * (slices - 1) + 2.0 * (slices - 1) / slices * shard / bw;
    return t;
  }

  // Weight-update-sharding gradient sync, reduce-scatter half: RS over the
  // in-slice ring on ICI; cross-slice, each chip's 1/k_inner shard
  // all-reduces over DCN (the hier_allreduce decomposition, with the
  // all-gather half split out because WUS gathers UPDATED params later).
  double wus_rs_time(double bytes, int k, int slices, int8_t axis = -1) const {
    if (k <= 1 || bytes <= 0) return 0.0;
    slices = std::max(1, std::min(slices, num_slices));
    int k_inner = std::max(1, k / slices);
    double t = reducescatter_time(bytes, k_inner, axis);
    if (slices > 1) {
      double shard = bytes * comm_bytes_factor / k_inner;
      double bw, lat;
      dcn_ring(slices, &bw, &lat);
      t += lat * (slices - 1) + 2.0 * (slices - 1) / slices * shard / bw;
    }
    return t;
  }

  // All-gather half of the WUS sync: rebuild the replicated compute params
  // from the per-chip shards after the local optimizer step.
  double wus_ag_time(double bytes, int k, int slices, int8_t axis = -1) const {
    if (k <= 1 || bytes <= 0) return 0.0;
    slices = std::max(1, std::min(slices, num_slices));
    int k_inner = std::max(1, k / slices);
    double t = allgather_time(bytes, k_inner, axis);
    if (slices > 1) {
      double shard = bytes * comm_bytes_factor / k_inner;
      double bw, lat;
      dcn_ring(slices, &bw, &lat);
      t += lat * (slices - 1) + (double)(slices - 1) / slices * shard / bw;
    }
    return t;
  }

  // Fraction of a padded tile a dimension actually fills: the MXU is a
  // 128x128 systolic array, so a dim that is not a multiple of the tile
  // edge pads up and wastes the remainder (a 160-wide matmul runs two
  // 128-tiles at 62% fill).
  static double tile_util(double d, double tile) {
    if (d <= 0) return 1.0;
    double tiles = std::ceil(d / tile);
    return d / (tiles * tile);
  }

  // Shape-aware achievable fraction of peak for an (M,N,K) matmul:
  // the calibrated per-class scalar (``asymptote``; defaults to
  // mxu_efficiency, the large-shape asymptote) scaled by tile fill on
  // all three dims. Large multiples of 128 reproduce the flat model
  // exactly; narrow/ragged shapes — a per-chip batch of a few rows, a
  // 96-channel conv — pay the padding the flat model hid (VERDICT r4
  // Weak #4: "every unmeasured op inherits the single scalar"). Conv
  // callers pass conv_efficiency as the asymptote.
  double matmul_efficiency(double M, double N, double K,
                           double asymptote = -1.0) const {
    if (asymptote <= 0) asymptote = mxu_efficiency;
    double u = tile_util(M, 128.0) * tile_util(N, 128.0) *
               tile_util(K, 128.0);
    return asymptote * std::max(0.05, u);
  }

  // Roofline: time for `flop` FLOPs touching `bytes` of HBM on one chip.
  // `dtype_size` > 2 (f32) halves MXU throughput. `min_op_time` is charged
  // additively as per-kernel dispatch overhead — fusing two kernels into
  // one (e.g. two narrow matmuls into a wide one) saves a dispatch, which
  // the reference's measured per-op costs capture implicitly
  // (src/runtime/model.cu:38-74) and a pure roofline would miss.
  // `eff` overrides the flat mxu_efficiency (shape-aware callers).
  double compute_time(double flop, double bytes, int dtype_size = 2,
                      double eff = -1.0) const {
    if (eff <= 0) eff = mxu_efficiency;
    double peak = flops * eff * (dtype_size <= 2 ? 1.0 : 0.5);
    return std::max(flop / peak, bytes / hbm_bw) + min_op_time;
  }
};

// Measured-cost override table: key = "<guid>:<choice>" or param-hash from
// Python-side profiling, value = seconds. Analog of the reference's
// hash_to_op_cost cache fed by real microbenchmarks (simulator.h:750-752).
using MeasuredCosts = std::map<std::string, double>;

}  // namespace ffsearch

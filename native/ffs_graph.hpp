// PCG graph representation for the search core.
//
// Analog of PCG::Graph (include/flexflow/graph.h:293): nodes are compute
// ops with global (unsharded) shapes; parallelization is a per-node
// *sharding choice* (see ffs_strategy.hpp) rather than inserted parallel
// ops — under GSPMD the four resharding operators become spec transitions
// on edges, so the search manipulates specs directly and the Python side
// materializes constraint boundaries from them.
#pragma once

#include <cstdint>
#include <map>
#include <string>
#include <vector>

#include "ffs_json.hpp"

namespace ffsearch {

using Shape = std::vector<int64_t>;

inline int64_t shape_elems(const Shape& s) {
  int64_t n = 1;
  for (int64_t d : s) n *= d;
  return n;
}

enum class Role : uint8_t { Sample, Channel, Head, Seq, Expert, Other };

inline Role role_from_string(const std::string& s) {
  if (s == "sample") return Role::Sample;
  if (s == "channel") return Role::Channel;
  if (s == "head") return Role::Head;
  if (s == "seq") return Role::Seq;
  if (s == "expert") return Role::Expert;
  return Role::Other;
}

struct EdgeRef {
  int64_t src_guid = -1;  // -1 => graph input (fed from host)
  int src_idx = 0;
};

struct Node {
  int64_t guid = 0;
  std::string type;  // OperatorType name, e.g. "LINEAR"
  std::string name;
  std::vector<EdgeRef> inputs;
  std::vector<Shape> input_shapes;
  std::vector<Shape> output_shapes;
  std::vector<std::vector<Role>> roles;        // per output dim roles
  std::map<std::string, Shape> params;          // param name -> shape
  double fwd_flops = 0.0;
  int dtype_size = 4;
  Json attrs;  // op-specific attributes (num_heads, axis, ...)

  int64_t param_bytes() const {
    int64_t b = 0;
    for (const auto& kv : params) b += shape_elems(kv.second) * dtype_size;
    return b;
  }
  int64_t output_bytes(int i) const {
    return shape_elems(output_shapes[i]) * dtype_size;
  }
  int64_t input_bytes(int i) const {
    return shape_elems(input_shapes[i]) * dtype_size;
  }
  int64_t total_io_bytes() const {
    int64_t b = param_bytes();
    for (size_t i = 0; i < input_shapes.size(); ++i) b += input_bytes(i);
    for (size_t i = 0; i < output_shapes.size(); ++i) b += output_bytes(i);
    return b;
  }
};

struct Graph {
  std::vector<Node> nodes;            // topological order (as built)
  std::map<int64_t, int> index_of;    // guid -> index in nodes
  // consumers[guid] = list of (consumer node index, consumer input slot)
  std::map<int64_t, std::vector<std::pair<int, int>>> consumers;

  static Graph from_json(const Json& j) {
    Graph g;
    for (const Json& nj : j.items()) {
      Node n;
      n.guid = nj.get("guid").as_int();
      n.type = nj.get("type").as_string();
      n.name = nj.get("name").as_string();
      for (const Json& e : nj.get("inputs").items()) {
        EdgeRef r;
        r.src_guid = e[0].as_int(-1);
        r.src_idx = static_cast<int>(e[1].as_int(0));
        n.inputs.push_back(r);
      }
      auto parse_shapes = [](const Json& arr) {
        std::vector<Shape> out;
        for (const Json& sj : arr.items()) {
          Shape s;
          for (const Json& d : sj.items()) s.push_back(d.as_int());
          out.push_back(s);
        }
        return out;
      };
      n.input_shapes = parse_shapes(nj.get("input_shapes"));
      n.output_shapes = parse_shapes(nj.get("output_shapes"));
      for (const Json& rj : nj.get("roles").items()) {
        std::vector<Role> rr;
        for (const Json& r : rj.items()) rr.push_back(role_from_string(r.as_string()));
        n.roles.push_back(rr);
      }
      for (const auto& kv : nj.get("params").fields()) {
        Shape s;
        for (const Json& d : kv.second.items()) s.push_back(d.as_int());
        n.params[kv.first] = s;
      }
      n.fwd_flops = nj.get("flops").as_double();
      n.dtype_size = static_cast<int>(nj.get("dtype_size").as_int(4));
      n.attrs = nj.get("attrs");
      g.index_of[n.guid] = static_cast<int>(g.nodes.size());
      g.nodes.push_back(std::move(n));
    }
    for (size_t i = 0; i < g.nodes.size(); ++i) {
      for (size_t slot = 0; slot < g.nodes[i].inputs.size(); ++slot) {
        const EdgeRef& r = g.nodes[i].inputs[slot];
        if (r.src_guid >= 0)
          g.consumers[r.src_guid].push_back({static_cast<int>(i),
                                             static_cast<int>(slot)});
      }
    }
    return g;
  }

};

}  // namespace ffsearch

// Sharding-choice enumeration + resharding cost model.
//
// This is the TPU-native re-expression of the reference's substitution
// generators (src/runtime/substitution.cc:1726-1860): where the reference
// rewrites the PCG to insert Repartition/Replicate/Combine/Reduction ops
// around Linear/Attention/Conv (create_partition_linear_combine,
// create_replicate_linear_combine, create_partition_attention_combine, ...),
// we enumerate the *sharding choices* those rewrites produce directly:
//
//   dp       = partition sample dim              (Repartition on batch)
//   dp_col   = column-parallel weights           (Partition(out-dim)+Combine)
//   dp_row   = row-parallel weights + psum       (Replicate(in)+Reduction)
//   dp_head  = attribute parallelism over heads  (Partition(head)+Combine)
//   rep      = fully replicated
//
// An edge whose producer spec != consumer required spec carries a reshard
// cost — the GSPMD collective that the reference's parallel ops performed
// as Legion region copies.
#pragma once

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <map>
#include <set>
#include <string>
#include <vector>

#include "ffs_graph.hpp"
#include "ffs_machine.hpp"

namespace ffsearch {

// Axis ids in a Spec entry: -1 replicated; 0..3 name the mesh axes of the
// (data, model, seq, expert) hybrid mesh — the N-D generalization of the
// reference's MachineView enumeration (graph.h:221) where a view is a
// device grid the op is laid out on.
constexpr int8_t kRep = -1;
constexpr int8_t kData = 0;
constexpr int8_t kModel = 1;
constexpr int8_t kSeq = 2;
constexpr int8_t kExpert = 3;
// sample parallelism (reference config.h:134 enable_sample_parallel): the
// sample dim sharded over BOTH the data and model axes jointly — a 2-D
// partition of the batch, used when an op's weights are replicated and
// the model axis would otherwise sit idle for it
constexpr int8_t kDataModel = 4;

using Spec = std::vector<int8_t>;

struct MeshShape {
  int dp = 1;  // data axis
  int mp = 1;  // model (tensor/attribute) axis
  int sp = 1;  // seq (context/ring) axis
  int ep = 1;  // expert axis
  int pp = 1;  // pipe axis (pipeline stages; r4 — the reference only
               // stubs OP_PIPELINE, ffconst.h:153). pp > 1 requires a
               // repeated-block graph; per-node choices then apply to the
               // inner (dp) mesh and the pipeline wraps them (ffs_sim.hpp
               // simulate_pipeline, which prices the GPipe-vs-circular
               // schedule and the microbatch count as dimensions; "_wus"
               // twins stay in play — the pipeline executor reduce-
               // scatters the stacked body grads over the data axes).
  int axis_size(int8_t axis) const {
    switch (axis) {
      case kData: return dp;
      case kModel: return mp;
      case kSeq: return sp;
      case kExpert: return ep;
      case kDataModel: return dp * mp;
      default: return 1;
    }
  }
  int total() const { return dp * mp * sp * ep * pp; }
};

inline Spec rep_spec(size_t rank) { return Spec(rank, kRep); }

// Named mesh axis ("data"/"model"/"seq"/"expert", e.g. repartition(axis=...))
// -> axis id; unrecognized/absent names fall back to the dim-derived
// default (dim 0 = batch = data, else model). Single definition shared by
// mesh pinning (ffs_search.cpp) and choice pricing below.
inline int8_t axis_from_name(const std::string& name, int64_t dim) {
  if (name == "data") return kData;
  if (name == "model") return kModel;
  if (name == "seq") return kSeq;
  if (name == "expert") return kExpert;
  return dim == 0 ? kData : kModel;
}

// How many ICI slices the data axis spans. Mesh legality (enumerate_meshes)
// keeps model/seq/expert inside one slice — their latency-sensitive
// collectives ride ICI — so only the gradient ring (data axis) crosses DCN.
inline int slices_spanned(const MeshShape& mesh, const MachineModel& m) {
  if (m.num_slices <= 1) return 1;
  int inner = mesh.mp * mesh.sp * mesh.ep;
  int dp_in_slice = std::max(1, m.chips_per_slice() / inner);
  return std::max(1, mesh.dp / dp_in_slice);
}

inline int shards_of(const Spec& s, const MeshShape& mesh) {
  int k = 1;
  for (int8_t e : s)
    if (e >= 0) k *= mesh.axis_size(e);
  return k;
}

struct Choice {
  std::string name;
  std::vector<Spec> out;               // per output tensor
  std::vector<Spec> in;                // required spec per input tensor
  std::map<std::string, Spec> param;   // per parameter
  double work_div = 1.0;               // compute FLOPs divided by this
  double psum_bytes = 0.0;             // partial-sum bytes reduced over model axis
  int psum_k = 1;
  int8_t psum_axis = kModel;           // mesh axis the psum rides (torus pricing)
  int8_t gather_axis = kModel;         // mesh axis a Combine gathers over
  double gradsync_bytes = 0.0;         // per-iteration gradient allreduce bytes
  int gradsync_k = 1;                  // chips in the gradient ring (dp * sp)
  bool wus = false;                    // weight-update sharding: gradsync runs
                                       // as reduce-scatter + all-gather and the
                                       // optimizer state shards over the ring
  bool ovl = false;                    // comms-compute overlap: the gradient
                                       // sync issues as size-targeted bucketed
                                       // async collectives in reverse-backward
                                       // order; only the un-hidden tail is
                                       // priced (overlap_price below), plus a
                                       // per-bucket launch overhead
  double bwd_psum_bytes = 0.0;         // backward-only partial-sum all-reduce
                                       // (col-parallel dX; replicated scatter
                                       // grads) over psum_axis
  double wgather_bytes = 0.0;          // forward-only weight all-gather over
                                       // psum_axis (tiny-batch row-parallel
                                       // lowering moves the kernel, once)
  double ring_bytes = 0.0;             // K/V bytes a device sends over a full
                                       // ring-attention rotation (seq axis)
  int ring_k = 1;                      // seq-ring size (hop count = ring_k-1)
  double gather_bytes = 0.0;           // all-gather a parallel-op boundary
  int gather_k = 1;                    // (Combine) forces
  std::string kernel;                  // searched kernel implementation
                                       // ("" = the op's default lowering;
                                       // "flash" / "fused" /
                                       // "conv_bn_fused" for the "_k:"
                                       // choice twins — ISSUE 15)
  bool remat = false;                  // rematerialization: checkpoint the
                                       // op's boundary (inputs) and
                                       // recompute its interior in backward
                                       // — node_act_bytes drops to zero and
                                       // node_cost charges one extra
                                       // forward ("_r" twins — ISSUE 20)
};

// ---- kernel-implementation dimension ("_k:<impl>" twins) -------------------
//
// The search decides HOW TO SHARD every op but, until this dimension,
// not WHICH KERNEL runs it. Ops with registered kernel alternatives
// spawn "_k:<impl>" twins of every sharding choice (composing with the
// "_wus"/"_ovl" suffix lattice — canonical order base[_wus][_ovl][_k:i]),
// each priced per-impl: measured "<guid>:fwd:<impl>" rows override a
// learned "<TYPE>:<impl>" class which overrides the analytic
// HBM-traffic delta vs the default lowering. FlexFlow/Unity's joint
// algorithmic+parallelization optimization (substitution.cc:2229)
// expressed on the suffix lattice.

// Default kernel impl of (node, choice) — what executes when no "_k:"
// twin is chosen. Attention's ring impl is carried by the existing
// "_ring" seq-sharding suffix (ring is exactly the seq-sharded
// execution, so its legality gate IS the seq mesh), not a "_k:" twin.
inline const char* kernel_default_impl(const Node& n, const Choice& c) {
  if (n.type == "MULTIHEAD_ATTENTION")
    return c.name.find("_ring") != std::string::npos ? "ring" : "einsum";
  if (n.type == "CONV2D") return "conv";
  if (c.wus) return "triad";
  return "";
}

// Structural legality of a kernel alternative on `n`: "" = legal, else a
// named rejection reason recorded in the search trace (the flash gate
// mirrors ops/pallas_kernels.flash_attention_available — Q-block tile
// divisibility and lane-aligned head dim; conv_bn_fused mirrors the
// layout.py fold eligibility shipped as the `bn_fusable` node attr).
inline std::string kernel_gate(const Node& n, const std::string& impl,
                               bool training = true) {
  if (impl == "flash") {
    if (n.type != "MULTIHEAD_ATTENTION") return "not_attention";
    int64_t heads = n.attrs.get("num_heads").as_int(0);
    const Shape& os = n.output_shapes.empty() ? Shape{} : n.output_shapes[0];
    if (os.size() < 3 || heads <= 0) return "no_attention_geometry";
    int64_t seq = os[1];
    int64_t head_dim = os.back() / heads;
    for (const Shape& is : n.input_shapes)
      if ((int64_t)is.size() < 2 || is[1] != seq)
        return "not_self_attention";
    if (seq % 128) return "seq_not_divisible_by_flash_tile_128";
    if (head_dim % 8) return "head_dim_not_lane_aligned_8";
    // attention-prob dropout has no flash lowering (the kernel never
    // materializes the probabilities to drop) — training forwards take
    // the einsum path, so pricing flash would be a priced-vs-executed
    // gap; at inference dropout is off and flash stays legal
    if (training && n.attrs.get("dropout").as_double(0.0) > 0.0)
      return "attention_prob_dropout_unsupported";
    return "";
  }
  if (impl == "fused") {
    // fused optimizer-update region: collapses the WUS
    // RS -> update-triad -> AG chain into one dispatch
    if (n.param_bytes() <= 0) return "no_parameters";
    return "";
  }
  if (impl == "conv_bn_fused") {
    if (n.type != "CONV2D") return "not_conv";
    if (n.attrs.get("bn_fusable").as_int(0) == 0)
      return "no_foldable_batchnorm_consumer";
    return "";
  }
  return "unknown_impl";
}

// Layout-only ops XLA fuses into their producer/consumer on TPU: a slice,
// concat or reshape of a matmul output compiles to index arithmetic inside
// the neighboring fused kernel, not a standalone HBM round-trip. Charging
// them real traffic would make kernel-fusion rewrites (one wide matmul +
// split vs two narrow matmuls) look like losses when on hardware they win.
inline bool is_view_op(const std::string& t) {
  return t == "SPLIT" || t == "CONCAT" || t == "RESHAPE" || t == "FLAT" ||
         t == "IDENTITY" || t == "NOOP" || t == "INPUT";
}

// ---- rematerialization dimension ("_r" twins) ------------------------------
//
// A "_r" twin checkpoints the op's boundary (input) activations and
// recomputes its interior in backward: node_act_bytes drops to zero (the
// inputs are already counted at their producers; the output is rebuilt
// from them before the backward pass) and node_cost charges one extra
// forward in backward — through the same measured > learned > analytic
// chain, so a flash "_k:" parent's recompute prices the flash forward.
// The frontier DP's existing per-candidate memory terms then weigh freed
// HBM against recompute seconds per op: a memory-capped search picks "_r"
// exactly where the freed bytes buy a better mesh/batch (ISSUE 20).

// Structural legality of a remat twin of choice `c` on `n`: "" = legal,
// else a named rejection reason recorded in the search trace. The
// interior-vs-boundary test is impl-aware — einsum attention's interior
// includes the materialized [B,H,S,S] score tensor (the same score-bytes
// formula node_cost's flash delta subtracts); flash/ring never
// materialize it, so their interior is the output alone.
inline std::string remat_gate(const Node& n, const Choice& c,
                              bool training = true) {
  if (!training) return "not_training";
  if (is_view_op(n.type)) return "view_op_no_interior";
  // stateful interiors: recomputing the forward would re-advance state
  // (BN running stats) or re-sample masks/assignments (dropout, MoE
  // routing) — the recomputed interior would not match the one the
  // forward pass produced, so numerics drift
  if (n.type == "BATCH_NORM") return "stateful_interior";
  if (n.type == "DROPOUT" || n.attrs.get("dropout").as_double(0.0) > 0.0)
    return "dropout_interior";
  if (n.type == "EXPERTS" || n.type == "AGGREGATE" || n.type == "GROUP_BY" ||
      n.type == "TOPK" || n.type == "CACHE")
    return "stateful_interior";
  // the recompute re-runs the forward's collectives too; the pricing
  // charges compute only, so choices whose forward moves bytes (psum /
  // ring / gather / weight-gather) do not spawn twins — this also keeps
  // the emitted collective census identical (recompute duplicates
  // edges, not collectives)
  if (c.psum_bytes > 0 || c.ring_bytes > 0 || c.gather_bytes > 0 ||
      c.wgather_bytes > 0)
    return "forward_collective_interior";
  // interior (what the checkpoint frees) must exceed the boundary (what
  // it keeps): output bytes + impl-aware extras vs the UNIQUE input
  // tensors (self-attention's q=k=v count once)
  double interior = 0;
  for (size_t i = 0; i < n.output_shapes.size(); ++i)
    interior += (double)n.output_bytes((int)i);
  if (n.type == "MULTIHEAD_ATTENTION" && c.kernel != "flash" &&
      c.name.find("_ring") == std::string::npos &&
      !n.output_shapes.empty() && n.output_shapes[0].size() >= 2) {
    int64_t heads = n.attrs.get("num_heads").as_int(1);
    const Shape& os = n.output_shapes[0];
    interior += (double)os[0] * (double)heads * (double)os[1] *
                (double)os[1] * 4.0;
  }
  double boundary = 0;
  std::vector<std::pair<int64_t, int>> seen;
  for (size_t i = 0; i < n.input_shapes.size(); ++i) {
    if (i < n.inputs.size() && n.inputs[i].src_guid >= 0) {
      std::pair<int64_t, int> key{n.inputs[i].src_guid, n.inputs[i].src_idx};
      if (std::find(seen.begin(), seen.end(), key) != seen.end()) continue;
      seen.push_back(key);
    }
    boundary += (double)n.input_bytes((int)i);
  }
  if (interior <= boundary) return "interior_not_larger_than_boundary";
  return "";
}

// ---- latency-hiding (comms-compute overlap) pricing -----------------------

// Bucket sizes the "_ovl" latency-hiding term sweeps (MB of wire payload
// per bucket). Small buckets start hiding earlier (the un-hideable tail is
// one bucket's comm) but each bucket pays a launch; the sweep's argmin is
// the searched bucket size "--overlap-bucket-mb auto" follows.
constexpr double kOvlBucketMB[] = {1.0, 2.0, 4.0, 8.0, 16.0, 32.0};
constexpr int kOvlBucketCount = 6;

struct OverlapPricing {
  double exposed = 0;    // comm time the step still waits on
  double hidden = 0;     // comm time priced as hidden under compute
  int buckets = 1;
  double bucket_mb = 0;  // argmin of the sweep
};

// Exposed time of `comm_s` seconds of gradient-sync comm issued as B
// size-targeted buckets in reverse-backward order, with `hideable_s` of
// compute still running when the first bucket's collective fires:
//   exposed(B) = max(comm/B, comm - hideable) + B * launch
// The comm/B floor is the last bucket's collective — produced by the last
// backward op, nothing left to hide it under (the optimizer-fusion
// prefetch window is part of hideable_s when the caller knows it).
// `wire_bytes` are post-comm_bytes_factor payload bytes (bucket count is
// a property of what moves on the wire).
inline OverlapPricing overlap_price(const MachineModel& m, double comm_s,
                                    double wire_bytes, double hideable_s) {
  OverlapPricing best;
  best.exposed = comm_s;
  if (comm_s <= 0) return best;
  bool first = true;
  for (int i = 0; i < kOvlBucketCount; ++i) {
    double mb = kOvlBucketMB[i];
    int B = std::max(1, (int)std::ceil(wire_bytes / (mb * 1e6)));
    double exp = std::max(comm_s / B, comm_s - std::max(0.0, hideable_s)) +
                 B * m.collective_launch_overhead;
    if (first || exp < best.exposed) {
      best.exposed = exp;
      best.hidden = std::max(0.0, comm_s - std::max(comm_s / B,
                                                    comm_s - hideable_s));
      best.buckets = B;
      best.bucket_mb = mb;
      first = false;
    }
  }
  return best;
}

// ---- reshard cost ---------------------------------------------------------

// Cost of transforming a tensor of `global_bytes` laid out as `a` into
// layout `b`. Approximations follow §2.3's op→collective mapping.
inline double reshard_cost(const Spec& a, const Spec& b, double global_bytes,
                           const MeshShape& mesh, const MachineModel& m) {
  if (a == b) return 0.0;
  int ka = shards_of(a, mesh), kb = shards_of(b, mesh);
  if (ka <= 1 && kb <= 1) return 0.0;
  // (dim, base axis) pairs; the joint kDataModel entry expands into its
  // base axes so data ⊂ data+model reads as pure additional slicing
  std::set<std::pair<int, int8_t>> sa, sb;
  auto expand = [](std::set<std::pair<int, int8_t>>& s, int i, int8_t ax) {
    if (ax == kDataModel) {
      s.insert({i, kData});
      s.insert({i, kModel});
    } else {
      s.insert({i, ax});
    }
  };
  for (size_t i = 0; i < a.size(); ++i) if (a[i] >= 0) expand(sa, (int)i, a[i]);
  for (size_t i = 0; i < b.size(); ++i) if (b[i] >= 0) expand(sb, (int)i, b[i]);
  bool a_in_b = std::includes(sb.begin(), sb.end(), sa.begin(), sa.end());
  if (a_in_b) return 0.0;  // pure additional slicing: local
  global_bytes *= m.comm_bytes_factor;  // bf16 activations on TPU
  bool b_in_a = std::includes(sa.begin(), sa.end(), sb.begin(), sb.end());
  int k_keep = 1;
  for (const auto& p : sa)
    if (sb.count(p)) k_keep *= mesh.axis_size(p.second);
  int kg = std::max(1, ka / k_keep);  // group size that must communicate
  if (b_in_a) {
    // all-gather: each chip ends with B/kb bytes, (1 - kb/ka) arriving remotely
    double out_bytes = global_bytes / kb;
    double frac = 1.0 - static_cast<double>(kb) / ka;
    return m.ici_latency * (kg - 1) + out_bytes * frac / m.ring_bw();
  }
  // mixed: all-to-all within the communicating group
  double per_chip = std::max(global_bytes / ka, global_bytes / kb);
  return m.ici_latency + per_chip * (kg - 1) / kg / m.ring_bw();
}

// ---- choice enumeration ---------------------------------------------------

namespace detail {

inline bool div_ok(int64_t size, int k) { return k > 0 && size % k == 0; }

// Spec for "shard sample dim 0 on data" given shape; kRep everywhere else.
inline Spec dp_spec(const Shape& shp, int dp) {
  Spec s = rep_spec(shp.size());
  if (!shp.empty() && dp > 1 && div_ok(shp[0], dp)) s[0] = kData;
  return s;
}

inline double pbytes(const Node& n) { return (double)n.param_bytes(); }

// Index of the Seq-role dim in output 0 (-1 if none).
inline int seq_dim_of(const Node& n) {
  if (n.roles.empty()) return -1;
  for (size_t d = 0; d < n.roles[0].size(); ++d)
    if (n.roles[0][d] == Role::Seq) return static_cast<int>(d);
  return -1;
}

// Total per-device parameter bytes under a choice's param shardings.
inline double sharded_param_bytes(const Node& n, const Choice& c,
                                  const MeshShape& mesh) {
  double b = 0;
  for (const auto& kv : n.params) {
    auto it = c.param.find(kv.first);
    int k = it != c.param.end() ? shards_of(it->second, mesh) : 1;
    b += (double)shape_elems(kv.second) * n.dtype_size / k;
  }
  return b;
}

// Tiny-batch weight movement — ONE rule for every row-parallel
// contraction (Linear, Conv2D, anything whose kernel shards the
// contraction dim): with at most one MXU tile edge (128) of output rows
// per data shard and an output smaller than its weight, GSPMD resolves
// the contraction by moving the WEIGHT — all-gather of the model-sharded
// kernel forward (once), all-reduce of the weight gradient backward
// (searched XDL emitted 7x the priced bytes before this term existed,
// fflint FFL202 / ROADMAP). At real batch sizes the term self-gates off.
// Mirrored exactly by analysis/dataflow.weight_movement_edges — the
// static edge rule and this priced term must agree or the census-parity
// test (tests/test_dataflow.py) fails.
inline void tiny_batch_weight_movement(Choice& c, const Node& n,
                                       double rows, int eff_dp) {
  if (rows > 0 && eff_dp > 0 && rows / eff_dp <= 128.0 &&
      (double)n.output_bytes(0) < pbytes(n)) {
    c.wgather_bytes += pbytes(n);
    c.bwd_psum_bytes += pbytes(n);
  }
}

}  // namespace detail

// Enumerate the legal sharding choices of `n` on mesh (dp, mp).
// `enable_pp` gates parameter/attribute parallelism
// (--enable-parameter-parallel, reference model.cc:3612); `enable_sp2`
// gates the 2-D sample partition (--enable-sample-parallel, config.h:134).
inline std::vector<Choice> enumerate_choices(const Node& n, const MeshShape& mesh,
                                             bool enable_pp,
                                             bool enable_sp2 = true,
                                             bool enable_wus = false,
                                             bool enable_ovl = false,
                                             bool enable_kernels = false,
                                             bool training = true,
                                             bool enable_remat = false) {
  using detail::div_ok;
  using detail::dp_spec;
  const int dp = mesh.dp, mp = mesh.mp;
  std::vector<Choice> out;
  const Shape& oshp = n.output_shapes.empty() ? Shape{} : n.output_shapes[0];
  const size_t orank = oshp.size();
  int64_t batch = orank ? oshp[0] : 0;
  bool sample0 = !n.roles.empty() && !n.roles[0].empty() &&
                 n.roles[0][0] == Role::Sample;

  auto base_choice = [&](const std::string& name) {
    Choice c;
    c.name = name;
    for (const auto& s : n.output_shapes) c.out.push_back(rep_spec(s.size()));
    for (const auto& s : n.input_shapes) c.in.push_back(rep_spec(s.size()));
    for (const auto& kv : n.params) c.param[kv.first] = rep_spec(kv.second.size());
    return c;
  };

  // choice 0: fully replicated — always legal
  out.push_back(base_choice("rep"));

  bool dp_legal = sample0 && dp > 1 && div_ok(batch, dp);
  auto make_dp = [&]() {
    Choice c = base_choice("dp");
    for (size_t i = 0; i < n.output_shapes.size(); ++i)
      c.out[i] = dp_spec(n.output_shapes[i], dp);
    for (size_t i = 0; i < n.input_shapes.size(); ++i) {
      // shard inputs that carry the same batch extent on dim 0
      const Shape& is = n.input_shapes[i];
      if (!is.empty() && is[0] == batch) c.in[i] = dp_spec(is, dp);
    }
    c.work_div = dp;
    c.gradsync_bytes = detail::pbytes(n);
    c.gradsync_k = dp;
    return c;
  };
  if (dp_legal) out.push_back(make_dp());

  // 2-D sample partition: batch over data x model jointly. Worth it for
  // ops whose params are replicated (their gradient ring widens to
  // dp*mp, but the work divides by dp*mp instead of dp while the model
  // axis would otherwise idle through this op).
  if (enable_sp2 && sample0 && mesh.mp > 1 && dp > 0 &&
      detail::div_ok(batch, (int64_t)dp * mesh.mp)) {
    Choice c = base_choice("sample2");
    for (size_t i = 0; i < n.output_shapes.size(); ++i) {
      const Shape& os = n.output_shapes[i];
      if (!os.empty() && os[0] == batch) c.out[i][0] = kDataModel;
    }
    for (size_t i = 0; i < n.input_shapes.size(); ++i) {
      const Shape& is = n.input_shapes[i];
      if (!is.empty() && is[0] == batch) c.in[i][0] = kDataModel;
    }
    c.work_div = (double)dp * mesh.mp;
    c.gradsync_bytes = detail::pbytes(n);
    c.gradsync_k = dp * mesh.mp;
    out.push_back(std::move(c));
  }

  const bool pp = enable_pp && mp > 1;
  const std::string& t = n.type;

  if (t == "LINEAR" && pp) {
    auto kit = n.params.find("kernel");
    if (kit != n.params.end() && kit->second.size() == 2) {
      int64_t in_dim = kit->second[0], out_dim = kit->second[1];
      int eff_dp = dp_legal ? dp : 1;
      double in_bytes = n.input_shapes.empty()
          ? 0.0 : (double)shape_elems(n.input_shapes[0]) * n.dtype_size;
      if (div_ok(out_dim, mp)) {  // column parallel: Partition(out)+Combine
        Choice c = dp_legal ? make_dp() : base_choice("col");
        c.name = dp_legal ? "dp_col" : "col";
        c.param["kernel"] = {kRep, kModel};
        if (c.param.count("bias")) c.param["bias"] = {kModel};
        c.out[0].back() = kModel;
        c.work_div = static_cast<double>(eff_dp) * mp;
        c.gradsync_bytes = detail::pbytes(n) / mp;
        c.gradsync_k = eff_dp;
        // backward dX contracts over the model-sharded out dim: per-chip
        // partials all-reduce (the Megatron pairing — col pays in bwd
        // what row pays in fwd). Was unpriced; fflint FFL202 caught
        // searched strategies emitting ARs the DP never costed (PR 3).
        c.bwd_psum_bytes = in_bytes / eff_dp;
        c.psum_k = mp;
        out.push_back(std::move(c));
      }
      if (div_ok(in_dim, mp)) {  // row parallel: Replicate+Reduction (psum)
        Choice c = dp_legal ? make_dp() : base_choice("row");
        c.name = dp_legal ? "dp_row" : "row";
        c.param["kernel"] = {kModel, kRep};
        c.in[0].back() = kModel;
        // output stays unsharded on model: psum of partials
        c.psum_bytes = (double)n.output_bytes(0) / eff_dp;
        c.psum_k = mp;
        c.work_div = static_cast<double>(eff_dp) * mp;
        c.gradsync_bytes = detail::pbytes(n) / mp;
        c.gradsync_k = eff_dp;
        // Rows = all output dims but the last (a [B,S,E] Linear runs
        // B*S MXU rows, not B).
        double rows = oshp.empty()
            ? 0.0 : (double)shape_elems(oshp) / oshp.back();
        detail::tiny_batch_weight_movement(c, n, rows, eff_dp);
        out.push_back(std::move(c));
      }
    }
  } else if (t == "EMBEDDING" && pp) {
    auto kit = n.params.find("kernel");
    if (kit != n.params.end() && kit->second.size() == 2) {
      int64_t vocab = kit->second[0], edim = kit->second[1];
      int eff_dp = dp_legal ? dp : 1;
      if (div_ok(edim, mp)) {
        Choice c = dp_legal ? make_dp() : base_choice("col");
        c.name = dp_legal ? "dp_col" : "col";
        c.param["kernel"] = {kRep, kModel};
        c.out[0].back() = kModel;
        c.work_div = static_cast<double>(eff_dp) * mp;
        c.gradsync_bytes = detail::pbytes(n) / mp;
        c.gradsync_k = eff_dp;
        out.push_back(std::move(c));
      }
      if (div_ok(vocab, mp)) {  // vocab-sharded: masked lookup + psum
        Choice c = dp_legal ? make_dp() : base_choice("row");
        c.name = dp_legal ? "dp_row" : "row";
        c.param["kernel"] = {kModel, kRep};
        c.psum_bytes = (double)n.output_bytes(0) / eff_dp;
        c.psum_k = mp;
        c.work_div = static_cast<double>(eff_dp) * mp;
        // XLA cannot keep the dkernel scatter vocab-sharded (the update
        // rows are index-dependent): the gradient materializes replicated
        // and all-reduces the FULL table over the model axis, and the
        // data ring then carries full table bytes too — the ~7x
        // underpricing fflint FFL202 flagged on searched XDL (ROADMAP).
        c.bwd_psum_bytes = detail::pbytes(n);
        c.gradsync_bytes = detail::pbytes(n);
        c.gradsync_k = eff_dp;
        out.push_back(std::move(c));
      }
    }
  } else if (t == "CONV2D" && pp && n.attrs.get("groups").as_int(1) == 1) {
    auto kit = n.params.find("kernel");  // OIHW
    if (kit != n.params.end() && kit->second.size() == 4) {
      int64_t oc = kit->second[0], ic = kit->second[1];
      int eff_dp = dp_legal ? dp : 1;
      double in_bytes = n.input_shapes.empty()
          ? 0.0 : (double)shape_elems(n.input_shapes[0]) * n.dtype_size;
      if (div_ok(oc, mp)) {
        Choice c = dp_legal ? make_dp() : base_choice("col");
        c.name = dp_legal ? "dp_col" : "col";
        c.param["kernel"] = {kModel, kRep, kRep, kRep};
        if (c.param.count("bias")) c.param["bias"] = {kModel};
        if (c.out[0].size() == 4) c.out[0][1] = kModel;  // NCHW channel
        c.work_div = static_cast<double>(eff_dp) * mp;
        c.gradsync_bytes = detail::pbytes(n) / mp;
        c.gradsync_k = eff_dp;
        // backward dX contracts over the channel-sharded out dim —
        // same unpriced AR as the col-parallel Linear (FFL202, PR 3)
        c.bwd_psum_bytes = in_bytes / eff_dp;
        c.psum_k = mp;
        out.push_back(std::move(c));
      }
      if (div_ok(ic, mp)) {
        Choice c = dp_legal ? make_dp() : base_choice("row");
        c.name = dp_legal ? "dp_row" : "row";
        c.param["kernel"] = {kRep, kModel, kRep, kRep};
        if (c.in[0].size() == 4) c.in[0][1] = kModel;
        c.psum_bytes = (double)n.output_bytes(0) / eff_dp;
        c.psum_k = mp;
        c.work_div = static_cast<double>(eff_dp) * mp;
        c.gradsync_bytes = detail::pbytes(n) / mp;
        c.gradsync_k = eff_dp;
        // Conv MXU rows = N*H*W of the output (channel is the
        // contraction's free dim).
        double rows = n.output_shapes[0].size() == 4
            ? (double)(n.output_shapes[0][0] * n.output_shapes[0][2] *
                       n.output_shapes[0][3])
            : (double)batch;
        detail::tiny_batch_weight_movement(c, n, rows, eff_dp);
        out.push_back(std::move(c));
      }
    }
  } else if (t == "MULTIHEAD_ATTENTION" && pp) {
    int64_t heads = n.attrs.get("num_heads").as_int(0);
    int64_t kv_heads = n.attrs.get("num_kv_heads").as_int(heads);
    if (kv_heads <= 0) kv_heads = heads;
    if (heads > 0 && div_ok(heads, mp)) {
      // attribute parallelism: shard the head axis of every weight whose
      // dim 0 == num_heads (wq [H,E,D], wo [H,D,E]) — the reference's
      // create_partition_attention_combine (substitution.cc:1764). Under
      // GQA (attention.cc:214 head-count split) wk/wv carry num_kv_heads
      // on dim 0: shard them too when kv_heads divides mp; otherwise they
      // stay replicated and their gradient ring spans ALL dp*mp chips —
      // priced separately so the search sees the true GQA cost.
      int eff_dp = dp_legal ? dp : 1;
      Choice c = dp_legal ? make_dp() : base_choice("head");
      c.name = dp_legal ? "dp_head" : "head";
      bool any = false;
      bool kv_sharded = div_ok(kv_heads, mp);
      double sharded_bytes = 0.0, replicated_bytes = 0.0;
      for (const auto& kv : n.params) {
        int64_t dim0 = kv.second.empty() ? 0 : kv.second[0];
        double bytes = (double)shape_elems(kv.second) * n.dtype_size;
        if (dim0 == heads || (dim0 == kv_heads && kv_sharded)) {
          Spec s = rep_spec(kv.second.size());
          s[0] = kModel;
          c.param[kv.first] = s;
          sharded_bytes += bytes;
          any = true;
        } else {
          replicated_bytes += bytes;
        }
      }
      if (any) {
        c.psum_bytes = (double)n.output_bytes(0) / eff_dp;  // output proj psum
        c.psum_k = mp;
        c.work_div = static_cast<double>(eff_dp) * mp;
        // head-sharded params ring over dp; replicated (kv) params ring
        // over every chip — fold both into one equivalent-bytes ring
        // (a ring of k chips moves ~2B/bw per chip regardless of k, so
        // payload, not ring size, dominates)
        if (eff_dp > 1) {
          c.gradsync_bytes = sharded_bytes / mp + replicated_bytes;
          c.gradsync_k = eff_dp;
        } else if (replicated_bytes > 0) {
          // pure TP: replicated kv grads still allreduce over mp
          c.gradsync_bytes = replicated_bytes;
          c.gradsync_k = mp;
        }
        out.push_back(std::move(c));
      }
    }
  } else if (t == "REPARTITION" || t == "COMBINE" || t == "REPLICATE" ||
             t == "REDUCTION") {
    // Explicit PCG constraint boundaries (ops/parallel_ops.py): price the
    // collective each boundary forces, so the substitution engine's
    // moves/eliminations of these nodes change the searched cost. The
    // degree must equal the mesh axis extent to be realizable (the Python
    // strategy applier enforces the same for Repartition). A Repartition
    // may NAME its mesh axis (repartition(axis=...), serialized as
    // mesh_axis) — cost the axis the executor will actually use.
    int64_t dim = n.attrs.get("dim").as_int(0);
    int64_t deg = n.attrs.get("degree").as_int(1);
    int8_t ax = axis_from_name(n.attrs.get("mesh_axis").as_string(), dim);
    if (deg > 1 && mesh.axis_size(ax) == deg && orank > 0 &&
        dim < (int64_t)orank) {
      out.clear();
      Choice c = base_choice("constrain");
      if (t == "REPARTITION") {
        c.out[0][dim] = ax;        // output constrained sharded on dim
        c.in[0] = c.out[0];        // producer pays the reshard at the edge
      } else if (t == "COMBINE") {
        c.in[0][dim] = ax;         // consumes the sharded layout...
        c.gather_bytes = (double)n.output_bytes(0);  // ...and gathers it
        c.gather_k = (int)deg;
        c.gather_axis = ax;
      } else if (t == "REDUCTION") {
        c.psum_bytes = (double)n.output_bytes(0);
        c.psum_k = (int)deg;
        c.psum_axis = ax;
      }
      // REPLICATE: in/out replicated — the reshard from a sharded producer
      // is the broadcast cost, charged on the input edge
      out.push_back(std::move(c));
    }
  } else if (t == "FUSED_PARALLEL") {
    // fuse_parallel_ops result (substitution.cc:1925 analog): the whole
    // chain is ONE boundary — compose the steps into the final layout and
    // charge a single reshard at the producer edge (vs the two separate
    // collectives the unfused pair priced — the reason fusing wins)
    const Json& steps = n.attrs.get("ops");
    if (!steps.is_null() && orank > 0) {
      Spec sp_ = rep_spec(orank);
      bool legal = true;
      for (const Json& st_ : steps.items()) {
        std::string kind = st_[0].as_string();
        int64_t dim = st_[1].as_int(0);
        int64_t deg = st_[2].as_int(1);
        // optional 4th element: the step's mesh-axis name
        int8_t ax = axis_from_name(
            st_.items().size() > 3 ? st_[3].as_string() : std::string(),
            dim);
        if (kind == "REPARTITION") {
          if (dim < 0 || dim >= (int64_t)orank ||
              mesh.axis_size(ax) != deg || oshp[dim] % deg) {
            legal = false;
            break;
          }
          sp_[dim] = ax;
        } else if (kind == "COMBINE") {
          if (dim < 0 || dim >= (int64_t)orank ||
              mesh.axis_size(ax) != deg) {
            legal = false;
            break;
          }
          sp_[dim] = kRep;
        } else if (kind == "REPLICATE") {
          sp_ = rep_spec(orank);
        } else {
          legal = false;
          break;
        }
      }
      if (legal) {
        out.clear();
        Choice c = base_choice("fused_constrain");
        c.out[0] = sp_;
        c.in[0] = sp_;  // one reshard, charged at the producer edge
        out.push_back(std::move(c));
      }
    }
  } else if (t == "EXPERTS" && mesh.ep > 1) {
    // expert parallelism: the stacked expert weights [E, ...] shard over
    // the 'expert' mesh axis; token dispatch/combine is the
    // reduce-scatter + all-gather exchange of parallel/expert.py (cost ~ an
    // all-reduce of the [E, C, D] grouped activations). This is the SPMD
    // form of the reference's per-expert device placement (moe.cc:65-83).
    int64_t experts = n.attrs.get("n_experts").as_int(0);
    int ep = mesh.ep;
    if (experts > 0 && div_ok(experts, ep)) {
      const size_t base_count = out.size();
      for (size_t bi = 0; bi < base_count; ++bi) {
        Choice c = out[bi];
        int eff_dp = (!c.out[0].empty() && c.out[0][0] == kData) ? dp : 1;
        // the runtime shards tokens over data x expert jointly
        // (parallel/expert.py falls back to the dense path otherwise) —
        // don't offer a plan the executor would refuse
        if (!div_ok(batch, (int64_t)eff_dp * ep)) continue;
        c.name += "_ep";
        for (auto& kv : c.param)
          if (!kv.second.empty() && kv.second[0] == kRep)
            kv.second[0] = kExpert;
        c.work_div *= ep;
        // grouped activations [E, C, D] (f32) cross the expert axis twice
        // (reduce-scatter in, all-gather out) ~= one all-reduce
        double alpha_cap = n.attrs.get("alpha").as_double(2.0);
        double kk = (double)n.attrs.get("k").as_int(1);
        int64_t b_tokens = orank ? oshp[0] : 1;
        int64_t d_model = orank ? oshp.back() : 1;
        c.psum_bytes = alpha_cap * kk * (double)b_tokens * d_model * 4.0 /
                       eff_dp;
        c.psum_k = ep;
        c.psum_axis = kExpert;
        c.gradsync_bytes = detail::pbytes(n) / ep;
        c.gradsync_k = eff_dp;
        out.push_back(std::move(c));
      }
    }
  } else if ((t.rfind("EW_", 0) == 0 || t == "RELU" || t == "GELU" ||
              t == "SIGMOID" || t == "TANH" || t == "ELU" || t == "EXP" ||
              t == "SIN" || t == "COS" || t == "POW" || t == "RSQRT" ||
              t == "IDENTITY" || t == "DROPOUT" || t == "CAST" ||
              t.rfind("SCALAR_", 0) == 0) && pp && orank >= 2 &&
             div_ok(oshp.back(), mp)) {
    // follow-style ops can also carry a model-sharded last dim so a
    // col-parallel producer's layout flows through without a gather
    Choice c = dp_legal ? make_dp() : base_choice("mp_last");
    c.name = dp_legal ? "dp_mp_last" : "mp_last";
    c.out[0].back() = kModel;
    for (size_t i = 0; i < n.input_shapes.size(); ++i) {
      const Shape& is = n.input_shapes[i];
      if (!is.empty() && is.back() == oshp.back()) c.in[i].back() = kModel;
    }
    c.work_div = static_cast<double>(dp_legal ? dp : 1) * mp;
    out.push_back(std::move(c));
  }

  // ---- sequence/context parallelism over the 'seq' axis ------------------
  // New scope vs the reference (SURVEY §5.7): attention becomes ring
  // attention (K/V rotate on the ICI ring via ppermute,
  // flexflow_tpu/parallel/ring_attention.py); seq-batchlike ops simply
  // carry the seq-sharded layout, dividing their work like an extra batch
  // axis. Every base choice spawns a seq-extended variant so hybrid
  // dp x mp x sp strategies compose.
  const int sp = mesh.sp;
  int sd = detail::seq_dim_of(n);
  if (sp > 1 && sd >= 0 && sd < (int)orank && div_ok(oshp[sd], sp)) {
    const int64_t seq_extent = oshp[sd];
    // an op that marks a Seq role declares that dim position-independent
    // (shardable); attention additionally needs the ring rewrite and only
    // supports it for self-attention (equal q/k/v sequence extents)
    bool is_attn = t == "MULTIHEAD_ATTENTION";
    bool self_attn = true;
    for (const Shape& is : n.input_shapes)
      if ((int)is.size() <= sd || is[sd] != seq_extent) self_attn = false;
    if (!is_attn || self_attn) {
      const size_t base_count = out.size();
      for (size_t bi = 0; bi < base_count; ++bi) {
        Choice c = out[bi];
        if ((int)c.out[0].size() <= sd || c.out[0][sd] != kRep) continue;
        c.name += is_attn ? "_ring" : "_sp";
        for (size_t i = 0; i < n.output_shapes.size(); ++i) {
          const Shape& os = n.output_shapes[i];
          if ((int)os.size() > sd && os[sd] == seq_extent &&
              c.out[i][sd] == kRep)
            c.out[i][sd] = kSeq;
        }
        for (size_t i = 0; i < n.input_shapes.size(); ++i) {
          const Shape& is = n.input_shapes[i];
          if ((int)is.size() > sd && is[sd] == seq_extent &&
              c.in[i][sd] == kRep)
            c.in[i][sd] = kSeq;
        }
        c.work_div *= sp;
        // row-parallel partial sums shrink with the seq-sharded output
        if (c.psum_bytes > 0) c.psum_bytes /= sp;
        if (c.bwd_psum_bytes > 0) c.bwd_psum_bytes /= sp;
        if (is_attn) {
          // K/V rotation cost: each device sends its projected K+V block
          // (sp-1) times around the seq ring. Block bytes = global K+V
          // (~2x the [B,S,E] output) over all sharding of B/H/S.
          int eff_dp = (!c.out[0].empty() && c.out[0][0] == kData) ? dp : 1;
          auto wk = c.param.find("wk");
          int eff_mp = (wk != c.param.end() && !wk->second.empty() &&
                        wk->second[0] == kModel) ? mesh.mp : 1;
          double kv_global = 2.0 * (double)n.output_bytes(0);
          c.ring_bytes = kv_global / ((double)eff_dp * eff_mp * sp) * (sp - 1);
          c.ring_k = sp;
        }
        // weights are replicated over the seq axis: their gradients reduce
        // over seq as well as data
        if (!n.params.empty() && n.param_bytes() > 0) {
          if (c.gradsync_bytes > 0) {
            c.gradsync_k *= sp;
          } else {
            c.gradsync_bytes = detail::sharded_param_bytes(n, c, mesh);
            c.gradsync_k = sp;
          }
        }
        out.push_back(std::move(c));
      }
    }
  }

  // ---- weight-update sharding (WUS) variants ------------------------------
  // Every choice that carries a data-ring gradient sync spawns a "_wus"
  // twin: the sync prices as reduce-scatter + all-gather instead of an
  // all-reduce, and the optimizer state (+ f32 master) shards over the
  // ring — node_param_memory and the simulator's update-traffic term
  // divide by gradsync_k. The DP weighs both forms per mesh, so WUS is a
  // searched strategy dimension, not a global toggle (ISSUE 4).
  // Twins only exist on meshes with a data ring: the executor shards the
  // master/optimizer state over the DATA axes, so a pure-TP mesh (dp=1)
  // has no shard dimension for WUS to use.
  if (enable_wus && mesh.dp > 1) {
    const size_t base_count = out.size();
    for (size_t bi = 0; bi < base_count; ++bi) {
      const Choice& b = out[bi];
      if (b.gradsync_bytes <= 0 || b.gradsync_k <= 1) continue;
      Choice c = b;
      c.name += "_wus";
      c.wus = true;
      out.push_back(std::move(c));
    }
  }

  // ---- comms-compute overlap ("_ovl") variants ----------------------------
  // Every "_wus" choice spawns an "_ovl" twin: the gradient sync issues
  // as bucketed async collectives structured so XLA hides them under
  // remaining backward compute, and the DP prices only the un-hidden
  // tail plus per-bucket launch overhead (ISSUE 9). The twin can WIN at
  // higher byte counts than a low-byte sync choice — latency hiding is
  // a searched dimension, not an executor flag. Only WUS parents spawn
  // twins because the runtime's bucket chaining rides on the WUS
  // reduce-scatter shard constraints (executor._chain_constrained) —
  // pricing hiding the executor cannot deliver would misrank strategies.
  if (enable_ovl) {
    const size_t base_count = out.size();
    for (size_t bi = 0; bi < base_count; ++bi) {
      const Choice& b = out[bi];
      if (!b.wus) continue;
      if (b.gradsync_bytes <= 0 || b.gradsync_k <= 1) continue;
      Choice c = b;
      c.name += "_ovl";
      c.ovl = true;
      out.push_back(std::move(c));
    }
  }

  // ---- kernel-implementation ("_k:<impl>") variants ------------------------
  // Runs LAST so the kernel suffix composes with every sharding/"_wus"/
  // "_ovl" twin already enumerated (canonical base[_wus][_ovl][_k:impl]).
  // Each twin is a different LOWERING of the same sharded computation:
  // identical specs and collectives, different compute/update pricing
  // (node_cost's per-impl chain). Legality gates fire here; their named
  // reasons are re-derived into the search trace by per_op_trace.
  if (enable_kernels) {
    const size_t base_count = out.size();
    for (size_t bi = 0; bi < base_count; ++bi) {
      // by VALUE: the push_backs below may reallocate `out`, and a
      // reference into it would dangle across the checks that follow
      const Choice b = out[bi];
      // flash attention: streams K/V through VMEM per Q block — no
      // materialized [B,H,S,S] score tensor in HBM. Not on "_ring"
      // parents: ring attention IS its own kernel (impl "ring").
      if (t == "MULTIHEAD_ATTENTION" &&
          b.name.find("_ring") == std::string::npos &&
          kernel_gate(n, "flash", training).empty()) {
        Choice c = b;
        c.name += "_k:flash";
        c.kernel = "flash";
        out.push_back(std::move(c));
      }
      // train-time Conv+BN fused region (the eval fold's legality,
      // shipped as the bn_fusable attr, reused at train time)
      if (t == "CONV2D" && training &&
          kernel_gate(n, "conv_bn_fused").empty()) {
        Choice c = b;
        c.name += "_k:conv_bn_fused";
        c.kernel = "conv_bn_fused";
        out.push_back(std::move(c));
      }
      // fused optimizer update: the WUS RS -> triad -> AG chain
      // collapses from three dispatches to one fused region. Attention
      // keeps its "_k:" dimension for the attention core.
      if (training && b.wus && t != "MULTIHEAD_ATTENTION" &&
          kernel_gate(n, "fused").empty()) {
        Choice c = b;
        c.name += "_k:fused";
        c.kernel = "fused";
        out.push_back(std::move(c));
      }
    }
  }

  // ---- rematerialization ("_r") variants ----------------------------------
  // Runs after the kernel block so "_r" is the final suffix of the
  // canonical lattice base[_wus][_ovl][_k:impl][_r] and the recompute
  // prices the actual lowering (a flash parent's "_r" twin recomputes
  // the flash forward). Legality gates (remat_gate) fire here; their
  // named reasons are re-derived into the search trace by per_op_trace.
  if (enable_remat && training) {
    const size_t base_count = out.size();
    for (size_t bi = 0; bi < base_count; ++bi) {
      // by VALUE: the push_backs below may reallocate `out`
      const Choice b = out[bi];
      if (!remat_gate(n, b, training).empty()) continue;
      Choice c = b;
      c.name += "_r";
      c.remat = true;
      out.push_back(std::move(c));
    }
  }
  return out;
}

// ---- per-node cost given a choice ----------------------------------------

struct NodeCost {
  double fwd = 0, bwd = 0, comm = 0, gradsync = 0;
  // comm seconds the "_ovl" pricing treated as hidden under compute
  // (informational — never part of total(); the simtrace hidden lanes
  // and the search trace's overlap column read it)
  double gradsync_hidden = 0;
  // bucket size (MB) the "_ovl" sweep committed to, 0 for non-ovl
  // choices — the per-op searched value "--overlap-bucket-mb auto"
  // follows (byte-weighted across the winning assignment)
  double ovl_bucket_mb = 0;
  int ovl_buckets = 0;
  // which model priced fwd/bwd (SRC_ANALYTIC / SRC_LEARNED /
  // SRC_MEASURED) — recorded per candidate in the search trace and per
  // node in the simulate response so every priced number is traceable
  // to its source
  int8_t src = SRC_ANALYTIC;
  double total() const { return fwd + bwd + comm + gradsync; }
};

// The learned model's feature vector for (node, choice) — MUST mirror
// flexflow_tpu/costmodel/corpus.py featurize() (see ffs_machine.hpp).
inline void learned_features(const Node& n, const Choice& c,
                             double (&f)[kLearnedFeatures]) {
  double div = std::max(1.0, c.work_div);
  f[0] = std::log1p(n.fwd_flops / div);
  f[1] = std::log1p((double)n.total_io_bytes() / div);
  f[2] = std::log1p((double)n.param_bytes());
  f[3] = std::log(div);
}

// Learned per-chip (fwd, bwd) compute seconds for (node, choice):
// false when no table is loaded, the op class is below the coverage
// gate (absent from the table), or the query falls outside the trained
// feature hull — callers then keep the analytic roofline. Shared by
// node_cost and the search trace's learned-vs-analytic columns.
inline bool learned_compute(const Node& n, const Choice& c,
                            const MachineModel& m, double* fwd,
                            double* bwd, bool* matched_impl = nullptr) {
  if (matched_impl != nullptr) *matched_impl = false;
  if (m.learned.empty()) return false;
  double f[kLearnedFeatures];
  learned_features(n, c, f);
  // compute-kernel twins prefer their per-impl class ("TYPE:impl",
  // trained on per-impl corpus rows); base class is the fallback —
  // `matched_impl` reports which matched, so node_cost knows whether
  // the analytic per-impl delta still applies on top
  if (!c.kernel.empty() && c.kernel != "fused" &&
      m.learned_predict(n.type + ":" + c.kernel, f, fwd, bwd)) {
    if (matched_impl != nullptr) *matched_impl = true;
    return true;
  }
  return m.learned_predict(n.type, f, fwd, bwd);
}

// Optimizer update-triad HBM time of (node, choice): read p + read g +
// write p (3x the shard's param bytes) + read+write per optimizer-state
// copy; WUS divides by the gradient ring. The "_k:fused" kernel twin
// collapses the RS-epilogue / per-leaf update kernels / AG-prologue
// chain into ONE fused region: the separate update kernels' re-read of
// p between dispatches disappears (3 -> 2 param round trips) and two of
// the three dispatch launches are saved. Shared by node_cost's hide
// window and its final update term so both price the same triad.
inline double update_triad_time(const Node& n, const Choice& c,
                                const MeshShape& mesh, const MachineModel& m,
                                double opt_state_factor) {
  if (opt_state_factor < 0 || n.param_bytes() <= 0) return 0.0;
  double copies = (c.kernel == "fused") ? 2.0 : 3.0;
  double upd = detail::sharded_param_bytes(n, c, mesh) *
               (copies + 2.0 * opt_state_factor) / m.hbm_bw;
  if (c.wus && c.gradsync_k > 1) upd /= c.gradsync_k;
  if (c.kernel == "fused")
    upd = std::max(0.0, upd - 2.0 * m.collective_launch_overhead);
  return upd;
}

// Per-node forward/backward time. When a measured-cost table is supplied
// (real-chip microbenchmarks, the analog of the reference's
// measure_operator_cost cache, src/runtime/model.cu:38-74 +
// simulator.h:750-752), entries "<guid>:fwd" / "<guid>:bwd" override the
// analytic roofline; sharded work scales as measured/work_div. Backward is
// measured separately — not assumed 2x forward — when the profiler provides
// it.
// `opt_state_factor >= 0` additionally folds the optimizer update-triad
// time (read p/g, write p, + 2x per state copy, HBM-bound) into
// nc.gradsync — for the frontier DP only, which otherwise cannot see the
// per-chip update traffic a WUS choice divides by the gradient ring. The
// taskgraph simulator prices its own global update task and passes the
// default (-1) here.
inline NodeCost node_cost(const Node& n, const Choice& c, const MeshShape& mesh,
                          const MachineModel& m, bool training,
                          const MeasuredCosts* measured = nullptr,
                          double opt_state_factor = -1.0) {
  NodeCost nc;
  if (is_view_op(n.type)) return nc;  // fused away by XLA: free
  double div = std::max(1.0, c.work_div);
  // kernel twins that change the COMPUTE lowering (flash,
  // conv_bn_fused; "fused" only moves the update term): their measured
  // rows are keyed "<guid>:fwd:<impl>" and their learned class
  // "<TYPE>:<impl>" — the base rows/class time the DEFAULT lowering and
  // must not silently price a different kernel
  const bool compute_impl = !c.kernel.empty() && c.kernel != "fused";
  const double* mfwd = nullptr;
  const double* mbwd = nullptr;
  if (measured != nullptr) {
    const std::string kf = std::to_string(n.guid) + ":fwd" +
                           (compute_impl ? ":" + c.kernel : std::string());
    const std::string kb = std::to_string(n.guid) + ":bwd" +
                           (compute_impl ? ":" + c.kernel : std::string());
    auto itf = measured->find(kf);
    if (itf != measured->end()) mfwd = &itf->second;
    auto itb = measured->find(kb);
    if (itb != measured->end()) mbwd = &itb->second;
  }
  double flop = n.fwd_flops / div;
  double bytes = (double)n.total_io_bytes() / div;
  // shape-aware MXU efficiency for matmul-carrying ops: derive (M,N,K)
  // from the node's shapes, then shrink the dim the CHOICE shards —
  // a col-parallel Linear runs an N/mp-wide matmul per chip, a
  // dp-sharded one an M/dp-tall one. Measured costs override all this.
  double eff = -1.0;
  if (n.type == "LINEAR" || n.type == "CONV2D") {
    // per-chip (M, N, K) from the choice's STRUCTURED per-dim axis
    // assignments (not its name, which would rot as choices grow): each
    // sharded dim divides by its mesh-axis extent
    auto dim_shards = [&](const std::vector<Spec>& specs, size_t ti,
                          size_t di) -> double {
      if (ti >= specs.size() || di >= specs[ti].size()) return 1.0;
      int8_t e = specs[ti][di];
      return e >= 0 ? (double)mesh.axis_size(e) : 1.0;
    };
    double M = 0, N = 0, K = 0;
    if (n.type == "LINEAR" && !n.input_shapes.empty() &&
        !n.input_shapes[0].empty() && !n.output_shapes.empty()) {
      const Shape& is = n.input_shapes[0];
      const Shape& os = n.output_shapes[0];
      K = (double)is.back() / dim_shards(c.in, 0, is.size() - 1);
      M = 1;
      for (size_t i = 0; i + 1 < os.size(); ++i)
        M *= (double)os[i] / dim_shards(c.out, 0, i);
      N = (double)os.back() / dim_shards(c.out, 0, os.size() - 1);
    } else if (n.type == "CONV2D") {
      auto kit = n.params.find("kernel");  // OIHW
      if (kit != n.params.end() && kit->second.size() == 4 &&
          !n.output_shapes.empty() && n.output_shapes[0].size() == 4) {
        const Shape& os = n.output_shapes[0];
        N = (double)kit->second[0] / dim_shards(c.out, 0, 1);
        K = (double)(kit->second[1] * kit->second[2] * kit->second[3]) /
            dim_shards(c.in, 0, 1);
        M = (double)os[0] / dim_shards(c.out, 0, 0) *
            (double)(os[2] * os[3]);
      }
    }
    // conv-class asymptote: measured conv MFU sits far below matmul MFU
    // even channels-last (per-op-class calibration, ffs_machine.hpp)
    double asym = (n.type == "CONV2D") ? m.conv_efficiency
                                       : m.mxu_efficiency;
    if (M > 0 && N > 0 && K > 0)
      eff = m.matmul_efficiency(M, N, K, asym);
    else if (n.type == "CONV2D")
      eff = m.conv_efficiency;  // geometry unavailable: flat conv class
  }
  // pricing priority: measured per-op profile > learned regression >
  // analytic roofline. The learned model predicts per-chip SHARDED
  // seconds directly (its targets were measured/work_div and work_div
  // is a feature), so no further division applies. Kernel twins prefer
  // a per-impl learned class; absent one, the DEFAULT lowering's price
  // (base learned or analytic) gets the impl's analytic HBM-traffic
  // delta applied below.
  double lfwd = 0, lbwd = 0;
  bool learned_is_impl = false;
  bool has_learned =
      mfwd == nullptr &&
      learned_compute(n, c, m, &lfwd, &lbwd, &learned_is_impl);
  if (mfwd != nullptr) {
    nc.fwd = std::max(*mfwd / div, m.min_op_time);
    nc.src = SRC_MEASURED;
  } else if (has_learned) {
    nc.fwd = std::max(lfwd, m.min_op_time);
    nc.src = SRC_LEARNED;
  } else {
    nc.fwd = m.compute_time(flop, bytes, n.dtype_size, eff);
  }
  if (training) {
    if (mbwd != nullptr)
      nc.bwd = std::max(*mbwd / div, m.min_op_time);
    else if (has_learned)
      nc.bwd = std::max(lbwd, m.min_op_time);
    else
      nc.bwd = 2.0 * nc.fwd;  // dX + dW passes
  }
  if (compute_impl && mfwd == nullptr && !learned_is_impl) {
    // analytic per-impl delta on the default lowering's price, floored
    // at the pure flop bound (the impl removes HBM traffic, not math)
    double asym = eff > 0 ? eff : m.mxu_efficiency;
    double peak = m.flops * asym * (n.dtype_size <= 2 ? 1.0 : 0.5);
    double floor_f = flop / peak + m.min_op_time;
    double floor_b = 2.0 * flop / peak + m.min_op_time;
    if (c.kernel == "flash") {
      // HBM-traffic model vs the materialized-scores einsum: the
      // default lowering round-trips the f32 [B,H,S,S] probability
      // tensor (write+read fwd; recomputed probs + dP write+read bwd)
      // — flash keeps scores in VMEM. The calibrated einsum price
      // implicitly contains that traffic; subtract it.
      int64_t heads = n.attrs.get("num_heads").as_int(1);
      const Shape& os = n.output_shapes[0];
      double score_b = (double)os[0] * heads * (double)os[1] *
                       (double)os[1] * 4.0 / div;
      nc.fwd = std::max(nc.fwd - 2.0 * score_b / m.hbm_bw, floor_f);
      if (training)
        nc.bwd = std::max(nc.bwd - 4.0 * score_b / m.hbm_bw, floor_b);
    } else if (c.kernel == "conv_bn_fused") {
      // fused Conv+BN region: the conv output's write + the BN's read
      // of it never round-trip HBM, and one dispatch is saved
      int k_out = c.out.empty() ? 1 : shards_of(c.out[0], mesh);
      double bnd = 2.0 * (double)n.output_bytes(0) / k_out / m.hbm_bw;
      nc.fwd = std::max(nc.fwd - bnd - m.min_op_time, floor_f);
      if (training)
        nc.bwd = std::max(nc.bwd - bnd, floor_b);
    }
  }
  if (training && c.remat)
    // rematerialization: the backward pass first re-runs this op's
    // forward from its checkpointed inputs. Applied after the per-impl
    // delta so the recompute prices the chosen lowering; nc.src stays
    // whatever priced fwd (cost_source provenance intact).
    nc.bwd += nc.fwd;
  if (c.psum_bytes > 0 && c.psum_k > 1) {
    double t = m.allreduce_time(c.psum_bytes, c.psum_k, c.psum_axis);
    nc.comm = training ? 2.0 * t : t;  // bwd mirrors the collective
  }
  if (training && c.bwd_psum_bytes > 0 && c.psum_k > 1)
    // backward-only partial-sum all-reduce (col-parallel dX, replicated
    // scatter gradients, tiny-batch weight-grad movement)
    nc.comm += m.allreduce_time(c.bwd_psum_bytes, c.psum_k, c.psum_axis);
  if (c.wgather_bytes > 0 && c.psum_k > 1)
    // forward-only weight all-gather (tiny-batch row lowering) — charged
    // once; its backward counterpart is the bwd_psum weight-grad AR
    nc.comm += m.allgather_time(c.wgather_bytes, c.psum_k, c.psum_axis);
  if (c.ring_bytes > 0 && c.ring_k > 1) {
    // ring attention K/V rotation; the backward rotates K/V and dK/dV
    double t = m.ring_time(c.ring_bytes, c.ring_k, kSeq);
    nc.comm += training ? 3.0 * t : t;
  }
  if (c.gather_bytes > 0 && c.gather_k > 1) {
    double t = m.allgather_time(c.gather_bytes, c.gather_k, c.gather_axis);
    nc.comm += training ? 2.0 * t : t;  // bwd scatters the gradient back
  }
  if (training && c.gradsync_bytes > 0 && c.gradsync_k > 1) {
    int spans = slices_spanned(mesh, m);
    double sync;
    if (c.wus)
      // WUS: reduce-scatter the gradients, update shard-locally, then
      // all-gather the updated (bf16) compute params — roughly the
      // all-reduce's wire bytes, but the optimizer update and its state
      // shrink by gradsync_k (node_param_memory / the simulator's
      // update-traffic term), which is where WUS wins.
      sync = m.wus_rs_time(c.gradsync_bytes, c.gradsync_k, spans, kData) +
             m.wus_ag_time(c.gradsync_bytes, c.gradsync_k, spans, kData);
    else
      sync = m.hier_allreduce_time(c.gradsync_bytes, c.gradsync_k, spans,
                                   kData);
    if (c.ovl) {
      // latency hiding: the bucketed async sync hides under the overlap
      // window the DP already prices for this op — its backward compute
      // (early buckets' collectives ride under the rest of backward)
      // plus, when the update-triad term is being priced, the optimizer
      // fusion tail the WUS param all-gather prefetches under.
      double hide = nc.bwd +
                    update_triad_time(n, c, mesh, m, opt_state_factor);
      OverlapPricing ov = overlap_price(
          m, sync, c.gradsync_bytes * m.comm_bytes_factor, hide);
      nc.gradsync = ov.exposed;
      nc.gradsync_hidden = ov.hidden;
      nc.ovl_bucket_mb = ov.bucket_mb;
      nc.ovl_buckets = ov.buckets;
    } else {
      nc.gradsync = sync;
    }
  }
  if (training)
    nc.gradsync += update_triad_time(n, c, mesh, m, opt_state_factor);
  return nc;
}

// Per-device parameter (+optimizer-state) bytes of a node under a choice —
// permanent for the whole iteration.
inline double node_param_memory(const Node& n, const Choice& c,
                                const MeshShape& mesh,
                                double opt_state_factor) {
  if (is_view_op(n.type)) return 0.0;
  double factor = 1.0 + opt_state_factor;
  if (c.wus && c.gradsync_k > 1)
    // weight-update sharding: the optimizer moments (and the f32 master
    // they update) shard over the gradient ring; only the compute-param
    // copy stays replicated
    factor = 1.0 + opt_state_factor / c.gradsync_k;
  return detail::sharded_param_bytes(n, c, mesh) * factor;
}

// Per-device activation bytes a node's outputs occupy while live.
inline double node_act_bytes(const Node& n, const Choice& c,
                             const MeshShape& mesh) {
  if (is_view_op(n.type)) return 0.0;  // fused away: materializes nothing
  if (c.remat) return 0.0;  // "_r": the output is not a saved residual —
                            // backward rebuilds it from the checkpointed
                            // inputs (counted at their producers)
  double mem = 0;
  for (size_t i = 0; i < n.output_shapes.size(); ++i) {
    int k = i < c.out.size() ? shards_of(c.out[i], mesh) : 1;
    mem += (double)n.output_bytes(i) / k;
  }
  return mem;
}

// Per-device memory of a node under a choice: sharded params (+optimizer
// state) + sharded activations. Under training every activation is a
// saved-for-backward residual, so the whole-graph sum IS the backward-start
// peak; inference uses the liveness-aware accounting in the DP/simulator
// instead (reference bump-allocator role, simulator.h:699-700).
inline double node_memory(const Node& n, const Choice& c, const MeshShape& mesh,
                          double opt_state_factor) {
  return node_param_memory(n, c, mesh, opt_state_factor) +
         node_act_bytes(n, c, mesh);
}

}  // namespace ffsearch

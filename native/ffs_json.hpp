// Minimal JSON value + parser + writer for the ffsearch core.
//
// The reference vendors nlohmann/json (deps/json) for substitution-rule
// loading (src/runtime/substitution_loader.cc); this is a self-contained
// ~300-line replacement covering the subset ffsearch needs: objects,
// arrays, strings (with escapes), doubles, bools, null. Numbers are held
// as double (graph sizes / byte counts fit in 53 bits).
#pragma once

#include <cmath>
#include <cstdint>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace ffsearch {

class Json;
using JsonArray = std::vector<Json>;
using JsonObject = std::map<std::string, Json>;

class Json {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Json() : type_(Type::Null) {}
  Json(std::nullptr_t) : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(double d) : type_(Type::Number), num_(d) {}
  Json(int i) : type_(Type::Number), num_(i) {}
  Json(int64_t i) : type_(Type::Number), num_(static_cast<double>(i)) {}
  Json(size_t i) : type_(Type::Number), num_(static_cast<double>(i)) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Json(JsonArray a) : type_(Type::Array), arr_(std::move(a)) {}
  Json(JsonObject o) : type_(Type::Object), obj_(std::move(o)) {}

  static Json array() { return Json(JsonArray{}); }
  static Json object() { return Json(JsonObject{}); }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_number() const { return type_ == Type::Number; }
  bool is_string() const { return type_ == Type::String; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_object() const { return type_ == Type::Object; }

  bool as_bool(bool dflt = false) const {
    return type_ == Type::Bool ? bool_ : dflt;
  }
  double as_double(double dflt = 0.0) const {
    return type_ == Type::Number ? num_ : dflt;
  }
  int64_t as_int(int64_t dflt = 0) const {
    return type_ == Type::Number ? static_cast<int64_t>(std::llround(num_)) : dflt;
  }
  const std::string& as_string() const {
    static const std::string empty;
    return type_ == Type::String ? str_ : empty;
  }
  const JsonArray& items() const {
    static const JsonArray empty;
    return type_ == Type::Array ? arr_ : empty;
  }
  const JsonObject& fields() const {
    static const JsonObject empty;
    return type_ == Type::Object ? obj_ : empty;
  }
  JsonArray& items_mut() { return arr_; }
  JsonObject& fields_mut() { return obj_; }

  // object access: get(key) returns Null json when missing
  const Json& get(const std::string& key) const {
    static const Json null_json;
    if (type_ != Type::Object) return null_json;
    auto it = obj_.find(key);
    return it == obj_.end() ? null_json : it->second;
  }
  bool has(const std::string& key) const {
    return type_ == Type::Object && obj_.count(key) > 0;
  }
  void set(const std::string& key, Json v) {
    type_ = Type::Object;
    obj_[key] = std::move(v);
  }
  void push_back(Json v) {
    type_ = Type::Array;
    arr_.push_back(std::move(v));
  }
  size_t size() const {
    if (type_ == Type::Array) return arr_.size();
    if (type_ == Type::Object) return obj_.size();
    return 0;
  }
  const Json& operator[](size_t i) const { return arr_.at(i); }

  // ---- parse ----
  static Json parse(const std::string& text) {
    Parser p(text);
    Json v = p.parse_value();
    p.skip_ws();
    if (!p.at_end()) throw std::runtime_error("json: trailing characters");
    return v;
  }

  // ---- serialize ----
  std::string dump() const {
    std::ostringstream os;
    write(os);
    return os.str();
  }

 private:
  struct Parser {
    const std::string& s;
    size_t i = 0;
    explicit Parser(const std::string& text) : s(text) {}
    bool at_end() const { return i >= s.size(); }
    void skip_ws() {
      while (i < s.size() && (s[i] == ' ' || s[i] == '\t' || s[i] == '\n' || s[i] == '\r'))
        ++i;
    }
    char peek() {
      if (at_end()) throw std::runtime_error("json: unexpected end");
      return s[i];
    }
    char next() {
      char c = peek();
      ++i;
      return c;
    }
    void expect(char c) {
      if (next() != c) throw std::runtime_error(std::string("json: expected ") + c);
    }
    Json parse_value() {
      skip_ws();
      char c = peek();
      if (c == '{') return parse_object();
      if (c == '[') return parse_array();
      if (c == '"') return Json(parse_string());
      if (c == 't') { literal("true"); return Json(true); }
      if (c == 'f') { literal("false"); return Json(false); }
      if (c == 'n') { literal("null"); return Json(); }
      return parse_number();
    }
    void literal(const char* lit) {
      for (const char* p = lit; *p; ++p) expect(*p);
    }
    Json parse_object() {
      expect('{');
      JsonObject obj;
      skip_ws();
      if (peek() == '}') { ++i; return Json(std::move(obj)); }
      while (true) {
        skip_ws();
        std::string key = parse_string();
        skip_ws();
        expect(':');
        obj[key] = parse_value();
        skip_ws();
        char c = next();
        if (c == '}') break;
        if (c != ',') throw std::runtime_error("json: expected , or }");
      }
      return Json(std::move(obj));
    }
    Json parse_array() {
      expect('[');
      JsonArray arr;
      skip_ws();
      if (peek() == ']') { ++i; return Json(std::move(arr)); }
      while (true) {
        arr.push_back(parse_value());
        skip_ws();
        char c = next();
        if (c == ']') break;
        if (c != ',') throw std::runtime_error("json: expected , or ]");
      }
      return Json(std::move(arr));
    }
    std::string parse_string() {
      expect('"');
      std::string out;
      while (true) {
        char c = next();
        if (c == '"') break;
        if (c == '\\') {
          char e = next();
          switch (e) {
            case 'n': out += '\n'; break;
            case 't': out += '\t'; break;
            case 'r': out += '\r'; break;
            case 'b': out += '\b'; break;
            case 'f': out += '\f'; break;
            case '/': out += '/'; break;
            case '\\': out += '\\'; break;
            case '"': out += '"'; break;
            case 'u': {  // \uXXXX — keep BMP only, encode UTF-8
              unsigned cp = 0;
              for (int k = 0; k < 4; ++k) {
                char h = next();
                cp <<= 4;
                if (h >= '0' && h <= '9') cp |= h - '0';
                else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
                else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
                else throw std::runtime_error("json: bad \\u escape");
              }
              if (cp < 0x80) out += static_cast<char>(cp);
              else if (cp < 0x800) {
                out += static_cast<char>(0xC0 | (cp >> 6));
                out += static_cast<char>(0x80 | (cp & 0x3F));
              } else {
                out += static_cast<char>(0xE0 | (cp >> 12));
                out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
                out += static_cast<char>(0x80 | (cp & 0x3F));
              }
              break;
            }
            default: throw std::runtime_error("json: bad escape");
          }
        } else {
          out += c;
        }
      }
      return out;
    }
    Json parse_number() {
      size_t start = i;
      if (peek() == '-') ++i;
      while (!at_end() && (isdigit(s[i]) || s[i] == '.' || s[i] == 'e' ||
                           s[i] == 'E' || s[i] == '+' || s[i] == '-'))
        ++i;
      return Json(std::stod(s.substr(start, i - start)));
    }
  };

  void write(std::ostringstream& os) const {
    switch (type_) {
      case Type::Null: os << "null"; break;
      case Type::Bool: os << (bool_ ? "true" : "false"); break;
      case Type::Number: {
        if (std::isfinite(num_) && num_ == std::floor(num_) &&
            std::fabs(num_) < 9.0e15) {
          os << static_cast<int64_t>(num_);
        } else {
          os.precision(17);
          os << num_;
        }
        break;
      }
      case Type::String: write_string(os, str_); break;
      case Type::Array: {
        os << '[';
        for (size_t k = 0; k < arr_.size(); ++k) {
          if (k) os << ',';
          arr_[k].write(os);
        }
        os << ']';
        break;
      }
      case Type::Object: {
        os << '{';
        bool first = true;
        for (const auto& kv : obj_) {
          if (!first) os << ',';
          first = false;
          write_string(os, kv.first);
          os << ':';
          kv.second.write(os);
        }
        os << '}';
        break;
      }
    }
  }
  static void write_string(std::ostringstream& os, const std::string& s) {
    os << '"';
    for (char c : s) {
      switch (c) {
        case '"': os << "\\\""; break;
        case '\\': os << "\\\\"; break;
        case '\n': os << "\\n"; break;
        case '\t': os << "\\t"; break;
        case '\r': os << "\\r"; break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            os << buf;
          } else {
            os << c;
          }
      }
    }
    os << '"';
  }

  Type type_;
  bool bool_ = false;
  double num_ = 0.0;
  std::string str_;
  JsonArray arr_;
  JsonObject obj_;
};

}  // namespace ffsearch

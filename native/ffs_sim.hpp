// Event-driven taskgraph simulator.
//
// Analog of the reference's Simulator::simulate_runtime
// (src/runtime/simulator.cc:822-900): build a SimTask DAG for one training
// iteration — forward per op, backward per op (reverse order), resharding
// collectives on edges, partial-sum collectives, per-parameter gradient
// all-reduce, optimizer update — then list-schedule it on two streams per
// chip (compute, ICI) reflecting how XLA overlaps async collectives with
// compute. SPMD symmetry means one chip's schedule is the iteration time.
//
// The reference's `search_overlap_backward_update` flag (config.h:130)
// maps to `overlap`: when false, gradient all-reduces wait for the whole
// backward pass (no overlap), as in its default Legion schedule.
#pragma once

#include <algorithm>
#include <cstdint>
#include <map>
#include <set>
#include <string>
#include <vector>

#include "ffs_graph.hpp"
#include "ffs_machine.hpp"
#include "ffs_strategy.hpp"

namespace ffsearch {

struct SimTask {
  enum class Kind { Fwd, Bwd, Comm, GradSync, Update };
  Kind kind;
  int node_idx = -1;  // -1 for Update
  double duration = 0;
  std::vector<int> deps;  // indices into task vector
  // collective detail (Comm/GradSync): what the cost charges, so the
  // priced set can be diffed against the collectives XLA actually emits
  // (SURVEY §7 hard-part 3; tests/test_collective_validation.py)
  std::string collective;  // "allreduce"|"allgather"|"ppermute"|"reshard"|""
  double bytes = 0;        // global payload bytes priced
  // filled by the scheduler:
  double start = 0, finish = 0;
  // seconds of this comm-stream task that ran while the compute stream
  // was busy — the predicted-hidden interval the simtrace sim: lanes
  // surface (filled by the post-schedule pass; 0 for compute tasks)
  double hidden = 0;
};

struct SimResult {
  double iteration_time = 0;
  double fwd_time = 0, bwd_time = 0, comm_time = 0, gradsync_time = 0;
  // total comm/gradsync seconds hidden under compute in the schedule
  // (plus the pipeline/"_ovl" analytic hidden terms) — the predicted
  // twin of the devtrace's measured overlapped_comms_s
  double hidden_comm_time = 0;
  double memory = 0;  // per-device bytes
  std::vector<SimTask> tasks;  // schedule (for --taskgraph export)
};

class TaskgraphSimulator {
 public:
  TaskgraphSimulator(const Graph& g, const MachineModel& m, const MeshShape& mesh,
                     bool training = true, bool overlap = true,
                     double opt_state_factor = 2.0,
                     const MeasuredCosts* measured = nullptr)
      : g_(g), m_(m), mesh_(mesh), training_(training), overlap_(overlap),
        opt_state_factor_(opt_state_factor), measured_(measured) {}

  // `assign[i]` = chosen Choice for g_.nodes[i].
  SimResult simulate(const std::vector<Choice>& assign) const {
    const size_t N = g_.nodes.size();
    std::vector<SimTask> tasks;
    std::vector<int> fwd_id(N, -1), bwd_id(N, -1);
    auto add = [&](SimTask t) {
      tasks.push_back(std::move(t));
      return static_cast<int>(tasks.size()) - 1;
    };

    SimResult res;
    // liveness accounting (inference): an activation frees at its last
    // consumer; track the peak instead of the sum (reference
    // bump-allocator role, simulator.h:699-700). Training keeps the sum:
    // every activation is a saved-for-backward residual.
    std::map<std::pair<int64_t, int>, size_t> last_use;
    if (!training_)
      for (size_t i = 0; i < N; ++i)
        for (const EdgeRef& e : g_.nodes[i].inputs)
          if (e.src_guid >= 0) last_use[{e.src_guid, e.src_idx}] = i;
    double act_live = 0, act_peak = 0;
    // ---- forward + edge reshard tasks ----
    for (size_t i = 0; i < N; ++i) {
      const Node& n = g_.nodes[i];
      const Choice& c = assign[i];
      NodeCost nc = node_cost(n, c, mesh_, m_, training_, measured_);
      std::vector<int> deps;
      for (size_t slot = 0; slot < n.inputs.size(); ++slot) {
        const EdgeRef& e = n.inputs[slot];
        if (e.src_guid < 0) continue;
        int pi = g_.index_of.at(e.src_guid);
        const Choice& pc = assign[pi];
        const Spec& prod = pc.out[e.src_idx];
        const Spec& need = slot < c.in.size() ? c.in[slot]
                                              : rep_spec(prod.size());
        double rb = reshard_cost(prod, need,
                                 (double)g_.nodes[pi].output_bytes(e.src_idx),
                                 mesh_, m_);
        if (rb > 0) {
          SimTask ct{SimTask::Kind::Comm, (int)i, rb, {fwd_id[pi]},
                     "reshard",
                     (double)g_.nodes[pi].output_bytes(e.src_idx)};
          deps.push_back(add(std::move(ct)));
          res.comm_time += rb;
        } else {
          deps.push_back(fwd_id[pi]);
        }
      }
      SimTask ft{SimTask::Kind::Fwd, (int)i, nc.fwd, deps, "", 0};
      fwd_id[i] = add(std::move(ft));
      res.fwd_time += nc.fwd;
      if (c.psum_bytes > 0 && c.psum_k > 1) {
        double t = m_.allreduce_time(c.psum_bytes, c.psum_k, c.psum_axis);
        SimTask ct{SimTask::Kind::Comm, (int)i, t, {fwd_id[i]},
                   "allreduce", c.psum_bytes};
        fwd_id[i] = add(std::move(ct));  // consumers wait on the psum
        res.comm_time += t;
      }
      if (c.ring_bytes > 0 && c.ring_k > 1) {
        // ring-attention K/V rotation (seq axis): runs on the ICI stream
        double t = m_.ring_time(c.ring_bytes, c.ring_k, kSeq);
        SimTask ct{SimTask::Kind::Comm, (int)i, t, {fwd_id[i]},
                   "ppermute", c.ring_bytes};
        fwd_id[i] = add(std::move(ct));
        res.comm_time += t;
      }
      if (c.gather_bytes > 0 && c.gather_k > 1) {
        // all-gather a Combine boundary forces
        double t = m_.allgather_time(c.gather_bytes, c.gather_k, c.gather_axis);
        SimTask ct{SimTask::Kind::Comm, (int)i, t, {fwd_id[i]},
                   "allgather", c.gather_bytes};
        fwd_id[i] = add(std::move(ct));
        res.comm_time += t;
      }
      if (c.wgather_bytes > 0 && c.psum_k > 1) {
        // tiny-batch row lowering: the kernel all-gathers once forward
        double t = m_.allgather_time(c.wgather_bytes, c.psum_k, c.psum_axis);
        SimTask ct{SimTask::Kind::Comm, (int)i, t, {fwd_id[i]},
                   "allgather", c.wgather_bytes};
        fwd_id[i] = add(std::move(ct));
        res.comm_time += t;
      }
      res.memory += node_param_memory(n, c, mesh_, opt_state_factor_);
      if (training_) {
        res.memory += node_act_bytes(n, c, mesh_);
      } else {
        act_live += node_act_bytes(n, c, mesh_);
        act_peak = std::max(act_peak, act_live);
        // inputs whose last consumer is this node free now. A view op
        // aliases its input, so consumption through a view conservatively
        // never frees (overcounts slightly rather than undercounting).
        for (const EdgeRef& e : is_view_op(n.type)
                                    ? std::vector<EdgeRef>{} : n.inputs) {
          if (e.src_guid < 0) continue;
          auto lu = last_use.find({e.src_guid, e.src_idx});
          if (lu != last_use.end() && lu->second == i) {
            int pi = g_.index_of.at(e.src_guid);
            const Choice& pc = assign[pi];
            int k = e.src_idx < (int)pc.out.size()
                        ? shards_of(pc.out[e.src_idx], mesh_) : 1;
            act_live -=
                (double)g_.nodes[pi].output_bytes(e.src_idx) / k;
            last_use.erase(lu);  // free once even with multi-input reuse
          }
        }
      }
    }
    if (!training_) res.memory += act_peak;

    if (training_) {
      // ---- backward (reverse topo): bwd_i after bwd of all consumers ----
      for (int i = static_cast<int>(N) - 1; i >= 0; --i) {
        const Node& n = g_.nodes[i];
        const Choice& c = assign[i];
        NodeCost nc = node_cost(n, c, mesh_, m_, true, measured_);
        std::vector<int> deps = {fwd_id[i]};
        auto it = g_.consumers.find(n.guid);
        if (it != g_.consumers.end())
          for (const auto& cons : it->second)
            if (bwd_id[cons.first] >= 0) deps.push_back(bwd_id[cons.first]);
        double bwd_comm_bytes = 0;
        double dur = nc.bwd;
        if (c.psum_k > 1 && c.psum_bytes > 0) {
          dur += m_.allreduce_time(c.psum_bytes, c.psum_k, c.psum_axis);
          bwd_comm_bytes += c.psum_bytes;
        }
        if (c.psum_k > 1 && c.bwd_psum_bytes > 0) {
          // backward-only partial-sum AR (col-parallel dX, replicated
          // scatter grads, tiny-batch weight-grad movement)
          dur += m_.allreduce_time(c.bwd_psum_bytes, c.psum_k, c.psum_axis);
          bwd_comm_bytes += c.bwd_psum_bytes;
        }
        if (c.ring_bytes > 0 && c.ring_k > 1)  // bwd rotates K/V and dK/dV
          dur += 2.0 * m_.ring_time(c.ring_bytes, c.ring_k, kSeq);
        SimTask bt{SimTask::Kind::Bwd, i, dur, deps,
                   bwd_comm_bytes > 0 ? "allreduce" : "", bwd_comm_bytes};
        bwd_id[i] = add(std::move(bt));
        res.bwd_time += dur;
      }
      // ---- per-parameter gradient sync + optimizer update ----
      std::vector<int> sync_ids;
      int last_bwd = N > 0 ? bwd_id[0] : -1;
      // reverse node order = backward-completion order: the scheduler
      // below assigns the comm stream in task-creation order, and a real
      // runtime fires each parameter's all-reduce the moment its backward
      // finishes (head layers first) — creation order must match or the
      // simulated syncs all queue behind the one that is ready last
      int spans = slices_spanned(mesh_, m_);
      for (size_t j = 0; j < N; ++j) {
        size_t i = N - 1 - j;
        const Choice& c = assign[i];
        if (c.gradsync_bytes > 0 && c.gradsync_k > 1) {
          std::vector<int> deps = {bwd_id[i]};
          // "_ovl": the executor issues this op's sync as bucketed async
          // collectives the moment its grads exist — never serialized
          // behind the whole backward, even under the no-overlap default
          // schedule. The per-bucket launch overhead is charged on the
          // task (hiding is not free); the hiding itself emerges from
          // the two-stream list schedule and is reported by the
          // post-schedule hidden pass below.
          if (!c.ovl && !overlap_ && last_bwd >= 0)
            deps.push_back(last_bwd);
          double wire = c.gradsync_bytes * m_.comm_bytes_factor;
          double bwd_dur = tasks[bwd_id[i]].duration;
          if (c.wus) {
            // WUS: reduce-scatter the gradients (the RS half keeps the
            // census 'allreduce' bucket — XLA's AR decomposition), then
            // all-gather the updated compute params. Priced as two
            // tasks so the collective census diff sees both kinds.
            double t1 = m_.wus_rs_time(c.gradsync_bytes, c.gradsync_k,
                                       spans, kData);
            double t2 = m_.wus_ag_time(c.gradsync_bytes, c.gradsync_k,
                                       spans, kData);
            if (c.ovl)
              t1 += overlap_price(m_, t1 + t2, wire, bwd_dur).buckets *
                    m_.collective_launch_overhead;
            SimTask rs{SimTask::Kind::GradSync, (int)i, t1, deps,
                       "allreduce", c.gradsync_bytes};
            int rs_id = add(std::move(rs));
            SimTask ag{SimTask::Kind::GradSync, (int)i, t2, {rs_id},
                       "allgather", c.gradsync_bytes};
            sync_ids.push_back(add(std::move(ag)));
            res.gradsync_time += t1 + t2;
          } else {
            double t = m_.hier_allreduce_time(c.gradsync_bytes,
                                              c.gradsync_k, spans, kData);
            if (c.ovl)
              t += overlap_price(m_, t, wire, bwd_dur).buckets *
                   m_.collective_launch_overhead;
            SimTask st{SimTask::Kind::GradSync, (int)i, t, deps,
                       "allreduce", c.gradsync_bytes};
            sync_ids.push_back(add(std::move(st)));
            res.gradsync_time += t;
          }
        }
      }
      // optimizer update traffic: read p + read g + write p (3x params)
      // plus read+write of each optimizer-state copy (2x per copy;
      // opt_state_factor = state copies: 0 plain SGD, 1 momentum, 2 Adam).
      // Bandwidth: the measured update-triad rate when profiled
      // ("__update_bw__" — elementwise updates run well below the
      // datasheet HBM figure), else the analytic hbm_bw.
      double upd_bw = m_.hbm_bw;
      if (measured_) {
        auto it = measured_->find("__update_bw__");
        if (it != measured_->end() && it->second > 0) upd_bw = it->second;
      }
      double upd_bytes = 0, upd_saved = 0;
      for (size_t i = 0; i < N; ++i) {
        // WUS: the update triad runs on the per-chip shard only —
        // optimizer HBM traffic divides by the gradient-ring size.
        // "_k:fused" choices price the one-dispatch fused region: one
        // param round trip fewer and two launches saved, CAPPED at the
        // node's own update time (mirrors update_triad_time's per-node
        // floor, ffs_strategy.hpp — a tiny fused op must not let its
        // launch saving eat into other ops' update traffic, or the
        // replay would price fused cheaper than the DP did).
        const Choice& c = assign[i];
        double div = (c.wus && c.gradsync_k > 1) ? (double)c.gradsync_k
                                                 : 1.0;
        double copies = (c.kernel == "fused") ? 2.0 : 3.0;
        double nb = (double)g_.nodes[i].param_bytes() *
                    (copies + 2.0 * opt_state_factor_) / div;
        upd_bytes += nb;
        if (c.kernel == "fused" && g_.nodes[i].param_bytes() > 0)
          upd_saved += std::min(2.0 * m_.collective_launch_overhead,
                                nb / upd_bw);
      }
      std::vector<int> deps = sync_ids;
      if (last_bwd >= 0) deps.push_back(last_bwd);
      SimTask ut{SimTask::Kind::Update, -1,
                 std::max(0.0, upd_bytes / upd_bw - upd_saved), deps, "",
                 0};
      add(std::move(ut));
    }

    // ---- list schedule on {compute, comm} streams ----
    double compute_free = 0, comm_free = 0, makespan = 0;
    for (auto& t : tasks) {
      double ready = 0;
      for (int d : t.deps)
        if (d >= 0) ready = std::max(ready, tasks[d].finish);
      bool on_comm = t.kind == SimTask::Kind::Comm ||
                     t.kind == SimTask::Kind::GradSync;
      double& stream = on_comm ? comm_free : compute_free;
      t.start = std::max(ready, stream);
      t.finish = t.start + t.duration;
      stream = t.finish;
      makespan = std::max(makespan, t.finish);
    }
    // post-schedule hidden pass: seconds of each comm-stream task that
    // ran while the compute stream was busy — the predicted hidden
    // intervals (compute tasks are sequential on one stream, so their
    // [start, finish) spans are disjoint and sorted)
    {
      std::vector<std::pair<double, double>> busy;
      for (const auto& t : tasks)
        if (t.kind != SimTask::Kind::Comm &&
            t.kind != SimTask::Kind::GradSync && t.duration > 0)
          busy.push_back({t.start, t.finish});
      size_t lo = 0;
      for (auto& t : tasks) {
        if (t.kind != SimTask::Kind::Comm &&
            t.kind != SimTask::Kind::GradSync)
          continue;
        double h = 0;
        while (lo < busy.size() && busy[lo].second <= t.start) ++lo;
        for (size_t b = lo; b < busy.size() && busy[b].first < t.finish;
             ++b)
          h += std::max(0.0, std::min(t.finish, busy[b].second) -
                                 std::max(t.start, busy[b].first));
        t.hidden = h;
        res.hidden_comm_time += h;
      }
    }
    res.iteration_time = makespan;
    if (measured_) {
      // fixed per-step dispatch/runtime cost measured on the live device
      // (program launch + host runtime; large on tunneled devices)
      auto it = measured_->find("__step_overhead__");
      if (it != measured_->end()) res.iteration_time += it->second;
    }
    res.tasks = std::move(tasks);
    return res;
  }

 private:
  const Graph& g_;
  const MachineModel& m_;
  MeshShape mesh_;
  bool training_;
  bool overlap_;
  double opt_state_factor_;
  const MeasuredCosts* measured_;
};

// ---- GPipe pipeline simulation (pp > 1 meshes) ----------------------------

// Repeated-block metadata detected by the Python side
// (flexflow_tpu/parallel/pipeline_detect.py) and shipped in the request.
struct PipelineMeta {
  bool present = false;
  int num_blocks = 0;
  std::set<int64_t> body, head, tail;
  double block_out_bytes = 0;
  int64_t batch = 0;
};

// Iteration time of the graph run as a pp-stage pipeline with M
// microbatches, per-node inner choices `assign` (computed by the frontier
// DP on the inner dp-only mesh). Model (parallel/pipeline.py semantics):
//   * `circular=false` (GPipe): stages hold k = num_blocks/pp consecutive
//     blocks and run all of them per tick; T = M + pp - 1 ticks (bubble
//     (pp-1)/T). `circular=true`: blocks assign round-robin, one block
//     per tick, each microbatch circulates k rounds; T = kM + pp - 1
//     ticks (bubble (pp-1)/(kM+pp-1)) — the schedule is a PRICED
//     dimension, as are M (swept over the divisor lattice of batch/dp by
//     the caller) and the per-op "_wus" gradient-sync twins;
//   * each tick ppermutes the microbatch activation one hop (bwd: the
//     returning gradient too); the sharded microbatch queue
//     (`shard_queue`, the runtime default when pp | M) adds two
//     single-microbatch ppermute streams per tick plus pp-1 drain hops;
//   * head/tail ops run outside the pipeline on the full batch;
//   * stage weights shard 1/pp: gradient sync, optimizer update and
//     parameter memory divide by pp; a body/head choice with `wus` prices
//     its sync as reduce-scatter + all-gather with the update triad and
//     optimizer-state memory divided by the gradient ring;
//   * queue memory: 2x the body boundary tensor over dp, divided by pp
//     when the queue is sharded; the circular schedule adds a stage-0
//     recirculation buffer (one boundary tensor over dp).
// `res.tasks` carries zero-duration census records (collective, bytes) so
// strategy replays (ffs_simulate) can diff priced vs inferred/emitted
// collectives on pipe meshes too.
// `body_remat` prices block-body rematerialization (the pipeline face of
// the "_r" dimension, ISSUE 20): the stage checkpoints each block
// instance's boundary input and recomputes the block interior in
// backward — backward ticks gain one forward tick of recompute, and the
// body residual term shrinks from every interior activation to the
// per-block boundaries (~1/block-depth). Swept as a candidate dimension
// by eval_graph alongside M and the schedule.
inline SimResult simulate_pipeline(const Graph& g, const MachineModel& m,
                                   const MeshShape& mesh,
                                   const std::vector<Choice>& assign,
                                   const PipelineMeta& meta, bool training,
                                   double opt_state_factor,
                                   const MeasuredCosts* measured, int M,
                                   bool circular = false,
                                   bool shard_queue = true,
                                   bool body_remat = false) {
  SimResult res;
  const int pp = mesh.pp;
  const int k = pp > 0 ? std::max(1, meta.num_blocks / pp) : 1;
  const int rounds = circular ? k : 1;
  const bool qshard = shard_queue && pp > 0 && M % pp == 0;
  double fwd_body = 0, bwd_body = 0, fwd_edge = 0;
  double body_act = 0, body_param_mem = 0;
  // body gradient-sync bytes, split by (wus, ovl): the "_ovl" groups
  // price only the un-hidden tail of their sync (the stacked body grads
  // finish with the last backward tick, so the hiding window is the
  // optimizer-fusion tail, not backward compute)
  double body_gs_plain = 0, body_gs_wus = 0;
  double body_gs_plain_ovl = 0, body_gs_wus_ovl = 0;
  int body_ops = 0;
  int gradsync_k = mesh.dp;
  double ht_time = 0, ht_param_mem = 0, ht_act = 0, ht_gradsync = 0;
  double upd_bytes = 0, upd_saved = 0;
  // update-triad bandwidth (measured override when profiled) — hoisted
  // above the node loop so the per-node fused launch-saving cap below
  // can price each node's own update time
  double upd_bw = m.hbm_bw;
  if (measured != nullptr) {
    auto it = measured->find("__update_bw__");
    if (it != measured->end() && it->second > 0) upd_bw = it->second;
  }
  MeshShape inner = mesh;
  inner.pp = 1;
  const int spans = slices_spanned(inner, m);
  const double mem_f = training ? opt_state_factor : 0.0;
  auto add_task = [&](SimTask::Kind kind, int node, double dur,
                      const char* coll, double bytes) {
    res.tasks.push_back(SimTask{kind, node, dur, {}, coll, bytes});
  };
  for (size_t i = 0; i < g.nodes.size(); ++i) {
    const Node& n = g.nodes[i];
    const Choice& c = assign[i];
    NodeCost nc = node_cost(n, c, inner, m, training, measured);
    double pmem = node_param_memory(n, c, inner, mem_f);
    double act = 0;
    for (size_t oi = 0; oi < n.output_shapes.size(); ++oi)
      act += (double)n.output_bytes(oi) /
             (oi < c.out.size() ? shards_of(c.out[oi], inner) : 1);
    const bool body = meta.body.count(n.guid) > 0;
    if (body) {
      fwd_body += nc.fwd;
      bwd_body += nc.bwd;
      fwd_edge += nc.comm;
      body_param_mem += pmem;
      body_act += act;
      if (training && c.gradsync_bytes > 0 && c.gradsync_k > 1)
        (c.ovl ? (c.wus ? body_gs_wus_ovl : body_gs_plain_ovl)
               : (c.wus ? body_gs_wus : body_gs_plain)) +=
            c.gradsync_bytes;
      if (!is_view_op(n.type)) ++body_ops;
    } else {
      ht_time += nc.fwd + nc.bwd + nc.comm;
      ht_param_mem += pmem;
      ht_act += act;
      if (training && c.gradsync_bytes > 0 && c.gradsync_k > 1) {
        double t;
        if (c.wus) {
          t = m.wus_rs_time(c.gradsync_bytes, c.gradsync_k, spans, kData) +
              m.wus_ag_time(c.gradsync_bytes, c.gradsync_k, spans, kData);
          add_task(SimTask::Kind::GradSync, (int)i, 0, "allreduce",
                   c.gradsync_bytes);
          add_task(SimTask::Kind::GradSync, (int)i, 0, "allgather",
                   c.gradsync_bytes);
        } else {
          t = m.hier_allreduce_time(c.gradsync_bytes, c.gradsync_k, spans,
                                    kData);
          add_task(SimTask::Kind::GradSync, (int)i, 0, "allreduce",
                   c.gradsync_bytes);
        }
        if (c.ovl) {
          // head/tail op outside the pipeline: its bucketed async sync
          // hides under the op's own backward compute, as in node_cost
          OverlapPricing ov = overlap_price(
              m, t, c.gradsync_bytes * m.comm_bytes_factor, nc.bwd);
          res.hidden_comm_time += ov.hidden;
          t = ov.exposed;
        }
        ht_gradsync += t;
      }
    }
    if (training && n.param_bytes() > 0) {
      // optimizer update-triad HBM traffic: stage weights already /pp;
      // WUS additionally divides by the gradient ring; "_k:fused"
      // choices price the one-dispatch fused region with the launch
      // saving capped at the node's own update time (update_triad_time)
      double div = (c.wus && c.gradsync_k > 1) ? (double)c.gradsync_k : 1.0;
      double copies = (c.kernel == "fused") ? 2.0 : 3.0;
      double nb = detail::sharded_param_bytes(n, c, inner) /
                  (body ? (double)pp : 1.0) *
                  (copies + 2.0 * opt_state_factor) / div;
      upd_bytes += nb;
      if (c.kernel == "fused")
        upd_saved += std::min(2.0 * m.collective_launch_overhead,
                              nb / upd_bw);
    }
    // per-op collective census records (durations already in nc.comm)
    double psum_total = (training ? 2.0 : 1.0) * c.psum_bytes +
                        (training ? c.bwd_psum_bytes : 0.0);
    if (psum_total > 0 && c.psum_k > 1)
      add_task(SimTask::Kind::Comm, (int)i, 0, "allreduce", psum_total);
    if (c.gather_bytes > 0 && c.gather_k > 1)
      add_task(SimTask::Kind::Comm, (int)i, 0, "allgather",
               (training ? 2.0 : 1.0) * c.gather_bytes);
    if (c.wgather_bytes > 0 && c.psum_k > 1)
      add_task(SimTask::Kind::Comm, (int)i, 0, "allgather",
               c.wgather_bytes);
    if (c.ring_bytes > 0 && c.ring_k > 1)
      add_task(SimTask::Kind::Comm, (int)i, 0, "ppermute",
               (training ? 3.0 : 1.0) * c.ring_bytes);
  }
  const double ticks = (double)rounds * M + pp - 1;
  // per-tick stage compute, floored by the per-op dispatch minimum of the
  // ops one stage executes per microbatch per tick (one block's worth
  // under the circular schedule, k blocks' worth under GPipe)
  double op_floor = (double)body_ops / (pp * rounds) * m.min_op_time;
  double tick_fwd = std::max(fwd_body / ((double)pp * rounds * M), op_floor);
  double tick_bwd = std::max(bwd_body / ((double)pp * rounds * M), op_floor);
  if (training && body_remat)
    // block-body remat: every backward tick first re-runs the block's
    // forward from its checkpointed boundary input
    tick_bwd += tick_fwd;
  // activation hop: boundary tensor / (M * dp) per microbatch shard.
  // Each tick, every stage forwards simultaneously, so the tick's hop
  // cost is the slowest hop: if the pipeline's chip range extends past
  // one slice, at least one stage boundary crosses DCN, and that hop
  // gates the tick — price all ticks' hops at DCN in that case
  // (enumerate_meshes allows pipe stages to span slices).
  double hop_bytes = meta.block_out_bytes * m.comm_bytes_factor /
                     ((double)M * mesh.dp);
  int inner_chips = mesh.dp * mesh.mp * mesh.sp * mesh.ep;
  bool spans_slices =
      m.num_slices > 1 && inner_chips * pp > m.chips_per_slice();
  double hop1 = spans_slices ? (m.dcn_latency + hop_bytes / m.dcn_bw)
                             : (m.ici_latency + hop_bytes / m.ici_bw);
  // sharded queue: the input and output streams are two more
  // single-microbatch ppermutes riding the ring every tick, plus pp-1
  // output-drain hops after the last compute tick. The streams are
  // prefetch/writeback traffic (their payload is consumed S-1 ticks
  // later), so they overlap compute and charge bandwidth only; the
  // activation hop stays on the critical path with its latency.
  double stream_bw = spans_slices ? m.dcn_bw : m.ici_bw;
  double hop = hop1 + (qshard ? 2.0 * hop_bytes / stream_bw : 0.0);
  double drain = qshard ? (pp - 1) * hop1 : 0.0;
  add_task(SimTask::Kind::Comm, -1, 0, "ppermute",
           (ticks * (qshard ? 3.0 : 1.0) + (qshard ? pp - 1 : 0)) *
               meta.block_out_bytes / ((double)M * mesh.dp) *
               (training ? 2.0 : 1.0));
  res.fwd_time = ticks * (tick_fwd + hop) + drain + fwd_edge;
  res.comm_time = ticks * hop * (training ? 2.0 : 1.0) + drain + fwd_edge;
  // fwd_edge (per-op collectives of body choices) charges iteration_time
  // too — pp>1 meshes must not be costed comm-free vs the taskgraph sim
  res.iteration_time =
      ht_time + ticks * (tick_fwd + hop) + drain + fwd_edge;
  if (training) {
    res.bwd_time = ticks * (tick_bwd + hop);
    res.iteration_time += res.bwd_time;
    double upd_time = std::max(0.0, upd_bytes / upd_bw - upd_saved);
    if (mesh.dp > 1 && body_gs_plain > 0) {
      double t = m.hier_allreduce_time(body_gs_plain / pp, gradsync_k,
                                       spans, kData);
      res.gradsync_time += t;
      add_task(SimTask::Kind::GradSync, -1, t, "allreduce",
               body_gs_plain / pp);
    }
    if (mesh.dp > 1 && body_gs_wus > 0) {
      // WUS twins under the pipeline: reduce-scatter the stage-sharded
      // body grads over the data ring, all-gather the updated compute
      // params — both on bytes/pp (the stage's stacked slice)
      double t1 = m.wus_rs_time(body_gs_wus / pp, gradsync_k, spans, kData);
      double t2 = m.wus_ag_time(body_gs_wus / pp, gradsync_k, spans, kData);
      res.gradsync_time += t1 + t2;
      add_task(SimTask::Kind::GradSync, -1, t1, "allreduce",
               body_gs_wus / pp);
      add_task(SimTask::Kind::GradSync, -1, t2, "allgather",
               body_gs_wus / pp);
    }
    if (mesh.dp > 1 && body_gs_plain_ovl + body_gs_wus_ovl > 0) {
      // "_ovl" body groups: the stacked body grads only finish with the
      // last backward tick (grad accumulation over microbatches), so
      // the hiding window is the optimizer-fusion tail — the update
      // triad the WUS param all-gather prefetches under — not backward
      // compute. Census bytes are recorded unchanged; only the priced
      // exposed time shrinks.
      double hide = upd_time;
      if (body_gs_plain_ovl > 0) {
        double t = m.hier_allreduce_time(body_gs_plain_ovl / pp,
                                         gradsync_k, spans, kData);
        OverlapPricing ov = overlap_price(
            m, t, body_gs_plain_ovl / pp * m.comm_bytes_factor, hide);
        hide = std::max(0.0, hide - ov.hidden);
        res.gradsync_time += ov.exposed;
        res.hidden_comm_time += ov.hidden;
        add_task(SimTask::Kind::GradSync, -1, ov.exposed, "allreduce",
                 body_gs_plain_ovl / pp);
      }
      if (body_gs_wus_ovl > 0) {
        double t =
            m.wus_rs_time(body_gs_wus_ovl / pp, gradsync_k, spans, kData) +
            m.wus_ag_time(body_gs_wus_ovl / pp, gradsync_k, spans, kData);
        OverlapPricing ov = overlap_price(
            m, t, body_gs_wus_ovl / pp * m.comm_bytes_factor, hide);
        res.gradsync_time += ov.exposed;
        res.hidden_comm_time += ov.hidden;
        add_task(SimTask::Kind::GradSync, -1, ov.exposed, "allreduce",
                 body_gs_wus_ovl / pp);
        add_task(SimTask::Kind::GradSync, -1, 0, "allgather",
                 body_gs_wus_ovl / pp);
      }
    }
    res.gradsync_time += ht_gradsync;
    res.iteration_time += res.gradsync_time;
    res.iteration_time += upd_time;
  }
  if (measured != nullptr) {
    auto it = measured->find("__step_overhead__");
    if (it != measured->end()) res.iteration_time += it->second;
  }
  // queue + output buffer: replicated over pipe in the fallback lowering,
  // sharded 1/pp otherwise (plus the in/out stream microbatches); the
  // circular schedule keeps a stage-0 recirculation buffer windowed to
  // the M-pp+1 in-flight slots in BOTH lowerings (a value banked at tick
  // v+pp-1 is consumed at tick v+M, so at most M-pp+1 slots are ever
  // live — parallel/pipeline.py's ring buffer, data-sharded over dp)
  double queue_mem =
      2.0 * meta.block_out_bytes / mesh.dp / (qshard ? pp : 1);
  if (rounds > 1)
    queue_mem += meta.block_out_bytes / mesh.dp * (double)(M - pp + 1) / M;
  if (qshard)
    queue_mem += 3.0 * meta.block_out_bytes / ((double)M * mesh.dp);
  double body_act_eff = body_act / pp;
  if (training && body_remat && meta.block_out_bytes > 0)
    // block-body remat residuals: k*M boundary slots of
    // block_out/(M*dp) each per stage (= k*block_out/dp), plus the one
    // block interior transiently rebuilt during the current backward
    // tick — instead of every interior activation of the stage's blocks
    body_act_eff = (double)k * meta.block_out_bytes / mesh.dp +
                   body_act / ((double)meta.num_blocks * M);
  res.memory = body_param_mem / pp + ht_param_mem +
               (training ? body_act_eff + ht_act : 0.0) + queue_mem;
  return res;
}

}  // namespace ffsearch

// Event-driven taskgraph simulator.
//
// Analog of the reference's Simulator::simulate_runtime
// (src/runtime/simulator.cc:822-900): build a SimTask DAG for one training
// iteration — forward per op, backward per op (reverse order), resharding
// collectives on edges, partial-sum collectives, per-parameter gradient
// all-reduce, optimizer update — then list-schedule it on two streams per
// chip (compute, ICI) reflecting how XLA overlaps async collectives with
// compute. SPMD symmetry means one chip's schedule is the iteration time.
//
// The reference's `search_overlap_backward_update` flag (config.h:130)
// maps to `overlap`: when false, gradient all-reduces wait for the whole
// backward pass (no overlap), as in its default Legion schedule.
#pragma once

#include <algorithm>
#include <cstdint>
#include <map>
#include <string>
#include <vector>

#include "ffs_graph.hpp"
#include "ffs_machine.hpp"
#include "ffs_strategy.hpp"

namespace ffsearch {

struct SimTask {
  enum class Kind { Fwd, Bwd, Comm, GradSync, Update };
  Kind kind;
  int node_idx = -1;  // -1 for Update
  double duration = 0;
  std::vector<int> deps;  // indices into task vector
  // filled by the scheduler:
  double start = 0, finish = 0;
};

struct SimResult {
  double iteration_time = 0;
  double fwd_time = 0, bwd_time = 0, comm_time = 0, gradsync_time = 0;
  double memory = 0;  // per-device bytes
  std::vector<SimTask> tasks;  // schedule (for --taskgraph export)
};

class TaskgraphSimulator {
 public:
  TaskgraphSimulator(const Graph& g, const MachineModel& m, const MeshShape& mesh,
                     bool training = true, bool overlap = true,
                     double opt_state_factor = 2.0,
                     const MeasuredCosts* measured = nullptr)
      : g_(g), m_(m), mesh_(mesh), training_(training), overlap_(overlap),
        opt_state_factor_(opt_state_factor), measured_(measured) {}

  // `assign[i]` = chosen Choice for g_.nodes[i].
  SimResult simulate(const std::vector<Choice>& assign) const {
    const size_t N = g_.nodes.size();
    std::vector<SimTask> tasks;
    std::vector<int> fwd_id(N, -1), bwd_id(N, -1);
    auto add = [&](SimTask t) {
      tasks.push_back(std::move(t));
      return static_cast<int>(tasks.size()) - 1;
    };

    SimResult res;
    // ---- forward + edge reshard tasks ----
    for (size_t i = 0; i < N; ++i) {
      const Node& n = g_.nodes[i];
      const Choice& c = assign[i];
      NodeCost nc = node_cost(n, c, mesh_, m_, training_, measured_);
      std::vector<int> deps;
      for (size_t slot = 0; slot < n.inputs.size(); ++slot) {
        const EdgeRef& e = n.inputs[slot];
        if (e.src_guid < 0) continue;
        int pi = g_.index_of.at(e.src_guid);
        const Choice& pc = assign[pi];
        const Spec& prod = pc.out[e.src_idx];
        const Spec& need = slot < c.in.size() ? c.in[slot]
                                              : rep_spec(prod.size());
        double rb = reshard_cost(prod, need,
                                 (double)g_.nodes[pi].output_bytes(e.src_idx),
                                 mesh_, m_);
        if (rb > 0) {
          SimTask ct{SimTask::Kind::Comm, (int)i, rb, {fwd_id[pi]}};
          deps.push_back(add(std::move(ct)));
          res.comm_time += rb;
        } else {
          deps.push_back(fwd_id[pi]);
        }
      }
      SimTask ft{SimTask::Kind::Fwd, (int)i, nc.fwd, deps};
      fwd_id[i] = add(std::move(ft));
      res.fwd_time += nc.fwd;
      if (c.psum_bytes > 0 && c.psum_k > 1) {
        double t = m_.allreduce_time(c.psum_bytes, c.psum_k);
        SimTask ct{SimTask::Kind::Comm, (int)i, t, {fwd_id[i]}};
        fwd_id[i] = add(std::move(ct));  // consumers wait on the psum
        res.comm_time += t;
      }
      if (c.ring_bytes > 0 && c.ring_k > 1) {
        // ring-attention K/V rotation (seq axis): runs on the ICI stream
        double t = m_.ring_time(c.ring_bytes, c.ring_k);
        SimTask ct{SimTask::Kind::Comm, (int)i, t, {fwd_id[i]}};
        fwd_id[i] = add(std::move(ct));
        res.comm_time += t;
      }
      if (c.gather_bytes > 0 && c.gather_k > 1) {
        // all-gather a Combine boundary forces
        double t = m_.allgather_time(c.gather_bytes, c.gather_k);
        SimTask ct{SimTask::Kind::Comm, (int)i, t, {fwd_id[i]}};
        fwd_id[i] = add(std::move(ct));
        res.comm_time += t;
      }
      res.memory += node_memory(n, c, mesh_, opt_state_factor_);
    }

    if (training_) {
      // ---- backward (reverse topo): bwd_i after bwd of all consumers ----
      for (int i = static_cast<int>(N) - 1; i >= 0; --i) {
        const Node& n = g_.nodes[i];
        const Choice& c = assign[i];
        NodeCost nc = node_cost(n, c, mesh_, m_, true, measured_);
        std::vector<int> deps = {fwd_id[i]};
        auto it = g_.consumers.find(n.guid);
        if (it != g_.consumers.end())
          for (const auto& cons : it->second)
            if (bwd_id[cons.first] >= 0) deps.push_back(bwd_id[cons.first]);
        double dur = nc.bwd + (c.psum_k > 1 && c.psum_bytes > 0
                                   ? m_.allreduce_time(c.psum_bytes, c.psum_k)
                                   : 0.0);
        if (c.ring_bytes > 0 && c.ring_k > 1)  // bwd rotates K/V and dK/dV
          dur += 2.0 * m_.ring_time(c.ring_bytes, c.ring_k);
        SimTask bt{SimTask::Kind::Bwd, i, dur, deps};
        bwd_id[i] = add(std::move(bt));
        res.bwd_time += dur;
      }
      // ---- per-parameter gradient sync + optimizer update ----
      std::vector<int> sync_ids;
      int last_bwd = N > 0 ? bwd_id[0] : -1;
      // reverse node order = backward-completion order: the scheduler
      // below assigns the comm stream in task-creation order, and a real
      // runtime fires each parameter's all-reduce the moment its backward
      // finishes (head layers first) — creation order must match or the
      // simulated syncs all queue behind the one that is ready last
      int spans = slices_spanned(mesh_, m_);
      for (size_t j = 0; j < N; ++j) {
        size_t i = N - 1 - j;
        const Choice& c = assign[i];
        if (c.gradsync_bytes > 0 && c.gradsync_k > 1) {
          double t = m_.hier_allreduce_time(c.gradsync_bytes, c.gradsync_k,
                                            spans);
          std::vector<int> deps = {bwd_id[i]};
          if (!overlap_ && last_bwd >= 0) deps.push_back(last_bwd);
          SimTask st{SimTask::Kind::GradSync, (int)i, t, deps};
          sync_ids.push_back(add(std::move(st)));
          res.gradsync_time += t;
        }
      }
      // optimizer update traffic: read p + read g + write p (3x params)
      // plus read+write of each optimizer-state copy (2x per copy;
      // opt_state_factor = state copies: 0 plain SGD, 1 momentum, 2 Adam).
      // Bandwidth: the measured update-triad rate when profiled
      // ("__update_bw__" — elementwise updates run well below the
      // datasheet HBM figure), else the analytic hbm_bw.
      double upd_bw = m_.hbm_bw;
      if (measured_) {
        auto it = measured_->find("__update_bw__");
        if (it != measured_->end() && it->second > 0) upd_bw = it->second;
      }
      double upd_bytes = 0;
      for (size_t i = 0; i < N; ++i)
        upd_bytes += (double)g_.nodes[i].param_bytes() *
                     (3.0 + 2.0 * opt_state_factor_);
      std::vector<int> deps = sync_ids;
      if (last_bwd >= 0) deps.push_back(last_bwd);
      SimTask ut{SimTask::Kind::Update, -1, upd_bytes / upd_bw, deps};
      add(std::move(ut));
    }

    // ---- list schedule on {compute, comm} streams ----
    double compute_free = 0, comm_free = 0, makespan = 0;
    for (auto& t : tasks) {
      double ready = 0;
      for (int d : t.deps)
        if (d >= 0) ready = std::max(ready, tasks[d].finish);
      bool on_comm = t.kind == SimTask::Kind::Comm ||
                     t.kind == SimTask::Kind::GradSync;
      double& stream = on_comm ? comm_free : compute_free;
      t.start = std::max(ready, stream);
      t.finish = t.start + t.duration;
      stream = t.finish;
      makespan = std::max(makespan, t.finish);
    }
    res.iteration_time = makespan;
    if (measured_) {
      // fixed per-step dispatch/runtime cost measured on the live device
      // (program launch + host runtime; large on tunneled devices)
      auto it = measured_->find("__step_overhead__");
      if (it != measured_->end()) res.iteration_time += it->second;
    }
    res.tasks = std::move(tasks);
    return res;
  }

 private:
  const Graph& g_;
  const MachineModel& m_;
  MeshShape mesh_;
  bool training_;
  bool overlap_;
  double opt_state_factor_;
  const MeasuredCosts* measured_;
};

}  // namespace ffsearch

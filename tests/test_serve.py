"""flexflow_tpu/serve: latency-objective search, continuous batching,
sharded KV-cache decode, train-anywhere/serve-anywhere (ISSUE 13).

CPU, 8 virtual devices (conftest). The heavyweight legs (zoo-model
Conv+BN-fold parity, latency-researched cross-mesh load) keep configs
tiny; anything beyond them is @slow.
"""

import json
import os
import tempfile
import time

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import CompMode, LossType
from flexflow_tpu.machine import MachineSpec, make_mesh
from flexflow_tpu.model import FFModel
from flexflow_tpu.optimizers import AdamOptimizer, SGDOptimizer

RS = np.random.RandomState(0)


def _mlp(bs=8, in_dim=16, out_dim=4, comp_mode=CompMode.INFERENCE):
    ff = FFModel(FFConfig(batch_size=bs))
    x = ff.create_tensor((bs, in_dim), name="x")
    t = ff.dense(x, 32, name="h1")
    t = ff.relu(t)
    t = ff.dense(t, out_dim, name="head")
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
               comp_mode=comp_mode)
    return ff


# ---------------------------------------------------------------------------
# batching / scheduling (pure python)


class TestBatching:
    def test_queue_fifo_and_depth(self):
        from flexflow_tpu.serve.batching import RequestQueue
        q = RequestQueue()
        r1 = q.submit([np.zeros(3)])
        r2 = q.submit([np.ones(3)])
        assert q.depth() == 2
        got = q.pop_up_to(1)
        assert got == [r1] and q.depth() == 1
        assert q.pop_up_to(5) == [r2] and q.depth() == 0

    def test_scheduler_size_close(self):
        from flexflow_tpu.serve.batching import BatchScheduler, RequestQueue
        q = RequestQueue()
        s = BatchScheduler((2, 4), max_wait_s=3600)
        for _ in range(3):
            q.submit([np.zeros(2)])
        assert s.poll(q) == []  # 3 < max bucket 4, nothing aged
        q.submit([np.zeros(2)])
        batch = s.poll(q)
        assert len(batch) == 4  # size close at the largest bucket

    def test_scheduler_deadline_close(self):
        from flexflow_tpu.serve.batching import BatchScheduler, RequestQueue
        q = RequestQueue()
        s = BatchScheduler((4,), max_wait_s=0.01)
        req = q.submit([np.zeros(2)])
        assert s.poll(q, now=req.enqueue_t + 0.001) == []
        batch = s.poll(q, now=req.enqueue_t + 0.02)
        assert batch == [req]  # deadline close with a lone request

    def test_scheduler_flush(self):
        from flexflow_tpu.serve.batching import BatchScheduler, RequestQueue
        q = RequestQueue()
        s = BatchScheduler((8,), max_wait_s=3600)
        q.submit([np.zeros(2)])
        assert len(s.poll(q, flush=True)) == 1

    def test_pick_bucket(self):
        from flexflow_tpu.serve.batching import pick_bucket
        assert pick_bucket(1, (1, 4, 8)) == 1
        assert pick_bucket(3, (1, 4, 8)) == 4
        assert pick_bucket(5, (1, 4, 8)) == 8
        assert pick_bucket(9, (1, 4, 8)) == 8  # caller caps at max

    def test_pad_to_bucket(self):
        from flexflow_tpu.serve.batching import Request, pad_to_bucket
        reqs = [Request([np.full((3,), i, np.float32)]) for i in range(3)]
        arrays = pad_to_bucket(reqs, 4)
        assert arrays[0].shape == (4, 3)
        assert np.array_equal(arrays[0][:3, 0], [0, 1, 2])
        assert np.all(arrays[0][3] == 0)  # padding rows are zeros
        with pytest.raises(ValueError):
            pad_to_bucket(reqs, 2)

    def test_request_wait_timeout_and_error(self):
        from flexflow_tpu.serve.batching import Request
        r = Request([np.zeros(1)])
        with pytest.raises(TimeoutError):
            r.wait(0.01)
        r.finish(error=RuntimeError("boom"))
        with pytest.raises(RuntimeError):
            r.wait(1)


# ---------------------------------------------------------------------------
# latency-objective search (native DP, no jit)


def _native_or_skip():
    from flexflow_tpu.search.native import available
    if not available():
        pytest.skip("native search unavailable")


class TestLatencyObjective:
    _cache = {}

    def _strategies(self, batch=8, n_chips=8):
        """(training, inference) native strategies for the transformer
        zoo model on a simulated v4 slice. Cached per config — two
        tests share one pair of native searches (tier-1 budget)."""
        key = (batch, n_chips)
        if key in self._cache:
            return self._cache[key]
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     create_transformer)
        from flexflow_tpu.search.native import native_optimize
        from flexflow_tpu.search.unity import (machine_to_json,
                                               serialize_graph)
        mcfg = TransformerConfig(num_layers=4, hidden_size=256,
                                 num_heads=8, seq_length=64,
                                 batch_size=batch)
        ff = create_transformer(mcfg, FFConfig(batch_size=batch,
                                               only_data_parallel=True,
                                               workers_per_node=1))
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        nodes = serialize_graph(ff.executor.nodes)
        machine = machine_to_json(
            MachineSpec(chip="tpu-v4", chips_per_slice=n_chips), n_chips)
        base = dict(budget=8, alpha=0.05, batch=batch, seed=42, rules=[],
                    enable_parameter_parallel=True,
                    enable_pipeline_parallel=False)
        train = native_optimize(dict(
            nodes=nodes, machine=machine, measured={},
            config=dict(base, training=True, opt_state_factor=1.0)))
        inf = native_optimize(dict(
            nodes=nodes, machine=machine, measured={},
            config=dict(base, training=False, opt_state_factor=0.0)))
        self._cache[key] = (train, inf)
        return train, inf

    def test_inference_sharding_differs_from_training(self):
        """Acceptance: the latency objective changes the answer on a
        zoo model — the INFERENCE-searched sharding differs from the
        TRAINING-searched one on the transformer."""
        _native_or_skip()
        train, inf = self._strategies()

        def sig(resp):
            return {k: (v.get("choice"),
                        tuple(tuple(e) for e in v["outputs"]))
                    for k, v in resp["ops"].items()}
        assert sig(train) != sig(inf), (
            "latency-objective search produced the training sharding")

    def test_inference_strategy_has_no_training_only_choices(self):
        """Forward-only pricing: no '_wus'/'_ovl' gradient-sync choice
        twins can win under the INFERENCE objective (there is no
        gradient sync to shard or hide)."""
        _native_or_skip()
        _, inf = self._strategies()
        bad = [k for k, v in inf["ops"].items()
               if any(t in (v.get("choice") or "")
                      for t in ("_wus", "_ovl"))]
        assert not bad, f"inference strategy carries training choices: {bad}"

    def test_objective_recorded_in_info_and_strategy_json(self):
        _native_or_skip()
        from flexflow_tpu.search import unity as _unity
        ff = _mlp(comp_mode=CompMode.TRAINING)
        cfg = FFConfig(batch_size=8)
        cfg.search_budget = 2
        cfg.computation_mode = CompMode.INFERENCE
        cfg.opt_state_factor = 0.0
        mesh_axes, strategy, info = _unity.graph_optimize(
            ff.executor.nodes, MachineSpec(chip="tpu-v4",
                                           chips_per_slice=8),
            cfg, 8, batch=8)
        assert info["objective"] == "latency"
        sj = _unity.strategy_json(mesh_axes, strategy, ff.executor.nodes,
                                  objective=info["objective"])
        assert sj["objective"] == "latency"
        cfg.computation_mode = CompMode.TRAINING
        cfg.opt_state_factor = 1.0
        _, _, info_t = _unity.graph_optimize(
            ff.executor.nodes, MachineSpec(chip="tpu-v4",
                                           chips_per_slice=8),
            cfg, 8, batch=8)
        assert info_t["objective"] == "step_time"


# ---------------------------------------------------------------------------
# serving engine (continuous batching end to end)


class TestServingEngine:
    def test_results_match_predict_and_gauges_flow(self):
        from flexflow_tpu.obs.registry import get_registry
        ff = _mlp()
        engine = ff.serve(batch_buckets=(1, 4, 8), max_wait_ms=1.0,
                          search_budget=0)
        samples = [RS.randn(16).astype(np.float32) for _ in range(6)]
        reqs = [engine.submit([s]) for s in samples]
        served = engine.pump()
        assert served == 6
        direct = ff.predict(np.stack(samples + samples[:2]))
        for i, r in enumerate(reqs):
            np.testing.assert_allclose(r.wait(10), direct[i], atol=1e-5)
        snap = get_registry().to_dict()
        assert snap["observations"]["serve/request_latency_s"]["count"] >= 6
        assert snap["observations"]["serve/batch_occupancy"]["count"] >= 1

    def test_padded_bucket_and_occupancy(self):
        ff = _mlp()
        engine = ff.serve(batch_buckets=(4, 8), max_wait_ms=0.5,
                          search_budget=0)
        s = RS.randn(16).astype(np.float32)
        req = engine.submit([s])
        time.sleep(0.002)  # age past the deadline
        assert engine.step() == 1  # deadline close -> padded into bucket 4
        out = req.wait(10)
        direct = ff.predict(np.stack([s] * 8))[0]
        np.testing.assert_allclose(out, direct, atol=1e-5)

    def test_background_thread_serving(self):
        ff = _mlp()
        engine = ff.serve(batch_buckets=(1, 8), max_wait_ms=0.5,
                          search_budget=0, start=True)
        try:
            s = RS.randn(16).astype(np.float32)
            out = engine.submit([s]).wait(30)
            assert out.shape == (4,)
        finally:
            engine.stop()

    def test_bucket_report_shape(self):
        ff = _mlp()
        engine = ff.serve(batch_buckets=(2, 8), search_budget=0)
        rep = engine.bucket_report()
        assert set(rep) == {"2", "8"}
        for e in rep.values():
            assert e["objective"] == "reused-training-strategy"
            assert "strategy_differs_from_training" in e

    def test_searched_buckets_record_latency_objective(self):
        """Each bucket's searched objective is recorded — latency@batchN
        when the native search priced it."""
        _native_or_skip()
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     create_transformer)
        cfg = TransformerConfig(num_layers=2, hidden_size=64, num_heads=4,
                                seq_length=16, batch_size=8)
        ff = create_transformer(cfg, FFConfig(batch_size=8))
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
                   comp_mode=CompMode.INFERENCE)
        engine = ff.serve(batch_buckets=(1, 8), max_wait_ms=0.5,
                          search_budget=4)
        rep = engine.bucket_report()
        assert rep["1"]["objective"] == "latency@batch1"
        assert rep["8"]["objective"] == "latency@batch8"
        # and the engine still serves correctly under the searched
        # shardings (bucket 1 typically picks a completely different
        # mesh factorization than training)
        x = RS.randn(cfg.seq_length, cfg.hidden_size).astype(np.float32)
        req = engine.submit([x])
        engine.pump()
        direct = ff.predict(np.stack([x] * 8))[0]
        np.testing.assert_allclose(req.wait(10), direct, atol=1e-4)


# ---------------------------------------------------------------------------
# sharded KV-cache incremental decode


class TestKVCacheDecode:
    def _llama(self, seq_parallel=None):
        from flexflow_tpu.models.llama import (LlamaModelConfig,
                                               create_llama)
        cfg = LlamaModelConfig(batch_size=2, seq_length=16,
                               num_hidden_layers=2,
                               seq_parallel=seq_parallel)
        ff = create_llama(cfg, FFConfig(batch_size=2))
        mesh = None
        if seq_parallel:
            mesh = make_mesh(8, {"data": 2, "seq": 4})
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [],
                   comp_mode=CompMode.INFERENCE, mesh=mesh)
        return ff, cfg

    def test_prefill_and_decode_parity_vs_full_recompute(self):
        """Acceptance: KV-cache incremental decode is parity-tested
        against full-sequence recompute — prefill(8) + 8 single-token
        decode steps reproduce predict()'s logits."""
        from flexflow_tpu.serve.kv_cache import DecodeSession
        ff, cfg = self._llama()
        ids = RS.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
        full = ff.predict(ids)
        sess = DecodeSession(ff)
        pre = sess.prefill([ids[:, :8]])
        np.testing.assert_allclose(pre, full[:, :8], atol=2e-5)
        steps = [sess.decode([ids[:, t:t + 1]]) for t in range(8, 16)]
        inc = np.concatenate(steps, axis=1)
        np.testing.assert_allclose(inc, full[:, 8:], atol=2e-5)
        # the session is at max_len now: one more block must refuse
        with pytest.raises(ValueError):
            sess.decode([ids[:, :1]])

    def test_cache_is_sharded_on_seq_axis(self):
        """The cache is a first-class sharded tensor: with a 'seq' mesh
        axis (ring-attention sharding) the cache's sequence dim shards
        over it, and decode stays numerically correct."""
        from flexflow_tpu.serve.kv_cache import DecodeSession, init_kv_cache
        ff, cfg = self._llama(seq_parallel="seq")
        caches = init_kv_cache(ff)
        spec = next(iter(caches.values()))["k"].sharding.spec
        assert spec[2] == "seq", f"cache seq dim not sharded: {spec}"
        ids = RS.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
        full = ff.predict(ids)
        sess = DecodeSession(ff)
        pre = sess.prefill([ids[:, :12]])
        np.testing.assert_allclose(pre, full[:, :12], atol=2e-5)

    @pytest.mark.slow
    def test_generate_greedy(self):
        from flexflow_tpu.serve.kv_cache import DecodeSession
        ff, cfg = self._llama()
        ids = RS.randint(0, cfg.vocab_size, (2, 4)).astype(np.int32)
        gen = DecodeSession(ff).generate(ids, steps=5)
        assert gen.shape == (2, 9)
        # greedy continuation must match argmax over the full forward
        full = ff.predict(np.concatenate(
            [gen, np.zeros((2, 16 - 9), np.int32)], axis=1))
        assert np.array_equal(gen[:, 4],
                              np.argmax(full[:, 3, :], axis=-1))

    def test_non_causal_attention_refuses(self):
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     create_transformer)
        from flexflow_tpu.serve.kv_cache import init_kv_cache
        cfg = TransformerConfig(num_layers=1, hidden_size=32, num_heads=2,
                                seq_length=8, batch_size=2, causal=False)
        ff = create_transformer(cfg, FFConfig(batch_size=2))
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
                   comp_mode=CompMode.INFERENCE)
        with pytest.raises(NotImplementedError):
            init_kv_cache(ff)


# ---------------------------------------------------------------------------
# train-anywhere / serve-anywhere


def _conv_bn_model(bs=8):
    ff = FFModel(FFConfig(batch_size=bs))
    x = ff.create_tensor((bs, 3, 16, 16), name="img")
    t = ff.conv2d(x, 8, 3, 3, 1, 1, 1, 1, name="c1")
    t = ff.batch_norm(t, relu=True, name="bn1")
    t = ff.conv2d(t, 8, 3, 3, 2, 2, 1, 1, name="c2")
    t = ff.batch_norm(t, relu=True, name="bn2")
    t = ff.flat(t)
    t = ff.dense(t, 10, name="fc")
    t = ff.softmax(t)
    return ff


class TestLoadForServing:
    def _train_and_save(self, d):
        from flexflow_tpu.ckpt import save_sharded
        train = _conv_bn_model()
        train.compile(AdamOptimizer(alpha=1e-3),
                      LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [],
                      mesh=make_mesh(4, {"data": 4}))
        x = RS.randn(8, 3, 16, 16).astype(np.float32)
        y = RS.randint(0, 10, (8, 1)).astype(np.int32)
        train.fit(x, y, epochs=1, verbose=False)  # move BN stats
        save_sharded(d, train)
        return train, x

    def test_cross_mesh_predict_equivalent(self):
        """Acceptance: a training checkpoint saved on a {data:4} mesh
        loads for serving on a DIFFERENT mesh and predicts numerically
        equivalently (through the Conv+BN-folded inference path)."""
        from flexflow_tpu.serve import load_for_serving
        with tempfile.TemporaryDirectory() as d:
            train, x = self._train_and_save(d)
            ref = train.predict(x)
            serve = load_for_serving(d, _conv_bn_model(),
                                     mesh=make_mesh(2, {"data": 2}),
                                     search_budget=0)
            assert serve.serve_load_info["cross_mesh"]
            assert serve.serve_load_info["plan"]["action"] == "research"
            assert serve.opt_state is None  # INFERENCE: no optimizer state
            np.testing.assert_allclose(serve.predict(x), ref, atol=1e-5)
            # training model compiled without a search: manifest
            # strategy carries no objective annotation
            from flexflow_tpu.ckpt import elastic
            manifest = elastic.load_manifest(d)
            assert "objective" not in (manifest.get("strategy") or {})

    def test_latency_research_mode(self):
        """With the native search, load_for_serving re-searches
        latency-objective shardings for the live topology."""
        _native_or_skip()
        from flexflow_tpu.serve import load_for_serving
        with tempfile.TemporaryDirectory() as d:
            train, x = self._train_and_save(d)
            ref = train.predict(x)
            serve = load_for_serving(d, _conv_bn_model(), search_budget=4)
            info = serve.serve_load_info
            assert info["mode"] == "latency-research"
            assert info["objective"] == "latency"
            np.testing.assert_allclose(serve.predict(x), ref, atol=1e-5)

    @pytest.mark.slow
    def test_same_topology_reuses_saved_strategy_without_search(self):
        from flexflow_tpu.serve import load_for_serving
        with tempfile.TemporaryDirectory() as d:
            train, x = self._train_and_save(d)
            ref = train.predict(x)
            serve = load_for_serving(d, _conv_bn_model(),
                                     mesh=make_mesh(4, {"data": 4}),
                                     search_budget=0)
            assert not serve.serve_load_info["cross_mesh"]
            np.testing.assert_allclose(serve.predict(x), ref, atol=1e-5)


# ---------------------------------------------------------------------------
# zoo-model Conv+BN fold + bf16 serve-predict parity (ISSUE 13 satellite)


class TestZooFoldedPredictParity:
    """Loaded-from-manifest predict under Conv+BN fold + bf16 compute
    matches the training-compiled predict on two zoo models."""

    def _roundtrip(self, build, x, bf16_tol):
        import jax.numpy as jnp

        from flexflow_tpu.ckpt import save_sharded
        from flexflow_tpu.serve import load_for_serving
        train = build()
        train.compile(SGDOptimizer(lr=0.01),
                      LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [],
                      mesh=make_mesh(2, {"data": 2}))
        # perturb BN running stats so the fold is non-trivial
        for name, st in train.state.items():
            if isinstance(st, dict) and "mean" in st and "var" in st:
                st["mean"] = st["mean"] + 0.1
                st["var"] = st["var"] * 1.5
        ref = train.predict(x)  # f32, folded inference nodes
        with tempfile.TemporaryDirectory() as d:
            save_sharded(d, train)
            # serve compile under a bf16 (TPU-policy) machine spec on a
            # different mesh: fold + bf16 + cross-mesh in one shot
            serve = load_for_serving(
                d, build(), mesh=make_mesh(4, {"data": 4}),
                search_budget=0,
                machine_spec=MachineSpec(chip="tpu-v4", chips_per_slice=4))
            assert serve.executor.compute_dtype == jnp.bfloat16
            out = serve.predict(x)
        assert np.argmax(out, -1).tolist() == np.argmax(ref, -1).tolist()
        np.testing.assert_allclose(out, ref, atol=bf16_tol)

    @pytest.mark.slow
    def test_resnet_bn(self):
        # slow tier (t1 budget): Conv+BN fold parity stays tier-1 via
        # test_layout.py::TestConvBNFold and cross-mesh load_for_serving
        # via TestLoadForServing::test_cross_mesh_predict_equivalent
        from flexflow_tpu.models.resnet import ResNetConfig, create_resnet
        cfg = ResNetConfig(batch_size=4, image_size=32,
                           stages=(1, 1, 0, 0), num_classes=10,
                           batch_norm=True)
        x = RS.randn(4, 3, 32, 32).astype(np.float32)
        self._roundtrip(lambda: create_resnet(cfg), x, bf16_tol=0.05)

    @pytest.mark.slow
    def test_alexnet_bn(self):
        from flexflow_tpu.models.alexnet import create_alexnet
        x = RS.randn(4, 3, 64, 64).astype(np.float32)
        self._roundtrip(
            lambda: create_alexnet(batch_size=4, num_classes=10,
                                   image_size=64, batch_norm=True),
            x, bf16_tol=0.05)


# ---------------------------------------------------------------------------
# closed-loop load generation + smoke


class TestLoadgen:
    def test_closed_loop_stats(self):
        ff = _mlp()
        engine = ff.serve(batch_buckets=(1, 4, 8), max_wait_ms=1.0,
                          search_budget=0, start=True)
        try:
            from flexflow_tpu.serve.loadgen import run_closed_loop
            samples = [RS.randn(16).astype(np.float32) for _ in range(20)]
            stats = run_closed_loop(engine, lambda i: [samples[i % 20]],
                                    num_requests=10, concurrency=3,
                                    warmup=2)
        finally:
            engine.stop()
        assert stats["num_measured"] == 10
        assert not stats["errors"]
        assert stats["p50_s"] > 0 and stats["p99_s"] >= stats["p50_s"]

    def test_serve_smoke_writes_artifact(self, tmp_path):
        from flexflow_tpu.serve.loadgen import run_serve_smoke
        report = run_serve_smoke(trace_dir=str(tmp_path), num_requests=8)
        path = report.get("artifact")
        assert path and os.path.exists(path)
        data = json.load(open(path))
        assert data["header"]["kind"] == "serve"
        assert data["closed_loop"]["num_measured"] == 8

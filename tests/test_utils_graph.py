"""Unit tests mirroring the reference's tests/unit suite (SURVEY §4):
dominators, disjoint_set, topo_sort, hash_combine, driver CLI."""

import pytest

from flexflow_tpu.utils.graph_algorithms import (DisjointSet, dominators,
                                                 hash_combine,
                                                 immediate_post_dominator,
                                                 post_dominators, topo_sort)

# diamond: a -> b, a -> c, b -> d, c -> d, d -> e
DIAMOND = {"a": ["b", "c"], "b": ["d"], "c": ["d"], "d": ["e"], "e": []}


class TestGraphAlgorithms:
    def test_topo_sort(self):
        order = topo_sort(DIAMOND)
        pos = {n: i for i, n in enumerate(order)}
        assert pos["a"] < pos["b"] < pos["d"] < pos["e"]
        assert pos["a"] < pos["c"] < pos["d"]

    def test_topo_sort_cycle(self):
        with pytest.raises(ValueError):
            topo_sort({"a": ["b"], "b": ["a"]})

    def test_dominators(self):
        dom = dominators(DIAMOND, "a")
        assert dom["d"] == {"a", "d"}  # neither b nor c dominates d
        assert dom["b"] == {"a", "b"}
        assert dom["e"] == {"a", "d", "e"}

    def test_post_dominators_find_bottleneck(self):
        pdom = post_dominators(DIAMOND, "e")
        # d post-dominates everything: it is the sequence-split point
        assert "d" in pdom["a"] and "d" in pdom["b"] and "d" in pdom["c"]
        assert immediate_post_dominator(DIAMOND, "b", "e") == "d"
        assert immediate_post_dominator(DIAMOND, "d", "e") == "e"

    def test_disjoint_set(self):
        ds = DisjointSet()
        ds.union(1, 2)
        ds.union(3, 4)
        assert ds.same(1, 2) and not ds.same(2, 3)
        ds.union(2, 3)
        assert ds.same(1, 4)

    def test_hash_combine_deterministic(self):
        h1 = hash_combine(hash_combine(0, "linear"), (64, 128))
        h2 = hash_combine(hash_combine(0, "linear"), (64, 128))
        h3 = hash_combine(hash_combine(0, "linear"), (64, 256))
        assert h1 == h2 != h3


class TestDriver:
    def test_launcher_parses_flags_and_runs_script(self, tmp_path, capsys):
        script = tmp_path / "prog.py"
        script.write_text(
            "import sys\n"
            "from flexflow_tpu.driver import get_config\n"
            "cfg = get_config()\n"
            "print('B', cfg.batch_size, 'BUDGET', cfg.search_budget,"
            " 'REST', sys.argv[1:])\n")
        from flexflow_tpu.driver import main

        rc = main(["-b", "16", "--budget", "7", str(script), "--app-flag"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "B 16 BUDGET 7 REST ['--app-flag']" in out

"""Per-op numerical alignment vs torch CPU.

Mirrors the reference's tests/align strategy (SURVEY §4): the same op run
in the framework and in PyTorch, outputs compared with epsilon. Each op is
exercised through a single-op FFModel graph (predict path), so these also
cover the op library's forward lowering.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.ffconst import ActiMode, DataType, PoolType

RS = np.random.RandomState(0)
B = 4


def run_op(build, in_shapes, dtypes=None, feeds=None, return_model=False):
    ff = FFModel(FFConfig(batch_size=B, only_data_parallel=True))
    ts = []
    for i, shp in enumerate(in_shapes):
        dt = (dtypes or [DataType.FLOAT] * len(in_shapes))[i]
        ts.append(ff.create_tensor((B,) + tuple(shp), dtype=dt))
    out_t = build(ff, *ts)
    ff.compile(SGDOptimizer(lr=0.01), LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
               outputs=out_t)
    xs = feeds if feeds is not None else [
        RS.randn(B, *shp).astype(np.float32) for shp in in_shapes]
    out = ff.predict(xs if len(xs) > 1 else xs[0])
    if return_model:
        return out, xs, ff
    return out, xs


def close(a, b, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol,
                               atol=atol)


class TestDenseConvPool:
    def test_linear_with_bias_and_relu(self):
        out, (x,), ff = run_op(
            lambda ff, t: ff.dense(t, 8, activation=ActiMode.AC_MODE_RELU,
                                   name="d"),
            [(16,)], return_model=True)
        k = ff.get_parameter("d", "kernel")
        b = ff.get_parameter("d", "bias")
        want = F.relu(torch.from_numpy(x) @ torch.from_numpy(k)
                      + torch.from_numpy(b)).numpy()
        close(out, want, rtol=1e-3, atol=1e-4)

    def test_pool2d_avg_matches_torch(self):
        out, (x,) = run_op(lambda ff, t: ff.pool2d(t, 2, 2, 2, 2, 0, 0,
                           pool_type=PoolType.POOL_AVG), [(3, 8, 8)])
        want = F.avg_pool2d(torch.from_numpy(x), 2).numpy()
        close(out, want)

    def test_pool2d_max_matches_torch(self):
        out, (x,) = run_op(lambda ff, t: ff.pool2d(t, 3, 3, 2, 2, 1, 1),
                           [(3, 9, 9)])
        want = F.max_pool2d(torch.from_numpy(x), 3, 2, 1).numpy()
        close(out, want)


class TestNormalization:
    def test_layernorm_matches_torch(self):
        out, (x,) = run_op(lambda ff, t: ff.layer_norm(t, name="ln"), [(6, 10)])
        want = F.layer_norm(torch.from_numpy(x), (10,)).numpy()
        close(out, want, rtol=1e-3, atol=1e-4)

    def test_softmax_matches_torch(self):
        out, (x,) = run_op(lambda ff, t: ff.softmax(t), [(7,)])
        close(out, F.softmax(torch.from_numpy(x), dim=-1).numpy())


class TestShapeOps:
    def test_transpose_reshape_reverse(self):
        def build(ff, t):
            t = ff.transpose(t, (0, 2, 1))
            t = ff.reshape(t, (B, 24))
            return ff.reverse(t, axis=1)

        out, (x,) = run_op(build, [(4, 6)])
        want = x.transpose(0, 2, 1).reshape(B, 24)[:, ::-1]
        close(out, want)

    def test_concat_split(self):
        def build(ff, a, b):
            c = ff.concat([a, b], axis=1)
            parts = ff.split(c, [3, 5], axis=1)
            return parts[1]

        out, (xa, xb) = run_op(build, [(3,), (5,)])
        close(out, xb)

    def test_flat(self):
        out, (x,) = run_op(lambda ff, t: ff.flat(t), [(2, 3, 4)])
        close(out, x.reshape(B, 24))


class TestMathOps:
    def test_batch_matmul_matches_torch(self):
        def build(ff, a, b):
            return ff.batch_matmul(a, b)

        xa = RS.randn(B, 5, 6).astype(np.float32)
        xb = RS.randn(B, 6, 7).astype(np.float32)
        out, _ = run_op(build, [(5, 6), (6, 7)], feeds=[xa, xb])
        close(out, torch.bmm(torch.from_numpy(xa), torch.from_numpy(xb)).numpy(),
              rtol=1e-3, atol=1e-4)

    def test_reduce_and_mean(self):
        out, (x,) = run_op(lambda ff, t: ff.reduce_sum(t, [1], keepdims=False),
                           [(5, 3)])
        close(out, x.sum(axis=1))
        out2, (x2,) = run_op(lambda ff, t: ff.mean(t, [1, 2]), [(5, 3)])
        close(out2, x2.mean(axis=(1, 2)))

    def test_elementwise_binary(self):
        def build(ff, a, b):
            t = ff.add(a, b)
            t = ff.multiply(t, a)
            t = ff.subtract(t, b)
            return ff.max(t, a)

        out, (xa, xb) = run_op(build, [(9,), (9,)])
        want = np.maximum((xa + xb) * xa - xb, xa)
        close(out, want)

    def test_unary_chain(self):
        def build(ff, t):
            t = ff.sigmoid(t)
            t = ff.scalar_multiply(t, 2.0)
            t = ff.pow(t, 2.0)
            return ff.rsqrt(t)

        out, (x,) = run_op(build, [(11,)])
        s = 1.0 / (1.0 + np.exp(-x))
        close(out, 1.0 / np.sqrt((2 * s) ** 2), rtol=1e-3, atol=1e-4)

    def test_gather_topk(self):
        idx = RS.randint(0, 10, (B, 3)).astype(np.int32)
        x = RS.randn(B, 10).astype(np.float32)

        def build(ff, t, i):
            return ff.gather(t, i, axis=1)

        out, _ = run_op(build, [(10,), (3,)],
                        dtypes=[DataType.FLOAT, DataType.INT32],
                        feeds=[x, idx])
        want = np.take_along_axis(x, idx, axis=1)
        close(out, want)

    def test_embedding_matches_weight_rows(self):
        idx = RS.randint(0, 20, (B, 2)).astype(np.int32)

        def build(ff, i):
            return ff.embedding(i, 20, 6, name="emb")

        ff = FFModel(FFConfig(batch_size=B, only_data_parallel=True))
        t = ff.create_tensor((B, 2), dtype=DataType.INT32)
        build(ff, t)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        w = ff.get_parameter("emb")
        out = ff.predict(idx)
        close(out, w[idx])


class TestTrainingGradients:
    def test_linear_gradient_matches_torch(self):
        # one SGD step on y = xW + b, MSE loss: compare updated W with torch
        x = RS.randn(B, 6).astype(np.float32)
        y = RS.randn(B, 3).astype(np.float32)
        ff = FFModel(FFConfig(batch_size=B, only_data_parallel=True,
                              weight_decay=0.0, allow_mixed_precision=False))
        t = ff.create_tensor((B, 6))
        ff.dense(t, 3, name="d")
        ff.compile(SGDOptimizer(lr=0.1, weight_decay=0.0),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        w0 = ff.get_parameter("d").copy()
        b0 = ff.get_parameter("d", "bias").copy()
        ff.set_batch(x, y)
        ff.forward(); ff.backward(); ff.update()
        w1 = ff.get_parameter("d")

        tw = torch.tensor(w0, requires_grad=True)
        tb = torch.tensor(b0, requires_grad=True)
        loss = F.mse_loss(torch.from_numpy(x) @ tw + tb, torch.from_numpy(y))
        loss.backward()
        want = w0 - 0.1 * tw.grad.numpy()
        close(w1, want, rtol=1e-3, atol=1e-4)

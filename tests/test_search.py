"""Auto-parallelization search stack (SURVEY §2.5).

Deviceless tests of the native core (analytic machine model means no chip
is needed — the improvement over the reference's GPU-microbenchmark-only
simulator noted in SURVEY §4), plus integration through FFModel.compile on
the virtual 8-device mesh.
"""

import json

import numpy as np
import pytest

from flexflow_tpu.search.native import available, native_optimize, native_simulate

pytestmark = pytest.mark.skipif(not available(),
                                reason="native ffsearch library unavailable")

MACHINE = {
    "num_devices": 8, "flops": 197e12, "hbm_bw": 0.82e12, "hbm_cap": 16e9,
    "ici_bw": 45e9, "ici_latency": 1e-6, "dcn_bw": 25e9, "dcn_latency": 1e-5,
    "num_slices": 1,
}


def _cfg(**kw):
    base = dict(budget=5, alpha=0.05, only_data_parallel=False,
                enable_parameter_parallel=True, overlap=True, training=True,
                memory_threshold=0, seed=1, rules=[])
    base.update(kw)
    return base


def linear_node(guid, name, src, b, din, dout):
    return {
        "guid": guid, "type": "LINEAR", "name": name, "inputs": [src],
        "input_shapes": [[b, din]], "output_shapes": [[b, dout]],
        "roles": [["sample", "channel"]],
        "params": {"kernel": [din, dout], "bias": [dout]},
        "flops": 2.0 * b * din * dout, "dtype_size": 4, "attrs": {},
    }


def mlp_graph(b=64, d=1024, h=4096):
    return [
        linear_node(1, "d1", [-1, 0], b, d, h),
        {"guid": 2, "type": "RELU", "name": "r1", "inputs": [[1, 0]],
         "input_shapes": [[b, h]], "output_shapes": [[b, h]],
         "roles": [["sample", "channel"]], "params": {},
         "flops": float(b * h), "dtype_size": 4, "attrs": {}},
        linear_node(3, "d2", [2, 0], b, h, d),
    ]


class TestNativeSearch:
    def test_big_batch_small_weights_prefers_data_parallel(self):
        # 16k batch, small weights: gradient sync is cheap, activations are
        # not — DP must win
        nodes = [linear_node(1, "d1", [-1, 0], 16384, 256, 256),
                 linear_node(2, "d2", [1, 0], 16384, 256, 256)]
        resp = native_optimize({"machine": MACHINE, "config": _cfg(),
                                "measured": {}, "nodes": nodes})
        assert resp["mesh"]["data"] > 1
        assert resp["ops"]["1"]["choice"].startswith("dp")

    def test_fat_weights_tiny_batch_uses_model_parallel(self):
        # batch 8 with 8k x 8k weights: DP pays a 256 MB gradient allreduce
        # per layer; sharding the weights must win
        nodes = mlp_graph(b=8, d=8192, h=8192)
        resp = native_optimize({"machine": MACHINE, "config": _cfg(),
                                "measured": {}, "nodes": nodes})
        assert resp["mesh"]["model"] > 1
        # guids may shift when a rewrite fires (e.g. fuse_linear_RELU
        # merges the activation into the matmul) — find any Linear kernel
        kspecs = [oj["params"]["kernel"] for oj in resp["ops"].values()
                  if "kernel" in oj.get("params", {})]
        assert kspecs and any("model" in ks for ks in kspecs), resp["ops"]

    def test_only_data_parallel_flag(self):
        nodes = mlp_graph(b=8, d=8192, h=8192)
        resp = native_optimize({
            "machine": MACHINE, "config": _cfg(only_data_parallel=True),
            "measured": {}, "nodes": nodes})
        assert resp["mesh"]["model"] == 1

    def test_memory_threshold_prunes_fat_strategies(self):
        # threshold below replicated weight bytes forces weight sharding
        nodes = mlp_graph(b=8, d=8192, h=8192)
        weights = 2 * 8192 * 8192 * 4 * 3.0  # params * (1+opt_factor)
        resp = native_optimize({
            "machine": MACHINE,
            "config": _cfg(memory_threshold=weights / 4),
            "measured": {}, "nodes": nodes})
        assert resp["predicted_memory"] < weights / 4
        assert resp["mesh"]["model"] > 1

    def test_attention_head_parallel_choice_exists(self):
        b, s, e, hds = 8, 512, 1024, 16
        nodes = [{
            "guid": 1, "type": "MULTIHEAD_ATTENTION", "name": "attn",
            "inputs": [[-1, 0], [-1, 0], [-1, 0]],
            "input_shapes": [[b, s, e]] * 3, "output_shapes": [[b, s, e]],
            "roles": [["sample", "seq", "channel"]],
            "params": {"wq": [hds, e, e // hds], "wk": [hds, e, e // hds],
                       "wv": [hds, e, e // hds], "wo": [hds, e // hds, e]},
            "flops": 4.0 * b * s * e * e + 2.0 * b * s * s * e,
            "dtype_size": 4, "attrs": {"num_heads": hds},
        }]
        resp = native_optimize({"machine": MACHINE, "config": _cfg(budget=0),
                                "measured": {}, "nodes": nodes})
        assert resp["mesh"]["data"] * resp["mesh"]["model"] == 8

    def test_torus_topology_flips_model_axis_assignment(self):
        """VERDICT r4 Missing #4: per-axis torus pricing. On 12 chips,
        the SAME hybrid strategy (col+row Linear pair) prices cheapest at
        model=6 on a (6,2) torus but at model=4 on a (4,3) torus — each
        is the degree that embeds as a full wrapped ring; a fragmented
        embedding pays line penalties (EnhancedMachineModel role,
        reference simulator.h:229-279). Asserted through the simulator at
        pinned meshes so the check survives cost-model evolution in the
        col/row edge terms (the search-level flip depends on every other
        term too)."""
        b, d, h = 3072, 2048, 6144

        def lin(g, name, src, din, dout):
            return {"guid": g, "type": "LINEAR", "name": name,
                    "inputs": [src], "input_shapes": [[b, din]],
                    "output_shapes": [[b, dout]],
                    "roles": [["sample", "channel"]],
                    "params": {"kernel": [din, dout], "bias": [dout]},
                    "flops": 2.0 * b * din * dout, "dtype_size": 2,
                    "attrs": {}}

        nodes = [lin(1, "d1", [-1, 0], d, h), lin(2, "d2", [1, 0], h, d)]
        machine12 = dict(MACHINE, num_devices=12)
        times = {}
        for torus in ((6, 2), (4, 3)):
            for mp in (6, 4):
                resp = native_simulate({
                    "machine": dict(machine12, torus=list(torus)),
                    "config": _cfg(budget=0), "measured": {},
                    "nodes": nodes,
                    "mesh": {"data": 12 // mp, "model": mp,
                             "seq": 1, "expert": 1},
                    "assignment": {"1": "dp_col", "2": "dp_row"}})
                times[(torus, mp)] = resp["iteration_time"]
        # the wrapped-ring embedding must win on its own torus, both ways
        assert times[((6, 2), 6)] < times[((6, 2), 4)], times
        assert times[((4, 3), 4)] < times[((4, 3), 6)], times
        # and fragmenting an axis across the other torus prices higher
        assert times[((4, 3), 6)] > times[((6, 2), 6)] * 1.02, times
        assert times[((6, 2), 4)] > times[((4, 3), 4)] * 1.02, times

    def test_torus_fragmentation_prices_higher(self):
        # a 3-axis mesh that fits a (2,2,2) cube exactly must price
        # higher on a (4,2) torus, where the third axis becomes a
        # wrap-less sub-ring; a flat (no-torus) machine matches the cube
        b, s, e, hds = 2, 16384, 512, 2
        dd = e // hds
        nodes = [{
            "guid": 1, "type": "MULTIHEAD_ATTENTION", "name": "attn",
            "inputs": [[-1, 0], [-1, 0], [-1, 0]],
            "input_shapes": [[b, s, e]] * 3, "output_shapes": [[b, s, e]],
            "roles": [["sample", "seq", "channel"]],
            "params": {"wq": [hds, e, dd], "wk": [hds, e, dd],
                       "wv": [hds, e, dd], "wo": [hds, dd, e]},
            "flops": 4.0 * b * s * e * e + 2.0 * b * s * s * e * 2,
            "dtype_size": 2, "attrs": {"num_heads": hds},
        }]
        times = {}
        for key, torus in (("flat", []), ("4x2", [4, 2]),
                           ("cube", [2, 2, 2])):
            resp = native_optimize({
                "machine": dict(MACHINE, torus=torus),
                "config": _cfg(budget=0), "measured": {}, "nodes": nodes})
            mesh = {k: v for k, v in resp["mesh"].items() if v > 1}
            assert mesh == {"data": 2, "model": 2, "seq": 2}, (key, mesh)
            times[key] = resp["predicted_time"]
        assert times["4x2"] > times["cube"] * 1.02, times
        assert times["flat"] == pytest.approx(times["cube"], rel=1e-9)

    def test_gqa_head_choice_shards_kv_when_divisible(self):
        # VERDICT r4 Weak #3: GQA (wk/wv carry num_kv_heads on dim 0)
        # must shard kv weights too when kv_heads divides the model axis
        def attn_node(hds, kv):
            b, s, e = 2, 512, 1024
            d = e // hds
            return [{
                "guid": 1, "type": "MULTIHEAD_ATTENTION", "name": "attn",
                "inputs": [[-1, 0], [-1, 0], [-1, 0]],
                "input_shapes": [[b, s, e]] * 3,
                "output_shapes": [[b, s, e]],
                "roles": [["sample", "seq", "channel"]],
                "params": {"wq": [hds, e, d], "wk": [kv, e, d],
                           "wv": [kv, e, d], "wo": [hds, d, e]},
                "flops": 4.0 * b * s * e * e + 2.0 * b * s * s * e,
                "dtype_size": 2,
                "attrs": {"num_heads": hds, "num_kv_heads": kv},
            }]

        resp = native_optimize({
            "machine": MACHINE,
            "config": _cfg(budget=0),
            "measured": {}, "nodes": attn_node(16, 4)})
        op = resp["ops"]["1"]
        assert "head" in op["choice"], op
        assert resp["mesh"]["model"] > 1
        assert op["params"]["wq"][0] == "model"
        assert op["params"]["wo"][0] == "model"
        # kv=4 divides any model axis the 8-chip mesh can pick (2 or 4)
        assert op["params"]["wk"][0] == "model", op["params"]
        assert op["params"]["wv"][0] == "model", op["params"]

        # MQA (kv=1): kv weights can never shard — they stay replicated
        # but the head choice must still exist (q/o sharded)
        resp1 = native_optimize({
            "machine": MACHINE,
            "config": _cfg(budget=0),
            "measured": {}, "nodes": attn_node(16, 1)})
        op1 = resp1["ops"]["1"]
        assert "head" in op1["choice"], op1
        assert op1["params"]["wq"][0] == "model"
        assert op1["params"]["wk"][0] != "model"

    def test_long_seq_small_batch_picks_seq_axis(self):
        # batch 2 with 2 heads on 8 chips: dp<=2 and head-parallel mp<=2, so
        # full utilization of the attention core (the dominant cost at
        # s=65536) requires a seq axis — the search must discover ring
        # attention (reference has no analog; SURVEY §5.7 new scope)
        b, s, e, hds = 2, 65536, 512, 2
        nodes = [{
            "guid": 1, "type": "MULTIHEAD_ATTENTION", "name": "attn",
            "inputs": [[-1, 0], [-1, 0], [-1, 0]],
            "input_shapes": [[b, s, e]] * 3, "output_shapes": [[b, s, e]],
            "roles": [["sample", "seq", "channel"]],
            "params": {"wq": [hds, e, e // hds], "wk": [hds, e, e // hds],
                       "wv": [hds, e, e // hds], "wo": [hds, e // hds, e]},
            "flops": 4.0 * b * s * e * e + 2.0 * b * s * s * e * 2,
            "dtype_size": 2, "attrs": {"num_heads": hds},
        }]
        resp = native_optimize({"machine": MACHINE, "config": _cfg(budget=0),
                                "measured": {}, "nodes": nodes})
        assert resp["mesh"]["seq"] > 1, resp["mesh"]
        # the ring rewrite may additionally carry the weight-update-
        # sharding twin suffix (a searched dimension since ISSUE 4)
        assert "_ring" in resp["ops"]["1"]["choice"], resp["ops"]["1"]
        # the output spec carries the seq axis on the sequence dim
        assert resp["ops"]["1"]["outputs"][0][1] == "seq"

    def test_seq_sharding_flows_through_batchlike_ops(self):
        # attention (ring) -> relu -> linear: the intermediate ops must be
        # able to carry the seq-sharded layout (no gather between them)
        b, s, e, hds = 2, 65536, 512, 2
        attn = {
            "guid": 1, "type": "MULTIHEAD_ATTENTION", "name": "attn",
            "inputs": [[-1, 0], [-1, 0], [-1, 0]],
            "input_shapes": [[b, s, e]] * 3, "output_shapes": [[b, s, e]],
            "roles": [["sample", "seq", "channel"]],
            "params": {"wq": [hds, e, e // hds], "wk": [hds, e, e // hds],
                       "wv": [hds, e, e // hds], "wo": [hds, e // hds, e]},
            "flops": 4.0 * b * s * e * e + 2.0 * b * s * s * e * 2,
            "dtype_size": 2, "attrs": {"num_heads": hds},
        }
        relu = {"guid": 2, "type": "RELU", "name": "r", "inputs": [[1, 0]],
                "input_shapes": [[b, s, e]], "output_shapes": [[b, s, e]],
                "roles": [["sample", "seq", "other"]], "params": {},
                "flops": float(b * s * e), "dtype_size": 2, "attrs": {}}
        lin = {"guid": 3, "type": "LINEAR", "name": "l", "inputs": [[2, 0]],
               "input_shapes": [[b, s, e]], "output_shapes": [[b, s, e]],
               "roles": [["sample", "seq", "channel"]],
               "params": {"kernel": [e, e], "bias": [e]},
               "flops": 2.0 * b * s * e * e, "dtype_size": 2, "attrs": {}}
        resp = native_optimize({"machine": MACHINE, "config": _cfg(budget=0),
                                "measured": {}, "nodes": [attn, relu, lin]})
        assert resp["mesh"]["seq"] > 1, resp["mesh"]
        for g in ("1", "2", "3"):
            assert resp["ops"][g]["outputs"][0][1] == "seq", (g, resp["ops"][g])

    def test_substitution_rules_restrict_choices(self):
        nodes = mlp_graph(b=8, d=8192, h=8192)
        resp = native_optimize({
            "machine": MACHINE,
            "config": _cfg(rules=[{"op_type": "LINEAR", "allow": ["rep", "dp"]}],
                           enable_substitution=False),
            "measured": {}, "nodes": nodes})
        for g in ("1", "3"):
            assert resp["ops"][g]["choice"] in ("rep", "dp")

    def test_measured_costs_override(self):
        nodes = [linear_node(1, "d1", [-1, 0], 1024, 512, 512)]
        base = native_optimize({"machine": MACHINE, "config": _cfg(budget=0),
                                "measured": {}, "nodes": nodes})
        # penalize the node's measured fwd/bwd (profile.py schema:
        # "<guid>:fwd"/"<guid>:bwd", scaled by the choice's work_div): the
        # reported time must reflect the 1s profiles
        measured = {"1:fwd": 1.0, "1:bwd": 1.0}
        slow = native_optimize({
            "machine": MACHINE, "config": _cfg(budget=0),
            "measured": measured, "nodes": nodes})
        assert slow["predicted_time"] > base["predicted_time"] * 100


class TestSimulator:
    def test_taskgraph_and_overlap(self):
        nodes = mlp_graph(b=2048, d=1024, h=1024)
        req = {"machine": MACHINE, "config": _cfg(),
               "mesh": {"data": 8, "model": 1},
               "assignment": {"1": "dp", "2": "dp", "3": "dp"},
               "nodes": nodes, "measured": {}}
        r = native_simulate(req)
        kinds = {t["kind"] for t in r["tasks"]}
        assert {"fwd", "bwd", "gradsync", "update"} <= kinds
        assert r["iteration_time"] > 0
        # no-overlap schedule must be >= overlapped one
        req_no = dict(req, config=_cfg(overlap=False))
        r_no = native_simulate(req_no)
        assert r_no["iteration_time"] >= r["iteration_time"] - 1e-12

    def test_dp_beats_replicated_for_big_batch(self):
        nodes = mlp_graph(b=8192, d=1024, h=1024)
        base = {"machine": MACHINE, "config": _cfg(), "nodes": nodes,
                "measured": {}}
        rep = native_simulate(dict(base, mesh={"data": 8, "model": 1},
                                   assignment={"1": "rep", "2": "rep", "3": "rep"}))
        dp = native_simulate(dict(base, mesh={"data": 8, "model": 1},
                                  assignment={"1": "dp", "2": "dp", "3": "dp"}))
        assert dp["iteration_time"] < rep["iteration_time"]


class TestCompileIntegration:
    def test_search_drives_compile_and_trains(self):
        from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                                  SGDOptimizer)
        from flexflow_tpu.ffconst import ActiMode

        rs = np.random.RandomState(0)
        n, d = 256, 16
        centers = rs.randn(4, d) * 3
        y = rs.randint(0, 4, n)
        x = (centers[y] + rs.randn(n, d)).astype(np.float32)
        cfg = FFConfig(batch_size=64, search_budget=5,
                       enable_parameter_parallel=True)
        ff = FFModel(cfg)
        t = ff.create_tensor((64, d))
        h = ff.dense(t, 128, activation=ActiMode.AC_MODE_RELU)
        out = ff.dense(h, 4)
        out = ff.softmax(out)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.ACCURACY])
        assert ff.search_info is not None
        assert ff.search_info["predicted_time"] > 0
        ff.fit(x, y.astype(np.int32).reshape(-1, 1), epochs=4, verbose=False)
        rep = ff.evaluate(x, y.astype(np.int32).reshape(-1, 1))
        assert rep["accuracy"] > 0.9

    def test_search_respects_batch_divisibility(self):
        # batch 6 on 8 devices: dp must not be 8 (regression: the mesh
        # factorization used to ignore the batch, crashing _shard_batch)
        from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                                  SGDOptimizer)

        cfg = FFConfig(batch_size=6, search_budget=3,
                       enable_parameter_parallel=True)
        ff = FFModel(cfg)
        t = ff.create_tensor((6, 16))
        out = ff.dense(t, 4)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
        assert 6 % axes.get("data", 1) == 0
        rs = np.random.RandomState(0)
        ff.fit(rs.randn(12, 16).astype(np.float32),
               rs.randn(12, 4).astype(np.float32), epochs=1, verbose=False)

    def test_search_discovers_seq_parallel_transformer(self):
        # long-seq BERT proxy, tiny batch + few heads: the searched strategy
        # must carry a seq mesh axis, switch attention onto the ring path,
        # and the whole thing must execute on the virtual 8-device mesh
        from flexflow_tpu import FFConfig, LossType, MetricsType, SGDOptimizer
        from flexflow_tpu.ffconst import OperatorType
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     create_transformer)

        cfg = TransformerConfig(num_layers=1, hidden_size=64, num_heads=2,
                                seq_length=512, batch_size=2)
        ff_cfg = FFConfig(batch_size=2, search_budget=2,
                          enable_parameter_parallel=True)
        ff = create_transformer(cfg, ff_cfg)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
                   [MetricsType.MEAN_SQUARED_ERROR])
        assert ff.search_info is not None
        axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
        assert axes.get("seq", 1) > 1, axes
        attn_ops = [n.op for n in ff.executor.nodes
                    if n.op.op_type == OperatorType.MULTIHEAD_ATTENTION]
        assert attn_ops and all(op.seq_parallel == "seq" for op in attn_ops)
        rs = np.random.RandomState(0)
        x = rs.randn(4, 512, 64).astype(np.float32)
        y = rs.randn(4, 512, 1).astype(np.float32)
        ff.fit(x, y, epochs=1, verbose=False)  # ring attention executes
        out = ff.predict(x[:2])
        assert np.isfinite(np.asarray(out)).all()

    def test_strategy_export_import_roundtrip(self, tmp_path):
        from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                                  SGDOptimizer)
        from flexflow_tpu.ffconst import ActiMode

        path = str(tmp_path / "strategy.json")

        def build(cfg):
            ff = FFModel(cfg)
            t = ff.create_tensor((32, 16))
            h = ff.dense(t, 64, activation=ActiMode.AC_MODE_RELU, name="h")
            out = ff.dense(h, 4, name="out")
            ff.compile(SGDOptimizer(lr=0.1),
                       LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                       [MetricsType.ACCURACY])
            return ff

        cfg1 = FFConfig(batch_size=32, search_budget=5,
                        enable_parameter_parallel=True,
                        export_strategy_file=path)
        ff1 = build(cfg1)
        data = json.load(open(path))
        assert "mesh" in data and "ops" in data

        cfg2 = FFConfig(batch_size=32, import_strategy_file=path)
        ff2 = build(cfg2)
        assert (dict(zip(ff2.mesh.axis_names, ff2.mesh.devices.shape)) ==
                {k: v for k, v in data["mesh"].items()})
        rs = np.random.RandomState(0)
        x = rs.randn(32, 16).astype(np.float32)
        y = rs.randint(0, 4, (32, 1)).astype(np.int32)
        ff2.fit(x, y, epochs=1, verbose=False)  # imported strategy executes


class TestMultiSlice:
    """DCN/multi-slice search (VERDICT r2 #5): slice-aware mesh
    enumeration + hierarchical (ICI-within-slice, DCN-across) gradient
    sync costs. Reference parity target: NetworkedMachineModel
    (simulator.h:515) re-expressed for the TPU slice topology."""

    def _machine(self, dcn_bw, num_slices=2):
        return {"num_devices": 8, "flops": 197e12, "hbm_bw": 0.82e12,
                "hbm_cap": 16e9, "ici_bw": 45e9, "ici_latency": 1e-6,
                "dcn_bw": dcn_bw, "dcn_latency": 1e-5,
                "num_slices": num_slices}

    def _mlp(self, b=4096, d=4096):
        return [
            linear_node(1, "l1", [-1, 0], b, d, d),
            {"guid": 2, "type": "RELU", "name": "r", "inputs": [[1, 0]],
             "input_shapes": [[b, d]], "output_shapes": [[b, d]],
             "roles": [["sample", "other"]], "params": {},
             "flops": float(b * d), "dtype_size": 4, "attrs": {}},
            linear_node(3, "l2", [2, 0], b, d, d),
        ]

    def test_lowering_dcn_bw_flips_strategy(self):
        nodes = self._mlp()
        cfg = _cfg(budget=2, batch=4096, enable_substitution=False)
        fast = native_optimize({"machine": self._machine(25e9),
                                "config": cfg, "measured": {},
                                "nodes": nodes, "final": [3, 0]})
        slow = native_optimize({"machine": self._machine(0.3e9),
                                "config": cfg, "measured": {},
                                "nodes": nodes, "final": [3, 0]})
        # fast DCN: sharded training with cross-slice gradient sync (the
        # search may additionally pick the weight-update-sharding and/or
        # latency-hiding twins — suffix order is base[_wus][_ovl])
        assert fast["ops"]["1"]["choice"] in (
            "dp_col", "dp_col_wus", "dp_col_ovl", "dp_col_wus_ovl"), \
            fast["ops"]
        # slow DCN: the search abandons parameter sync entirely —
        # replicated weights, no gradient ring over the starved DCN
        assert slow["ops"]["1"]["choice"] == "rep", slow["ops"]
        assert slow["predicted_time"] > fast["predicted_time"]

    def test_inner_axes_confined_to_slice(self):
        # 8 chips in 2 slices of 4: meshes with model*seq*expert > 4
        # would put latency-bound collectives on DCN — must not be
        # enumerated (fewer candidates than the single-slice machine)
        nodes = self._mlp(b=8, d=4096)
        cfg = _cfg(budget=0, batch=8)
        one = native_optimize({"machine": self._machine(25e9, 1),
                               "config": cfg, "measured": {},
                               "nodes": nodes, "final": [3, 0]})
        two = native_optimize({"machine": self._machine(25e9, 2),
                               "config": cfg, "measured": {},
                               "nodes": nodes, "final": [3, 0]})
        assert (two["stats"]["mesh_candidates"]
                < one["stats"]["mesh_candidates"])
        assert two["mesh"]["model"] <= 4

    def test_single_slice_unchanged(self):
        # num_slices=1 must behave exactly as before (pure ICI)
        nodes = self._mlp(b=512, d=1024)
        cfg = _cfg(budget=0, batch=512)
        r = native_optimize({"machine": self._machine(25e9, 1),
                             "config": cfg, "measured": {},
                             "nodes": nodes, "final": [3, 0]})
        assert r["predicted_time"] > 0


class TestSampleParallel:
    """2-D sample partition (reference enable_sample_parallel,
    config.h:134): the batch dim shards over data x model jointly when an
    op's params are replicated and the model axis would otherwise idle."""

    def _graph(self):
        # row-parallel linear (odd out_dim kills col/mp_last choices for
        # everything downstream) feeding a flop-heavy elementwise op: the
        # gelu can only reach all 8 chips via the 2-D sample partition
        b, din, dout = 2048, 8192, 4097
        return [
            {"guid": 1, "type": "LINEAR", "name": "row", "inputs": [[-1, 0]],
             "input_shapes": [[b, din]], "output_shapes": [[b, dout]],
             "roles": [["sample", "channel"]],
             "params": {"kernel": [din, dout], "bias": [dout]},
             "flops": 2.0 * b * din * dout, "dtype_size": 4, "attrs": {}},
            {"guid": 2, "type": "GELU", "name": "g", "inputs": [[1, 0]],
             "input_shapes": [[b, dout]], "output_shapes": [[b, dout]],
             "roles": [["sample", "other"]], "params": {},
             "flops": 400.0 * b * dout, "dtype_size": 4, "attrs": {}},
        ], b

    def test_two_d_sample_partition_wins(self):
        nodes, b = self._graph()
        on = native_optimize({"machine": MACHINE,
                              "config": _cfg(budget=2, batch=b,
                                             enable_substitution=False),
                              "measured": {}, "nodes": nodes,
                              "final": [2, 0]})
        off = native_optimize({"machine": MACHINE,
                               "config": _cfg(budget=2, batch=b,
                                              enable_sample_parallel=False,
                                              enable_substitution=False),
                               "measured": {}, "nodes": nodes,
                               "final": [2, 0]})
        assert on["ops"]["2"]["choice"] == "sample2", on["ops"]
        assert on["ops"]["2"]["outputs"][0][0] == "data+model"
        assert on["predicted_time"] < off["predicted_time"]

    def test_sample_partition_executes_through_compile(self):
        # decode -> PartitionSpec(("data","model")) -> GSPMD execution on
        # the virtual 8-device mesh
        import numpy as np
        from flexflow_tpu import (FFConfig, FFModel, LossType, SGDOptimizer)
        from jax.sharding import PartitionSpec as P

        cfg = FFConfig(batch_size=64, search_budget=2,
                       enable_parameter_parallel=True)
        cfg.enable_substitution = False  # probe sample2, not rewrites
        ff = FFModel(cfg)
        t = ff.create_tensor((64, 64))
        h = ff.dense(t, 33, name="row")   # odd out_dim: no col/mp_last
        h = ff.gelu(h, name="g")
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        rs = np.random.RandomState(0)
        x = rs.randn(64, 64).astype(np.float32)
        y = rs.randn(64, 33).astype(np.float32)
        ff.fit(x, y, epochs=1, verbose=False)
        preds = ff.predict(x)
        assert preds.shape == (64, 33)
        assert np.isfinite(preds).all()
        # single-device numerics check: same graph on a 1-chip config
        cfg1 = FFConfig(batch_size=64, only_data_parallel=True,
                        workers_per_node=1)
        ff1 = FFModel(cfg1)
        t1 = ff1.create_tensor((64, 64))
        h1 = ff1.gelu(ff1.dense(t1, 33, name="row"), name="g")
        ff1.compile(SGDOptimizer(lr=0.1),
                    LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        for lname in ("row",):
            for pname in ("kernel", "bias"):
                ff1.set_parameter(lname, ff.get_parameter(lname, pname),
                                  pname)
        np.testing.assert_allclose(ff1.predict(x), preds, rtol=2e-4,
                                   atol=2e-5)


class TestLivenessMemory:
    """Peak-liveness activation accounting (VERDICT r3 Next #7; reference
    bump-allocator role, simulator.h:699-700): under inference an
    activation frees at its last consumer, so a deep chain's footprint is
    ~2 layers of activations, not the whole-graph sum the old model
    charged. Training keeps the sum — every activation is a saved
    residual."""

    def test_sum_model_would_reject_liveness_admits(self):
        from flexflow_tpu import FFConfig, FFModel, LossType
        from flexflow_tpu.machine import MachineSpec
        from flexflow_tpu.search.native import available, native_optimize
        from flexflow_tpu.search.unity import (machine_to_json,
                                               serialize_graph)

        if not available():
            pytest.skip("native search unavailable")
        # 16-layer MLP, batch 256, width 1024: each activation 1 MB,
        # params 4 MB/layer (f32)
        ff = FFModel(FFConfig(batch_size=256))
        t = ff.create_tensor((256, 1024))
        L = 16
        for i in range(L):
            t = ff.dense(t, 1024, name=f"fc{i}")
        ff.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
        nodes = serialize_graph(ff.executor.nodes)
        act = 256 * 1024 * 4          # 1 MB per layer output
        params = L * (1024 * 1024 + 1024) * 4
        act_sum = L * act
        # single device: threshold admits params + a few live activations
        # but NOT params + all activations (the old sum model's estimate)
        threshold = params + 4 * act
        assert threshold < params + act_sum
        machine = machine_to_json(
            MachineSpec(chip="tpu-v4", chips_per_slice=1), 1)
        r = native_optimize(dict(
            nodes=nodes, machine=machine, measured={},
            config=dict(budget=0, alpha=0.05, overlap=True, batch=256,
                        opt_state_factor=0.0, seed=42, rules=[],
                        training=False, memory_threshold=threshold)))
        assert "error" not in r, r
        assert r["predicted_memory"] <= threshold
        # and the liveness peak is far below the sum model's estimate
        assert r["predicted_memory"] < params + act_sum

    def test_training_keeps_residual_sum(self):
        from flexflow_tpu import FFConfig, FFModel, LossType
        from flexflow_tpu.machine import MachineSpec
        from flexflow_tpu.search.native import available, native_optimize
        from flexflow_tpu.search.unity import (machine_to_json,
                                               serialize_graph)

        if not available():
            pytest.skip("native search unavailable")
        ff = FFModel(FFConfig(batch_size=256))
        t = ff.create_tensor((256, 1024))
        for i in range(16):
            t = ff.dense(t, 1024, name=f"fc{i}")
        ff.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
        nodes = serialize_graph(ff.executor.nodes)
        machine = machine_to_json(
            MachineSpec(chip="tpu-v4", chips_per_slice=1), 1)
        cfgd = dict(budget=0, alpha=0.05, overlap=True, batch=256,
                    opt_state_factor=0.0, seed=42, rules=[])
        r_train = native_optimize(dict(nodes=nodes, machine=machine,
                                       measured={},
                                       config=dict(cfgd, training=True)))
        r_inf = native_optimize(dict(nodes=nodes, machine=machine,
                                     measured={},
                                     config=dict(cfgd, training=False)))
        # training must charge all 16 saved activations; inference peaks
        # at a couple of live ones
        assert r_train["predicted_memory"] > r_inf["predicted_memory"] + \
            10 * 256 * 1024 * 4


class TestArbitraryDcnTopology:
    """Arbitrary inter-slice fabric (VERDICT r3 Missing #6; the reference
    NetworkedMachineModel's adjacency-matrix + ECMP role,
    simulator.h:515 + network.cc): explicit slice-pair links reduce to
    the cross-slice ring's bottleneck bandwidth and routed latency."""

    def test_line_topology_routes_and_bottlenecks(self):
        from flexflow_tpu.machine import MachineSpec

        # 4 slices in a line 0-1-2-3: ring pair (3,0) routes 3 hops;
        # middle link is the 10 GB/s bottleneck
        spec = MachineSpec(chip="tpu-v4", chips_per_slice=4, num_slices=4,
                           dcn_links=[(0, 1, 50e9), (1, 2, 10e9),
                                      (2, 3, 50e9)])
        bw, lat = spec.effective_dcn()
        assert bw == 10e9
        assert lat == spec.dcn_latency * 3  # the routed (3,0) pair
        # uniform fabric unchanged
        uni = MachineSpec(chip="tpu-v4", chips_per_slice=4, num_slices=4)
        assert uni.effective_dcn() == (uni.dcn_bw, uni.dcn_latency)

    def test_machine_file_dcn_links(self, tmp_path):
        from flexflow_tpu.machine import MachineSpec

        p = tmp_path / "fabric.cfg"
        p.write_text("chip = tpu-v4\n"
                     "chips_per_slice = 4\n"
                     "num_slices = 3\n"
                     "dcn_link = 0 1 40e9\n"
                     "dcn_link = 1 2 5e9\n"
                     "dcn_link = 2 0 40e9\n")
        spec = MachineSpec.from_file(str(p))
        assert spec.dcn_links == [(0, 1, 40e9), (1, 2, 5e9), (2, 0, 40e9)]
        bw, lat = spec.effective_dcn()
        assert bw == 5e9 and lat == spec.dcn_latency

    def test_weak_fabric_flips_search_strategy(self):
        """A weak bottleneck link must steer the search exactly like a
        uniformly-slow DCN does (the existing dcn_bw flip test, but the
        slowness now comes from one link in an explicit fabric)."""
        from flexflow_tpu.machine import MachineSpec
        from flexflow_tpu.search.unity import machine_to_json

        def optimize(links):
            spec = MachineSpec(chip="tpu-v4", chips_per_slice=4,
                               num_slices=2, dcn_links=links)
            nodes = mlp_graph(b=4096, d=4096, h=4096)
            return native_optimize({
                "machine": machine_to_json(spec, 8),
                "config": _cfg(budget=2, batch=4096,
                               enable_substitution=False),
                "measured": {}, "nodes": nodes, "final": [3, 0]})

        fast = optimize([(0, 1, 25e9)])
        slow = optimize([(0, 1, 0.3e9)])
        assert slow["predicted_time"] > fast["predicted_time"]

    def test_partial_span_prices_its_own_links(self):
        """ISSUE 20 satellite: machine_to_json ships the RAW per-pair
        link matrix and the native pricer (MachineModel::dcn_ring)
        bottlenecks over the slices a collective actually SPANS. A
        dp=2 x mp=4 sync on a 4-slice line fabric crosses only the
        0-1 pair: the far 1-2/2-3 links must not move the price (the
        old global collapse charged their bottleneck), while slowing
        the near 0-1 link must."""
        machine16 = dict(MACHINE, num_devices=16, num_slices=4)

        def sim(links):
            nodes = mlp_graph(b=4096, d=4096, h=4096)
            return native_simulate({
                "machine": dict(machine16, dcn_links=links),
                "config": _cfg(budget=0), "measured": {}, "nodes": nodes,
                "mesh": {"data": 2, "model": 4, "seq": 1, "expert": 1},
                "assignment": {"1": "dp_col", "2": "dp", "3": "dp_row"},
            })["iteration_time"]

        near_fast = sim([[0, 1, 50e9], [1, 2, 1e9], [2, 3, 50e9]])
        near_only = sim([[0, 1, 50e9]])
        near_slow = sim([[0, 1, 1e9], [1, 2, 50e9], [2, 3, 50e9]])
        assert near_fast == near_only  # far links priced out of the span
        assert near_slow > near_fast * 1.02


class TestMemoryValidation:
    """SURVEY §7 hard part 4 / VERDICT r4 #6: predicted-vs-actual memory."""

    def _small_searched(self):
        from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer

        cfg = FFConfig(batch_size=32, search_budget=2,
                       enable_parameter_parallel=True)
        ff = FFModel(cfg)
        t = ff.create_tensor((32, 64))
        h = ff.dense(t, 256, name="h1")
        h = ff.relu(h)
        ff.dense(h, 64, name="h2")
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        return ff

    def test_predicted_vs_actual_memory(self):
        from flexflow_tpu.search.validate import predicted_vs_actual_memory

        ff = self._small_searched()
        r = predicted_vs_actual_memory(ff)
        assert r["predicted"] > 0 and r["actual"] > 0
        # same order of magnitude: the simulator models params + opt
        # state + residuals; XLA adds layout padding and fused temps
        assert 0.2 < r["ratio"] < 5.0, r

    def test_unsearched_model_is_rejected(self):
        from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
        from flexflow_tpu.search.validate import predicted_vs_actual_memory

        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 16))
        ff.dense(t, 4)
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        with pytest.raises(ValueError, match="search-compiled"):
            predicted_vs_actual_memory(ff)

    def test_threshold_applies_calibrated_correction(self, tmp_path,
                                                     monkeypatch):
        """A calibrated actual/predicted memory ratio of 2.0 must halve
        the threshold the DP searches against (the chip has to fit the
        ACTUAL bytes, not the simulator's estimate)."""
        import json as _json

        from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
        from flexflow_tpu.search import native as native_mod
        from flexflow_tpu.search import unity

        ff = FFModel(FFConfig(batch_size=32))
        t = ff.create_tensor((32, 16))
        ff.dense(t, 8)
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])

        cal = tmp_path / "cal.json"
        cal.write_text(_json.dumps({"results": [
            {"model": "a", "mem_ratio": 2.0},
            {"model": "b", "mem_ratio": 2.0}]}))
        monkeypatch.setenv("FFS_CALIBRATION_FILE", str(cal))
        assert unity._memory_correction() == 2.0

        captured = {}

        def fake(req):
            captured.update(req)
            raise RuntimeError("captured")

        monkeypatch.setattr(native_mod, "native_optimize", fake)
        cfg = FFConfig(batch_size=32, search_budget=2, memory_search=True,
                       memory_threshold_mb=100)
        with pytest.raises(RuntimeError, match="captured"):
            unity.graph_optimize(ff.executor.nodes, ff.machine_spec, cfg, 8,
                                 batch=32)
        assert captured["config"]["memory_threshold"] == \
            100 * (1 << 20) / 2.0

        # no calibration file -> correction 1.0, threshold unscaled
        monkeypatch.setenv("FFS_CALIBRATION_FILE", str(tmp_path / "no.json"))
        assert unity._memory_correction() == 1.0


class TestShapeAwareMxuEfficiency:
    """VERDICT r4 Weak #4 (second half): the flat mxu_efficiency scalar
    becomes shape-aware — matmul dims that pad past a 128-tile boundary
    price the wasted tiles; memory-bound skinny matmuls stay governed by
    the HBM roofline where tile fill is irrelevant."""

    def _per_flop(self, b, d):
        node = {"guid": 1, "type": "LINEAR", "name": "l",
                "inputs": [[-1, 0]], "input_shapes": [[b, d]],
                "output_shapes": [[b, d]],
                "roles": [["sample", "channel"]],
                "params": {"kernel": [d, d], "bias": [d]},
                "flops": 2.0 * b * d * d, "dtype_size": 2, "attrs": {}}
        resp = native_optimize({
            "machine": MACHINE,
            "config": _cfg(budget=0, only_data_parallel=True),
            "measured": {}, "nodes": [node]})
        return resp["predicted_time"] / (2.0 * b * d * d)

    def test_tile_misalignment_prices_wasted_tiles(self):
        # 1025 pads to 9x128 tiles at 89% fill per dim; compute-bound at
        # this size, so the per-flop cost must rise ~ (1/0.89)^2
        ratio = self._per_flop(16384, 1025) / self._per_flop(16384, 1024)
        assert ratio > 1.10, ratio

    def test_memory_bound_shapes_ignore_tile_fill(self):
        # 160-wide at 64k rows is HBM-bound: tile fill is irrelevant and
        # the roofline max() must keep the padding penalty invisible
        ratio = self._per_flop(65536, 160) / self._per_flop(65536, 128)
        assert ratio < 1.05, ratio

    def test_aligned_shapes_reproduce_flat_model(self):
        # exact multiples of 128 must price exactly as the r4 flat model
        from flexflow_tpu.search.native import native_simulate

        node = {"guid": 1, "type": "LINEAR", "name": "l",
                "inputs": [[-1, 0]], "input_shapes": [[4096, 1024]],
                "output_shapes": [[4096, 1024]],
                "roles": [["sample", "channel"]],
                "params": {"kernel": [1024, 1024], "bias": [1024]},
                "flops": 2.0 * 4096 * 1024 * 1024, "dtype_size": 2,
                "attrs": {}}
        resp = native_simulate({
            "machine": dict(MACHINE, num_devices=1),
            "config": dict(training=True, overlap=True,
                           opt_state_factor=0.0),
            "mesh": dict(data=1, model=1, seq=1, expert=1),
            "assignment": {"1": "rep"}, "measured": {},
            "nodes": [node]})
        flop = 2.0 * 4096 * 1024 * 1024
        io_bytes = (2 * 4096 * 1024 + 1024 * 1024 + 1024) * 2
        flat_fwd = max(flop / (MACHINE["flops"] * 0.55),
                       io_bytes / MACHINE["hbm_bw"]) + 5e-7
        assert resp["fwd_time"] == pytest.approx(flat_fwd, rel=1e-6)

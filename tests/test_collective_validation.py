"""Priced-vs-emitted collective validation (VERDICT r3 Next #3).

For a searched/selected strategy on the 8-device virtual mesh, the
collectives in the compiled SPMD HLO must be the set the native simulator
charged: nothing XLA inserted goes unpriced (beyond the tolerance), and
nothing priced vanishes. SURVEY §7 hard-part 3 — the failure mode where a
strategy's predicted win evaporates because GSPMD inserted collectives
the search never costed.
"""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, LossType, SGDOptimizer
from flexflow_tpu.models.transformer import (TransformerConfig,
                                             create_transformer)
from flexflow_tpu.search.native import available
from flexflow_tpu.search.validate import (diff_collectives,
                                          emitted_collectives,
                                          priced_collectives,
                                          train_step_hlo)

pytestmark = pytest.mark.skipif(not available(),
                                reason="native search unavailable")


def _compile_transformer(ff_config, mesh=None, **cfg_kw):
    cfg = TransformerConfig(**dict(
        dict(num_layers=2, hidden_size=128, num_heads=4, seq_length=64,
             batch_size=16), **cfg_kw))
    ff = create_transformer(cfg, ff_config)
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [], mesh=mesh)
    return ff


class TestCollectiveValidation:
    def test_tensor_parallel_strategy(self):
        """Searched dp x mp strategy: every emitted collective is priced."""
        c = FFConfig(batch_size=16, seed=7)
        c.search_budget = 4
        c.enable_parameter_parallel = True
        c.enable_pipeline_parallel = False
        ff = _compile_transformer(c)
        axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
        assert axes.get("model", 1) > 1, f"expected a TP strategy, got {axes}"
        emitted = emitted_collectives(train_step_hlo(ff))
        priced = priced_collectives(ff)
        assert emitted, "TP strategy must emit collectives"
        assert priced.get("allreduce", 0) > 0
        problems = diff_collectives(priced, emitted)
        assert not problems, "\n".join(problems)

    def test_seq_parallel_strategy(self):
        """Ring attention over the seq axis: the emitted
        collective-permutes are covered by the priced K/V rotation."""
        from flexflow_tpu.machine import make_mesh

        c = FFConfig(batch_size=16, seed=7)
        ff = _compile_transformer(c, mesh=make_mesh(8, {"data": 2,
                                                        "seq": 4}),
                                  seq_parallel="seq")
        emitted = emitted_collectives(train_step_hlo(ff))
        priced = priced_collectives(ff)
        assert emitted.get("ppermute", 0) > 0, (
            f"ring attention must emit collective-permute, got {emitted}")
        problems = diff_collectives(priced, emitted)
        assert not problems, "\n".join(problems)

    def test_unpriced_collective_is_flagged(self):
        """The checker itself must alert when XLA emits a kind the
        simulator never charged."""
        problems = diff_collectives(
            priced={"allreduce": 1e6},
            emitted={"allreduce": 1e6, "ppermute": 5e6})
        assert any("ppermute" in p and "priced none" in p for p in problems)

    def test_overpriced_collective_is_flagged(self):
        problems = diff_collectives(
            priced={"allreduce": 10e6, "ppermute": 8e6},
            emitted={"allreduce": 1e6})
        assert any("emitted none" in p for p in problems)

"""Keras frontend (SURVEY §2.6): Sequential + functional Model + callbacks.

Mirrors the reference's Keras examples
(examples/python/keras/func_mnist_mlp.py, seq_cifar10_cnn.py style).
"""

import numpy as np
import pytest

from flexflow_tpu.keras import Model, Sequential
from flexflow_tpu.keras.backend import to_categorical
from flexflow_tpu.keras.callbacks import EarlyStopping, History
from flexflow_tpu.keras.layers import (Activation, Add, Concatenate, Conv2D,
                                       Dense, Dropout, Flatten, Input,
                                       MaxPooling2D)
from flexflow_tpu.keras.optimizers import SGD


def blobs(n=512, d=16, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(classes, d) * 3
    y = rs.randint(0, classes, n)
    x = (centers[y] + rs.randn(n, d)).astype(np.float32)
    return x, y.astype(np.int32).reshape(-1, 1)


class TestSequential:
    def test_mlp_trains(self):
        x, y = blobs()
        model = Sequential([
            Input((16,)),
            Dense(64, activation="relu"),
            Dense(4, activation="softmax"),
        ])
        model.compile(optimizer=SGD(learning_rate=0.1),
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"], batch_size=64)
        model.fit(x, y, epochs=4, verbose=False)
        rep = model.evaluate(x, y, verbose=False)
        assert rep["accuracy"] > 0.9

    def test_cnn_runs(self):
        rs = np.random.RandomState(0)
        x = rs.randn(32, 1, 12, 12).astype(np.float32)
        y = rs.randint(0, 3, (32, 1)).astype(np.int32)
        model = Sequential([
            Input((1, 12, 12)),
            Conv2D(4, 3, activation="relu"),
            MaxPooling2D(2),
            Flatten(),
            Dense(3, activation="softmax"),
        ])
        model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"], batch_size=16)
        model.fit(x, y, epochs=1, verbose=False)
        preds = model.predict(x)
        assert preds.shape == (32, 3)

    def test_categorical_loss(self):
        x, y = blobs()
        y1h = to_categorical(y, 4)
        model = Sequential([Input((16,)), Dense(4, activation="softmax")])
        model.compile(optimizer=SGD(0.1), loss="categorical_crossentropy",
                      metrics=["accuracy"], batch_size=64)
        model.fit(x, y1h, epochs=3, verbose=False)
        rep = model.evaluate(x, y1h, verbose=False)
        assert rep["accuracy"] > 0.8


class TestFunctional:
    def test_branches_and_merge(self):
        x, y = blobs()
        inp = Input((16,))
        a = Dense(32, activation="relu")(inp)
        b = Dense(32, activation="tanh")(inp)
        h = Add()([a, b])
        h2 = Concatenate(axis=-1)([h, a])
        out = Dense(4, activation="softmax")(h2)
        model = Model(inputs=inp, outputs=out)
        model.compile(optimizer=SGD(0.1),
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"], batch_size=64)
        model.fit(x, y, epochs=4, verbose=False)
        rep = model.evaluate(x, y, verbose=False)
        assert rep["accuracy"] > 0.9

    def test_callbacks_early_stopping(self):
        x, y = blobs()
        model = Sequential([Input((16,)), Dense(4, activation="softmax")])
        model.compile(optimizer=SGD(0.05),
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"], batch_size=64)
        hist = History()
        es = EarlyStopping(monitor="loss", patience=0, min_delta=10.0)
        h = model.fit(x, y, epochs=10, callbacks=[hist, es], verbose=False)
        # min_delta=10 means "never improves": first epoch sets best, second
        # trips patience=0 -> exactly 2 epochs ran and loss was logged
        assert len(hist.history["loss"]) == 2
        assert len(h["loss"]) == 2

    def test_predict_handles_remainder(self):
        x, y = blobs(n=100)
        model = Sequential([Input((16,)), Dense(4)])
        model.compile(optimizer="sgd", loss="mse", batch_size=64)
        out = model.predict(x)  # 100 = 64 + tail of 36
        assert out.shape == (100, 4)

    def test_get_set_weights(self):
        x, y = blobs()
        model = Sequential([Input((16,)), Dense(4, name="dense_out")])
        model.compile(optimizer="sgd", loss="mse", batch_size=64)
        layer = model.layers[-1]
        (k, b) = layer.get_weights()
        assert k.shape == (16, 4)
        layer.set_weights([np.ones_like(k), np.zeros_like(b)])
        out = model.predict(x[:64])
        np.testing.assert_allclose(out[:, 0], x[:64].sum(axis=1), rtol=1e-4)


class TestDatasets:
    def test_mnist_shapes(self):
        from flexflow_tpu.keras.datasets import mnist

        (x_tr, y_tr), (x_te, y_te) = mnist.load_data()
        assert x_tr.shape[1:] == (28, 28)
        assert x_tr.dtype == np.uint8
        assert len(x_tr) == len(y_tr)

"""Test configuration: force an 8-device virtual CPU mesh.

Analog of the reference's testing gap fix (SURVEY §4): JAX's CPU backend
with xla_force_host_platform_device_count gives a free "fake TPU slice" so
every functional + sharding test runs devicelessly.
"""

import os

# Must run before jax initializes a backend. The sandbox pins
# JAX_PLATFORMS=axon (TPU tunnel); jax.config.update overrides it.
os.environ.pop("JAX_PLATFORMS", None)
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)

"""Search provenance & simulator observability (ISSUE 8 tentpole).

Covers the three layers the tentpole added:

- the native structured search trace (``emit_search_trace``): schema
  version, per-mesh candidate rows with rejection reasons, frontier-DP
  evolution arithmetic, per-op candidate-choice cost decomposition;
- the simulated-schedule layer (``obs/simtrace.py``): sim: Perfetto
  lanes next to the measured device lanes, the ``.simtrace.json``
  artifact, and the learned-cost-model corpus row join
  (op -> priced terms -> measured seconds — the acceptance row);
- ``scripts/explain.py``: SEARCH_TRACE.json + EXPLAIN.md + a merged
  Perfetto trace carrying a ``sim:`` lane.
"""

from __future__ import annotations

import glob
import importlib.util
import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.search


def _tiny_graph_nodes():
    """Two stacked Linears over a [32, 64] input — enough for dp / col /
    row / wus choices and a dominated frontier."""
    roles = [["sample", "channel"]]
    return [
        dict(guid=1, type="INPUT", name="x", inputs=[], input_shapes=[],
             output_shapes=[[32, 64]], roles=roles, params={},
             flops=0.0, dtype_size=4, attrs={}),
        dict(guid=2, type="LINEAR", name="dense1", inputs=[[1, 0]],
             input_shapes=[[32, 64]], output_shapes=[[32, 128]],
             roles=roles, params={"kernel": [64, 128], "bias": [128]},
             flops=32 * 64 * 128 * 2.0, dtype_size=4, attrs={}),
        dict(guid=3, type="LINEAR", name="dense2", inputs=[[2, 0]],
             input_shapes=[[32, 128]], output_shapes=[[32, 10]],
             roles=roles, params={"kernel": [128, 10], "bias": [10]},
             flops=32 * 128 * 10 * 2.0, dtype_size=4, attrs={}),
    ]


def _native_request(**config):
    cfg = dict(budget=1, training=True, enable_substitution=False,
               batch=32)
    cfg.update(config)
    return dict(
        nodes=_tiny_graph_nodes(),
        machine=dict(num_devices=8, flops=1e12, hbm_bw=1e11, hbm_cap=16e9,
                     ici_bw=1e10, ici_latency=1e-6, dcn_bw=1e9,
                     dcn_latency=1e-5, num_slices=1, mxu_efficiency=0.55,
                     conv_efficiency=0.35, min_op_time=5e-7,
                     comm_bytes_factor=1.0, torus=[]),
        config=cfg,
        measured={},
    )


class TestNativeSearchTrace:
    @pytest.fixture(scope="class")
    def trace(self):
        from flexflow_tpu.search.native import native_optimize
        resp = native_optimize(_native_request(emit_search_trace=True))
        assert "search_trace" in resp
        return resp["search_trace"]

    def test_schema_versioned(self, trace):
        assert trace["schema_version"] == 1
        assert trace["graph"] in ("original", "rewritten")
        assert trace["config"]["training"] is True

    def test_off_by_default(self):
        from flexflow_tpu.search.native import native_optimize
        resp = native_optimize(_native_request())
        assert "search_trace" not in resp

    def test_mesh_rows_carry_rejection_reasons(self, trace):
        statuses = {}
        for row in trace["meshes"]:
            statuses.setdefault(row["status"], []).append(row)
            if row["status"] != "winner":
                assert row.get("reason"), row
        # exactly one winner; dominated rows priced end-to-end; illegal
        # factorizations name the legality gate that rejected them
        assert len(statuses["winner"]) == 1
        assert statuses["dominated"]
        for row in statuses["dominated"]:
            assert row["time_s"] > statuses["winner"][0]["time_s"]
            assert row["reason"] == "slower_than_winner"
        for row in statuses.get("illegal", []):
            assert row["reason"] in (
                "parameter_parallel_disabled", "only_data_parallel",
                "no_seq_dim", "seq_extent_indivisible", "no_expert_ops",
                "experts_indivisible", "pipeline_disabled",
                "no_repeated_blocks", "pipe_composes_with_dp_only",
                "blocks_indivisible_by_stages", "batch_indivisible_by_dp",
                "pinned_axis_extent_mismatch", "inner_axes_cross_slice")

    def test_winner_matches_response_mesh(self, trace):
        from flexflow_tpu.search.native import native_optimize
        resp = native_optimize(_native_request(emit_search_trace=True))
        assert resp["search_trace"]["winner_mesh"] == resp["mesh"]

    def test_dp_evolution_arithmetic(self, trace):
        evo = trace["dp_evolution"]
        assert len(evo) == len(_tiny_graph_nodes())
        for row in evo:
            assert row["expanded"] == row["states_in"] * row["choices"]
            assert (row["unique_frontiers"] + row["pruned_dominated"]
                    == row["expanded"])
            assert (row["kept"] + row["pruned_alpha"] + row["pruned_beam"]
                    == row["unique_frontiers"])
            assert row["kept"] >= 1
            assert row["best_cost"] >= 0

    def test_per_op_candidates_decomposed(self, trace):
        ops = {o["name"]: o for o in trace["ops"]}
        d1 = ops["dense1"]
        names = [c["choice"] for c in d1["candidates"]]
        assert "rep" in names
        chosen = [c for c in d1["candidates"] if c["chosen"]]
        assert len(chosen) == 1
        assert chosen[0]["choice"] == d1["chosen"]
        for c in d1["candidates"]:
            t = c["terms"]
            for key in ("fwd_s", "bwd_s", "compute_s", "comm_s",
                        "gradsync_s", "collective_s", "opt_state_s",
                        "total_s"):
                assert key in t
            assert t["compute_s"] == pytest.approx(t["fwd_s"] + t["bwd_s"])
            assert t["collective_s"] == pytest.approx(
                t["comm_s"] + t["gradsync_s"])
            assert t["total_s"] == pytest.approx(
                t["compute_s"] + t["collective_s"] + t["opt_state_s"],
                rel=1e-6)
            m = c["memory"]
            assert m["param_bytes"] >= 0
            assert m["opt_state_bytes"] >= 0
            assert m["act_bytes"] >= 0

    def test_choice_collectives_described(self, trace):
        # SOME candidate on the winning mesh implies wire traffic, and
        # every implied collective names its kind/bytes/ring/cause
        described = [e for o in trace["ops"]
                     for c in o["candidates"]
                     for e in c["collectives"]]
        assert described
        for e in described:
            assert e["kind"] in ("allreduce", "allgather", "ppermute")
            assert e["bytes"] > 0
            assert e["ring"] > 1
            assert e["cause"]

    def test_dp_mesh_gradsync_and_wus_collectives(self):
        """On a data-parallel mesh the dp choice implies the gradient
        all-reduce and its _wus twin the reduce-scatter + param
        all-gather pair — the collective column of the explain table."""
        from flexflow_tpu.search.native import native_optimize
        resp = native_optimize(_native_request(
            emit_search_trace=True, only_data_parallel=True))
        tr = resp["search_trace"]
        assert tr["winner_mesh"]["data"] == 8
        ops = {o["name"]: o for o in tr["ops"]}
        cands = {c["choice"]: c for c in ops["dense1"]["candidates"]}
        dp = cands["dp"]
        assert {e["cause"] for e in dp["collectives"]} == \
            {"grad_allreduce"}
        wus = cands["dp_wus"]
        assert {e["cause"] for e in wus["collectives"]} == \
            {"grad_reduce_scatter", "wus_param_allgather"}
        # WUS shards the optimizer state over the gradient ring: its
        # memory row must show the shrink the DP weighed
        assert (wus["memory"]["opt_state_bytes"]
                < dp["memory"]["opt_state_bytes"])


class TestSimLaneEvents:
    def test_lanes_and_zero_duration_filter(self):
        from flexflow_tpu.obs.simtrace import (SIM_TID_COMMS,
                                               SIM_TID_COMPUTE,
                                               sim_lane_events)
        tasks = [
            dict(kind="fwd", node=0, start=0.0, finish=1e-3),
            dict(kind="gradsync", node=0, start=1e-3, finish=2e-3,
                 collective="allreduce", bytes=4096),
            dict(kind="comm", node=1, start=0.0, finish=0.0,
                 collective="ppermute", bytes=128),  # census-only record
        ]
        evs = sim_lane_events(tasks, {0: "dense1", 1: "dense2"},
                              t0_us=100.0)
        assert len(evs) == 2  # zero-duration census row skipped
        fwd, gs = evs
        assert fwd["name"] == "dense1:fwd"
        assert fwd["tid"] == SIM_TID_COMPUTE
        assert fwd["ts"] == pytest.approx(100.0)
        assert fwd["dur"] == pytest.approx(1e3)
        assert gs["tid"] == SIM_TID_COMMS
        assert gs["args"]["collective"] == "allreduce"
        assert gs["ts"] == pytest.approx(100.0 + 1e3)


@pytest.fixture(scope="module")
def searched_mlp():
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType
    from flexflow_tpu.models.mlp import create_mlp
    from flexflow_tpu.optimizers import SGDOptimizer

    cfg = FFConfig(batch_size=16)
    cfg.search_budget = 2
    cfg.enable_parameter_parallel = True
    cfg.enable_pipeline_parallel = False
    cfg.search_trace = True
    ff = create_mlp(batch_size=16, in_dim=64, hidden_dims=(128, 128),
                    out_dim=10, ff_config=cfg)
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


class TestCorpusRows:
    def test_row_joins_op_priced_measured(self, searched_mlp):
        """Acceptance: one corpus row joins op identity (class, shape,
        sharding choice) -> the simulator's priced terms -> measured
        per-op seconds — the learned-TPU-cost-model training format."""
        from flexflow_tpu.obs.simtrace import corpus_rows
        from flexflow_tpu.search.validate import simulate_strategy

        ff = searched_mlp
        resp = simulate_strategy(ff)
        # a measured table as --profiling / --search-measure-ops builds
        guid = ff.executor.nodes[1].op.guid
        measured = {f"{guid}:fwd": 3.1e-4, f"{guid}:bwd": 6.2e-4}
        rows = corpus_rows(ff, resp, measured=measured)
        assert len(rows) == len(ff.executor.nodes)
        by_guid = {r["guid"]: r for r in rows}
        row = by_guid[guid]
        # op identity
        assert row["type"] == "LINEAR"
        assert row["out_shape"]
        assert row["choice"]  # the searched sharding choice
        # priced terms from the simulated schedule
        assert row["priced"]["fwd_s"] > 0
        assert row["priced"]["bwd_s"] > 0
        # measured seconds + provenance
        assert row["measured"]["fwd_s"] == pytest.approx(3.1e-4)
        assert row["measured"]["bwd_s"] == pytest.approx(6.2e-4)
        assert row["measured"]["source"] == "measured"
        # an op absent from the table is priced-only, source None
        other = next(r for r in rows if r["guid"] != guid
                     and r["type"] == "LINEAR")
        assert other["measured"]["source"] is None

    def test_searched_choice_recorded(self, searched_mlp):
        from flexflow_tpu.obs.simtrace import corpus_rows
        from flexflow_tpu.search.validate import simulate_strategy

        rows = corpus_rows(searched_mlp, simulate_strategy(searched_mlp))
        choices = {r["choice"] for r in rows if r["type"] == "LINEAR"}
        assert choices  # the strategy's choice names ride along


class TestSearchedFitArtifacts:
    @pytest.fixture(scope="class")
    def traced_fit(self, tmp_path_factory):
        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.ffconst import LossType
        from flexflow_tpu.models.mlp import create_mlp
        from flexflow_tpu.optimizers import SGDOptimizer

        td = str(tmp_path_factory.mktemp("searchtrace"))
        cfg = FFConfig(batch_size=16)
        cfg.search_budget = 2
        cfg.enable_parameter_parallel = True
        cfg.enable_pipeline_parallel = False
        cfg.search_trace = True
        ff = create_mlp(batch_size=16, in_dim=64, hidden_dims=(128, 128),
                        out_dim=10, ff_config=cfg)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
        rs = np.random.RandomState(0)
        x = rs.randn(64, 64).astype(np.float32)
        y = rs.randint(0, 10, size=(64, 1)).astype(np.int32)
        ff.fit(x, y, epochs=1, verbose=False, trace_dir=td)
        return td, ff

    def _one(self, td, pattern):
        paths = glob.glob(os.path.join(td, pattern))
        assert len(paths) == 1, f"{pattern}: {paths}"
        return paths[0]

    def test_searchtrace_artifact(self, traced_fit):
        td, ff = traced_fit
        st = json.load(open(self._one(td, "fit_*.searchtrace.json")))
        assert st["schema_version"] == 1
        assert st["header"]["kind"] == "searchtrace"
        mesh = st["winner_mesh"]
        axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
        for k, v in axes.items():
            assert mesh.get(k, 1) == v
        assert any(o["candidates"] for o in st["ops"])

    def test_simtrace_artifact(self, traced_fit):
        td, _ = traced_fit
        sim = json.load(open(self._one(td, "fit_*.simtrace.json")))
        assert sim["predicted"]["step_s"] > 0
        assert sim["tasks"] > 0
        assert sim["per_op"]
        for r in sim["per_op"]:
            assert "priced" in r and "measured" in r

    def test_sim_lanes_in_perfetto_trace(self, traced_fit):
        td, _ = traced_fit
        trace = json.load(open(self._one(td, "fit_*.trace.json")))
        events = trace["traceEvents"]
        lanes = {e["args"]["name"] for e in events
                 if e.get("name") == "thread_name"}
        assert {"sim:compute", "sim:comms"} <= lanes
        sim = [e for e in events if e.get("cat") == "simtrace"]
        assert sim
        # aligned onto the tracer timeline: the sim step starts at a
        # traced step's start
        steps = [e["ts"] for e in events
                 if e.get("name") == "step" and e.get("ph") == "X"]
        assert min(e["ts"] for e in sim) == pytest.approx(
            max(steps), abs=1e3)

    def test_merged_trace_keeps_sim_lanes(self, traced_fit):
        td, _ = traced_fit
        from flexflow_tpu.obs import merge_host_traces
        data = json.load(open(merge_host_traces(td)))
        labels = {e["args"]["name"] for e in data["traceEvents"]
                  if e.get("name") == "thread_name"}
        assert any(l.endswith(":sim:compute") for l in labels)
        assert any(l.endswith(":sim:comms") for l in labels)


class TestExplainCLI:
    def test_explain_end_to_end(self, tmp_path, monkeypatch):
        spec = importlib.util.spec_from_file_location(
            "explain_cli", os.path.join(REPO, "scripts", "explain.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        out = str(tmp_path / "out")
        monkeypatch.setattr(sys, "argv", [
            "explain.py", "--model", "mlp", "--budget", "1",
            "--out-dir", out])
        assert mod.main() == 0
        st = json.load(open(os.path.join(out, "SEARCH_TRACE.json")))
        assert st["search_trace"]["schema_version"] == 1
        assert st["corpus"]  # learned-cost-model rows ride along
        assert st["corpus"][0]["priced"]
        md = open(os.path.join(out, "EXPLAIN.md")).read()
        assert "Chosen vs runner-up" in md
        assert "Mesh candidates" in md
        assert "Simulated timeline path" in md
        merged = json.load(open(st["merged_trace"]))
        labels = {e["args"]["name"] for e in merged["traceEvents"]
                  if e.get("name") == "thread_name"}
        assert any("sim:compute" in l for l in labels)

"""Auxiliary subsystems (SURVEY §5): dataloader, checkpoint/resume,
recompile-on-condition, dot export, recursive logger."""

import os

import numpy as np
import pytest

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          RecompileState, SGDOptimizer, create_data_loaders)
from flexflow_tpu.ffconst import ActiMode


def blobs(n=256, d=16, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(classes, d) * 3
    y = rs.randint(0, classes, n)
    x = (centers[y] + rs.randn(n, d)).astype(np.float32)
    return x, y.astype(np.int32).reshape(-1, 1)


def small_model(batch=64, d=16, budget=0, hidden=32):
    cfg = FFConfig(batch_size=batch, search_budget=budget)
    ff = FFModel(cfg)
    t = ff.create_tensor((batch, d))
    h = ff.dense(t, hidden, activation=ActiMode.AC_MODE_RELU, name="h1")
    out = ff.dense(h, 4, name="out")
    out = ff.softmax(out)
    ff.compile(SGDOptimizer(lr=0.1), LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.ACCURACY])
    return ff


class TestDataLoader:
    def test_staged_loader_trains(self):
        x, y = blobs()
        ff = small_model()
        loaders = create_data_loaders(ff, x, y)
        assert loaders.num_batches == 4
        ff.fit_loader(loaders, epochs=4, verbose=False)
        rep = ff.evaluate(x, y)
        assert rep["accuracy"] > 0.9

    def test_loader_wraps_and_truncates(self):
        x, y = blobs(n=150)  # 150 -> truncated to 128 = 2 batches
        ff = small_model()
        loaders = create_data_loaders(ff, x, y)
        assert loaders.num_batches == 2
        b1, l1 = loaders.next_batch()
        b2, _ = loaders.next_batch()
        b3, _ = loaders.next_batch()  # wraps to batch 0
        name = ff.executor.input_names[0]
        np.testing.assert_array_equal(np.asarray(b1[name]),
                                      np.asarray(b3[name]))

    def test_host_resident_loader(self):
        x, y = blobs()
        ff = small_model()
        loaders = create_data_loaders(ff, x, y, stage_on_device=False)
        inputs, labels = loaders.next_batch()
        assert inputs[ff.executor.input_names[0]].shape == (64, 16)


class TestCheckpoint:
    def test_roundtrip_resumes_exactly(self, tmp_path):
        x, y = blobs()
        ff = small_model()
        ff.fit(x, y, epochs=2, verbose=False)
        path = str(tmp_path / "ckpt")
        ff.save_checkpoint(path)

        ff2 = small_model()
        it = ff2.load_checkpoint(path)
        assert it == ff._iter
        np.testing.assert_array_equal(
            ff.get_parameter("h1"), ff2.get_parameter("h1"))
        # identical predictions after restore
        np.testing.assert_allclose(ff.predict(x[:64]), ff2.predict(x[:64]),
                                   rtol=1e-6)
        # and training continues
        ff2.fit(x, y, epochs=1, verbose=False)

    def test_shape_mismatch_rejected(self, tmp_path):
        x, y = blobs()
        ff = small_model(hidden=32)
        path = str(tmp_path / "ckpt")
        ff.save_checkpoint(path)
        ff_bigger = small_model(hidden=64)
        with pytest.raises(ValueError, match="shape"):
            ff_bigger.load_checkpoint(path)


class TestRecompile:
    def test_alter_widens_hidden_layer(self):
        x, y = blobs()
        ff = small_model(hidden=32)
        ff.fit(x, y, epochs=1, verbose=False)
        out_kernel_before = ff.get_parameter("out")

        fired = {"n": 0}

        def trigger():
            fired["n"] += 1
            return fired["n"] == 1  # fire exactly once

        def alter(model):
            # widen h1: 32 -> 64 (analog of MoE capacity adaptation)
            h1 = next(l for l in model.layers if l.name == "h1")
            h1.properties["out_dim"] = 64

        rs = RecompileState(trigger, alter, ff)
        assert ff.recompile_on_condition(rs) is True
        assert rs.recompilations == 1
        # h1 got fresh (wider) params; out was re-initialized too since its
        # input dim changed
        assert ff.get_parameter("h1").shape == (16, 64)
        assert ff.get_parameter("out").shape == (64, 4)
        ff.fit(x, y, epochs=1, verbose=False)  # trains after recompile
        # second call: trigger false -> no-op
        assert ff.recompile_on_condition(rs) is False


class TestObservability:
    def test_dot_export(self, tmp_path):
        path = str(tmp_path / "pcg.dot")
        cfg = FFConfig(batch_size=32,
                       export_strategy_computation_graph_file=path,
                       include_costs_dot_graph=True)
        ff = FFModel(cfg)
        t = ff.create_tensor((32, 16))
        h = ff.dense(t, 32, name="d1")
        ff.dense(h, 4, name="d2")
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        dot = open(path).read()
        assert "digraph pcg" in dot
        assert "d1" in dot and "d2" in dot and "->" in dot
        assert "flops" in dot  # include_costs

    def test_recursive_logger_indents(self, capsys):
        import io

        from flexflow_tpu.utils.logger import RecursiveLogger

        buf = io.StringIO()
        log = RecursiveLogger("t", stream=buf)
        log.info("top")
        with log.enter("level1"):
            log.info("inner")
            with log.enter():
                log.info("deepest")
        lines = buf.getvalue().splitlines()
        assert lines[0].endswith("top")
        assert "[1]" in lines[2] and "[2]" in lines[3]


class TestFlagWiring:
    """VERDICT r2 weak #4: every accepted flag must have an observable
    effect (or be gone)."""

    def test_machine_model_file_json(self, tmp_path):
        import json
        from flexflow_tpu.machine import MachineSpec

        path = tmp_path / "machine.json"
        path.write_text(json.dumps({
            "chip": "tpu-v4", "chips_per_slice": 4, "num_slices": 2,
            "dcn_bw": 12.5e9, "min_op_time": 1e-6}))
        spec = MachineSpec.from_file(str(path))
        assert spec.chip == "tpu-v4" and spec.num_slices == 2
        assert spec.dcn_bw == 12.5e9 and spec.min_op_time == 1e-6
        assert spec.flops == 275e12  # v4 datasheet number

    def test_machine_model_file_reference_format(self, tmp_path):
        # the reference's machine_config_example key=value vocabulary
        # (GB/s + ms) maps onto the TPU model: nvlink->ICI, nic->DCN,
        # num_nodes->slices; unknown keys ignored
        from flexflow_tpu.machine import MachineSpec

        path = tmp_path / "machine_config"
        path.write_text("""
# comment
num_nodes = 2
nvlink_latency = 0.001
nvlink_bandwidth = 18.52
nic_bandwidth = 10.9448431
membus_bandwidth = 4.26623
intra_socket_sys_mem_to_sys_mem = membus
""")
        spec = MachineSpec.from_file(str(path))
        assert spec.num_slices == 2
        assert abs(spec.ici_bw - 18.52e9) < 1e6
        assert abs(spec.ici_latency - 1e-6) < 1e-9
        assert abs(spec.dcn_bw - 10.9448431e9) < 1e6

    def test_machine_model_file_flows_into_compile(self, tmp_path):
        import json
        from flexflow_tpu import (FFConfig, FFModel, LossType, SGDOptimizer)

        path = tmp_path / "m.json"
        path.write_text(json.dumps({"chip": "tpu-v5p"}))
        cfg = FFConfig(batch_size=8, machine_model_file=str(path),
                       machine_model_version=1)
        ff = FFModel(cfg)
        t = ff.create_tensor((8, 4))
        ff.dense(t, 2)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        assert ff.machine_spec.chip == "tpu-v5p"

    def test_requested_search_failure_is_fatal(self, monkeypatch):
        """A requested search (--budget N) must hard-error when the
        native core is broken, not silently measure data-parallel
        (VERDICT r4 Weak #6)."""
        from flexflow_tpu import (FFConfig, FFModel, LossType, SGDOptimizer)
        from flexflow_tpu.search import unity

        def boom(*a, **k):
            raise RuntimeError("libffsearch.so exploded")

        monkeypatch.setattr(unity, "graph_optimize", boom)
        cfg = FFConfig(batch_size=8, search_budget=5)
        ff = FFModel(cfg)
        t = ff.create_tensor((8, 16))
        ff.dense(t, 4)
        with pytest.raises(RuntimeError, match="search was requested"):
            ff.compile(SGDOptimizer(lr=0.1),
                       LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])

    def test_perform_fusion_flag_parses(self):
        from flexflow_tpu import FFConfig

        cfg = FFConfig()
        assert cfg.perform_fusion
        rest = cfg.parse_args(["--disable-fusion", "leftover"])
        assert not cfg.perform_fusion and rest == ["leftover"]

    def test_machine_model_version_without_file_rejected(self):
        import pytest as _pytest
        from flexflow_tpu import (FFConfig, FFModel, LossType, SGDOptimizer)

        cfg = FFConfig(batch_size=8, machine_model_version=1)
        ff = FFModel(cfg)
        t = ff.create_tensor((8, 4))
        ff.dense(t, 2)
        with _pytest.raises(ValueError, match="machine-model-file"):
            ff.compile(SGDOptimizer(lr=0.1),
                       LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])

    def test_profiling_flag_produces_op_profile(self):
        from flexflow_tpu import (FFConfig, FFModel, LossType, SGDOptimizer)

        cfg = FFConfig(batch_size=8, profiling=True)
        ff = FFModel(cfg)
        t = ff.create_tensor((8, 4))
        ff.dense(t, 2)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        assert ff.op_profile  # per-op measured fwd/bwd table
        assert any(k.endswith(":fwd") for k in ff.op_profile)

    def test_search_logging_env(self, capsys, monkeypatch):
        from flexflow_tpu import (FFConfig, FFModel, LossType, SGDOptimizer)

        monkeypatch.setenv("FF_LOG_SEARCH", "1")
        cfg = FFConfig(batch_size=8, search_budget=2,
                       enable_parameter_parallel=True)
        ff = FFModel(cfg)
        t = ff.create_tensor((8, 4))
        ff.dense(t, 2)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        err = capsys.readouterr().err
        assert "graph_optimize" in err and "best mesh" in err

    def test_removed_simulator_flags_fall_through(self):
        from flexflow_tpu import FFConfig

        cfg = FFConfig()
        rest = cfg.parse_args(["--simulator-segment-size", "99",
                               "--epochs", "2"])
        assert cfg.epochs == 2
        assert "--simulator-segment-size" in rest
        assert not hasattr(cfg, "simulator_segment_size")


class TestInferenceMode:
    """CompMode.INFERENCE is real (VERDICT r3 Next #6): forward-only
    executable, no opt state, forward-only cost model in the search."""

    def test_inference_compile_allocates_no_opt_state(self):
        import numpy as np
        from flexflow_tpu import (AdamOptimizer, FFConfig, FFModel,
                                  LossType)
        from flexflow_tpu.ffconst import CompMode

        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 16))
        t = ff.dense(t, 32)
        t = ff.dense(t, 4)
        ff.compile(AdamOptimizer(alpha=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
                   comp_mode=CompMode.INFERENCE)
        assert ff.opt_state is None
        out = ff.predict(np.zeros((8, 16), np.float32))
        assert out.shape == (8, 4)
        with pytest.raises(RuntimeError, match="INFERENCE"):
            ff.fit(np.zeros((8, 16), np.float32),
                   np.zeros((8, 4), np.float32), epochs=1, verbose=False)

    def test_search_picks_lighter_strategy_under_memory_threshold(self):
        """Same graph + tight memory threshold: the training search needs
        param sharding (opt state triples the footprint), the inference
        search fits a plain data-parallel layout."""
        from flexflow_tpu.machine import MachineSpec
        from flexflow_tpu.search.native import available, native_optimize
        from flexflow_tpu.search.unity import (machine_to_json,
                                               serialize_graph)
        from flexflow_tpu import FFConfig, FFModel, LossType

        if not available():
            pytest.skip("native search unavailable")
        ff = FFModel(FFConfig(batch_size=64))
        t = ff.create_tensor((64, 1024))
        for i in range(4):
            t = ff.dense(t, 1024, name=f"fc{i}")
        ff.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
        nodes = serialize_graph(ff.executor.nodes)
        machine = machine_to_json(
            MachineSpec(chip="tpu-v4", chips_per_slice=8), 8)
        # params: 4 x 1024 x 1024 x 4B = 16.8 MB; threshold fits
        # params + activations but NOT 3x params (Adam m+v)
        threshold = 30e6
        base = dict(budget=2, alpha=0.05, overlap=True, batch=64,
                    opt_state_factor=2.0, seed=42, rules=[],
                    enable_parameter_parallel=True,
                    memory_threshold=threshold)
        r_train = native_optimize(dict(
            nodes=nodes, machine=machine, measured={},
            config=dict(base, training=True)))
        r_inf = native_optimize(dict(
            nodes=nodes, machine=machine, measured={},
            config=dict(base, training=False)))
        assert r_inf["predicted_time"] < r_train["predicted_time"]
        train_mesh = {k: v for k, v in r_train["mesh"].items() if v > 1}
        inf_mesh = {k: v for k, v in r_inf["mesh"].items() if v > 1}
        # training: opt state triples the param footprint — the search is
        # forced into heavy model sharding; inference picks a different,
        # less-sharded layout that would NOT fit under training costs
        assert train_mesh.get("model", 1) > inf_mesh.get("model", 1), (
            train_mesh, inf_mesh)
        assert r_inf["predicted_memory"] <= threshold
        assert r_train["predicted_memory"] <= threshold
        # the inference-chosen footprint + opt state would blow the budget
        assert (r_inf["predicted_memory"] +
                2.0 * 16.8e6 / max(1, inf_mesh.get("model", 1))) > threshold

"""Multi-slice DCN hierarchy (ISSUE 16): the two-level machine model,
the ('slice', 'data') runtime mesh, hierarchical collective pricing,
slice-loss resume planning, the fabric-split census — and the
acceptance search: on a simulated 2 x v4-32 the hierarchical search
must pick a DP-over-DCN x hybrid-within-slice strategy that prices
strictly cheaper than the flat-mesh strategy forced onto the same
chips, with the cross-slice collectives visible in the trace."""

import numpy as np
import pytest

from flexflow_tpu.machine import MachineSpec, make_mesh
from flexflow_tpu.multislice import (MultiSliceSpec, multislice_machine_spec,
                                     remap_strategy_for_slices, slice_axes,
                                     slice_of_process, slice_process_groups)


class TestMultiSliceSpec:
    def test_defaults_and_device_count(self):
        s = MultiSliceSpec()
        assert s.num_slices == 2 and s.chips_per_slice == 4
        assert s.num_devices == 8

    def test_validation(self):
        with pytest.raises(ValueError):
            MultiSliceSpec(num_slices=0)
        with pytest.raises(ValueError):
            MultiSliceSpec(chips_per_slice=0)
        with pytest.raises(ValueError):
            MultiSliceSpec(dcn_bw=0.0)

    def test_to_machine_spec_roundtrip(self):
        s = MultiSliceSpec(num_slices=2, chips_per_slice=32, chip="tpu-v4")
        m = s.to_machine_spec()
        assert isinstance(m, MachineSpec)
        assert m.num_slices == 2 and m.chips_per_slice == 32
        back = MultiSliceSpec.from_machine_spec(m)
        assert back.num_slices == 2 and back.chips_per_slice == 32

    def test_slice_of_device(self):
        s = MultiSliceSpec(num_slices=2, chips_per_slice=4)
        assert [s.slice_of_device(i) for i in range(8)] == \
            [0, 0, 0, 0, 1, 1, 1, 1]

    def test_surviving_drops_slices(self):
        s = MultiSliceSpec(num_slices=3, chips_per_slice=4)
        surv = s.surviving([1])
        assert surv.num_slices == 2 and surv.chips_per_slice == 4
        with pytest.raises(ValueError):
            s.surviving([0, 1, 2])  # nobody left

    def test_module_helper(self):
        m = multislice_machine_spec(8, 2)
        assert m.num_slices == 2 and m.chips_per_slice == 4
        with pytest.raises(ValueError):
            multislice_machine_spec(9, 2)  # not divisible


class TestSliceMesh:
    def test_slice_axes_splits_data_outermost(self):
        axes = slice_axes({"data": 8, "model": 2}, 2)
        assert list(axes.items())[0] == ("slice", 2)
        assert axes == {"slice": 2, "data": 4, "model": 2}

    def test_slice_axes_indivisible_raises(self):
        with pytest.raises(ValueError):
            slice_axes({"data": 6}, 4)

    def test_remap_strategy_extends_data_specs(self):
        from flexflow_tpu.parallel.strategy import OpStrategy, P
        st = {1: OpStrategy(output_specs=[P("data", None)],
                            param_specs={"kernel": P(None, "model")})}
        remap_strategy_for_slices(st)
        assert st[1].output_specs[0] == P(("slice", "data"), None)
        assert st[1].param_specs["kernel"] == P(None, "model")

    def test_slice_of_process_contiguous_blocks(self):
        assert [slice_of_process(p, 4, 2) for p in range(4)] == [0, 0, 1, 1]
        with pytest.raises(ValueError):
            slice_of_process(0, 3, 2)

    def test_slice_process_groups(self):
        assert slice_process_groups(4, 2) == [[0, 1], [2, 3]]


class TestHierarchicalPricing:
    """machine.py's two-level collective pricing: any collective that
    spans slices pays DCN rates on its cross-slice leg and must price
    STRICTLY above its single-slice (pure-ICI) twin."""

    def _specs(self):
        multi = MultiSliceSpec(num_slices=2, chips_per_slice=32,
                               chip="tpu-v4").to_machine_spec()
        flat = MachineSpec(chip="tpu-v4", chips_per_slice=64)
        return multi, flat

    def test_slices_spanned(self):
        multi, flat = self._specs()
        assert multi.slices_spanned(32) == 1
        assert multi.slices_spanned(64) == 2
        assert flat.slices_spanned(64) == 1

    @pytest.mark.parametrize("kind", ["all-reduce", "reduce-scatter",
                                      "all-gather", "all-to-all",
                                      "collective-permute"])
    def test_dcn_spanning_prices_above_ici_twin(self, kind):
        multi, flat = self._specs()
        nbytes = 64e6
        spanning = multi.collective_time(kind, nbytes, 64)
        ici_flat = flat.collective_time(kind, nbytes, 64)
        ici_one_slice = multi.collective_time(kind, nbytes, 32)
        assert spanning > ici_flat, (kind, spanning, ici_flat)
        assert spanning > ici_one_slice, (kind, spanning, ici_one_slice)

    def test_dcn_collective_time_scales_with_slices(self):
        multi, _ = self._specs()
        t2 = multi.dcn_collective_time("all-reduce", 1e8, 2)
        t4 = multi.dcn_collective_time("all-reduce", 1e8, 4)
        assert 0.0 < t2 < t4

    def test_detect_machine_spec_threads_slices(self):
        from flexflow_tpu.machine import detect_machine_spec
        spec = detect_machine_spec(8, slices=2)
        assert spec.num_slices == 2 and spec.chips_per_slice == 4
        with pytest.raises(ValueError):
            detect_machine_spec(9, slices=2)


class TestPlanResumeSliceLoss:
    """ckpt/elastic.plan_resume's slice-loss topology class: losing a
    whole number of slices from a multi-slice checkpoint."""

    def _manifest(self, mesh, n):
        return {"mesh": mesh, "num_devices": n}

    def test_lost_one_of_two_slices(self):
        from flexflow_tpu.ckpt import plan_resume
        plan = plan_resume(self._manifest({"slice": 2, "data": 4}, 8), 4)
        assert plan["action"] == "research"
        assert plan["topology"] == "slice_loss"
        assert plan["lost_slices"] == 1
        assert plan["surviving_slices"] == 1
        assert plan["slices"] == 1

    def test_lost_one_of_three_slices_keeps_multislice(self):
        from flexflow_tpu.ckpt import plan_resume
        plan = plan_resume(self._manifest({"slice": 3, "data": 6}, 12), 8)
        assert plan["topology"] == "slice_loss"
        assert plan["surviving_slices"] == 2 and plan["slices"] == 2

    def test_partial_slice_loss_is_device_change(self):
        from flexflow_tpu.ckpt import plan_resume
        # 3 of 8 devices survive: not a whole slice — generic re-search
        plan = plan_resume(self._manifest({"slice": 2, "data": 4}, 8), 3)
        assert plan["action"] == "research"
        assert plan["topology"] == "device_change"

    def test_flat_checkpoint_is_device_change(self):
        from flexflow_tpu.ckpt import plan_resume
        plan = plan_resume(self._manifest({"data": 8}, 8), 4)
        assert plan["action"] == "research"
        assert plan["topology"] == "device_change"

    def test_same_devices_still_reuses(self):
        from flexflow_tpu.ckpt import plan_resume
        plan = plan_resume(self._manifest({"slice": 2, "data": 4}, 8), 8)
        assert plan["action"] == "reuse"
        assert "topology" not in plan


class TestFabricCensus:
    """obs/inspect's replica-group parser + ICI/DCN byte attribution."""

    def test_parse_explicit_groups(self):
        from flexflow_tpu.obs.inspect import parse_replica_groups
        assert parse_replica_groups("{{0,1},{2,3}}") == [[0, 1], [2, 3]]

    def test_parse_iota_groups(self):
        from flexflow_tpu.obs.inspect import parse_replica_groups
        assert parse_replica_groups("[1,8]<=[8]") == [list(range(8))]
        assert parse_replica_groups("[2,4]<=[8]") == [[0, 1, 2, 3],
                                                      [4, 5, 6, 7]]

    def test_parse_iota_transpose(self):
        from flexflow_tpu.obs.inspect import parse_replica_groups
        # iota(8).reshape(2,4).T.reshape(4,2): strided pairs
        assert parse_replica_groups("[4,2]<=[2,4]T(1,0)") == \
            [[0, 4], [1, 5], [2, 6], [3, 7]]

    def test_census_splits_by_fabric(self):
        from flexflow_tpu.obs.inspect import collective_census_by_fabric
        hlo = "\n".join([
            # within-slice (devices 0-3 = slice 0): ICI
            "  %a = f32[1024]{0} all-reduce(%x), replica_groups={{0,1,2,3},{4,5,6,7}}",
            # spans both slices: DCN
            "  %b = f32[512]{0} all-gather(%y), replica_groups={{0,4},{1,5},{2,6},{3,7}}",
            # implicit flat group: conservative DCN
            "  %c = f32[256]{0} all-reduce(%z)",
        ])
        fab = collective_census_by_fabric(hlo, chips_per_slice=4)
        # decomposed attribution (r16): %b's groups hold ONE chip per
        # slice (no intra-slice stage) — full 2048 B cross DCN; %c's
        # implicit flat group decomposes hierarchically over the 4-chip
        # slices, so 1024/4 B cross DCN and the intra-slice
        # reduce-scatter/all-gather stages charge the rest to ICI
        assert fab["ici"]["count"] == 1
        assert fab["ici"]["bytes"] == 4096.0 + 256 * 4 * (1 - 1 / 4)
        assert fab["dcn"]["count"] == 2
        assert fab["dcn"]["bytes"] == 512 * 4 + 256 * 4 / 4


class TestRuntimeSliceAxis:
    """model.compile --slices: the ('slice', 'data') outer mesh axis is
    numerically transparent — same model, same data, same losses as the
    flat data mesh on the same 8 virtual devices."""

    def _train(self, slices):
        import jax
        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.ffconst import LossType
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     create_transformer)
        from flexflow_tpu.optimizers import SGDOptimizer
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        cfg = TransformerConfig(num_layers=1, hidden_size=32, num_heads=2,
                                seq_length=8, batch_size=16)
        c = FFConfig(batch_size=cfg.batch_size, seed=3)
        c.slices = slices
        ff = create_transformer(cfg, c)
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
                   mesh=make_mesh(8, {"data": 8}))
        rs = np.random.RandomState(0)
        x = rs.randn(cfg.batch_size, cfg.seq_length,
                     cfg.hidden_size).astype(np.float32)
        y = rs.randn(cfg.batch_size, cfg.seq_length, 1).astype(np.float32)
        ff.fit(x, y, epochs=2, verbose=False)
        return ff, float(ff.evaluate(x, y)["loss"])

    def test_sliced_mesh_matches_flat(self):
        ff_flat, loss_flat = self._train(slices=1)
        ff_sl, loss_sl = self._train(slices=2)
        assert dict(zip(ff_flat.mesh.axis_names,
                        ff_flat.mesh.devices.shape)) == {"data": 8}
        assert dict(zip(ff_sl.mesh.axis_names,
                        ff_sl.mesh.devices.shape)) == {"slice": 2,
                                                       "data": 4}
        assert loss_sl == pytest.approx(loss_flat, rel=1e-6)

    def test_slices_reject_pipe_mesh(self):
        import jax
        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.ffconst import LossType
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     create_transformer)
        from flexflow_tpu.optimizers import SGDOptimizer
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        cfg = TransformerConfig(num_layers=2, hidden_size=32, num_heads=2,
                                seq_length=8, batch_size=8)
        c = FFConfig(batch_size=cfg.batch_size)
        c.slices = 2
        c.pipeline_microbatches = 4
        ff = create_transformer(cfg, c)
        with pytest.raises(ValueError, match="pipe"):
            ff.compile(SGDOptimizer(lr=0.05),
                       LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
                       mesh=make_mesh(4, {"pipe": 2, "data": 2}))

    def test_config_flag_parses(self):
        from flexflow_tpu.config import FFConfig
        c = FFConfig()
        c.parse_args(["--slices", "2"])
        assert c.slices == 2
        with pytest.raises(ValueError):
            c.parse_args(["--slices", "0"])


def _acceptance_requests():
    """The 2 x v4-32 acceptance fixture: the same tiny strong-scaling
    transformer serialized once, plus machine JSONs for the two-slice
    machine and the flat 64-chip machine with identical chips."""
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType
    from flexflow_tpu.models.transformer import (TransformerConfig,
                                                 create_transformer)
    from flexflow_tpu.optimizers import SGDOptimizer
    from flexflow_tpu.search.unity import machine_to_json, serialize_graph

    n_chips = 64
    mcfg = TransformerConfig(num_layers=2, hidden_size=128, num_heads=4,
                             seq_length=64, batch_size=n_chips)
    ff = create_transformer(
        mcfg, FFConfig(batch_size=mcfg.batch_size, only_data_parallel=True,
                       workers_per_node=1))
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
    nodes = serialize_graph(ff.executor.nodes,
                            final_guid=ff.executor.final_ref[0])
    multi = machine_to_json(
        MultiSliceSpec(num_slices=2, chips_per_slice=32,
                       chip="tpu-v4").to_machine_spec(), n_chips)
    flat = machine_to_json(
        MachineSpec(chip="tpu-v4", chips_per_slice=n_chips), n_chips)
    cfg = dict(budget=8, alpha=0.05, training=True, overlap=True,
               batch=mcfg.batch_size, opt_state_factor=2.0, seed=42,
               rules=[], enable_parameter_parallel=True,
               emit_search_trace=True)
    return nodes, multi, flat, cfg


@pytest.mark.skipif(
    not __import__("flexflow_tpu.search.native",
                   fromlist=["available"]).available(),
    reason="native search unavailable")
class TestHierarchicalSearchAcceptance:
    """ISSUE 16 acceptance: on a simulated 2 x v4-32 the hierarchical
    search picks a DP/WUS-over-DCN x hybrid-within-slice strategy that
    prices STRICTLY cheaper than the flat-mesh winner forced onto the
    same chips, with cross-slice collectives visible in the search
    trace (slices_spanned mesh rows) and the per-op collective census
    (fabric="dcn" rows)."""

    @pytest.fixture(scope="class")
    def results(self):
        from flexflow_tpu.search.native import native_optimize, native_simulate
        nodes, multi, flat, cfg = _acceptance_requests()
        hier = native_optimize(dict(nodes=nodes, machine=multi,
                                    measured={}, config=cfg))
        flatw = native_optimize(dict(nodes=nodes, machine=flat,
                                     measured={}, config=cfg))
        # force the flat machine's winner onto the two-slice machine:
        # same chips, same mesh, same per-op choices — but every
        # data-axis collective now pays DCN rates on its outer leg
        forced = native_simulate(dict(
            nodes=nodes, machine=multi, measured={},
            config=dict(cfg, emit_search_trace=False),
            mesh=flatw["mesh"],
            assignment={g: o["choice"]
                        for g, o in flatw["ops"].items()}))
        return hier, flatw, forced

    def test_hierarchical_beats_forced_flat(self, results):
        hier, flatw, forced = results
        assert hier["predicted_time"] < forced["iteration_time"], (
            hier["predicted_time"], forced["iteration_time"])

    def test_native_dcn_spanning_prices_above_ici_twin(self, results):
        # the IDENTICAL mesh + assignment priced on the two-slice
        # machine (data axis over DCN) vs the flat 64-chip machine
        # (pure ICI): the native simulator must charge strictly more
        # when the gradient sync crosses the slice boundary
        hier, flatw, forced = results
        assert forced["iteration_time"] > flatw["predicted_time"], (
            forced["iteration_time"], flatw["predicted_time"])

    def test_hierarchy_shapes_the_mesh(self, results):
        hier, flatw, _ = results
        hmesh = {k: v for k, v in hier["mesh"].items() if v > 1}
        fmesh = {k: v for k, v in flatw["mesh"].items() if v > 1}
        # the two-level machine steers the search to a different
        # decomposition than the flat fabric does
        assert hmesh != fmesh, (hmesh, fmesh)
        # the winner's inner (non-data) axes fit within one slice, so
        # only the data axis crosses the DCN
        inner = 1
        for a in ("model", "seq", "expert"):
            inner *= hmesh.get(a, 1)
        assert inner <= 32 and 32 % inner == 0
        assert hier.get("slices_spanned", 0) >= 2

    def test_trace_records_slices_spanned(self, results):
        hier, _, _ = results
        meshes = hier["search_trace"]["meshes"]
        rows = [r for r in meshes if r.get("slices_spanned", 0) > 1]
        assert rows, "no trace rows record a DCN-spanning mesh"
        # the inner_axes_cross_slice gate rejects meshes whose
        # model/seq/expert product would straddle the slice boundary
        assert any(r.get("reason") == "inner_axes_cross_slice"
                   for r in meshes if r.get("status") == "illegal")

    def test_census_records_dcn_fabric(self, results):
        hier, _, _ = results
        fabrics = set()
        for oj in hier["search_trace"]["ops"]:
            for cand in oj.get("candidates", []):
                for row in cand.get("collectives", []):
                    fabrics.add(row.get("fabric"))
                    if row.get("fabric") == "dcn":
                        assert row.get("slices", 0) >= 2
        assert "dcn" in fabrics, fabrics
        assert "ici" in fabrics, fabrics

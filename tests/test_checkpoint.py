"""Elastic fault-tolerant checkpointing (ISSUE 10).

v2 per-shard checkpoints (flexflow_tpu/ckpt): round-trip of the full
sharded-state zoo (WUS data-sharded master/Adam moments, pipeline
stacked body params, bf16 bit-views), crash-atomicity (manifest-last
commit: a save killed at ANY point leaves the previous checkpoint
loadable), retain-N GC, async-manager overhead + goodput gauges,
FFS_FAULT injection, FFL8xx integrity lint, and the hardened legacy v1
path. The cross-host kill/resume and fail-fast legs live in
tests/test_multihost.py; everything here runs on the conftest 8-device
virtual CPU mesh.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from flexflow_tpu import (AdamOptimizer, FFConfig, FFModel, LossType,
                          SGDOptimizer, lint_model)
from flexflow_tpu.ffconst import ActiMode
from flexflow_tpu.machine import make_mesh
from flexflow_tpu.ckpt import (CheckpointManager, latest_complete,
                               list_steps, load_manifest, load_sharded,
                               plan_resume, save_sharded, verify_step_dir)
from flexflow_tpu.ckpt import manifest as mf


def blobs(n=256, d=16, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(classes, d) * 3
    y = rs.randint(0, classes, n)
    x = (centers[y] + rs.randn(n, d)).astype(np.float32)
    return x, y.astype(np.int32).reshape(-1, 1)


def small_model(hidden=32, optimizer=None, mesh=None, checkpoint_dir=None):
    cfg = FFConfig(batch_size=64, checkpoint_dir=checkpoint_dir)
    ff = FFModel(cfg)
    t = ff.create_tensor((64, 16))
    h = ff.dense(t, hidden, activation=ActiMode.AC_MODE_RELU, name="h1")
    out = ff.dense(h, 4, name="out")
    ff.softmax(out)
    ff.compile(optimizer or AdamOptimizer(alpha=0.01),
               mesh=mesh)
    return ff


def bits(a: np.ndarray) -> np.ndarray:
    a = np.asarray(a)
    if a.dtype.kind in "iub":
        return a
    return a.view(np.dtype(f"uint{8 * a.dtype.itemsize}"))


def assert_tree_bitwise(t1, t2, path=""):
    if isinstance(t1, dict):
        assert set(t1) == set(t2), f"{path}: keys differ"
        for k in t1:
            assert_tree_bitwise(t1[k], t2[k], f"{path}/{k}")
        return
    if hasattr(t1, "shape"):
        np.testing.assert_array_equal(
            bits(np.asarray(t1)), bits(np.asarray(t2)),
            err_msg=f"bit mismatch at {path}")
        return
    assert t1 == t2, f"{path}: {t1} != {t2}"


class TestShardedRoundtrip:
    def test_roundtrip_bitwise_and_training_continuity(self, tmp_path):
        x, y = blobs()
        ff = small_model()
        ff.fit(x, y, epochs=2, verbose=False)
        save_sharded(str(tmp_path), ff)
        ff2 = small_model()
        assert load_sharded(str(tmp_path), ff2) == ff._iter
        assert_tree_bitwise(ff.params, ff2.params, "params")
        assert_tree_bitwise(ff.opt_state["m"], ff2.opt_state["m"], "m")
        np.testing.assert_array_equal(np.asarray(ff._rng),
                                      np.asarray(ff2._rng))
        # bit-identical continuation: same data, same rng stream
        ff.fit(x, y, epochs=1, verbose=False)
        ff2.fit(x, y, epochs=1, verbose=False)
        assert ff._last_loss == ff2._last_loss

    def test_bf16_bits_exact_v2_and_v1(self, tmp_path):
        """ml_dtypes bfloat16 leaves round-trip bit-exactly in both
        formats (stored as uint16 views, true dtype in the manifest —
        no more f32 widening detour)."""
        x, y = blobs()
        ff = small_model(optimizer=AdamOptimizer(
            alpha=0.01, state_dtype=jnp.bfloat16))
        ff.fit(x, y, epochs=2, verbose=False)
        m0 = np.asarray(ff.opt_state["m"]["h1"]["kernel"])
        assert str(m0.dtype) == "bfloat16"  # the fixture is real bf16
        save_sharded(str(tmp_path / "v2"), ff)
        ff2 = small_model(optimizer=AdamOptimizer(
            alpha=0.01, state_dtype=jnp.bfloat16))
        load_sharded(str(tmp_path / "v2"), ff2)
        np.testing.assert_array_equal(
            m0.view(np.uint16),
            np.asarray(ff2.opt_state["m"]["h1"]["kernel"]).view(np.uint16))
        # the v2 manifest records the true dtype, not a widened one
        manifest = load_manifest(str(tmp_path / "v2"))
        meta = manifest["leaves"]["opt_state/m/h1/kernel"]
        assert meta["dtype"] == "bfloat16" and meta["saved_dtype"] == "uint16"
        # legacy v1: same bit-exactness
        ff.save_checkpoint(str(tmp_path / "v1ck"))
        ff3 = small_model(optimizer=AdamOptimizer(
            alpha=0.01, state_dtype=jnp.bfloat16))
        ff3.load_checkpoint(str(tmp_path / "v1ck"))
        np.testing.assert_array_equal(
            m0.view(np.uint16),
            np.asarray(ff3.opt_state["m"]["h1"]["kernel"]).view(np.uint16))

    def test_wus_sharded_master_and_moments_roundtrip(self, tmp_path):
        """WUS zoo member: data-sharded f32 master params + Adam moments
        survive the per-shard save (each shard written once, reassembled,
        re-placed onto the sharded layout) and training continues
        bit-identically."""
        def build():
            cfg = FFConfig(batch_size=16, seed=42)
            cfg.weight_update_sharding = "on"
            ff = FFModel(cfg)
            t = ff.create_tensor((16, 64), name="x")
            t = ff.dense(t, 512, name="d0")
            t = ff.relu(t)
            ff.dense(t, 64, name="d1")
            ff.compile(AdamOptimizer(alpha=1e-2),
                       LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
                       mesh=make_mesh(8, {"data": 8}))
            return ff

        rs = np.random.RandomState(0)
        x = rs.randn(16, 64).astype(np.float32)
        y = rs.randn(16, 64).astype(np.float32)
        ff = build()
        assert ff.executor.weight_update_sharding
        assert ff.opt_state["m"]["d0"]["kernel"].sharding.spec[0] == "data"
        ff.fit(x, y, epochs=2, verbose=False)
        save_sharded(str(tmp_path), ff)
        ff2 = build()
        load_sharded(str(tmp_path), ff2)
        # the restored moments keep the data-sharded master layout
        assert ff2.opt_state["m"]["d0"]["kernel"].sharding.spec[0] == "data"
        assert_tree_bitwise(ff.params, ff2.params, "params")
        assert_tree_bitwise(ff.opt_state["m"], ff2.opt_state["m"], "m")
        ff.fit(x, y, epochs=1, verbose=False)
        ff2.fit(x, y, epochs=1, verbose=False)
        assert ff._last_loss == ff2._last_loss

    @pytest.mark.slow
    def test_pipeline_stacked_body_roundtrip(self, tmp_path):
        """Pipeline zoo member: the pp>1 executor's stacked body params
        ([R, ...] over the pipe axis) round-trip through the shard
        index. slow: two pipeline compiles (~23s) — the tier-1 budget
        keeps the WUS/elastic/zoo round-trips; this leg runs with the
        slow suite and the run_t1.sh elasticity stage."""
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     create_transformer)
        def build():
            cfg = TransformerConfig(num_layers=4, hidden_size=32,
                                    num_heads=2, seq_length=16,
                                    batch_size=16)
            ff = create_transformer(cfg, FFConfig(batch_size=16, seed=7))
            ff.compile(SGDOptimizer(lr=1e-3),
                       LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
                       mesh=make_mesh(8, {"pipe": 2, "data": 4}))
            return ff

        rs = np.random.RandomState(0)
        x = rs.randn(16, 16, 32).astype(np.float32)
        y = rs.randn(16, 16, 1).astype(np.float32)
        ff = build()
        from flexflow_tpu.parallel.pipeline_exec import (
            BODY_KEY, PipelineGraphExecutor)
        assert isinstance(ff.executor, PipelineGraphExecutor)
        ff.fit(x, y, epochs=1, verbose=False)
        w0 = ff.get_parameter("ffn1_2")
        save_sharded(str(tmp_path), ff)
        ff.fit(x, y, epochs=1, verbose=False)  # advance past the save
        ff2 = build()
        assert load_sharded(str(tmp_path), ff2) == 1
        np.testing.assert_array_equal(bits(w0),
                                      bits(ff2.get_parameter("ffn1_2")))
        assert BODY_KEY in ff2.params
        ff2.fit(x, y, epochs=1, verbose=False)  # trains after restore
        assert np.isfinite(ff2._last_loss)

    def test_elastic_load_onto_different_mesh(self, tmp_path):
        """Save on {data:4, model:2}, restore onto {data:8}: global
        arrays reassemble from the shard index and re-place onto the
        live strategy — predictions identical."""
        x, y = blobs()
        cfg = FFConfig(batch_size=64, enable_parameter_parallel=True)
        ff = FFModel(cfg)
        t = ff.create_tensor((64, 16))
        h = ff.dense(t, 32, activation=ActiMode.AC_MODE_RELU, name="h1")
        ff.softmax(ff.dense(h, 4, name="out"))
        ff.compile(AdamOptimizer(alpha=0.01),
                   mesh=make_mesh(8, {"data": 4, "model": 2}))
        ff.fit(x, y, epochs=2, verbose=False)
        save_sharded(str(tmp_path), ff)
        manifest = load_manifest(str(tmp_path))
        assert manifest["mesh"] == {"data": 4, "model": 2}
        ff2 = small_model(mesh=make_mesh(8, {"data": 8}))
        load_sharded(str(tmp_path), ff2)
        # the VALUES are bit-identical across the mesh change; the
        # forward pass may differ by reduction order only
        np.testing.assert_array_equal(bits(ff.get_parameter("h1")),
                                      bits(ff2.get_parameter("h1")))
        np.testing.assert_allclose(ff.predict(x[:64]), ff2.predict(x[:64]),
                                   rtol=1e-6, atol=1e-7)
        # plan_resume: same device count reuses the recorded strategy
        assert plan_resume(manifest, 8)["action"] == "reuse"
        assert plan_resume(manifest, 4)["action"] == "research"


class TestCrashAtomicity:
    def _trained(self, tmp_path, epochs=1):
        x, y = blobs()
        ff = small_model()
        ff.fit(x, y, epochs=epochs, verbose=False)
        save_sharded(str(tmp_path), ff, step=ff._iter)
        return ff, x, y

    def test_kill_during_shard_write_keeps_previous(self, tmp_path,
                                                    monkeypatch):
        """A save that dies while writing shard data leaves no manifest:
        the directory still loads — at the PREVIOUS step."""
        ff, x, y = self._trained(tmp_path)
        first = ff._iter
        ff.fit(x, y, epochs=1, verbose=False)

        def boom(*a, **k):
            raise OSError("simulated SIGKILL mid-shard-write")

        monkeypatch.setattr(np, "savez", boom)
        with pytest.raises(OSError):
            save_sharded(str(tmp_path), ff, step=ff._iter)
        monkeypatch.undo()
        step, _ = latest_complete(str(tmp_path))
        assert step == first
        ff2 = small_model()
        assert load_sharded(str(tmp_path), ff2) == first

    def test_kill_before_manifest_keeps_previous(self, tmp_path,
                                                 monkeypatch):
        """Shards + index fully written but the commit record missing:
        still the previous checkpoint (manifest-last is the contract)."""
        ff, x, y = self._trained(tmp_path)
        first = ff._iter
        ff.fit(x, y, epochs=1, verbose=False)

        real = mf.atomic_write_json

        def no_commit(path, obj):
            if os.path.basename(path) == mf.MANIFEST_NAME:
                raise OSError("simulated SIGKILL before manifest commit")
            return real(path, obj)

        monkeypatch.setattr(mf, "atomic_write_json", no_commit)
        with pytest.raises(OSError):
            save_sharded(str(tmp_path), ff, step=ff._iter)
        monkeypatch.undo()
        steps = list_steps(str(tmp_path))
        assert [(s, ok) for s, _, ok in steps] == [(first, True),
                                                   (ff._iter, False)]
        ff2 = small_model()
        assert load_sharded(str(tmp_path), ff2) == first

    def test_no_tmp_litter_matches_artifact_patterns(self, tmp_path):
        ff, _, _ = self._trained(tmp_path)
        step, sdir = latest_complete(str(tmp_path))
        assert not [f for f in os.listdir(sdir) if f.endswith(".tmp")]

    def test_v1_interrupted_save_keeps_previous(self, tmp_path,
                                                monkeypatch):
        """Legacy v1 crash-atomicity satellite: a preempted re-save can
        no longer shadow the previous good checkpoint."""
        x, y = blobs()
        ff = small_model()
        ff.fit(x, y, epochs=1, verbose=False)
        stem = str(tmp_path / "ck")
        ff.save_checkpoint(stem)
        w0 = ff.get_parameter("h1")
        ff.fit(x, y, epochs=1, verbose=False)

        def boom(*a, **k):
            raise OSError("simulated preemption mid-npz")

        monkeypatch.setattr(np, "savez", boom)
        with pytest.raises(OSError):
            ff.save_checkpoint(stem)
        monkeypatch.undo()
        ff2 = small_model()
        assert ff2.load_checkpoint(stem) == 4  # the FIRST save's iter
        np.testing.assert_array_equal(bits(w0),
                                      bits(ff2.get_parameter("h1")))

    def test_corrupt_shard_detected_on_load_and_verify(self, tmp_path):
        ff, _, _ = self._trained(tmp_path)
        _, sdir = latest_complete(str(tmp_path))
        p = os.path.join(sdir, "shards_host0000.npz")
        raw = bytearray(open(p, "rb").read())
        off = raw.find(b"params/h1/kernel::0.npy")
        raw[off + 200] ^= 0xFF
        open(p, "wb").write(bytes(raw))
        rep = verify_step_dir(sdir)
        assert not rep["complete"]
        assert any("corruption" in e for e in rep["errors"])
        with pytest.raises(ValueError, match="corruption"):
            load_sharded(str(tmp_path), small_model())

    def test_missing_checkpoint_fails_fast(self, tmp_path):
        ff = small_model()
        with pytest.raises(FileNotFoundError, match="complete checkpoint"):
            load_sharded(str(tmp_path / "nowhere"), ff)
        with pytest.raises(FileNotFoundError, match="no checkpoint"):
            ff.load_checkpoint(str(tmp_path / "nowhere_v1"))


class TestManagerAndFit:
    def test_fit_resume_bitwise_equals_uninterrupted(self, tmp_path):
        """save-at-step-k / resume / train-to-n == uninterrupted-run-
        to-n, bitwise, on the 8-way mesh (acceptance criterion)."""
        x, y = blobs()
        ffu = small_model()
        ffu.fit(x, y, epochs=6, verbose=False)  # 24 steps uninterrupted
        cdir = str(tmp_path / "ck")
        ffa = small_model()
        ffa.fit(x, y, epochs=3, verbose=False,
                checkpoint_dir=cdir, checkpoint_every=5)
        ffb = small_model()
        ffb.fit(x, y, epochs=6, verbose=False,
                checkpoint_dir=cdir, checkpoint_every=5, resume=True)
        assert ffb._iter == ffu._iter == 24
        assert_tree_bitwise(ffu.params, ffb.params, "params")
        assert ffu._last_loss == ffb._last_loss

    def test_resume_full_epoch_covered_verbose(self, tmp_path, capsys):
        """A restored checkpoint that covers whole epochs must not crash
        the verbose epoch report (regression: the skipped epoch had no
        loss to print) and the resumed run's throughput counts only the
        steps it actually executed."""
        x, y = blobs()
        cdir = str(tmp_path)
        ffa = small_model()
        ffa.fit(x, y, epochs=2, verbose=False, checkpoint_dir=cdir,
                checkpoint_every=4)
        ffb = small_model()
        thr = ffb.fit(x, y, epochs=3, verbose=True, checkpoint_dir=cdir,
                      checkpoint_every=4, resume=True)
        out = capsys.readouterr().out
        # epochs 0-1 are inside the checkpoint: no report lines for them
        assert "epoch 0:" not in out and "epoch 2:" in out
        assert ffb._iter == 12
        # 1 executed epoch of 4 batches x 64 — not the full 3-epoch grid
        assert np.isfinite(thr)

    def test_dir_without_cadence_still_saves_final(self, tmp_path):
        """checkpoint_dir with no checkpoint_every means "checkpoint
        once, at the end" — a configured directory must never stay
        silently empty (the next --resume would restart from 0)."""
        x, y = blobs(n=64)
        ff = small_model()
        ff.fit(x, y, epochs=2, verbose=False, checkpoint_dir=str(tmp_path))
        latest = latest_complete(str(tmp_path))
        assert latest is not None and latest[0] == ff._iter
        ff2 = small_model()
        mgr = CheckpointManager(ff2, str(tmp_path))
        assert mgr.resume() == ff._iter

    def test_retain_gc_keeps_newest_never_deletes_last(self, tmp_path):
        x, y = blobs(n=64)
        ff = small_model()
        mgr = CheckpointManager(ff, str(tmp_path), every=1, retain=2,
                                async_write=False)
        for _ in range(5):
            ff.fit(x, y, epochs=1, verbose=False)
            mgr.save(ff._iter)
        kept = [s for s, _, ok in list_steps(str(tmp_path)) if ok]
        assert kept == [4, 5]
        # retain floor of 1: even retain=0 input keeps the last one
        mgr2 = CheckpointManager(ff, str(tmp_path), every=1, retain=0)
        assert mgr2.retain == 1
        mf.collect_garbage(str(tmp_path), 1)
        assert [s for s, _, ok in list_steps(str(tmp_path)) if ok] == [5]

    def test_async_stall_is_snapshot_not_write(self, tmp_path,
                                               monkeypatch):
        """The <10%-of-step-time criterion, made deterministic with the
        slow_write fault: the writer sleeps 500 ms per shard file, yet
        the training-thread stall (snapshot only) never includes that
        delay — the write runs off the critical path. A first
        (unmeasured) save warms the snapshot/thread-start path so the
        measured stall is cold-start-free; the two-sided assertion
        (stall well under the delay AND the writer visibly paying it)
        is what makes the test deterministic under suite load rather
        than a bet on absolute scheduler latency."""
        import time
        monkeypatch.setenv("FFS_FAULT", "slow_write:500")
        x, y = blobs(n=64)
        ff = small_model()
        ff.fit(x, y, epochs=1, verbose=False)
        warm = CheckpointManager(ff, str(tmp_path), every=1,
                                 async_write=True, run_name="stall_warmup")
        warm.save(ff._iter)  # warmup: lazy imports, thread start, D2H
        warm.wait()
        mgr = CheckpointManager(ff, str(tmp_path), every=1,
                                async_write=True, run_name="stall_test")
        stalls, paid = [], []
        for _ in range(3):
            ff.fit(x, y, epochs=1, verbose=False)  # advance _iter
            t0 = time.perf_counter()
            mgr.save(ff._iter)
            stalls.append(time.perf_counter() - t0)
            t1 = time.perf_counter()
            mgr.wait()
            paid.append(stalls[-1] + (time.perf_counter() - t1))
        # min over attempts: ONE fast return proves the commit runs off
        # the training thread; individual attempts may eat scheduler
        # noise without making the property false
        assert min(stalls) < 0.250, (
            f"training-thread stalls {[f'{s * 1e3:.1f}ms' for s in stalls]} "
            f"all swallowed the 500ms injected write latency — the save "
            f"is not async")
        assert all(p >= 0.500 for p in paid), (
            f"stall+wait {[f'{p * 1e3:.1f}ms' for p in paid]} never paid "
            f"the injected delay — the fault seam is dead and this test "
            f"is vacuous")
        from flexflow_tpu.obs import get_registry
        snap = get_registry().to_dict()
        obs = snap["observations"]
        assert obs["stall_test/ckpt_save_stall_s"]["min"] < 0.250
        assert obs["stall_test/ckpt_async_write_s"]["min"] >= 0.500
        assert snap["counters"]["stall_test/ckpt_bytes_written"] > 0

    def test_goodput_gauge_and_lost_step_accounting(self, tmp_path):
        x, y = blobs(n=64)
        cdir = str(tmp_path)
        ff = small_model()
        ff.fit(x, y, epochs=4, verbose=False, checkpoint_dir=cdir,
               checkpoint_every=2, resume=False)
        from flexflow_tpu.obs import get_registry
        g = get_registry().to_dict()["gauges"]
        assert 0.0 < g["fit/goodput_effective"] <= 1.0
        # simulate a crash that lost steps: progress heartbeat says the
        # dead run got further than the newest complete checkpoint
        mf.note_progress(cdir, ff._iter + 3)
        ff2 = small_model()
        mgr = CheckpointManager(ff2, cdir, every=2, run_name="resumed")
        it = mgr.resume()
        assert it == ff._iter
        assert mgr.restart_lost_steps == 3
        mgr.finalize(elapsed_s=1.0, steps=10, final_save=False)
        g2 = get_registry().to_dict()["gauges"]
        assert g2["resumed/ckpt_restart_lost_steps"] == 3
        assert g2["resumed/goodput_effective"] < 1.0
        assert g2["resumed/ckpt_restore_s"] > 0

    def test_resume_without_dir_rejected(self):
        x, y = blobs(n=64)
        ff = small_model()
        with pytest.raises(ValueError, match="checkpoint directory"):
            ff.fit(x, y, epochs=1, verbose=False, resume=True)

    def test_resume_partial_only_dir_fails_fast(self, tmp_path):
        os.makedirs(tmp_path / "step_00000002")
        ff = small_model()
        mgr = CheckpointManager(ff, str(tmp_path), every=1)
        with pytest.raises(FileNotFoundError, match="complete checkpoint"):
            mgr.resume()

    def test_writer_error_surfaces_on_training_thread(self, tmp_path,
                                                      monkeypatch):
        x, y = blobs(n=64)
        ff = small_model()
        ff.fit(x, y, epochs=1, verbose=False)
        mgr = CheckpointManager(ff, str(tmp_path), every=1,
                                async_write=True)
        import flexflow_tpu.ckpt.manager as mgr_mod

        def boom(*a, **k):
            raise OSError("disk full")

        monkeypatch.setattr(mgr_mod.sharded, "write_snapshot", boom)
        mgr.save(ff._iter)  # enqueues; the failure lands in the writer
        with pytest.raises(RuntimeError, match="disk full"):
            mgr.wait()


class TestFaultHarness:
    def test_parse_and_seams(self, monkeypatch):
        from flexflow_tpu.ckpt import faults
        monkeypatch.setenv(
            "FFS_FAULT",
            "kill_host:1@step:3,corrupt_shard:d0/kernel@step:2,"
            "slow_write:5")
        plan = faults.get_plan()
        assert plan.kills == [(1, 3)]
        assert plan.corrupts == [("d0/kernel", 2)]
        assert plan.slow_write_s == pytest.approx(0.005)
        # corrupt fires once, only for the named leaf/step
        payload = b"x" * 64
        assert plan.corrupt_bytes("d1/kernel", 2, payload) is payload
        assert plan.corrupt_bytes("d0/kernel", 1, payload) is payload
        hurt = plan.corrupt_bytes("d0/kernel", 2, payload)
        assert hurt != payload and len(hurt) == len(payload)
        assert plan.corrupt_bytes("d0/kernel", 2, payload) is payload
        # this process is rank 0 — a kill spec for rank 1 must not fire
        plan.step_hook(3)

    def test_unset_env_is_noop_and_bad_spec_raises(self, monkeypatch):
        from flexflow_tpu.ckpt import faults
        monkeypatch.delenv("FFS_FAULT", raising=False)
        assert faults.get_plan() is None
        faults.step_hook(0)  # cheap no-op seam
        monkeypatch.setenv("FFS_FAULT", "kill_host:1@iteration:3")
        with pytest.raises(ValueError, match="cannot parse fault"):
            faults.get_plan()

    def test_corrupt_shard_fault_end_to_end(self, tmp_path, monkeypatch):
        """The injected corruption is invisible at save time (checksum
        precedes the flip) and caught at load — the integrity property
        the harness exists to exercise."""
        monkeypatch.setenv("FFS_FAULT", "corrupt_shard:out/kernel@step:7")
        x, y = blobs(n=64)
        ff = small_model()
        ff.fit(x, y, epochs=7, verbose=False)
        save_sharded(str(tmp_path), ff, step=7)
        monkeypatch.delenv("FFS_FAULT")
        with pytest.raises(ValueError, match="checksum mismatch"):
            load_sharded(str(tmp_path), small_model())


class TestCheckpointLint:
    def test_clean_and_skip(self, tmp_path):
        x, y = blobs(n=64)
        cdir = str(tmp_path)
        ff = small_model(checkpoint_dir=cdir)
        ff.fit(x, y, epochs=2, verbose=False, checkpoint_every=1)
        rep = lint_model(ff)
        assert rep.passes["checkpoint-integrity"] == "ok"
        assert not [d for d in rep.diagnostics
                    if d.rule.startswith("FFL80")]
        rep2 = lint_model(small_model())
        assert rep2.passes["checkpoint-integrity"].startswith("skipped")

    def test_ffl801_partial_only(self, tmp_path):
        os.makedirs(tmp_path / "step_00000002")
        rep = lint_model(small_model(checkpoint_dir=str(tmp_path)))
        assert [d.rule for d in rep.errors] == ["FFL801"]

    def test_ffl802_corruption(self, tmp_path):
        x, y = blobs(n=64)
        ff = small_model()
        ff.fit(x, y, epochs=1, verbose=False)
        save_sharded(str(tmp_path), ff)
        _, sdir = latest_complete(str(tmp_path))
        p = os.path.join(sdir, "shards_host0000.npz")
        raw = bytearray(open(p, "rb").read())
        raw[raw.find(b"params/h1/kernel::0.npy") + 200] ^= 0xFF
        open(p, "wb").write(bytes(raw))
        rep = lint_model(small_model(checkpoint_dir=str(tmp_path)))
        assert any(d.rule == "FFL802" for d in rep.errors)

    def test_ffl803_shape_mismatch(self, tmp_path):
        x, y = blobs(n=64)
        ff = small_model(hidden=32)
        ff.fit(x, y, epochs=1, verbose=False)
        save_sharded(str(tmp_path), ff)
        rep = lint_model(small_model(hidden=64,
                                     checkpoint_dir=str(tmp_path)))
        shapes = [d for d in rep.errors if d.rule == "FFL803"]
        assert shapes and any("h1" in (d.tensor or "") for d in shapes)

    def test_ffl804_mesh_change_is_info(self, tmp_path):
        x, y = blobs(n=64)
        cfg_mesh = make_mesh(8, {"data": 4, "model": 2})
        ff = small_model(mesh=cfg_mesh)
        ff.fit(x, y, epochs=1, verbose=False)
        save_sharded(str(tmp_path), ff)
        rep = lint_model(small_model(mesh=make_mesh(8, {"data": 8}),
                                     checkpoint_dir=str(tmp_path)))
        from flexflow_tpu.analysis import Severity
        infos = rep.by_rule("FFL804")
        assert infos and infos[0].severity == Severity.INFO
        assert not rep.errors


class TestInspectCli:
    def test_summary_verify_and_exit_codes(self, tmp_path):
        import subprocess
        import sys
        x, y = blobs(n=64)
        ff = small_model()
        ff.fit(x, y, epochs=1, verbose=False)
        save_sharded(str(tmp_path / "good"), ff)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(repo, "scripts", "ckpt_inspect.py")
        # one real subprocess run proves the CLI entry point end to end
        r = subprocess.run([sys.executable, script, str(tmp_path / "good")],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "integrity: verified" in r.stdout
        # remaining exit-code matrix via main() in-process (each
        # subprocess pays a multi-second jax import — tier-1 budget)
        sys.path.insert(0, os.path.dirname(script))
        try:
            from ckpt_inspect import inspect, main
        finally:
            sys.path.pop(0)
        # empty/partial: exit 2
        os.makedirs(tmp_path / "partial" / "step_00000002")
        assert main([str(tmp_path / "partial")]) == 2
        # corrupt: exit 1, json report carries the errors
        _, sdir = latest_complete(str(tmp_path / "good"))
        p = os.path.join(sdir, "shards_host0000.npz")
        raw = bytearray(open(p, "rb").read())
        raw[raw.find(b"params/h1/kernel::0.npy") + 200] ^= 0xFF
        open(p, "wb").write(bytes(raw))
        assert main([str(tmp_path / "good"), "--json"]) == 1
        assert inspect(str(tmp_path / "good"))["latest"]["errors"]


class TestChunkedShards:
    """Shard-file chunking (ROADMAP elastic follow-on (b), ISSUE 13
    satellite): payloads above FFS_CKPT_CHUNK_BYTES split into CRC'd
    chunks at write, reassemble at load, verify deep-checks every
    chunk, and the serving loader's reads are capped at chunk size."""

    def _save_chunked(self, tmp_path, monkeypatch, threshold="128"):
        monkeypatch.setenv("FFS_CKPT_CHUNK_BYTES", threshold)
        x, y = blobs()
        ff = small_model()
        ff.fit(x, y, epochs=1, verbose=False)
        step_dir = save_sharded(str(tmp_path), ff)
        return ff, step_dir

    def test_roundtrip_bitwise_and_chunks_on_disk(self, tmp_path,
                                                  monkeypatch):
        import glob
        import json as _json

        ff, step_dir = self._save_chunked(tmp_path, monkeypatch)
        # chunks actually materialized (h1 kernel is 16x32 f32 = 2KB+)
        rows = []
        for f in glob.glob(os.path.join(step_dir, "index_host*.json")):
            idx = _json.load(open(f))
            for leaf, rr in idx["shards"].items():
                rows.extend(rr)
        chunked = [r for r in rows if r.get("chunks")]
        assert chunked, "no shard exceeded the 128B chunk threshold"
        for r in chunked:
            assert sum(c["bytes"] for c in r["chunks"]) == r["bytes"]
            assert all(c["bytes"] <= 128 for c in r["chunks"][:-1])
        # loads back bit-identically (threshold also active at load —
        # reader handles chunked rows regardless of the env)
        ff2 = small_model()
        assert load_sharded(str(tmp_path), ff2) == ff._iter
        assert_tree_bitwise(ff.params, ff2.params, "params")
        assert_tree_bitwise(ff.opt_state["m"], ff2.opt_state["m"], "m")

    def test_verify_step_dir_checks_chunks(self, tmp_path, monkeypatch):
        import glob

        ff, step_dir = self._save_chunked(tmp_path, monkeypatch)
        rep = verify_step_dir(step_dir, deep=True)
        assert rep["complete"], rep["errors"]
        # flip a byte inside a chunk entry: deep verify must flag it
        p = glob.glob(os.path.join(step_dir, "shards_host*.npz"))[0]
        raw = bytearray(open(p, "rb").read())
        k = raw.find(b"::c0.npy")
        assert k > 0, "no chunk entries in npz"
        raw[k + 200] ^= 0xFF
        open(p, "wb").write(bytes(raw))
        rep = verify_step_dir(step_dir, deep=True)
        assert not rep["complete"]
        assert any("c0" in e or "unreadable" in e for e in rep["errors"])

    def test_chunk_corruption_detected_at_load(self, tmp_path,
                                               monkeypatch):
        import glob

        ff, step_dir = self._save_chunked(tmp_path, monkeypatch)
        p = glob.glob(os.path.join(step_dir, "shards_host*.npz"))[0]
        data = dict(np.load(p))
        ck = [k for k in data if "::c" in k][0]
        arr = data[ck].copy()
        arr.flat[0] += 1.0
        data[ck] = arr
        np.savez(p, **data)
        with pytest.raises(ValueError, match="corruption"):
            load_sharded(str(tmp_path), small_model())

    def test_default_threshold_leaves_small_shards_unchunked(
            self, tmp_path):
        import glob
        import json as _json

        x, y = blobs()
        ff = small_model()
        ff.fit(x, y, epochs=1, verbose=False)
        step_dir = save_sharded(str(tmp_path), ff)
        for f in glob.glob(os.path.join(step_dir, "index_host*.json")):
            idx = _json.load(open(f))
            for leaf, rr in idx["shards"].items():
                assert all("chunks" not in r for r in rr)

    def test_load_without_opt_state(self, tmp_path, monkeypatch):
        """include_opt_state=False (the serving loader's path): params
        and op state restore, optimizer leaves are never read, and the
        live opt_state object is untouched."""
        from flexflow_tpu.obs.registry import get_registry

        ff, step_dir = self._save_chunked(tmp_path, monkeypatch)
        ff2 = small_model()
        sentinel = ff2.opt_state
        before = get_registry().get("ckpt/restore_read_bytes")
        assert load_sharded(str(tmp_path), ff2,
                            include_opt_state=False) == ff._iter
        assert ff2.opt_state is sentinel
        assert_tree_bitwise(ff.params, ff2.params, "params")
        # fewer bytes read than a full restore of the same checkpoint
        partial = get_registry().get("ckpt/restore_read_bytes") - before
        ff3 = small_model()
        load_sharded(str(tmp_path), ff3)
        full = (get_registry().get("ckpt/restore_read_bytes")
                - before - partial)
        assert partial < full

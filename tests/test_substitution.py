"""Graph-substitution engine (native/ffs_subst.hpp).

Analog of the reference's GraphXfer machinery: backtracking pattern
match + apply (src/runtime/substitution.cc:596), hand-written generators
(:1726-1860), the machine-generated rule corpus
(substitutions/graph_subst_3_v2.json + substitution_loader.cc), and the
best-first driver (base_optimize, substitution.cc:2229). Deviceless at the
native level; compile-level integration runs on the virtual 8-device mesh.
"""

import os

import numpy as np
import pytest

from flexflow_tpu.search.native import (available, native_list_rules,
                                        native_optimize)

pytestmark = pytest.mark.skipif(not available(),
                                reason="native ffsearch library unavailable")

MACHINE = {
    "num_devices": 8, "flops": 197e12, "hbm_bw": 0.82e12, "hbm_cap": 16e9,
    "ici_bw": 45e9, "ici_latency": 1e-6, "dcn_bw": 25e9, "dcn_latency": 1e-5,
    "num_slices": 1,
}

REFERENCE_CORPUS = "/root/reference/substitutions/graph_subst_3_v2.json"


def _cfg(**kw):
    base = dict(budget=5, alpha=0.05, only_data_parallel=False,
                enable_parameter_parallel=True, overlap=True, training=True,
                memory_threshold=0, seed=1, rules=[])
    base.update(kw)
    return base


def _node(guid, typ, name, inputs, ishapes, oshapes, roles=None, params=None,
          flops=0.0, attrs=None):
    return {
        "guid": guid, "type": typ, "name": name, "inputs": inputs,
        "input_shapes": ishapes, "output_shapes": oshapes,
        "roles": roles or [["sample"] + ["other"] * (len(s) - 1)
                           for s in oshapes],
        "params": params or {}, "flops": float(flops), "dtype_size": 4,
        "attrs": attrs or {},
    }


def _linear(guid, name, src, b, din, dout):
    return _node(guid, "LINEAR", name, [src], [[b, din]], [[b, dout]],
                 roles=[["sample", "channel"]],
                 params={"kernel": [din, dout], "bias": [dout]},
                 flops=2.0 * b * din * dout,
                 attrs={"out_dim": dout, "activation": 0, "use_bias": 1})


class TestRuleLoading:
    def test_native_rule_list_parses(self):
        rules = [{
            "name": "my_rule",
            "srcOp": [{"type": "COMBINE", "input": [{"opId": -1, "tsId": 0}],
                       "para": [{"key": "PM_PARALLEL_DIM", "value": 1}]}],
            "dstOp": [{"type": "IDENTITY", "input": [{"opId": -1, "tsId": 0}],
                       "para": []}],
            "mappedOutput": [{"srcOpId": 0, "srcTsId": 0,
                              "dstOpId": 0, "dstTsId": 0}],
        }]
        out = native_list_rules(rules)
        assert out["count"] == 1
        assert out["names"] == ["my_rule"]

    @pytest.mark.skipif(not os.path.exists(REFERENCE_CORPUS),
                        reason="reference corpus not mounted")
    def test_reference_640_rule_corpus_loads(self):
        # the full machine-generated TASO corpus in the reference
        # serializer's format (substitution_loader.cc RuleCollection)
        import json
        with open(REFERENCE_CORPUS) as f:
            data = json.load(f)
        out = native_list_rules(data)
        assert out["count"] == 640
        assert out["names"][0].startswith("taso_rule")


class TestNativeRewrites:
    def _pair_graph(self, b=512, d=1024):
        # linear -> Repartition(dim1,2) -> Combine(dim1,2) -> relu
        return [
            _linear(1, "lin", [-2, 0], b, d, d),
            _node(2, "REPARTITION", "part", [[1, 0]], [[b, d]], [[b, d]],
                  attrs={"dim": 1, "degree": 2}),
            _node(3, "COMBINE", "comb", [[2, 0]], [[b, d]], [[b, d]],
                  attrs={"dim": 1, "degree": 2}),
            _node(4, "RELU", "relu", [[3, 0]], [[b, d]], [[b, d]],
                  flops=b * d),
        ]

    def test_eliminates_inverse_parallel_op_pair(self):
        resp = native_optimize({"machine": MACHINE, "config": _cfg(budget=2),
                                "measured": {}, "nodes": self._pair_graph(),
                                "final": [4, 0]})
        rules = [r["rule"] for r in resp["rewrites"]]
        assert "eliminate_repartition_combine" in rules, rules
        assert resp["stats"]["rewrites_applied"] >= 1
        # the pair is gone from the strategy's op set; the relu survives
        assert "2" not in resp["ops"] and "3" not in resp["ops"]
        assert "4" in resp["ops"]
        # and the rewrite strictly improved the predicted time
        base = native_optimize({
            "machine": MACHINE, "config": _cfg(budget=2,
                                               enable_substitution=False),
            "measured": {}, "nodes": self._pair_graph(), "final": [4, 0]})
        assert resp["predicted_time"] < base["predicted_time"]
        assert base["stats"]["rewrites_applied"] == 0

    def test_move_then_eliminate_composition(self):
        # Combine -> RELU -> Repartition: neither boundary can be removed in
        # one step (the relu blocks adjacency). The best-first loop must
        # compose two rewrites — move the Combine past the relu
        # (cost-neutral), then eliminate the now-adjacent inverse pair —
        # killing the 128 MB all-gather entirely. This is the multi-step
        # behavior base_optimize's queue exists for (substitution.cc:2229).
        b, d = 1, 1 << 25
        nodes = [
            _node(1, "COMBINE", "comb", [[-2, 0]], [[b, d]], [[b, d]],
                  attrs={"dim": 1, "degree": 2}),
            _node(2, "RELU", "relu", [[1, 0]], [[b, d]], [[b, d]],
                  flops=b * d),
            _node(3, "REPARTITION", "part", [[2, 0]], [[b, d]], [[b, d]],
                  attrs={"dim": 1, "degree": 2}),
            _node(4, "GELU", "gelu", [[3, 0]], [[b, d]], [[b, d]],
                  flops=8.0 * b * d),
        ]
        machine = dict(MACHINE, num_devices=2)
        req = {"machine": machine, "config": _cfg(budget=4, batch=1),
               "measured": {}, "nodes": nodes, "final": [4, 0]}
        resp = native_optimize(req)
        rules = [r["rule"] for r in resp["rewrites"]]
        assert "move_combine_past_RELU" in rules, (rules, resp["stats"])
        assert "eliminate_combine_repartition" in rules, rules
        base = native_optimize(dict(
            req, config=_cfg(budget=4, batch=1, enable_substitution=False)))
        # the all-gather is gone: strictly faster than the unrewritten graph
        assert resp["predicted_time"] < base["predicted_time"] * 0.9

    def test_fuses_parallel_linears(self):
        # two same-input linears + add in the bandwidth-bound regime
        # (b >> d): one wide matmul + free split reads x once instead of
        # twice and saves a kernel dispatch. (Flop-bound shapes model no
        # win — the MXU does the same FLOPs either way — so the engine
        # correctly leaves those alone.)
        b, d = 8192, 256
        nodes = [
            _linear(1, "qa", [-2, 0], b, d, d),
            _linear(2, "qb", [-2, 0], b, d, d),
            _node(3, "EW_ADD", "add", [[1, 0], [2, 0]],
                  [[b, d], [b, d]], [[b, d]], flops=b * d),
        ]
        resp = native_optimize({"machine": MACHINE,
                                "config": _cfg(budget=2,
                                               enable_parameter_parallel=False),
                                "measured": {}, "nodes": nodes,
                                "final": [3, 0]})
        rules = [r["rule"] for r in resp["rewrites"]]
        assert "fuse_parallel_linears" in rules, (rules, resp["stats"])
        fusion = next(r for r in resp["rewrites"]
                      if r["rule"] == "fuse_parallel_linears")
        added_types = [a["type"] for a in fusion["added"]]
        assert added_types == ["LINEAR", "SPLIT"]
        wide = fusion["added"][0]
        assert wide["attrs"]["out_dim"] == 2 * d
        assert [list(map(int, s)) for s in wide["output_shapes"]] == [[b, 2 * d]]

    def test_moves_combines_past_binary(self):
        # Combine(a) + Combine(b) -> EW_ADD => EW_ADD -> Combine: one
        # all-gather instead of two, add runs sharded
        b, d = 64, 1 << 20
        nodes = [
            _node(1, "COMBINE", "ca", [[-2, 0]], [[b, d]], [[b, d]],
                  attrs={"dim": 1, "degree": 2}),
            _node(2, "COMBINE", "cb", [[-3, 0]], [[b, d]], [[b, d]],
                  attrs={"dim": 1, "degree": 2}),
            _node(3, "EW_ADD", "add", [[1, 0], [2, 0]],
                  [[b, d], [b, d]], [[b, d]], flops=b * d),
        ]
        machine = dict(MACHINE, num_devices=2)
        req = {"machine": machine, "config": _cfg(budget=3),
               "measured": {}, "nodes": nodes, "final": [3, 0]}
        resp = native_optimize(req)
        rules = [r["rule"] for r in resp["rewrites"]]
        assert "move_combines_past_EW_ADD" in rules, (rules, resp["stats"])
        base = native_optimize(dict(
            req, config=_cfg(budget=3, enable_substitution=False)))
        assert resp["predicted_time"] < base["predicted_time"]

    def test_moves_combine_past_conv(self):
        # Combine(batch) -> Conv => Conv -> Combine: the gather moves to
        # the conv's (4x smaller) output and the conv work stays sharded
        b, ci, co, hw = 8, 64, 16, 32
        conv_flops = 2.0 * b * co * hw * hw * ci * 9
        nodes = [
            _node(1, "COMBINE", "comb", [[-2, 0]],
                  [[b, ci, hw, hw]], [[b, ci, hw, hw]],
                  attrs={"dim": 0, "degree": 2}),
            _node(2, "CONV2D", "conv", [[1, 0]],
                  [[b, ci, hw, hw]], [[b, co, hw, hw]],
                  roles=[["sample", "channel", "other", "other"]],
                  params={"kernel": [co, ci, 3, 3], "bias": [co]},
                  flops=conv_flops, attrs={"groups": 1}),
            _node(3, "RELU", "relu", [[2, 0]],
                  [[b, co, hw, hw]], [[b, co, hw, hw]],
                  flops=b * co * hw * hw),
        ]
        machine = dict(MACHINE, num_devices=2)
        req = {"machine": machine, "config": _cfg(budget=3, batch=b),
               "measured": {}, "nodes": nodes, "final": [3, 0]}
        resp = native_optimize(req)
        rules = [r["rule"] for r in resp["rewrites"]]
        assert "move_combine_past_CONV2D" in rules, (rules, resp["stats"])
        base = native_optimize(dict(
            req, config=_cfg(budget=3, batch=b, enable_substitution=False)))
        assert resp["predicted_time"] < base["predicted_time"]

    def test_repartition_push_subsumed_by_choice_dp(self):
        # Reference rule family: RELU -> Repartition => Repartition -> RELU
        # (shard the elementwise work earlier). In this framework's design
        # parallelism is a per-op *sharding choice*, not a graph edit, so
        # the DP reaches the sharded cost directly: the unary ops pick
        # 'mp_last' upstream of the boundary and the rewrite is redundant.
        # The rule ships in the corpus for reference parity; this test pins
        # the subsumption (no rewrite needed, work already sharded).
        b, d = 1, 1 << 22
        nodes = [
            _node(1, "RELU", "relu", [[-2, 0]], [[b, d]], [[b, d]],
                  flops=b * d),
            _node(2, "REPARTITION", "part", [[1, 0]], [[b, d]], [[b, d]],
                  attrs={"dim": 1, "degree": 2}),
            _node(3, "GELU", "gelu", [[2, 0]], [[b, d]], [[b, d]],
                  flops=8.0 * b * d),
        ]
        machine = dict(MACHINE, num_devices=2)
        resp = native_optimize({"machine": machine,
                                "config": _cfg(budget=3, batch=b),
                                "measured": {}, "nodes": nodes,
                                "final": [3, 0]})
        # the unaries run model-sharded without any graph rewrite
        assert resp["ops"]["1"]["choice"] == "mp_last"
        assert resp["ops"]["3"]["choice"] == "mp_last"
        base = native_optimize({"machine": machine,
                                "config": _cfg(budget=3, batch=b,
                                               enable_substitution=False),
                                "measured": {}, "nodes": nodes,
                                "final": [3, 0]})
        assert resp["predicted_time"] <= base["predicted_time"] + 1e-12

    def test_concat_of_combines_merges_gathers(self):
        b, d = 32, 1 << 18
        nodes = [
            _node(1, "COMBINE", "ca", [[-2, 0]], [[b, d]], [[b, d]],
                  attrs={"dim": 1, "degree": 2}),
            _node(2, "COMBINE", "cb", [[-3, 0]], [[b, d]], [[b, d]],
                  attrs={"dim": 1, "degree": 2}),
            _node(3, "CONCAT", "cat", [[1, 0], [2, 0]],
                  [[b, d], [b, d]], [[2 * b, d]], attrs={"axis": 0}),
        ]
        machine = dict(MACHINE, num_devices=2)
        req = {"machine": machine, "config": _cfg(budget=3),
               "measured": {}, "nodes": nodes, "final": [3, 0]}
        resp = native_optimize(req)
        rules = [r["rule"] for r in resp["rewrites"]]
        assert "concat_of_combines_d1_a0" in rules, (rules, resp["stats"])
        base = native_optimize(dict(
            req, config=_cfg(budget=3, enable_substitution=False)))
        assert resp["predicted_time"] < base["predicted_time"]

    def test_rewrite_never_drops_designated_output(self):
        # final on the Repartition's output: eliminating the pair would lose
        # it (the rule maps only the Combine output) — engine must refuse
        nodes = self._pair_graph()
        resp = native_optimize({"machine": MACHINE, "config": _cfg(budget=2),
                                "measured": {}, "nodes": nodes,
                                "final": [2, 0]})
        for r in resp["rewrites"]:
            assert 2 not in r["removed"] or any(
                rm[0] == 2 for rm in r["output_remap"]), resp["rewrites"]
        assert "2" in resp["ops"]


class TestTraceReplay:
    def _nodes(self):
        from flexflow_tpu.executor import OpNode
        from flexflow_tpu.layer import Layer
        from flexflow_tpu.ffconst import DataType, OperatorType
        from flexflow_tpu.ops import OpRegistry
        lyr = Layer(OperatorType.LINEAR, "lin", [], data_type=DataType.FLOAT)
        lyr.properties.update(out_dim=8, use_bias=True)
        op = OpRegistry.create(lyr, [(4, 16)])
        return [OpNode(op, [("input", "x")])]

    def test_malformed_trace_raises_runtime_error(self):
        from flexflow_tpu.search.rewrite import apply_rewrites
        nodes = self._nodes()
        bad = [{"rule": "r", "removed": [], "output_remap": [],
                "added": [{"type": "LINEAR", "name": "n", "guid": 99,
                           "inputs": [[-7, 0]],  # unknown external id
                           "attrs": {}, "output_shapes": [[4, 8]]}]}]
        with pytest.raises(RuntimeError):
            apply_rewrites(nodes, bad)

    def test_failed_replay_leaves_caller_nodes_untouched(self):
        from flexflow_tpu.executor import OpNode
        from flexflow_tpu.layer import Layer
        from flexflow_tpu.ffconst import DataType, OperatorType
        from flexflow_tpu.ops import OpRegistry
        from flexflow_tpu.search.rewrite import apply_rewrites
        nodes = self._nodes()
        guid = nodes[0].guid
        relu = Layer(OperatorType.RELU, "relu", [], data_type=DataType.FLOAT)
        consumer = OpNode(OpRegistry.create(relu, [(4, 8)]),
                          [("op", guid, 0)])
        nodes.append(consumer)
        before = [list(n.input_refs) for n in nodes]
        # first entry valid — replaces the linear with a fresh one and
        # REWIRES the consumer's input ref via output_remap; second entry
        # malformed — the caller's nodes must not see the partial rewrite
        trace = [
            {"rule": "ok", "removed": [guid],
             "output_remap": [[guid, 0, 50, 0]],
             "added": [{"type": "LINEAR", "name": "n", "guid": 50,
                        "inputs": [[-2, 0]],
                        "attrs": {"out_dim": 8, "use_bias": 1},
                        "output_shapes": [[4, 8]]}]},
            {"rule": "bad", "removed": [], "output_remap": [],
             "added": [{"type": "NOT_A_TYPE", "name": "x", "guid": 51,
                        "inputs": [[50, 0]], "attrs": {},
                        "output_shapes": [[4, 8]]}]},
        ]
        with pytest.raises(RuntimeError):
            apply_rewrites(nodes, trace)
        assert [list(n.input_refs) for n in nodes] == before
        assert consumer.input_refs == [("op", guid, 0)]


class TestCompileIntegration:
    def test_pair_elimination_through_compile(self):
        from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                                  SGDOptimizer)
        from flexflow_tpu.ffconst import ActiMode, OperatorType

        cfg = FFConfig(batch_size=32, search_budget=3,
                       enable_parameter_parallel=True)
        ff = FFModel(cfg)
        t = ff.create_tensor((32, 16))
        h = ff.dense(t, 64, activation=ActiMode.AC_MODE_RELU)
        h = ff.repartition(h, dim=1, degree=2)
        h = ff.combine(h, dim=1, degree=2)
        out = ff.dense(h, 4)
        out = ff.softmax(out)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.ACCURACY])
        assert ff.search_info["stats"]["rewrites_applied"] >= 1
        types = [n.op.op_type for n in ff.executor.nodes]
        assert OperatorType.REPARTITION not in types
        assert OperatorType.COMBINE not in types
        rs = np.random.RandomState(0)
        x = rs.randn(32, 16).astype(np.float32)
        y = rs.randint(0, 4, (32, 1)).astype(np.int32)
        ff.fit(x, y, epochs=1, verbose=False)
        preds = ff.predict(x)
        assert preds.shape == (32, 4)
        assert np.isfinite(preds).all()

    def test_linear_fusion_through_compile(self):
        from flexflow_tpu import (FFConfig, FFModel, LossType, SGDOptimizer)
        from flexflow_tpu.ffconst import OperatorType

        cfg = FFConfig(batch_size=64, search_budget=3,
                       enable_parameter_parallel=False)
        ff = FFModel(cfg)
        t = ff.create_tensor((64, 256))
        a = ff.dense(t, 128, name="qa")
        b = ff.dense(t, 128, name="qb")
        out = ff.add(a, b)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
                   outputs=out)
        assert ff.search_info["stats"]["rewrites_applied"] >= 1
        # fused: one wide linear + split replaced the two linears
        types = [n.op.op_type for n in ff.executor.nodes]
        assert types.count(OperatorType.LINEAR) == 1
        assert OperatorType.SPLIT in types
        rs = np.random.RandomState(0)
        x = rs.randn(64, 256).astype(np.float32)
        y = rs.randn(64, 128).astype(np.float32)
        ff.fit(x, y, epochs=1, verbose=False)
        preds = ff.predict(x)
        assert preds.shape == (64, 128)
        assert np.isfinite(preds).all()

    def test_disable_substitution_flag(self):
        from flexflow_tpu import (FFConfig, FFModel, LossType, SGDOptimizer)

        cfg = FFConfig(batch_size=32, search_budget=3,
                       enable_parameter_parallel=True,
                       enable_substitution=False)
        ff = FFModel(cfg)
        t = ff.create_tensor((32, 16))
        h = ff.repartition(ff.dense(t, 64), dim=1, degree=2)
        h = ff.combine(h, dim=1, degree=2)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        assert ff.search_info["stats"]["rewrites_applied"] == 0

    def test_default_corpus_loaded_at_startup(self):
        # the shipped corpus (substitutions/ffs_subst_v1.json — analog of
        # the reference's graph_subst_3_v2.json) loads when no explicit
        # --substitution-json is given
        from flexflow_tpu import (FFConfig, FFModel, LossType, SGDOptimizer)

        cfg = FFConfig(batch_size=32, search_budget=2,
                       enable_parameter_parallel=True)
        ff = FFModel(cfg)
        t = ff.create_tensor((32, 16))
        ff.dense(t, 8)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        # 24 builtin generator rules + 54 corpus rules
        assert ff.search_info["stats"]["rules_loaded"] >= 70

    def test_reference_corpus_accepted_by_compile(self, tmp_path):
        # --substitution-json pointing at a reference-format corpus must
        # load (rules parse; none need apply on this graph)
        import json as _json
        from flexflow_tpu import (FFConfig, FFModel, LossType, SGDOptimizer)

        corpus = {"_t": "RuleCollection", "rule": [{
            "_t": "Rule", "name": "ref_style_rule",
            "srcOp": [
                {"_t": "Operator", "type": "OP_PARTITION",
                 "input": [{"opId": -1, "tsId": 0}],
                 "para": [{"key": "PM_PARALLEL_DIM", "value": 0},
                          {"key": "PM_PARALLEL_DEGREE", "value": 2}]},
                {"_t": "Operator", "type": "OP_COMBINE",
                 "input": [{"opId": 0, "tsId": 0}],
                 "para": [{"key": "PM_PARALLEL_DIM", "value": 0},
                          {"key": "PM_PARALLEL_DEGREE", "value": 2}]},
            ],
            "dstOp": [{"_t": "Operator", "type": "OP_PARTITION",
                       "input": [{"opId": -1, "tsId": 0}],
                       "para": [{"key": "PM_PARALLEL_DIM", "value": 0},
                                {"key": "PM_PARALLEL_DEGREE", "value": 2}]}],
            "mappedOutput": [{"srcOpId": 1, "srcTsId": 0,
                              "dstOpId": 0, "dstTsId": 0}],
        }]}
        path = tmp_path / "rules.json"
        path.write_text(_json.dumps(corpus))
        cfg = FFConfig(batch_size=32, search_budget=2,
                       enable_parameter_parallel=True,
                       substitution_json=str(path))
        ff = FFModel(cfg)
        t = ff.create_tensor((32, 16))
        ff.dense(t, 8)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        # builtin generators + the file's rule all loaded
        assert ff.search_info["stats"]["rules_loaded"] >= 9


class TestComputeRewriteFamilies:
    """r4 algebraic families (VERDICT r3 Next #5): QKV 3-linear merge,
    activation-epilogue fusion, Conv+BN fold (inference), and
    fuse_parallel_ops -> FusedParallelOp. Each must strictly improve
    predicted time and survive compile-and-train."""

    def test_qkv_merge_improves_and_trains(self):
        from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
        from flexflow_tpu.ffconst import OperatorType

        # native level: 3 same-input linears (the qkv pattern) in the
        # bandwidth-bound regime on ONE device -> one wide matmul + split
        # wins (at dp > 1 the engine deliberately prefers pairwise fusion:
        # a lone merged matmul leaves its gradient all-reduce nothing to
        # overlap with — measured in the list schedule)
        b, d = 8192, 256
        nodes = [
            _linear(1, "q", [-2, 0], b, d, d),
            _linear(2, "k", [-2, 0], b, d, d),
            _linear(3, "v", [-2, 0], b, d, d),
            _node(4, "CONCAT", "cat", [[1, 0], [2, 0], [3, 0]],
                  [[b, d]] * 3, [[b, 3 * d]], attrs={"axis": 1}),
        ]
        base = {"machine": dict(MACHINE, num_devices=1), "measured": {},
                "nodes": nodes, "final": [4, 0]}
        resp = native_optimize(dict(
            base, config=_cfg(budget=3, enable_parameter_parallel=False)))
        no_rw = native_optimize(dict(
            base, config=_cfg(budget=3, enable_parameter_parallel=False,
                              enable_substitution=False)))
        rules = [r["rule"] for r in resp["rewrites"]]
        assert any("fuse_parallel_linears3" in r for r in rules), rules
        assert resp["predicted_time"] < no_rw["predicted_time"]

        # compile-and-train through FFModel
        cfg = FFConfig(batch_size=64, search_budget=3,
                       enable_parameter_parallel=False)
        ff = FFModel(cfg)
        t = ff.create_tensor((64, 256))
        q = ff.dense(t, 64, name="q")
        k = ff.dense(t, 64, name="k")
        v = ff.dense(t, 64, name="v")
        out = ff.concat([q, k, v], axis=1)
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [], outputs=out)
        rs = np.random.RandomState(0)
        x = rs.randn(64, 256).astype(np.float32)
        y = rs.randn(64, 192).astype(np.float32)
        ff.fit(x, y, epochs=1, verbose=False)
        assert np.isfinite(ff.predict(x)).all()

    def test_linear_activation_fusion(self):
        from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
        from flexflow_tpu.ffconst import ActiMode, OperatorType

        b, d = 4096, 512
        nodes = [
            _linear(1, "fc", [-2, 0], b, d, d),
            _node(2, "RELU", "act", [[1, 0]], [[b, d]], [[b, d]],
                  flops=b * d),
        ]
        base = {"machine": MACHINE, "measured": {}, "nodes": nodes,
                "final": [2, 0]}
        resp = native_optimize(dict(
            base, config=_cfg(budget=2, enable_parameter_parallel=False)))
        no_rw = native_optimize(dict(
            base, config=_cfg(budget=2, enable_parameter_parallel=False,
                              enable_substitution=False)))
        rules = [r["rule"] for r in resp["rewrites"]]
        assert any("fuse_linear_RELU" in r for r in rules), rules
        assert resp["predicted_time"] < no_rw["predicted_time"]
        fused = next(r for r in resp["rewrites"]
                     if "fuse_linear_RELU" in r["rule"])
        assert fused["added"][0]["attrs"]["activation"] == 1

        # compile-and-train: the fused Linear must carry the relu
        cfg = FFConfig(batch_size=64, search_budget=2,
                       enable_parameter_parallel=False)
        ff = FFModel(cfg)
        t = ff.create_tensor((64, 128))
        h = ff.dense(t, 64, name="fc")
        out = ff.relu(h)
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [], outputs=out)
        if ff.search_info["stats"]["rewrites_applied"]:
            types = [n.op.op_type for n in ff.executor.nodes]
            assert OperatorType.RELU not in types
            lin = next(n.op for n in ff.executor.nodes
                       if n.op.op_type == OperatorType.LINEAR)
            assert lin.activation == ActiMode.AC_MODE_RELU
        rs = np.random.RandomState(0)
        x = rs.randn(64, 128).astype(np.float32)
        y = rs.randn(64, 64).astype(np.float32)
        ff.fit(x, y, epochs=1, verbose=False)
        out_np = ff.predict(x)
        assert (out_np >= 0).all()  # relu survived the rewrite

    def test_conv_bn_fold_exact_numerics(self):
        """Conv+BN fold as the explicit post-import pass
        (flexflow_tpu.transforms.fold_conv_batchnorm): numerics must
        match the unfused model EXACTLY — rewrites re-init weights, which
        is why this is not an automatic search rule."""
        from flexflow_tpu import FFConfig, FFModel, LossType
        from flexflow_tpu.ffconst import CompMode, OperatorType
        from flexflow_tpu.transforms import fold_conv_batchnorm

        rs = np.random.RandomState(0)
        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 4, 8, 8))
        t = ff.conv2d(t, 4, 3, 3, 1, 1, 1, 1, use_bias=False, name="conv")
        t = ff.batch_norm(t, relu=True, name="bn")
        t = ff.flat(t)
        t = ff.dense(t, 4, name="head")
        ff.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
                   comp_mode=CompMode.INFERENCE)
        # pretrained-looking weights + non-trivial BN stats
        ff.set_parameter("conv",
                         rs.randn(4, 4, 3, 3).astype(np.float32) * 0.3)
        ff.set_parameter("bn", rs.rand(4).astype(np.float32) + 0.5, "scale")
        ff.set_parameter("bn", rs.randn(4).astype(np.float32) * 0.1, "bias")
        ff.state["bn"] = {
            "mean": np.asarray(rs.randn(4), np.float32) * 0.2,
            "var": np.asarray(rs.rand(4), np.float32) + 0.5,
        }
        x = rs.randn(8, 4, 8, 8).astype(np.float32)
        want = ff.predict(x)
        assert fold_conv_batchnorm(ff) == 1
        types = [n.op.op_type for n in ff.executor.nodes]
        assert OperatorType.BATCHNORM not in types
        conv = next(n.op for n in ff.executor.nodes
                    if n.op.op_type == OperatorType.CONV2D)
        assert conv.use_bias
        got = ff.predict(x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

        # training-compiled models must refuse the fold
        ff_tr = FFModel(FFConfig(batch_size=8))
        t = ff_tr.create_tensor((8, 4, 8, 8))
        t = ff_tr.conv2d(t, 4, 3, 3, 1, 1, 1, 1, name="conv")
        t = ff_tr.batch_norm(t, relu=True, name="bn")
        ff_tr.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
        with pytest.raises(ValueError, match="INFERENCE"):
            fold_conv_batchnorm(ff_tr)

    def test_fuse_parallel_ops_produces_fused_op(self):
        from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
        from flexflow_tpu.ffconst import OperatorType

        # native level: Combine(d1) -> Replicate chain
        b, d = 2048, 1024
        nodes = [
            _linear(1, "fc", [-2, 0], b, d, d),
            _node(2, "COMBINE", "comb", [[1, 0]], [[b, d]], [[b, d]],
                  attrs={"dim": 1, "degree": 2}),
            _node(3, "REPLICATE", "repl", [[2, 0]], [[b, d]], [[b, d]],
                  attrs={"degree": 2}),
            _linear(4, "fc2", [3, 0], b, d, d),
        ]
        base = {"machine": MACHINE, "measured": {}, "nodes": nodes,
                "final": [4, 0]}
        resp = native_optimize(dict(base, config=_cfg(budget=3)))
        rules = [r["rule"] for r in resp["rewrites"]]
        assert any("fuse_parallel_ops" in r for r in rules), rules
        fused = next(r for r in resp["rewrites"]
                     if "fuse_parallel_ops" in r["rule"])
        assert fused["added"][0]["type"] == "FUSED_PARALLEL"
        assert fused["added"][0]["attrs"]["ops"] == [
            ["COMBINE", 1, 2], ["REPLICATE", 0, 2]]

        # compile-and-train with the explicit PCG chain
        cfg = FFConfig(batch_size=32, search_budget=3,
                       enable_parameter_parallel=True)
        ff = FFModel(cfg)
        t = ff.create_tensor((32, 16))
        h = ff.dense(t, 64, name="fc")
        h = ff.combine(h, dim=1, degree=2)
        h = ff.replicate(h, degree=2)
        out = ff.dense(h, 16, name="fc2")
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [], outputs=out)
        if any("fuse_parallel_ops" in r["rule"]
               for r in ff.search_info.get("rewrites", [])):
            types = [n.op.op_type for n in ff.executor.nodes]
            assert OperatorType.FUSED_PARALLEL in types
        rs = np.random.RandomState(0)
        x = rs.randn(32, 16).astype(np.float32)
        y = rs.randn(32, 16).astype(np.float32)
        ff.fit(x, y, epochs=1, verbose=False)
        assert np.isfinite(ff.predict(x)).all()

    def test_disable_fusion_gates_fuse_parallel_ops(self):
        """--disable-fusion (perform_fusion=False) must drop the
        fuse_parallel_ops rewrite family and nothing else."""
        b, d = 2048, 1024
        nodes = [
            _linear(1, "fc", [-2, 0], b, d, d),
            _node(2, "COMBINE", "comb", [[1, 0]], [[b, d]], [[b, d]],
                  attrs={"dim": 1, "degree": 2}),
            _node(3, "REPLICATE", "repl", [[2, 0]], [[b, d]], [[b, d]],
                  attrs={"degree": 2}),
            _linear(4, "fc2", [3, 0], b, d, d),
        ]
        base = {"machine": MACHINE, "measured": {}, "nodes": nodes,
                "final": [4, 0]}
        resp = native_optimize(
            dict(base, config=dict(_cfg(budget=3), perform_fusion=False)))
        rules = [r["rule"] for r in resp.get("rewrites", [])]
        assert not any("fuse_parallel_ops" in r for r in rules), rules


class TestNewCorpusFamilyNumerics:
    """r5 corpus families (5b, 11, 12, 13, 14): executor-level parity —
    compile WITH the single rule vs WITHOUT substitution, copy weights by
    layer name, and the predictions must match (all five are layout
    rewrites, value-preserving by construction)."""

    def _rule(self, name):
        import json as _json
        corpus = _json.load(open(os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "substitutions", "ffs_subst_v1.json")))
        return next(r for r in corpus if r["name"] == name)

    def _parity(self, build, rule_name, tmp_path, x, workers=2):
        import json as _json

        from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer

        path = tmp_path / "rule.json"
        path.write_text(_json.dumps([self._rule(rule_name)]))
        outs = {}
        fired = None
        for key, kw in (("plain", dict(enable_substitution=False)),
                        ("rewritten",
                         dict(substitution_json=str(path)))):
            # device count pinned so the searched mesh's axis extents can
            # match the graph's explicit degree-2 parallel ops (GSPMD
            # legality: degree == axis extent)
            cfg = FFConfig(batch_size=x.shape[0], search_budget=4,
                           enable_parameter_parallel=True,
                           workers_per_node=workers, num_nodes=1, **kw)
            ff = FFModel(cfg)
            build(ff)
            ff.compile(SGDOptimizer(lr=0.05),
                       LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
            if key == "plain":
                ref = ff
            else:
                fired = [r["rule"] for r in
                         (ff.search_info or {}).get("rewrites", [])]
                for name in ff.get_layer_names():
                    for pname in list(ref.params.get(name, {})):
                        try:
                            ff.set_parameter(
                                name, ref.get_parameter(name, pname), pname)
                        except KeyError:
                            pass
            outs[key] = ff.predict(x)
        np.testing.assert_allclose(outs["rewritten"], outs["plain"],
                                   rtol=2e-4, atol=2e-5)
        return fired

    def test_replicate_past_unary(self, tmp_path):
        def build(ff):
            t = ff.create_tensor((32, 16))
            h = ff.dense(t, 16, name="fc")
            h = ff.replicate(h, degree=2)
            h = ff.relu(h)
            ff.dense(h, 8, name="out")

        rs = np.random.RandomState(0)
        self._parity(build, "corpus_move_replicate_past_RELU",
                     tmp_path, rs.randn(32, 16).astype(np.float32))

    def test_merge_repartitions_below_binary(self, tmp_path):
        def build(ff):
            t = ff.create_tensor((32, 16))
            a = ff.dense(t, 16, name="a")
            # different producer for b, so the builtin same-input QKV
            # merge (fuse_parallel_linears) can't fire and re-init weights
            b = ff.dense(ff.scalar_multiply(t, 0.5), 16, name="b")
            a = ff.repartition(a, dim=0, degree=2)
            b = ff.repartition(b, dim=0, degree=2)
            ff.add(a, b)

        rs = np.random.RandomState(1)
        self._parity(build, "corpus_merge_repartitions_below_EW_ADD_d0",
                     tmp_path, rs.randn(32, 16).astype(np.float32))

    def test_shard_binary_via_repartition(self, tmp_path):
        def build(ff):
            t = ff.create_tensor((32, 16))
            a = ff.dense(t, 16, name="a")
            b = ff.dense(ff.scalar_multiply(t, 0.5), 16, name="b")
            s = ff.add(a, b)
            ff.repartition(s, dim=0, degree=2)

        rs = np.random.RandomState(2)
        self._parity(build, "corpus_shard_EW_ADD_via_repartition_d0",
                     tmp_path, rs.randn(32, 16).astype(np.float32))

    def test_concat_of_repartitions(self, tmp_path):
        def build(ff):
            t = ff.create_tensor((32, 16))
            a = ff.dense(t, 16, name="a")
            b = ff.dense(ff.scalar_multiply(t, 0.5), 16, name="b")
            a = ff.repartition(a, dim=0, degree=2)
            b = ff.repartition(b, dim=0, degree=2)
            ff.concat([a, b], axis=1)

        rs = np.random.RandomState(3)
        self._parity(build, "corpus_concat_of_repartitions_d0_a1",
                     tmp_path, rs.randn(32, 16).astype(np.float32))

    def test_fuse_repartition_repartition(self):
        """Family 14 at the native level: Repartition(d0) -> Repartition(d1)
        collapses into one FUSED_PARALLEL boundary (executor numerics of
        FUSED_PARALLEL are covered by the family-10 compile test)."""
        import json as _json
        corpus = _json.load(open(os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "substitutions", "ffs_subst_v1.json")))
        rule = next(r for r in corpus
                    if r["name"] == "corpus_fuse_parallel_ops_part0_part1")
        b, d = 2048, 1024
        nodes = [
            _linear(1, "fc", [-2, 0], b, d, d),
            _node(2, "REPARTITION", "rp0", [[1, 0]], [[b, d]], [[b, d]],
                  attrs={"dim": 0, "degree": 2}),
            _node(3, "REPARTITION", "rp1", [[2, 0]], [[b, d]], [[b, d]],
                  attrs={"dim": 1, "degree": 2}),
            _linear(4, "fc2", [3, 0], b, d, d),
        ]
        # 4 devices: the pinned degree-2 axes ({data:2, model:2}) must
        # factor the machine exactly (enumerate_meshes now refuses
        # meshes the executor's degree==extent legality would reject)
        resp = native_optimize({
            "machine": dict(MACHINE, num_devices=4), "measured": {},
            "nodes": nodes, "final": [4, 0],
            "config": _cfg(budget=3, rules=[], subst_budget=16),
            "subst_rules": [rule]})
        fired = [r["rule"] for r in resp.get("rewrites", [])]
        assert any("fuse_parallel_ops_part0_part1" in r for r in fired), fired
        added = next(r for r in resp["rewrites"]
                     if "fuse_parallel_ops_part0_part1" in r["rule"])
        assert added["added"][0]["type"] == "FUSED_PARALLEL"

    def test_broadcast_rank_mismatch_is_rejected(self):
        """Soundness guard: a rule moving parallel ops across a binary
        must NOT apply when the operands' ranks differ (dim indices
        would refer to different logical axes — advisor r5 finding)."""
        from flexflow_tpu.search.native import native_match_rules

        import json as _json
        corpus = _json.load(open(os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "substitutions", "ffs_subst_v1.json")))
        rule = next(r for r in corpus
                    if r["name"] == "corpus_shard_EW_ADD_via_repartition_d0")
        b = 8
        nodes = [
            {"guid": 1, "type": "EW_ADD", "name": "add",
             "inputs": [[-1, 0], [-2, 0]],
             "input_shapes": [[b, 4, 6, 8], [6, 8]],
             "output_shapes": [[b, 4, 6, 8]],
             "roles": [["sample", "other", "other", "other"]],
             "params": {}, "flops": float(b * 4 * 6 * 8),
             "dtype_size": 4, "attrs": {}},
            {"guid": 2, "type": "REPARTITION", "name": "rp",
             "inputs": [[1, 0]], "input_shapes": [[b, 4, 6, 8]],
             "output_shapes": [[b, 4, 6, 8]],
             "roles": [["sample", "other", "other", "other"]],
             "params": {}, "flops": 0.0, "dtype_size": 4,
             "attrs": {"dim": 0, "degree": 2}},
        ]
        resp = native_match_rules({"nodes": nodes, "subst_rules": [rule]})
        stats = resp[rule["name"]]
        assert stats["applied"] == 0, (
            f"rank-mismatched broadcast must not rewrite: {stats}")

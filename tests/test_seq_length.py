"""FFIterationConfig.seq_length semantics (VERDICT r4 Missing #5).

The reference threads seq_length through forward/backward so short
batches skip compute (config.h:162-167, model.h:481-485 BatchMatmul
a/b_seq_length_dim). TPU design: the iteration protocol dispatches to a
BUCKET executor — the same layer graph re-materialized at the next
power-of-two length — so every op runs at the active length under a
bounded set of static jit shapes.
"""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models.transformer import TransformerConfig, create_transformer

S_FULL = 64
S_ACTIVE = 32  # power of two: bucket == active length (exact parity)


def _model(seq_length):
    cfg = TransformerConfig(num_layers=1, hidden_size=16, num_heads=2,
                            seq_length=seq_length, batch_size=4)
    ff = create_transformer(cfg, FFConfig(batch_size=4,
                                          only_data_parallel=True))
    ff.compile(SGDOptimizer(lr=0.1), LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               [MetricsType.MEAN_SQUARED_ERROR])
    return ff


def _batch():
    rs = np.random.RandomState(0)
    x = rs.randn(4, S_FULL, 16).astype(np.float32)
    y = rs.randn(4, S_FULL, 1).astype(np.float32)
    return x, y


class TestSeqLengthIteration:
    def test_short_seq_matches_truncated_model(self):
        """forward(seq_length=32) on a seq-64 model must train exactly
        like a seq-32 model fed the truncated batch (same seed => same
        init params)."""
        x, y = _batch()
        ff = _model(S_FULL)
        ff.set_batch(x, y)
        ff.forward(seq_length=S_ACTIVE)
        ff.zero_gradients()
        ff.backward()
        ff.update()

        ref = _model(S_ACTIVE)
        ref.set_batch(x[:, :S_ACTIVE], y[:, :S_ACTIVE])
        ref.forward()
        ref.zero_gradients()
        ref.backward()
        ref.update()

        assert ff._last_loss == pytest.approx(ref._last_loss, rel=1e-5)
        for name in ff.get_layer_names():
            for pname in list(ff.params.get(name, {})):
                np.testing.assert_allclose(
                    ff.get_parameter(name, pname),
                    ref.get_parameter(name, pname), rtol=1e-5, atol=1e-6,
                    err_msg=f"{name}.{pname} diverged")

    def test_bucket_runs_fewer_flops(self):
        """The bucket executor's op graph computes at the active length —
        measurably less work, the point of the reference's seq_length."""
        ff = _model(S_FULL)
        x, y = _batch()
        ff.set_batch(x, y)
        ff.forward(seq_length=S_ACTIVE)
        ff.update()
        bucket_ex = ff._seq_execs[S_ACTIVE]
        full = sum(n.op.flops() for n in ff.executor.nodes)
        bucket = sum(n.op.flops() for n in bucket_ex.nodes)
        assert bucket < 0.6 * full, (bucket, full)

    def test_bucket_is_power_of_two_and_bounded(self):
        ff = _model(S_FULL)
        assert ff._seq_bucket(20) == 32   # next pow2
        assert ff._seq_bucket(32) == 32
        assert ff._seq_bucket(33) is None  # pow2 == declared: full path
        assert ff._seq_bucket(64) is None
        assert ff._seq_bucket(None) is None
        # repeated short iterations reuse ONE bucket executable
        x, y = _batch()
        ff.set_batch(x, y)
        for L in (17, 20, 25):
            ff.forward(seq_length=L)
            ff.update()
        assert list(ff._seq_execs) == [32]

    def test_no_seq_dim_model_ignores_seq_length(self):
        """MLPs have no SEQ-role dim: seq_length args are ignored, as in
        the reference where only seq ops consume FFIterationConfig."""
        ff = FFModel(FFConfig(batch_size=8, only_data_parallel=True))
        t = ff.create_tensor((8, 16))
        ff.dense(t, 4)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        rs = np.random.RandomState(1)
        ff.set_batch(rs.randn(8, 16).astype(np.float32),
                     rs.randn(8, 4).astype(np.float32))
        ff.forward(seq_length=7)
        ff.update()
        assert ff._declared_seq() is None
        assert not ff._seq_execs

"""fflint verifier tests (flexflow_tpu/analysis).

Each pass proves it fires on a hand-seeded violation — illegal sharding
degree, unpriced collective, mismatched host order, bf16 statistics,
redundant transposes, dead ops — with the rule id and severity the
README catalog promises, plus a clean-model no-diagnostics case and the
compile-time wiring (``compile(lint="error")`` rejects an illegal
imported strategy before any parameter is allocated). Diagnostics carry
tensor-level anchors (``out[i]`` / ``in[j]`` / ``param:name``) so a rule
points at the offending tensor, not just the op; the edge-level
collective rules (FFL205/210-213) are exercised in tests/test_dataflow.py.
"""

import json
import os

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from flexflow_tpu import (DataType, FFConfig, FFModel, LossType,
                          SGDOptimizer, Severity, lint_model)
from flexflow_tpu.analysis import LintContext, run_passes
from flexflow_tpu.analysis.passes.calibration import CalibrationPass
from flexflow_tpu.analysis.passes.collectives import (
    CollectiveInferencePass, infer_strategy_collectives)
from flexflow_tpu.analysis.passes.dtype import DtypePolicyPass
from flexflow_tpu.analysis.passes.hygiene import GraphHygienePass
from flexflow_tpu.analysis.passes.layout import LayoutConsistencyPass
from flexflow_tpu.analysis.passes.multihost import (MultihostOrderPass,
                                                    collective_sequence)
from flexflow_tpu.analysis.passes.sharding import ShardingLegalityPass

pytestmark = pytest.mark.analysis


def small_mlp(batch=16, compile_kw=None, **cfg_kw):
    from flexflow_tpu.models.mlp import create_mlp
    ff = create_mlp(batch_size=batch, in_dim=64, hidden_dims=(128, 128),
                    out_dim=10, ff_config=FFConfig(batch_size=batch,
                                                   **cfg_kw))
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [],
               **(compile_kw or {}))
    return ff


def ctx_of(ff, **kw):
    return LintContext(nodes=ff.executor.nodes, mesh=ff.mesh,
                       strategy=ff.strategy, machine_spec=ff.machine_spec,
                       config=ff.config, final_ref=ff.executor.final_ref,
                       ff=ff, **kw)


def rules(diags):
    return {d.rule for d in diags}


class TestCleanModel:
    def test_no_diagnostics_on_clean_mlp(self):
        rep = lint_model(small_mlp())
        assert not rep.errors and not rep.warnings, rep.format_human()
        # the static passes all ran; multihost/calibration record WHY not
        assert rep.passes["sharding-legality"] == "ok"
        assert rep.passes["graph-hygiene"] == "ok"
        assert "skipped" in rep.passes["multihost-order"]
        assert "skipped" in rep.passes["calibration"]

    def test_report_json_shape(self):
        rep = lint_model(small_mlp())
        doc = rep.to_json()
        assert set(doc) == {"context", "passes", "counts", "diagnostics"}
        assert doc["counts"] == dict(error=0, warning=0, info=0)
        json.dumps(doc)  # serializable


class TestShardingLegality:
    def test_illegal_degree_fires_ffl101(self):
        ff = small_mlp()
        # head output dim is 10: sharding it 8-way cannot divide
        head = ff.executor.nodes[-2]
        head.output_specs[0] = P(None, "data")
        diags = run_passes(ctx_of(ff), [ShardingLegalityPass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL101"]
        assert hits and hits[0].severity == Severity.ERROR
        assert "not divisible" in hits[0].message
        # diagnostics anchor the offending TENSOR, not just the op
        assert hits[0].tensor == "out[0]"

    def test_unknown_axis_fires_ffl102(self):
        ff = small_mlp()
        ff.executor.nodes[0].output_specs[0] = P("bogus")
        diags = run_passes(ctx_of(ff), [ShardingLegalityPass()]).diagnostics
        assert any(d.rule == "FFL102" and d.severity == Severity.ERROR
                   for d in diags)

    def test_duplicate_axis_fires_ffl105(self):
        ff = small_mlp()
        ff.executor.nodes[0].output_specs[0] = P("data", "data")
        diags = run_passes(ctx_of(ff), [ShardingLegalityPass()]).diagnostics
        assert any(d.rule == "FFL105" and d.severity == Severity.ERROR
                   for d in diags)

    def test_repartition_axis_mismatch_fires_ffl104(self):
        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 64))
        t = ff.repartition(t, dim=1, degree=4, axis="model")  # no model axis
        t = ff.dense(t, 10)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
        diags = run_passes(ctx_of(ff), [ShardingLegalityPass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL104"]
        assert hits and hits[0].severity == Severity.ERROR
        assert "repartition" in hits[0].message
        assert hits[0].tensor == "out[0]"


class TestCollectiveInference:
    def test_dp_grad_sync_is_inferred(self):
        ff = small_mlp()
        inferred = infer_strategy_collectives(ctx_of(ff))
        assert "allreduce" in inferred
        # at data degree >= 4 weight-update sharding auto-engages: the
        # sync is then inferred as reduce-scatter (":grad-rs", allreduce
        # bucket) + param all-gather instead of a plain ":grad" allreduce
        assert any(s.endswith((":grad", ":grad-rs"))
                   for s in inferred["allreduce"]["sources"])

    def test_unpriced_inferred_collective_fires_ffl204(self):
        ff = small_mlp()
        # the simulator (injected) priced NOTHING for a data-parallel
        # strategy whose grad sync provably exists
        ctx = ctx_of(ff, priced={})
        diags = run_passes(ctx, [CollectiveInferencePass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL204"]
        assert hits and hits[0].severity == Severity.ERROR
        assert "priced none" in hits[0].message

    def test_unpriced_emitted_collective_fires_ffl201(self):
        ff = small_mlp()
        ctx = ctx_of(ff,
                     priced={"allreduce": 1e6},
                     emitted={"allreduce": 1e6, "ppermute": 5e6})
        diags = run_passes(ctx, [CollectiveInferencePass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL201"]
        assert hits and hits[0].severity == Severity.ERROR
        assert "ppermute" in hits[0].message

    def test_phantom_priced_collective_fires_ffl203(self):
        ff = small_mlp()
        ctx = ctx_of(ff, priced={"allreduce": 1e6, "ppermute": 8e6},
                     emitted={"allreduce": 1e6})
        diags = run_passes(ctx, [CollectiveInferencePass()]).diagnostics
        assert any(d.rule == "FFL203"
                   and d.severity == Severity.WARNING for d in diags)

    def test_replicated_strategy_infers_no_grad_sync(self):
        ff = small_mlp()
        for node in ff.executor.nodes:
            node.output_specs = [None] * len(node.output_specs)
        ff.strategy = {}
        inferred = infer_strategy_collectives(ctx_of(ff))
        assert "allreduce" not in inferred


class TestLayoutConsistency:
    def test_redundant_transpose_pair_fires_ffl301(self):
        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 16, 32))
        t = ff.transpose(t, (0, 2, 1))
        t = ff.transpose(t, (0, 2, 1))  # composes to identity
        t = ff.dense(t, 10)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
        diags = run_passes(ctx_of(ff),
                           [LayoutConsistencyPass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL301"]
        assert hits and hits[0].severity == Severity.WARNING
        assert "identity" in hits[0].message

    def test_nhwc_on_rank2_fires_ffl303(self):
        ff = small_mlp()
        ff.executor.nodes[0].output_layouts = ["NHWC"]
        diags = run_passes(ctx_of(ff),
                           [LayoutConsistencyPass()]).diagnostics
        assert any(d.rule == "FFL303" and d.severity == Severity.ERROR
                   for d in diags)

    def test_broken_nhwc_chain_fires_ffl302(self):
        ff = FFModel(FFConfig(batch_size=8, conv_compute_layout="nhwc"))
        t = ff.create_tensor((8, 3, 16, 16))
        t = ff.conv2d(t, 8, 3, 3, 1, 1, 1, 1)
        t = ff.relu(t)
        t = ff.conv2d(t, 8, 3, 3, 1, 1, 1, 1)
        t = ff.flat(t)
        t = ff.dense(t, 10)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
        # break the chain: force the relu (an NHWC pass-through op)
        # back to NCHW between the two NHWC convs
        relu_node = next(n for n in ff.executor.nodes
                         if n.op.op_type.name == "RELU")
        relu_node.input_layouts = ["NCHW"]
        relu_node.output_layouts = ["NCHW"]
        diags = run_passes(ctx_of(ff),
                           [LayoutConsistencyPass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL302"]
        assert hits and hits[0].severity == Severity.WARNING
        assert "NHWC chain" in hits[0].message


class TestDtypePolicy:
    def test_bf16_statistics_fire_ffl401_and_402(self):
        import jax
        import jax.numpy as jnp

        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 4, 8, 8))
        t = ff.batch_norm(t, relu=False)
        t = ff.flat(t)
        t = ff.dense(t, 10)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
        bn = next(n.op for n in ff.executor.nodes
                  if n.op.op_type.name == "BATCHNORM")

        def bad_forward(params, inputs, ctx, state=None):
            # the seeded violation: statistics accumulated AND applied
            # in the input dtype (a bf16-accumulated sum, bf16 mean/var)
            (x,) = inputs
            n = x.shape[0] * x.shape[2] * x.shape[3]
            zero = jnp.zeros((), x.dtype)
            mean = jax.lax.reduce(x, zero, jax.lax.add, (0, 2, 3)) / n
            var = jax.lax.reduce(
                (x - mean[None, :, None, None]) ** 2, zero, jax.lax.add,
                (0, 2, 3)) / n
            bn._new_state = {"mean": mean, "var": var}
            y = (x - mean[None, :, None, None]) * jax.lax.rsqrt(
                var[None, :, None, None] + 1e-5)
            return [y]

        bn.forward = bad_forward
        diags = run_passes(ctx_of(ff), [DtypePolicyPass()]).diagnostics
        assert any(d.rule == "FFL401" and d.severity == Severity.ERROR
                   for d in diags), diags
        assert any(d.rule == "FFL402" and d.severity == Severity.ERROR
                   for d in diags), diags

    def test_good_batchnorm_is_clean(self):
        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 4, 8, 8))
        t = ff.batch_norm(t, relu=False)
        t = ff.flat(t)
        t = ff.dense(t, 10)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
        diags = run_passes(ctx_of(ff), [DtypePolicyPass()]).diagnostics
        assert not diags, diags

    def test_low_precision_output_cast_fires_ffl403(self):
        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 64))
        t = ff.dense(t, 10)
        t = ff.cast(t, DataType.BFLOAT16)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
        diags = run_passes(ctx_of(ff), [DtypePolicyPass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL403"]
        assert hits and hits[0].severity == Severity.ERROR
        assert "truncated logits" in hits[0].message


HLO_A = """
ENTRY %main {
  %ar = f32[1024,8]{1,0} all-reduce(f32[1024,8]{1,0} %p0)
  %ag = f32[2048]{0} all-gather(f32[256]{0} %p1)
}
"""
HLO_B = """
ENTRY %main {
  %ag = f32[2048]{0} all-gather(f32[256]{0} %p1)
  %ar = f32[1024,8]{1,0} all-reduce(f32[1024,8]{1,0} %p0)
}
"""
HLO_C = """
ENTRY %main {
  %ar = f32[1024,8]{1,0} all-reduce(f32[1024,8]{1,0} %p0)
}
"""


class TestMultihostOrder:
    def _ctx(self, texts):
        ff = small_mlp()
        return ctx_of(ff, hlo_per_host=texts)

    def test_sequence_extraction(self):
        seq = collective_sequence(HLO_A)
        assert [k for k, _ in seq] == ["all-reduce", "all-gather"]

    def test_matching_hosts_clean(self):
        rep = run_passes(self._ctx([HLO_A, HLO_A]), [MultihostOrderPass()])
        assert not rep.diagnostics
        assert rep.passes["multihost-order"] == "ok"

    def test_order_divergence_fires_ffl501(self):
        rep = run_passes(self._ctx([HLO_A, HLO_B]), [MultihostOrderPass()])
        hits = [d for d in rep.diagnostics if d.rule == "FFL501"]
        assert hits and hits[0].severity == Severity.ERROR
        assert "position 0" in hits[0].message

    def test_count_mismatch_fires_ffl502(self):
        rep = run_passes(self._ctx([HLO_A, HLO_C]), [MultihostOrderPass()])
        assert any(d.rule == "FFL502" and d.severity == Severity.ERROR
                   for d in rep.diagnostics)

    def test_single_program_skips(self):
        rep = run_passes(self._ctx(None), [MultihostOrderPass()])
        assert "skipped" in rep.passes["multihost-order"]


class TestMultihostOrderPerSlice:
    """Hierarchical (multi-slice) comparison: ``slice_of_host`` groups
    the per-host programs into process sets; FFL501/502 fire WITHIN a
    slice (with slice attribution) and FFL503 fires when the slice
    LEADERS diverge across the DCN — seeded violations for each."""

    def _ctx(self, texts, slices):
        ff = small_mlp()
        return ctx_of(ff, hlo_per_host=texts, slice_of_host=slices)

    def test_clean_two_slices(self):
        rep = run_passes(self._ctx([HLO_A] * 4, [0, 0, 1, 1]),
                         [MultihostOrderPass()])
        assert not rep.diagnostics
        assert rep.passes["multihost-order"] == "ok"

    def test_within_slice_divergence_names_the_slice(self):
        # host 3 (slice 1) reorders its collectives: FFL501 attributed
        # to slice 1, and NO FFL503 (the leaders still agree)
        rep = run_passes(self._ctx([HLO_A, HLO_A, HLO_A, HLO_B],
                                   [0, 0, 1, 1]), [MultihostOrderPass()])
        hits = [d for d in rep.diagnostics if d.rule == "FFL501"]
        assert hits and "slice 1" in hits[0].message
        assert not any(d.rule == "FFL503" for d in rep.diagnostics)

    def test_within_slice_count_mismatch_fires_ffl502(self):
        rep = run_passes(self._ctx([HLO_A, HLO_C, HLO_A, HLO_A],
                                   [0, 0, 1, 1]), [MultihostOrderPass()])
        hits = [d for d in rep.diagnostics if d.rule == "FFL502"]
        assert hits and "slice 0" in hits[0].message

    def test_cross_slice_leader_divergence_fires_ffl503(self):
        # each slice internally consistent, but slice 1 compiled a
        # reordered program — the DCN gradient sync would deadlock
        rep = run_passes(self._ctx([HLO_A, HLO_A, HLO_B, HLO_B],
                                   [0, 0, 1, 1]), [MultihostOrderPass()])
        hits = [d for d in rep.diagnostics if d.rule == "FFL503"]
        assert hits and hits[0].severity == Severity.ERROR
        assert not any(d.rule in ("FFL501", "FFL502")
                       for d in rep.diagnostics)

    def test_cross_slice_count_mismatch_is_ffl503(self):
        rep = run_passes(self._ctx([HLO_A, HLO_A, HLO_C, HLO_C],
                                   [0, 0, 1, 1]), [MultihostOrderPass()])
        assert any(d.rule == "FFL503" and "collectives" in d.message
                   for d in rep.diagnostics)


class TestGraphHygiene:
    def test_dead_op_fires_ffl601(self):
        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 64))
        head = ff.dense(t, 10, name="head")
        ff.dense(t, 32, name="dead_branch")  # output never consumed
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [],
                   outputs=head)
        diags = run_passes(ctx_of(ff), [GraphHygienePass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL601"]
        assert hits and hits[0].severity == Severity.WARNING
        assert hits[0].op == "dead_branch"
        assert "parameters" in hits[0].message  # it owns weights

    def test_unused_input_fires_ffl602(self):
        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 64), name="used")
        ff.create_tensor((8, 32), name="unused")
        t = ff.dense(t, 10)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
        diags = run_passes(ctx_of(ff), [GraphHygienePass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL602"]
        assert hits and hits[0].tensor == "unused"

    def test_shape_contradiction_fires_ffl603(self):
        ff = small_mlp()
        ff.executor.nodes[1].op.input_shapes[0] = (16, 999)
        diags = run_passes(ctx_of(ff), [GraphHygienePass()]).diagnostics
        assert any(d.rule == "FFL603" and d.severity == Severity.ERROR
                   for d in diags)

    def test_duplicate_name_fires_ffl604(self):
        ff = small_mlp()
        ff.executor.nodes[1].op.name = ff.executor.nodes[0].op.name
        diags = run_passes(ctx_of(ff), [GraphHygienePass()]).diagnostics
        assert any(d.rule == "FFL604" and d.severity == Severity.ERROR
                   for d in diags)


class TestCalibrationPass:
    def _searched_ctx(self, ff):
        ctx = ctx_of(ff)
        ctx.searched = True
        return ctx

    def test_no_calibration_fires_ffl701(self, tmp_path, monkeypatch):
        monkeypatch.setenv("FFS_CALIBRATION_FILE",
                           str(tmp_path / "nonexistent.json"))
        ff = small_mlp()
        diags = run_passes(self._searched_ctx(ff),
                           [CalibrationPass()]).diagnostics
        assert any(d.rule == "FFL701" and d.severity == Severity.WARNING
                   for d in diags)

    def test_partial_corrections_fire_ffl702(self, tmp_path, monkeypatch):
        import jax
        platform = jax.devices()[0].platform
        cal = dict(platform=platform, op_corrections={
            platform: {"LINEAR": dict(factor=1.2, weight=1.0)}})
        p = tmp_path / "cal.json"
        p.write_text(json.dumps(cal))
        monkeypatch.setenv("FFS_CALIBRATION_FILE", str(p))
        ff = small_mlp()  # graph also has SOFTMAX (flops > 0), uncorrected
        diags = run_passes(self._searched_ctx(ff),
                           [CalibrationPass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL702"]
        assert hits and "SOFTMAX" in hits[0].message

    def test_stale_platform_fires_ffl703(self, tmp_path, monkeypatch):
        cal = dict(platform="tpu", op_corrections={
            "tpu": {"LINEAR": dict(factor=1.2, weight=1.0),
                    "SOFTMAX": dict(factor=1.1, weight=1.0)}})
        p = tmp_path / "cal.json"
        p.write_text(json.dumps(cal))
        monkeypatch.setenv("FFS_CALIBRATION_FILE", str(p))
        ff = small_mlp()  # running on cpu: tpu-only corrections = stale
        diags = run_passes(self._searched_ctx(ff),
                           [CalibrationPass()]).diagnostics
        assert any(d.rule == "FFL703" for d in diags)

    def test_heuristic_strategy_skips(self):
        ff = small_mlp()
        rep = run_passes(ctx_of(ff), [CalibrationPass()])
        assert "skipped" in rep.passes["calibration"]


class TestDriftCorrections:
    """The recalibration loop: drift reports -> per-op factors ->
    measured tables (scripts/calibrate.py + search/profile.py)."""

    def _calibrate_module(self):
        import importlib.util
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "calibrate", os.path.join(repo, "scripts", "calibrate.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_derive_op_corrections_weights_by_share(self):
        mod = self._calibrate_module()
        rep = dict(
            header=dict(platform="cpu"),
            predicted=dict(total_s=0.01),
            measured=dict(step_s=0.02),  # 2x drift
            per_op=[dict(type="LINEAR", sharded_s=0.008),
                    dict(type="SOFTMAX", sharded_s=0.002)])
        corr = mod.derive_op_corrections([rep])
        assert corr["cpu"]["LINEAR"]["factor"] == pytest.approx(2.0)
        assert corr["cpu"]["LINEAR"]["weight"] == pytest.approx(0.8)
        assert corr["cpu"]["SOFTMAX"]["weight"] == pytest.approx(0.2)

    def test_derive_buckets_platforms_separately(self):
        # a CPU-traced report must never blend into (or clobber) a
        # factor derived on the chip — buckets are per platform
        mod = self._calibrate_module()
        cpu = dict(header=dict(platform="cpu"),
                   predicted=dict(total_s=0.01),
                   measured=dict(step_s=0.04),  # 4x drift on CPU
                   per_op=[dict(type="LINEAR", sharded_s=0.01)])
        tpu = dict(header=dict(platform="tpu"),
                   predicted=dict(total_s=0.01),
                   measured=dict(step_s=0.011),  # 1.1x on the chip
                   per_op=[dict(type="LINEAR", sharded_s=0.01)])
        corr = mod.derive_op_corrections([cpu, tpu])
        assert corr["cpu"]["LINEAR"]["factor"] == pytest.approx(4.0)
        assert corr["tpu"]["LINEAR"]["factor"] == pytest.approx(1.1)

    def test_corrections_scale_measured_tables(self, tmp_path, monkeypatch):
        import jax
        platform = jax.devices()[0].platform
        p = tmp_path / "cal.json"
        p.write_text(json.dumps(dict(op_corrections={
            platform: {"LINEAR": dict(factor=3.0, weight=1.0)}})))
        monkeypatch.setenv("FFS_CALIBRATION_FILE", str(p))
        from flexflow_tpu.search.profile import apply_drift_corrections
        ff = small_mlp()
        nodes = ff.executor.nodes
        guid = next(n.op.guid for n in nodes
                    if n.op.op_type.name == "LINEAR")
        measured = {f"{guid}:fwd": 1e-5, f"{guid}:bwd": 2e-5}
        out = apply_drift_corrections(measured, nodes)
        assert out[f"{guid}:fwd"] == pytest.approx(3e-5)
        assert out[f"{guid}:bwd"] == pytest.approx(6e-5)
        # another platform's bucket never applies here
        p.write_text(json.dumps(dict(op_corrections={
            "not-" + platform: {"LINEAR": dict(factor=3.0, weight=1.0)}})))
        out2 = apply_drift_corrections(measured, nodes)
        assert out2[f"{guid}:fwd"] == pytest.approx(1e-5)


class TestCompileWiring:
    def test_lint_error_rejects_illegal_imported_strategy(self, tmp_path):
        # a strategy file sharding a batch-6 model 8-way: legal to
        # import (the axis exists), illegal to run (6 % 8 != 0) — lint
        # catches it at compile, before any parameter is allocated
        strat = dict(version=1, mesh=dict(data=8), ops={
            "mlp_0": dict(choice=None, outputs=[["data"]], params={})})
        sf = tmp_path / "strategy.json"
        sf.write_text(json.dumps(strat))
        from flexflow_tpu.models.mlp import create_mlp
        cfg = FFConfig(batch_size=6)
        cfg.import_strategy_file = str(sf)
        ff = create_mlp(batch_size=6, in_dim=64, hidden_dims=(128,),
                        out_dim=10, ff_config=cfg)
        with pytest.raises(ValueError, match="fflint"):
            ff.compile(SGDOptimizer(lr=0.01),
                       LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [],
                       lint="error")

    def test_lint_warn_records_report(self):
        ff = small_mlp(compile_kw=dict(lint="warn"))
        assert ff.lint_report is not None
        assert not ff.lint_report.has_errors()

    def test_lint_off_by_default(self):
        ff = small_mlp()
        assert ff.lint_report is None

    def test_config_flag_parses(self):
        cfg = FFConfig()
        rest = cfg.parse_args(["--lint", "error", "--epochs", "2"])
        assert cfg.lint == "error" and cfg.epochs == 2 and not rest
        with pytest.raises(ValueError):
            FFConfig().parse_args(["--lint", "nonsense"])


class TestPipelineLegality:
    """FFL106-108: static pipeline legality on pipe meshes — the
    conditions that otherwise surface as ValueErrors from
    PipelineGraphExecutor.__init__ at compile time."""

    _models = {}  # compiled fixtures shared across the class's tests

    @classmethod
    def _transformer(cls, layers=4, batch=16, dropout=0.0):
        key = (layers, batch, dropout)
        if key in cls._models:
            return cls._models[key]
        from flexflow_tpu.machine import make_mesh
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     create_transformer)
        cfg = TransformerConfig(num_layers=layers, hidden_size=32,
                                num_heads=2, seq_length=8,
                                batch_size=batch, dropout=dropout)
        ff = create_transformer(cfg, FFConfig(batch_size=batch))
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
                   mesh=make_mesh(1, {"data": 1}))
        cls._models[key] = ff
        return ff

    def _pipe_ctx(self, ff, axes, config=None):
        from flexflow_tpu.machine import make_mesh
        n = int(np.prod(list(axes.values())))
        return LintContext(nodes=ff.executor.nodes,
                           mesh=make_mesh(n, axes),
                           strategy=ff.strategy, config=config)

    def test_indivisible_blocks_fire_ffl106(self):
        # 6 repeated blocks cannot split into 4 stages
        ff = self._transformer(layers=6)
        ctx = self._pipe_ctx(ff, {"pipe": 4})
        diags = run_passes(ctx, [ShardingLegalityPass()]).errors
        assert "FFL106" in rules(diags), [d.format() for d in diags]

    def test_no_repeated_body_fires_ffl106(self):
        ff = small_mlp()  # 128 -> 128 -> 10: not shape-preserving blocks
        ctx = self._pipe_ctx(ff, {"pipe": 2, "data": 2})
        diags = run_passes(ctx, [ShardingLegalityPass()]).errors
        assert "FFL106" in rules(diags)

    def test_dropout_in_blocks_fires_ffl107(self):
        # detection refuses dropout bodies; the relaxed re-detection
        # tells "stateful body" apart from "no repeated structure"
        ff = self._transformer(layers=2, dropout=0.1)
        ctx = self._pipe_ctx(ff, {"pipe": 2, "data": 2})
        diags = run_passes(ctx, [ShardingLegalityPass()]).errors
        assert "FFL107" in rules(diags)

    def test_batch_indivisible_fires_ffl108(self):
        ff = self._transformer(layers=6)  # shared with the FFL106 case
        cfg = FFConfig(batch_size=16)
        cfg.pipeline_microbatches = 16  # 16 % (16 * 2) != 0
        ctx = self._pipe_ctx(ff, {"pipe": 2, "data": 2}, config=cfg)
        diags = run_passes(ctx, [ShardingLegalityPass()]).errors
        assert "FFL108" in rules(diags)

    def test_legal_pipe_context_is_clean(self):
        ff = self._transformer(layers=6)
        cfg = FFConfig(batch_size=16)
        cfg.pipeline_microbatches = 4
        ctx = self._pipe_ctx(ff, {"pipe": 2, "data": 2}, config=cfg)
        rep = run_passes(ctx, [ShardingLegalityPass()])
        assert not {"FFL106", "FFL107", "FFL108"} & rules(rep.errors), \
            [d.format() for d in rep.errors]


class TestOrchestrator:
    def test_crashing_pass_reports_ffl000(self):
        class Boom:
            name = "boom"

            def run(self, ctx):
                raise RuntimeError("kaboom")

        ff = small_mlp()
        rep = run_passes(ctx_of(ff), [Boom()])
        assert "crashed" in rep.passes["boom"]
        assert any(d.rule == "FFL000" for d in rep.diagnostics)

    def test_errors_sort_before_warnings_in_json(self):
        ff = small_mlp()
        ff.executor.nodes[0].output_specs[0] = P("bogus")
        ff.create_tensor((8, 3), name="unused_x")  # not in executor: no-op
        rep = run_passes(ctx_of(ff), [ShardingLegalityPass(),
                                      GraphHygienePass()])
        doc = rep.to_json()
        sevs = [d["severity"] for d in doc["diagnostics"]]
        assert sevs == sorted(sevs, key=["error", "warning",
                                         "info"].index)

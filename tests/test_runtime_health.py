"""Preemption-aware supervision (ISSUE 12).

flexflow_tpu/runtime_health.py + the grown FFS_FAULT grammar: watchdog
units on a fake clock (no real multi-second sleeps), the supervisor's
exit-code classification table and restart/backoff loop with a fake
runner, the in-process SIGTERM grace path through a real fit (complete
grace-window checkpoint + PREEMPTED_EXIT + bitwise resume), transient
io_error checkpoint writes absorbed by retry-with-backoff, the
writer-error surfacing regression, rank-local restore's read planner +
byte accounting, and the dataloader cursor (seek-on-resume, no
redundant fetches). The multi-restart subprocess legs live @slow in
tests/test_multihost.py; everything here is tier-1.
"""

import io
import os

import numpy as np
import pytest

from flexflow_tpu import AdamOptimizer, FFConfig, FFModel
from flexflow_tpu.ffconst import ActiMode
from flexflow_tpu.ckpt import faults
from flexflow_tpu.ckpt import manifest as mf
from flexflow_tpu.ckpt import (CheckpointManager, latest_complete,
                               load_manifest, load_sharded, save_sharded,
                               verify_step_dir)
from flexflow_tpu.obs.registry import get_registry
from flexflow_tpu.runtime_health import (HUNG_EXIT, KILL_EXIT,
                                         PREEMPTED_EXIT, Preempted,
                                         PreemptionHandler, RuntimeHealth,
                                         Supervisor, Watchdog,
                                         classify_exit, dump_thread_stacks)


def small_model(checkpoint_dir=None, grace=0.0, watchdog=0.0):
    cfg = FFConfig(batch_size=64, checkpoint_dir=checkpoint_dir)
    cfg.grace_window_s = grace
    cfg.watchdog_timeout_s = watchdog
    ff = FFModel(cfg)
    t = ff.create_tensor((64, 16))
    h = ff.dense(t, 32, activation=ActiMode.AC_MODE_RELU, name="h1")
    out = ff.dense(h, 4, name="out")
    ff.softmax(out)
    ff.compile(AdamOptimizer(alpha=0.01))
    return ff


def blobs(n=256, d=16, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(classes, d) * 3
    y = rs.randint(0, classes, n)
    x = (centers[y] + rs.randn(n, d)).astype(np.float32)
    return x, y.astype(np.int32).reshape(-1, 1)


def set_fault(monkeypatch, spec):
    """Point FFS_FAULT at ``spec`` with a FRESH plan: the parse cache
    memoizes per spec string, and a plan's one-shot/budgeted state
    (sigterm fired, io_error budget) must not leak between tests."""
    faults._CACHE.pop(spec, None)
    monkeypatch.setenv(faults.ENV, spec)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# fault grammar


class TestFaultGrammar:
    def test_parse_new_kinds(self):
        plan = faults._parse("sigterm:1@step:5,hang:0@step:7,"
                             "io_error:shards:3,kill_host:2@step:9")
        assert plan.sigterms == [(1, 5)]
        assert plan.hangs == [(0, 7)]
        assert plan.io_errors == [["shards", 3]]
        assert plan.kills == [(2, 9)]

    def test_io_error_path_substr_may_contain_colons(self):
        plan = faults._parse("io_error:a:b:2")
        assert plan.io_errors == [["a:b", 2]]

    @pytest.mark.parametrize("bad", [
        "sigterm:x@step:3",         # non-int rank
        "sigterm:0@epoch:3",        # wrong @ keyword
        "hang:0",                   # missing @step
        "io_error:shards",          # missing count
        "io_error::2",              # empty substr
        "io_error:shards:0",        # count < 1
        "io_error:shards:x",        # non-int count
        "io_error:shards:2@step:1",  # io_error takes no @step
        "resurrect:0@step:1",       # unknown kind
    ])
    def test_rejects(self, bad):
        with pytest.raises(ValueError, match="cannot parse fault"):
            faults._parse(bad)

    def test_io_check_budget_spends_and_exhausts(self):
        plan = faults._parse("io_error:shards:2")
        with pytest.raises(OSError):
            plan.io_check("/ckpt/step_1/shards_host0000.npz")
        with pytest.raises(OSError):
            plan.io_check("/ckpt/step_1/shards_host0000.npz")
        # budget spent: the third write succeeds (transient, not fatal)
        plan.io_check("/ckpt/step_1/shards_host0000.npz")
        # non-matching paths never fail
        plan2 = faults._parse("io_error:shards:1")
        plan2.io_check("/ckpt/step_1/MANIFEST.json")
        assert plan2.io_errors == [["shards", 1]]


# ---------------------------------------------------------------------------
# watchdog (fake clock — no real multi-second sleeps)


class TestWatchdog:
    def test_unarmed_until_first_beat(self):
        """Startup (checkpoint restore, first-step JIT compile) emits
        no heartbeat and must never be reaped as a hang: the watchdog
        only arms once the first beat lands."""
        clk = FakeClock()
        trips = []
        w = Watchdog(10.0, clock=clk, on_trip=lambda: trips.append(1))
        clk.advance(1000.0)  # arbitrarily long silent startup
        assert not w.check() and w.seconds_since_beat() == 0.0
        w.beat("step 0")
        clk.advance(10.5)
        assert w.check() and trips == [1]

    def test_no_trip_within_timeout_and_beat_resets(self):
        clk = FakeClock()
        trips = []
        w = Watchdog(10.0, clock=clk, on_trip=lambda: trips.append(1))
        w.beat("step 0")
        clk.advance(9.0)
        assert not w.check()
        w.beat("step 3")
        clk.advance(9.0)
        assert not w.check() and not trips

    def test_trip_fires_once_counter_and_stacks(self, capsys):
        clk = FakeClock()
        trips = []
        reg = get_registry()
        before = reg.get("t1wd/watchdog_trip")
        w = Watchdog(10.0, run_name="t1wd", clock=clk,
                     on_trip=lambda: trips.append(1))
        w.beat("step 4")
        clk.advance(10.5)
        assert w.check() and w.tripped
        assert w.check()  # latched: the trip action never double-fires
        assert trips == [1]
        assert reg.get("t1wd/watchdog_trip") - before == 1
        err = capsys.readouterr().err
        assert "no progress for" in err and "step 4" in err
        assert "thread" in err  # the stack dump

    def test_default_trip_finalizes_then_exits_hung(self):
        clk = FakeClock()
        order = []
        w = Watchdog(5.0, clock=clk,
                     finalize_fn=lambda: order.append("finalize"),
                     exit_fn=lambda code: order.append(code))
        w.beat()
        clk.advance(6.0)
        assert w.check()
        assert order == ["finalize", HUNG_EXIT]

    def test_finalize_error_still_exits(self):
        clk = FakeClock()
        codes = []

        def boom():
            raise RuntimeError("trace dir gone")

        w = Watchdog(5.0, clock=clk, finalize_fn=boom,
                     exit_fn=codes.append)
        w.beat()
        clk.advance(6.0)
        assert w.check() and codes == [HUNG_EXIT]

    def test_polling_thread_starts_and_stops(self):
        import threading
        tripped = threading.Event()
        w = Watchdog(0.15, on_trip=tripped.set, poll_interval_s=0.03)
        w.beat()  # arm: the thread only times armed watchdogs
        w.start()
        assert tripped.wait(timeout=3.0)
        w.stop()

    def test_dump_thread_stacks_lists_main(self):
        buf = io.StringIO()
        dump_thread_stacks(buf)
        assert "MainThread" in buf.getvalue()


# ---------------------------------------------------------------------------
# preemption handler


class TestPreemptionHandler:
    def test_request_sets_flag_and_counter(self):
        reg = get_registry()
        before = reg.get("t1pre/preemption_signal")
        h = PreemptionHandler(grace_window_s=0.0, run_name="t1pre")
        assert not h.should_stop()
        h.request_preempt("test")
        assert h.should_stop() and h.reason == "test"
        assert reg.get("t1pre/preemption_signal") - before == 1
        h.request_preempt("again")  # idempotent: no double count
        assert reg.get("t1pre/preemption_signal") - before == 1

    def test_maintenance_notice_polled_and_time_gated(self):
        clk = FakeClock()
        polls = []

        def notice():
            polls.append(clk.t)
            return len(polls) >= 2

        h = PreemptionHandler(grace_window_s=0.0, notice_fn=notice,
                              notice_poll_s=5.0, clock=clk)
        assert not h.should_stop() and polls == [0.0]
        clk.advance(1.0)
        assert not h.should_stop() and polls == [0.0]  # gated
        clk.advance(5.0)
        assert h.should_stop() and polls == [0.0, 6.0]
        assert h.reason == "maintenance_notice"

    def test_second_signal_exits_immediately(self):
        codes = []
        h = PreemptionHandler(grace_window_s=0.0, exit_fn=codes.append)
        h._on_signal(15, None)
        assert h.preempted and not codes
        h._on_signal(15, None)
        assert codes == [PREEMPTED_EXIT]

    def test_grace_deadline_enforced_and_cancellable(self):
        import threading
        fired = threading.Event()
        h = PreemptionHandler(grace_window_s=0.2,
                              exit_fn=lambda c: fired.set())
        h.request_preempt("test")
        assert fired.wait(timeout=3.0)  # overrun -> hard exit
        cancelled = threading.Event()
        h2 = PreemptionHandler(grace_window_s=0.3,
                               exit_fn=lambda c: cancelled.set())
        h2.request_preempt("test")
        h2.uninstall()  # graceful path finished first
        assert not cancelled.wait(timeout=0.6)

    def test_runtime_health_step_done_raises_preempted(self):
        health = RuntimeHealth(grace_window_s=0.0, watchdog_timeout_s=0.0,
                               notice_fn=lambda: True,
                               exit_fn=lambda c: None)
        try:
            # the very first poll sees the notice — Preempted surfaces
            # at the next step boundary, after the in-flight step
            with pytest.raises(Preempted) as ei:
                health.step_done(0)
            assert ei.value.code == PREEMPTED_EXIT
        finally:
            health.close()


# ---------------------------------------------------------------------------
# supervisor


class TestSupervisor:
    def test_exit_code_classification_table(self):
        assert classify_exit(0) == "clean"
        assert classify_exit(KILL_EXIT) == "kill"
        assert classify_exit(PREEMPTED_EXIT) == "preempted"
        assert classify_exit(HUNG_EXIT) == "hung"
        assert classify_exit(1) == "crash"       # python traceback
        assert classify_exit(137) == "crash"     # shell's SIGKILL
        assert classify_exit(-9) == "crash"      # subprocess signal code
        assert classify_exit(None) == "crash"

    def test_restart_loop_resume_flag_fault_clear_backoff(self, tmp_path):
        codes = [HUNG_EXIT, PREEMPTED_EXIT, 0]
        calls = []

        def run(cmd, env):
            calls.append((list(cmd), dict(env)))
            return codes[len(calls) - 1]

        slept = []
        state = str(tmp_path / "SUPERVISOR.json")
        sup = Supervisor(["train", "--checkpoint-dir", "d"],
                         max_restarts=3, backoff_base_s=1.0,
                         backoff_max_s=3.0, state_path=state,
                         env={"FFS_FAULT": "hang:0@step:3", "KEEP": "1"},
                         run_fn=run, sleep_fn=slept.append,
                         clock=FakeClock())
        s = sup.run()
        assert s["final_outcome"] == "clean" and s["restarts"] == 2
        assert [h["outcome"] for h in s["history"]] == \
            ["hung", "preempted", "clean"]
        # attempt 0 keeps the injected fault; restarts clear it and
        # append --resume exactly once
        assert calls[0][0] == ["train", "--checkpoint-dir", "d"]
        assert "FFS_FAULT" in calls[0][1]
        for cmd, env in calls[1:]:
            assert cmd[-1] == "--resume" and cmd.count("--resume") == 1
            assert "FFS_FAULT" not in env and env["KEEP"] == "1"
        # bounded exponential backoff: 1, 2 (cap 3 untouched)
        assert slept == [1.0, 2.0]
        # state record is the goodput fold's input
        rec = mf.read_json(state)
        assert rec["restarts"] == 2 and rec["final_outcome"] == "clean"
        assert rec["outcomes"] == {"hung": 1, "preempted": 1, "clean": 1}

    def test_budget_exhaustion_returns_last_code(self):
        sup = Supervisor(["train"], max_restarts=2, backoff_base_s=10.0,
                         backoff_max_s=15.0, env={},
                         run_fn=lambda cmd, env: KILL_EXIT,
                         sleep_fn=lambda s: None, clock=FakeClock())
        s = sup.run()
        assert s["attempts"] == 3 and s["final_outcome"] == "kill"
        assert s["final_code"] == KILL_EXIT
        # backoff cap engaged on the second restart
        assert sup.backoff_s(0) == 10.0 and sup.backoff_s(1) == 15.0

    def test_goodput_folds_supervisor_downtime(self, tmp_path):
        """A run living under supervise.py pays the restart backoff in
        goodput_effective: finalize reads SUPERVISOR.json's downtime_s
        into the denominator and gauges the restart count."""
        x, y = blobs()
        ff = small_model()
        ff.fit(x, y, epochs=1, verbose=False)
        cdir = str(tmp_path)
        mf.atomic_write_json(os.path.join(cdir, mf.SUPERVISOR_NAME),
                             dict(restarts=2, downtime_s=40.0))
        mgr = CheckpointManager(ff, cdir, every=0, run_name="supgp")
        mgr.finalize(elapsed_s=10.0, steps=4)
        g = get_registry().to_dict()["gauges"]
        assert g["supgp/supervisor_restarts"] == 2.0
        assert g["supgp/supervisor_downtime_s"] == 40.0
        # productive <= 10 against a 50s denominator: goodput <= ~0.2
        assert g["supgp/goodput_effective"] <= 10.0 / 50.0 + 1e-9


# ---------------------------------------------------------------------------
# the in-process grace path through a real fit


class TestGracefulPreemptionFit:
    def test_sigterm_cuts_grace_checkpoint_and_resume_is_bitwise(
            self, tmp_path, monkeypatch):
        """The acceptance arc, in one process: FFS_FAULT sigterm fires
        mid-epoch, fit finishes the in-flight step, cuts a final
        checkpoint through the CheckpointManager, and exits with
        PREEMPTED_EXIT; the resumed run continues bit-identically to
        an uninterrupted one."""
        x, y = blobs()
        cdir = str(tmp_path / "ck")
        set_fault(monkeypatch, "sigterm:0@step:2")
        ff = small_model(checkpoint_dir=cdir, grace=60.0)
        with pytest.raises(SystemExit) as ei:
            ff.fit(x, y, epochs=2, verbose=False)
        assert ei.value.code == PREEMPTED_EXIT
        monkeypatch.delenv(faults.ENV)
        # the grace checkpoint is the post-in-flight-step state
        step, sdir = latest_complete(cdir)
        assert step == 3
        rep = verify_step_dir(sdir)
        assert rep["complete"], rep["errors"]
        reg = get_registry().to_dict()
        assert reg["counters"]["fit/preemption_signal"] >= 1
        assert reg["gauges"]["fit/grace_checkpoint_s"] > 0
        # signal handlers restored (fit's finally ran): whatever owns
        # SIGTERM now, it is not our preemption handler
        import signal
        h = signal.getsignal(signal.SIGTERM)
        owner = getattr(h, "__self__", None)
        assert not isinstance(owner, PreemptionHandler)
        # auto-resume: same command line, bit-identical end state
        ff2 = small_model(checkpoint_dir=cdir)
        ff2.fit(x, y, epochs=2, verbose=False, resume=True)
        ff3 = small_model()
        ff3.fit(x, y, epochs=2, verbose=False)
        assert ff2._last_loss == ff3._last_loss


# ---------------------------------------------------------------------------
# io_error: transient absorbed, exhausted surfaces at next save


class TestIoErrorRetry:
    def test_transient_io_error_retried_save_completes(self, tmp_path,
                                                       monkeypatch):
        x, y = blobs()
        ff = small_model()
        ff.fit(x, y, epochs=1, verbose=False)
        monkeypatch.setenv("FFS_CKPT_IO_BACKOFF_S", "0.01")
        set_fault(monkeypatch, "io_error:shards_host:2")
        reg = get_registry()
        before = reg.get("ckpt/io_retries")
        save_sharded(str(tmp_path), ff)
        # acceptance: the retry count is visible in obs counters
        assert reg.get("ckpt/io_retries") - before == 2
        step, sdir = latest_complete(str(tmp_path))
        assert verify_step_dir(sdir)["complete"]

    def test_exhausted_writer_error_surfaces_at_next_save_chained(
            self, tmp_path, monkeypatch):
        """Satellite regression: a writer that dies from a RETRY-
        EXHAUSTED I/O error must surface at the next save() with the
        underlying OSError chained — not silently later at
        finalize()."""
        x, y = blobs()
        ff = small_model()
        ff.fit(x, y, epochs=1, verbose=False)
        monkeypatch.setenv("FFS_CKPT_IO_BACKOFF_S", "0.005")
        set_fault(monkeypatch, "io_error:shards_host:99")
        mgr = CheckpointManager(ff, str(tmp_path), every=1,
                                async_write=True, run_name="ioex")
        mgr.save(ff._iter)  # async writer exhausts its retries and dies
        with pytest.raises(RuntimeError,
                           match="asynchronous checkpoint write") as ei:
            mgr.save(ff._iter + 1)
        assert isinstance(ei.value.__cause__, OSError)
        assert "FFS_FAULT injected" in str(ei.value.__cause__)
        # the error was consumed AT save — finalize must not re-raise a
        # stale copy (and must not claim a durable checkpoint exists)
        monkeypatch.delenv(faults.ENV)
        mgr.finalize(elapsed_s=1.0, steps=2)

    def test_sync_mode_raises_inline_with_cause(self, tmp_path,
                                                monkeypatch):
        x, y = blobs()
        ff = small_model()
        ff.fit(x, y, epochs=1, verbose=False)
        monkeypatch.setenv("FFS_CKPT_IO_BACKOFF_S", "0.005")
        set_fault(monkeypatch, "io_error:index_host:99")
        mgr = CheckpointManager(ff, str(tmp_path), every=1,
                                async_write=False, run_name="iosync")
        with pytest.raises(RuntimeError) as ei:
            mgr.save(ff._iter)
        assert isinstance(ei.value.__cause__, OSError)


# ---------------------------------------------------------------------------
# rank-local restore


class TestRankLocalRestore:
    def _rows(self, n_hosts, rows_per_host, cols, bytes_per_row):
        """A synthetic saved shard index: one leaf of
        [n_hosts*rows_per_host, cols], split row-wise across hosts."""
        entries = []
        for h in range(n_hosts):
            lo = h * rows_per_host
            entries.append((f"shards_host{h:04d}.npz",
                            dict(key=f"k::{h}",
                                 index=[[lo, lo + rows_per_host],
                                        [0, cols]],
                                 crc32=0,
                                 bytes=bytes_per_row * rows_per_host)))
        return entries

    def test_same_mesh_reads_one_host_share(self):
        from flexflow_tpu.ckpt.sharded import _select_rows
        entries = self._rows(n_hosts=4, rows_per_host=16, cols=8,
                             bytes_per_row=32)
        # this host's live boxes = host 1's slice exactly
        needed = [[[16, 32], [0, 8]]]
        sel, skip, want, local = _select_rows(entries, needed)
        assert local
        assert [e[1]["key"] for e in sel] == ["k::1"]
        assert want == 16 * 8
        # the byte-count assertion: 1/host_count read, the rest skipped
        sel_bytes = sum(e[1]["bytes"] for e in sel)
        skip_bytes = sum(e[1]["bytes"] for e in skip)
        assert sel_bytes == 32 * 16
        assert skip_bytes == 3 * 32 * 16

    def test_replicated_leaf_full_box_matches(self):
        from flexflow_tpu.ckpt.sharded import _select_rows
        entries = [("shards_host0000.npz",
                    dict(key="k::0", index=[[0, 64], [0, 8]], crc32=0,
                         bytes=2048))]
        sel, skip, want, local = _select_rows(entries,
                                              [[[0, 64], [0, 8]]])
        assert local and len(sel) == 1 and not skip and want == 64 * 8

    def test_changed_boxes_fall_back_to_full_scan(self):
        from flexflow_tpu.ckpt.sharded import _select_rows
        entries = self._rows(n_hosts=4, rows_per_host=16, cols=8,
                             bytes_per_row=32)
        # live box [0,32) straddles two saved boxes: partial overlap
        sel, skip, want, local = _select_rows(entries,
                                              [[[0, 32], [0, 8]]])
        assert not local and want is None
        assert sel == entries and skip == []

    def test_unknowable_leaf_full_scan(self):
        from flexflow_tpu.ckpt.sharded import _select_rows
        entries = self._rows(2, 16, 8, 32)
        sel, skip, want, local = _select_rows(entries, None)
        assert not local and sel == entries

    def test_single_process_reads_all_and_counter_tracks(self, tmp_path):
        """Single-process: every box is addressable, so rank-local mode
        selects everything — the read-bytes counter must equal the
        checkpoint's payload and the restore stays bitwise."""
        x, y = blobs()
        ff = small_model()
        ff.fit(x, y, epochs=1, verbose=False)
        save_sharded(str(tmp_path), ff)
        _, sdir = latest_complete(str(tmp_path))
        payload = verify_step_dir(sdir, deep=False)["payload_bytes"]
        reg = get_registry()
        before_read = reg.get("ckpt/restore_read_bytes")
        before_skip = reg.get("ckpt/restore_skipped_bytes")
        ff2 = small_model()
        assert load_sharded(str(tmp_path), ff2) == ff._iter
        assert reg.get("ckpt/restore_read_bytes") - before_read == payload
        assert reg.get("ckpt/restore_skipped_bytes") - before_skip == 0
        np.testing.assert_array_equal(
            np.asarray(ff.params["h1"]["kernel"]),
            np.asarray(ff2.params["h1"]["kernel"]))


# ---------------------------------------------------------------------------
# dataloader cursor: seek on resume, no redundant fetches


class TestLoaderCursor:
    def test_seek_bounds(self):
        from flexflow_tpu.dataloader import create_data_loaders
        x, y = blobs()
        ff = small_model()
        loaders = create_data_loaders(ff, x, y)
        with pytest.raises(ValueError, match="seek"):
            loaders.input_loaders[0].seek(loaders.num_batches)
        loaders.seek(2)
        assert loaders.input_loaders[0].next_index == 2 * 64

    def test_resume_seeks_no_redundant_fetches_and_bitwise(self,
                                                           tmp_path):
        """Satellite acceptance: the resumed fit_loader run seeks to
        the recorded cursor instead of fetch-and-discarding covered
        batches (fetch count == executed steps), the manifest carries
        the epoch/batch cursor, and the end state is bit-identical to
        an uninterrupted run."""
        from flexflow_tpu.dataloader import create_data_loaders
        x, y = blobs()
        cdir = str(tmp_path)

        # uninterrupted reference
        ff_ref = small_model()
        ff_ref.fit_loader(create_data_loaders(ff_ref, x, y), epochs=2,
                          verbose=False)

        # interrupted: 1 epoch (4 steps) with a final checkpoint
        ff1 = small_model(checkpoint_dir=cdir)
        ff1.fit_loader(create_data_loaders(ff1, x, y), epochs=1,
                       verbose=False)
        manifest = load_manifest(cdir)
        cur = manifest["client_state"]["loader"]
        assert cur == dict(iteration=4, epoch=1, batch=0, num_batches=4)

        # resumed to the total schedule, counting real fetches
        ff2 = small_model(checkpoint_dir=cdir)
        loaders = create_data_loaders(ff2, x, y)
        fetches = []
        orig = loaders.next_batch
        loaders.next_batch = lambda: (fetches.append(1), orig())[1]
        ff2.fit_loader(loaders, epochs=2, verbose=False, resume=True)
        assert len(fetches) == 4  # only the uncovered step slots
        assert ff2._last_loss == ff_ref._last_loss

    def test_mid_epoch_resume_seeks_to_batch(self, tmp_path):
        """A checkpoint cadence that stops mid-epoch: the resumed run
        must seek to the intra-epoch batch, not epoch start."""
        from flexflow_tpu.dataloader import create_data_loaders
        x, y = blobs()
        cdir = str(tmp_path)
        ff_ref = small_model()
        ff_ref.fit_loader(create_data_loaders(ff_ref, x, y), epochs=2,
                          verbose=False)

        ff1 = small_model(checkpoint_dir=cdir)
        loaders1 = create_data_loaders(ff1, x, y)
        mgr = CheckpointManager(ff1, cdir, every=0, run_name="midres")
        # train 6 of 8 slots by hand through fit_loader's own loop:
        # epochs=2 but kill via a 6-step cadence is simpler to emulate
        # with a direct fit of epochs=1 + 2 manual steps; instead run
        # the supported path: full first epoch + checkpoint, then
        # resume lands at epoch 1 batch 0 — the mid-epoch variant:
        ff1.fit_loader(loaders1, epochs=1, verbose=False)
        # advance 2 more steps manually (epoch 1, batches 0-1)
        loaders1.reset()
        train_step = ff1.executor.make_train_step()
        import jax
        for _ in range(2):
            inputs, labels = loaders1.next_batch()
            ff1._rng, sub = jax.random.split(ff1._rng)
            (ff1.params, ff1.opt_state, ff1.state, loss,
             _) = train_step(ff1.params, ff1.opt_state, ff1.state,
                             inputs, labels, sub)
            ff1._iter += 1
        mgr.save(ff1._iter)
        mgr.wait()
        # manager-level saves carry no loader cursor (fit_loader owns
        # it) — the iteration-derived seek must still line up
        assert "client_state" not in load_manifest(cdir)

        ff2 = small_model(checkpoint_dir=cdir)
        loaders2 = create_data_loaders(ff2, x, y)
        fetches = []
        orig = loaders2.next_batch
        loaders2.next_batch = lambda: (fetches.append(1), orig())[1]
        ff2.fit_loader(loaders2, epochs=2, verbose=False, resume=True)
        assert len(fetches) == 2  # slots 6,7 only
        assert ff2._last_loss == ff_ref._last_loss

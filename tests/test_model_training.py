"""End-to-end training tests: the minimum slice of SURVEY §7 stage 1.

Covers: FFModel layer API -> compile -> jitted fit loop; loss decreases;
metrics; evaluate; predict; reference-parity forward/backward/update
protocol; data-parallel strategy over the 8-device CPU mesh.
"""

import numpy as np
import pytest

import jax

from flexflow_tpu import (
    AdamOptimizer,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.ffconst import ActiMode, DataType


def make_blobs(n=256, d=8, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(classes, d) * 3
    y = rs.randint(0, classes, n)
    x = centers[y] + rs.randn(n, d)
    return x.astype(np.float32), y.astype(np.int32)


def test_mlp_learns():
    x, y = make_blobs()
    ff = FFModel(FFConfig(batch_size=32))
    t = ff.create_tensor((32, 8))
    t = ff.dense(t, 32, activation=ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.ACCURACY])
    assert len(jax.devices()) == 8  # conftest forced the virtual mesh
    before = ff.evaluate(x, y)
    ff.fit(x, y, epochs=5, verbose=False)
    after = ff.evaluate(x, y)
    assert after["loss"] < before["loss"]
    assert after["accuracy"] > 0.8


def test_mlp_adam_and_mse():
    rs = np.random.RandomState(1)
    x = rs.randn(128, 4).astype(np.float32)
    w = rs.randn(4, 1).astype(np.float32)
    y = x @ w
    ff = FFModel(FFConfig(batch_size=32))
    t = ff.create_tensor((32, 4))
    t = ff.dense(t, 16, activation=ActiMode.AC_MODE_TANH)
    t = ff.dense(t, 1)
    ff.compile(AdamOptimizer(alpha=0.01),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
    ff.fit(x, y, epochs=20, verbose=False)
    assert ff.evaluate(x, y)["loss"] < 0.1


def test_adam_bf16_state():
    """Reduced-precision (bf16) m/v storage must converge like f32 state
    (the bench's TPU-native optimizer configuration, bench.py)."""
    import jax.numpy as jnp

    rs = np.random.RandomState(1)
    x = rs.randn(128, 4).astype(np.float32)
    w = rs.randn(4, 1).astype(np.float32)
    y = x @ w

    def run(state_dtype):
        ff = FFModel(FFConfig(batch_size=32, seed=5))
        t = ff.create_tensor((32, 4))
        t = ff.dense(t, 16, activation=ActiMode.AC_MODE_TANH)
        t = ff.dense(t, 1)
        ff.compile(AdamOptimizer(alpha=0.01, state_dtype=state_dtype),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        ff.fit(x, y, epochs=20, verbose=False)
        return ff.evaluate(x, y)["loss"]

    loss_bf16 = run(jnp.bfloat16)
    loss_f32 = run(None)
    assert loss_bf16 < 0.1
    assert abs(loss_bf16 - loss_f32) < 0.05


def test_forward_backward_update_protocol():
    """Reference iteration protocol (flexflow_cffi.py:2073-2086)."""
    x, y = make_blobs(64, 8, 4)
    ff = FFModel(FFConfig(batch_size=64))
    t = ff.create_tensor((64, 8))
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.ACCURACY])
    loss0 = ff.evaluate(x, y)["loss"]
    for _ in range(5):
        ff.set_batch(x, y)
        ff.begin_trace(111)
        ff.forward()
        ff.zero_gradients()
        ff.backward()
        ff.update()
        ff.end_trace(111)
    assert ff.evaluate(x, y)["loss"] < loss0


def test_predict_shape():
    ff = FFModel()
    t = ff.create_tensor((16, 10))
    t = ff.dense(t, 3)
    ff.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
    out = ff.predict(np.zeros((16, 10), np.float32))
    assert out.shape == (16, 3)


def test_dp_matches_single_device():
    """DP over 8 virtual devices must match single-device numerics
    (SURVEY §7 stage 2 acceptance)."""
    from flexflow_tpu.machine import make_mesh

    x, y = make_blobs(64, 8, 4)

    def build(mesh):
        ff = FFModel(FFConfig(batch_size=64, seed=7))
        t = ff.create_tensor((64, 8))
        t = ff.dense(t, 16, activation=ActiMode.AC_MODE_RELU)
        t = ff.dense(t, 4)
        t = ff.softmax(t)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.ACCURACY], mesh=mesh)
        return ff

    ff8 = build(make_mesh(8, {"data": 8}))
    ff1 = build(make_mesh(1, {"data": 1}))
    for ff in (ff8, ff1):
        ff.fit(x, y, epochs=3, verbose=False)
    w8 = ff8.get_parameter(ff8.get_layer_names()[0])
    w1 = ff1.get_parameter(ff1.get_layer_names()[0])
    np.testing.assert_allclose(w8, w1, rtol=1e-4, atol=1e-5)


def test_cnn_forward_and_train():
    """Mini AlexNet-style CNN on random CIFAR-shaped data (stage-1 slice)."""
    rs = np.random.RandomState(0)
    x = rs.randn(32, 3, 16, 16).astype(np.float32)
    y = rs.randint(0, 10, 32).astype(np.int32)
    ff = FFModel(FFConfig(batch_size=32))
    t = ff.create_tensor((32, 3, 16, 16))
    t = ff.conv2d(t, 8, 3, 3, 1, 1, 1, 1, activation=ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.conv2d(t, 16, 3, 3, 1, 1, 1, 1, activation=ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 32, activation=ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 10)
    t = ff.softmax(t)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.ACCURACY])
    l0 = ff.evaluate(x, y)["loss"]
    ff.fit(x, y, epochs=10, verbose=False)
    assert ff.evaluate(x, y)["loss"] < l0


def test_parameter_parallel_matches_dp():
    """--enable-parameter-parallel: model-axis sharded Linear must keep
    numerics (GSPMD inserts the Combine/Reduction collectives)."""
    from flexflow_tpu.machine import make_mesh

    x, y = make_blobs(64, 8, 4)

    def build(enable_pp):
        cfg = FFConfig(batch_size=64, seed=3)
        cfg.enable_parameter_parallel = enable_pp
        ff = FFModel(cfg)
        t = ff.create_tensor((64, 8))
        t = ff.dense(t, 16, activation=ActiMode.AC_MODE_RELU)
        t = ff.dense(t, 4)
        t = ff.softmax(t)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.ACCURACY])
        return ff

    ff_tp = build(True)
    assert "model" in ff_tp.mesh.axis_names
    ff_dp = build(False)
    for ff in (ff_tp, ff_dp):
        ff.fit(x, y, epochs=3, verbose=False)
    w_tp = ff_tp.get_parameter(ff_tp.get_layer_names()[0])
    w_dp = ff_dp.get_parameter(ff_dp.get_layer_names()[0])
    np.testing.assert_allclose(w_tp, w_dp, rtol=1e-4, atol=1e-5)


def test_moe_trains_with_lb_loss():
    x, y = make_blobs(64, 8, 4)
    ff = FFModel(FFConfig(batch_size=64))
    t = ff.create_tensor((64, 8))
    t = ff.moe(t, num_exp=4, num_select=2, expert_hidden_size=16,
               alpha=2.0, lambda_bal=0.04)
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.ACCURACY])
    l0 = ff.evaluate(x, y)["loss"]
    ff.fit(x, y, epochs=10, verbose=False)
    assert ff.evaluate(x, y)["loss"] < l0


def test_fit_smaller_than_batch_raises():
    ff = FFModel()
    t = ff.create_tensor((32, 4))
    t = ff.dense(t, 2)
    ff.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
    with pytest.raises(ValueError, match="smaller than batch"):
        ff.fit(np.zeros((16, 4), np.float32), np.zeros((16, 2), np.float32))


def test_duplicate_layer_names_do_not_collide():
    ff = FFModel()
    t = ff.create_tensor((8, 4))
    t = ff.dense(t, 8, name="fc")
    t = ff.dense(t, 2, name="fc")
    ff.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
    names = ff.get_layer_names()
    assert len(set(names)) == 2

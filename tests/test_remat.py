"""Rematerialization as a searched dimension (ISSUE 20).

The ``_r`` suffix-lattice twins: native enumeration of per-op remat
choices priced as +recompute-forward in the backward term against
-interior ``act_memory`` in the frontier DP's memory terms, legality
gates with named rejection reasons in the search trace, the
``FFS_NO_REMAT`` / ``--remat-search off`` opt-out (bit-identical
searches), the memory-capped acceptance fixture (a batch that fits ONLY
with remat), executor parity (``jax.checkpoint`` per-op is bit-for-bit
with the plain forward over a seeded 3-step run and cuts the compiled
HBM peak), remat x flash composition at the executor, and the
pipeline-body block-level remat bit at pp=2.

Runs on the conftest 8-device virtual CPU mesh.
"""

import copy
import json

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import LossType
from flexflow_tpu.machine import make_mesh
from flexflow_tpu.model import FFModel
from flexflow_tpu.optimizers import SGDOptimizer

BATCH = 16

# ---- native mini-graph harness (test_kernel_search's pattern) -------------

_MACHINE = {"num_devices": 8, "flops": 197e12, "hbm_bw": 0.82e12,
            "hbm_cap": 16e9, "ici_bw": 45e9, "ici_latency": 1e-6,
            "dcn_bw": 25e9, "dcn_latency": 1e-5, "num_slices": 1,
            "comm_bytes_factor": 0.5}


def _attn_ffn_nodes(seq=512, dropout=0.0):
    """Self-attention + FFN up/down pair: the remat gate's three classes
    on one graph — einsum attention spawns ``_r`` (score matrix is
    interior), the up-projection spawns (output 4x the input), the
    down-projection is rejected (interior <= boundary)."""
    attrs = {"num_heads": 8}
    if dropout:
        attrs["dropout"] = dropout
    return [
        dict(guid=1, type="MULTIHEAD_ATTENTION", name="attn",
             inputs=[[-1, 0], [-1, 0], [-1, 0]],
             input_shapes=[[8, seq, 128]] * 3,
             output_shapes=[[8, seq, 128]],
             roles=[["sample", "seq", "channel"]],
             params={"wq": [8, 128, 16], "wk": [8, 128, 16],
                     "wv": [8, 128, 16], "wo": [8, 16, 128]},
             flops=1e9, dtype_size=4, attrs=attrs),
        dict(guid=2, type="LINEAR", name="up", inputs=[[1, 0]],
             input_shapes=[[8, seq, 128]], output_shapes=[[8, seq, 512]],
             roles=[["sample", "seq", "channel"]],
             params={"kernel": [128, 512], "bias": [512]},
             flops=1e9, dtype_size=4, attrs={}),
        dict(guid=3, type="LINEAR", name="down", inputs=[[2, 0]],
             input_shapes=[[8, seq, 512]], output_shapes=[[8, seq, 128]],
             roles=[["sample", "seq", "channel"]],
             params={"kernel": [512, 128], "bias": [128]},
             flops=1e9, dtype_size=4, attrs={}),
    ]


def _req(nodes, **cfg):
    base = dict(budget=2, training=True, enable_parameter_parallel=True,
                enable_substitution=False, batch=8,
                emit_search_trace=True)
    base.update(cfg)
    return dict(nodes=nodes, machine=dict(_MACHINE), measured={},
                config=base)


def _native():
    from flexflow_tpu.search import native
    if not native.available():
        pytest.skip("native search unavailable")
    return native


def _trace_ops(resp):
    return {o["name"]: o for o in resp["search_trace"]["ops"]}


class TestNativeRematDimension:
    def test_r_twins_spawn_and_compose_with_suffix_lattice(self):
        native = _native()
        resp = native.native_optimize(_req(_attn_ffn_nodes()))
        ops = _trace_ops(resp)
        up = [c["choice"] for c in ops["up"]["candidates"]]
        # the remat suffix is LAST in the canonical order and composes
        # with the whole _wus/_ovl lattice
        assert any(n.endswith("_r") and "_wus" in n for n in up), up
        attn = [c["choice"] for c in ops["attn"]["candidates"]]
        assert any(n.endswith("_r") for n in attn), attn
        # flash twins carry no _r: flash keeps no score matrix, so the
        # interior<=boundary gate rejects the twin instead of pricing a
        # remat that frees nothing
        assert not any("_k:flash" in n and n.endswith("_r") for n in attn)
        # the down-projection's interior IS its boundary: no twin at all
        down = [c["choice"] for c in ops["down"]["candidates"]]
        assert not any(n.endswith("_r") for n in down), down

    def test_priced_strictly_slower_with_remat_row(self):
        native = _native()
        resp = native.native_optimize(_req(_attn_ffn_nodes()))
        ops = _trace_ops(resp)
        cands = {c["choice"]: c for c in ops["up"]["candidates"]}
        base, twin = cands["dp"], cands["dp_r"]
        # +recompute-forward in backward: the twin can only win through
        # the DP's memory terms, never on time
        assert twin["terms"]["total_s"] > base["terms"]["total_s"]
        assert twin["cost_source"] == base["cost_source"]
        row = twin["remat"]
        assert row["freed_act_bytes"] > 0
        assert row["recompute_s"] == pytest.approx(
            base["terms"]["fwd_s"], rel=1e-9)

    def test_named_rejections_in_trace(self):
        native = _native()
        # dropout interior: recompute would need the dropout mask
        resp = native.native_optimize(
            _req(_attn_ffn_nodes(dropout=0.1)))
        rej = [r["reason"]
               for r in _trace_ops(resp)["attn"].get("remat_rejections")
               or []]
        assert "dropout_interior" in rej, rej
        # interior <= boundary carries its named reason too
        resp2 = native.native_optimize(_req(_attn_ffn_nodes()))
        rej2 = [r["reason"]
                for r in _trace_ops(resp2)["down"].get("remat_rejections")
                or []]
        assert rej2 == ["interior_not_larger_than_boundary"], rej2

    def test_opt_out_removes_dimension_bit_identically(self):
        native = _native()
        on = native.native_optimize(_req(_attn_ffn_nodes()))
        off = native.native_optimize(
            _req(_attn_ffn_nodes(), remat_search="off"))
        names_off = [c["choice"] for o in off["search_trace"]["ops"]
                     for c in o["candidates"]]
        assert not any(n.endswith("_r") for n in names_off)
        off2 = native.native_optimize(
            _req(_attn_ffn_nodes(), remat_search="off"))
        assert json.dumps(off, sort_keys=True) == \
            json.dumps(off2, sort_keys=True)
        names_on = [c["choice"] for o in on["search_trace"]["ops"]
                    for c in o["candidates"]]
        assert set(names_off) < set(names_on)

    def test_replay_tolerates_and_falls_back_r_suffix(self):
        native = _native()
        base = dict(nodes=_attn_ffn_nodes(), machine=dict(_MACHINE),
                    measured={},
                    config=dict(training=True,
                                enable_parameter_parallel=True),
                    mesh={"data": 8, "model": 1, "seq": 1, "expert": 1,
                          "pipe": 1},
                    assignment={"1": "dp_r", "2": "dp_wus_r", "3": "dp"})
        r = native.native_simulate(base)
        assert r["iteration_time"] > 0
        # remat search off: the "_r" request falls back along the suffix
        # lattice to the un-remat twin instead of erroring, and prices
        # faster (no recompute in backward)
        off = copy.deepcopy(base)
        off["config"]["remat_search"] = "off"
        r2 = native.native_simulate(off)
        assert r2["iteration_time"] <= r["iteration_time"]
        # the recompute lands in the backward term (the step total may
        # tie when overlapped comm paces the critical path)
        assert r["bwd_time"] > r2["bwd_time"]


def _deep_mlp_nodes(b, d, h, layers):
    nodes, src = [], [-1, 0]
    for i in range(layers):
        nodes.append(dict(guid=2 * i + 1, type="LINEAR", name=f"up{i}",
                          inputs=[src], input_shapes=[[b, d]],
                          output_shapes=[[b, h]],
                          roles=[["sample", "channel"]],
                          params={"kernel": [d, h], "bias": [h]},
                          flops=2.0 * b * d * h, dtype_size=4, attrs={}))
        nodes.append(dict(guid=2 * i + 2, type="LINEAR", name=f"down{i}",
                          inputs=[[2 * i + 1, 0]], input_shapes=[[b, h]],
                          output_shapes=[[b, d]],
                          roles=[["sample", "channel"]],
                          params={"kernel": [h, d], "bias": [d]},
                          flops=2.0 * b * d * h, dtype_size=4, attrs={}))
        src = [2 * i + 2, 0]
    return nodes


class TestMemoryCappedAcceptance:
    """The tentpole fixture: a memory-capped simulated v4-32 search
    where the ``_r``-enabled winner fits a batch the remat-less search
    rejects outright."""

    def _run(self, threshold, remat):
        native = _native()
        machine = dict(_MACHINE, num_devices=32, flops=275e12,
                       hbm_bw=1.2e12, hbm_cap=32e9)
        return native.native_optimize(dict(
            nodes=_deep_mlp_nodes(131072, 256, 2048, 6),
            machine=machine, measured={},
            config=dict(budget=0, training=True, only_data_parallel=True,
                        enable_substitution=False, batch=131072, seed=42,
                        opt_state_factor=0.0, memory_threshold=threshold,
                        remat_search=remat)))

    def test_capped_v4_32_search_fits_only_with_remat(self):
        free = self._run(0, "auto")
        assert not any(v["choice"].endswith("_r")
                       for v in free["ops"].values())
        cap = free["predicted_memory"] * 0.6
        capped = self._run(cap, "auto")
        assert capped["predicted_memory"] <= cap
        winners = {v["choice"] for v in capped["ops"].values()}
        assert any(c.endswith("_r") for c in winners), winners
        # remat buys memory with time: strictly slower than uncapped
        assert capped["predicted_time"] > free["predicted_time"]
        # the remat-less search cannot fit the same batch
        with pytest.raises(RuntimeError, match="no feasible strategy"):
            self._run(cap, "off")


class TestFlagPlumbing:
    def test_flag_parsing(self):
        cfg = FFConfig()
        assert cfg.parse_args(["--remat-search", "off"]) == []
        assert cfg.remat_search == "off"
        assert FFConfig().remat_search == "auto"
        with pytest.raises(ValueError):
            FFConfig().parse_args(["--remat-search", "sometimes"])

    def test_suffix_helpers(self):
        from flexflow_tpu.search.unity import (kernel_choice_of,
                                               remat_choice_of)
        assert remat_choice_of("dp_r")
        assert remat_choice_of("dp_wus_ovl_k:fused_r")
        assert not remat_choice_of("dp")
        assert not remat_choice_of(None)
        # the kernel extractor must not swallow the trailing remat suffix
        assert kernel_choice_of("dp_k:flash_r") == "flash"
        assert kernel_choice_of("dp_wus_k:fused_r") == "fused"
        assert kernel_choice_of("dp_r") is None

    def test_executed_remat_ops(self):
        from flexflow_tpu.search.unity import executed_remat_ops

        class _Op:
            def __init__(self, guid, name):
                self.guid, self.name = guid, name

        class _Node:
            def __init__(self, guid, name):
                self.op = _Op(guid, name)

        class _St:
            def __init__(self, choice):
                self.choice = choice

        nodes = [_Node(1, "a"), _Node(2, "b"), _Node(3, "c")]
        strategy = {1: _St("dp_r"), 2: _St("dp"), 3: _St("dp_k:fused_r")}
        assert executed_remat_ops(nodes, strategy) == {"a", "c"}
        assert executed_remat_ops(nodes, None) == set()

    def test_env_opt_out_forces_remat_off(self, monkeypatch):
        monkeypatch.setenv("FFS_NO_REMAT", "1")
        ff = _mlp(remat_ops=None)
        assert ff.remat_ops is None


def _mlp(remat_ops, layers=4, lint="off"):
    """Heuristic MLP on the 8-way data mesh; remat forced per-op so both
    runs share ONE strategy (the _plain_mlp pattern)."""
    cfg = FFConfig(batch_size=BATCH, seed=42)
    cfg.lint = lint
    ff = FFModel(cfg)
    x = ff.create_tensor((BATCH, 64), name="x")
    t = x
    for i in range(layers):
        t = ff.dense(t, 2048, name=f"up{i}")
        t = ff.relu(t)
        t = ff.dense(t, 64, name=f"down{i}")
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
               mesh=make_mesh(8, {"data": 8}))
    if remat_ops:
        ff.executor.remat_ops = set(remat_ops)
    return ff


class TestExecutorParity:
    def _train(self, ff, steps=3, d=64):
        import jax
        rs = np.random.RandomState(0)
        x = rs.randn(BATCH, d).astype(np.float32)
        y = rs.randn(BATCH, d).astype(np.float32)
        for _ in range(steps):
            ff.fit([x], y, epochs=1, verbose=False)
        return [np.asarray(l) for l in
                jax.tree_util.tree_leaves(ff.params)]

    def test_remat_bitwise_and_cuts_hbm_on_8way_mesh(self):
        """Acceptance: jax.checkpoint per-op is bit-for-bit with the
        plain forward over 3 seeded steps AND the compiled HBM peak
        (args + temps) drops >= 20% when the wide interiors remat."""
        from flexflow_tpu.search.validate import compiled_train_step
        states, peaks = {}, {}
        for mode in ("off", "on"):
            ff = _mlp({f"up{i}" for i in range(4)}
                      if mode == "on" else None,
                      lint="warn" if mode == "on" else "off")
            ma = compiled_train_step(ff).memory_analysis()
            peaks[mode] = ma.argument_size_in_bytes + ma.temp_size_in_bytes
            if mode == "on":
                # no FFL2xx drift: recompute duplicates edges, not
                # collectives — the priced-vs-emitted census stays clean
                assert ff.lint_report is not None
                assert not ff.lint_report.has_errors(), \
                    ff.lint_report.format_human()
            states[mode] = self._train(ff)
        for a, b in zip(states["off"], states["on"]):
            assert np.array_equal(a, b)
        assert peaks["on"] <= 0.8 * peaks["off"], peaks

    def test_long_context_attention_hbm_peak_at_seq_2k(self, monkeypatch):
        """Long-context attention (seq 2048): the winning composition is
        flash + remat, exactly the lattice twin ``_k:flash_r``. Remat of
        the EINSUM attention alone cannot cut the compiled peak — the
        recompute re-materializes the same O(seq^2) score interior at
        backward time (this is why remat_gate rejects flashless twins
        only when interior <= boundary, not the reverse). Flash removes
        the interior entirely; remat then frees the boundary
        activations. Measured on this fixture the flash+remat compiled
        peak is ~4% of the einsum-plain peak, so the 20% bound below has
        a 5x margin."""
        monkeypatch.setenv("FLEXFLOW_TPU_PALLAS", "interpret")
        from flexflow_tpu.search.validate import compiled_train_step

        def build(impl, remat):
            cfg = FFConfig(batch_size=2, seed=42)
            ff = FFModel(cfg)
            x = ff.create_tensor((2, 2048, 32), name="x")
            t = x
            for i in range(2):
                t = ff.multihead_attention(t, t, t, 32, 2,
                                           name=f"attn{i}")
            ff.dense(t, 32, name="fc")
            ff.compile(SGDOptimizer(lr=0.01),
                       LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
            for n in ff.executor.nodes:
                if n.op.name.startswith("attn"):
                    n.op.kernel_impl = impl
            if remat:
                ff.executor.remat_ops = {f"attn{i}" for i in range(2)}
            return ff

        peaks = {}
        for key, (impl, remat) in dict(einsum=("einsum", False),
                                       flash_r=("flash", True)).items():
            ma = compiled_train_step(build(impl, remat)).memory_analysis()
            peaks[key] = (ma.argument_size_in_bytes
                          + ma.temp_size_in_bytes)
        # each layer's score/prob interior is ~2*2*2048*2048*4 B; at
        # seq 2048 those dwarf every boundary tensor
        assert peaks["flash_r"] < 0.2 * peaks["einsum"], peaks

    def test_remat_composes_with_flash_kernel(self, monkeypatch):
        """remat x ``_k:`` composition at the executor: a checkpointed
        attention running the flash (interpret) lowering stays within
        the documented 2e-5 class of the plain einsum step."""
        monkeypatch.setenv("FLEXFLOW_TPU_PALLAS", "interpret")
        import jax

        def build(impl, remat):
            cfg = FFConfig(batch_size=4, seed=42)
            ff = FFModel(cfg)
            x = ff.create_tensor((4, 256, 32), name="x")
            t = ff.multihead_attention(x, x, x, 32, 4, name="attn")
            ff.dense(t, 32, name="fc")
            ff.compile(SGDOptimizer(lr=0.01),
                       LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
            for n in ff.executor.nodes:
                if n.op.name == "attn":
                    n.op.kernel_impl = impl
            if remat:
                ff.executor.remat_ops = {"attn"}
            return ff

        rs = np.random.RandomState(0)
        x = rs.randn(4, 256, 32).astype(np.float32)
        y = rs.randn(4, 256, 32).astype(np.float32)
        leaves = {}
        for key, (impl, remat) in dict(
                plain=("einsum", False),
                flash_r=("flash", True)).items():
            ff = build(impl, remat)
            ff.fit([x], y, epochs=1, verbose=False)
            leaves[key] = [np.asarray(l) for l in
                           jax.tree_util.tree_leaves(ff.params)]
        diffs = [float(np.max(np.abs(a.astype(np.float64)
                                     - b.astype(np.float64))))
                 for a, b in zip(leaves["plain"], leaves["flash_r"])]
        assert max(diffs) < 2e-5, diffs

    def test_pipeline_body_remat_parity_at_pp2(self):
        """The block-level remat bit re-derives block interiors inside
        the pp=2 SPMD pipeline. Parity class: the recomputed interior is
        re-fused by XLA in its own backward subgraph, so reduction
        ordering (layernorm/softmax sums) can drift in the last ulps —
        observed max diff ~1.5e-8 (one f32-ulp class at these
        magnitudes) over 3 seeded steps; bound at 5e-8 (vs the per-op
        jax.checkpoint path, which IS bit-for-bit; see
        test_remat_bitwise_and_cuts_hbm_on_8way_mesh)."""
        import jax
        from tests.test_pipeline import _DEEP_NARROW, _build_transformer

        rs = np.random.RandomState(0)
        # half the _DEEP_NARROW depth on a 4-device mesh: the remat bit
        # wraps whole block bodies, so 2 blocks/stage exercise the same
        # template path as 4 at half the compile cost
        cfg = dict(_DEEP_NARROW, num_layers=4)
        x = rs.randn(cfg["batch_size"], cfg["seq_length"],
                     cfg["hidden_size"]).astype(np.float32)
        y = rs.randn(cfg["batch_size"], cfg["seq_length"],
                     cfg["hidden_size"]).astype(np.float32)
        states = {}
        for remat in (False, True):
            ff = _build_transformer(
                cfg, mesh=make_mesh(4, {"pipe": 2, "data": 2}))
            ff.executor.body_remat = remat
            assert ff.executor.num_stages == 2
            for _ in range(3):
                ff.fit([x], y, epochs=1, verbose=False)
            states[remat] = [np.asarray(l) for l in
                             jax.tree_util.tree_leaves(ff.params)]
        diffs = [float(np.max(np.abs(a.astype(np.float64)
                                     - b.astype(np.float64))))
                 for a, b in zip(states[False], states[True])]
        assert max(diffs) < 5e-8, diffs

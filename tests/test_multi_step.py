"""Multi-step compiled training (trace-replay analog, executor.make_multi_step)."""

import numpy as np
import jax

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.ffconst import ActiMode


def build():
    ff = FFModel(FFConfig(batch_size=16, only_data_parallel=True, seed=7))
    t = ff.create_tensor((16, 8))
    h = ff.dense(t, 16, activation=ActiMode.AC_MODE_RELU, name="h")
    ff.dense(h, 2, name="out")
    ff.compile(SGDOptimizer(lr=0.05), LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               [MetricsType.MEAN_SQUARED_ERROR])
    return ff


class TestMultiStep:
    def test_matches_sequential_steps(self):
        rs = np.random.RandomState(0)
        x = rs.randn(16, 8).astype(np.float32)
        y = rs.randn(16, 2).astype(np.float32)

        ff1 = build()
        ff2 = build()
        for lname, sub in ff1.params.items():
            for pname in sub:
                ff2.set_parameter(lname, np.asarray(sub[pname]), pname)

        inputs1 = ff1._stage_inputs([x])
        labels1 = ff1._shard_batch(y)
        rng = jax.random.PRNGKey(0)
        step = ff1.executor.make_train_step()
        p, o, s = ff1.params, ff1.opt_state, ff1.state
        r = rng
        losses_seq = []
        for _ in range(3):
            r, sub = jax.random.split(r)
            p, o, s, loss, _ = step(p, o, s, inputs1, labels1, sub)
            losses_seq.append(float(loss))

        inputs2 = ff2._stage_inputs([x])
        labels2 = ff2._shard_batch(y)
        multi = ff2.executor.make_multi_step(3)
        p2, o2, s2, losses = multi(ff2.params, ff2.opt_state, ff2.state,
                                   inputs2, labels2, rng)
        np.testing.assert_allclose(np.asarray(losses), losses_seq,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(p2["out"]["kernel"]),
                                   np.asarray(p["out"]["kernel"]),
                                   rtol=1e-5, atol=1e-6)

    def test_stacked_batches(self):
        rs = np.random.RandomState(1)
        ff = build()
        xs = rs.randn(4, 16, 8).astype(np.float32)  # 4 distinct batches
        ys = rs.randn(4, 16, 2).astype(np.float32)
        name = ff.executor.input_names[0]
        multi = ff.executor.make_multi_step(4, stacked=True)
        import jax.numpy as jnp

        p, o, s, losses = multi(ff.params, ff.opt_state, ff.state,
                                {name: jnp.asarray(xs)}, jnp.asarray(ys),
                                jax.random.PRNGKey(0))
        assert losses.shape == (4,)
        assert np.isfinite(np.asarray(losses)).all()

"""Pipeline parallelism (SPMD GPipe over a 'pipe' mesh axis).

Exceeds the reference, where pipeline parallelism is an enum with no
runtime (ffconst.h:153 OP_PIPELINE). Numerics and gradients are checked
against the plain sequential execution of the same stages.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from flexflow_tpu.machine import make_mesh
from flexflow_tpu.parallel.pipeline import (pipeline_spmd, shard_stacked,
                                            stack_stage_params)

S, D = 4, 16


def stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def make_params(seed):
    rs = np.random.RandomState(seed)
    return [{"w": jnp.asarray(rs.randn(D, D).astype(np.float32) * 0.3),
             "b": jnp.asarray(rs.randn(D).astype(np.float32) * 0.1)}
            for _ in range(S)]


def sequential(per_stage, x):
    for p in per_stage:
        x = stage_fn(p, x)
    return x


class TestPipeline:
    def test_matches_sequential(self):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(0)
        stacked = shard_stacked(stack_stage_params(per_stage), mesh)
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.randn(16, D).astype(np.float32))
        want = sequential(per_stage, x)
        got = jax.jit(lambda p, x: pipeline_spmd(
            stage_fn, p, x, mesh, num_microbatches=4))(stacked, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    @pytest.mark.parametrize("microbatches", [1, 2, 8])
    def test_microbatch_counts(self, microbatches):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(2)
        stacked = shard_stacked(stack_stage_params(per_stage), mesh)
        x = jnp.asarray(np.random.RandomState(3).randn(16, D)
                        .astype(np.float32))
        want = sequential(per_stage, x)
        got = pipeline_spmd(stage_fn, stacked, x, mesh,
                            num_microbatches=microbatches)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    def test_gradients_flow_through_pipeline(self):
        # GPipe backward = autodiff through shard_map + ppermute: grads of
        # every stage's params must match the sequential model's
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(4)
        stacked = stack_stage_params(per_stage)
        stacked_dev = shard_stacked(stacked, mesh)
        rs = np.random.RandomState(5)
        x = jnp.asarray(rs.randn(8, D).astype(np.float32))
        y = jnp.asarray(rs.randn(8, D).astype(np.float32))

        def loss_pipe(p):
            out = pipeline_spmd(stage_fn, p, x, mesh, num_microbatches=2)
            return jnp.mean((out - y) ** 2)

        def loss_seq(stages):
            return jnp.mean((sequential(stages, x) - y) ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(stacked_dev)
        g_seq = jax.grad(loss_seq)(per_stage)
        for i in range(S):
            for k in ("w", "b"):
                np.testing.assert_allclose(
                    np.asarray(g_pipe[k][i]), np.asarray(g_seq[i][k]),
                    rtol=5e-4, atol=5e-6)

    def test_trains_end_to_end(self):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(6)
        params = shard_stacked(stack_stage_params(per_stage), mesh)
        rs = np.random.RandomState(7)
        x = jnp.asarray(rs.randn(16, D).astype(np.float32))
        y = jnp.asarray((rs.randn(16, D) * 0.1).astype(np.float32))

        @jax.jit
        def step(p):
            def loss(p):
                out = pipeline_spmd(stage_fn, p, x, mesh,
                                    num_microbatches=4)
                return jnp.mean((out - y) ** 2)

            l, g = jax.value_and_grad(loss)(p)
            return jax.tree.map(lambda w, gw: w - 0.3 * gw, p, g), l

        l0 = None
        for i in range(30):
            params, l = step(params)
            l0 = l0 if l0 is not None else float(l)
        assert float(l) < l0 * 0.5, (l0, float(l))

    def test_stage_count_mismatch_rejected(self):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        eight = make_params(8) + make_params(9)  # 8 stages vs pipe=4
        stacked = stack_stage_params(eight)
        x = jnp.ones((8, D), jnp.float32)
        with pytest.raises(ValueError, match="drop stages"):
            pipeline_spmd(stage_fn, stacked, x, mesh, num_microbatches=2)

    def test_composes_with_data_axis(self):
        # the data axis shards each microbatch (review finding: previously
        # both data replicas redundantly computed the full batch)
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(10)
        stacked = shard_stacked(stack_stage_params(per_stage), mesh)
        x = jnp.asarray(np.random.RandomState(11).randn(16, D)
                        .astype(np.float32))
        want = sequential(per_stage, x)
        got = pipeline_spmd(stage_fn, stacked, x, mesh, num_microbatches=4)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)
        # pipe-only mesh (no data axis) still works
        mesh1 = make_mesh(4, {"pipe": S})
        stacked1 = shard_stacked(stack_stage_params(per_stage), mesh1)
        got1 = pipeline_spmd(stage_fn, stacked1, x, mesh1,
                             num_microbatches=2)
        np.testing.assert_allclose(np.asarray(got1), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)


class TestPipelinedTransformer:
    """The pipeline carrying the framework's real ops: S pre-norm
    transformer blocks (MultiHeadAttention / LayerNorm / Linear) as the
    repeated stage."""

    def test_pipelined_transformer_matches_sequential(self):
        from flexflow_tpu.parallel.pipeline import transformer_block_stage

        S_, b, s, e = 4, 4, 8, 32
        mesh = make_mesh(8, {"pipe": S_, "data": 2})
        init_fn, stage = transformer_block_stage(
            embed_dim=e, num_heads=4, seq_length=s,
            batch_per_microbatch=b // 2, ffn_mult=2)
        rngs = jax.random.split(jax.random.PRNGKey(0), S_)
        per_stage = [init_fn(k) for k in rngs]
        stacked = shard_stacked(stack_stage_params(per_stage), mesh)
        x = jnp.asarray(np.random.RandomState(0).randn(b, s, e)
                        .astype(np.float32) * 0.3)
        want = x
        for p in per_stage:
            want = stage(p, want)
        got = jax.jit(lambda pp, xx: pipeline_spmd(
            stage, pp, xx, mesh, num_microbatches=2))(stacked, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_pipelined_transformer_trains(self):
        from flexflow_tpu.parallel.pipeline import transformer_block_stage

        S_, b, s, e = 4, 4, 8, 16
        mesh = make_mesh(8, {"pipe": S_, "data": 2})
        init_fn, stage = transformer_block_stage(
            embed_dim=e, num_heads=2, seq_length=s,
            batch_per_microbatch=b // 2, ffn_mult=2)
        per_stage = [init_fn(k) for k in
                     jax.random.split(jax.random.PRNGKey(1), S_)]
        params = shard_stacked(stack_stage_params(per_stage), mesh)
        rs = np.random.RandomState(2)
        x = jnp.asarray(rs.randn(b, s, e).astype(np.float32) * 0.3)
        y = jnp.asarray((rs.randn(b, s, e) * 0.1).astype(np.float32))

        @jax.jit
        def step(p):
            def loss(p):
                out = pipeline_spmd(stage, p, x, mesh, num_microbatches=2)
                return jnp.mean((out - y) ** 2)

            l, g = jax.value_and_grad(loss)(p)
            return jax.tree.map(lambda w, gw: w - 0.05 * gw, p, g), l

        l0 = None
        for _ in range(20):
            params, l = step(params)
            l0 = l0 if l0 is not None else float(l)
        assert float(l) < l0 * 0.8, (l0, float(l))


def _build_transformer(cfg_kwargs, ff_kwargs=None, mesh=None, lr=0.001,
                       microbatches=0, **compile_kw):
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType
    from flexflow_tpu.models.transformer import (TransformerConfig,
                                                 create_transformer)
    from flexflow_tpu.optimizers import SGDOptimizer

    cfg = TransformerConfig(**cfg_kwargs)
    c = FFConfig(batch_size=cfg.batch_size, seed=7, **(ff_kwargs or {}))
    c.pipeline_microbatches = microbatches
    ff = create_transformer(cfg, c)
    ff.compile(SGDOptimizer(lr=lr), LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               [], mesh=mesh, **compile_kw)
    return ff


_DEEP_NARROW = dict(num_layers=8, hidden_size=64, num_heads=4,
                    seq_length=32, batch_size=16)


class TestPipelineDetection:
    def test_transformer_blocks(self):
        ff = _build_transformer(_DEEP_NARROW, mesh=make_mesh(1, {"data": 1}))
        from flexflow_tpu.parallel.pipeline_detect import (
            detect_repeated_blocks)
        pb = detect_repeated_blocks(ff.executor.nodes)
        assert pb is not None
        assert pb.num_blocks == 8
        assert pb.body_in == ("input", "input")
        # tail = the classification head dense
        assert [ff.executor.nodes[i].op.name for i in pb.tail] == ["head"]

    def test_non_repeated_graph_returns_none(self):
        from flexflow_tpu import FFConfig, FFModel, LossType
        from flexflow_tpu.parallel.pipeline_detect import (
            detect_repeated_blocks)

        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 16))
        t = ff.dense(t, 32)
        t = ff.dense(t, 4)  # different shapes: not repeated blocks
        ff.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
        assert detect_repeated_blocks(ff.executor.nodes) is None


class TestPipelineLowering:
    """FFModel.compile lowers a 'pipe' mesh onto PipelineGraphExecutor
    (VERDICT r3 Next #1: pipeline as a framework capability, not a
    library demo)."""

    @pytest.mark.slow
    def test_explicit_pipe_mesh_matches_single_device(self):
        from flexflow_tpu.parallel.pipeline_exec import (
            BODY_KEY, PipelineGraphExecutor)

        rs = np.random.RandomState(0)
        x = rs.randn(16, 32, 64).astype(np.float32)
        y = rs.randn(16, 32, 1).astype(np.float32)
        ff_pipe = _build_transformer(
            _DEEP_NARROW, mesh=make_mesh(8, {"pipe": 4, "data": 2}),
            microbatches=4)
        assert isinstance(ff_pipe.executor, PipelineGraphExecutor)
        # body params stacked [R, ...] and sharded over the pipe axis
        leaf = ff_pipe.params[BODY_KEY]["op4"]["kernel"]
        assert leaf.shape[0] == 8
        assert "pipe" in jax.tree.leaves(leaf.sharding.spec)[0:1][0] or \
            leaf.sharding.spec[0] == "pipe"
        ff_ref = _build_transformer(_DEEP_NARROW,
                                    mesh=make_mesh(1, {"data": 1}))
        for lname, sub in ff_ref.params.items():
            for pname in sub:
                ff_pipe.set_parameter(lname,
                                      ff_ref.get_parameter(lname, pname),
                                      pname)
        np.testing.assert_allclose(ff_pipe.predict(x), ff_ref.predict(x),
                                   rtol=1e-5, atol=1e-5)
        for ff in (ff_pipe, ff_ref):
            ff.fit(x, y, epochs=3, verbose=False)
        np.testing.assert_allclose(ff_pipe.get_parameter("ffn1_2"),
                                   ff_ref.get_parameter("ffn1_2"),
                                   rtol=2e-3, atol=2e-4)

    @pytest.mark.slow
    def test_search_picks_pipe_and_executes(self):
        """Deep-narrow transformer on the 8-device mesh: the search must
        DISCOVER a pipe>1 mesh and the compiled model must train."""
        rs = np.random.RandomState(0)
        x = rs.randn(16, 32, 64).astype(np.float32)
        y = rs.randn(16, 32, 1).astype(np.float32)
        ff = _build_transformer(
            _DEEP_NARROW,
            ff_kwargs=dict(search_budget=4, enable_parameter_parallel=True))
        axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
        assert axes.get("pipe", 1) > 1, f"search chose {axes}"
        from flexflow_tpu.parallel.pipeline_exec import PipelineGraphExecutor
        assert isinstance(ff.executor, PipelineGraphExecutor)
        l0 = ff.evaluate(x, y)["loss"]
        ff.fit(x, y, epochs=3, verbose=False)
        l1 = ff.evaluate(x, y)["loss"]
        assert np.isfinite(l1) and l1 < l0

    @pytest.mark.slow
    def test_checkpoint_roundtrip_with_stacked_body(self, tmp_path):
        rs = np.random.RandomState(0)
        x = rs.randn(16, 32, 64).astype(np.float32)
        y = rs.randn(16, 32, 1).astype(np.float32)
        ff = _build_transformer(
            _DEEP_NARROW, mesh=make_mesh(8, {"pipe": 2, "data": 4}),
            microbatches=4)
        ff.fit(x, y, epochs=1, verbose=False)
        w0 = ff.get_parameter("ffn1_3")
        path = str(tmp_path / "pipe_ck")
        ff.save_checkpoint(path)
        ff.fit(x, y, epochs=1, verbose=False)
        assert ff.load_checkpoint(path) == 1
        np.testing.assert_allclose(ff.get_parameter("ffn1_3"), w0,
                                   rtol=1e-6, atol=1e-7)


class TestPipelineSearchCostModel:
    """Native GPipe cost model (simulated v4-32, deviceless)."""

    @pytest.mark.slow
    def test_pipe_beats_dp_tp_on_deep_narrow(self):
        from flexflow_tpu.machine import MachineSpec
        from flexflow_tpu.search.native import available, native_optimize
        from flexflow_tpu.search.unity import (machine_to_json,
                                               serialize_graph)
        from flexflow_tpu.parallel.pipeline_detect import (
            detect_repeated_blocks, pipeline_meta_json)

        if not available():
            pytest.skip("native search unavailable")
        ff = _build_transformer(
            dict(num_layers=32, hidden_size=256, num_heads=8,
                 seq_length=128, batch_size=32),
            ff_kwargs=dict(only_data_parallel=True, workers_per_node=1),
            mesh=None)
        nodes = ff.executor.nodes
        pb = detect_repeated_blocks(nodes)
        assert pb is not None and pb.num_blocks == 32
        machine = machine_to_json(
            MachineSpec(chip="tpu-v4", chips_per_slice=32), 32)
        base = dict(budget=4, alpha=0.05, training=True, overlap=True,
                    batch=32, opt_state_factor=0.0, seed=42, rules=[])
        req = dict(nodes=serialize_graph(nodes), machine=machine,
                   measured={},
                   config=dict(base, enable_parameter_parallel=True),
                   pipeline=pipeline_meta_json(nodes, pb))
        r = native_optimize(req)
        assert r["mesh"].get("pipe", 1) > 1, r["mesh"]
        assert r.get("pipeline", {}).get("microbatches", 0) >= 1
        # must beat the best strategy the search finds WITHOUT pipe
        r2 = native_optimize(dict(
            req, config=dict(base, enable_parameter_parallel=True,
                             enable_pipeline_parallel=False)))
        assert r["predicted_time"] < r2["predicted_time"]

    def test_disable_flag_respected(self):
        rs = np.random.RandomState(0)
        ff = _build_transformer(
            _DEEP_NARROW,
            ff_kwargs=dict(search_budget=4, enable_parameter_parallel=True,
                           enable_pipeline_parallel=False))
        axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
        assert axes.get("pipe", 1) == 1

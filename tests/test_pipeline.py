"""Pipeline parallelism (SPMD GPipe over a 'pipe' mesh axis).

Exceeds the reference, where pipeline parallelism is an enum with no
runtime (ffconst.h:153 OP_PIPELINE). Numerics and gradients are checked
against the plain sequential execution of the same stages.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from flexflow_tpu.machine import make_mesh
from flexflow_tpu.parallel.pipeline import (pipeline_spmd, shard_stacked,
                                            stack_stage_params)

S, D = 4, 16


def stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def make_params(seed):
    rs = np.random.RandomState(seed)
    return [{"w": jnp.asarray(rs.randn(D, D).astype(np.float32) * 0.3),
             "b": jnp.asarray(rs.randn(D).astype(np.float32) * 0.1)}
            for _ in range(S)]


def sequential(per_stage, x):
    for p in per_stage:
        x = stage_fn(p, x)
    return x


class TestPipeline:
    def test_matches_sequential(self):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(0)
        stacked = shard_stacked(stack_stage_params(per_stage), mesh)
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.randn(16, D).astype(np.float32))
        want = sequential(per_stage, x)
        got = jax.jit(lambda p, x: pipeline_spmd(
            stage_fn, p, x, mesh, num_microbatches=4))(stacked, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    @pytest.mark.parametrize("microbatches", [1, 2, 8])
    def test_microbatch_counts(self, microbatches):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(2)
        stacked = shard_stacked(stack_stage_params(per_stage), mesh)
        x = jnp.asarray(np.random.RandomState(3).randn(16, D)
                        .astype(np.float32))
        want = sequential(per_stage, x)
        got = pipeline_spmd(stage_fn, stacked, x, mesh,
                            num_microbatches=microbatches)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    def test_gradients_flow_through_pipeline(self):
        # GPipe backward = autodiff through shard_map + ppermute: grads of
        # every stage's params must match the sequential model's
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(4)
        stacked = stack_stage_params(per_stage)
        stacked_dev = shard_stacked(stacked, mesh)
        rs = np.random.RandomState(5)
        x = jnp.asarray(rs.randn(8, D).astype(np.float32))
        y = jnp.asarray(rs.randn(8, D).astype(np.float32))

        def loss_pipe(p):
            out = pipeline_spmd(stage_fn, p, x, mesh, num_microbatches=2)
            return jnp.mean((out - y) ** 2)

        def loss_seq(stages):
            return jnp.mean((sequential(stages, x) - y) ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(stacked_dev)
        g_seq = jax.grad(loss_seq)(per_stage)
        for i in range(S):
            for k in ("w", "b"):
                np.testing.assert_allclose(
                    np.asarray(g_pipe[k][i]), np.asarray(g_seq[i][k]),
                    rtol=5e-4, atol=5e-6)

    def test_trains_end_to_end(self):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(6)
        params = shard_stacked(stack_stage_params(per_stage), mesh)
        rs = np.random.RandomState(7)
        x = jnp.asarray(rs.randn(16, D).astype(np.float32))
        y = jnp.asarray((rs.randn(16, D) * 0.1).astype(np.float32))

        @jax.jit
        def step(p):
            def loss(p):
                out = pipeline_spmd(stage_fn, p, x, mesh,
                                    num_microbatches=4)
                return jnp.mean((out - y) ** 2)

            l, g = jax.value_and_grad(loss)(p)
            return jax.tree.map(lambda w, gw: w - 0.3 * gw, p, g), l

        l0 = None
        for i in range(30):
            params, l = step(params)
            l0 = l0 if l0 is not None else float(l)
        assert float(l) < l0 * 0.5, (l0, float(l))

    def test_stage_count_mismatch_rejected(self):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        eight = make_params(8) + make_params(9)  # 8 stages vs pipe=4
        stacked = stack_stage_params(eight)
        x = jnp.ones((8, D), jnp.float32)
        with pytest.raises(ValueError, match="drop stages"):
            pipeline_spmd(stage_fn, stacked, x, mesh, num_microbatches=2)

    def test_composes_with_data_axis(self):
        # the data axis shards each microbatch (review finding: previously
        # both data replicas redundantly computed the full batch)
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(10)
        stacked = shard_stacked(stack_stage_params(per_stage), mesh)
        x = jnp.asarray(np.random.RandomState(11).randn(16, D)
                        .astype(np.float32))
        want = sequential(per_stage, x)
        got = pipeline_spmd(stage_fn, stacked, x, mesh, num_microbatches=4)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)
        # pipe-only mesh (no data axis) still works
        mesh1 = make_mesh(4, {"pipe": S})
        stacked1 = shard_stacked(stack_stage_params(per_stage), mesh1)
        got1 = pipeline_spmd(stage_fn, stacked1, x, mesh1,
                             num_microbatches=2)
        np.testing.assert_allclose(np.asarray(got1), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)


class TestPipelinedTransformer:
    """The pipeline carrying the framework's real ops: S pre-norm
    transformer blocks (MultiHeadAttention / LayerNorm / Linear) as the
    repeated stage."""

    def test_pipelined_transformer_matches_sequential(self):
        from flexflow_tpu.parallel.pipeline import transformer_block_stage

        S_, b, s, e = 4, 4, 8, 32
        mesh = make_mesh(8, {"pipe": S_, "data": 2})
        init_fn, stage = transformer_block_stage(
            embed_dim=e, num_heads=4, seq_length=s,
            batch_per_microbatch=b // 2, ffn_mult=2)
        rngs = jax.random.split(jax.random.PRNGKey(0), S_)
        per_stage = [init_fn(k) for k in rngs]
        stacked = shard_stacked(stack_stage_params(per_stage), mesh)
        x = jnp.asarray(np.random.RandomState(0).randn(b, s, e)
                        .astype(np.float32) * 0.3)
        want = x
        for p in per_stage:
            want = stage(p, want)
        got = jax.jit(lambda pp, xx: pipeline_spmd(
            stage, pp, xx, mesh, num_microbatches=2))(stacked, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_pipelined_transformer_trains(self):
        from flexflow_tpu.parallel.pipeline import transformer_block_stage

        S_, b, s, e = 4, 4, 8, 16
        mesh = make_mesh(8, {"pipe": S_, "data": 2})
        init_fn, stage = transformer_block_stage(
            embed_dim=e, num_heads=2, seq_length=s,
            batch_per_microbatch=b // 2, ffn_mult=2)
        per_stage = [init_fn(k) for k in
                     jax.random.split(jax.random.PRNGKey(1), S_)]
        params = shard_stacked(stack_stage_params(per_stage), mesh)
        rs = np.random.RandomState(2)
        x = jnp.asarray(rs.randn(b, s, e).astype(np.float32) * 0.3)
        y = jnp.asarray((rs.randn(b, s, e) * 0.1).astype(np.float32))

        @jax.jit
        def step(p):
            def loss(p):
                out = pipeline_spmd(stage, p, x, mesh, num_microbatches=2)
                return jnp.mean((out - y) ** 2)

            l, g = jax.value_and_grad(loss)(p)
            return jax.tree.map(lambda w, gw: w - 0.05 * gw, p, g), l

        l0 = None
        for _ in range(20):
            params, l = step(params)
            l0 = l0 if l0 is not None else float(l)
        assert float(l) < l0 * 0.8, (l0, float(l))

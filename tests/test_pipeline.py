"""Pipeline parallelism (SPMD GPipe/circular pipelines over a 'pipe'
mesh axis).

Exceeds the reference, where pipeline parallelism is an enum with no
runtime (ffconst.h:153 OP_PIPELINE). Numerics and gradients are checked
against the plain sequential execution of the same stages; the circular
schedule and the sharded microbatch queue are additionally checked
bit-for-bit against the GPipe/replicated-queue baseline.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from flexflow_tpu.machine import make_mesh
from flexflow_tpu.parallel.pipeline import (circular_block_order,
                                            pipeline_spmd, shard_stacked,
                                            stack_stage_params)

S, D = 4, 16


def stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def make_params(seed):
    rs = np.random.RandomState(seed)
    return [{"w": jnp.asarray(rs.randn(D, D).astype(np.float32) * 0.3),
             "b": jnp.asarray(rs.randn(D).astype(np.float32) * 0.1)}
            for _ in range(S)]


def sequential(per_stage, x):
    for p in per_stage:
        x = stage_fn(p, x)
    return x


class TestPipeline:
    def test_matches_sequential(self):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(0)
        stacked = shard_stacked(stack_stage_params(per_stage), mesh)
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.randn(16, D).astype(np.float32))
        want = sequential(per_stage, x)
        got = jax.jit(lambda p, x: pipeline_spmd(
            stage_fn, p, x, mesh, num_microbatches=4))(stacked, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    @pytest.mark.parametrize("microbatches", [1, 2, 8])
    def test_microbatch_counts(self, microbatches):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(2)
        stacked = shard_stacked(stack_stage_params(per_stage), mesh)
        x = jnp.asarray(np.random.RandomState(3).randn(16, D)
                        .astype(np.float32))
        want = sequential(per_stage, x)
        got = pipeline_spmd(stage_fn, stacked, x, mesh,
                            num_microbatches=microbatches)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    def test_gradients_flow_through_pipeline(self):
        # GPipe backward = autodiff through shard_map + ppermute: grads of
        # every stage's params must match the sequential model's
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(4)
        stacked = stack_stage_params(per_stage)
        stacked_dev = shard_stacked(stacked, mesh)
        rs = np.random.RandomState(5)
        x = jnp.asarray(rs.randn(8, D).astype(np.float32))
        y = jnp.asarray(rs.randn(8, D).astype(np.float32))

        def loss_pipe(p):
            out = pipeline_spmd(stage_fn, p, x, mesh, num_microbatches=2)
            return jnp.mean((out - y) ** 2)

        def loss_seq(stages):
            return jnp.mean((sequential(stages, x) - y) ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(stacked_dev)
        g_seq = jax.grad(loss_seq)(per_stage)
        for i in range(S):
            for k in ("w", "b"):
                np.testing.assert_allclose(
                    np.asarray(g_pipe[k][i]), np.asarray(g_seq[i][k]),
                    rtol=5e-4, atol=5e-6)

    def test_trains_end_to_end(self):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(6)
        params = shard_stacked(stack_stage_params(per_stage), mesh)
        rs = np.random.RandomState(7)
        x = jnp.asarray(rs.randn(16, D).astype(np.float32))
        y = jnp.asarray((rs.randn(16, D) * 0.1).astype(np.float32))

        @jax.jit
        def step(p):
            def loss(p):
                out = pipeline_spmd(stage_fn, p, x, mesh,
                                    num_microbatches=4)
                return jnp.mean((out - y) ** 2)

            l, g = jax.value_and_grad(loss)(p)
            return jax.tree.map(lambda w, gw: w - 0.3 * gw, p, g), l

        l0 = None
        for i in range(30):
            params, l = step(params)
            l0 = l0 if l0 is not None else float(l)
        assert float(l) < l0 * 0.5, (l0, float(l))

    def test_stage_count_mismatch_rejected(self):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        eight = make_params(8) + make_params(9)  # 8 stages vs pipe=4
        stacked = stack_stage_params(eight)
        x = jnp.ones((8, D), jnp.float32)
        with pytest.raises(ValueError, match="drop stages"):
            pipeline_spmd(stage_fn, stacked, x, mesh, num_microbatches=2)

    def test_composes_with_data_axis(self):
        # the data axis shards each microbatch (review finding: previously
        # both data replicas redundantly computed the full batch)
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = make_params(10)
        stacked = shard_stacked(stack_stage_params(per_stage), mesh)
        x = jnp.asarray(np.random.RandomState(11).randn(16, D)
                        .astype(np.float32))
        want = sequential(per_stage, x)
        got = pipeline_spmd(stage_fn, stacked, x, mesh, num_microbatches=4)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)
        # pipe-only mesh (no data axis) still works
        mesh1 = make_mesh(4, {"pipe": S})
        stacked1 = shard_stacked(stack_stage_params(per_stage), mesh1)
        got1 = pipeline_spmd(stage_fn, stacked1, x, mesh1,
                             num_microbatches=2)
        np.testing.assert_allclose(np.asarray(got1), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)


class TestPipelinedTransformer:
    """The pipeline carrying the framework's real ops: S pre-norm
    transformer blocks (MultiHeadAttention / LayerNorm / Linear) as the
    repeated stage."""

    def test_pipelined_transformer_matches_sequential(self):
        from flexflow_tpu.parallel.pipeline import transformer_block_stage

        S_, b, s, e = 4, 4, 8, 32
        mesh = make_mesh(8, {"pipe": S_, "data": 2})
        init_fn, stage = transformer_block_stage(
            embed_dim=e, num_heads=4, seq_length=s,
            batch_per_microbatch=b // 2, ffn_mult=2)
        rngs = jax.random.split(jax.random.PRNGKey(0), S_)
        per_stage = [init_fn(k) for k in rngs]
        stacked = shard_stacked(stack_stage_params(per_stage), mesh)
        x = jnp.asarray(np.random.RandomState(0).randn(b, s, e)
                        .astype(np.float32) * 0.3)
        want = x
        for p in per_stage:
            want = stage(p, want)
        got = jax.jit(lambda pp, xx: pipeline_spmd(
            stage, pp, xx, mesh, num_microbatches=2))(stacked, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_pipelined_transformer_trains(self):
        from flexflow_tpu.parallel.pipeline import transformer_block_stage

        S_, b, s, e = 4, 4, 8, 16
        mesh = make_mesh(8, {"pipe": S_, "data": 2})
        init_fn, stage = transformer_block_stage(
            embed_dim=e, num_heads=2, seq_length=s,
            batch_per_microbatch=b // 2, ffn_mult=2)
        per_stage = [init_fn(k) for k in
                     jax.random.split(jax.random.PRNGKey(1), S_)]
        params = shard_stacked(stack_stage_params(per_stage), mesh)
        rs = np.random.RandomState(2)
        x = jnp.asarray(rs.randn(b, s, e).astype(np.float32) * 0.3)
        y = jnp.asarray((rs.randn(b, s, e) * 0.1).astype(np.float32))

        @jax.jit
        def step(p):
            def loss(p):
                out = pipeline_spmd(stage, p, x, mesh, num_microbatches=2)
                return jnp.mean((out - y) ** 2)

            l, g = jax.value_and_grad(loss)(p)
            return jax.tree.map(lambda w, gw: w - 0.05 * gw, p, g), l

        l0 = None
        for _ in range(20):
            params, l = step(params)
            l0 = l0 if l0 is not None else float(l)
        assert float(l) < l0 * 0.8, (l0, float(l))


def _build_transformer(cfg_kwargs, ff_kwargs=None, mesh=None, lr=0.001,
                       microbatches=0, **compile_kw):
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType
    from flexflow_tpu.models.transformer import (TransformerConfig,
                                                 create_transformer)
    from flexflow_tpu.optimizers import SGDOptimizer

    cfg = TransformerConfig(**cfg_kwargs)
    c = FFConfig(batch_size=cfg.batch_size, seed=7, **(ff_kwargs or {}))
    c.pipeline_microbatches = microbatches
    ff = create_transformer(cfg, c)
    ff.compile(SGDOptimizer(lr=lr), LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               [], mesh=mesh, **compile_kw)
    return ff


_DEEP_NARROW = dict(num_layers=8, hidden_size=64, num_heads=4,
                    seq_length=32, batch_size=16)


class TestPipelineDetection:
    def test_transformer_blocks(self):
        # detection only walks the node graph — half the _DEEP_NARROW
        # depth keeps the compile cheap without changing what is tested
        ff = _build_transformer(dict(_DEEP_NARROW, num_layers=4),
                                mesh=make_mesh(1, {"data": 1}))
        from flexflow_tpu.parallel.pipeline_detect import (
            detect_repeated_blocks)
        pb = detect_repeated_blocks(ff.executor.nodes)
        assert pb is not None
        assert pb.num_blocks == 4
        assert pb.body_in == ("input", "input")
        # tail = the classification head dense
        assert [ff.executor.nodes[i].op.name for i in pb.tail] == ["head"]

    def test_non_repeated_graph_returns_none(self):
        from flexflow_tpu import FFConfig, FFModel, LossType
        from flexflow_tpu.parallel.pipeline_detect import (
            detect_repeated_blocks)

        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 16))
        t = ff.dense(t, 32)
        t = ff.dense(t, 4)  # different shapes: not repeated blocks
        ff.compile(loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
        assert detect_repeated_blocks(ff.executor.nodes) is None


class TestPipelineLowering:
    """FFModel.compile lowers a 'pipe' mesh onto PipelineGraphExecutor
    (VERDICT r3 Next #1: pipeline as a framework capability, not a
    library demo)."""

    @pytest.mark.slow
    def test_explicit_pipe_mesh_matches_single_device(self):
        from flexflow_tpu.parallel.pipeline_exec import (
            BODY_KEY, PipelineGraphExecutor)

        rs = np.random.RandomState(0)
        x = rs.randn(16, 32, 64).astype(np.float32)
        y = rs.randn(16, 32, 1).astype(np.float32)
        ff_pipe = _build_transformer(
            _DEEP_NARROW, mesh=make_mesh(8, {"pipe": 4, "data": 2}),
            microbatches=4)
        assert isinstance(ff_pipe.executor, PipelineGraphExecutor)
        # body params stacked [R, ...] and sharded over the pipe axis
        leaf = ff_pipe.params[BODY_KEY]["op4"]["kernel"]
        assert leaf.shape[0] == 8
        assert "pipe" in jax.tree.leaves(leaf.sharding.spec)[0:1][0] or \
            leaf.sharding.spec[0] == "pipe"
        ff_ref = _build_transformer(_DEEP_NARROW,
                                    mesh=make_mesh(1, {"data": 1}))
        for lname, sub in ff_ref.params.items():
            for pname in sub:
                ff_pipe.set_parameter(lname,
                                      ff_ref.get_parameter(lname, pname),
                                      pname)
        np.testing.assert_allclose(ff_pipe.predict(x), ff_ref.predict(x),
                                   rtol=1e-5, atol=1e-5)
        for ff in (ff_pipe, ff_ref):
            ff.fit(x, y, epochs=3, verbose=False)
        np.testing.assert_allclose(ff_pipe.get_parameter("ffn1_2"),
                                   ff_ref.get_parameter("ffn1_2"),
                                   rtol=2e-3, atol=2e-4)

    @pytest.mark.slow
    def test_search_picks_pipe_and_executes(self):
        """Deep-narrow transformer on the 8-device mesh: the search must
        DISCOVER a pipe>1 mesh and the compiled model must train."""
        rs = np.random.RandomState(0)
        x = rs.randn(16, 32, 64).astype(np.float32)
        y = rs.randn(16, 32, 1).astype(np.float32)
        # lr 1e-3 diverges on this random-data fixture (pre-existing:
        # also at the PR-4 seed) — 3e-4 trains monotonically
        ff = _build_transformer(
            _DEEP_NARROW, lr=3e-4,
            ff_kwargs=dict(search_budget=4, enable_parameter_parallel=True))
        axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
        assert axes.get("pipe", 1) > 1, f"search chose {axes}"
        # the searched pipeline records its microbatch count + schedule
        pinfo = (ff.search_info or {}).get("pipeline") or {}
        assert pinfo.get("microbatches", 0) >= 1
        assert pinfo.get("schedule") in ("gpipe", "circular")
        assert ff.executor.schedule == pinfo["schedule"]
        assert ff.executor.microbatches == pinfo["microbatches"]
        from flexflow_tpu.parallel.pipeline_exec import PipelineGraphExecutor
        assert isinstance(ff.executor, PipelineGraphExecutor)
        l0 = ff.evaluate(x, y)["loss"]
        ff.fit(x, y, epochs=3, verbose=False)
        l1 = ff.evaluate(x, y)["loss"]
        assert np.isfinite(l1) and l1 < l0

    @pytest.mark.slow
    def test_checkpoint_roundtrip_with_stacked_body(self, tmp_path):
        rs = np.random.RandomState(0)
        x = rs.randn(16, 32, 64).astype(np.float32)
        y = rs.randn(16, 32, 1).astype(np.float32)
        ff = _build_transformer(
            _DEEP_NARROW, mesh=make_mesh(8, {"pipe": 2, "data": 4}),
            microbatches=4)
        ff.fit(x, y, epochs=1, verbose=False)
        w0 = ff.get_parameter("ffn1_3")
        path = str(tmp_path / "pipe_ck")
        ff.save_checkpoint(path)
        ff.fit(x, y, epochs=1, verbose=False)
        assert ff.load_checkpoint(path) == 1
        np.testing.assert_allclose(ff.get_parameter("ffn1_3"), w0,
                                   rtol=1e-6, atol=1e-7)


class TestPipelineSearchCostModel:
    """Native GPipe cost model (simulated v4-32, deviceless)."""

    @pytest.mark.slow
    def test_pipe_beats_dp_tp_on_deep_narrow(self):
        from flexflow_tpu.machine import MachineSpec
        from flexflow_tpu.search.native import available, native_optimize
        from flexflow_tpu.search.unity import (machine_to_json,
                                               serialize_graph)
        from flexflow_tpu.parallel.pipeline_detect import (
            detect_repeated_blocks, pipeline_meta_json)

        if not available():
            pytest.skip("native search unavailable")
        ff = _build_transformer(
            dict(num_layers=32, hidden_size=256, num_heads=8,
                 seq_length=128, batch_size=32),
            ff_kwargs=dict(only_data_parallel=True, workers_per_node=1),
            mesh=None)
        nodes = ff.executor.nodes
        pb = detect_repeated_blocks(nodes)
        assert pb is not None and pb.num_blocks == 32
        machine = machine_to_json(
            MachineSpec(chip="tpu-v4", chips_per_slice=32), 32)
        base = dict(budget=4, alpha=0.05, training=True, overlap=True,
                    batch=32, opt_state_factor=0.0, seed=42, rules=[])
        req = dict(nodes=serialize_graph(nodes), machine=machine,
                   measured={},
                   config=dict(base, enable_parameter_parallel=True),
                   pipeline=pipeline_meta_json(nodes, pb))
        r = native_optimize(req)
        assert r["mesh"].get("pipe", 1) > 1, r["mesh"]
        assert r.get("pipeline", {}).get("microbatches", 0) >= 1
        # the schedule is searched alongside M (gpipe vs circular priced)
        assert r["pipeline"].get("schedule") in ("gpipe", "circular")
        # must beat the best strategy the search finds WITHOUT pipe
        r2 = native_optimize(dict(
            req, config=dict(base, enable_parameter_parallel=True,
                             enable_pipeline_parallel=False)))
        assert r["predicted_time"] < r2["predicted_time"]

    def test_disable_flag_respected(self):
        rs = np.random.RandomState(0)
        # the flag gate is depth-independent — 4 layers compile ~2x
        # faster than the full _DEEP_NARROW and still offer pipe splits
        ff = _build_transformer(
            dict(_DEEP_NARROW, num_layers=4),
            ff_kwargs=dict(search_budget=4, enable_parameter_parallel=True,
                           enable_pipeline_parallel=False))
        axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
        assert axes.get("pipe", 1) == 1


# ---------------------------------------------------------------------------
# circular schedule + sharded microbatch queue (pipeline overhaul, ISSUE 5)


R8 = 2 * S  # 8 blocks over 4 stages: k = 2 rounds per microbatch


def _make_blocks(seed, n=R8):
    rs = np.random.RandomState(seed)
    return [{"w": jnp.asarray(rs.randn(D, D).astype(np.float32) * 0.3),
             "b": jnp.asarray(rs.randn(D).astype(np.float32) * 0.1)}
            for _ in range(n)]


def _block_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _seq_blocks(blocks, x):
    for p in blocks:
        x = _block_fn(p, x)
    return x


class TestCircularSchedule:
    """stage s holds blocks s, s+S, ... and runs one block per tick; a
    microbatch circulates the ring k times (bubble (S-1)/(kM+S-1))."""

    def _stacked(self, blocks, mesh):
        order = circular_block_order(len(blocks), S)
        return shard_stacked(stack_stage_params(blocks, order=order), mesh)

    @pytest.mark.parametrize("shard_queue", [False, True])
    @pytest.mark.parametrize("microbatches", [4, 8])
    def test_matches_sequential_bitwise(self, shard_queue, microbatches):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        blocks = _make_blocks(0)
        stacked = self._stacked(blocks, mesh)
        x = jnp.asarray(np.random.RandomState(1).randn(16, D)
                        .astype(np.float32))
        want = _seq_blocks(blocks, x)
        got = pipeline_spmd(_block_fn, stacked, x, mesh,
                            num_microbatches=microbatches,
                            stage_leading_dim=True, schedule="circular",
                            shard_queue=shard_queue)
        # same per-microbatch computation graph, scheduled differently:
        # f32 results are bit-identical, not merely close
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_gradients_match_sequential(self):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        blocks = _make_blocks(2)
        order = circular_block_order(R8, S)
        stacked = self._stacked(blocks, mesh)
        rs = np.random.RandomState(3)
        x = jnp.asarray(rs.randn(16, D).astype(np.float32))
        y = jnp.asarray(rs.randn(16, D).astype(np.float32))

        def loss_pipe(p):
            out = pipeline_spmd(_block_fn, p, x, mesh, num_microbatches=4,
                                stage_leading_dim=True, schedule="circular",
                                shard_queue=True)
            return jnp.mean((out - y) ** 2)

        def loss_seq(bl):
            return jnp.mean((_seq_blocks(bl, x) - y) ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
        g_seq = jax.grad(loss_seq)(blocks)
        for row, b in enumerate(order):
            for k in ("w", "b"):
                np.testing.assert_allclose(
                    np.asarray(g_pipe[k][row]), np.asarray(g_seq[b][k]),
                    rtol=5e-4, atol=5e-6)

    def test_rejects_too_few_microbatches(self):
        # a returning microbatch would overtake the recirculation buffer
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        stacked = self._stacked(_make_blocks(4), mesh)
        x = jnp.ones((16, D), jnp.float32)
        with pytest.raises(ValueError, match="microbatches >= stages"):
            pipeline_spmd(_block_fn, stacked, x, mesh, num_microbatches=2,
                          stage_leading_dim=True, schedule="circular")


class TestShardedQueue:
    """queue + output buffer sharded over the pipe axis; results must be
    bit-identical to the replicated-queue lowering."""

    def test_bitwise_matches_replicated(self):
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = _make_blocks(5, n=S)
        stacked = shard_stacked(stack_stage_params(per_stage), mesh)
        x = jnp.asarray(np.random.RandomState(6).randn(16, D)
                        .astype(np.float32))
        outs = {}
        for sq in (False, True):
            outs[sq] = np.asarray(pipeline_spmd(
                _block_fn, stacked, x, mesh, num_microbatches=8,
                shard_queue=sq))
        np.testing.assert_array_equal(outs[False], outs[True])

    def test_indivisible_microbatches_fall_back(self):
        # M=2 does not divide over 4 stages: the replicated queue runs
        mesh = make_mesh(8, {"pipe": S, "data": 2})
        per_stage = _make_blocks(7, n=S)
        stacked = shard_stacked(stack_stage_params(per_stage), mesh)
        x = jnp.asarray(np.random.RandomState(8).randn(16, D)
                        .astype(np.float32))
        want = _seq_blocks(per_stage, x)
        got = pipeline_spmd(_block_fn, stacked, x, mesh, num_microbatches=2,
                            shard_queue=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)


_PIPE_TINY = dict(num_layers=4, hidden_size=32, num_heads=2,
                  seq_length=8, batch_size=16)

_parity_cache = {}


def _pipe_variant(tag):
    """Compiled tiny transformer (Adam) + its 3-step seeded f32 loss
    trajectory, cached per variant (several tests share the builds)."""
    if tag in _parity_cache:
        return _parity_cache[tag]
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType
    from flexflow_tpu.models.transformer import (TransformerConfig,
                                                 create_transformer)
    from flexflow_tpu.optimizers import AdamOptimizer
    variants = {
        "single": dict(mesh_axes={"data": 1}),
        "gpipe_repl": dict(mesh_axes={"pipe": 2, "data": 2},
                           ff_kwargs=dict(pipeline_schedule="gpipe",
                                          pipeline_shard_queue=False)),
        "circ_shard": dict(mesh_axes={"pipe": 2, "data": 2},
                           ff_kwargs=dict(pipeline_schedule="circular")),
        "circ_wus": dict(mesh_axes={"pipe": 2, "data": 2},
                         ff_kwargs=dict(pipeline_schedule="circular",
                                        weight_update_sharding="on")),
    }
    kw = variants[tag]
    mesh_axes = kw["mesh_axes"]
    cfg = TransformerConfig(**_PIPE_TINY)
    c = FFConfig(batch_size=cfg.batch_size, seed=7, **(kw.get("ff_kwargs")
                                                       or {}))
    if "pipe" in mesh_axes:
        c.pipeline_microbatches = 4
    ff = create_transformer(cfg, c)
    ff.compile(AdamOptimizer(alpha=1e-2),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
               mesh=make_mesh(int(np.prod(list(mesh_axes.values()))),
                              mesh_axes))
    if tag == "single":
        # snapshot the pristine init weights BEFORE training: the pipe
        # variants start from these (their executor consumes the init
        # rng in a different order, so trajectories would not compare)
        _parity_cache["__init_weights__"] = {
            lname: {pname: ff.get_parameter(lname, pname)
                    for pname in sub}
            for lname, sub in ff.params.items()}
    else:
        _pipe_variant("single")
        for lname, sub in _parity_cache["__init_weights__"].items():
            for pname, w in sub.items():
                ff.set_parameter(lname, w, pname)
    rs = np.random.RandomState(0)
    x = rs.randn(16, 8, 32).astype(np.float32)
    y = rs.randn(16, 8, 1).astype(np.float32)
    losses = []
    for _ in range(3):
        ff.set_batch(x, y)
        ff.forward(); ff.backward(); ff.update()
        losses.append(np.float32(ff._last_loss))
    _parity_cache[tag] = (ff, losses)
    return _parity_cache[tag]


class TestPipelineSchedulesEndToEnd:
    """FFModel-level seeded f32 training parity on the pp=2 host-device
    mesh (acceptance: circular + sharded-queue == GPipe baseline)."""

    # only tier-1 user of the gpipe_repl build (~18s of the 37s leg);
    # TestCircularSchedule asserts circular+sharded bitwise parity at
    # the functional layer, pp_x_dp keeps the FFModel-level leg cheap
    @pytest.mark.slow
    def test_circular_sharded_matches_gpipe_replicated(self):
        _, base = _pipe_variant("gpipe_repl")
        ff, circ = _pipe_variant("circ_shard")
        from flexflow_tpu.parallel.pipeline_exec import PipelineGraphExecutor
        assert isinstance(ff.executor, PipelineGraphExecutor)
        assert ff.executor.schedule == "circular"
        assert ff.executor.shard_queue
        for a, b in zip(base, circ):
            # bit-for-bit: same per-microbatch math, different schedule
            assert a.tobytes() == b.tobytes(), (base, circ)

    @pytest.mark.slow
    def test_pp_x_dp_matches_single_device(self):
        """pp=2 x dp=2 *training* composition vs single-device f32 (the
        previously-untested leg: forward parity and pp-only training were
        covered, pp x dp training was not). Slow tier (t1 budget,
        with test_loss_parity_vs_plain_sync — together they retire the
        circ_shard build from tier-1): functional-layer bitwise parity
        (TestCircularSchedule) and the circ_wus trajectory checks keep
        the pp x dp path covered."""
        _, single = _pipe_variant("single")
        _, pipe = _pipe_variant("circ_shard")
        assert all(np.isfinite(v) for v in pipe)
        np.testing.assert_allclose(np.asarray(pipe), np.asarray(single),
                                   rtol=1e-5, atol=1e-6)


class TestPipelineWUS:
    """Weight-update sharding at pp > 1 (previously the lowering kept
    plain sync): reduce-scatter body-grad sync composing with the
    pipe-stacked leading dim, sharded f32 master + moments, all-gather
    inside the optimizer fusion — the tests/test_wus.py invariants."""

    def test_master_and_moments_shard_pipe_x_data(self):
        from flexflow_tpu.parallel.pipeline_exec import BODY_KEY
        ff, losses = _pipe_variant("circ_wus")
        assert ff.executor.weight_update_sharding
        assert all(np.isfinite(v) for v in losses)
        sharded = 0
        for key, sub in ff.opt_state["m"][BODY_KEY].items():
            for pname, arr in sub.items():
                spec = arr.sharding.spec
                assert spec and spec[0] == "pipe", (key, pname, spec)
                if "data" in tuple(spec):
                    sharded += 1
        assert sharded > 0  # data axis actually landed on the moments

    @pytest.mark.slow
    def test_loss_parity_vs_plain_sync(self):
        # slow tier (t1 budget): retires the circ_shard build from
        # tier-1; WUS-vs-sync bitwise parity stays tier-1 in
        # tests/test_wus.py on the 8-way data mesh
        _, plain = _pipe_variant("circ_shard")
        _, wus = _pipe_variant("circ_wus")
        np.testing.assert_allclose(np.asarray(wus), np.asarray(plain),
                                   rtol=1e-6)

    def test_wus_specs_pass_fflint(self):
        from flexflow_tpu.analysis import LintContext, run_passes
        from flexflow_tpu.analysis.passes.sharding import (
            ShardingLegalityPass)
        ff, _ = _pipe_variant("circ_wus")
        specs = ff.executor.wus_param_specs()
        assert specs, "WUS sharded no body leaves"
        ctx = LintContext(nodes=ff.executor.nodes, mesh=ff.mesh,
                          strategy=ff.strategy, ff=ff)
        rep = run_passes(ctx, [ShardingLegalityPass()])
        assert not rep.errors, [d.format() for d in rep.errors]


class TestPipelineFflintClean:
    """Acceptance: the pipelined (WUS) strategy's collective census is
    priced — the collective-inference pass replays pipe strategies
    through simulate_pipeline and reports no FFL2xx errors."""

    def test_pipelined_wus_census_is_priced(self):
        from flexflow_tpu.analysis import LintContext, run_passes
        from flexflow_tpu.analysis.passes.collectives import (
            CollectiveInferencePass, infer_strategy_collectives)
        from flexflow_tpu.search.native import available
        ff, _ = _pipe_variant("circ_wus")
        ctx = LintContext(nodes=ff.executor.nodes, mesh=ff.mesh,
                          strategy=ff.strategy, ff=ff)
        inferred = infer_strategy_collectives(ctx)
        assert "ppermute" in inferred, inferred  # the pipeline hop
        if ff.executor.weight_update_sharding:
            assert "allgather" in inferred, inferred  # the WUS gather
        if not available():
            pytest.skip("native search unavailable")
        rep = run_passes(ctx, [CollectiveInferencePass()])
        assert rep.passes["collective-inference"] == "ok", rep.passes
        bad = [d for d in rep.errors if d.rule.startswith("FFL2")]
        assert not bad, "\n".join(d.format() for d in bad)


@pytest.mark.slow
class TestShardedQueueMemory:
    """Acceptance: compiled HBM peak (XLA memory_analysis) of the
    pipelined transformer fixture drops >= 25% with the sharded
    microbatch queue at pp=4 vs the replicated-queue baseline. Measured
    on the forward executable — the queue/output buffers are the
    pipeline's persistent activation memory; the training peak is
    dominated by saved-for-backward residuals the queue layout does not
    touch (it still must not regress). Marked slow (two full compiles);
    the tier-1 proxy is the native memory model's sharded-vs-replicated
    assertion plus the bench hbm_peak_bytes ratchet."""

    @staticmethod
    def _build(shard_queue):
        from flexflow_tpu.config import FFConfig
        from flexflow_tpu.ffconst import LossType
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     create_transformer)
        from flexflow_tpu.optimizers import AdamOptimizer
        cfg = TransformerConfig(num_layers=4, hidden_size=64, num_heads=2,
                                seq_length=32, batch_size=128)
        c = FFConfig(batch_size=128, seed=7)
        c.pipeline_shard_queue = shard_queue
        c.pipeline_microbatches = 8
        ff = create_transformer(cfg, c)
        ff.compile(AdamOptimizer(alpha=1e-3),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
                   mesh=make_mesh(4, {"pipe": 4}))
        return ff

    def test_forward_hbm_peak_drops_25pct_at_pp4(self):
        peaks = {}
        for sq in (False, True):
            ff = self._build(sq)
            rs = np.random.RandomState(0)
            x = ff._stage_inputs([rs.randn(128, 32, 64).astype(np.float32)])
            fwd = ff.executor.make_forward(training=False)
            ma = fwd.lower(ff.params, ff.state, x,
                           jax.random.PRNGKey(0)).compile().memory_analysis()
            peaks[sq] = (ma.argument_size_in_bytes
                         + ma.temp_size_in_bytes)
        assert peaks[True] <= 0.75 * peaks[False], peaks


class TestPipelineNativePricing:
    """Acceptance: ffs_simulate prices gpipe vs circular and M=2S vs
    larger M distinctly, and the `_wus` choice twins exist at pp > 1."""

    B, DIM = 128, 512

    def _chain(self):
        nodes = []
        for i in range(1, 5):
            nodes.append({
                "guid": i, "type": "LINEAR", "name": f"l{i}",
                "inputs": [[i - 1 if i > 1 else -1, 0]],
                "input_shapes": [[self.B, self.DIM]],
                "output_shapes": [[self.B, self.DIM]],
                "roles": [["sample", "channel"]],
                "params": {"kernel": [self.DIM, self.DIM],
                           "bias": [self.DIM]},
                "flops": 2.0 * self.B * self.DIM * self.DIM,
                "dtype_size": 4, "attrs": {},
            })
        return nodes

    def _simulate(self, choice, M, schedule, shard_queue=True):
        from flexflow_tpu.search.native import native_simulate
        machine = {"num_devices": 4, "flops": 197e12, "hbm_bw": 0.82e12,
                   "hbm_cap": 16e9, "ici_bw": 45e9, "ici_latency": 1e-6,
                   "dcn_bw": 25e9, "dcn_latency": 1e-5, "num_slices": 1}
        meta = dict(num_blocks=4, body=[1, 2, 3, 4], head=[], tail=[],
                    block_out_bytes=self.B * self.DIM * 4.0, batch=self.B,
                    microbatches=M, schedule=schedule,
                    shard_queue=shard_queue)
        return native_simulate({
            "nodes": self._chain(), "machine": machine, "measured": {},
            "config": {"training": True, "overlap": True,
                       "opt_state_factor": 2.0},
            "mesh": {"data": 2, "model": 1, "seq": 1, "expert": 1,
                     "pipe": 2},
            "pipeline": meta,
            "assignment": {str(i): choice for i in range(1, 5)}})

    def test_schedule_and_microbatches_priced_distinctly(self):
        from flexflow_tpu.search.native import available
        if not available():
            pytest.skip("native search unavailable")
        times = {}
        for sched in ("gpipe", "circular"):
            for M in (4, 8, 16):
                times[(sched, M)] = \
                    self._simulate("dp", M, sched)["iteration_time"]
        assert len(set(times.values())) == len(times), times
        # the bubble term: more microbatches shrink the gpipe bubble's
        # share, and circular runs kM+S-1 ticks of 1/k-sized stages
        assert times[("gpipe", 4)] != times[("circular", 4)]

    def test_wus_twins_enumerated_and_priced_at_pp(self):
        from flexflow_tpu.search.native import available
        if not available():
            pytest.skip("native search unavailable")
        r_dp = self._simulate("dp", 4, "gpipe")
        r_wus = self._simulate("dp_wus", 4, "gpipe")
        kinds = {t["collective"] for t in r_wus["tasks"]
                 if t.get("collective")}
        assert {"allreduce", "allgather", "ppermute"} <= kinds, kinds
        kinds_dp = {t["collective"] for t in r_dp["tasks"]
                    if t.get("collective")}
        assert "allgather" not in kinds_dp
        # sharded optimizer state: the twin's memory is strictly lower
        assert r_wus["memory"] < r_dp["memory"]

    def test_sharded_vs_replicated_queue_memory(self):
        from flexflow_tpu.search.native import available
        if not available():
            pytest.skip("native search unavailable")
        shard = self._simulate("dp", 4, "gpipe", shard_queue=True)
        repl = self._simulate("dp", 4, "gpipe", shard_queue=False)
        assert shard["memory"] < repl["memory"]

    def test_circular_recirc_window_hbm_drop(self):
        """Acceptance: the k>1 circular schedule's stage-0
        recirculation buffer is windowed to the M-S+1 in-flight slots
        in BOTH queue lowerings (a value banked at global step u is
        consumed exactly M ticks later, so only M-S+1 slots are ever
        live) — the replicated-queue fallback no longer pays the
        full-M-slot ring (ISSUE 20 satellite: the last pipeline memory
        gap). The circular-over-gpipe memory premium is therefore
        exactly block_out/dp * (M-S+1)/M regardless of queue sharding —
        a drop of block_out/dp * (S-1)/M on the replicated path vs the
        unwindowed model."""
        from flexflow_tpu.search.native import available
        if not available():
            pytest.skip("native search unavailable")
        M, dp, pp = 8, 2, 2  # num_blocks=4 -> k=2 rounds: recirc live
        mems = {(sched, sq): self._simulate(
                    "dp", M, sched, shard_queue=sq)["memory"]
                for sched in ("gpipe", "circular") for sq in (True, False)}
        recirc = self.B * self.DIM * 4.0 / dp * (M - pp + 1) / M
        for sq in (True, False):
            premium = mems[("circular", sq)] - mems[("gpipe", sq)]
            assert premium == pytest.approx(recirc, rel=1e-9), mems
        # queue sharding still saves the same bytes under either
        # schedule (the recirc window itself is schedule-only now)
        circ_gap = mems[("circular", False)] - mems[("circular", True)]
        gpipe_gap = mems[("gpipe", False)] - mems[("gpipe", True)]
        assert circ_gap == pytest.approx(gpipe_gap, rel=1e-9)
        assert gpipe_gap > 0.0, mems

    def test_searched_pipe_strategy_picks_wus_twins(self):
        """Acceptance: the searched pipeline strategy at pp > 1
        enumerates the `_wus` twins — a memory-capped search on a deep
        param-heavy chain lands on a pipe x data mesh with every body
        op's choice the reduce-scatter twin, plus a searched microbatch
        count and schedule."""
        from flexflow_tpu.search.native import available, native_optimize
        if not available():
            pytest.skip("native search unavailable")
        b, d, R = 4096, 2048, 4
        nodes = []
        for i in range(1, R + 1):
            nodes.append({
                "guid": i, "type": "LINEAR", "name": f"l{i}",
                "inputs": [[i - 1 if i > 1 else -1, 0]],
                "input_shapes": [[b, d]], "output_shapes": [[b, d]],
                "roles": [["sample", "channel"]],
                "params": {"kernel": [d, d], "bias": [d]},
                "flops": 2.0 * b * d * d, "dtype_size": 4, "attrs": {},
            })
        machine = {"num_devices": 8, "flops": 197e12, "hbm_bw": 0.82e12,
                   "hbm_cap": 9e7,  # dp=8 (even with WUS) does not fit
                   "ici_bw": 45e9, "ici_latency": 1e-6,
                   "dcn_bw": 25e9, "dcn_latency": 1e-5, "num_slices": 1}
        meta = dict(num_blocks=R, body=list(range(1, R + 1)), head=[],
                    tail=[], block_out_bytes=b * d * 4.0, batch=b)
        r = native_optimize(dict(
            nodes=nodes, machine=machine, measured={},
            config=dict(budget=2, alpha=0.05, training=True, overlap=True,
                        batch=b, opt_state_factor=2.0, seed=42, rules=[],
                        enable_parameter_parallel=False,
                        enable_substitution=False),
            pipeline=meta))
        mesh = r["mesh"]
        assert mesh.get("pipe", 1) > 1 and mesh.get("data", 1) > 1, mesh
        choices = {v["choice"] for v in r["ops"].values()}
        # the memory-capped search must keep picking the WUS dimension;
        # since ISSUE 9 the latency-hiding "_ovl" twin of a "_wus" choice
        # (dp_wus_ovl) also satisfies it — suffix order is base[_wus][_ovl]
        assert all("_wus" in c for c in choices), choices
        pj = r.get("pipeline") or {}
        assert pj.get("microbatches", 0) >= 2 * mesh["pipe"]
        assert pj.get("schedule") in ("gpipe", "circular"), pj

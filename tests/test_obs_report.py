"""Golden-file tests for scripts/obs_report.py (ISSUE 8 satellite).

The report renderer previously had no direct tests — it was only
exercised incidentally through the devtrace acceptance fit. These tests
render a COMMITTED fixture trace dir (tests/fixtures/obs_report_dir,
one run stem carrying every artifact kind the renderer consumes) and
assert the run row, the devtrace block, the drift table, the simulated
-vs-measured join, and the empty-dir exit-0 path.
"""

from __future__ import annotations

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "obs_report_dir")


@pytest.fixture(scope="module")
def mod():
    spec = importlib.util.spec_from_file_location(
        "obs_report_golden", os.path.join(REPO, "scripts",
                                          "obs_report.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


@pytest.fixture(scope="module")
def report(mod):
    return mod.build_report(FIXTURE)


class TestGoldenReport:
    def test_run_row(self, report):
        assert len(report["runs"]) == 1
        r = report["runs"][0]
        assert r["run"] == "demo_r00_host00"
        assert r["run_name"] == "demo"
        assert r["platform"] == "tpu"
        assert r["version"] == "0.1.0"
        # percentile fields come from the counters reservoir
        assert r["step_time_p50_s"] == pytest.approx(0.02)
        assert r["step_time_p99_s"] == pytest.approx(0.034)
        # compile step recorded separately, never inside the reservoir
        assert r["compile_time_s"] == pytest.approx(12.25)
        assert r["goodput"] == pytest.approx(0.998)
        assert r["mfu"] == pytest.approx(0.41)
        assert r["hbm_peak_bytes"] == pytest.approx(536870912.0)
        assert r["collective_bytes"] == pytest.approx(1048576.0)

    def test_devtrace_block(self, report):
        dt = report["runs"][0]["devtrace"]
        assert dt["steps"] == 2
        assert dt["window"] == [2, 4]
        assert dt["exposed_comms_frac"] == pytest.approx(
            0.008 / 0.041, rel=1e-3)
        assert dt["collectives"]["all-reduce"]["count"] == 24

    def test_drift_table(self, report):
        cd = report["runs"][0]["collective_drift"]
        assert cd["all-reduce"]["ratio"] == pytest.approx(1.15)
        assert cd["all-reduce"]["predicted_s"] == pytest.approx(0.005)
        # the ingestability stamp survives into the report
        assert cd["all-reduce"]["ingestable"] is True
        assert report["runs"][0]["drift_ratio"] == pytest.approx(0.95)

    def test_sim_block(self, report):
        sim = report["runs"][0]["sim"]
        assert sim["predicted_step_s"] == pytest.approx(0.0185)
        # predicted vs measured p50: the simulated-vs-measured timeline
        # coordinate of the report
        assert sim["predicted_vs_measured"] == pytest.approx(
            0.0185 / 0.02, rel=1e-3)

    def test_per_op_attribution_joins_corpus_row(self, report):
        """Acceptance: one row joins op -> priced terms -> measured
        seconds (the learned-cost-model corpus format)."""
        attr = report["runs"][0]["per_op_attribution"]
        assert attr["ops"] == 2
        by_name = {r["name"]: r for r in attr["rows"]}
        d1 = by_name["dense1"]
        assert d1["type"] == "LINEAR"
        assert d1["choice"] == "dp"
        # priced half: fwd+bwd+comm+gradsync from the simulated schedule
        assert d1["predicted_s"] == pytest.approx(
            0.0035 + 0.007 + 0.0 + 0.0015)
        # measured half: the profile table's whole-op per-op seconds
        assert d1["measured_s"] == pytest.approx(0.003 + 0.006)
        assert d1["source"] == "measured"
        # the ratio compares COMPARABLE quantities: sharded measured
        # compute (measured / work_div) vs the priced compute terms
        # (fwd+bwd only — predicted_s also carries comms)
        assert d1["work_div"] == 8
        assert d1["ratio"] == pytest.approx(
            (d1["measured_s"] / 8) / (0.0035 + 0.007), rel=1e-3)
        # an op without a measured row stays priced-only
        assert "measured_s" not in by_name["dense2"]

    def test_search_block(self, report):
        s = report["runs"][0]["search"]
        assert s["schema_version"] == 1
        assert s["winner_mesh"]["data"] == 8
        assert s["mesh_candidates"] == 4
        assert s["mesh_status"] == dict(winner=1, dominated=1,
                                        over_budget=1, illegal=1)

    def test_markdown_sections(self, mod, report):
        md = mod.to_markdown(report)
        assert "# Observability run report" in md
        assert "## Measured vs priced collectives" in md
        assert "## Simulator accuracy (predicted vs measured step)" in md
        assert "## Per-op predicted vs measured" in md
        assert "demo_r00_host00" in md

    def test_main_writes_outputs(self, mod, tmp_path):
        out = str(tmp_path / "OBS_REPORT.json")
        md = str(tmp_path / "OBS_REPORT.md")
        assert mod.main([FIXTURE, "--out", out, "--md", md]) == 0
        rep = json.load(open(out))
        assert rep["runs"][0]["run"] == "demo_r00_host00"
        assert "Per-op predicted vs measured" in open(md).read()

    def test_empty_dir_exit_zero(self, mod, tmp_path):
        out = str(tmp_path / "empty" / "OBS_REPORT.json")
        assert mod.main([str(tmp_path / "empty"), "--out", out]) == 0
        rep = json.load(open(out))
        assert rep["runs"] == []
        assert "note" in rep

"""Weight-update sharding (WUS, ISSUE 4): reduce-scatter gradient sync,
data-sharded master params + optimizer moments, fused all-gather of the
next step's compute params — as a searched, simulator-priced strategy
dimension and an executor mode behind ``--weight-update-sharding``.

Runs on the conftest 8-device virtual CPU mesh (f32 regime: the params
ARE the master copy, so forward gathers the shards on the fly; the bf16
master-copy regime adds the fused cast+gather, asserted structurally).
"""

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import LossType
from flexflow_tpu.machine import make_mesh
from flexflow_tpu.model import FFModel
from flexflow_tpu.optimizers import AdamOptimizer, SGDOptimizer

BATCH = 16


def build_mlp(wus_mode="auto", data_degree=8, optimizer=None, seed=42):
    """Param-heavy 2-layer MLP on a pure data mesh (the WUS target
    shape: optimizer state dwarfs activations)."""
    cfg = FFConfig(batch_size=BATCH, seed=seed)
    cfg.weight_update_sharding = wus_mode
    ff = FFModel(cfg)
    x = ff.create_tensor((BATCH, 64), name="x")
    t = ff.dense(x, 512, name="d0")
    t = ff.relu(t)
    t = ff.dense(t, 64, name="d1")
    mesh = make_mesh(8, {"data": data_degree} if data_degree == 8
                     else {"data": data_degree, "model": 8 // data_degree})
    ff.compile(optimizer or AdamOptimizer(alpha=1e-2),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [], mesh=mesh)
    return ff


class TestFlagAndAuto:
    def test_flag_parsing(self):
        cfg = FFConfig()
        assert cfg.parse_args(["--weight-update-sharding", "on"]) == []
        assert cfg.weight_update_sharding == "on"
        with pytest.raises(ValueError):
            FFConfig().parse_args(["--weight-update-sharding", "maybe"])

    def test_auto_engages_at_data_degree_4(self):
        assert build_mlp("auto", 8).executor.weight_update_sharding
        # data degree 2 (< 4): auto stays off for heuristic strategies
        assert not build_mlp("auto", 2).executor.weight_update_sharding

    def test_on_off_override(self):
        assert build_mlp("on", 2).executor.weight_update_sharding
        assert not build_mlp("off", 8).executor.weight_update_sharding

    def test_inference_mode_never_shards(self):
        from flexflow_tpu.ffconst import CompMode
        cfg = FFConfig(batch_size=BATCH)
        cfg.weight_update_sharding = "on"
        ff = FFModel(cfg)
        x = ff.create_tensor((BATCH, 32), name="x")
        ff.dense(x, 32, name="d0")
        ff.compile(SGDOptimizer(), LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
                   [], comp_mode=CompMode.INFERENCE,
                   mesh=make_mesh(8, {"data": 8}))
        assert not ff.executor.weight_update_sharding


class TestShardedState:
    def test_master_and_moments_carry_data_axis(self):
        ff = build_mlp("on")
        k = ff.params["d0"]["kernel"]
        assert "data" in (k.sharding.spec[0] or ()) \
            or k.sharding.spec[0] == "data"
        for key in ("m", "v"):
            s = ff.opt_state[key]["d0"]["kernel"].sharding.spec
            assert s[0] == "data", s

    def test_wus_param_specs_legal(self):
        """The executor's sharded-state specs pass fflint's
        sharding-legality rules (the wus:<param> tensors)."""
        from flexflow_tpu.analysis import LintContext, run_passes
        from flexflow_tpu.analysis.passes.sharding import ShardingLegalityPass
        ff = build_mlp("on")
        specs = ff.executor.wus_param_specs()
        assert "d0" in specs and "kernel" in specs["d0"]
        ctx = LintContext(nodes=ff.executor.nodes, mesh=ff.mesh,
                          strategy=ff.strategy, ff=ff)
        rep = run_passes(ctx, [ShardingLegalityPass()])
        assert rep.passes["sharding-legality"] == "ok"
        assert not rep.errors, [d.format() for d in rep.errors]

    def test_indivisible_params_stay_replicated(self):
        """A leaf with no dim the data degree divides is left alone —
        mixed sharded/replicated trees must train fine."""
        cfg = FFConfig(batch_size=BATCH)
        cfg.weight_update_sharding = "on"
        ff = FFModel(cfg)
        x = ff.create_tensor((BATCH, 12), name="x")
        ff.dense(x, 12, name="tiny")  # 12 % 8 != 0 on every dim
        ff.compile(AdamOptimizer(alpha=1e-2),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
                   mesh=make_mesh(8, {"data": 8}))
        spec = ff.params["tiny"]["kernel"].sharding.spec
        assert all(e is None for e in spec), spec
        ff.set_batch(np.zeros((BATCH, 12), np.float32),
                     np.zeros((BATCH, 12), np.float32))
        ff.forward(); ff.backward(); ff.update()
        assert np.isfinite(float(ff._last_loss))


class TestParity:
    def test_seeded_loss_parity_bitwise(self):
        """WUS-on vs WUS-off: identical f32 losses bit-for-bit for 3
        steps on the deviceless 8-way data mesh (acceptance criterion)."""
        rs = np.random.RandomState(0)
        x = rs.randn(3 * BATCH, 64).astype(np.float32)
        y = rs.randn(3 * BATCH, 64).astype(np.float32)
        losses = {}
        for mode in ("off", "on"):
            ff = build_mlp(mode)
            ls = []
            for s in range(3):
                ff.set_batch(x[s * BATCH:(s + 1) * BATCH],
                             y[s * BATCH:(s + 1) * BATCH])
                ff.forward(); ff.backward(); ff.update()
                ls.append(np.float32(ff._last_loss))
            losses[mode] = ls
        assert all(np.isfinite(v) for v in losses["on"])
        for a, b in zip(losses["off"], losses["on"]):
            assert a.tobytes() == b.tobytes(), (losses["off"], losses["on"])

    def test_eval_and_predict_gather_shards(self):
        ff = build_mlp("on")
        rs = np.random.RandomState(1)
        x = rs.randn(BATCH, 64).astype(np.float32)
        y = rs.randn(BATCH, 64).astype(np.float32)
        rep = ff.evaluate(x, y)
        assert np.isfinite(rep["loss"])
        out = ff.predict(x)
        assert out.shape == (BATCH, 64)

    def test_set_get_parameter_roundtrip(self):
        ff = build_mlp("on")
        w = np.arange(64 * 512, dtype=np.float32).reshape(64, 512)
        ff.set_parameter("d0", w)
        np.testing.assert_array_equal(ff.get_parameter("d0"), w)


class TestMemoryAndAliasing:
    """Compiled-memory-analysis assertions (acceptance criteria):
    donation actually aliases the param buffers, and WUS cuts the
    per-device HBM peak by >= 20% at data degree 8."""

    @staticmethod
    def _mem(ff):
        from flexflow_tpu.search.validate import compiled_train_step
        return compiled_train_step(ff).memory_analysis()

    def test_donation_aliases_param_buffers(self):
        """The train step must not hold duplicate param buffers: the
        donated params + optimizer state alias into the outputs, so
        alias bytes cover (almost all of) the argument bytes minus the
        un-donated batch/rng inputs."""
        ff = build_mlp("off")
        ma = self._mem(ff)
        batch_bytes = BATCH * 64 * 4 * 2 + 16  # x + labels + rng key
        aliasable = ma.argument_size_in_bytes - batch_bytes
        assert ma.alias_size_in_bytes >= 0.9 * aliasable, (
            ma.alias_size_in_bytes, ma.argument_size_in_bytes)

    def test_wus_cuts_hbm_peak_at_data_degree_8(self):
        peaks = {}
        for mode in ("off", "on"):
            ma = self._mem(build_mlp(mode))
            peaks[mode] = ma.argument_size_in_bytes + ma.temp_size_in_bytes
        assert peaks["on"] <= 0.8 * peaks["off"], peaks


class TestSearchedWUS:
    """WUS as a searched dimension: the native DP prices the
    reduce-scatter/all-gather twins distinctly and picks them for
    Adam-class optimizer state; fflint's census finds the set priced."""

    def _searched(self, name):
        from flexflow_tpu.search.native import available
        if not available():
            pytest.skip("native search unavailable")
        import importlib.util
        import os
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "fflint_cli", os.path.join(repo, "scripts", "fflint.py"))
        cli = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cli)
        cfg = FFConfig()
        cfg.search_budget = 4
        cfg.enable_parameter_parallel = True
        cfg.enable_pipeline_parallel = False
        ff, _ = cli.build_model(name, cfg)
        ff.compile(AdamOptimizer(alpha=1e-3),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        return ff

    @pytest.mark.analysis
    @pytest.mark.parametrize("name", ["transformer", "llama"])
    def test_searched_wus_census_is_priced(self, name):
        """Acceptance: searched bert/llama-family strategies with WUS
        enabled report the reduce-scatter/all-gather set as priced — no
        FFL2xx ERRORs from the collective-inference pass."""
        from flexflow_tpu.analysis import LintContext, run_passes
        from flexflow_tpu.analysis.passes.collectives import (
            CollectiveInferencePass, infer_strategy_collectives)
        ff = self._searched(name)
        choices = [getattr(ff.strategy.get(n.op.guid), "choice", None) or ""
                   for n in ff.executor.nodes]
        data_deg = dict(zip(ff.mesh.axis_names,
                            ff.mesh.devices.shape)).get("data", 1)
        if data_deg > 1:
            # the DP must price WUS distinctly and choose it for
            # Adam-class state on a data mesh
            assert any("_wus" in c for c in choices), choices
            assert ff.executor.weight_update_sharding
        ctx = LintContext(nodes=ff.executor.nodes, mesh=ff.mesh,
                          strategy=ff.strategy, ff=ff)
        if ff.executor.weight_update_sharding:
            inferred = infer_strategy_collectives(ctx)
            assert "allgather" in inferred, inferred  # the WUS gather
        rep = run_passes(ctx, [CollectiveInferencePass()])
        assert rep.passes["collective-inference"] == "ok", rep.passes
        bad = [d for d in rep.errors if d.rule.startswith("FFL2")]
        assert not bad, "\n".join(d.format() for d in bad)

    def test_simulator_prices_wus_vs_allreduce_distinctly(self):
        """ffs_simulate: the _wus twin of a dp choice yields an
        allgather task the plain choice does not, and a lower memory
        figure (sharded optimizer state)."""
        from flexflow_tpu.search.native import available, native_simulate
        if not available():
            pytest.skip("native search unavailable")
        b, d = 512, 1024
        nodes = [{
            "guid": 1, "type": "LINEAR", "name": "l", "inputs": [[-1, 0]],
            "input_shapes": [[b, d]], "output_shapes": [[b, d]],
            "roles": [["sample", "channel"]],
            "params": {"kernel": [d, d], "bias": [d]},
            "flops": 2.0 * b * d * d, "dtype_size": 4, "attrs": {},
        }]
        machine = {"num_devices": 8, "flops": 197e12, "hbm_bw": 0.82e12,
                   "hbm_cap": 16e9, "ici_bw": 45e9, "ici_latency": 1e-6,
                   "dcn_bw": 25e9, "dcn_latency": 1e-5, "num_slices": 1}
        out = {}
        for choice in ("dp", "dp_wus"):
            r = native_simulate({
                "nodes": nodes, "machine": machine, "measured": {},
                "config": {"training": True, "overlap": True,
                           "opt_state_factor": 2.0},
                "mesh": {"data": 8, "model": 1, "seq": 1, "expert": 1},
                "assignment": {"1": choice}})
            kinds = {t["collective"] for t in r["tasks"]
                     if t.get("collective")}
            out[choice] = (kinds, r["memory"])
        assert "allgather" not in out["dp"][0]
        assert {"allreduce", "allgather"} <= out["dp_wus"][0]
        assert out["dp_wus"][1] < out["dp"][1]  # sharded moments

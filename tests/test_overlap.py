"""Comms-compute overlap as a searched dimension (ISSUE 9): bucketed
async grad reduce-scatter in the executor (reverse-backward bucket
partition chained through optimization_barrier — bit-for-bit identical
to the synchronous sync), "_ovl" latency-hiding choice twins in the
native search (exposed = max(comm/B, comm - hideable) + B x launch,
bucket size swept and recorded), per-op WUS granularity, the
exposed-comms bench ratchet, and the fflint FFL207 rejected-overlap
INFO rule.

Runs on the conftest 8-device virtual CPU mesh.
"""

import os
import types

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import LossType
from flexflow_tpu.machine import make_mesh
from flexflow_tpu.model import FFModel
from flexflow_tpu.optimizers import AdamOptimizer

BATCH = 16


def build_mlp(wus_mode="on", overlap="auto", seed=42):
    """The test_wus MLP shape (param-heavy, pure data mesh) with the
    overlap knobs exposed."""
    cfg = FFConfig(batch_size=BATCH, seed=seed)
    cfg.weight_update_sharding = wus_mode
    cfg.overlap_bucket_mb = overlap
    ff = FFModel(cfg)
    x = ff.create_tensor((BATCH, 64), name="x")
    t = ff.dense(x, 512, name="d0")
    t = ff.relu(t)
    t = ff.dense(t, 64, name="d1")
    ff.compile(AdamOptimizer(alpha=1e-2),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
               mesh=make_mesh(8, {"data": 8}))
    return ff


class TestFlagAndAuto:
    def test_flag_parsing(self):
        cfg = FFConfig()
        assert cfg.parse_args(["--overlap-bucket-mb", "8"]) == []
        assert cfg.overlap_bucket_mb == "8"
        assert FFConfig().parse_args(["--overlap-bucket-mb", "auto"]) == []
        with pytest.raises(ValueError):
            FFConfig().parse_args(["--overlap-bucket-mb", "many"])

    def test_auto_engages_with_heuristic_wus(self):
        ff = build_mlp("on", "auto")
        assert ff.executor.grad_overlap
        # MB (1e6), the native bucket sweep's wire-byte unit
        assert ff.executor.overlap_bucket_bytes == 4_000_000

    def test_explicit_bucket_and_off(self):
        assert build_mlp("on", "1").executor.overlap_bucket_bytes == 1_000_000
        assert not build_mlp("on", "off").executor.grad_overlap
        assert not build_mlp("on", "0").executor.grad_overlap

    def test_overlap_requires_wus(self):
        # bucketing partitions the WUS grad tree; without WUS there are
        # no explicit sync constraints to bucket
        assert not build_mlp("off", "4").executor.grad_overlap


class TestBucketedParity:
    def test_bucketed_async_parity_bitwise(self):
        """Acceptance: bucketed-async vs synchronous grad sync are
        bit-for-bit identical for 3 seeded f32 steps on the 8-way data
        mesh (the barrier chain is the identity on values)."""
        rs = np.random.RandomState(0)
        x = rs.randn(3 * BATCH, 64).astype(np.float32)
        y = rs.randn(3 * BATCH, 64).astype(np.float32)
        losses = {}
        for mode, ovl in (("sync", "off"), ("bucketed", "1")):
            ff = build_mlp("on", ovl)
            # 1-MB buckets split this model's ~1.2 MB of f32 grads, so
            # the chained path (multiple buckets) is really exercised
            assert ff.executor.grad_overlap == (mode == "bucketed")
            ls = []
            for s in range(3):
                ff.set_batch(x[s * BATCH:(s + 1) * BATCH],
                             y[s * BATCH:(s + 1) * BATCH])
                ff.forward(); ff.backward(); ff.update()
                ls.append(np.float32(ff._last_loss))
            losses[mode] = ls
        assert all(np.isfinite(v) for v in losses["bucketed"])
        for a, b in zip(losses["sync"], losses["bucketed"]):
            assert a.tobytes() == b.tobytes(), losses

    def test_fit_eval_roundtrip_with_overlap(self):
        ff = build_mlp("on", "1")
        rs = np.random.RandomState(1)
        x = rs.randn(BATCH, 64).astype(np.float32)
        y = rs.randn(BATCH, 64).astype(np.float32)
        rep = ff.evaluate(x, y)
        assert np.isfinite(rep["loss"])


class TestPerOpWusGranularity:
    """ROADMAP carried follow-on: the executor honors each op's searched
    '_wus' choice instead of applying WUS globally."""

    def test_wus_ops_gates_specs(self):
        ff = build_mlp("on")
        ex = ff.executor
        assert ex.wus_spec("d0", "kernel", (64, 512)) is not None
        ex.wus_ops = {"d0"}  # as a mixed searched strategy would set
        assert ex.wus_spec("d0", "kernel", (64, 512)) is not None
        assert ex.wus_spec("d1", "kernel", (512, 64)) is None
        specs = ex.wus_param_specs()
        assert "d0" in specs and "d1" not in specs

    def test_replay_honors_per_op_choices(self):
        """simulate_strategy replays what the executor EXECUTES: ops in
        wus_ops carry the _wus(_ovl) suffixes, the rest stay plain."""
        from flexflow_tpu.search import validate as V

        ff = build_mlp("on", "1")
        ff.executor.wus_ops = {"d0"}
        captured = {}
        orig = V.native_simulate if hasattr(V, "native_simulate") else None

        import flexflow_tpu.search.native as native
        real = native.native_simulate

        def spy(req):
            captured.update(req["assignment"])
            return real(req)

        V_native = native.native_simulate
        native.native_simulate = spy
        try:
            V.simulate_strategy(ff)
        finally:
            native.native_simulate = V_native
        by_name = {n.op.name: str(n.op.guid) for n in ff.executor.nodes}
        assert captured[by_name["d0"]].endswith("_wus_ovl")
        assert "_wus" not in captured[by_name["d1"]]

    def test_model_builds_wus_ops_from_searched_choices(self):
        """FFModel.compile keys the per-op set off the searched '_wus'
        choices under 'auto' (forced 'on' stays global)."""
        assert build_mlp("on").executor.wus_ops is None


class TestNativeOvlPricing:
    """Acceptance: '_ovl' twins price distinctly from their sync
    parents, with identical census bytes, and the bucket sweep is
    recorded in the search trace."""

    @staticmethod
    def _chain_nodes(b=256, d=1024):
        roles = [["sample", "channel"]]
        lin = dict(input_shapes=[[b, d]], output_shapes=[[b, d]],
                   roles=roles, params={"kernel": [d, d], "bias": [d]},
                   flops=b * d * d * 2.0, dtype_size=4, attrs={})
        return [
            dict(guid=1, type="INPUT", name="x", inputs=[],
                 input_shapes=[], output_shapes=[[b, d]], roles=roles,
                 params={}, flops=0.0, dtype_size=4, attrs={}),
            dict(lin, guid=2, name="d1", inputs=[[1, 0]]),
            dict(lin, guid=3, name="d2", inputs=[[2, 0]]),
        ]

    _MACHINE = {"num_devices": 8, "flops": 1e12, "hbm_bw": 1e11,
                "hbm_cap": 16e9, "ici_bw": 1e10, "ici_latency": 1e-6,
                "dcn_bw": 1e9, "dcn_latency": 1e-5, "num_slices": 1}

    def _sim(self, choice):
        from flexflow_tpu.search.native import available, native_simulate
        if not available():
            pytest.skip("native search unavailable")
        return native_simulate({
            "nodes": self._chain_nodes(), "machine": self._MACHINE,
            "measured": {},
            "config": {"training": True, "overlap": False,
                       "opt_state_factor": 2.0},
            "mesh": {"data": 8, "model": 1, "seq": 1, "expert": 1},
            "assignment": {"1": "rep", "2": choice, "3": choice}})

    @pytest.mark.parametrize("parent", ["dp_wus"])
    def test_ovl_twin_prices_distinctly(self, parent):
        sync = self._sim(parent)
        ovl = self._sim(parent + "_ovl")
        # the twin hides real comm under compute and the step shortens
        assert sync["hidden_comm_time"] == 0
        assert ovl["hidden_comm_time"] > 0
        assert ovl["iteration_time"] < sync["iteration_time"]
        assert any(t.get("hidden_s") for t in ovl["tasks"])
        # census bytes are byte-for-byte identical: bucketing changes
        # WHEN collectives fire, never what moves on the wire

        def census(r):
            out = {}
            for t in r["tasks"]:
                if t.get("collective"):
                    out[t["collective"]] = out.get(t["collective"], 0.0) \
                        + t["bytes"]
            return out

        assert census(sync) == census(ovl)

    def test_plain_ovl_not_enumerated_replays_as_sync(self):
        """Only '_wus' parents spawn '_ovl' twins — the runtime's bucket
        chaining rides on the WUS shard constraints, so pricing hiding
        for plain sync would misrank strategies the executor then runs
        synchronously. A (stale/heuristic) 'dp_ovl' request falls back
        along the suffix lattice to plain 'dp' — never to '_wus'
        pricing the op doesn't execute."""
        sync = self._sim("dp")
        ovl = self._sim("dp_ovl")
        assert ovl["hidden_comm_time"] == 0
        assert ovl["iteration_time"] == pytest.approx(
            sync["iteration_time"])
        assert ovl["memory"] == pytest.approx(sync["memory"])

    def test_bucket_sweep_recorded_in_search_trace(self):
        from flexflow_tpu.search.native import available, native_optimize
        if not available():
            pytest.skip("native search unavailable")
        resp = native_optimize(dict(
            nodes=self._chain_nodes(), machine=self._MACHINE, measured={},
            config=dict(budget=1, training=True, enable_substitution=False,
                        only_data_parallel=True, batch=256,
                        emit_search_trace=True)))
        ops = {o["name"]: o for o in resp["search_trace"]["ops"]}
        ovl_cands = [c for c in ops["d1"]["candidates"]
                     if "_ovl" in c["choice"]]
        assert ovl_cands, [c["choice"] for c in ops["d1"]["candidates"]]
        for c in ovl_cands:
            ov = c["overlap"]
            assert ov["bucket_mb"] > 0
            assert ov["buckets"] >= 1
            sweep = ov["sweep"]
            assert len(sweep) >= 4
            for row in sweep:
                assert row["bucket_mb"] > 0 and row["exposed_s"] > 0
            # the committed bucket is the sweep's argmin
            best = min(sweep, key=lambda r: r["exposed_s"])
            assert best["bucket_mb"] == ov["bucket_mb"]
            assert "hidden_s" in c["terms"]

    def test_searched_bert_family_picks_ovl_on_v4_32(self):
        """Acceptance: the searched BERT-family strategy on the
        simulated v4-32 takes an '_ovl' choice, and the strategy records
        the searched bucket size 'auto' follows."""
        from flexflow_tpu.machine import MachineSpec
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     create_transformer)
        from flexflow_tpu.optimizers import SGDOptimizer
        from flexflow_tpu.search.native import available, native_optimize
        from flexflow_tpu.search.unity import (machine_to_json,
                                               serialize_graph)
        if not available():
            pytest.skip("native search unavailable")
        n_chips = 32
        mcfg = TransformerConfig(num_layers=2, hidden_size=1024,
                                 num_heads=16, seq_length=64,
                                 batch_size=n_chips)
        ff = create_transformer(
            mcfg, FFConfig(batch_size=mcfg.batch_size,
                           only_data_parallel=True, workers_per_node=1))
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        resp = native_optimize(dict(
            nodes=serialize_graph(ff.executor.nodes),
            machine=machine_to_json(
                MachineSpec(chip="tpu-v4", chips_per_slice=n_chips),
                n_chips, comm_bytes_factor=0.5),
            measured={},
            config=dict(budget=4, alpha=0.05, training=True, overlap=True,
                        batch=mcfg.batch_size, opt_state_factor=2.0,
                        seed=42, rules=[], enable_parameter_parallel=True,
                        enable_substitution=False,
                        enable_pipeline_parallel=False)))
        choices = {v["choice"] for v in resp["ops"].values()}
        assert any("_ovl" in c for c in choices), choices
        assert resp["overlap"]["bucket_mb"] > 0
        assert resp["overlap"]["ops"] >= 1

    def test_ovl_disabled_removes_dimension(self):
        from flexflow_tpu.search.native import available, native_optimize
        if not available():
            pytest.skip("native search unavailable")
        resp = native_optimize(dict(
            nodes=self._chain_nodes(), machine=self._MACHINE, measured={},
            config=dict(budget=1, training=True, enable_substitution=False,
                        only_data_parallel=True, batch=256,
                        comm_overlap="off", emit_search_trace=True)))
        names = [c["choice"] for o in resp["search_trace"]["ops"]
                 for c in o["candidates"]]
        assert not any("_ovl" in n for n in names)


class TestSimtraceHidden:
    def test_sim_lane_events_carry_hidden(self):
        from flexflow_tpu.obs.simtrace import sim_lane_events
        tasks = [dict(kind="gradsync", node=0, start=0.0, finish=1e-3,
                      collective="allreduce", bytes=4096, hidden_s=4e-4)]
        (ev,) = sim_lane_events(tasks, {0: "d"})
        assert ev["args"]["hidden_s"] == pytest.approx(4e-4)

    def test_simtrace_predicted_hidden_comm(self):
        from flexflow_tpu.obs.simtrace import simtrace_report
        from flexflow_tpu.search.validate import simulate_strategy
        ff = build_mlp("on", "1")
        resp = simulate_strategy(ff)
        assert "hidden_comm_time" in resp
        rep = simtrace_report(ff, resp)
        assert rep["predicted"]["hidden_comm_s"] is not None
        # per-op rows aggregate the hidden term
        assert all("hidden_s" in r["priced"] for r in rep["per_op"])


class TestFflint:
    @pytest.mark.analysis
    def test_bucketed_census_is_clean(self):
        """The bucketed RS shape (N bucket collectives summing to the
        unbucketed payload) diffs FFL2xx-clean: both inference and the
        emitted census aggregate bytes per kind."""
        from flexflow_tpu.analysis import LintContext, run_passes
        from flexflow_tpu.analysis.passes.collectives import (
            CollectiveInferencePass)
        ff = build_mlp("on", "1")
        ctx = LintContext(nodes=ff.executor.nodes, mesh=ff.mesh,
                          strategy=ff.strategy, ff=ff)
        rep = run_passes(ctx, [CollectiveInferencePass()])
        assert rep.passes["collective-inference"] == "ok"
        bad = [d for d in rep.errors if d.rule.startswith("FFL2")]
        assert not bad, "\n".join(d.format() for d in bad)

    def test_ffl207_flags_rejected_overlap(self):
        from flexflow_tpu.analysis.passes.collectives import (
            CollectiveInferencePass)

        def op_row(chosen, cands):
            return dict(name="dense", chosen=chosen, candidates=[
                dict(choice=c, chosen=(c == chosen),
                     terms=dict(total_s=1.0, collective_s=s))
                for c, s in cands])

        def ctx_for(ops):
            ff = types.SimpleNamespace(
                search_info=dict(search_trace=dict(ops=ops)))
            return types.SimpleNamespace(ff=ff)

        p = CollectiveInferencePass()
        # rejected _ovl twin + high exposed share -> INFO FFL207
        diags = p._overlap_rejections(ctx_for([op_row(
            "dp", [("dp", 0.5), ("dp_ovl", 0.2)])]))
        assert [d.rule for d in diags] == ["FFL207"]
        assert diags[0].severity.name == "INFO"
        # chosen _ovl: nothing was rejected
        assert not p._overlap_rejections(ctx_for([op_row(
            "dp_ovl", [("dp", 0.5), ("dp_ovl", 0.2)])]))
        # low exposed share: rejection is justified
        assert not p._overlap_rejections(ctx_for([op_row(
            "dp", [("dp", 0.05), ("dp_ovl", 0.2)])]))
        # no twin enumerated: not FFL207's business
        assert not p._overlap_rejections(ctx_for([op_row(
            "dp", [("dp", 0.5)])]))


class TestExposedRatchet:
    def test_ratchet_records_flags_and_skips(self, monkeypatch):
        import bench
        monkeypatch.delenv("FFS_SKIP_EXPOSED", raising=False)
        hist = {}
        # first measurement seeds the low-water mark
        reg, base = bench.exposed_ratchet(hist, "w:cpu", 0.30)
        assert (reg, base) == (False, None)
        assert hist["w:cpu"]["exposed_comms_frac"] == 0.30
        # an overlap win ratchets DOWN — clamped to halving per round,
        # so one outlier-low capture window cannot set an unreachable
        # floor (the fraction is a noisy measured metric)
        reg, _ = bench.exposed_ratchet(hist, "w:cpu", 0.10)
        assert not reg
        assert hist["w:cpu"]["exposed_comms_frac"] == 0.15
        # re-exposing comms beyond tol+abs flags a regression and keeps
        # the recorded best
        reg, base = bench.exposed_ratchet(hist, "w:cpu", 0.20)
        assert reg and base == 0.15
        assert hist["w:cpu"]["exposed_comms_frac"] == 0.15
        # sustained improvement converges geometrically
        reg, _ = bench.exposed_ratchet(hist, "w:cpu", 0.05)
        assert not reg
        assert hist["w:cpu"]["exposed_comms_frac"] == 0.075
        # noise-level drift above a ~zero baseline never flags
        bench.exposed_ratchet(hist, "z:cpu", 0.0)
        reg, _ = bench.exposed_ratchet(hist, "z:cpu", 0.004)
        assert not reg
        # FFS_SKIP_EXPOSED mirrors the census ratchet's opt-out
        monkeypatch.setenv("FFS_SKIP_EXPOSED", "1")
        reg, _ = bench.exposed_ratchet(hist, "w:cpu", 0.9)
        assert not reg


class TestSearchedOverlapWiring:
    def test_searched_ovl_engages_executor(self):
        """A searched strategy that picks '_ovl' twins turns the
        executor's bucketed structuring on under 'auto', with the
        searched bucket size."""
        cfg = FFConfig(batch_size=64)
        cfg.search_budget = 2
        cfg.enable_parameter_parallel = True
        cfg.enable_pipeline_parallel = False
        ff = FFModel(cfg)
        x = ff.create_tensor((64, 512), name="x")
        t = ff.dense(x, 2048, name="h0")
        t = ff.relu(t)
        t = ff.dense(t, 512, name="h1")
        ff.compile(AdamOptimizer(alpha=1e-3),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        choices = [getattr(ff.strategy.get(n.op.guid), "choice", None) or ""
                   for n in ff.executor.nodes]
        if not any("_ovl" in c for c in choices):
            pytest.skip("search did not pick _ovl on this machine model")
        assert ff.executor.grad_overlap
        assert ff.overlap_enabled
        info = ff.search_info.get("overlap") or {}
        if info.get("bucket_mb"):
            # MB (1e6), the native bucket sweep's wire-byte unit
            assert ff.executor.overlap_bucket_bytes == \
                int(float(info["bucket_mb"]) * 1e6)

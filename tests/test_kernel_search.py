"""Kernel-implementation choice as a searched dimension (ISSUE 15).

The ``_k:<impl>`` suffix-lattice twins: native enumeration + per-impl
pricing (flash attention HBM-traffic model, fused one-dispatch
optimizer update, train-time Conv+BN fusion), legality gates with named
rejection reasons in the search trace, the ``FFS_NO_KERNEL_SEARCH`` /
``--kernel-search off`` opt-out, executor parity (fused triad bitwise;
flash within the 2e-5 class), suffix-lattice decode/replay composing
with ``_wus``/``_ovl``, per-impl corpus classes, the fflint
FFL208/FFL209 priced-vs-executed rules, and serve provenance.

Runs on the conftest 8-device virtual CPU mesh.
"""

import copy
import json
import os

import numpy as np
import pytest

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import LossType
from flexflow_tpu.machine import make_mesh
from flexflow_tpu.model import FFModel
from flexflow_tpu.optimizers import AdamOptimizer, SGDOptimizer

BATCH = 16


# ---- native mini-graph harness (test_overlap's pattern) -------------------

_MACHINE = {"num_devices": 8, "flops": 197e12, "hbm_bw": 0.82e12,
            "hbm_cap": 16e9, "ici_bw": 45e9, "ici_latency": 1e-6,
            "dcn_bw": 25e9, "dcn_latency": 1e-5, "num_slices": 1,
            "mxu_efficiency": 0.55, "conv_efficiency": 0.35,
            "min_op_time": 5e-7, "collective_launch_overhead": 2e-6,
            "comm_bytes_factor": 0.5}


def _attn_linear_nodes(seq=512):
    """One self-attention (flash-legal at seq=512, 128|seq) + one
    Linear — the minimal graph every kernel dimension shows up on."""
    return [
        dict(guid=1, type="MULTIHEAD_ATTENTION", name="attn",
             inputs=[[-1, 0], [-1, 0], [-1, 0]],
             input_shapes=[[8, seq, 128]] * 3,
             output_shapes=[[8, seq, 128]],
             roles=[["sample", "seq", "channel"]],
             params={"wq": [8, 128, 16], "wk": [8, 128, 16],
                     "wv": [8, 128, 16], "wo": [8, 16, 128]},
             flops=1e9, dtype_size=4, attrs={"num_heads": 8}),
        dict(guid=2, type="LINEAR", name="fc", inputs=[[1, 0]],
             input_shapes=[[8, seq, 128]], output_shapes=[[8, seq, 128]],
             roles=[["sample", "seq", "channel"]],
             params={"kernel": [128, 128], "bias": [128]},
             flops=1e9, dtype_size=4, attrs={}),
    ]


def _req(nodes, **cfg):
    base = dict(budget=2, training=True, enable_parameter_parallel=True,
                enable_substitution=False, batch=8,
                emit_search_trace=True)
    base.update(cfg)
    return dict(nodes=nodes, machine=dict(_MACHINE), measured={},
                config=base)


def _native():
    from flexflow_tpu.search import native
    if not native.available():
        pytest.skip("native search unavailable")
    return native


class TestNativeEnumeration:
    def test_twins_spawn_and_compose_with_suffix_lattice(self):
        native = _native()
        resp = native.native_optimize(_req(_attn_linear_nodes()))
        ops = {o["name"]: o for o in resp["search_trace"]["ops"]}
        names = [c["choice"] for c in ops["attn"]["candidates"]]
        # the kernel suffix composes with the whole "_wus"/"_ovl" lattice
        assert any(n.endswith("_k:flash") and "_wus" in n and "_ovl" in n
                   for n in names), names
        fc = [c["choice"] for c in ops["fc"]["candidates"]]
        assert any(n.endswith("_k:fused") and "_wus" in n for n in fc), fc
        # fused twins only exist on wus parents (the chain they collapse)
        assert all("_wus" in n for n in fc if "_k:fused" in n)

    def test_priced_distinctly_with_impl_column(self):
        native = _native()
        resp = native.native_optimize(_req(_attn_linear_nodes()))
        ops = {o["name"]: o for o in resp["search_trace"]["ops"]}

        def total(opn, choice):
            c = next(c for c in ops[opn]["candidates"]
                     if c["choice"] == choice)
            return c["terms"]["total_s"], c.get("impl"), c["cost_source"]

        t_e, i_e, src = total("attn", "dp")
        t_f, i_f, _ = total("attn", "dp_k:flash")
        assert i_e == "einsum" and i_f == "flash" and src == "analytic"
        assert t_f < t_e  # the HBM-traffic model prices flash cheaper
        t_t, i_t, _ = total("fc", "dp_wus")
        t_u, i_u, _ = total("fc", "dp_wus_k:fused")
        assert i_t == "triad" and i_u == "fused"
        assert t_u < t_t  # one round trip + two launches cheaper

    def test_illegal_flash_rejected_with_named_reason(self):
        native = _native()
        resp = native.native_optimize(_req(_attn_linear_nodes(seq=64)))
        ops = {o["name"]: o for o in resp["search_trace"]["ops"]}
        rej = {r["impl"]: r["reason"]
               for r in ops["attn"].get("kernel_rejections") or []}
        assert rej.get("flash") == "seq_not_divisible_by_flash_tile_128"
        assert not any("_k:flash" in c["choice"]
                       for c in ops["attn"]["candidates"])

    def test_dropout_attention_rejects_flash(self):
        """Attention-prob dropout has no flash lowering: the training
        gate rejects the twin with a named reason instead of pricing a
        kernel the executor's forward can never take (review finding)."""
        native = _native()
        nodes = _attn_linear_nodes()
        nodes[0]["attrs"]["dropout"] = 0.1
        resp = native.native_optimize(_req(nodes))
        ops = {o["name"]: o for o in resp["search_trace"]["ops"]}
        rej = {r["impl"]: r["reason"]
               for r in ops["attn"].get("kernel_rejections") or []}
        assert rej.get("flash") == "attention_prob_dropout_unsupported"
        assert not any("_k:flash" in c["choice"]
                       for c in ops["attn"]["candidates"])

    def test_opt_out_removes_dimension(self):
        native = _native()
        on = native.native_optimize(_req(_attn_linear_nodes()))
        off = native.native_optimize(
            _req(_attn_linear_nodes(), kernel_search="off"))
        names_off = [c["choice"] for o in off["search_trace"]["ops"]
                     for c in o["candidates"]]
        assert not any("_k:" in n for n in names_off)
        # deterministic: two off-runs agree bit-for-bit (the pre-PR
        # search space — twins absent, pricing of every remaining
        # candidate untouched)
        off2 = native.native_optimize(
            _req(_attn_linear_nodes(), kernel_search="off"))
        assert json.dumps(off, sort_keys=True) == \
            json.dumps(off2, sort_keys=True)
        # the on-search saw strictly more candidates
        names_on = [c["choice"] for o in on["search_trace"]["ops"]
                    for c in o["candidates"]]
        assert set(names_off) < set(names_on)

    def test_replay_tolerates_and_falls_back_k_suffix(self):
        native = _native()
        nodes = _attn_linear_nodes()
        base = dict(nodes=nodes, machine=dict(_MACHINE), measured={},
                    config=dict(training=True,
                                enable_parameter_parallel=True),
                    mesh={"data": 4, "model": 2, "seq": 1, "expert": 1,
                          "pipe": 1},
                    assignment={"1": "dp_head_k:flash",
                                "2": "dp_wus_k:fused"})
        r = native.native_simulate(base)
        assert r["iteration_time"] > 0
        # kernel search off: the "_k:" request falls back along the
        # suffix lattice to the default lowering instead of erroring
        off = copy.deepcopy(base)
        off["config"]["kernel_search"] = "off"
        r2 = native.native_simulate(off)
        assert r2["iteration_time"] > 0
        # the fused/flash lowerings price cheaper than the fallback
        assert r["iteration_time"] <= r2["iteration_time"]

    def test_acceptance_v4_32_bert_family_picks_fused_kernel(self):
        """Simulated v4-32 BERT-family search prices `_k:flash` and
        `_k:fused` distinctly from their baselines and commits to at
        least one fused kernel."""
        from flexflow_tpu.machine import MachineSpec
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     create_transformer)
        from flexflow_tpu.search.unity import (machine_to_json,
                                               serialize_graph)
        native = _native()
        n_chips = 32
        mcfg = TransformerConfig(num_layers=2, hidden_size=1024,
                                 num_heads=16, seq_length=512,
                                 batch_size=n_chips)
        ff = create_transformer(
            mcfg, FFConfig(batch_size=mcfg.batch_size,
                           only_data_parallel=True, workers_per_node=1))
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        resp = native.native_optimize(dict(
            nodes=serialize_graph(ff.executor.nodes),
            machine=machine_to_json(
                MachineSpec(chip="tpu-v4", chips_per_slice=n_chips),
                n_chips, comm_bytes_factor=0.5),
            measured={},
            config=dict(budget=4, alpha=0.05, training=True, overlap=True,
                        batch=mcfg.batch_size, opt_state_factor=2.0,
                        seed=42, rules=[], enable_parameter_parallel=True,
                        enable_substitution=False,
                        enable_pipeline_parallel=False,
                        emit_search_trace=True)))
        choices = {v["choice"] for v in resp["ops"].values()}
        assert any("_k:" in c for c in choices), choices
        # distinct pricing of both kernel families on the winning mesh
        ops = resp["search_trace"]["ops"]
        saw_flash = saw_fused = False
        for oj in ops:
            by = {}
            for c in oj["candidates"]:
                impl = c.get("impl")
                if impl:
                    by.setdefault(impl, set()).add(
                        round(c["terms"]["total_s"], 12))
            if "flash" in by and "einsum" in by and by["flash"] != by["einsum"]:
                saw_flash = True
            if "fused" in by and "triad" in by and by["fused"] != by["triad"]:
                saw_fused = True
        assert saw_flash and saw_fused


class TestFlagPlumbing:
    def test_flag_parsing(self):
        cfg = FFConfig()
        assert cfg.parse_args(["--kernel-search", "off"]) == []
        assert cfg.kernel_search == "off"
        assert FFConfig().kernel_search == "auto"
        with pytest.raises(ValueError):
            FFConfig().parse_args(["--kernel-search", "sometimes"])

    def test_env_opt_out_strips_choices(self, monkeypatch):
        monkeypatch.setenv("FFS_NO_KERNEL_SEARCH", "1")
        ff = _searched_mlp()
        assert ff.kernel_choices is None
        assert not any(
            "_k:" in (getattr(s, "choice", None) or "")
            for s in ff.strategy.values())

    def test_searched_kernel_choices_reach_executor(self):
        ff = _searched_mlp()
        assert ff.kernel_choices is not None
        fused = {n for n, i in ff.kernel_choices.items() if i == "fused"}
        assert fused == ff.executor.fused_update_ops
        assert fused  # the wus MLP takes the fused update


def _searched_mlp(seed=42):
    cfg = FFConfig(batch_size=BATCH, seed=seed)
    cfg.search_budget = 2
    cfg.enable_parameter_parallel = True
    ff = FFModel(cfg)
    x = ff.create_tensor((BATCH, 64), name="x")
    t = ff.dense(x, 512, name="d0")
    t = ff.relu(t)
    t = ff.dense(t, 64, name="d1")
    ff.compile(AdamOptimizer(alpha=1e-2),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
    return ff


def _plain_mlp(optimizer, fused_ops=None):
    """Heuristic (non-searched) MLP on the 8-way data mesh; the fused
    update is forced per-op so both runs share ONE strategy."""
    cfg = FFConfig(batch_size=BATCH, seed=42)
    cfg.weight_update_sharding = "on"
    ff = FFModel(cfg)
    x = ff.create_tensor((BATCH, 64), name="x")
    t = ff.dense(x, 512, name="d0")
    t = ff.relu(t)
    t = ff.dense(t, 64, name="d1")
    ff.compile(optimizer, LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [],
               mesh=make_mesh(8, {"data": 8}))
    if fused_ops:
        ff.executor.kernel_choices = {n: "fused" for n in fused_ops}
        ff.executor.fused_update_ops = set(fused_ops)
    return ff


class TestExecutorParity:
    def _train(self, ff, steps=3):
        import jax
        rs = np.random.RandomState(0)
        x = rs.randn(BATCH, 64).astype(np.float32)
        y = rs.randn(BATCH, 64).astype(np.float32)
        for _ in range(steps):
            ff.fit([x], y, epochs=1, verbose=False)
        return [np.asarray(l) for l in jax.tree_util.tree_leaves(
            (ff.params, ff.opt_state))]

    @pytest.mark.parametrize("opt", ["adam", "sgd", "sgd_momentum"])
    def test_fused_update_bitwise_on_8way_mesh(self, opt):
        """The `_k:fused` one-dispatch update is bit-for-bit with the
        reference triad over a 3-step seeded run on the 8-way mesh."""
        mk = {"adam": lambda: AdamOptimizer(alpha=1e-2),
              "sgd": lambda: SGDOptimizer(lr=0.01),
              "sgd_momentum": lambda: SGDOptimizer(lr=0.01, momentum=0.9)}
        ref = self._train(_plain_mlp(mk[opt]()))
        fus = self._train(_plain_mlp(mk[opt](), fused_ops={"d0", "d1"}))
        for a, b in zip(ref, fus):
            assert np.array_equal(a, b)

    def test_fused_adam_pallas_interpret_bitwise(self, monkeypatch):
        """The Pallas fused-update kernel (interpret mode) computes the
        EXACT reference expression."""
        monkeypatch.setenv("FLEXFLOW_TPU_PALLAS", "interpret")
        import jax.numpy as jnp
        from flexflow_tpu.ops.fused_update import (_adam_math,
                                                   fused_adam_leaf)
        rs = np.random.RandomState(1)
        p = jnp.asarray(rs.randn(16, 128), jnp.float32)  # lane-aligned
        g = jnp.asarray(rs.randn(16, 128), jnp.bfloat16)
        m = jnp.zeros_like(p)
        v = jnp.zeros_like(p)
        kw = dict(beta1=0.9, beta2=0.999, eps=1e-8, wd=1e-4)
        a1 = _adam_math(p, g, m, v, jnp.float32(1e-2), **kw)
        a2 = fused_adam_leaf(p, g, m, v, jnp.float32(1e-2), **kw)
        for x, y in zip(a1, a2):
            assert np.array_equal(np.asarray(x), np.asarray(y))

    def test_conv_bn_fused_train_step_bitwise(self):
        """`_k:conv_bn_fused` (train-time fused region, batch-stats BN
        with preserved intermediate constraint) is bit-for-bit with the
        unfused pair — params AND BN running stats."""
        import jax

        def build(fused):
            cfg = FFConfig(batch_size=8, seed=42)
            ff = FFModel(cfg)
            x = ff.create_tensor((8, 3, 16, 16), name="x")
            t = ff.conv2d(x, 8, 3, 3, 1, 1, 1, 1, name="c1",
                          use_bias=False)
            t = ff.batch_norm(t, relu=True)
            t = ff.flat(t)
            t = ff.dense(t, 10, name="fc")
            ff.compile(SGDOptimizer(lr=0.01),
                       LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
            if fused:
                ff.executor.kernel_choices = {"c1": "conv_bn_fused"}
            return ff

        rs = np.random.RandomState(0)
        x = rs.randn(8, 3, 16, 16).astype(np.float32)
        y = rs.randint(0, 10, (8, 1)).astype(np.int32)
        states = []
        for fused in (False, True):
            ff = build(fused)
            if fused:
                fused_names = [n.op.name for n in
                               ff.executor._training_nodes()]
                assert any("+" in n for n in fused_names), fused_names
            for _ in range(3):
                ff.fit([x], y, epochs=1, verbose=False)
            states.append([np.asarray(l) for l in
                           jax.tree_util.tree_leaves(
                               (ff.params, ff.state))])
        for a, b in zip(*states):
            assert np.array_equal(a, b)

    def test_flash_vs_einsum_within_tolerance(self, monkeypatch):
        """Forced flash vs pinned einsum attention agree within the
        documented 2e-5 class over a training step (interpret mode)."""
        monkeypatch.setenv("FLEXFLOW_TPU_PALLAS", "interpret")
        import jax

        def build(impl):
            cfg = FFConfig(batch_size=4, seed=42)
            ff = FFModel(cfg)
            x = ff.create_tensor((4, 128, 32), name="x")
            t = ff.multihead_attention(x, x, x, 32, 4, name="attn")
            t = ff.dense(t, 32, name="fc")
            ff.compile(SGDOptimizer(lr=0.01),
                       LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
            for n in ff.executor.nodes:
                if n.op.name == "attn":
                    n.op.kernel_impl = impl
                    assert n.op.selected_impl() == impl
            return ff

        rs = np.random.RandomState(0)
        x = rs.randn(4, 128, 32).astype(np.float32)
        y = rs.randn(4, 128, 32).astype(np.float32)
        leaves = {}
        for impl in ("einsum", "flash"):
            ff = build(impl)
            ff.fit([x], y, epochs=1, verbose=False)
            leaves[impl] = [np.asarray(l) for l in
                            jax.tree_util.tree_leaves(ff.params)]
        diffs = [float(np.max(np.abs(a.astype(np.float64)
                                     - b.astype(np.float64))))
                 for a, b in zip(leaves["einsum"], leaves["flash"])]
        assert max(diffs) < 2e-5, diffs

    def test_forced_flash_falls_back_with_recorded_reason(self,
                                                          monkeypatch):
        monkeypatch.setenv("FLEXFLOW_TPU_PALLAS", "off")
        cfg = FFConfig(batch_size=4, seed=42)
        ff = FFModel(cfg)
        x = ff.create_tensor((4, 128, 32), name="x")
        t = ff.multihead_attention(x, x, x, 32, 4, name="attn")
        t = ff.dense(t, 32, name="fc")
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        attn = next(n.op for n in ff.executor.nodes
                    if n.op.name == "attn")
        attn.kernel_impl = "flash"
        rs = np.random.RandomState(0)
        ff.fit([rs.randn(4, 128, 32).astype(np.float32)],
               rs.randn(4, 128, 32).astype(np.float32),
               epochs=1, verbose=False)
        assert attn._kernel_fallback  # FFL209's runtime signal


class TestDecodeAndReplay:
    def test_strategy_file_roundtrip_with_k_suffix(self, tmp_path):
        ff = _searched_mlp()
        assert any("_k:" in (getattr(s, "choice", "") or "")
                   for s in ff.strategy.values())
        path = str(tmp_path / "s.json")
        from flexflow_tpu.search import unity
        axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
        unity.export_strategy_file(path, axes, ff.strategy,
                                   ff.executor.nodes)
        _, imported = unity.import_strategy_file(path, ff.executor.nodes)
        assert {getattr(s, "choice", None) for s in imported.values()} \
            == {getattr(s, "choice", None) for s in ff.strategy.values()}

    def test_simulate_strategy_replays_executed_kernels(self):
        from flexflow_tpu.search.validate import simulate_strategy
        ff = _searched_mlp()
        resp = simulate_strategy(ff)
        assert resp["iteration_time"] > 0
        assert "cost_sources" in resp

    def test_kernel_choice_of(self):
        from flexflow_tpu.search.unity import kernel_choice_of
        assert kernel_choice_of("dp_wus_ovl_k:fused") == "fused"
        assert kernel_choice_of("dp_head_k:flash") == "flash"
        assert kernel_choice_of("dp_wus") is None
        assert kernel_choice_of(None) is None


class TestCorpusImpl:
    def test_simtrace_rows_carry_impl(self):
        from flexflow_tpu.obs.simtrace import (CORPUS_SCHEMA_VERSION,
                                               corpus_rows)
        from flexflow_tpu.search.validate import simulate_strategy
        assert CORPUS_SCHEMA_VERSION >= 3
        ff = _searched_mlp()
        rows = corpus_rows(ff, simulate_strategy(ff))
        by_name = {r["name"]: r for r in rows}
        fused = [n for n, i in (ff.kernel_choices or {}).items()
                 if i == "fused"]
        assert fused and all(by_name[n]["impl"] == "fused" for n in fused)

    def test_row_class_per_impl(self):
        from flexflow_tpu.costmodel.corpus import row_class, row_impl
        flash_row = dict(type="MULTIHEAD_ATTENTION",
                         choice="dp_head_k:flash")
        assert row_impl(flash_row) == "flash"
        assert row_class(flash_row) == "MULTIHEAD_ATTENTION:flash"
        # v2 row without impl: derived from the choice suffix
        ring_row = dict(type="MULTIHEAD_ATTENTION", choice="dp_ring")
        assert row_impl(ring_row) == "ring"
        assert row_class(ring_row) == "MULTIHEAD_ATTENTION"  # base class
        fused_row = dict(type="LINEAR", choice="dp_wus_k:fused",
                         impl="fused")
        assert row_class(fused_row) == "LINEAR"  # update impl: base
        conv_row = dict(type="CONV2D", choice="dp_k:conv_bn_fused")
        assert row_class(conv_row) == "CONV2D:conv_bn_fused"

    def test_v2_fixture_rows_stay_trainable(self):
        from flexflow_tpu.costmodel.corpus import build_corpus
        corpus = build_corpus([os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tests", "fixtures", "costmodel")])
        assert len(corpus["rows"]) > 50  # the committed v2 corpus loads


class TestFflintKernelRules:
    @pytest.mark.analysis
    def test_ffl208_illegal_flash_shape(self):
        from flexflow_tpu.analysis import lint_model
        cfg = FFConfig(batch_size=4, seed=42)
        ff = FFModel(cfg)
        x = ff.create_tensor((4, 96, 32), name="x")  # 96 % 128 != 0
        t = ff.multihead_attention(x, x, x, 32, 4, name="attn")
        t = ff.dense(t, 32, name="fc")
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        attn_guid = next(n.op.guid for n in ff.executor.nodes
                         if n.op.name == "attn")
        ff.strategy[attn_guid].choice = "dp_k:flash"  # stale/corrupt
        report = lint_model(ff)
        assert any(d.rule == "FFL208" for d in report.diagnostics), \
            [d.rule for d in report.diagnostics]

    @pytest.mark.analysis
    def test_ffl209_platform_fallback_is_info(self, monkeypatch):
        monkeypatch.setenv("FLEXFLOW_TPU_PALLAS", "off")
        from flexflow_tpu.analysis import lint_model
        cfg = FFConfig(batch_size=4, seed=42)
        ff = FFModel(cfg)
        x = ff.create_tensor((4, 128, 32), name="x")  # shape-legal
        t = ff.multihead_attention(x, x, x, 32, 4, name="attn")
        t = ff.dense(t, 32, name="fc")
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        attn_guid = next(n.op.guid for n in ff.executor.nodes
                         if n.op.name == "attn")
        ff.strategy[attn_guid].choice = "dp_k:flash"
        report = lint_model(ff)
        d209 = [d for d in report.diagnostics if d.rule == "FFL209"]
        assert d209 and all(d.severity.name == "INFO" for d in d209)
        assert not any(d.rule == "FFL208" for d in report.diagnostics)


class TestServeProvenance:
    def test_bucket_report_records_kernel_choices(self):
        ff = _searched_mlp()
        eng = ff.serve(batch_buckets=[4], search_budget=0)
        try:
            rep = eng.bucket_report()
        finally:
            eng.stop()
        for b, e in rep.items():
            assert "kernel_choices" in e

    def test_decode_session_records_cached_einsum(self):
        from flexflow_tpu.serve.kv_cache import DecodeSession
        cfg = FFConfig(batch_size=2, seed=42)
        ff = FFModel(cfg)
        x = ff.create_tensor((2, 16, 32), name="x")
        t = ff.multihead_attention(x, x, x, 32, 4, name="attn",
                                   causal=True)
        t = ff.dense(t, 32, name="fc")
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        sess = DecodeSession(ff, batch=2, max_len=16)
        rep = sess.report()
        # recorded at build, never re-derived: the decode path can only
        # ever run the cached einsum, whatever flash availability says
        assert rep["kernel_choices"] == {"attn": "cached_einsum"}

"""Learned TPU cost model (ISSUE 14 tentpole): measure -> learn -> search.

Covers the four layers of flexflow_tpu/costmodel:

- corpus: fixture-trace ingestion, dedup round-trip, schema-drift
  loudness (the CI stage's contract), v1-row skip;
- model: train/predict parity through the COSTMODEL.json round-trip,
  coverage gate, hull-confidence behavior, synthetic-law recovery;
- native integration: per-candidate ``cost_source`` provenance in the
  search trace, measured > learned > analytic priority, out-of-hull
  fallback to analytic pricing, FFS_NO_LEARNED_COSTS bit-identical
  searches on the zoo (the acceptance row);
- validation surfaces: simtrace analytic-vs-learned side-by-side,
  obs_report accuracy block, fflint FFL704 staleness INFO.
"""

from __future__ import annotations

import json
import math
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "costmodel")

pytestmark = pytest.mark.costmodel


# ---------------------------------------------------------------------------
# shared fixtures


@pytest.fixture(scope="module")
def fixture_corpus():
    from flexflow_tpu.costmodel import build_corpus
    return build_corpus([FIXTURES])


@pytest.fixture(scope="module")
def trained(fixture_corpus, tmp_path_factory):
    """(model, path): trained on the committed fixture corpus and
    round-tripped through COSTMODEL.json."""
    from flexflow_tpu.costmodel import CostModel, train_model
    model = train_model(fixture_corpus)
    path = str(tmp_path_factory.mktemp("costmodel") / "COSTMODEL.json")
    model.save(path)
    return CostModel.load(path), path


def small_mlp(budget=1):
    from flexflow_tpu.config import FFConfig
    from flexflow_tpu.ffconst import LossType
    from flexflow_tpu.models.mlp import create_mlp
    from flexflow_tpu.optimizers import SGDOptimizer
    cfg = FFConfig(batch_size=16)
    cfg.search_budget = budget
    cfg.enable_parameter_parallel = True
    ff = create_mlp(batch_size=16, in_dim=64, hidden_dims=(128, 128),
                    out_dim=10, ff_config=cfg)
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
    return ff


def strategy_fingerprint(ff):
    """Order-stable (mesh, per-op choice+specs) identity of a searched
    strategy — the bit-identical comparison coordinate. Keyed by node
    POSITION, not name: auto-names carry the process-global guid
    counter, which differs between two models built in one process
    while the strategies themselves are identical."""
    mesh_axes = dict(zip(ff.mesh.axis_names,
                         (int(d) for d in ff.mesh.devices.shape)))
    ops = []
    for node in ff.executor.nodes:
        st = (ff.strategy or {}).get(node.op.guid)
        ops.append(dict(
            type=node.op.op_type.name,
            choice=getattr(st, "choice", None),
            outputs=[list(s) if s is not None else None
                     for s in (st.output_specs if st else [])],
            params={k: list(v)
                    for k, v in (st.param_specs if st else {}).items()},
        ))
    return json.dumps(dict(mesh=mesh_axes, ops=ops), sort_keys=True)


# ---------------------------------------------------------------------------
# corpus


class TestCorpus:
    def test_fixture_corpus_loads(self, fixture_corpus):
        rows = fixture_corpus["rows"]
        assert len(rows) >= 50
        classes = fixture_corpus["classes"]
        for cname in ("LINEAR", "CONV2D", "MULTIHEAD_ATTENTION"):
            assert classes.get(cname, 0) >= 8, classes
        for r in rows:
            assert r["schema"] == 2
            assert r["measured"]["source"] == "measured"
            assert r["io_bytes"] > 0
            assert r["flops"] >= 0

    def test_featurize_matches_native_transforms(self, fixture_corpus):
        from flexflow_tpu.costmodel import FEATURE_NAMES, featurize
        r = fixture_corpus["rows"][0]
        f = featurize(r)
        assert f.shape == (len(FEATURE_NAMES),)
        div = max(1.0, float(r["work_div"]))
        assert f[0] == pytest.approx(math.log1p(r["flops"] / div))
        assert f[1] == pytest.approx(math.log1p(r["io_bytes"] / div))
        assert f[2] == pytest.approx(math.log1p(r["param_bytes"]))
        assert f[3] == pytest.approx(math.log(div))

    def test_corpus_roundtrip(self, fixture_corpus, tmp_path):
        from flexflow_tpu.costmodel import load_corpus, save_corpus
        p = str(tmp_path / "COSTMODEL_CORPUS.json")
        save_corpus(p, fixture_corpus)
        back = load_corpus(p)
        assert back["corpus_schema"] == fixture_corpus["corpus_schema"]
        assert back["rows"] == fixture_corpus["rows"]

    def test_dedup_across_dirs(self, fixture_corpus, tmp_path):
        """The same dir ingested twice must not double-count rows."""
        from flexflow_tpu.costmodel import build_corpus
        double = build_corpus([FIXTURES, FIXTURES])
        assert len(double["rows"]) == len(fixture_corpus["rows"])
        assert double["stats"]["duplicates"] >= len(fixture_corpus["rows"])

    def test_schema_drift_fails_loudly(self, tmp_path):
        from flexflow_tpu.costmodel import (CORPUS_SCHEMA_VERSION,
                                            CorpusSchemaError,
                                            load_corpus, load_trace_dir)
        src = os.path.join(FIXTURES, "mlp_b16_r00_host00.simtrace.json")
        payload = json.load(open(src))
        payload["corpus_schema"] = CORPUS_SCHEMA_VERSION + 1
        drifted = tmp_path / "drift_r00_host00.simtrace.json"
        drifted.write_text(json.dumps(payload))
        with pytest.raises(CorpusSchemaError):
            load_trace_dir(str(tmp_path))
        # row-level drift too, and through load_corpus
        corpus = dict(schema_version=1,
                      corpus_schema=CORPUS_SCHEMA_VERSION,
                      rows=[dict(schema=CORPUS_SCHEMA_VERSION + 1,
                                 type="LINEAR")])
        cp = tmp_path / "corpus.json"
        cp.write_text(json.dumps(corpus))
        with pytest.raises(CorpusSchemaError):
            load_corpus(str(cp))

    def test_v1_rows_skipped_not_fatal(self):
        """The pre-featurization demo fixture (schema v1 rows) loads as
        zero trainable rows, counted as skipped — not an error."""
        from flexflow_tpu.costmodel import load_trace_dir
        rows, stats = load_trace_dir(
            os.path.join(REPO, "tests", "fixtures", "obs_report_dir"))
        assert rows == []
        assert stats["skipped"] >= 1

    def test_roofline_rows_ingest(self):
        """The committed repo-root roofline reports are corpus rows too
        (the conv-class coverage channel)."""
        from flexflow_tpu.costmodel import load_trace_dir
        rows, stats = load_trace_dir(REPO)
        assert stats["roofline_files"] >= 1
        assert any(r["type"] == "CONV2D" for r in rows)
        assert all(r["measured"]["source"] == "measured" for r in rows)


# ---------------------------------------------------------------------------
# model


class TestModel:
    def test_coverage_gate_and_heldout_error(self, trained):
        model, _ = trained
        for cname in ("LINEAR", "CONV2D", "MULTIHEAD_ATTENTION"):
            assert cname in model.classes
        # classes under MIN_CLASS_ROWS fixture rows stay analytic
        assert "FLAT" not in model.classes
        for cm in model.classes.values():
            assert cm.n_train >= 2
            assert cm.err_fwd >= 0.0
            assert cm.err_factor >= 1.0

    def test_train_predict_parity_roundtrip(self, fixture_corpus,
                                            trained):
        from flexflow_tpu.costmodel import train_model
        fresh = train_model(fixture_corpus)
        loaded, _ = trained
        for r in fixture_corpus["rows"][:20]:
            t1, c1 = fresh.predict(r)
            t2, c2 = loaded.predict(r)
            if t1 is None:
                assert t2 is None
                continue
            # round-trip through JSON (8-decimal coefs) stays within
            # float noise of the in-memory model
            assert t2 == pytest.approx(t1, rel=1e-4)
            assert c2 == pytest.approx(c1, rel=1e-4)

    def test_prediction_tracks_measured(self, fixture_corpus, trained):
        """On in-corpus LINEAR rows the learned prediction lands within
        ~3x of the measurement (CPU microbench noise) — versus the
        analytic roofline which misses by orders of magnitude here."""
        model, _ = trained
        ratios = []
        for r in fixture_corpus["rows"]:
            if r["type"] != "LINEAR":
                continue
            t, conf = model.predict(r)
            if t is None or conf < 0.3:
                continue
            true = float(r["measured"]["fwd_s"]) / max(
                1.0, float(r["work_div"]))
            ratios.append(t / true)
        assert len(ratios) >= 10
        med = sorted(abs(math.log(x)) for x in ratios)[len(ratios) // 2]
        assert math.exp(med) < 3.0

    def test_low_confidence_outside_hull(self, fixture_corpus, trained):
        model, _ = trained
        r = next(r for r in fixture_corpus["rows"]
                 if r["type"] == "LINEAR")
        t_in, c_in = model.predict(r)
        far = dict(r, flops=r["flops"] * 1e9, io_bytes=r["io_bytes"] * 1e9)
        t_out, c_out = model.predict(far)
        assert c_in > 0.5
        assert c_out < 0.05 * max(c_in, 1e-9) or c_out < 1e-3
        assert model.in_hull(r) and not model.in_hull(far)

    def test_unknown_class_none(self, trained):
        model, _ = trained
        t, c = model.predict(dict(type="NO_SUCH_OP", flops=1e6,
                                  io_bytes=1e5, param_bytes=0,
                                  work_div=1))
        assert t is None and c == 0.0

    def test_synthetic_law_recovery(self):
        """A corpus generated from a pure power law is recovered to
        within a few percent — the regression itself is sound."""
        from flexflow_tpu.costmodel import train_model
        rows = []
        rs = np.random.RandomState(7)
        for i in range(64):
            flops = float(10 ** rs.uniform(5, 9))
            io = float(10 ** rs.uniform(4, 8))
            t = 3e-4 * (flops / 1e8) ** 0.8 * (io / 1e6) ** 0.1
            rows.append(dict(
                schema=2, type="LINEAR", out_shape=[i], choice="dp",
                work_div=1, flops=flops, io_bytes=io, param_bytes=io / 3,
                dtype_size=4, mesh_axes={}, platform="cpu",
                measured=dict(fwd_s=t, bwd_s=2 * t, source="measured")))
        model = train_model(dict(rows=rows))
        errs = []
        for r in rows:
            t, _ = model.predict(r)
            errs.append(abs(math.log(t / r["measured"]["fwd_s"])))
        assert math.exp(float(np.median(errs))) < 1.05

    def test_platform_gate(self, trained, tmp_path, monkeypatch):
        """A model trained on another platform's corpus never engages
        (load_native_table returns None), same discipline as the
        collective_corrections platform buckets."""
        from flexflow_tpu.costmodel import CostModel, load_native_table
        model, path = trained
        assert load_native_table(path, platform="cpu") is not None
        assert load_native_table(path, platform="tpu") is None
        monkeypatch.setenv("FFS_NO_LEARNED_COSTS", "1")
        assert load_native_table(path, platform="cpu") is None


# ---------------------------------------------------------------------------
# native integration


def _tiny_nodes():
    roles = [["sample", "channel"]]
    return [
        dict(guid=1, type="INPUT", name="x", inputs=[], input_shapes=[],
             output_shapes=[[32, 64]], roles=roles, params={},
             flops=0.0, dtype_size=4, attrs={}),
        dict(guid=2, type="LINEAR", name="dense1", inputs=[[1, 0]],
             input_shapes=[[32, 64]], output_shapes=[[32, 128]],
             roles=roles, params={"kernel": [64, 128], "bias": [128]},
             flops=32 * 64 * 128 * 2.0, dtype_size=4, attrs={}),
        dict(guid=3, type="LINEAR", name="dense2", inputs=[[2, 0]],
             input_shapes=[[32, 128]], output_shapes=[[32, 10]],
             roles=roles, params={"kernel": [128, 10], "bias": [10]},
             flops=32 * 128 * 10 * 2.0, dtype_size=4, attrs={}),
    ]


def _machine(**kw):
    m = dict(num_devices=8, flops=1e12, hbm_bw=1e11, hbm_cap=16e9,
             ici_bw=1e10, ici_latency=1e-6, dcn_bw=1e9, dcn_latency=1e-5,
             num_slices=1, mxu_efficiency=0.55, conv_efficiency=0.35,
             min_op_time=5e-7, comm_bytes_factor=1.0, torus=[])
    m.update(kw)
    return m


def _wide_table(trained_model):
    """The trained native table with the hull opened wide so the tiny
    test graph's features land inside it."""
    tab = trained_model.native_table()
    for c in tab["classes"].values():
        c["fmin"] = [-100.0] * 4
        c["fmax"] = [100.0] * 4
    return tab


class TestNativeIntegration:
    def _simulate(self, machine, measured=None):
        from flexflow_tpu.search.native import native_simulate
        return native_simulate(dict(
            nodes=_tiny_nodes(), machine=machine,
            config=dict(training=True, overlap=True,
                        opt_state_factor=0.0),
            mesh=dict(data=8, model=1, seq=1, expert=1, pipe=1),
            assignment={"1": "rep", "2": "dp", "3": "dp"},
            measured=measured or {}))

    def test_search_trace_records_cost_source(self, trained):
        from flexflow_tpu.search.native import native_optimize
        model, _ = trained
        resp = native_optimize(dict(
            nodes=_tiny_nodes(),
            machine=_machine(learned=_wide_table(model)),
            config=dict(budget=1, training=True, batch=32,
                        enable_substitution=False,
                        emit_search_trace=True),
            measured={}))
        cands = [c for op in resp["search_trace"]["ops"]
                 for c in op["candidates"]]
        assert all(c["cost_source"] in ("learned", "analytic", "measured")
                   for c in cands)
        learned_cands = [c for c in cands if c["cost_source"] == "learned"]
        assert learned_cands, "no candidate was priced by the learned model"
        # the side-by-side columns explain.py's disagreement table reads
        for c in learned_cands:
            assert "compute_analytic_s" in c["terms"]
            assert "compute_learned_s" in c["terms"]

    def test_trace_all_analytic_without_table(self):
        from flexflow_tpu.search.native import native_optimize
        resp = native_optimize(dict(
            nodes=_tiny_nodes(), machine=_machine(),
            config=dict(budget=1, training=True, batch=32,
                        enable_substitution=False,
                        emit_search_trace=True),
            measured={}))
        cands = [c for op in resp["search_trace"]["ops"]
                 for c in op["candidates"]]
        assert {c["cost_source"] for c in cands} == {"analytic"}
        assert all("compute_learned_s" not in c["terms"] for c in cands)

    def test_out_of_hull_falls_back_to_analytic(self, trained):
        model, _ = trained
        tab = _wide_table(model)
        plain = self._simulate(_machine())
        priced = self._simulate(_machine(learned=tab))
        assert priced["cost_sources"]["2"] == "learned"
        far = dict(tab, classes={
            k: dict(v, fmin=[90.0] * 4, fmax=[100.0] * 4)
            for k, v in tab["classes"].items()})
        fell_back = self._simulate(_machine(learned=far))
        assert all(v in ("analytic",)
                   for v in fell_back["cost_sources"].values())
        assert fell_back["iteration_time"] == plain["iteration_time"]

    def test_measured_overrides_learned(self, trained):
        model, _ = trained
        resp = self._simulate(_machine(learned=_wide_table(model)),
                              measured={"2:fwd": 1e-3})
        assert resp["cost_sources"]["2"] == "measured"
        assert resp["cost_sources"]["3"] == "learned"


# ---------------------------------------------------------------------------
# end-to-end: search wiring + opt-out parity (acceptance rows)


class TestSearchWiring:
    def test_no_learned_costs_bit_identical(self, trained, monkeypatch):
        """With FFS_NO_LEARNED_COSTS=1 a searched zoo strategy is
        bit-identical to the no-model search, even with a trained
        COSTMODEL.json present."""
        _, path = trained
        monkeypatch.delenv("FFS_COSTMODEL_FILE", raising=False)
        monkeypatch.delenv("FFS_NO_LEARNED_COSTS", raising=False)
        base = small_mlp()
        assert base.search_info.get("cost_model") == "analytic"
        fp_base = strategy_fingerprint(base)
        monkeypatch.setenv("FFS_COSTMODEL_FILE", path)
        monkeypatch.setenv("FFS_NO_LEARNED_COSTS", "1")
        opted_out = small_mlp()
        assert opted_out.search_info.get("cost_model") == "analytic"
        assert strategy_fingerprint(opted_out) == fp_base

    def test_learned_model_engages_in_search(self, trained, monkeypatch):
        _, path = trained
        monkeypatch.setenv("FFS_COSTMODEL_FILE", path)
        monkeypatch.delenv("FFS_NO_LEARNED_COSTS", raising=False)
        ff = small_mlp()
        info = ff.search_info
        assert info.get("cost_model") == "learned"
        assert "LINEAR" in info.get("learned_cost_classes", [])

    def test_simtrace_side_by_side(self, trained, monkeypatch):
        """simulate_strategy(learned=False) is the control arm; the
        simtrace report carries cost_sources and the analytic twin."""
        from flexflow_tpu.obs.simtrace import simtrace_report
        from flexflow_tpu.search.validate import simulate_strategy
        _, path = trained
        monkeypatch.setenv("FFS_COSTMODEL_FILE", path)
        monkeypatch.delenv("FFS_NO_LEARNED_COSTS", raising=False)
        ff = small_mlp()
        resp = simulate_strategy(ff)
        srcs = set((resp.get("cost_sources") or {}).values())
        assert "learned" in srcs
        resp_an = simulate_strategy(ff, learned=False)
        assert set(resp_an["cost_sources"].values()) == {"analytic"}
        report = simtrace_report(ff, resp, resp_analytic=resp_an)
        assert report["corpus_schema"] == 3
        assert report["cost_sources"].get("learned", 0) >= 1
        assert report["predicted_analytic"]["step_s"] == \
            resp_an["iteration_time"]
        for row in report["per_op"]:
            assert row["priced"]["source"] in ("learned", "analytic",
                                               "measured")


# ---------------------------------------------------------------------------
# validation surfaces


class TestValidationSurfaces:
    def test_obs_report_accuracy_block(self, tmp_path):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "_obs_report", os.path.join(REPO, "scripts", "obs_report.py"))
        obs = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(obs)
        sim = dict(
            corpus_schema=2,
            predicted=dict(step_s=0.010),
            predicted_analytic=dict(step_s=0.002),
            cost_sources=dict(learned=3, analytic=2),
            mesh_axes={"data": 8}, tasks=5, per_op=[],
            header=dict(run_name="demo", platform="cpu", host_id=0))
        counters = dict(
            observations={"demo/step_time_s": dict(p50=0.012, p99=0.02)},
            gauges={}, header=dict(run_name="demo", platform="cpu"))
        (tmp_path / "demo_r00_host00.simtrace.json").write_text(
            json.dumps(sim))
        (tmp_path / "demo_r00_host00.counters.json").write_text(
            json.dumps(counters))
        report = obs.build_report(str(tmp_path))
        row = report["runs"][0]
        s = row["sim"]
        assert s["predicted_vs_measured"] == pytest.approx(0.01 / 0.012,
                                                           abs=1e-3)
        assert s["predicted_analytic_step_s"] == pytest.approx(0.002)
        assert s["predicted_vs_measured_analytic"] == pytest.approx(
            0.002 / 0.012, abs=1e-3)
        assert s["cost_sources"] == dict(learned=3, analytic=2)
        md = obs.to_markdown(report)
        assert "Simulator accuracy" in md
        assert "learned:3" in md

    def test_costmodel_cli_train_and_report(self, tmp_path):
        """The CI stage's contract: train on the committed fixtures
        produces COSTMODEL.json; report renders the accuracy block."""
        import subprocess
        out = tmp_path / "COSTMODEL.json"
        corpus = tmp_path / "COSTMODEL_CORPUS.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "costmodel.py"),
             "train", "--trace-dir", FIXTURES, "--corpus", str(corpus),
             "--out", str(out)],
            capture_output=True, text=True, env=env, timeout=120)
        assert r.returncode == 0, r.stderr
        assert out.exists() and corpus.exists()
        model = json.load(open(out))
        assert model["schema_version"] == 1
        assert "LINEAR" in model["classes"]
        r2 = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "costmodel.py"),
             "report", "--model", str(out), "--corpus", str(corpus)],
            capture_output=True, text=True, env=env, timeout=120)
        assert r2.returncode == 0, r2.stderr
        assert "Simulator accuracy on the corpus" in r2.stdout
        assert "analytic" in r2.stdout

    def test_fflint_ffl704_stale_model(self, trained, tmp_path,
                                       monkeypatch):
        """INFO diagnostic when the search was priced by a learned
        model whose held-out error exceeds the calibration tolerance."""
        from flexflow_tpu.analysis import run_passes
        from flexflow_tpu.analysis.passes.calibration import CalibrationPass
        from flexflow_tpu.costmodel import CostModel
        model, _ = trained
        # inflate every class's held-out error past tolerance
        stale = json.loads(json.dumps(model.to_json()))
        for c in stale["classes"].values():
            c["err_fwd"] = 1.0  # e^1 ~ 2.7x >> 1.25x tolerance
        stale_path = tmp_path / "COSTMODEL.json"
        stale_path.write_text(json.dumps(stale))
        monkeypatch.setenv("FFS_COSTMODEL_FILE", str(stale_path))
        monkeypatch.delenv("FFS_NO_LEARNED_COSTS", raising=False)
        from flexflow_tpu.analysis import LintContext

        def ctx_of(ff):
            ctx = LintContext(
                nodes=ff.executor.nodes, mesh=ff.mesh,
                strategy=ff.strategy, machine_spec=ff.machine_spec,
                config=ff.config, final_ref=ff.executor.final_ref, ff=ff)
            ctx.searched = True
            return ctx

        ff = small_mlp()
        assert ff.search_info.get("cost_model") == "learned"
        diags = run_passes(ctx_of(ff), [CalibrationPass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL704"]
        assert hits and "LINEAR" in "".join(d.message for d in hits)
        # healthy model (fixture-trained errors are modest but may
        # exceed tolerance for noisy classes) — with the opt-out set,
        # no FFL704 regardless
        monkeypatch.setenv("FFS_NO_LEARNED_COSTS", "1")
        ff2 = small_mlp()
        assert ff2.search_info.get("cost_model") == "analytic"
        diags2 = run_passes(ctx_of(ff2), [CalibrationPass()]).diagnostics
        assert not [d for d in diags2 if d.rule == "FFL704"]

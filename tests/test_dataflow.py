"""Edge-level sharding dataflow tests (flexflow_tpu/analysis/dataflow.py).

The static arbiter for implicit GSPMD reshards: per-op transfer rules
(``required_input_specs``), the src→dst collective classifier
(``classify_transition`` — the set-logic mirror of native
``reshard_cost``), the per-edge ``EdgeReshard`` table, the generalized
tiny-batch weight-movement rule, and the substitution-engine hook
(``verify_rewrite_dataflow``). Plus the seeded-violation tests for the
edge-level fflint rules FFL205 (ERROR since the edge table exists),
FFL210 (unpriced edge reshard), FFL211 (redundant reshard pair),
FFL212 (replicated materialization), FFL213 (rewrite regressed the
edge-spec map), and the census-parity tests proving the Python edge
rule reproduces the native simulator's tiny-batch weight-gather bytes
on searched XDL (seeded row-parallel choice) and ResNet (organic).
"""

import types

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from flexflow_tpu import (FFConfig, FFModel, LossType, SGDOptimizer,
                          Severity)
from flexflow_tpu.analysis import (LintContext, classify_transition,
                                   edge_reshard_table,
                                   required_input_specs, run_passes,
                                   verify_rewrite_dataflow,
                                   weight_movement_edges)
from flexflow_tpu.analysis.dataflow import (ANY, _TableCtx, _out_entries,
                                            _param_spec)
from flexflow_tpu.analysis.passes.collectives import CollectiveInferencePass

pytestmark = pytest.mark.analysis

AXES = {"data": 2, "model": 4}
# every kind priced huge: the FFL204/FFL210 unpriced checks stay quiet
# so a test can assert ONE rule in isolation
PRICED_ALL = {"allreduce": 1e9, "allgather": 1e9, "reshard": 1e9,
              "ppermute": 1e9}


def stub_mesh(**axes):
    return types.SimpleNamespace(axis_names=tuple(axes),
                                 devices=np.zeros(tuple(axes.values())))


def _compile(ff):
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
    return ff


def relu_chain(batch=64, width=128, n=3):
    ff = FFModel(FFConfig(batch_size=batch))
    t = ff.create_tensor((batch, width))
    for _ in range(n):
        t = ff.relu(t)
    t = ff.dense(t, 10)
    return _compile(ff)


def relus(ff):
    return [n for n in ff.executor.nodes if n.op.op_type.name == "RELU"]


def ctx_of(ff, mesh=None, **kw):
    return LintContext(nodes=ff.executor.nodes,
                       mesh=mesh or stub_mesh(**AXES),
                       strategy=ff.strategy, machine_spec=ff.machine_spec,
                       config=ff.config, final_ref=ff.executor.final_ref,
                       ff=ff, **kw)


def reqs_of(ctx, node):
    return required_input_specs(
        node,
        lambda n: _out_entries(ctx, n, 0),
        lambda n, name: _param_spec(ctx, n, name))


class TestClassifyTransition:
    SHAPE = (64, 128)  # 32768 B at fp32

    def test_equal_specs_move_nothing(self):
        assert classify_transition(("data", None), ("data", None),
                                   self.SHAPE, AXES) is None

    def test_size_one_axes_are_dropped(self):
        # sharding over a size-1 (or absent) axis is replication
        assert classify_transition(("model", None), (None, None),
                                   self.SHAPE, {"model": 1}) is None

    def test_additional_slicing_is_local(self):
        cls = classify_transition((None, None), ("data", None),
                                  self.SHAPE, AXES)
        assert cls["kind"] == "slice" and cls["bytes"] == 0.0
        assert cls["axes"] == ("data",) and cls["fabric"] == "ici"

    def test_full_allgather_bytes(self):
        cls = classify_transition(("model", None), (None, None),
                                  self.SHAPE, AXES)
        assert cls["kind"] == "allgather"
        # dst is replicated: every device receives the global tensor
        assert cls["bytes"] == 64 * 128 * 4.0
        assert cls["axes"] == ("model",)

    def test_partial_allgather_keeps_dst_shard(self):
        cls = classify_transition(("data", "model"), ("data", None),
                                  self.SHAPE, AXES)
        assert cls["kind"] == "allgather"
        assert cls["bytes"] == 64 * 128 * 4.0 / 2  # deg(dst) = data = 2
        assert cls["axes"] == ("model",)

    def test_mixed_transition_is_reshard(self):
        cls = classify_transition(("model", None), (None, "model"),
                                  self.SHAPE, AXES)
        assert cls["kind"] == "reshard"
        assert cls["bytes"] == 64 * 128 * 4.0 / 4  # max(ka, kb) = 4

    def test_multislice_prefix_rides_the_dcn(self):
        axes = {"slice": 2, "data": 2}
        # dropping the ('slice','data') prefix back to plain 'data'
        # gathers over the slice axis: cross-slice traffic
        cls = classify_transition((("slice", "data"), None),
                                  ("data", None), self.SHAPE, axes)
        assert cls["kind"] == "allgather"
        assert cls["axes"] == ("slice",) and cls["fabric"] == "dcn"
        assert cls["bytes"] == 64 * 128 * 4.0 / 2

    def test_element_width_scales_bytes(self):
        cls = classify_transition(("model", None), (None, None),
                                  self.SHAPE, AXES, elem=2.0)
        assert cls["bytes"] == 64 * 128 * 2.0


class TestRequiredInputSpecs:
    def test_linear_row_parallel_wants_contraction_sharded(self):
        from flexflow_tpu.models.mlp import create_mlp
        ff = _compile(create_mlp(batch_size=16, in_dim=64,
                                 hidden_dims=(128,), out_dim=10,
                                 ff_config=FFConfig(batch_size=16)))
        lin = next(n for n in ff.executor.nodes
                   if n.op.op_type.name == "LINEAR")
        lin.output_specs[0] = P("data", None)
        lin.param_specs["kernel"] = ("model", None)  # row-parallel
        ctx = ctx_of(ff)
        assert reqs_of(ctx, lin)[0] == ("data", "model")
        # col-parallel keeps the input contraction dim whole
        lin.param_specs["kernel"] = (None, "model")
        assert reqs_of(ctx, lin)[0] == ("data", None)

    def test_conv_row_parallel_wants_in_channels_sharded(self):
        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 4, 16, 16))
        t = ff.conv2d(t, 8, 3, 3, 1, 1, 1, 1)
        t = ff.flat(t)
        t = ff.dense(t, 10)
        _compile(ff)
        conv = next(n for n in ff.executor.nodes
                    if n.op.op_type.name == "CONV2D")
        conv.output_specs[0] = P("data")
        conv.param_specs["kernel"] = (None, "model", None, None)  # OIHW
        req = reqs_of(ctx_of(ff), conv)[0]
        assert req == ("data", "model", None, None)

    def test_transpose_permutes_the_requirement(self):
        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 16, 32))
        t = ff.transpose(t, (0, 2, 1))
        t = ff.flat(t)
        t = ff.dense(t, 10)
        _compile(ff)
        tr = next(n for n in ff.executor.nodes
                  if n.op.op_type.name == "TRANSPOSE")
        tr.output_specs[0] = P("data", "model", None)  # out is (8, 32, 16)
        # out dim j carries in dim perm[j]: the 'model' on out dim 1
        # must arrive on in dim 2
        assert reqs_of(ctx_of(ff), tr)[0] == ("data", None, "model")

    def test_flat_transfers_the_leading_dim_only(self):
        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 4, 16, 16))
        t = ff.flat(t)
        t = ff.dense(t, 10)
        _compile(ff)
        fl = next(n for n in ff.executor.nodes
                  if n.op.op_type.name == "FLAT")
        fl.output_specs[0] = P("data", "model")
        # batch survives the reshape; the folded (4,16,16) group cannot
        # inherit the flattened dim's 'model' sharding
        assert reqs_of(ctx_of(ff), fl)[0] == ("data", None, None, None)

    def test_concat_drops_the_seam_axis(self):
        ff = FFModel(FFConfig(batch_size=8))
        a = ff.create_tensor((8, 32))
        b = ff.create_tensor((8, 32))
        t = ff.concat([a, b], axis=1)
        t = ff.dense(t, 10)
        _compile(ff)
        cc = next(n for n in ff.executor.nodes
                  if n.op.op_type.name == "CONCAT")
        cc.output_specs[0] = P("data", "model")
        for req in reqs_of(ctx_of(ff), cc):
            assert req == ("data", None)

    def test_attention_follows_batch_and_seq(self):
        from flexflow_tpu.models.transformer import (TransformerConfig,
                                                     create_transformer)
        ff = create_transformer(
            TransformerConfig(num_layers=1, hidden_size=32, num_heads=2,
                              seq_length=16, batch_size=8),
            FFConfig(batch_size=8))
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        att = next(n for n in ff.executor.nodes
                   if n.op.op_type.name == "MULTIHEAD_ATTENTION")
        att.output_specs[0] = P("data", "seq", None)
        ctx = ctx_of(ff, mesh=stub_mesh(data=2, seq=2, model=2))
        for req in reqs_of(ctx, att):
            # B and S follow the output (ring attention rotates K/V via
            # the priced ppermute, not an edge); E stays whole
            assert req[0] == "data" and req[1] == "seq"
            assert all(e is None for e in req[2:])

    def test_parallel_op_inputs_accept_anything(self):
        ff = FFModel(FFConfig(batch_size=8))
        t = ff.create_tensor((8, 64))
        t = ff.repartition(t, dim=0, degree=8, axis="data")
        t = ff.dense(t, 10)
        _compile(ff)
        par = next(n for n in ff.executor.nodes
                   if getattr(n.op, "is_parallel_op", False))
        assert all(r is ANY for r in reqs_of(ctx_of(ff), par))


class TestEdgeTable:
    def test_clean_data_parallel_chain_has_no_moves(self):
        ff = relu_chain()
        table = edge_reshard_table(ctx_of(ff))
        assert all(e.kind == "slice" or e.explicit for e in table), [
            e.to_json() for e in table]

    def test_seeded_disagreement_yields_one_edge_per_seam(self):
        ff = relu_chain()
        r = relus(ff)
        r[0].output_specs[0] = P("model", None)
        table = edge_reshard_table(ctx_of(ff))
        seams = [e for e in table if e.producer == r[0].op.name
                 and not e.explicit]
        assert len(seams) == 1
        e = seams[0]
        assert e.kind in ("allgather", "reshard") and e.bytes > 0
        assert e.edge == (f"{r[0].op.name}.out[0] -> "
                          f"{r[1].op.name}.in[0]")
        assert e.to_json()["src_spec"] == "(model, ·)"

    def test_pipe_hop_is_explicit_ppermute(self):
        ff = relu_chain()
        nodes = ff.executor.nodes
        r = relus(ff)
        r[0].output_specs[0] = P("model", None)  # seam at r0 -> r1
        cut = nodes.index(r[1])
        stub_ff = types.SimpleNamespace(executor=types.SimpleNamespace(
            pb=types.SimpleNamespace(blocks=[
                list(range(cut)), list(range(cut, len(nodes)))])))
        ctx = _TableCtx(nodes, {}, {"data": 2, "model": 4, "pipe": 2},
                        ff=stub_ff)
        hop = [e for e in edge_reshard_table(ctx)
               if e.producer == r[0].op.name]
        assert hop and hop[0].kind == "ppermute"
        assert hop[0].reason == "pipe-hop" and hop[0].explicit

    def test_weight_movement_fires_on_tiny_batch_row_parallel(self):
        ff = relu_chain(batch=16, width=64, n=1)
        lin = next(n for n in ff.executor.nodes
                   if n.op.op_type.name == "LINEAR")
        lin.output_specs[0] = P("data", None)
        lin.param_specs["kernel"] = ("model", None)
        moves = weight_movement_edges(ctx_of(ff))
        assert [e.producer for e in moves] == [lin.op.name]
        e = moves[0]
        assert e.kind == "allgather" and e.in_idx == -1
        assert e.bytes == float(lin.op.params_elems()) * 4.0
        assert e.reason == "tiny-batch weight movement"
        # col-parallel output moves the activation, never the weight
        lin.output_specs[0] = P("data", "model")
        ctx2 = ctx_of(ff)
        assert not weight_movement_edges(ctx2)


class TestEdgeRules:
    """Seeded violations for the edge-attributed fflint rules."""

    def test_unpriced_edge_without_simulator_fires_ffl205_error(self):
        ff = relu_chain()
        r = relus(ff)
        r[0].output_specs[0] = P("model", None)
        # no model, no simulator, not searched: nothing EVER priced this
        ctx = LintContext(nodes=ff.executor.nodes, mesh=stub_mesh(**AXES),
                          strategy={}, ff=None)
        diags = run_passes(ctx, [CollectiveInferencePass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL205"]
        assert hits and all(d.severity == Severity.ERROR for d in hits)
        seam = next(d for d in hits if d.op == r[1].op.name)
        assert "->" in seam.message and seam.tensor == "in[0]"
        assert "(model, ·)" in seam.message

    def test_priced_edge_keeps_ffl205_quiet(self):
        ff = relu_chain()
        relus(ff)[0].output_specs[0] = P("model", None)
        ctx = ctx_of(ff, priced=dict(PRICED_ALL))
        diags = run_passes(ctx, [CollectiveInferencePass()]).diagnostics
        assert not [d for d in diags if d.rule in ("FFL205", "FFL210")]

    def test_zero_priced_edge_fires_ffl210_error(self):
        ff = relu_chain()
        r = relus(ff)
        r[0].output_specs[0] = P("model", None)
        ctx = ctx_of(ff, priced={})  # simulator replayed, charged nothing
        diags = run_passes(ctx, [CollectiveInferencePass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL210"]
        assert hits and all(d.severity == Severity.ERROR for d in hits)
        assert any(d.op == r[1].op.name and d.tensor == "in[0]"
                   for d in hits)
        assert "unpriced edge reshard" in hits[0].message

    def test_round_trip_reshard_pair_fires_ffl211(self):
        ff = relu_chain()
        r = relus(ff)
        r[0].output_specs[0] = P("model", None)
        r[1].output_specs[0] = P(None, "model")
        r[2].output_specs[0] = P("model", None)  # back where it started
        ctx = ctx_of(ff, priced=dict(PRICED_ALL))
        diags = run_passes(ctx, [CollectiveInferencePass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL211"]
        assert hits and hits[0].severity == Severity.WARNING
        assert "round trip" in hits[0].message
        assert hits[0].op == r[1].op.name

    def test_replicated_materialization_fires_ffl212(self):
        ff = relu_chain(batch=64, width=512)
        r = relus(ff)
        r[0].output_specs[0] = None          # materialized replicated
        # a None node spec falls through to the strategy map — drop the
        # default data-parallel entry so the output really is replicated
        ff.strategy.pop(r[0].op.guid, None)
        r[1].output_specs[0] = P("data", None)  # ... then sharded
        ctx = ctx_of(ff, priced=dict(PRICED_ALL))
        diags = run_passes(ctx, [CollectiveInferencePass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL212"]
        assert hits and hits[0].severity == Severity.WARNING
        assert hits[0].op == r[0].op.name
        assert hits[0].tensor == "out[0]"

    def test_recorded_rewrite_regression_fires_ffl213(self):
        ff = relu_chain()
        # what graph_optimize records when verify_rewrite_dataflow
        # rejects an accepted substitution (search/unity.py)
        ff.search_info = dict(rewrite_verification=dict(
            ok=False, findings=[dict(
                kind="reshard", pre_bytes=1 << 20, post_bytes=5 << 20,
                edge="fused_a_b.out[0] -> consumer.in[0]",
                src_spec="(data, ·)", dst_spec="(·, model)")]))
        ctx = ctx_of(ff, priced=dict(PRICED_ALL))
        diags = run_passes(ctx, [CollectiveInferencePass()]).diagnostics
        hits = [d for d in diags if d.rule == "FFL213"]
        assert hits and hits[0].severity == Severity.ERROR
        assert "rewrite" in hits[0].message
        assert "fused_a_b.out[0] -> consumer.in[0]" in hits[0].message

    def test_clean_rewrite_verification_stays_quiet(self):
        ff = relu_chain()
        ff.search_info = dict(rewrite_verification=dict(ok=True,
                                                        findings=[]))
        ctx = ctx_of(ff, priced=dict(PRICED_ALL))
        diags = run_passes(ctx, [CollectiveInferencePass()]).diagnostics
        assert not [d for d in diags if d.rule == "FFL213"]


class TestVerifyRewrite:
    def test_equivalent_graphs_verify_ok(self):
        pre, post = relu_chain(), relu_chain()
        res = verify_rewrite_dataflow(pre.executor.nodes,
                                      post.executor.nodes, {}, dict(AXES))
        assert res["ok"] and not res["findings"]

    def test_regressed_edge_map_is_flagged(self):
        pre, post = relu_chain(), relu_chain()
        # post-rewrite graph opened a reshard seam the pre graph lacked
        r = relus(post)
        r[0].output_specs[0] = P("model", None)
        r[1].output_specs[0] = P(None, "model")
        res = verify_rewrite_dataflow(pre.executor.nodes,
                                      post.executor.nodes, {}, dict(AXES))
        assert not res["ok"]
        f = res["findings"][0]
        assert f["kind"] == "reshard"
        assert f["post_bytes"] > f["pre_bytes"]
        assert f["edge"] and "->" in f["edge"]


class TestWeightMovementCensusParity:
    """The tiny-batch weight-movement special case left
    passes/collectives.py for the general edge rule
    (dataflow.weight_movement_edges); native
    detail::tiny_batch_weight_movement (ffs_strategy.hpp) prices the
    same gather. These tests pin the two to BYTE-EXACT parity: the
    Python rule's per-op gather bytes must equal the native simulator's
    per-node forward weight all-gather tasks — on searched ResNet
    (row-parallel conv choices arise organically at budget 4) and on
    searched XDL with a row-parallel Linear choice seeded in (the
    search organically picks none there)."""

    def _searched(self, name):
        from flexflow_tpu.search.native import available
        if not available():
            pytest.skip("native search unavailable")
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "_ffs_fflint_dataflow", os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "scripts", "fflint.py"))
        cli = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cli)
        cfg = FFConfig()
        cfg.search_budget = 4
        cfg.enable_parameter_parallel = True
        cfg.enable_pipeline_parallel = False
        ff, loss_kind = cli.build_model(name, cfg)
        cli.compile_model(ff, loss_kind)
        return ff

    def _native_wgather(self, ff):
        """Per-op forward weight all-gather bytes the native simulator
        schedules: comm tasks carrying 'allgather' on LINEAR/CONV2D
        nodes (parallel-op boundary gathers live on their own nodes)."""
        from flexflow_tpu.search.validate import simulate_strategy
        resp = simulate_strategy(ff)
        nodes = ff.executor.nodes
        out = {}
        for t in resp.get("tasks", []):
            if t.get("kind") != "comm" or t.get("collective") != "allgather":
                continue
            n = nodes[t["node"]]
            if n.op.op_type.name in ("LINEAR", "CONV2D"):
                out[n.op.name] = out.get(n.op.name, 0.0) + t["bytes"]
        return out

    def _python_wmoves(self, ff):
        ctx = LintContext(
            nodes=ff.executor.nodes, mesh=ff.mesh, strategy=ff.strategy,
            machine_spec=ff.machine_spec, config=ff.config,
            final_ref=ff.executor.final_ref, ff=ff)
        return {e.producer: e.bytes for e in weight_movement_edges(ctx)}

    def test_searched_resnet_organic_parity(self):
        ff = self._searched("resnet")
        moves = self._python_wmoves(ff)
        native = self._native_wgather(ff)
        assert moves, ("searched resnet no longer picks row-parallel "
                       "choices — the parity fixture went stale")
        assert set(moves) == set(native), (moves, native)
        for name, b in moves.items():
            assert b == pytest.approx(native[name]), (name, b, native)

    def test_seeded_xdl_row_parallel_parity(self):
        ff = self._searched("xdl")
        model_deg = dict(zip(ff.mesh.axis_names,
                             ff.mesh.devices.shape)).get("model", 1)
        if model_deg <= 1:
            pytest.skip("searched xdl mesh carries no model axis")
        # the search picks no row-parallel choice on xdl organically —
        # seed one on a Linear whose shapes satisfy both gates (the
        # weight is bigger than the output; rows fit one MXU tile)
        lin = next(
            n for n in ff.executor.nodes
            if n.op.op_type.name == "LINEAR"
            and n.op.input_shapes[0][-1] % model_deg == 0
            and n.op.params_elems() > np.prod(n.op.output_shapes[0]))
        st = ff.strategy[lin.op.guid]
        st.choice = "dp_row"
        st.output_specs[0] = P("data", None)
        st.param_specs["kernel"] = P("model", None)
        lin.output_specs[0] = P("data", None)
        lin.param_specs["kernel"] = ("model", None)
        moves = self._python_wmoves(ff)
        assert set(moves) == {lin.op.name}, moves
        native = self._native_wgather(ff)
        assert lin.op.name in native, (
            "native replay priced no weight gather for the seeded "
            "row-parallel choice", native)
        assert moves[lin.op.name] == pytest.approx(native[lin.op.name])

"""Llama model family (BASELINE.md stretch): RMSNorm + RoPE + GQA + SwiGLU,
numerics-checked against HuggingFace LlamaForCausalLM."""

import numpy as np
import pytest

from flexflow_tpu import FFConfig, LossType, SGDOptimizer
from flexflow_tpu.ffconst import MetricsType
from flexflow_tpu.models.llama import (LlamaModelConfig, create_llama,
                                       import_hf_weights)


def _compiled(cfg, **ffkw):
    ff = create_llama(cfg, FFConfig(batch_size=cfg.batch_size, **ffkw))
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
    return ff


class TestLlama:
    def test_logits_match_hf(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        hf_cfg = transformers.LlamaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            rms_norm_eps=1e-6, rope_theta=10000.0,
            attention_bias=False, tie_word_embeddings=False)
        torch.manual_seed(0)
        hf = transformers.LlamaForCausalLM(hf_cfg).eval()

        cfg = LlamaModelConfig(batch_size=2, seq_length=16)
        ff = _compiled(cfg, only_data_parallel=True, workers_per_node=1)
        assert import_hf_weights(ff, hf) == 3 + 9 * 2  # embed+final_ln+head + 9/layer
        rs = np.random.RandomState(0)
        ids = rs.randint(0, 256, (2, 16)).astype(np.int32)
        want = hf(torch.from_numpy(ids.astype(np.int64))).logits.detach().numpy()
        got = ff.predict(ids)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

    def test_trains_token_level_ce(self):
        cfg = LlamaModelConfig(batch_size=4, seq_length=16)
        ff = create_llama(cfg, FFConfig(batch_size=4))
        ff.compile(SGDOptimizer(lr=0.5),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
        rs = np.random.RandomState(1)
        # learnable pattern: next token = (token + 1) % vocab
        ids = rs.randint(0, 255, (32, 16)).astype(np.int32)
        labels = ((ids + 1) % 256).astype(np.int32)
        l0 = ff.evaluate(ids, labels)["loss"]
        ff.fit(ids, labels, epochs=10, verbose=False)
        l1 = ff.evaluate(ids, labels)["loss"]
        assert l1 < l0 * 0.9, (l0, l1)

    def test_searched_parallel_llama_runs(self):
        # the search sees a normal PCG: head axis (4 heads), seq axis, batch
        cfg = LlamaModelConfig(batch_size=16, seq_length=16)
        ff = create_llama(cfg, FFConfig(batch_size=16, search_budget=2,
                                        enable_parameter_parallel=True))
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
        rs = np.random.RandomState(2)
        ids = rs.randint(0, 256, (16, 16)).astype(np.int32)
        labels = ((ids + 1) % 256).astype(np.int32)
        ff.fit(ids, labels, epochs=1, verbose=False)
        out = ff.predict(ids)
        assert out.shape == (16, 16, 256)
        assert np.isfinite(out).all()

    @pytest.mark.slow
    def test_ring_attention_llama_matches_dense(self):
        # seq parallel via ring attention on the virtual mesh vs the same
        # weights on a single device
        from flexflow_tpu.machine import make_mesh

        cfg = LlamaModelConfig(batch_size=4, seq_length=32,
                               seq_parallel="seq")
        mesh = make_mesh(8, {"data": 2, "seq": 4})
        ff = create_llama(cfg, FFConfig(batch_size=4))
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [],
                   mesh=mesh)
        cfg1 = LlamaModelConfig(batch_size=4, seq_length=32)
        ff1 = _compiled(cfg1, only_data_parallel=True, workers_per_node=1)
        # copy ff's params into ff1
        for name in ff.get_layer_names():
            for pname in list(ff.params.get(name, {})):
                ff1.set_parameter(name, ff.get_parameter(name, pname), pname)
        rs = np.random.RandomState(3)
        ids = rs.randint(0, 256, (4, 32)).astype(np.int32)
        np.testing.assert_allclose(ff.predict(ids), ff1.predict(ids),
                                   rtol=2e-3, atol=2e-3)

    def test_gqa_with_parameter_parallel_mesh(self):
        # review regression: wk/wv have num_kv_heads on dim 0 — sharding
        # them on a model axis that divides num_heads but not num_kv_heads
        # must not be attempted (4 heads, 2 kv heads, model axis 4)
        from flexflow_tpu.machine import make_mesh

        cfg = LlamaModelConfig(batch_size=8, seq_length=16,
                               num_attention_heads=4, num_key_value_heads=2)
        mesh = make_mesh(8, {"data": 2, "model": 4})
        ff = create_llama(cfg, FFConfig(batch_size=8,
                                        enable_parameter_parallel=True))
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [], mesh=mesh)
        rs = np.random.RandomState(4)
        ids = rs.randint(0, 256, (8, 16)).astype(np.int32)
        out = ff.predict(ids)
        assert np.isfinite(out).all()

    @pytest.mark.slow
    def test_gqa_head_sharded_kv_matches_dense(self):
        # slow tier (t1 budget): the kv-head sharding gate stays tier-1
        # via test_gqa_with_parameter_parallel_mesh (indivisible case)
        # and test_gqa_qkv_bias_broadcasts
        # r5 (VERDICT Weak #3): kv_heads divisible by the model axis —
        # wk/wv shard too, and sharded numerics match the dense run
        from flexflow_tpu.machine import make_mesh
        from jax.sharding import PartitionSpec as P

        cfg = LlamaModelConfig(batch_size=8, seq_length=16,
                               num_attention_heads=4, num_key_value_heads=2)
        mesh = make_mesh(8, {"data": 4, "model": 2})
        ff = create_llama(cfg, FFConfig(batch_size=8,
                                        enable_parameter_parallel=True))
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [], mesh=mesh)
        # the heuristic TP overrides must shard wq AND wk/wv (kv=2, mp=2)
        attn_specs = [st.param_specs for st in ff.strategy.values()
                      if "wk" in st.param_specs]
        assert attn_specs, "no attention strategy entries"
        for specs in attn_specs:
            assert tuple(specs["wq"])[0] == "model"
            assert tuple(specs["wk"])[0] == "model"
            assert tuple(specs["wv"])[0] == "model"
        cfg1 = LlamaModelConfig(batch_size=8, seq_length=16,
                                num_attention_heads=4,
                                num_key_value_heads=2)
        ff1 = _compiled(cfg1, only_data_parallel=True, workers_per_node=1)
        for name in ff.get_layer_names():
            for pname in list(ff.params.get(name, {})):
                ff1.set_parameter(name, ff.get_parameter(name, pname), pname)
        rs = np.random.RandomState(6)
        ids = rs.randint(0, 256, (8, 16)).astype(np.int32)
        np.testing.assert_allclose(ff.predict(ids), ff1.predict(ids),
                                   rtol=2e-3, atol=2e-3)

    def test_gqa_qkv_bias_broadcasts(self):
        # review regression: bk/bv must carry num_kv_heads, not num_heads
        import jax
        from flexflow_tpu.ffconst import DataType, OperatorType
        from flexflow_tpu.layer import Layer
        from flexflow_tpu.ops import OpRegistry
        from flexflow_tpu.ops.base import OpContext

        lyr = Layer(OperatorType.MULTIHEAD_ATTENTION, "attn", [],
                    data_type=DataType.FLOAT)
        lyr.properties.update(embed_dim=32, num_heads=4, num_kv_heads=2,
                              qkv_bias=True, dropout=0.0)
        op = OpRegistry.create(lyr, [(2, 8, 32)] * 3)
        params = op.init_params(jax.random.PRNGKey(0))
        assert params["bk"].shape == (2, 8) and params["bq"].shape == (4, 8)
        x = np.random.RandomState(5).randn(2, 8, 32).astype(np.float32)
        (out,) = op.forward(params, [x, x, x], OpContext(training=False))
        assert out.shape == (2, 8, 32)

    def test_bad_kv_head_count_fails_fast(self):
        from flexflow_tpu.ffconst import DataType, OperatorType
        from flexflow_tpu.layer import Layer
        from flexflow_tpu.ops import OpRegistry

        lyr = Layer(OperatorType.MULTIHEAD_ATTENTION, "attn", [],
                    data_type=DataType.FLOAT)
        lyr.properties.update(embed_dim=48, num_heads=6, num_kv_heads=4)
        with pytest.raises(ValueError, match="num_kv_heads"):
            OpRegistry.create(lyr, [(2, 8, 48)] * 3)

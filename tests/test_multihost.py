"""Multi-host (multi-controller) execution path.

Reference analog: tests/multinode_helpers/mpi_wrapper1.sh (mpirun -np 2
with per-rank GPU masks). Here: 2 subprocesses x 2 virtual CPU devices,
jax.distributed rendezvous with gloo collectives, per-process batch
staging via jax.make_array_from_process_local_data — gradient sync must
reproduce the single-process run bit-for-bit up to reduction order.
"""

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from flexflow_tpu import distributed
from flexflow_tpu.machine import make_mesh


class TestLocalBatchRows:
    def test_single_process_is_identity(self):
        mesh = make_mesh(8, {"data": 8})
        sh = NamedSharding(mesh, P("data"))
        assert distributed.local_batch_rows(sh, 16) == (16, 0)

    def test_batch_partitions(self):
        mesh = make_mesh(8, {"data": 4, "model": 2})
        assert distributed._batch_partitions(
            NamedSharding(mesh, P("data"))) == 4
        assert distributed._batch_partitions(NamedSharding(mesh, P())) == 1

    def test_stage_local_single_process(self):
        mesh = make_mesh(8, {"data": 8})
        sh = NamedSharding(mesh, P("data"))
        arr = np.arange(32, dtype=np.float32).reshape(16, 2)
        out = distributed.stage_local_batch(arr, sh)
        assert out.shape == (16, 2)
        np.testing.assert_array_equal(np.asarray(out), arr)


class TestMultiProcess:
    @pytest.mark.slow
    def test_two_process_gradient_sync_and_hlo_order(self, tmp_path):
        """2 procs x 1 virtual device == one 2-device process. Slow tier
        (t1 budget): a real 2-proc spawn stays tier-1 via
        TestCheckpointFaultTolerance's fail-fast leg, and the elastic
        2-proc dryrun also runs from scripts/run_t1.sh.
        ``trace_dir`` additionally makes every worker dump its optimized
        train-step HLO and the parent diff the per-host collective
        sequences through fflint's FFL501/502 static deadlock pass —
        run_dryrun raises if collection or ordering breaks."""
        from flexflow_tpu.multihost_dryrun import run_dryrun

        run_dryrun(num_processes=2, devices_per_proc=1,
                   trace_dir=str(tmp_path))
        assert (tmp_path / "train_step_host0.hlo.txt").exists()
        assert (tmp_path / "train_step_host1.hlo.txt").exists()

    @pytest.mark.slow
    def test_two_process_multi_axis_legs(self):
        """2 procs x 2 devices: the tp/ring/checkpoint legs whose model
        and seq axes span hosts (heavier — slow tier)."""
        from flexflow_tpu.multihost_dryrun import run_dryrun

        run_dryrun(num_processes=2, devices_per_proc=2)


class TestCheckpointFaultTolerance:
    def test_nonshared_fs_load_fails_fast_every_rank(self):
        """ADVICE r5 regression: a checkpoint visible on only some ranks
        (non-shared filesystem) must raise the same actionable
        FileNotFoundError on EVERY rank, for both the legacy v1 and the
        v2 per-shard loader — the old behavior was FileNotFoundError on
        the ranks that could not see the files and a collective deadlock
        on the ones that could. The leg finishing inside its timeout IS
        the no-hang assertion."""
        from flexflow_tpu.multihost_dryrun import run_ckpt_failfast_dryrun

        run_ckpt_failfast_dryrun(num_processes=2, devices_per_proc=1)

    @pytest.mark.slow
    def test_kill_and_resume_elastic(self):
        """The full FFS_FAULT kill-and-resume arc (acceptance
        criterion): a host killed mid-epoch leaves a complete
        manifest-committed checkpoint and nothing readable beyond it;
        resume on the same mesh continues bit-identically; resume on a
        smaller mesh re-searches a strategy and converges within
        reduction-order tolerance. The tier-1-fast variant of this leg
        also runs (non-fatally) from scripts/run_t1.sh."""
        from flexflow_tpu.multihost_dryrun import run_elastic_dryrun

        summary = run_elastic_dryrun(num_processes=2, devices_per_proc=1)
        assert summary["same_mesh_bitwise"]


class TestPreemptionSupervision:
    @pytest.mark.slow
    def test_sigterm_grace_checkpoint_and_bitwise_resume(self):
        """ISSUE 12 acceptance: SIGTERM delivered to every rank
        mid-epoch produces a complete grace-window checkpoint of the
        post-in-flight-step state (each rank exits PREEMPTED_EXIT
        after the commit barrier), and the auto-resumed run continues
        bit-identically on the same mesh."""
        from flexflow_tpu.multihost_dryrun import run_preemption_dryrun

        summary = run_preemption_dryrun(num_processes=2,
                                        devices_per_proc=1)
        assert summary["bitwise"]

    @pytest.mark.slow
    def test_supervised_hang_kill_and_io_error_recovery(self):
        """ISSUE 12 acceptance (multi-restart legs): a hang trips the
        watchdog within the timeout and the Supervisor restarts from
        the last complete checkpoint to a clean finish without human
        intervention; a hard kill auto-resumes the same way; transient
        io_error saves succeed after retry with the retry count
        visible in obs counters. Also runs (non-fatally) from
        scripts/run_t1.sh."""
        from flexflow_tpu.multihost_dryrun import run_supervised_dryrun

        summary = run_supervised_dryrun()
        assert summary["hang"] == ["hung", "clean"]
        assert summary["kill"] == ["kill", "clean"]
        assert summary["io_retries"] == 2

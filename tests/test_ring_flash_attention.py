"""Ring attention (sequence/context parallelism) + Pallas flash attention.

SURVEY §5.7: the reference has NO sequence parallelism — this is the
first-class TPU capability that replaces it. Numerics are validated
against the dense einsum attention path.
"""

import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from flexflow_tpu.machine import make_mesh
from flexflow_tpu.ops.attention import scaled_dot_product_attention
from flexflow_tpu.parallel.ring_attention import ring_attention


def qkv(b=4, h=2, s=32, d=8, seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.randn(b, h, s, d).astype(np.float32))
    return mk(), mk(), mk()


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_attention(self, causal):
        mesh = make_mesh(8, {"data": 2, "seq": 4})
        q, k, v = qkv()
        want = scaled_dot_product_attention(q, k, v, causal=causal)
        got = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal)
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_seq_only_mesh(self):
        mesh = make_mesh(8, {"seq": 8})
        q, k, v = qkv(s=64)
        want = scaled_dot_product_attention(q, k, v, causal=True)
        got = ring_attention(q, k, v, mesh, batch_axis=None, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.slow
    def test_gradients_flow(self):
        mesh = make_mesh(8, {"data": 2, "seq": 4})
        q, k, v = qkv()

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(
                scaled_dot_product_attention(q, k, v, causal=True) ** 2)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_dense):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)


class TestFlashAttention:
    @pytest.fixture(autouse=True)
    def _interpret_mode(self, monkeypatch):
        monkeypatch.setenv("FLEXFLOW_TPU_PALLAS", "interpret")

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        from flexflow_tpu.ops.pallas_kernels import (flash_attention,
                                                     flash_attention_available)

        assert flash_attention_available(256, 8)
        q, k, v = qkv(b=2, h=2, s=256, d=8, seed=1)
        want = scaled_dot_product_attention(q, k, v, causal=causal)
        got = flash_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_backward_matches_dense(self):
        from flexflow_tpu.ops.pallas_kernels import flash_attention

        q, k, v = qkv(b=1, h=2, s=128, d=8, seed=2)
        g1 = jax.grad(lambda q: jnp.sum(
            flash_attention(q, k, v, causal=True) ** 2))(q)
        g2 = jax.grad(lambda q: jnp.sum(
            scaled_dot_product_attention(q, k, v, causal=True) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-3, atol=1e-4)

    def test_unavailable_for_ragged_seq(self):
        from flexflow_tpu.ops.pallas_kernels import flash_attention_available

        assert not flash_attention_available(100, 8)  # S % 128 != 0

    def test_sharded_flash_on_dp_mp_mesh(self):
        # round-1 advisor finding: a bare pallas_call inside a GSPMD jit is
        # an unpartitionable custom call. The shard_map wrapper must
        # compile on a dp x mp mesh and match the einsum path.
        from flexflow_tpu.ops.pallas_kernels import flash_attention_sharded

        mesh = make_mesh(8, {"data": 2, "model": 4})
        q, k, v = qkv(b=2, h=4, s=128, d=8, seed=3)
        want = scaled_dot_product_attention(q, k, v, causal=True)
        got = jax.jit(lambda q, k, v: flash_attention_sharded(
            q, k, v, mesh, batch_axis="data", head_axis="model",
            causal=True))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_attention_op_picks_sharded_flash_under_mesh(self):
        # the op's own dispatch: non-trivial mesh + flash available must
        # route through the shard_map wrapper and still match the dense
        # path end to end (forward traced with ctx.mesh set, under jit)
        from flexflow_tpu.ffconst import DataType, OperatorType
        from flexflow_tpu.layer import Layer
        from flexflow_tpu.ops import OpRegistry
        from flexflow_tpu.ops.base import OpContext

        mesh = make_mesh(8, {"data": 2, "model": 4})
        b, s, e, h = 2, 128, 32, 4
        lyr = Layer(OperatorType.MULTIHEAD_ATTENTION, "attn", [],
                    data_type=DataType.FLOAT)
        lyr.properties.update(embed_dim=e, num_heads=h, dropout=0.0,
                              causal=False, head_parallel="model")
        op = OpRegistry.create(lyr, [(b, s, e), (b, s, e), (b, s, e)])
        params = op.init_params(jax.random.PRNGKey(0))
        rs = np.random.RandomState(4)
        x = jnp.asarray(rs.randn(b, s, e).astype(np.float32))

        def fwd(p, x, use_mesh):
            ctx = OpContext(training=False, mesh=mesh if use_mesh else None)
            return op.forward(p, [x, x, x], ctx)[0]

        got = jax.jit(lambda p, x: fwd(p, x, True))(params, x)
        want = fwd(params, x, False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-4)


class TestSeqParallelModel:
    def test_transformer_block_with_ring_attention_trains(self):
        from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                                  SGDOptimizer)
        from flexflow_tpu.ffconst import ActiMode
        from flexflow_tpu.machine import make_mesh

        b, s, e, hds = 4, 32, 16, 4
        mesh = make_mesh(8, {"data": 2, "seq": 4})

        def build(seq_parallel):
            cfg = FFConfig(batch_size=b, only_data_parallel=True)
            ff = FFModel(cfg)
            t = ff.create_tensor((b, s, e))
            a = ff.multihead_attention(t, t, t, e, hds, causal=True,
                                       seq_parallel=seq_parallel, name="attn")
            h = ff.add(a, t, name="res")
            h = ff.layer_norm(h, name="ln")
            out = ff.dense(h, 1, name="head")
            ff.compile(SGDOptimizer(lr=0.01),
                       LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
                       [MetricsType.MEAN_SQUARED_ERROR],
                       mesh=mesh if seq_parallel else None)
            return ff

        rs = np.random.RandomState(0)
        x = rs.randn(b * 4, s, e).astype(np.float32)
        y = rs.randn(b * 4, s, 1).astype(np.float32)

        ff_sp = build("seq")
        ff_ref = build(None)
        # align initial params
        for lname, sub in ff_ref.params.items():
            for pname in sub:
                ff_sp.set_parameter(lname, np.asarray(sub[pname]), pname)
        p_sp = ff_sp.predict(x[:b])
        p_ref = ff_ref.predict(x[:b])
        np.testing.assert_allclose(p_sp, p_ref, rtol=2e-4, atol=2e-5)
        ff_sp.fit(x, y, epochs=1, verbose=False)  # trains under dp x sp


class TestRingFlashInner:
    """r4: the ring's inner block runs the Pallas flash kernel (scores in
    VMEM, never HBM) — numerics and gradients must match the dense path
    exactly. Interpret mode exercises the kernel on CPU."""

    @pytest.fixture(autouse=True)
    def _interpret_mode(self, monkeypatch):
        monkeypatch.setenv("FLEXFLOW_TPU_PALLAS", "interpret")

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_inner_matches_dense(self, causal):
        # S_loc = 512/4 = 128 = BLK_Q -> flash path taken per shard
        mesh = make_mesh(8, {"data": 2, "seq": 4})
        q, k, v = qkv(b=2, h=2, s=512, d=8)
        want = scaled_dot_product_attention(q, k, v, causal=causal)
        got = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal)
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.slow
    def test_flash_inner_gradients(self):
        mesh = make_mesh(8, {"seq": 8})
        q, k, v = qkv(b=1, h=2, s=1024, d=8)

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh, batch_axis=None,
                                          causal=True) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(
                scaled_dot_product_attention(q, k, v, causal=True) ** 2)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_dense):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-3, atol=5e-4)

    @pytest.mark.slow
    def test_flash_lse_primitive(self):
        """flash_attention_lse's lse output and its gradient path."""
        from flexflow_tpu.ops.pallas_kernels import flash_attention_lse

        rs = np.random.RandomState(0)
        q = jnp.asarray(rs.randn(2, 128, 8).astype(np.float32))
        k = jnp.asarray(rs.randn(2, 128, 8).astype(np.float32))
        v = jnp.asarray(rs.randn(2, 128, 8).astype(np.float32))

        def ref(q, k, v):
            s = jnp.einsum("bqd,bkd->bqk", q, k) / jnp.sqrt(jnp.float32(8))
            lse = jax.scipy.special.logsumexp(s, axis=-1)
            o = jnp.einsum("bqk,bkd->bqd", jnp.exp(s - lse[..., None]), v)
            return o, lse

        o, lse = flash_attention_lse(q, k, v, False, True)
        o_r, lse_r = ref(q, k, v)
        np.testing.assert_allclose(np.asarray(o), np.asarray(o_r),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_r),
                                   rtol=1e-5, atol=1e-5)
        # gradient including the lse output (the ring-merge dependency)
        f = lambda q, k, v: (
            jnp.sum(flash_attention_lse(q, k, v, False, True)[0] ** 2)
            + jnp.sum(jnp.sin(flash_attention_lse(q, k, v, False, True)[1])))
        fr = lambda q, k, v: (jnp.sum(ref(q, k, v)[0] ** 2)
                              + jnp.sum(jnp.sin(ref(q, k, v)[1])))
        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_blocked_backward_long_seq(self, causal):
        """S > MAX_BWD_SEQ takes the K-blocked backward kernel — grads
        must match the einsum reference (scores stay in VMEM tiles)."""
        from flexflow_tpu.ops.pallas_kernels import (MAX_BWD_SEQ, _flash,
                                                     _xla_attention)

        rs = np.random.RandomState(1)
        s = MAX_BWD_SEQ * 2
        q = jnp.asarray(rs.randn(1, s, 8).astype(np.float32))
        k = jnp.asarray(rs.randn(1, s, 8).astype(np.float32))
        v = jnp.asarray(rs.randn(1, s, 8).astype(np.float32))
        f = lambda q, k, v: jnp.sum(_flash(q, k, v, causal, True) ** 2)
        fr = lambda q, k, v: jnp.sum(_xla_attention(q, k, v, causal) ** 2)
        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-3, atol=5e-4)

"""Observability subsystem tests (flexflow_tpu/obs).

Acceptance (ISSUE 1): a traced ``fit`` on a small MLP produces a
Chrome-trace JSON with per-step spans, a summary JSON with HLO
FLOPs/bytes/peak-memory + a collective census, and a drift report with
a predicted-vs-measured step-time ratio — all on the CPU backend.
Plus: PerfMetrics accumulation semantics, the no-op tracer fast path,
census parsing, the counter registry, and bench.py's ratchet/atomic
history handling.
"""

import glob
import json
import os

import numpy as np
import pytest

import jax

from flexflow_tpu import (
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
    __version__,
)
from flexflow_tpu.ffconst import ActiMode


def make_blobs(n=128, d=8, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(classes, d) * 3
    y = rs.randint(0, classes, n)
    x = centers[y] + rs.randn(n, d)
    return x.astype(np.float32), y.astype(np.int32)


def build_mlp(batch_size=32, **cfg_kwargs):
    ff = FFModel(FFConfig(batch_size=batch_size, **cfg_kwargs))
    t = ff.create_tensor((batch_size, 8))
    t = ff.dense(t, 16, activation=ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.ACCURACY])
    return ff


class TestTracedFit:
    """The acceptance path: fit(trace_dir=...) emits all artifacts."""

    @pytest.fixture(scope="class")
    def traced_run(self, tmp_path_factory):
        td = str(tmp_path_factory.mktemp("trace"))
        x, y = make_blobs()
        ff = build_mlp()
        ff.fit(x, y, epochs=2, verbose=False, trace_dir=td)
        return td, ff

    def _one(self, td, pattern):
        paths = glob.glob(os.path.join(td, pattern))
        assert len(paths) == 1, f"{pattern}: {paths}"
        return paths[0]

    def test_chrome_trace_with_step_spans(self, traced_run):
        td, _ = traced_run
        trace = json.load(open(self._one(td, "fit_*.trace.json")))
        events = trace["traceEvents"]
        steps = [e for e in events if e.get("name") == "step"
                 and e.get("ph") == "X"]
        # 128 samples / 32 batch * 2 epochs = 8 steps
        assert len(steps) == 8
        assert all(e["dur"] > 0 for e in steps)
        # the issue's phase vocabulary is all present, nested in steps
        names = {e["name"] for e in events}
        for phase in ("data_load", "device_put", "dispatch",
                      "device_wait", "metrics_sync"):
            assert phase in names, f"missing phase {phase}"
        # version stamped into the artifact header (satellite)
        assert trace["metadata"]["flexflow_tpu_version"] == __version__
        assert trace["metadata"]["host_id"] == 0

    def test_jsonl_stream(self, traced_run):
        td, _ = traced_run
        lines = [json.loads(ln) for ln in
                 open(self._one(td, "fit_*.events.jsonl"))]
        assert lines[0]["record"] == "header"
        assert lines[0]["flexflow_tpu_version"] == __version__
        assert sum(1 for e in lines[1:] if e["name"] == "step") == 8

    def test_summary_hlo_costs_and_census(self, traced_run):
        td, _ = traced_run
        summ = json.load(open(self._one(td, "fit_*.summary.json")))
        assert summ["header"]["flexflow_tpu_version"] == __version__
        assert summ["flops"] > 0
        assert summ["bytes_accessed"] > 0
        assert summ["memory"]["peak_bytes"] > 0
        assert summ["memory"]["argument_bytes"] > 0
        # data-parallel grad sync over the 8-device CPU mesh MUST show
        # up as all-reduces in the census
        census = summ["collectives"]
        assert "all-reduce" in census
        assert census["all-reduce"]["count"] >= 1
        assert census["all-reduce"]["bytes"] > 0
        assert summ["collectives_total"]["count"] >= 1
        assert summ["mesh_axes"] == {"data": 8}

    def test_drift_report(self, traced_run):
        td, _ = traced_run
        rep = json.load(open(self._one(td, "fit_*.drift.json")))
        assert rep["header"]["flexflow_tpu_version"] == __version__
        assert rep["predicted"]["total_s"] > 0
        assert rep["measured"]["step_s"] > 0
        assert rep["ratio"] > 0
        # every op priced, with its sharding work division recorded
        assert rep["predicted"]["num_ops"] == 3
        assert all(r["work_div"] >= 1 for r in rep["per_op"])
        assert any(r["work_div"] == 8 for r in rep["per_op"])  # dp=8
        # comms priced from the census through the machine model
        assert "all-reduce" in rep["comm"]
        assert rep["comm"]["all-reduce"]["predicted_s"] > 0
        # phase attribution rode along
        assert "dispatch" in rep["phases"]

    def test_counters_exported(self, traced_run):
        td, _ = traced_run
        counters = json.load(open(self._one(td, "fit_*.counters.json")))
        assert counters["counters"]["executor.train_step_jits"] >= 1

    def test_drift_ingestable_by_calibrate(self, traced_run, tmp_path,
                                           monkeypatch):
        """The drift report round-trips through scripts/calibrate.py
        --ingest-drift into CALIBRATION.json rows."""
        import importlib.util
        import sys
        td, _ = traced_run
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "calibrate", os.path.join(repo, "scripts", "calibrate.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        # redirect CALIBRATION.json writes into tmp_path
        fake_repo = tmp_path / "repo"
        (fake_repo / "scripts").mkdir(parents=True)
        monkeypatch.setattr(mod.os.path, "abspath",
                            lambda p: str(fake_repo / "scripts" / "x.py"))
        assert mod.ingest_drift(td) == 0
        cal = json.load(open(fake_repo / "CALIBRATION.json"))
        rows = [r for r in cal["results"]
                if r.get("source") == "drift_report"]
        assert len(rows) == 1
        assert rows[0]["model"] == "fit"
        assert rows[0]["predicted_s"] > 0
        assert rows[0]["actual_s"] > 0


class TestTracerOffIsNoop:
    def test_fit_without_trace_dir_writes_nothing(self, tmp_path):
        x, y = make_blobs(64)
        ff = build_mlp()
        cwd_before = set(os.listdir(os.getcwd()))
        ff.fit(x, y, epochs=1, verbose=False)
        assert set(os.listdir(os.getcwd())) == cwd_before
        assert os.listdir(str(tmp_path)) == []

    def test_null_tracer_shared_and_inert(self):
        from flexflow_tpu.obs import NULL_TRACER, make_tracer
        t = make_tracer(None)
        assert t is NULL_TRACER
        assert not t.active
        with t.step():
            with t.phase("anything", foo=1):
                pass
        t.instant("x")
        assert t.export() == {}
        assert t.step_time_s() is None

    def test_crashed_fit_still_flushes_trace(self, tmp_path):
        # a traced run that dies mid-training must still export its
        # buffered spans — that trace is the diagnosis of the crash
        td = str(tmp_path)
        x, y = make_blobs(128)
        ff = build_mlp()
        real = ff.executor.make_train_step()
        calls = {"n": 0}

        def dying_step(*args):
            calls["n"] += 1
            if calls["n"] > 2:
                raise RuntimeError("injected mid-training failure")
            return real(*args)

        ff.executor.make_train_step = lambda: dying_step
        with pytest.raises(RuntimeError, match="injected"):
            ff.fit(x, y, epochs=2, verbose=False, trace_dir=td)
        trace = json.load(open(glob.glob(
            os.path.join(td, "fit_*.trace.json"))[0]))
        steps = [e for e in trace["traceEvents"]
                 if e.get("name") == "step" and e.get("ph") == "X"]
        # 2 completed steps + the aborted one (its span closes on the
        # way out, so the trace shows exactly where the run died)
        assert len(steps) == 3
        # the failure path flushes trace/counters ONLY: summary + drift
        # need a fresh lower+compile, which a dead run must not pay
        assert glob.glob(os.path.join(td, "fit_*.summary.json")) == []
        assert glob.glob(os.path.join(td, "fit_*.drift.json")) == []
        assert len(glob.glob(os.path.join(td, "fit_*.counters.json"))) == 1

    def test_unusable_trace_dir_degrades_to_noop(self, tmp_path):
        from flexflow_tpu.obs import NULL_TRACER, make_tracer
        blocker = tmp_path / "not_a_dir"
        blocker.write_text("")
        t = make_tracer(str(blocker / "sub"))
        assert t is NULL_TRACER
        # and a traced fit pointed there still trains
        x, y = make_blobs(64)
        ff = build_mlp()
        ff.fit(x, y, epochs=1, verbose=False,
               trace_dir=str(blocker / "sub"))

    def test_evaluate_traced(self, tmp_path):
        td = str(tmp_path)
        x, y = make_blobs(64)
        ff = build_mlp()
        ff.evaluate(x, y, trace_dir=td)
        paths = glob.glob(os.path.join(td, "evaluate_*.trace.json"))
        assert len(paths) == 1
        trace = json.load(open(paths[0]))
        assert any(e.get("name") == "step"
                   for e in trace["traceEvents"])


class TestPerfMetricsAccumulation:
    """Satellite: accumulation semantics — reset BETWEEN epochs,
    accumulate WITHIN an epoch."""

    def test_update_accumulates_within_epoch(self):
        from flexflow_tpu.metrics import PerfMetrics
        pm = PerfMetrics()
        pm.update({"accuracy": np.int32(10), "mse_loss": 2.0}, 32)
        pm.update({"accuracy": np.int32(6), "mse_loss": 1.0}, 32)
        assert pm.train_all == 64
        assert pm.train_correct == 16
        rep = pm.report()
        assert rep["accuracy"] == pytest.approx(16 / 64)
        assert rep["mse_loss"] == pytest.approx(3.0 / 64)

    def test_fit_resets_between_epochs(self):
        """After N epochs the accumulator holds ONE epoch's samples (a
        fresh PerfMetrics per epoch), not the whole run's."""
        x, y = make_blobs(128)
        ff = build_mlp()
        ff.fit(x, y, epochs=3, verbose=False)
        assert ff._metrics_acc.train_all == 128  # not 3 * 128
        # and within the final epoch all 4 batches accumulated
        assert 0 < ff._metrics_acc.train_correct <= 128

    def test_evaluate_accumulates_all_batches(self):
        x, y = make_blobs(96)
        ff = build_mlp()
        rep = ff.evaluate(x, y)
        assert "accuracy" in rep and "loss" in rep
        assert 0.0 <= rep["accuracy"] <= 1.0


class TestCollectiveCensus:
    def test_parses_counts_and_bytes(self):
        from flexflow_tpu.obs.inspect import collective_census
        hlo = """
  %x = f32[128,256] parameter(0)
  %all-reduce.1 = f32[128,256]{1,0} all-reduce(f32[128,256] %x), replica_groups={}
  %ag = f32[8,64] all-gather(f32[2,64] %y), dimensions={0}
  %rs-start = f32[64] reduce-scatter-start(f32[256] %z)
  %all-reduce-start.2 = f32[16]{0} all-reduce-start(f32[16] %w)
  %all-reduce-done.2 = f32[16]{0} all-reduce-done(%all-reduce-start.2)
"""
        census = collective_census(hlo)
        assert census["all-reduce"]["count"] == 2
        assert census["all-reduce"]["bytes"] == 128 * 256 * 4 + 16 * 4
        assert census["all-gather"]["count"] == 1
        assert census["all-gather"]["bytes"] == 8 * 64 * 4
        assert census["reduce-scatter"]["count"] == 1

    def test_lhs_names_do_not_match(self):
        from flexflow_tpu.obs.inspect import collective_census
        hlo = "%all-reduce.5 = f32[4] add(f32[4] %a, f32[4] %b)"
        assert collective_census(hlo) == {}

    def test_min_bytes_filter(self):
        from flexflow_tpu.obs.inspect import collective_census
        hlo = "%r = f32[2] all-reduce(f32[2] %a)"
        assert collective_census(hlo, min_bytes=1 << 12) == {}
        assert collective_census(hlo)["all-reduce"]["bytes"] == 8

    def test_validator_uses_census(self):
        """search/validate.emitted_collectives is the census normalized
        onto the simulator vocabulary (refactor must stay consistent)."""
        from flexflow_tpu.search.validate import emitted_collectives
        hlo = """
  %ar = f32[4096] all-reduce(f32[4096] %a)
  %rs = f32[2048] reduce-scatter(f32[4096] %b)
  %cp = f32[4096] collective-permute(f32[4096] %c)
"""
        out = emitted_collectives(hlo, min_bytes=1024)
        assert out["allreduce"] == 4096 * 4 + 2048 * 4
        assert out["ppermute"] == 4096 * 4


class TestCounterRegistry:
    def test_counters_gauges_observations(self):
        from flexflow_tpu.obs.registry import CounterRegistry
        r = CounterRegistry()
        r.inc("a")
        r.inc("a", 2)
        r.gauge("g", 7.5)
        r.observe("o", 1.0)
        r.observe("o", 3.0)
        d = r.to_dict()
        assert d["counters"]["a"] == 3
        assert d["gauges"]["g"] == 7.5
        assert d["observations"]["o"] == dict(count=2.0, sum=4.0,
                                              min=1.0, max=3.0,
                                              p50=1.0, p99=3.0)
        assert r.get("a") == 3
        r.reset()
        assert r.to_dict()["counters"] == {}

    def test_export_stamps_header(self, tmp_path):
        from flexflow_tpu.obs.registry import CounterRegistry
        r = CounterRegistry()
        r.inc("x")
        path = r.export(str(tmp_path / "c.json"))
        data = json.load(open(path))
        assert data["header"]["flexflow_tpu_version"] == __version__
        assert data["counters"]["x"] == 1


class TestMachineCollectiveTime:
    def test_kinds_priced(self):
        from flexflow_tpu.machine import MachineSpec
        spec = MachineSpec(chip="tpu-v5e", chips_per_slice=4)
        b = 1 << 20
        ar = spec.collective_time("all-reduce", b, 4)
        rs = spec.collective_time("reduce-scatter", b, 4)
        ag = spec.collective_time("all-gather", b, 4)
        cp = spec.collective_time("collective-permute", b, 4)
        assert ar > 0 and ag > 0 and cp > 0
        # census bytes are the RS op's per-shard OUTPUT (1/n of the
        # reduced buffer): priced as half the AR ring cost of the FULL
        # n*b payload, not of b
        assert rs == pytest.approx(spec.ici_allreduce_time(b * 4, 4) / 2)
        assert rs > ar / 2
        assert ag < ar  # allgather moves (n-1)/n vs AR's 2(n-1)/n
        assert spec.collective_time("all-reduce", b, 1) == 0.0


class TestMergeHostTraces:
    def test_merges_by_host_id(self, tmp_path):
        from flexflow_tpu.obs.tracer import StepTracer, merge_host_traces
        td = str(tmp_path)
        for host in (0, 1):
            tr = StepTracer(td, host_id=host, run_name="fit")
            with tr.step():
                with tr.phase("dispatch"):
                    pass
            tr.export()
        merged = merge_host_traces(td)
        assert merged is not None
        data = json.load(open(merged))
        assert data["metadata"]["merged_hosts"] == [0, 1]
        pids = {e["pid"] for e in data["traceEvents"]}
        assert pids == {0, 1}

    def test_repeated_runs_merge_onto_distinct_thread_rows(self, tmp_path):
        # two runs from the same host into one dir (fit then evaluate,
        # or a stale trace from an earlier invocation) must land on
        # separate (pid, tid) rows, not interleave on one thread
        from flexflow_tpu.obs.tracer import StepTracer, merge_host_traces
        td = str(tmp_path)
        for run in ("fit", "evaluate"):
            tr = StepTracer(td, host_id=0, run_name=run)
            with tr.step():
                with tr.phase("dispatch"):
                    pass
            tr.export()
        data = json.load(open(merge_host_traces(td)))
        spans = [e for e in data["traceEvents"] if e.get("ph") == "X"]
        assert len({(e["pid"], e["tid"]) for e in spans}) == 2
        labels = {e["args"]["name"] for e in data["traceEvents"]
                  if e["name"] == "thread_name"}
        assert len(labels) == 2 and all(
            l.startswith(("fit_r", "evaluate_r")) for l in labels)

    def test_empty_dir(self, tmp_path):
        from flexflow_tpu.obs.tracer import merge_host_traces
        assert merge_host_traces(str(tmp_path)) is None


class TestBenchRatchet:
    """Satellites: missing-key first run + atomic history write."""

    def _bench(self):
        import importlib.util
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench_mod", os.path.join(repo, "bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_first_run_of_new_family_no_keyerror(self):
        bench = self._bench()
        hist = {}
        vs, best, old = bench.ratchet(hist, "new_family:cpu", 100.0,
                                      {"bs": 8}, "best1x5")
        assert vs == 1.0
        assert best == 100.0
        assert old is None
        assert hist["new_family:cpu"]["samples_per_s"] == 100.0

    def test_legacy_bare_number_entry(self):
        bench = self._bench()
        hist = {"bert_proxy:tpu": 150.0}
        vs, best, _ = bench.ratchet(hist, "bert_proxy:tpu", 120.0,
                                    {}, "best3x30")
        assert vs == pytest.approx(120.0 / 150.0)
        assert best == 150.0

    def test_ratchet_keeps_best(self):
        bench = self._bench()
        hist = {"w:cpu": {"samples_per_s": 200.0, "protocol": "best1x5",
                          "config": {}}}
        vs, best, _ = bench.ratchet(hist, "w:cpu", 100.0, {}, "best1x5")
        assert best == 200.0
        assert hist["w:cpu"]["samples_per_s"] == 200.0

    def test_save_history_atomic(self, tmp_path):
        bench = self._bench()
        path = str(tmp_path / "bench_history.json")
        bench.save_history(path, {"a": {"samples_per_s": 1.0}})
        assert json.load(open(path)) == {"a": {"samples_per_s": 1.0}}
        # overwrite keeps valid JSON and leaves no temp litter
        bench.save_history(path, {"b": 2})
        assert json.load(open(path)) == {"b": 2}
        assert os.listdir(str(tmp_path)) == ["bench_history.json"]

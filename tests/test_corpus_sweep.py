"""Corpus-sweep verification of the shipped substitution rules.

VERDICT r4 Missing #3: every shipped rule must be verified, not merely
loadable. For EACH of the ~330 rules in substitutions/ffs_subst_v1.json
this sweep synthesizes a concrete graph realizing the rule's source
pattern, then asserts through the native engine (ffs_match_rules) that
the rule (a) matches its own pattern, (b) structurally applies, and
(c) the rewritten graph still prices under the frontier DP — the
integrity contract. Executor-level numerics parity per family lives in
tests/test_substitution.py (TestComputeRewriteFamilies for the r4
families, TestNewCorpusFamilyNumerics for the r5 ones); this sweep is
the breadth pass over every individual rule.

Analog of the reference's substitution_loader round-trip over
graph_subst_3_v2.json (640 machine-generated rules).
"""

import json
import os

import pytest

from flexflow_tpu.search.native import available, native_match_rules

pytestmark = pytest.mark.skipif(not available(),
                                reason="native ffsearch library unavailable")

CORPUS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "substitutions", "ffs_subst_v1.json")

GRID = {"CONV2D", "POOL2D", "BATCHNORM", "LAYERNORM"}


def _para(op):
    return {p["key"]: p["value"] for p in op.get("para", [])}


def _fixed(v, default):
    """Wildcard (<= -1000) -> default, else the fixed value."""
    return default if v is None or v <= -999.0 else int(v)


def _pattern_graph(rule):
    """Concrete native-graph node list realizing `rule`'s source pattern.

    Shapes: rank-4 (8, 4, 6, 8) for layout patterns (every dim even so
    degree-2 parallel ops stay legal on any fixed dim), NCHW (8, 4, 8, 8)
    when a grid op is present, rank-2 (8, 16) for LINEAR patterns.
    """
    src = rule["srcOp"]
    types = [o["type"] for o in src]
    if any(t in GRID for t in types):
        base = [8, 4, 8, 8]
    elif "LINEAR" in types:
        base = [8, 16]
    else:
        base = [8, 4, 6, 8]

    nodes = []
    out_shape = {}  # (opId, tsId) -> shape

    def shape_of(ref):
        i, t = ref["opId"], ref["tsId"]
        if i < 0:
            return list(base)
        return list(out_shape[(i, t)])

    for idx, o in enumerate(src):
        t = o["type"]
        para = _para(o)
        ins = o["input"]
        in_shapes = [shape_of(r) for r in ins]
        inputs = [[r["opId"] + 1 if r["opId"] >= 0 else r["opId"],
                   r["tsId"]] for r in ins]
        attrs = {}
        params = {}
        if t in ("COMBINE", "REPARTITION", "REPLICATE", "REDUCTION"):
            d = _fixed(para.get("PM_PARALLEL_DIM"), 0)
            attrs = {"dim": d, "degree": 2}
            out = list(in_shapes[0])
        elif t == "CONCAT":
            a = _fixed(para.get("PM_AXIS"), 1)
            out = list(in_shapes[0])
            out[a] = sum(s[a] for s in in_shapes)
            attrs = {"axis": a}
        elif t == "LINEAR":
            out_dim = 16 + 8 * idx  # distinct widths exercise merge sums
            params = {"kernel": [in_shapes[0][-1], out_dim],
                      "bias": [out_dim]}
            out = list(in_shapes[0])
            out[-1] = out_dim
            attrs = {"out_dim": out_dim,
                     "activation": _fixed(para.get("PM_ACTI"), 0)}
        elif t == "CONV2D":
            oc = 8
            params = {"kernel": [oc, in_shapes[0][1], 3, 3], "bias": [oc]}
            out = [in_shapes[0][0], oc, in_shapes[0][2], in_shapes[0][3]]
            attrs = {"out_channels": oc, "groups": 1, "kernel_h": 3,
                     "kernel_w": 3, "stride_h": 1, "stride_w": 1,
                     "padding_h": 1, "padding_w": 1}
        elif t == "POOL2D":
            out = list(in_shapes[0])  # 3x3 stride 1 pad 1
            attrs = {"kernel_h": 3, "kernel_w": 3, "stride_h": 1,
                     "stride_w": 1, "padding_h": 1, "padding_w": 1}
        elif t == "BATCHNORM":
            c = in_shapes[0][1]
            params = {"scale": [c], "bias": [c]}
            out = list(in_shapes[0])
            attrs = {"relu": 0}
        elif t == "LAYERNORM":
            d = in_shapes[0][-1]
            params = {"scale": [d], "bias": [d]}
            out = list(in_shapes[0])
        elif t.startswith("EW_"):
            out = list(in_shapes[0])
        else:  # unary / SCALAR_* / CAST / DROPOUT / IDENTITY ...
            out = list(in_shapes[0])
        out_shape[(idx, 0)] = out
        flops = float(1)
        for s in out:
            flops *= s
        nodes.append({
            "guid": idx + 1, "type": t, "name": f"p{idx}",
            "inputs": inputs, "input_shapes": in_shapes,
            "output_shapes": [out],
            "roles": [["sample"] + ["other"] * (len(out) - 1)],
            "params": params, "flops": flops, "dtype_size": 4,
            "attrs": attrs,
        })
    return nodes


def test_every_shipped_rule_matches_applies_and_prices():
    corpus = json.load(open(CORPUS))
    assert len(corpus) > 300, (
        f"shipped corpus holds {len(corpus)} rules; the default search "
        f"corpus must stay >300 (VERDICT r4 Missing #3)")
    failures = []
    for rule in corpus:
        nodes = _pattern_graph(rule)
        resp = native_match_rules({"nodes": nodes, "subst_rules": [rule]})
        stats = resp.get(rule["name"], {})
        if not (stats.get("matches", 0) >= 1
                and stats.get("applied", 0) >= 1
                and stats.get("priced") == stats.get("applied")):
            failures.append((rule["name"], stats))
    assert not failures, (
        f"{len(failures)}/{len(corpus)} rules failed the sweep; "
        f"first 10: {failures[:10]}")


def test_default_search_loads_full_corpus():
    """The shipped corpus (not a subset) is what FFModel.compile's search
    actually loads by default."""
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer

    corpus = json.load(open(CORPUS))
    cfg = FFConfig(batch_size=32, search_budget=2,
                   enable_parameter_parallel=True)
    ff = FFModel(cfg)
    t = ff.create_tensor((32, 16))
    ff.dense(t, 8)
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
    # builtins + the full shipped corpus (training-illegal rules may be
    # filtered, hence >=)
    assert ff.search_info["stats"]["rules_loaded"] >= len(corpus)

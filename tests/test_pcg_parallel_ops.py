"""PCG primitives + parallel (resharding) ops.

Covers SURVEY §2.3: ParallelDim/ParallelTensorShape round-trips and the
four resharding ops as graph nodes (reference src/parallel_ops/*.cc).
"""

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import LossType, MetricsType
from flexflow_tpu.machine import make_mesh
from flexflow_tpu.model import FFModel
from flexflow_tpu.optimizers import SGDOptimizer
from flexflow_tpu.parallel.pcg import (ParallelDim, ParallelTensorShape,
                                       shape_from_partition_spec,
                                       spec_to_degrees)


class TestParallelTensorShape:
    def test_spec_roundtrip(self):
        mesh = make_mesh(8, {"data": 4, "model": 2})
        pts = ParallelTensorShape((
            ParallelDim(64, 4, ("data",)), ParallelDim(128),
            ParallelDim(256, 2, ("model",)),
        ))
        spec = pts.partition_spec()
        assert spec == P("data", None, "model")
        back = shape_from_partition_spec((64, 128, 256), spec, mesh)
        assert back.degrees == (4, 1, 2)
        assert back.sizes == (64, 128, 256)
        assert pts.total_degree == 8

    def test_replica_dim_dropped_from_spec(self):
        pts = ParallelTensorShape((
            ParallelDim(4, 4, ("data",), is_replica_dim=True),
            ParallelDim(32), ParallelDim(64),
        ))
        assert pts.partition_spec() == P(None, None)
        assert pts.sizes == (32, 64)
        assert pts.num_replica == 4

    def test_invalid_degree(self):
        with pytest.raises(ValueError):
            ParallelDim(10, 4, ("data",))

    def test_spec_to_degrees(self):
        mesh = make_mesh(8, {"data": 4, "model": 2})
        assert spec_to_degrees((64, 32), P("data"), mesh) == [4, 1]
        assert spec_to_degrees((64, 32), None, mesh) == [1, 1]
        assert spec_to_degrees((64, 32), P(("data", "model"),), mesh) == [8, 1]


class TestParallelOps:
    def _train(self, build, n=16, d=8):
        cfg = FFConfig(batch_size=n, only_data_parallel=True)
        ff = FFModel(cfg)
        x_t = ff.create_tensor((n, d))
        out = build(ff, x_t)
        ff.compile(SGDOptimizer(lr=0.01), LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
                   [MetricsType.MEAN_SQUARED_ERROR])
        rs = np.random.RandomState(0)
        x = rs.randn(n, d).astype(np.float32)
        y = rs.randn(n, out.shape[-1]).astype(np.float32)
        ff.fit(x, y, epochs=1, verbose=False)
        return ff, x

    def test_repartition_combine_replicate_pipeline(self):
        def build(ff, x):
            h = ff.dense(x, 32)
            h = ff.repartition(h, dim=0, degree=8)
            h = ff.relu(h)
            h = ff.combine(h, dim=0, degree=8)
            h = ff.replicate(h, degree=8)
            return ff.dense(h, 4)

        ff, x = self._train(build)
        out = ff.predict(x)
        assert out.shape == (16, 4)
        assert np.isfinite(out).all()

    def test_reduction_sums_replica_groups(self):
        def build(ff, x):
            h = ff.dense(x, 32, name="d1")
            return ff.reduction(h, dim=1, degree=4)  # 32 -> 8, sums groups

        ff, x = self._train(build)
        out = ff.predict(x)
        assert out.shape == (16, 8)
        k = ff.get_parameter("d1")
        b = ff.get_parameter("d1", "bias")
        ref = (x @ k + b).reshape(16, 4, 8).sum(axis=1)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


class TestExplicitAxisPinning:
    def test_named_axis_repartition_pins_searched_mesh(self):
        """repartition(dim=0, degree=2, axis="model"): the search must
        pin the NAMED mesh axis (not the dim-derived default) and only
        enumerate meshes the strategy applier will accept (r5 review)."""
        import numpy as np

        from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer

        ff = FFModel(FFConfig(batch_size=32, search_budget=2,
                              enable_parameter_parallel=True))
        t = ff.create_tensor((32, 16))
        h = ff.dense(t, 64)
        h = ff.repartition(h, dim=0, degree=2, axis="model")
        ff.dense(h, 16)
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
        assert axes.get("model", 1) in (1, 2), axes
        rs = np.random.RandomState(0)
        ff.fit(rs.randn(32, 16).astype(np.float32),
               rs.randn(32, 16).astype(np.float32), epochs=1, verbose=False)

"""Conv-family layout overhaul tests (ISSUE 2).

NHWC execution-layout parity vs the NCHW reference path (fwd + bwd, on
CPU), the layout-propagation pass's once-per-chain transpose guarantee,
execution-time Conv+BN(+ReLU) folding parity, the census byte-volume
ratchet, and the _declared_seq multi-extent fix.
"""

import numpy as np
import pytest

import jax

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.ffconst import ActiMode, OperatorType, PoolType

RS = np.random.RandomState(0)
B = 4


def build_conv_chain(layout, fold=True, batch=B):
    """conv -> bn(relu) -> pool -> conv(relu) -> groupnorm -> flat -> dense:
    one conv chain exercising every NHWC-capable op plus pass-through."""
    ff = FFModel(FFConfig(batch_size=batch, only_data_parallel=True,
                          conv_compute_layout=layout, fold_conv_bn=fold))
    t = ff.create_tensor((batch, 3, 16, 16))
    x = ff.conv2d(t, 8, 3, 3, 1, 1, 1, 1)
    x = ff.batch_norm(x, relu=True)
    x = ff.pool2d(x, 2, 2, 2, 2, 0, 0, pool_type=PoolType.POOL_AVG)
    x = ff.conv2d(x, 8, 3, 3, 1, 1, 1, 1, activation=ActiMode.AC_MODE_RELU)
    x = ff.group_norm(x, 4)
    x = ff.flat(x)
    out = ff.dense(x, 10)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [], outputs=out)
    return ff


def build_branchy(layout):
    """Inception-style diamond: one producer feeds parallel conv branches
    that concat on the channel axis — the case where per-op transposes
    would multiply but per-chain placement must not."""
    ff = FFModel(FFConfig(batch_size=B, only_data_parallel=True,
                          conv_compute_layout=layout))
    t = ff.create_tensor((B, 4, 12, 12))
    s = ff.conv2d(t, 8, 3, 3, 1, 1, 1, 1, activation=ActiMode.AC_MODE_RELU)
    b1 = ff.conv2d(s, 8, 1, 1, 1, 1, 0, 0, activation=ActiMode.AC_MODE_RELU)
    b2 = ff.conv2d(s, 8, 3, 3, 1, 1, 1, 1, activation=ActiMode.AC_MODE_RELU)
    b3 = ff.pool2d(s, 3, 3, 1, 1, 1, 1, pool_type=PoolType.POOL_AVG)
    x = ff.concat([b1, b2, b3], axis=1)
    x = ff.flat(x)
    out = ff.dense(x, 5)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [], outputs=out)
    return ff


def leaves(tree):
    return [np.asarray(v) for v in jax.tree.leaves(tree)]


def max_leaf_diff(a, b):
    return max(float(np.abs(x - y).max()) for x, y in zip(leaves(a),
                                                          leaves(b)))


X = RS.randn(8, 3, 16, 16).astype(np.float32)
Y = RS.randint(0, 10, (8, 1)).astype(np.int32)


class TestNHWCParity:
    """NHWC and NCHW execution must agree numerically fwd AND bwd — the
    gradient check runs a full SGD epoch and compares every updated
    parameter and BN running stat."""

    def test_forward_parity(self):
        pa = build_conv_chain("nchw").predict(X[:B])
        pb = build_conv_chain("nhwc").predict(X[:B])
        np.testing.assert_allclose(pa, pb, rtol=1e-4, atol=1e-5)

    def test_backward_parity_via_sgd_epoch(self):
        ffa, ffb = build_conv_chain("nchw"), build_conv_chain("nhwc")
        for ff in (ffa, ffb):
            ff.fit(X, Y, batch_size=B, epochs=1, verbose=False)
        assert max_leaf_diff(ffa.params, ffb.params) < 1e-5
        sa = {k: v for k, v in ffa.state.items() if not k.startswith("__")}
        sb = {k: v for k, v in ffb.state.items() if not k.startswith("__")}
        assert max_leaf_diff(sa, sb) < 1e-5

    def test_branchy_parity(self):
        x = RS.randn(B, 4, 12, 12).astype(np.float32)
        pa = build_branchy("nchw").predict(x)
        pb = build_branchy("nhwc").predict(x)
        np.testing.assert_allclose(pa, pb, rtol=1e-4, atol=1e-5)

    def test_auto_is_nchw_on_cpu(self):
        ff = build_conv_chain("auto")
        assert ff.layout_info["enabled"] is False


class TestLayoutPass:
    def test_one_transpose_pair_per_chain(self):
        ff = build_conv_chain("nhwc")
        info = ff.layout_info
        assert info["enabled"] is True
        # every NHWC-capable op converted, and exactly ONE boundary pair:
        # input->NHWC at the first conv, NHWC->NCHW before flat
        assert info["nhwc_ops"] == 5
        assert info["transposes"] == 2

    def test_branchy_still_one_pair(self):
        ff = build_branchy("nhwc")
        info = ff.layout_info
        # 3 branch heads + concat + stem conv compute NHWC, but the
        # branches share the stem's NHWC value: still one pair total
        assert info["nhwc_ops"] == 5
        assert info["transposes"] == 2

    def test_exec_layout_set_on_ops(self):
        ff = build_conv_chain("nhwc")
        by_type = {}
        for n in ff.executor.nodes:
            by_type.setdefault(n.op.op_type, n.op)
        for t in (OperatorType.CONV2D, OperatorType.POOL2D,
                  OperatorType.BATCHNORM, OperatorType.GROUPNORM):
            assert getattr(by_type[t], "exec_layout", "NCHW") == "NHWC"
        # flat/dense stay on the boundary layout
        assert getattr(by_type[OperatorType.FLAT], "exec_layout",
                       "NCHW") == "NCHW"


class TestConvBNFold:
    def _trained_pair(self, layout="nchw"):
        """Same weights, fold on vs off, after a training epoch (so BN
        running stats are non-trivial)."""
        ffa = build_conv_chain(layout, fold=True)
        ffb = build_conv_chain(layout, fold=False)
        # align initial params by GRAPH order (param dicts come back
        # key-sorted from jit, and guid-suffixed names don't sort stably
        # across builds); copy through host — the jitted step donates its
        # param buffers, so aliasing them between models would leave the
        # second model holding deleted arrays
        import jax.numpy as jnp
        names_a = [n.op.name for n in ffa.executor.nodes
                   if n.op.name in ffa.params]
        names_b = [n.op.name for n in ffb.executor.nodes
                   if n.op.name in ffb.params]
        for ka, kb in zip(names_a, names_b):
            for pn in ffa.params[ka]:
                ffb.params[kb][pn] = jnp.asarray(np.asarray(ffa.params[ka][pn]))
        ffb._compute_params_dirty = True
        ffa.fit(X, Y, batch_size=B, epochs=1, verbose=False)
        ffb.fit(X, Y, batch_size=B, epochs=1, verbose=False)
        return ffa, ffb

    def test_fold_applied_to_inference_nodes_only(self):
        ff = build_conv_chain("nchw", fold=True)
        full = ff.executor.nodes
        folded = ff.executor._inference_nodes()
        assert len(folded) == len(full) - 1  # conv+bn pair collapsed
        names = [n.op.name for n in folded]
        assert any("+" in n for n in names)
        # training step untouched
        assert len(ff.executor.nodes) == len(full)

    def test_fold_parity_eval_and_predict(self):
        ffa, ffb = self._trained_pair()
        pa, pb = ffa.predict(X[:B]), ffb.predict(X[:B])
        np.testing.assert_allclose(pa, pb, rtol=1e-4, atol=1e-5)
        ea = ffa.evaluate(X, Y, batch_size=B)
        eb = ffb.evaluate(X, Y, batch_size=B)
        assert abs(ea["loss"] - eb["loss"]) < 1e-4

    def test_fold_parity_nhwc(self):
        ffa, ffb = self._trained_pair("nhwc")
        np.testing.assert_allclose(ffa.predict(X[:B]), ffb.predict(X[:B]),
                                   rtol=1e-4, atol=1e-5)

    def test_conv_with_activation_not_folded(self):
        """A conv that owns an activation cannot fold into the BN."""
        ff = FFModel(FFConfig(batch_size=B, only_data_parallel=True))
        t = ff.create_tensor((B, 3, 8, 8))
        x = ff.conv2d(t, 4, 3, 3, 1, 1, 1, 1,
                      activation=ActiMode.AC_MODE_RELU)
        x = ff.batch_norm(x, relu=False)
        x = ff.flat(x)
        out = ff.dense(x, 3)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [],
                   outputs=out)
        assert len(ff.executor._inference_nodes()) == len(ff.executor.nodes)


class TestBf16ConvCoverage:
    def test_convs_compute_bf16_under_master_weights(self):
        """The master-weight regime's bf16 compute must actually COVER
        the conv family: every convolution in the compiled train step
        runs on bf16 operands (the BN statistics deliberately stay f32 —
        conv.py). Compiling against a TPU machine spec selects bf16 even
        on the CPU backend, so the emitted HLO is checkable here."""
        from flexflow_tpu.machine import MachineSpec
        from flexflow_tpu.search.validate import train_step_hlo

        ff = FFModel(FFConfig(batch_size=B, only_data_parallel=True,
                              conv_compute_layout="nhwc"))
        t = ff.create_tensor((B, 3, 8, 8))
        x = ff.conv2d(t, 4, 3, 3, 1, 1, 1, 1)
        x = ff.batch_norm(x, relu=True)
        x = ff.flat(x)
        out = ff.dense(x, 3)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [],
                   machine_spec=MachineSpec(chip="tpu-v5e"), outputs=out)
        import jax.numpy as jnp
        assert ff.executor.compute_dtype == jnp.bfloat16
        hlo = train_step_hlo(ff)
        conv_lines = [l for l in hlo.splitlines() if "convolution(" in l]
        assert conv_lines, "no convolution in the compiled step"
        f32_convs = [l for l in conv_lines if "f32[" in l.split(" = ")[0]
                     and "bf16" not in l]
        assert not f32_convs, f"f32 convolutions leaked: {f32_convs[:2]}"


class TestNHWCOpMeasurable:
    def test_profile_measures_nhwc_conv_standalone(self):
        """The roofline/calibration channel must be able to time NHWC
        ops: example inputs follow the execution layout."""
        from flexflow_tpu.search.profile import measure_op, op_cost_key

        ff = build_conv_chain("nhwc", batch=2)
        conv = next(n.op for n in ff.executor.nodes
                    if n.op.op_type == OperatorType.CONV2D)
        assert conv.exec_layout == "NHWC"
        fwd, bwd = measure_op(conv, repeats=1, warmup=0)
        assert fwd > 0 and bwd > 0
        # layout is part of the measurement identity
        nchw = build_conv_chain("nchw", batch=2)
        conv2 = next(n.op for n in nchw.executor.nodes
                     if n.op.op_type == OperatorType.CONV2D)
        assert op_cost_key(conv) != op_cost_key(conv2)


class TestCensusByteRatchet:
    def _bench(self):
        import importlib
        import bench
        return importlib.reload(bench)

    def test_first_run_records_baseline(self):
        bench = self._bench()
        hist = {}
        reg, base = bench.census_ratchet(hist, "fam:cpu", 1024.0)
        assert reg is False and base is None
        assert hist["fam:cpu"]["collective_bytes"] == 1024.0

    def test_regression_flagged_and_baseline_kept(self):
        bench = self._bench()
        hist = {"fam:cpu": {"collective_bytes": 1000.0,
                            "samples_per_s": 5.0}}
        reg, base = bench.census_ratchet(hist, "fam:cpu", 1200.0)
        assert reg is True and base == 1000.0
        assert hist["fam:cpu"]["collective_bytes"] == 1000.0

    def test_lower_bytes_ratchet_down(self):
        bench = self._bench()
        hist = {"fam:cpu": {"collective_bytes": 1000.0}}
        reg, _ = bench.census_ratchet(hist, "fam:cpu", 900.0)
        assert reg is False
        assert hist["fam:cpu"]["collective_bytes"] == 900.0

    def test_throughput_ratchet_preserves_byte_baseline(self):
        bench = self._bench()
        hist = {"fam:cpu": {"samples_per_s": 5.0,
                            "collective_bytes": 1000.0}}
        bench.ratchet(hist, "fam:cpu", 6.0, {"bs": 8}, "best1x5")
        assert hist["fam:cpu"]["collective_bytes"] == 1000.0
        assert hist["fam:cpu"]["samples_per_s"] == 6.0

    def test_equal_volume_green(self):
        bench = self._bench()
        hist = {"fam:cpu": {"collective_bytes": 1000.0}}
        reg, _ = bench.census_ratchet(hist, "fam:cpu", 1000.0)
        assert reg is False


class TestDeclaredSeqMultiExtent:
    def test_disagreeing_seq_extents_disable_bucketing(self):
        """Two rank-3 paths with different position extents: no single
        bucketable sequence — _declared_seq must return None (full-length
        path) instead of whichever op iterated last (ADVICE r5)."""
        ff = FFModel(FFConfig(batch_size=B, only_data_parallel=True))
        a = ff.create_tensor((B, 12, 8))
        b = ff.create_tensor((B, 20, 8))
        xa = ff.relu(ff.dense(a, 8))
        xb = ff.relu(ff.dense(b, 8))
        x = ff.concat([xa, xb], axis=1)
        x = ff.flat(x)
        out = ff.dense(x, 4)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [],
                   outputs=out)
        assert ff._declared_seq() is None
        # and the iteration protocol quietly runs full-length
        xs = [RS.randn(B, 12, 8).astype(np.float32),
              RS.randn(B, 20, 8).astype(np.float32)]
        y = RS.randint(0, 4, (B, 1)).astype(np.int32)
        ff.set_batch(xs, y)
        ff.forward(seq_length=10)
        ff.backward()
        ff.update()
        assert np.isfinite(float(ff._last_loss))

    def test_single_extent_still_found(self):
        ff = FFModel(FFConfig(batch_size=B, only_data_parallel=True))
        a = ff.create_tensor((B, 16, 8))
        x = ff.relu(ff.dense(a, 8))
        x = ff.flat(x)
        out = ff.dense(x, 4)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [],
                   outputs=out)
        assert ff._declared_seq() == 16


class TestAllgatherValue:
    def test_single_process_identity(self):
        from flexflow_tpu import distributed as dist
        assert dist.allgather_value(7) == [7]


class TestRooflineReport:
    def test_report_and_markdown(self):
        from flexflow_tpu.machine import MachineSpec
        from flexflow_tpu.obs.roofline import (finish_aggregates,
                                               format_markdown,
                                               roofline_report)
        ff = build_conv_chain("nchw", batch=2)
        spec = MachineSpec(chip="cpu-sim")
        rep = roofline_report(ff.executor.nodes, spec, repeats=1,
                              include_bwd=False)
        rows = [r for r in rep["rows"] if "fwd_s" in r]
        assert rows, "no op measured"
        for r in rows:
            assert r["bound"] in ("compute", "bandwidth")
            assert r["fwd_s"] > 0
        assert "conv" in rep["classes"]
        finish_aggregates(rep["classes"],
                          rep["machine"]["peak_flops"])
        assert rep["classes"]["conv"]["efficiency"] is not None
        md = format_markdown(rep)
        assert "Per-class aggregates" in md
        assert "| conv |" in md

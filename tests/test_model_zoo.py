"""Model zoo (SURVEY §1 L9): every reference example family builds,
compiles, and takes one training step on the virtual mesh.

Small configs keep CPU runtime sane; the full reference configs are the
defaults in flexflow_tpu/models and run in examples/ + scripts/osdi22ae.
"""

import numpy as np
import pytest

from flexflow_tpu import (AdamOptimizer, FFConfig, FFModel, LossType,
                          MetricsType, SGDOptimizer)
from flexflow_tpu.models import (CandleUnoConfig, DLRMConfig, InceptionConfig,
                                 MoEConfig, ResNeXtConfig, ResNetConfig,
                                 XDLConfig, create_candle_uno, create_dlrm,
                                 create_inception_v3, create_moe,
                                 create_moe_encoder, create_resnet,
                                 create_resnext50, create_xdl)

RS = np.random.RandomState(0)


def one_step(ff, xs, y, loss=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
             metrics=(MetricsType.ACCURACY,), opt=None):
    ff.compile(opt or SGDOptimizer(lr=0.01), loss, list(metrics))
    ff.set_batch(xs, y)
    ff.forward()
    ff.zero_gradients()
    ff.backward()
    ff.update()
    assert np.isfinite(float(ff._last_loss if hasattr(ff, "_last_loss") else 0.0) or 0.0)
    return ff


class TestVisionModels:
    @pytest.mark.slow
    def test_resnet_small(self):
        cfg = ResNetConfig(batch_size=2, image_size=64, stages=(1, 1, 1, 1))
        ff = create_resnet(cfg)
        x = RS.randn(2, 3, 64, 64).astype(np.float32)
        y = RS.randint(0, 10, (2, 1)).astype(np.int32)
        one_step(ff, x, y)

    @pytest.mark.slow
    def test_resnext_small(self):
        cfg = ResNeXtConfig(batch_size=2, image_size=64, stages=(1, 1, 1, 1),
                            cardinality=8)
        ff = create_resnext50(cfg)
        x = RS.randn(2, 3, 64, 64).astype(np.float32)
        y = RS.randint(0, 1000, (2, 1)).astype(np.int32)
        one_step(ff, x, y)

    @pytest.mark.slow
    def test_inception_small(self):
        cfg = InceptionConfig(batch_size=2, image_size=75, num_classes=10)
        ff = create_inception_v3(cfg)
        x = RS.randn(2, 3, 75, 75).astype(np.float32)
        y = RS.randint(0, 10, (2, 1)).astype(np.int32)
        one_step(ff, x, y)


class TestRecsysModels:
    def test_dlrm(self):
        cfg = DLRMConfig(batch_size=8, vocab_size=1000, num_sparse_features=4)
        ff = create_dlrm(cfg)
        xs = [RS.randint(0, 1000, (8, 1)).astype(np.int32)
              for _ in range(4)] + [RS.randn(8, cfg.dense_dim).astype(np.float32)]
        y = RS.rand(8, 1).astype(np.float32)
        one_step(ff, xs, y, loss=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
                 metrics=(MetricsType.MEAN_SQUARED_ERROR,))

    def test_xdl(self):
        cfg = XDLConfig(batch_size=8, embedding_size=(1000, 1000))
        ff = create_xdl(cfg)
        xs = [RS.randint(0, 1000, (8, 1)).astype(np.int32) for _ in range(2)]
        y = RS.randint(0, 2, (8, 1)).astype(np.int32)
        one_step(ff, xs, y)


class TestCandleUno:
    def test_small_towers(self):
        cfg = CandleUnoConfig(batch_size=8, dense_layers=(32,) * 2,
                              dense_feature_layers=(32,) * 2,
                              input_features={"dose1": 1, "cell": 24,
                                              "drug_desc": 40})
        ff = create_candle_uno(cfg)
        xs = [RS.randn(8, d).astype(np.float32) for d in (1, 24, 40)]
        y = RS.rand(8, 1).astype(np.float32)
        one_step(ff, xs, y, loss=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
                 metrics=(MetricsType.MEAN_SQUARED_ERROR,))


def _fflint_cli():
    """The fflint CLI module — its ZOO list is the single source of
    truth for 'every zoo model', so a model added there is
    automatically swept here too."""
    import importlib.util
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "fflint_cli", os.path.join(repo, "scripts", "fflint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestCensusInvariant:
    """ROADMAP "collective census as a search invariant", closed: for
    EVERY zoo model, the searched strategy's statically-inferred
    collective set must be covered by the set the native simulator
    priced (fflint collective-inference pass, FFL204/FFL201 are
    ERROR-severity). Since the edge-level dataflow (analysis/dataflow.py)
    the inference is per-edge, so this is also the zoo-wide
    "every searched strategy lints EDGE-clean" invariant: any implicit
    producer→consumer reshard the replay priced zero bytes for is an
    FFL210 ERROR, and an accepted substitution rewrite that regressed
    the edge-spec map is an FFL213 ERROR — both fail here. A model
    whose searched strategy implies data movement the search never
    costed fails CI here, not on the chip."""

    # inception is the slowest twin (~36s, 5x the next) and the
    # invariant is per-model-identical; tier-1 keeps the other four.
    @pytest.mark.analysis
    @pytest.mark.parametrize(
        "name",
        [pytest.param(n, marks=[pytest.mark.slow] if n == "inception"
                      else []) for n in _fflint_cli().ZOO])
    def test_searched_strategy_collectives_are_priced(self, name):
        from flexflow_tpu.search.native import available
        if not available():
            pytest.skip("native search unavailable")
        from flexflow_tpu.analysis import LintContext, run_passes
        from flexflow_tpu.analysis.passes.collectives import (
            CollectiveInferencePass)

        cli = _fflint_cli()
        cfg = FFConfig()
        cfg.search_budget = 4
        cfg.enable_parameter_parallel = True
        cfg.enable_pipeline_parallel = False
        ff, loss_kind = cli.build_model(name, cfg)
        cli.compile_model(ff, loss_kind)
        ctx = LintContext(
            nodes=ff.executor.nodes, mesh=ff.mesh, strategy=ff.strategy,
            machine_spec=ff.machine_spec, config=ff.config,
            final_ref=ff.executor.final_ref, ff=ff)
        rep = run_passes(ctx, [CollectiveInferencePass()])
        assert rep.passes["collective-inference"] == "ok", rep.passes
        errors = rep.errors
        assert not errors, (
            f"{name}: searched strategy carries unpriced collectives:\n"
            + "\n".join(d.format() for d in errors))


class TestCensusByteDrift:
    """ISSUE 4 satellite: the simulator underpriced the vocab-parallel
    embedding gradient all-reduce (~7x) and channel-parallel conv
    resharding (~3x) — fflint FFL202 WARNINGs from PR 3 (ROADMAP). With
    the col-bwd-AR, replicated-scatter-grad, and tiny-batch
    weight-movement terms priced (native/ffs_strategy.hpp), the searched
    strategies' emitted census must sit within the 3x byte tolerance of
    the priced set: no under-priced kind survives.

    ISSUE 18 closes the PR 3 follow-on: the EDGE-level bytes
    (analysis/dataflow.py — per producer→consumer spec disagreement)
    are now the reference. Per kind, the native-priced set must cover
    the statically-inferred edge bytes, so the embedding/conv
    all-gather underpricing the census pass found cannot silently
    reopen: a native pricing term that drops below the edge-derived
    lower bound fails here as drift."""

    def _drift(self, name):
        from flexflow_tpu.search.native import available
        if not available():
            pytest.skip("native search unavailable")
        cli = _fflint_cli()
        cfg = FFConfig()
        cfg.search_budget = 4
        cfg.enable_parameter_parallel = True
        cfg.enable_pipeline_parallel = False
        ff, loss_kind = cli.build_model(name, cfg)
        cli.compile_model(ff, loss_kind)
        from flexflow_tpu.search.validate import (COLLECTIVE_COVER,
                                                  diff_collectives,
                                                  emitted_collectives,
                                                  priced_collectives,
                                                  train_step_hlo)
        priced = priced_collectives(ff)
        emitted = emitted_collectives(train_step_hlo(ff))
        # under-pricing only: phantom priced collectives ("emitted none")
        # are over-counts, the safe direction for the DP's ranking
        problems = [p for p in diff_collectives(priced, emitted)
                    if "emitted none" not in p]
        # edge-bytes-as-reference (same searched build, no extra search):
        # the statically-inferred implicit edge + weight-movement bytes
        # are a LOWER bound GSPMD will realize — the priced cover of each
        # kind must at least reach it or the search ranked blind
        from flexflow_tpu.analysis import (LintContext, edge_reshard_table,
                                           weight_movement_edges)
        ctx = LintContext(
            nodes=ff.executor.nodes, mesh=ff.mesh, strategy=ff.strategy,
            machine_spec=ff.machine_spec, config=ff.config,
            final_ref=ff.executor.final_ref, ff=ff)
        edge_bytes = {}
        for e in list(edge_reshard_table(ctx)) + weight_movement_edges(ctx):
            if e.explicit or e.kind == "slice" or e.bytes < (1 << 12):
                continue
            edge_bytes[e.kind] = edge_bytes.get(e.kind, 0.0) + e.bytes
        for kind, eb in edge_bytes.items():
            pb = sum(priced.get(k, 0.0)
                     for k in COLLECTIVE_COVER.get(kind, {kind}))
            if pb < eb:
                problems.append(
                    f"{kind}: edge-inferred {eb / 1e6:.2f} MB exceeds "
                    f"priced cover {pb / 1e6:.2f} MB — native pricing "
                    f"dropped below the static edge reference")
        return problems

    @pytest.mark.analysis
    def test_searched_xdl_byte_drift_shrinks(self):
        under = self._drift("xdl")
        assert not under, "\n".join(under)

    @pytest.mark.analysis
    @pytest.mark.slow
    def test_searched_resnet_byte_drift_shrinks(self):
        # slow tier (t1 budget): the drift machinery itself stays tier-1
        # via the xdl variant above; resnet adds the conv-reshard case
        under = self._drift("resnet")
        assert not under, "\n".join(under)


class TestMoE:
    def test_flat_moe_trains_and_balances(self):
        cfg = MoEConfig(batch_size=16, input_dim=32, num_exp=4, num_select=2,
                        hidden_size=16)
        ff = create_moe(cfg)
        x = RS.randn(64, 32).astype(np.float32)
        y = RS.randint(0, 10, (64, 1)).astype(np.int32)
        ff.compile(AdamOptimizer(alpha=1e-3),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.ACCURACY])
        ff.fit(x, y, epochs=2, verbose=False)  # aux load-balance loss active

    def test_moe_encoder(self):
        cfg = MoEConfig(batch_size=4, num_encoder_layers=2, hidden_size=16,
                        num_exp=2, num_select=1, seq_length=8, num_classes=5)
        ff = create_moe_encoder(cfg)
        x = RS.randn(4, 8, 16).astype(np.float32)
        y = RS.randint(0, 5, (4, 8, 1)).astype(np.int32)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
                   [MetricsType.MEAN_SQUARED_ERROR])
        ff.set_batch(x, RS.randn(4, 8, 5).astype(np.float32))
        ff.forward(); ff.backward(); ff.update()

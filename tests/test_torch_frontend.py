"""torch.fx frontend (SURVEY §2.6, python/flexflow/torch/model.py parity).

Traces torch modules, translates to FFModel, checks numerics against the
torch CPU forward (the reference's tests/align strategy)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.torch import PyTorchModel, torch_to_ff_file


class SmallMLP(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.act = nn.ReLU()
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


class BranchyNet(nn.Module):
    def __init__(self):
        super().__init__()
        self.a = nn.Linear(16, 32)
        self.b = nn.Linear(16, 32)
        self.out = nn.Linear(32, 4)

    def forward(self, x):
        return self.out(torch.relu(self.a(x)) + torch.tanh(self.b(x)))


class ScalarLeftNet(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(16, 4)

    def forward(self, x):
        return 1.0 - self.fc(x) * 0.5


class AttnNet(nn.Module):
    def __init__(self):
        super().__init__()
        self.attn = nn.MultiheadAttention(32, 4, batch_first=True)
        self.fc = nn.Linear(32, 4)

    def forward(self, x):
        out, _ = self.attn(x, x, x)
        return self.fc(out)


class SmallCNN(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2d(1, 4, 3)
        self.pool = nn.MaxPool2d(2)
        self.flat = nn.Flatten()
        self.fc = nn.Linear(4 * 5 * 5, 3)

    def forward(self, x):
        return self.fc(self.flat(self.pool(torch.relu(self.conv(x)))))


def build_ff(module, in_shape, batch=8):
    ff = FFModel(FFConfig(batch_size=batch, only_data_parallel=True))
    t = ff.create_tensor((batch,) + in_shape)
    ptm = PyTorchModel(module)
    out = ptm.torch_to_ff(ff, [t])
    ff.compile(SGDOptimizer(lr=0.01), LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
               [MetricsType.MEAN_SQUARED_ERROR])
    return ff, ptm, out


class TestTorchFrontend:
    def test_mlp_alignment(self):
        m = SmallMLP().eval()
        ff, ptm, _ = build_ff(m, (16,))
        copied = ptm.copy_weights_to(ff)
        assert copied == 2
        x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
        ours = ff.predict(x)
        theirs = m(torch.from_numpy(x)).detach().numpy()
        np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-5)

    def test_branches_and_functions(self):
        m = BranchyNet().eval()
        ff, ptm, _ = build_ff(m, (16,))
        ptm.copy_weights_to(ff)
        x = np.random.RandomState(1).randn(8, 16).astype(np.float32)
        np.testing.assert_allclose(ff.predict(x),
                                   m(torch.from_numpy(x)).detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_cnn_alignment(self):
        m = SmallCNN().eval()
        ff, ptm, _ = build_ff(m, (1, 12, 12))
        ptm.copy_weights_to(ff)
        x = np.random.RandomState(2).randn(8, 1, 12, 12).astype(np.float32)
        np.testing.assert_allclose(ff.predict(x),
                                   m(torch.from_numpy(x)).detach().numpy(),
                                   rtol=1e-3, atol=1e-4)

    def test_scalar_on_left_sub(self):
        # 1 - y must NOT translate to y - 1 (operand order regression)
        m = ScalarLeftNet().eval()
        ff, ptm, _ = build_ff(m, (16,))
        ptm.copy_weights_to(ff)
        x = np.random.RandomState(4).randn(8, 16).astype(np.float32)
        np.testing.assert_allclose(ff.predict(x),
                                   m(torch.from_numpy(x)).detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_multihead_attention_with_getitem(self):
        # nn.MultiheadAttention returns a tuple; fx traces getitem[0]
        m = AttnNet().eval()
        ff, ptm, out = build_ff(m, (6, 32))
        assert out.shape == (8, 6, 4)
        x = np.random.RandomState(5).randn(8, 6, 32).astype(np.float32)
        assert np.isfinite(ff.predict(x)).all()

    def test_ff_file_roundtrip(self, tmp_path):
        path = str(tmp_path / "model.ff")
        torch_to_ff_file(SmallMLP(), path, {"x": (16,)})
        ptm = PyTorchModel.from_file(path)  # no torch needed from here on
        ff = FFModel(FFConfig(batch_size=8, only_data_parallel=True))
        t = ff.create_tensor((8, 16))
        ptm.torch_to_ff(ff, [t])
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        x = np.random.RandomState(3).randn(8, 16).astype(np.float32)
        assert ff.predict(x).shape == (8, 4)

    def test_training_through_traced_graph(self):
        ff, ptm, _ = build_ff(SmallMLP(), (16,), batch=32)
        rs = np.random.RandomState(0)
        x = rs.randn(64, 16).astype(np.float32)
        y = rs.randn(64, 4).astype(np.float32)
        ff.fit(x, y, epochs=2, verbose=False)  # trains without error

    def test_bare_parameter_stays_trainable(self):
        """A bare nn.Parameter used directly in forward (learned
        positional embedding) must lower to a TRAINABLE leaf, not a baked
        Const (advisor r4: training semantics silently diverged)."""

        class PosMLP(nn.Module):
            def __init__(self):
                super().__init__()
                self.pos = nn.Parameter(torch.randn(16) * 0.1)
                self.fc = nn.Linear(16, 4)

            def forward(self, x):
                return self.fc(x + self.pos)

        m = PosMLP()
        ff, ptm, _ = build_ff(m, (16,), batch=8)
        # forward parity with torch
        ptm.copy_weights_to(ff)
        rs = np.random.RandomState(0)
        x = rs.randn(8, 16).astype(np.float32)
        want = m(torch.from_numpy(x)).detach().numpy()
        np.testing.assert_allclose(ff.predict(x), want, rtol=1e-4, atol=1e-5)
        # the parameter must move under training
        const_layers = [l.name for l in ff.layers
                        if l.properties.get("trainable")]
        assert const_layers, "bare nn.Parameter was not lowered trainable"
        before = np.asarray(ff.params[const_layers[0]]["weight"]).copy()
        y = rs.randn(8, 4).astype(np.float32)
        ff.fit(x, y, epochs=2, verbose=False)
        after = np.asarray(ff.params[const_layers[0]]["weight"])
        assert not np.allclose(before, after), \
            "trainable Const did not receive gradient updates"


class TransformerBlockNet(nn.Module):
    """GPT-style block built from standard torch pieces (VERDICT r2 #7:
    the frontend must trace nn.MultiheadAttention-based transformers)."""

    def __init__(self, e=32, h=4, f=64):
        super().__init__()
        self.ln1 = nn.LayerNorm(e)
        self.attn = nn.MultiheadAttention(e, h, batch_first=True)
        self.ln2 = nn.LayerNorm(e)
        self.ff1 = nn.Linear(e, f)
        self.ff2 = nn.Linear(f, e)
        self.head = nn.Linear(e, 4)

    def forward(self, x):
        a, _ = self.attn(self.ln1(x), self.ln1(x), self.ln1(x),
                         need_weights=False)
        x = x + a
        x = x + self.ff2(torch.relu(self.ff1(self.ln2(x))))
        return self.head(x)


class TestTransformerTracing:
    def test_mha_block_matches_torch(self):
        torch.manual_seed(0)
        m = TransformerBlockNet().eval()
        ff, ptm, _ = build_ff(m, (8, 32), batch=4)
        assert ptm.copy_weights_to(ff) >= 6  # attn + 2 ln + 3 linear
        x = np.random.RandomState(0).randn(4, 8, 32).astype(np.float32)
        want = m(torch.from_numpy(x)).detach().numpy()
        got = ff.predict(x)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)

    @pytest.mark.parametrize("norm_first", [False, True])
    def test_nn_transformer_encoder_matches_torch(self, norm_first):
        torch.manual_seed(1)

        class EncNet(nn.Module):
            def __init__(self):
                super().__init__()
                self.enc = nn.TransformerEncoder(
                    nn.TransformerEncoderLayer(
                        32, 4, 64, dropout=0.0, batch_first=True,
                        norm_first=norm_first), num_layers=2)
                self.head = nn.Linear(32, 4)

            def forward(self, x):
                return self.head(self.enc(x))

        m = EncNet().eval()
        ff, ptm, _ = build_ff(m, (8, 32), batch=4)
        assert ptm.copy_weights_to(ff) >= 11  # 2 layers x 5 mods + head
        x = np.random.RandomState(1).randn(4, 8, 32).astype(np.float32)
        want = m(torch.from_numpy(x)).detach().numpy()
        got = ff.predict(x)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)

    def test_traced_transformer_trains_one_step_matches_torch(self):
        # one SGD step on the traced graph vs torch autograd: same loss
        # trajectory (MSE, lr 0.1) — the VERDICT's "trains and matches
        # torch numerics for one step" bar
        torch.manual_seed(2)
        m = TransformerBlockNet()
        ff, ptm, _ = build_ff(m, (8, 32), batch=4)
        ptm.copy_weights_to(ff)
        rs = np.random.RandomState(2)
        x = rs.randn(4, 8, 32).astype(np.float32)
        y = rs.randn(4, 8, 4).astype(np.float32)

        # initial losses agree (weights imported faithfully)
        crit = nn.MSELoss()
        loss_t0 = float(crit(m(torch.from_numpy(x)), torch.from_numpy(y)))
        pred0 = ff.predict(x)
        np.testing.assert_allclose(float(((pred0 - y) ** 2).mean()),
                                   loss_t0, rtol=1e-3)

        # one SGD step each side (lr matches build_ff's compile) → losses
        # still agree
        opt = torch.optim.SGD(m.parameters(), lr=0.01)
        crit(m(torch.from_numpy(x)), torch.from_numpy(y)).backward()
        opt.step()
        loss_t1 = float(crit(m(torch.from_numpy(x)), torch.from_numpy(y)))
        ff.fit(x, y, epochs=1, verbose=False)
        pred1 = ff.predict(x)
        np.testing.assert_allclose(float(((pred1 - y) ** 2).mean()),
                                   loss_t1, rtol=5e-2)

    def test_function_kinds_broadened(self):
        class FnNet(nn.Module):
            def forward(self, x):
                a = torch.exp(x).rsqrt()
                b = torch.sqrt(torch.relu(x) + 1.0)
                c, d = torch.chunk(a * b, 2, dim=1)
                e = torch.stack([c, d], dim=1)
                f = e.reshape(e.shape[0], -1)
                return nn.functional.silu(f).unsqueeze(1).squeeze(1)

        m = FnNet().eval()
        ff, ptm, _ = build_ff(m, (16,), batch=4)
        x = np.random.RandomState(3).rand(4, 16).astype(np.float32) + 0.5
        want = m(torch.from_numpy(x)).detach().numpy()
        got = ff.predict(x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestFunctionalPooling:
    def test_functional_pools_match_torch(self):
        class PoolNet(nn.Module):
            def forward(self, x):
                a = nn.functional.max_pool2d(torch.relu(x), 2, 2)
                b = nn.functional.avg_pool2d(a, kernel_size=2)
                return nn.functional.adaptive_avg_pool2d(b, 1).flatten(1)

        m = PoolNet().eval()
        ff, ptm, _ = build_ff(m, (3, 16, 16), batch=2)
        x = np.random.RandomState(7).randn(2, 3, 16, 16).astype(np.float32)
        want = m(torch.from_numpy(x)).detach().numpy()
        got = ff.predict(x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


# ---- r4 depth: GPT-2-class module + new translation kinds ------------------

class MiniGPT2(nn.Module):
    """GPT-2-style causal block written with plain torch ops: packed qkv
    Linear + chunk(3) + view/transpose + matmul + additive causal-mask
    buffer (get_attr) + softmax + GELU MLP. The shape of module the
    reference's HF-aware tracer targeted (torch/model.py:2424-2444)."""

    def __init__(self, e=32, h=4, s=8):
        super().__init__()
        self.e, self.h, self.s = e, h, s
        self.ln_1 = nn.LayerNorm(e)
        self.c_attn = nn.Linear(e, 3 * e)
        self.c_proj = nn.Linear(e, e)
        self.ln_2 = nn.LayerNorm(e)
        self.mlp_fc = nn.Linear(e, 4 * e)
        self.mlp_proj = nn.Linear(4 * e, e)
        bias = (1.0 - torch.tril(torch.ones(s, s))) * -1e9
        self.register_buffer("attn_bias", bias.view(1, 1, s, s))

    def forward(self, x):
        b = x.shape[0]
        e, h, s = self.e, self.h, self.s
        d = e // h
        a = self.ln_1(x)
        qkv = self.c_attn(a)
        q, k, v = qkv.chunk(3, dim=2)
        q = q.view(b, s, h, d).transpose(1, 2)
        k = k.view(b, s, h, d).transpose(1, 2)
        v = v.view(b, s, h, d).transpose(1, 2)
        att = torch.matmul(q, k.transpose(2, 3)) * (1.0 / d ** 0.5)
        att = att + self.attn_bias
        att = torch.softmax(att, dim=-1)
        y = torch.matmul(att, v)
        y = y.transpose(1, 2).reshape(b, s, e)
        x = x + self.c_proj(y)
        m = self.mlp_proj(torch.nn.functional.gelu(self.mlp_fc(self.ln_2(x))))
        return x + m


class TestGPT2ClassModule:
    def test_traces_matches_and_trains(self):
        torch.manual_seed(0)
        m = MiniGPT2().eval()
        ff, ptm, _ = build_ff(m, (8, 32), batch=4)
        ptm.copy_weights_to(ff)
        x = np.random.RandomState(0).randn(4, 8, 32).astype(np.float32)
        ours = ff.predict(x)
        theirs = m(torch.from_numpy(x)).detach().numpy()
        np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-4)
        # trains one step without error and the loss is finite
        y = np.random.RandomState(1).randn(4, 8, 32).astype(np.float32)
        ff.fit(x, y, epochs=1, verbose=False)
        assert np.isfinite(ff.predict(x)).all()


class NewKindsNet(nn.Module):
    """Exercises einsum, masked_fill, where, clamp, expand, abs,
    log_softmax, amax in one traced module."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(16, 16)
        self.register_buffer("mask",
                             (torch.arange(16) % 2 == 0).float())

    def forward(self, x):
        h = self.fc(x)
        h = h.masked_fill(self.mask > 0.5, 0.25)
        h = torch.clamp(h, min=-2.0, max=2.0)
        g = torch.einsum("bi,bj->bij", h, h)
        g = g.amax(dim=2)
        g = torch.abs(g)
        z = torch.where(self.mask > 0.5, g, h)
        return torch.log_softmax(z, dim=-1)


class TestNewTranslationKinds:
    def test_new_kinds_alignment(self):
        torch.manual_seed(0)
        m = NewKindsNet().eval()
        ff, ptm, _ = build_ff(m, (16,), batch=8)
        ptm.copy_weights_to(ff)
        x = np.random.RandomState(2).randn(8, 16).astype(np.float32)
        ours = ff.predict(x)
        theirs = m(torch.from_numpy(x)).detach().numpy()
        np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-5)

    def test_group_norm_and_silu(self):
        class GN(nn.Module):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2d(8, 8, 3, padding=1)
                self.gn = nn.GroupNorm(4, 8)
                self.act = nn.SiLU()

            def forward(self, x):
                return self.act(self.gn(self.conv(x)))

        torch.manual_seed(0)
        m = GN().eval()
        ff, ptm, _ = build_ff(m, (8, 8, 8), batch=4)
        ptm.copy_weights_to(ff)
        x = np.random.RandomState(3).randn(4, 8, 8, 8).astype(np.float32)
        np.testing.assert_allclose(ff.predict(x),
                                   m(torch.from_numpy(x)).detach().numpy(),
                                   rtol=1e-3, atol=1e-4)

    def test_sdpa_function(self):
        class SDPA(nn.Module):
            def __init__(self):
                super().__init__()
                self.qkv = nn.Linear(32, 96)

            def forward(self, x):  # x [B, 4, 8, 32] as [B,H,S,E']
                q, k, v = self.qkv(x).chunk(3, dim=-1)
                return torch.nn.functional.scaled_dot_product_attention(
                    q, k, v, is_causal=True)

        torch.manual_seed(0)
        m = SDPA().eval()
        ff, ptm, _ = build_ff(m, (4, 8, 32), batch=2)
        ptm.copy_weights_to(ff)
        x = np.random.RandomState(4).randn(2, 4, 8, 32).astype(np.float32)
        np.testing.assert_allclose(ff.predict(x),
                                   m(torch.from_numpy(x)).detach().numpy(),
                                   rtol=2e-3, atol=2e-4)


class TestHFStateDictPath:
    def test_llama_from_torch_weights_through_frontend(self):
        transformers = pytest.importorskip("transformers")
        from flexflow_tpu.torch.model import from_hf_causal_lm

        hf_cfg = transformers.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=32,
            rms_norm_eps=1e-6, rope_theta=10000.0,
            attention_bias=False, tie_word_embeddings=False)
        torch.manual_seed(0)
        hf = transformers.LlamaForCausalLM(hf_cfg).eval()
        ff, load = from_hf_causal_lm(hf, batch_size=2, seq_length=8)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
        assert load() == 3 + 9 * 2
        rs = np.random.RandomState(0)
        ids = rs.randint(0, 64, (2, 8)).astype(np.int32)
        want = hf(torch.from_numpy(ids.astype(np.int64))
                  ).logits.detach().numpy()
        np.testing.assert_allclose(ff.predict(ids), want,
                                   rtol=2e-3, atol=2e-3)

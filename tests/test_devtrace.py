"""Device-trace attribution tests (flexflow_tpu/obs/devtrace, ISSUE 6).

Acceptance: a deviceless CPU ``fit(..., profile_steps=...)`` produces a
merged Perfetto trace containing device lanes plus per-step
compute/comms/exposed-comms attribution, and ``scripts/calibrate.py
--ingest-drift`` folds the measured-vs-priced collective drift into
CALIBRATION.json per-collective corrections.

The parser core is pinned by a committed fixture trace
(tests/fixtures/devtrace_small.trace.json.gz — the exact Chrome-trace
shape ``jax.profiler`` emits on the CPU backend: ``ff_step``
annotations, ``args.hlo_op`` device spans, python-tracer noise) with
hand-computed interval arithmetic the bucket math must reproduce.
"""

import glob
import gzip
import json
import os

import numpy as np
import pytest

from flexflow_tpu import (
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.ffconst import ActiMode
from flexflow_tpu.obs.devtrace import (
    attribute_steps,
    attribution_report,
    classify_hlo_op,
    extract_device_events,
    extract_step_windows,
    intersect_total,
    interval_total,
    load_chrome_trace,
    merge_intervals,
    parse_profile_steps,
)

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "devtrace_small.trace.json.gz")


def build_mlp(batch_size=32):
    ff = FFModel(FFConfig(batch_size=batch_size))
    t = ff.create_tensor((batch_size, 8))
    t = ff.dense(t, 16, activation=ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 4)
    t = ff.softmax(t)
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.ACCURACY])
    return ff


def make_blobs(n=128, d=8, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(classes, d) * 3
    y = rs.randint(0, classes, n)
    x = centers[y] + rs.randn(n, d)
    return x.astype(np.float32), y.astype(np.int32)


class TestParseProfileSteps:
    def test_window(self):
        assert parse_profile_steps("2:4") == (2, 4)
        assert parse_profile_steps("0:1") == (0, 1)

    def test_single_step(self):
        assert parse_profile_steps("3") == (3, 4)

    def test_unset(self):
        assert parse_profile_steps(None) is None
        assert parse_profile_steps("") is None

    def test_invalid(self):
        for bad in ("4:2", "-1:2", "a:b", "2:2"):
            with pytest.raises(ValueError):
                parse_profile_steps(bad)


class TestClassifyHloOp:
    def test_collective_kinds(self):
        for kind in ("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all", "collective-permute",
                     "collective-broadcast"):
            assert classify_hlo_op(kind) == ("collective", kind)
            assert classify_hlo_op(f"{kind}.17") == ("collective", kind)
            # async pairs keep the kind
            assert classify_hlo_op(f"{kind}-start.2") == ("collective",
                                                          kind)

    def test_host_ops(self):
        for name in ("infeed.1", "outfeed", "send.2", "recv-done",
                     "host-call.3"):
            assert classify_hlo_op(name)[0] == "host"

    def test_compute_default(self):
        for name in ("dot.4", "fusion.12", "convert.9", "copy.1",
                     "broadcast_add_fusion.clone",
                     # embedded-but-not-prefix collective substrings
                     # must NOT classify as comms
                     "fused_all_reduce_epilogue"):
            assert classify_hlo_op(name) == ("compute", None)


class TestIntervalMath:
    def test_merge(self):
        assert merge_intervals([(3, 5), (1, 2), (4, 7)]) == [(1, 2),
                                                             (3, 7)]
        assert merge_intervals([(1, 2), (2, 3)]) == [(1, 3)]
        assert merge_intervals([(1, 1), (2, 1)]) == []
        assert interval_total(merge_intervals([(0, 2), (1, 4)])) == 4

    def test_intersect(self):
        a = merge_intervals([(0, 10)])
        b = merge_intervals([(2, 4), (8, 12)])
        assert intersect_total(a, b) == 4
        assert intersect_total(b, a) == 4
        assert intersect_total(a, merge_intervals([(20, 30)])) == 0


class TestFixtureAttribution:
    """Hand-computed interval arithmetic over the committed fixture."""

    def _parsed(self):
        trace = load_chrome_trace(FIXTURE)
        return (extract_device_events(trace),
                extract_step_windows(trace))

    def test_device_events_and_noise_filter(self):
        events, windows = self._parsed()
        # 8 hlo-op spans; python-tracer frames + runtime bookkeeping
        # (no hlo args, host pid) are dropped
        assert len(events) == 8
        assert windows == {0: (1000.0, 2000.0), 1: (2000.0, 3000.0)}

    def test_step0_buckets(self):
        events, windows = self._parsed()
        rows = attribute_steps(events, windows)
        s0 = rows[0]
        assert s0["step"] == 0
        # compute: [1100,1600) u [1950,2000) = 550us (convert.9 clipped
        # at the step boundary)
        assert s0["compute_s"] == pytest.approx(550e-6)
        # comms: AR [1500,1800) + RS [1850,1950) = 400us
        assert s0["comms_s"] == pytest.approx(400e-6)
        # AR overlaps compute on [1500,1600) only
        assert s0["overlapped_comms_s"] == pytest.approx(100e-6)
        assert s0["exposed_comms_s"] == pytest.approx(300e-6)
        assert s0["host_s"] == pytest.approx(50e-6)
        assert s0["idle_s"] == pytest.approx(100e-6)
        assert s0["per_kind"]["all-reduce"]["count"] == 1
        assert s0["per_kind"]["all-reduce"]["time_s"] == pytest.approx(
            300e-6)
        assert s0["per_kind"]["reduce-scatter"]["time_s"] == pytest.approx(
            100e-6)

    def test_step1_fully_overlapped(self):
        events, windows = self._parsed()
        s1 = attribute_steps(events, windows)[1]
        assert s1["compute_s"] == pytest.approx(550e-6)
        assert s1["comms_s"] == pytest.approx(300e-6)
        # the all-gather sits entirely under dot.2: nothing exposed
        assert s1["overlapped_comms_s"] == pytest.approx(300e-6)
        assert s1["exposed_comms_s"] == pytest.approx(0.0, abs=1e-12)
        assert s1["idle_s"] == pytest.approx(450e-6)

    def test_aggregate_report(self):
        rep = attribution_report([FIXTURE])
        assert rep["steps"] == 2
        assert rep["device_events"] == 8
        assert rep["totals"]["compute_s"] == pytest.approx(1100e-6)
        assert rep["totals"]["exposed_comms_s"] == pytest.approx(300e-6)
        # per-kind measured seconds: the drift join's measured half
        coll = rep["collectives"]
        ar = coll["all-reduce"]
        assert ar["time_s"] == pytest.approx(300e-6)
        assert ar["count"] == 1
        assert ar["per_step_s"] == pytest.approx(150e-6)
        # per-kind hidden/exposed split (ISSUE 9): each kind's measured
        # time partitions into overlapped-under-compute + exposed
        for e in coll.values():
            assert e["overlapped_s"] + e["exposed_s"] == pytest.approx(
                e["time_s"])
            assert (e["overlapped_per_step_s"] + e["exposed_per_step_s"]
                    == pytest.approx(e["per_step_s"]))
        # the all-gather sits entirely under compute in the fixture
        assert coll["all-gather"]["per_step_s"] == pytest.approx(150e-6)
        assert coll["all-gather"]["exposed_per_step_s"] == pytest.approx(
            0.0, abs=1e-12)
        assert coll["reduce-scatter"]["per_step_s"] == pytest.approx(50e-6)


class TestRegistryReservoir:
    def test_percentiles_bounded_memory(self):
        from flexflow_tpu.obs.registry import (RESERVOIR_SIZE,
                                               CounterRegistry)
        r = CounterRegistry()
        for i in range(2000):
            r.observe("lat", float(i))
        o = r.to_dict()["observations"]["lat"]
        # streaming summary is exact
        assert o["count"] == 2000.0
        assert o["min"] == 0.0 and o["max"] == 1999.0
        # reservoir percentiles approximate the uniform stream
        assert 600 < o["p50"] < 1400
        assert o["p99"] > o["p50"]
        assert len(r._samples["lat"]) <= RESERVOIR_SIZE

    def test_small_series_exact(self):
        from flexflow_tpu.obs.registry import CounterRegistry
        r = CounterRegistry()
        for v in (1.0, 2.0, 3.0, 4.0):
            r.observe("x", v)
        o = r.to_dict()["observations"]["x"]
        assert o["p50"] == 2.0
        assert o["p99"] == 4.0


class TestMergeClockAlignment:
    """Satellite: per-host traces stamp a shared wall-clock epoch and
    merge shifts events onto it — including devtrace lanes."""

    def test_cross_host_shift_and_lane_rows(self, tmp_path):
        from flexflow_tpu.obs.tracer import StepTracer, merge_host_traces
        td = str(tmp_path)
        trs = []
        for host in (0, 1):
            tr = StepTracer(td, host_id=host, run_name="fit")
            trs.append(tr)
        # same monotonic-relative event on both hosts, but host 1's
        # clock pair says it STARTED 0.25s later in wall time
        trs[1]._wall_origin = trs[0]._wall_origin + 0.25
        for tr in trs:
            with tr.step():
                pass
        # host 0 also carries a devtrace lane event
        trs[0].add_trace_events(
            [dict(name="dot.1", ph="X", tid=64, ts=100.0, dur=10.0,
                  cat="devtrace")],
            {64: "device:compute"})
        for tr in trs:
            assert tr._clock_pair_spread_us >= 0.0
            tr.export()
        data = json.load(open(merge_host_traces(td)))
        steps = {e["pid"]: e for e in data["traceEvents"]
                 if e.get("name") == "step" and e.get("ph") == "X"}
        # host 1's step shifted ~0.25s later on the merged timeline
        assert steps[1]["ts"] - steps[0]["ts"] == pytest.approx(
            0.25e6, rel=0.05)
        # the device lane kept its own thread row, labeled through
        labels = {(e["pid"], e["tid"]): e["args"]["name"]
                  for e in data["traceEvents"]
                  if e.get("name") == "thread_name"}
        lane = [e for e in data["traceEvents"] if e.get("name") == "dot.1"]
        assert len(lane) == 1
        assert labels[(0, lane[0]["tid"])].endswith(":device:compute")
        assert lane[0]["tid"] != steps[0]["tid"]


class TestProfiledFit:
    """The acceptance path: deviceless CPU fit with --profile-steps."""

    @pytest.fixture(scope="class")
    def profiled_run(self, tmp_path_factory):
        # run_t1.sh points FFS_T1_TRACE_DIR at a stable dir so its obs
        # stage can render OBS_REPORT.json from this run's artifacts
        td = os.environ.get("FFS_T1_TRACE_DIR") or str(
            tmp_path_factory.mktemp("devtrace"))
        os.makedirs(td, exist_ok=True)
        x, y = make_blobs()
        ff = build_mlp()
        ff.fit(x, y, epochs=2, verbose=False, trace_dir=td,
               profile_steps="2:4")
        return td, ff

    def _one(self, td, pattern):
        paths = glob.glob(os.path.join(td, pattern))
        assert len(paths) >= 1, f"{pattern}: {paths}"
        return paths[0]

    def test_devtrace_artifact(self, profiled_run):
        td, _ = profiled_run
        dv = json.load(open(self._one(td, "fit_*.devtrace.json")))
        assert dv["window"] == [2, 4]
        assert dv["steps"] == 2
        for row in dv["per_step"]:
            for key in ("compute_s", "comms_s", "overlapped_comms_s",
                        "exposed_comms_s", "host_s", "idle_s", "wall_s"):
                assert key in row
            assert row["compute_s"] > 0
            # dp=8 over the virtual CPU mesh: the grad sync is real
            assert row["comms_s"] > 0
            assert row["exposed_comms_s"] + row["overlapped_comms_s"] == \
                pytest.approx(row["comms_s"])
        assert dv["collectives"]["all-reduce"]["count"] > 0
        assert dv["collectives"]["all-reduce"]["per_step_s"] > 0

    def test_device_lanes_in_trace(self, profiled_run):
        td, _ = profiled_run
        trace = json.load(open(self._one(td, "fit_*.trace.json")))
        events = trace["traceEvents"]
        lanes = {e["args"]["name"] for e in events
                 if e.get("name") == "thread_name"}
        assert {"train_loop", "device:compute", "device:comms"} <= lanes
        comms = [e for e in events if e.get("cat") == "devtrace"
                 and (e.get("args") or {}).get("kind") == "all-reduce"]
        assert comms, "no all-reduce spans on the device lane"
        # per-step attribution counter track
        counters = [e for e in events
                    if e.get("name") == "step_attribution"
                    and e.get("ph") == "C"]
        assert len(counters) == 2
        assert "exposed_comms_ms" in counters[0]["args"]
        # device lanes rebased onto the tracer timeline: each lane span
        # falls inside the host-side span of SOME step
        step_spans = [(e["ts"], e["ts"] + e["dur"]) for e in events
                      if e.get("name") == "step" and e.get("ph") == "X"]
        mid = comms[0]["ts"] + comms[0]["dur"] / 2
        assert any(s - 1e3 <= mid <= e + 1e3 for s, e in step_spans)

    def test_merged_trace_keeps_lanes(self, profiled_run):
        td, _ = profiled_run
        from flexflow_tpu.obs import merge_host_traces
        merged = merge_host_traces(td)
        assert merged is not None
        data = json.load(open(merged))
        labels = {e["args"]["name"] for e in data["traceEvents"]
                  if e.get("name") == "thread_name"}
        assert any(l.endswith(":device:compute") for l in labels)
        assert any(l.endswith(":device:comms") for l in labels)

    def test_drift_report_collective_join(self, profiled_run):
        td, _ = profiled_run
        rep = json.load(open(self._one(td, "fit_*.drift.json")))
        cd = rep["collective_drift"]
        assert "all-reduce" in cd
        assert cd["all-reduce"]["measured_s"] > 0
        assert cd["all-reduce"]["predicted_s"] > 0
        assert cd["all-reduce"]["ratio"] > 0
        sm = rep["step_metrics"]
        assert 0 < sm["goodput"] <= 1.0
        assert sm["mfu"] > 0
        assert sm["step_time_p50"] <= sm["step_time_p99"]

    def test_registry_histograms(self, profiled_run):
        td, _ = profiled_run
        counters = json.load(open(self._one(td, "fit_*.counters.json")))
        obs = counters["observations"]
        st = obs["fit/step_time_s"]
        assert st["count"] >= 7  # 8 steps minus the jit-carrying first
        assert st["p50"] <= st["p99"]
        assert "fit/devtrace_exposed_comms_s" in obs
        assert counters["gauges"]["fit/goodput"] > 0

    def test_obs_report_renders(self, profiled_run, tmp_path):
        td, _ = profiled_run
        import importlib.util
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "obs_report", os.path.join(repo, "scripts", "obs_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        out = str(tmp_path / "OBS_REPORT.json")
        md = str(tmp_path / "OBS_REPORT.md")
        assert mod.main([td, "--out", out, "--md", md]) == 0
        report = json.load(open(out))
        runs = {r["run_name"]: r for r in report["runs"]}
        assert "fit" in runs
        r = runs["fit"]
        assert r["step_time_p50_s"] > 0
        assert r["devtrace"]["exposed_comms_frac"] >= 0
        assert "all-reduce" in r["collective_drift"]
        assert "Measured vs priced collectives" in open(md).read()

    def test_obs_report_empty_dir_nonfatal(self, tmp_path):
        import importlib.util
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "obs_report2", os.path.join(repo, "scripts", "obs_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        out = str(tmp_path / "empty" / "OBS_REPORT.json")
        assert mod.main([str(tmp_path / "empty"), "--out", out]) == 0
        assert json.load(open(out))["runs"] == []

    def test_drift_rows_marked_uningestable_on_cpu(self, profiled_run):
        # deviceless capture: the measured half is host-CPU wall time,
        # the predicted half analytic ICI — the rows must carry
        # ingestable: false so calibration never eats the 400-600x
        # backend-mismatch "drift"
        td, _ = profiled_run
        rep = json.load(open(self._one(td, "fit_*.drift.json")))
        for row in rep["collective_drift"].values():
            assert row["ingestable"] is False

    def test_ingest_skips_cpu_collective_drift(self, profiled_run,
                                               tmp_path, monkeypatch,
                                               capsys):
        """CPU-platform collective-drift rows are skipped with a warning
        by calibrate.py --ingest-drift: no collective_corrections bucket
        is derived from a deviceless run (op_corrections, which ARE
        platform-meaningful, still land in the cpu bucket)."""
        import importlib.util
        td, _ = profiled_run
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "calibrate", os.path.join(repo, "scripts", "calibrate.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        fake_repo = tmp_path / "repo"
        (fake_repo / "scripts").mkdir(parents=True)
        monkeypatch.setattr(mod.os.path, "abspath",
                            lambda p: str(fake_repo / "scripts" / "x.py"))
        assert mod.ingest_drift(td) == 0
        cal = json.load(open(fake_repo / "CALIBRATION.json"))
        assert "cpu" not in (cal.get("collective_corrections") or {})
        assert "cpu" in cal["op_corrections"]
        assert "non-ingestable collective-drift" in capsys.readouterr().out

    def test_ingest_chip_collective_drift_still_lands(self, tmp_path,
                                                      monkeypatch):
        """A TPU-platform drift report (ingestable rows) still derives
        per-kind collective corrections — the skip is CPU-only."""
        import importlib.util
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "calibrate2", os.path.join(repo, "scripts", "calibrate.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        td = tmp_path / "trace"
        td.mkdir()
        rep = dict(
            header=dict(run_name="fit", platform="tpu"),
            predicted=dict(total_s=1e-3), measured=dict(step_s=1.2e-3),
            ratio=1.2, per_op=[],
            collective_drift={"all-reduce": dict(
                predicted_s=1e-4, measured_s=1.3e-4, ratio=1.3,
                ingestable=True)})
        (td / "fit_r00_host00.drift.json").write_text(json.dumps(rep))
        fake_repo = tmp_path / "repo"
        (fake_repo / "scripts").mkdir(parents=True)
        monkeypatch.setattr(mod.os.path, "abspath",
                            lambda p: str(fake_repo / "scripts" / "x.py"))
        assert mod.ingest_drift(str(td)) == 0
        cal = json.load(open(fake_repo / "CALIBRATION.json"))
        corr = cal["collective_corrections"]["tpu"]
        assert corr["all-reduce"]["factor"] == pytest.approx(1.3)

    def test_profile_without_trace_dir_degrades(self, capsys):
        # --profile-steps without --trace-dir must warn and train, not
        # raise mid-fit
        x, y = make_blobs(64)
        ff = build_mlp()
        ff.fit(x, y, epochs=1, verbose=False, profile_steps="0:1")
        assert "profiling skipped" in capsys.readouterr().err


class TestCollectiveCorrectionHook:
    """The machine-model side of the drift closure: measured per-kind
    factors scale collective_time (the wus_rs/ag_time measured hook)."""

    def test_factor_scales_kind(self):
        from flexflow_tpu.machine import MachineSpec
        spec = MachineSpec(chip="tpu-v5e", chips_per_slice=4)
        b = 1 << 20
        base_ar = spec.collective_time("all-reduce", b, 4)
        base_ag = spec.collective_time("all-gather", b, 4)
        spec.collective_corrections = {"all-reduce": 2.0}
        assert spec.collective_time("all-reduce", b, 4) == pytest.approx(
            2.0 * base_ar)
        # uncalibrated kinds are untouched
        assert spec.collective_time("all-gather", b, 4) == pytest.approx(
            base_ag)

    def test_drift_ratio_from_uncorrected_base(self):
        # a run priced with corrections already applied must re-derive
        # the ABSOLUTE factor (measured / uncorrected-analytic), not the
        # ~1.0 residual — otherwise re-ingest would un-calibrate
        from flexflow_tpu.obs.drift import collective_drift
        pred = {"all-reduce": dict(predicted_s=2e-3,
                                   predicted_uncorrected_s=1e-3)}
        meas = {"all-reduce": dict(per_step_s=2e-3)}
        cd = collective_drift(pred, meas)
        assert cd["all-reduce"]["ratio"] == pytest.approx(2.0)
        assert cd["all-reduce"]["predicted_s"] == pytest.approx(2e-3)

    def test_loader_platform_bucketed(self, tmp_path):
        from flexflow_tpu.machine import load_collective_corrections
        cal = tmp_path / "CALIBRATION.json"
        cal.write_text(json.dumps(dict(collective_corrections=dict(
            tpu={"all-reduce": dict(factor=1.3, weight=0.9),
                 "reduce-scatter": dict(factor=0.8, weight=0.4)},
            cpu={"all-reduce": dict(factor=500.0, weight=1.0)}))))
        corr = load_collective_corrections("tpu", path=str(cal))
        assert corr == {"all-reduce": 1.3, "reduce-scatter": 0.8}
        assert load_collective_corrections("v5e", path=str(cal)) == {}
        assert load_collective_corrections(
            "tpu", path=str(tmp_path / "missing.json")) == {}

"""ONNX frontend translation table, driven by ModelProto-like stand-ins
(the onnx package is absent in this environment — SURVEY §2.6)."""

import types

import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.onnx import ONNXModel


def attr(name, **kw):
    a = types.SimpleNamespace(name=name, i=None, f=None, s=None,
                              ints=None, floats=None)
    for k, v in kw.items():
        setattr(a, k, v)
    return a


def onnx_node(op_type, inputs, outputs, *attrs):
    return types.SimpleNamespace(op_type=op_type, input=list(inputs),
                                 output=list(outputs), attribute=list(attrs))


def fake_model(nodes):
    graph = types.SimpleNamespace(node=nodes, initializer=[])
    return types.SimpleNamespace(graph=graph)


class TestONNXFrontend:
    def test_mlp_graph(self):
        nodes = [
            onnx_node("Gemm", ["x"], ["h"], attr("out_dim", i=32)),
            onnx_node("Relu", ["h"], ["h_act"]),
            onnx_node("Gemm", ["h_act"], ["logits"], attr("out_dim", i=4)),
            onnx_node("Softmax", ["logits"], ["probs"], attr("axis", i=-1)),
        ]
        ff = FFModel(FFConfig(batch_size=8, only_data_parallel=True))
        t = ff.create_tensor((8, 16))
        out = ONNXModel(fake_model(nodes)).apply(ff, {"x": t})
        assert out.shape == (8, 4)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
        x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
        probs = ff.predict(x)
        np.testing.assert_allclose(probs.sum(axis=-1), 1.0, rtol=1e-5)

    def test_conv_pool_residual(self):
        nodes = [
            onnx_node("Conv", ["x"], ["c1"], attr("out_channels", i=4),
                      attr("kernel_shape", ints=[3, 3]),
                      attr("strides", ints=[1, 1]),
                      attr("pads", ints=[1, 1, 1, 1])),
            onnx_node("Relu", ["c1"], ["r1"]),
            onnx_node("Add", ["r1", "c1"], ["res"]),
            onnx_node("MaxPool", ["res"], ["p1"],
                      attr("kernel_shape", ints=[2, 2])),
            onnx_node("Flatten", ["p1"], ["flat"]),
            onnx_node("Gemm", ["flat"], ["out"], attr("out_dim", i=3)),
        ]
        ff = FFModel(FFConfig(batch_size=4, only_data_parallel=True))
        t = ff.create_tensor((4, 1, 8, 8))
        out = ONNXModel(fake_model(nodes)).apply(ff, {"x": t})
        assert out.shape == (4, 3)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        x = np.random.RandomState(1).randn(4, 1, 8, 8).astype(np.float32)
        assert ff.predict(x).shape == (4, 3)

    def test_concat_split_transpose(self):
        nodes = [
            onnx_node("Split", ["x"], ["a", "b"], attr("axis", i=1),
                      attr("split", ints=[8, 8])),
            onnx_node("Concat", ["a", "b"], ["cat"], attr("axis", i=1)),
            onnx_node("Transpose", ["cat"], ["tr"], attr("perm", ints=[0, 1])),
            onnx_node("ReduceMean", ["tr"], ["m"], attr("axes", ints=[1]),
                      attr("keepdims", i=0)),
        ]
        ff = FFModel(FFConfig(batch_size=4, only_data_parallel=True))
        t = ff.create_tensor((4, 16))
        out = ONNXModel(fake_model(nodes)).apply(ff, {"x": t})
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        x = np.random.RandomState(2).randn(4, 16).astype(np.float32)
        got = ff.predict(x)
        np.testing.assert_allclose(got.reshape(-1), x.mean(axis=1),
                                   rtol=1e-5, atol=1e-6)


class TestRealONNXBytes:
    """Wire-format ModelProto bytes with initializer payloads — the path a
    real torch.onnx.export file takes (no custom attributes anywhere).
    Numerics are checked against torch (reference parity:
    /root/reference/python/flexflow/onnx/model.py reads initializers)."""

    def _mlp_bytes_and_torch(self):
        import torch
        from flexflow_tpu.onnx.proto import encode_model, encode_node

        torch.manual_seed(0)
        m = torch.nn.Sequential(torch.nn.Linear(16, 32), torch.nn.ReLU(),
                                torch.nn.Linear(32, 4))
        w0 = m[0].weight.detach().numpy()   # [32, 16] — transB layout
        b0 = m[0].bias.detach().numpy()
        w1 = m[2].weight.detach().numpy()
        b1 = m[2].bias.detach().numpy()
        nodes = [
            encode_node("Gemm", ["x", "w0", "b0"], ["h"],
                        alpha=1.0, beta=1.0, transB=1),
            encode_node("Relu", ["h"], ["h_act"]),
            encode_node("Gemm", ["h_act", "w1", "b1"], ["out"],
                        alpha=1.0, beta=1.0, transB=1),
        ]
        data = encode_model(
            nodes, {"w0": w0, "b0": b0, "w1": w1, "b1": b1},
            inputs={"x": (8, 16)}, outputs={"out": (8, 4)})
        return data, m

    def test_gemm_shapes_from_initializers(self):
        data, _ = self._mlp_bytes_and_torch()
        om = ONNXModel(data)  # raw bytes, own protobuf reader
        assert set(om.initializers) == {"w0", "b0", "w1", "b1"}
        ff = FFModel(FFConfig(batch_size=8, only_data_parallel=True))
        t = ff.create_tensor((8, 16))
        out = om.apply(ff, {"x": t})
        assert out.shape == (8, 4)

    def test_weights_import_matches_torch(self):
        import torch

        data, m = self._mlp_bytes_and_torch()
        om = ONNXModel(data)
        ff = FFModel(FFConfig(batch_size=8, only_data_parallel=True))
        t = ff.create_tensor((8, 16))
        om.apply(ff, {"x": t})
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        assert om.copy_weights_to(ff) == 4
        x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
        want = m(torch.from_numpy(x)).detach().numpy()
        got = ff.predict(x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_conv_net_from_initializers_matches_torch(self):
        import torch
        from flexflow_tpu.onnx.proto import encode_model, encode_node

        torch.manual_seed(1)
        conv = torch.nn.Conv2d(3, 8, 3, stride=1, padding=1)
        fc = torch.nn.Linear(8 * 4 * 4, 5)

        def torch_fwd(x):
            h = torch.relu(conv(x))
            h = torch.nn.functional.max_pool2d(h, 2, 2)
            return fc(h.flatten(1))

        nodes = [
            encode_node("Conv", ["x", "cw", "cb"], ["c"],
                        kernel_shape=[3, 3], strides=[1, 1],
                        pads=[1, 1, 1, 1]),
            encode_node("Relu", ["c"], ["r"]),
            encode_node("MaxPool", ["r"], ["p"],
                        kernel_shape=[2, 2], strides=[2, 2]),
            encode_node("Flatten", ["p"], ["f"]),
            encode_node("Gemm", ["f", "fw", "fb"], ["out"],
                        alpha=1.0, beta=1.0, transB=1),
        ]
        data = encode_model(
            nodes,
            {"cw": conv.weight.detach().numpy(),
             "cb": conv.bias.detach().numpy(),
             "fw": fc.weight.detach().numpy(),
             "fb": fc.bias.detach().numpy()},
            inputs={"x": (4, 3, 8, 8)}, outputs={"out": (4, 5)})
        om = ONNXModel(data)
        ff = FFModel(FFConfig(batch_size=4, only_data_parallel=True))
        t = ff.create_tensor((4, 3, 8, 8))
        out = om.apply(ff, {"x": t})
        assert out.shape == (4, 5)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        assert om.copy_weights_to(ff) == 4
        x = np.random.RandomState(3).randn(4, 3, 8, 8).astype(np.float32)
        want = torch_fwd(torch.from_numpy(x)).detach().numpy()
        got = ff.predict(x)
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)

    def test_reshape_and_split_from_constant_inputs(self):
        from flexflow_tpu.onnx.proto import encode_model, encode_node

        nodes = [
            encode_node("Reshape", ["x", "shp"], ["r"]),
            encode_node("Split", ["r", "sizes"], ["a", "b"], axis=1),
            encode_node("Concat", ["b", "a"], ["out"], axis=1),
        ]
        data = encode_model(
            nodes,
            {"shp": np.asarray([0, 8], dtype=np.int64),
             "sizes": np.asarray([2, 6], dtype=np.int64)},
            inputs={"x": (4, 2, 4)}, outputs={"out": (4, 8)})
        om = ONNXModel(data)
        ff = FFModel(FFConfig(batch_size=4, only_data_parallel=True))
        t = ff.create_tensor((4, 2, 4))
        out = om.apply(ff, {"x": t})
        assert out.shape == (4, 8)

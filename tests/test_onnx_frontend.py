"""ONNX frontend translation table, driven by ModelProto-like stand-ins
(the onnx package is absent in this environment — SURVEY §2.6)."""

import types

import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.onnx import ONNXModel


def attr(name, **kw):
    a = types.SimpleNamespace(name=name, i=None, f=None, s=None,
                              ints=None, floats=None)
    for k, v in kw.items():
        setattr(a, k, v)
    return a


def onnx_node(op_type, inputs, outputs, *attrs):
    return types.SimpleNamespace(op_type=op_type, input=list(inputs),
                                 output=list(outputs), attribute=list(attrs))


def fake_model(nodes):
    graph = types.SimpleNamespace(node=nodes, initializer=[])
    return types.SimpleNamespace(graph=graph)


class TestONNXFrontend:
    def test_mlp_graph(self):
        nodes = [
            onnx_node("Gemm", ["x"], ["h"], attr("out_dim", i=32)),
            onnx_node("Relu", ["h"], ["h_act"]),
            onnx_node("Gemm", ["h_act"], ["logits"], attr("out_dim", i=4)),
            onnx_node("Softmax", ["logits"], ["probs"], attr("axis", i=-1)),
        ]
        ff = FFModel(FFConfig(batch_size=8, only_data_parallel=True))
        t = ff.create_tensor((8, 16))
        out = ONNXModel(fake_model(nodes)).apply(ff, {"x": t})
        assert out.shape == (8, 4)
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.SPARSE_CATEGORICAL_CROSSENTROPY, [])
        x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
        probs = ff.predict(x)
        np.testing.assert_allclose(probs.sum(axis=-1), 1.0, rtol=1e-5)

    def test_conv_pool_residual(self):
        nodes = [
            onnx_node("Conv", ["x"], ["c1"], attr("out_channels", i=4),
                      attr("kernel_shape", ints=[3, 3]),
                      attr("strides", ints=[1, 1]),
                      attr("pads", ints=[1, 1, 1, 1])),
            onnx_node("Relu", ["c1"], ["r1"]),
            onnx_node("Add", ["r1", "c1"], ["res"]),
            onnx_node("MaxPool", ["res"], ["p1"],
                      attr("kernel_shape", ints=[2, 2])),
            onnx_node("Flatten", ["p1"], ["flat"]),
            onnx_node("Gemm", ["flat"], ["out"], attr("out_dim", i=3)),
        ]
        ff = FFModel(FFConfig(batch_size=4, only_data_parallel=True))
        t = ff.create_tensor((4, 1, 8, 8))
        out = ONNXModel(fake_model(nodes)).apply(ff, {"x": t})
        assert out.shape == (4, 3)
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        x = np.random.RandomState(1).randn(4, 1, 8, 8).astype(np.float32)
        assert ff.predict(x).shape == (4, 3)

    def test_concat_split_transpose(self):
        nodes = [
            onnx_node("Split", ["x"], ["a", "b"], attr("axis", i=1),
                      attr("split", ints=[8, 8])),
            onnx_node("Concat", ["a", "b"], ["cat"], attr("axis", i=1)),
            onnx_node("Transpose", ["cat"], ["tr"], attr("perm", ints=[0, 1])),
            onnx_node("ReduceMean", ["tr"], ["m"], attr("axes", ints=[1]),
                      attr("keepdims", i=0)),
        ]
        ff = FFModel(FFConfig(batch_size=4, only_data_parallel=True))
        t = ff.create_tensor((4, 16))
        out = ONNXModel(fake_model(nodes)).apply(ff, {"x": t})
        ff.compile(SGDOptimizer(lr=0.01),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        x = np.random.RandomState(2).randn(4, 16).astype(np.float32)
        got = ff.predict(x)
        np.testing.assert_allclose(got.reshape(-1), x.mean(axis=1),
                                   rtol=1e-5, atol=1e-6)

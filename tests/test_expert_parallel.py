"""Expert parallelism: fused Experts op + 'expert' mesh axis (SURVEY §2.3;
reference analog: per-expert placement, examples/cpp/mixture_of_experts/
moe.cc:65-83).

Numerics contract: the shard_map expert-parallel path must match the dense
(replicated) path bit-for-bit up to float tolerance, because the routing
tensors are computed from replicated gate/assign.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flexflow_tpu.machine import make_mesh
from flexflow_tpu.parallel.expert import dense_moe_ffn, expert_parallel_ffn
from flexflow_tpu.ops.moe import expert_capacity, make_dispatch_tensors


def _routing(rs, b, k, e, cap):
    gate = jax.nn.softmax(jnp.asarray(rs.randn(b, e).astype(np.float32)))
    values, assign = jax.lax.top_k(gate, k)
    dispatch, combine = make_dispatch_tensors(assign, values, e, cap)
    return gate, dispatch, combine


class TestExpertParallelFFN:
    @pytest.mark.parametrize("mesh_axes", [
        {"expert": 8}, {"data": 2, "expert": 4}, {"data": 4, "expert": 2},
    ])
    def test_matches_dense_path(self, mesh_axes):
        rs = np.random.RandomState(0)
        b, d, h, e, k = 16, 8, 12, 8, 2
        cap = expert_capacity(b, k, e, 2.0)
        _, dispatch, combine = _routing(rs, b, k, e, cap)
        x = jnp.asarray(rs.randn(b, d).astype(np.float32))
        w_h = jnp.asarray(rs.randn(e, d, h).astype(np.float32) * 0.1)
        b_h = jnp.asarray(rs.randn(e, h).astype(np.float32) * 0.1)
        w_o = jnp.asarray(rs.randn(e, h, d).astype(np.float32) * 0.1)
        b_o = jnp.asarray(rs.randn(e, d).astype(np.float32) * 0.1)

        want = dense_moe_ffn(x, dispatch, combine, w_h, b_h, w_o, b_o)
        mesh = make_mesh(int(np.prod(list(mesh_axes.values()))), mesh_axes)
        got = expert_parallel_ffn(x, dispatch, combine, w_h, b_h, w_o, b_o,
                                  mesh, expert_axis="expert")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_falls_back_when_experts_indivisible(self):
        rs = np.random.RandomState(1)
        b, d, h, e, k = 8, 4, 6, 3, 1  # 3 experts on expert axis of 2
        cap = expert_capacity(b, k, e, 2.0)
        _, dispatch, combine = _routing(rs, b, k, e, cap)
        x = jnp.asarray(rs.randn(b, d).astype(np.float32))
        w_h = jnp.asarray(rs.randn(e, d, h).astype(np.float32) * 0.1)
        b_h = jnp.zeros((e, h))
        w_o = jnp.asarray(rs.randn(e, h, d).astype(np.float32) * 0.1)
        b_o = jnp.zeros((e, d))
        mesh = make_mesh(2, {"expert": 2})
        got = expert_parallel_ffn(x, dispatch, combine, w_h, b_h, w_o, b_o,
                                  mesh, expert_axis="expert")
        want = dense_moe_ffn(x, dispatch, combine, w_h, b_h, w_o, b_o)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


class TestExpertsOpEndToEnd:
    def _build(self, mesh, expert_parallel):
        from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                                  SGDOptimizer)

        ff = FFModel(FFConfig(batch_size=16))
        t = ff.create_tensor((16, 8))
        gate = ff.dense(t, 8, name="gate")
        gate = ff.softmax(gate)
        out = ff.experts(t, gate, n=8, k=2, hidden_size=12, alpha=2.0,
                         lambda_bal=0.01, expert_parallel=expert_parallel,
                         name="ex")
        ff.compile(SGDOptimizer(lr=0.003),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
                   [MetricsType.MEAN_SQUARED_ERROR], mesh=mesh)
        return ff

    def test_sharded_matches_dense_and_trains(self):
        rs = np.random.RandomState(0)
        x = rs.randn(16, 8).astype(np.float32)
        y = rs.randn(16, 8).astype(np.float32)

        mesh = make_mesh(8, {"data": 2, "expert": 4})
        ff = self._build(mesh, expert_parallel="expert")
        out_sharded = np.asarray(ff.predict(x))

        ff2 = self._build(make_mesh(1, {"data": 1}), None)
        ff2.params = jax.device_put(jax.tree.map(np.asarray, ff.params))
        out_dense = np.asarray(ff2.predict(x))
        np.testing.assert_allclose(out_sharded, out_dense, rtol=1e-4,
                                   atol=1e-4)

        hist = []
        for _ in range(3):
            ff.fit(x, y, epochs=1, verbose=False)
            hist.append(ff.evaluate(x, y)["loss"])
        assert hist[-1] < hist[0]  # trains through the shard_map path

    def test_load_balance_uses_all_topk_slots(self):
        # the aux loss must be E * <f, P> with f the token fraction over
        # ALL top-k slots (regression: f was computed from slot 0 only)
        from flexflow_tpu.layer import Layer
        from flexflow_tpu.ffconst import OperatorType
        from flexflow_tpu.ops.base import OpContext, OpRegistry

        b, d, e, k = 8, 4, 4, 2
        layer = Layer(OperatorType.EXPERTS, "ex", [])
        layer.properties.update(dict(
            n=e, k=k, hidden_size=6, alpha=2.0, lambda_bal=1.0))
        op = OpRegistry.create(layer, [(b, d), (b, e)])
        params = op.init_params(jax.random.PRNGKey(0))
        rs = np.random.RandomState(3)
        x = jnp.asarray(rs.randn(b, d).astype(np.float32))
        gate = np.asarray(jax.nn.softmax(
            jnp.asarray(rs.randn(b, e).astype(np.float32))))

        _, assign = jax.lax.top_k(jnp.asarray(gate), k)
        assign = np.asarray(assign)
        p_mean = gate.mean(0)
        f_full = np.zeros(e)
        for col in range(k):
            f_full += np.bincount(assign[:, col], minlength=e)
        f_full /= b * k
        f_top1 = np.bincount(assign[:, 0], minlength=e) / b
        want_full = e * np.sum(f_full * p_mean)
        want_top1 = e * np.sum(f_top1 * p_mean)
        assert want_full != pytest.approx(want_top1)  # discriminating gate

        ctx = OpContext(training=True, compute_dtype=jnp.float32)
        op.forward(params, [x, jnp.asarray(gate)], ctx)
        assert float(op._aux_loss) == pytest.approx(want_full, rel=1e-5)


class TestSearchDiscoversExpertParallel:
    def test_fat_experts_pick_expert_axis(self):
        from flexflow_tpu.search.native import available, native_optimize
        if not available():
            pytest.skip("native ffsearch library unavailable")
        b, d, h, e = 8, 4096, 4096, 8
        nodes = [{
            "guid": 1, "type": "EXPERTS", "name": "ex",
            "inputs": [[-1, 0], [-1, 0]],
            "input_shapes": [[b, d], [b, e]], "output_shapes": [[b, d]],
            "roles": [["sample", "channel"]],
            "params": {"w_h": [e, d, h], "b_h": [e, h],
                       "w_o": [e, h, d], "b_o": [e, d]},
            "flops": 4.0 * e * 2 * b * d * h, "dtype_size": 4,
            "attrs": {"n_experts": e, "k": 2, "alpha": 2.0,
                      "hidden_size": h},
        }]
        machine = {"num_devices": 8, "flops": 197e12, "hbm_bw": 0.82e12,
                   "hbm_cap": 16e9, "ici_bw": 45e9, "ici_latency": 1e-6,
                   "dcn_bw": 25e9, "dcn_latency": 1e-5, "num_slices": 1}
        cfg = dict(budget=0, alpha=0.05, only_data_parallel=False,
                   enable_parameter_parallel=True, overlap=True,
                   training=True, memory_threshold=0, seed=1, rules=[])
        resp = native_optimize({"machine": machine, "config": cfg,
                                "measured": {}, "nodes": nodes})
        assert resp["mesh"]["expert"] > 1, resp["mesh"]
        # the search must land on the expert axis; since ISSUE 9 the
        # "_wus"/"_ovl" twins may stack after it (base[_wus][_ovl]), so
        # membership, not endswith
        choice = resp["ops"]["1"]["choice"]
        assert "_ep" in choice, choice
        assert resp["ops"]["1"]["params"]["w_h"][0] == "expert"

    def test_searched_moe_model_runs_expert_parallel(self):
        from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                                  SGDOptimizer)
        from flexflow_tpu.ffconst import OperatorType
        from flexflow_tpu.search.native import available
        if not available():
            pytest.skip("native ffsearch library unavailable")

        ff = FFModel(FFConfig(batch_size=8, search_budget=2,
                              enable_parameter_parallel=True))
        t = ff.create_tensor((8, 64))
        out = ff.moe(t, num_exp=8, num_select=2, expert_hidden_size=512,
                     lambda_bal=0.01, name="m")
        out = ff.dense(out, 4)
        ff.compile(SGDOptimizer(lr=0.05),
                   LossType.MEAN_SQUARED_ERROR_AVG_REDUCE, [])
        axes = dict(zip(ff.mesh.axis_names, ff.mesh.devices.shape))
        rs = np.random.RandomState(0)
        ff.fit(rs.randn(8, 64).astype(np.float32),
               rs.randn(8, 4).astype(np.float32), epochs=1, verbose=False)
        if axes.get("expert", 1) > 1:
            ops = [n.op for n in ff.executor.nodes
                   if n.op.op_type == OperatorType.EXPERTS]
            assert ops and ops[0].expert_parallel == "expert"

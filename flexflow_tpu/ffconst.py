"""Framework-wide enums.

TPU-native analog of the reference's ``include/flexflow/ffconst.h`` enum
surface (OperatorType ffconst.h:63-156, DataType, LossType :33-39,
MetricsType :52-60, CompMode, ParameterSyncType :46, ActiMode, AggrMode,
PoolType). Values are our own; names keep API parity so frontends and
strategy files interoperate.
"""

import enum

import jax.numpy as jnp


class DataType(enum.Enum):
    BOOL = "bool"
    INT32 = "int32"
    INT64 = "int64"
    HALF = "float16"
    BFLOAT16 = "bfloat16"
    FLOAT = "float32"
    DOUBLE = "float64"

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.value)

    @property
    def size(self) -> int:
        return self.jnp_dtype.itemsize

    @classmethod
    def from_jnp(cls, dtype) -> "DataType":
        return cls(jnp.dtype(dtype).name)


class ActiMode(enum.Enum):
    AC_MODE_NONE = 0
    AC_MODE_RELU = 1
    AC_MODE_SIGMOID = 2
    AC_MODE_TANH = 3
    AC_MODE_GELU = 4


class AggrMode(enum.Enum):
    AGGR_MODE_NONE = 0
    AGGR_MODE_SUM = 1
    AGGR_MODE_AVG = 2


class PoolType(enum.Enum):
    POOL_MAX = 0
    POOL_AVG = 1


class LossType(enum.Enum):
    CATEGORICAL_CROSSENTROPY = 10
    SPARSE_CATEGORICAL_CROSSENTROPY = 11
    MEAN_SQUARED_ERROR_AVG_REDUCE = 12
    MEAN_SQUARED_ERROR_SUM_REDUCE = 13
    IDENTITY = 14


class MetricsType(enum.Enum):
    ACCURACY = 1001
    CATEGORICAL_CROSSENTROPY = 1002
    SPARSE_CATEGORICAL_CROSSENTROPY = 1003
    MEAN_SQUARED_ERROR = 1004
    ROOT_MEAN_SQUARED_ERROR = 1005
    MEAN_ABSOLUTE_ERROR = 1006


class CompMode(enum.Enum):
    TRAINING = 0
    INFERENCE = 1


class ParameterSyncType(enum.Enum):
    """How gradients are synchronized across data-parallel replicas.

    On TPU both map to a ``psum`` over the data mesh axes inside the jitted
    step (the reference distinguishes a zero-copy parameter server from NCCL
    allreduce — config.h:55-59); we keep the names for config parity.
    """

    NONE = 0
    PS = 1
    NCCL = 2


class OperatorType(enum.Enum):
    # sources
    NOOP = enum.auto()
    INPUT = enum.auto()
    WEIGHT = enum.auto()
    # dense / conv stack
    CONV2D = enum.auto()
    POOL2D = enum.auto()
    BATCHNORM = enum.auto()
    LINEAR = enum.auto()
    EMBEDDING = enum.auto()
    # attention / transformer
    MULTIHEAD_ATTENTION = enum.auto()
    LAYERNORM = enum.auto()
    # RMSNorm: new scope vs the reference (no analog in ffconst.h) — the
    # Llama/T5 model family's normalization
    RMSNORM = enum.auto()
    SOFTMAX = enum.auto()
    # elementwise
    EW_ADD = enum.auto()
    EW_SUB = enum.auto()
    EW_MUL = enum.auto()
    EW_DIV = enum.auto()
    EW_MAX = enum.auto()
    EW_MIN = enum.auto()
    RELU = enum.auto()
    GELU = enum.auto()
    SIGMOID = enum.auto()
    TANH = enum.auto()
    ELU = enum.auto()
    EXP = enum.auto()
    SIN = enum.auto()
    COS = enum.auto()
    POW = enum.auto()
    RSQRT = enum.auto()
    IDENTITY = enum.auto()
    SCALAR_MULTIPLY = enum.auto()
    SCALAR_ADD = enum.auto()
    SCALAR_SUB = enum.auto()
    SCALAR_TRUE_DIV = enum.auto()
    # matmul / shape
    BATCHMATMUL = enum.auto()
    CONCAT = enum.auto()
    SPLIT = enum.auto()
    RESHAPE = enum.auto()
    TRANSPOSE = enum.auto()
    FLAT = enum.auto()
    REVERSE = enum.auto()
    CAST = enum.auto()
    DROPOUT = enum.auto()
    GATHER = enum.auto()
    REDUCE_SUM = enum.auto()
    REDUCE_MAX = enum.auto()
    MEAN = enum.auto()
    TOPK = enum.auto()
    ARG_TOPK = enum.auto()
    # r4 additions for torch.fx frontend depth (reference table
    # python/flexflow/torch/model.py:2408-2496 covers these kinds)
    CONST = enum.auto()      # embedded constant (fx get_attr buffers)
    WHERE = enum.auto()      # select(cond, a, b) — masked_fill/where
    EXPAND = enum.auto()     # broadcast_to (torch expand/repeat)
    EINSUM = enum.auto()     # general einsum contraction
    GROUPNORM = enum.auto()  # nn.GroupNorm
    LOG = enum.auto()        # elementwise natural log
    # MoE quartet (+ gating sugar)
    GROUP_BY = enum.auto()
    AGGREGATE = enum.auto()
    AGGREGATE_SPEC = enum.auto()
    CACHE = enum.auto()
    EXPERTS = enum.auto()
    # fused compute
    FUSED = enum.auto()
    # parallel (resharding) ops — first-class PCG citizens (ffconst.h:149-156)
    REPARTITION = enum.auto()
    COMBINE = enum.auto()
    REPLICATE = enum.auto()
    REDUCTION = enum.auto()
    PIPELINE = enum.auto()
    FUSED_PARALLEL = enum.auto()
    # loss/metrics pseudo-ops (appear in taskgraph simulation)
    LOSS = enum.auto()
    METRICS = enum.auto()
    OPTIMIZER = enum.auto()
    ALLREDUCE = enum.auto()


PARALLEL_OP_TYPES = frozenset(
    {
        OperatorType.REPARTITION,
        OperatorType.COMBINE,
        OperatorType.REPLICATE,
        OperatorType.REDUCTION,
        OperatorType.PIPELINE,
        OperatorType.FUSED_PARALLEL,
    }
)

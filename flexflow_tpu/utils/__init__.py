"""Utility subsystems: dot export, search tracing, profiling."""

"""shard_map compatibility: jax >= 0.8 promotes it to jax.shard_map and
renames check_rep -> check_vma; older jax keeps jax.experimental."""

from __future__ import annotations

try:
    from jax import shard_map as _new_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=True):
        return _new_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=check_rep)
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # noqa: F401

"""RecursiveLogger: depth-indented search/trace logging.

Analog of include/flexflow/utils/recursive_logger.h:10-27 — the reference
tags each line with its recursion depth ("[depth] message") so nested
search decisions read as a tree.
"""

from __future__ import annotations

import contextlib
import sys
from typing import Optional, TextIO


class RecursiveLogger:
    def __init__(self, name: str = "search", stream: Optional[TextIO] = None,
                 enabled: bool = True):
        self.name = name
        self.stream = stream or sys.stderr
        self.enabled = enabled
        self.depth = 0

    @contextlib.contextmanager
    def enter(self, tag: str = ""):
        """Nested scope: lines inside are indented one level deeper
        (reference's TAG_ENTER/LEAVE)."""
        if tag:
            self.info(tag)
        self.depth += 1
        try:
            yield self
        finally:
            self.depth -= 1

    def info(self, msg: str) -> None:
        if self.enabled:
            self.stream.write(f"[{self.name}] [{self.depth}] "
                              + "  " * self.depth + msg + "\n")

    def spew(self, msg: str) -> None:  # reference's finer level
        self.info(msg)

"""Generic graph algorithms used by the search and tooling.

Analog of the reference's header-only utilities (SURVEY §2.1 misc utils):
``include/flexflow/dominators.h`` (topo_sort, post-dominators — used to
find sequence-split nodes), ``disjoint_set.h`` (union-find), and
``basic_graph.h``-style views (reversed). Pure Python on plain
adjacency dicts: {node: iterable of successors}.
"""

from __future__ import annotations

from typing import Dict, Hashable, Iterable, List, Optional, Set, TypeVar

T = TypeVar("T", bound=Hashable)

Adj = Dict[T, Iterable[T]]


def topo_sort(adj: Adj) -> List[T]:
    """Topological order; raises ValueError on cycles (dominators.h analog)."""
    indeg: Dict[T, int] = {u: 0 for u in adj}
    for u, vs in adj.items():
        for v in vs:
            indeg[v] = indeg.get(v, 0) + 1
            indeg.setdefault(u, indeg.get(u, 0))
    ready = [u for u, d in sorted(indeg.items(), key=lambda kv: repr(kv[0]))
             if d == 0]
    out: List[T] = []
    while ready:
        u = ready.pop()
        out.append(u)
        for v in adj.get(u, ()):  # noqa: B020
            indeg[v] -= 1
            if indeg[v] == 0:
                ready.append(v)
    if len(out) != len(indeg):
        raise ValueError("graph has a cycle")
    return out


def reversed_graph(adj: Adj) -> Adj:
    out: Dict[T, List[T]] = {u: [] for u in adj}
    for u, vs in adj.items():
        for v in vs:
            out.setdefault(v, []).append(u)
            out.setdefault(u, out.get(u, []))
    return out


def dominators(adj: Adj, root: T) -> Dict[T, Set[T]]:
    """dom(v) = nodes on every path root→v (iterative dataflow,
    dominators.h semantics). Unreachable nodes are omitted."""
    order = [u for u in topo_sort(adj)]
    reach = _reachable(adj, root)
    order = [u for u in order if u in reach]
    dom: Dict[T, Set[T]] = {root: {root}}
    preds = reversed_graph(adj)
    changed = True
    while changed:
        changed = False
        for v in order:
            if v == root:
                continue
            ps = [p for p in preds.get(v, []) if p in dom]
            if not ps:
                continue
            new = set.intersection(*(dom[p] for p in ps)) | {v}
            if dom.get(v) != new:
                dom[v] = new
                changed = True
    return dom


def post_dominators(adj: Adj, sink: T) -> Dict[T, Set[T]]:
    """pdom(v) = nodes on every path v→sink — the reference uses these to
    pick sequence-split bottlenecks (graph.h:170 DP decomposition)."""
    return dominators(reversed_graph(adj), sink)


def immediate_post_dominator(adj: Adj, node: T, sink: T) -> Optional[T]:
    pdom = post_dominators(adj, sink)
    cands = pdom.get(node, set()) - {node}
    if not cands:
        return None
    # the ipdom is the *closest* candidate: the one every other candidate
    # post-dominates (all others lie beyond it on the way to the sink)
    for c in cands:
        if all(o in pdom.get(c, set()) or o == c for o in cands):
            return c
    return None


def _reachable(adj: Adj, root: T) -> Set[T]:
    seen = {root}
    stack = [root]
    while stack:
        u = stack.pop()
        for v in adj.get(u, ()):
            if v not in seen:
                seen.add(v)
                stack.append(v)
    return seen


class DisjointSet:
    """Union-find with path compression (disjoint_set.h analog)."""

    def __init__(self):
        self._parent: Dict[T, T] = {}

    def find(self, x: T) -> T:
        p = self._parent.setdefault(x, x)
        if p != x:
            p = self._parent[x] = self.find(p)
        return p

    def union(self, a: T, b: T) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self._parent[rb] = ra

    def same(self, a: T, b: T) -> bool:
        return self.find(a) == self.find(b)


def hash_combine(seed: int, value: Hashable) -> int:
    """Deterministic 64-bit hash_combine (hash_utils.h analog; avoids
    Python's per-process hash randomization for strategy cache keys)."""
    import zlib

    v = zlib.crc32(repr(value).encode()) & 0xFFFFFFFF
    seed ^= (v + 0x9E3779B97F4A7C15 + ((seed << 6) & (2**64 - 1)) + (seed >> 2))
    return seed & (2**64 - 1)

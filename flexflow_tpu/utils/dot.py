"""Graphviz export of the PCG + chosen strategy.

Analog of the reference's DotFile/RecordFormatter utilities
(include/flexflow/utils/dot/, src/utils/dot/record_formatter.cc) and
Graph::export_strategy_computation_graph (include/flexflow/graph.h:339),
wired to --export-strategy-computation-graph / --include-costs-dot-graph
(config.h:143-145).
"""

from __future__ import annotations

from typing import Optional


def _fmt_spec(spec) -> str:
    if spec is None:
        return "rep"
    entries = [str(e) if e is not None else "." for e in spec]
    return "[" + ",".join(entries) + "]" if entries else "rep"


def export_strategy_dot(nodes, mesh, path: str,
                        include_costs: bool = False,
                        search_info: Optional[dict] = None) -> None:
    """Write a .dot file: one record node per op showing name, type,
    output shape, and the sharding decision."""
    lines = ["digraph pcg {", '  rankdir="TB";',
             '  node [shape=record, fontsize=10];']
    axes = dict(zip(mesh.axis_names, mesh.devices.shape)) if mesh else {}
    lines.append(f'  label="mesh: {axes}";')
    guids = {n.op.guid for n in nodes}
    for node in nodes:
        op = node.op
        spec = node.output_specs[0] if node.output_specs else None
        cost = ""
        if include_costs:
            cost = f"|flops {op.flops():.3g}"
        label = (f"{{{op.name}|{op.op_type.name}|"
                 f"out {tuple(op.output_shapes[0])}|"
                 f"spec {_fmt_spec(spec)}{cost}}}")
        lines.append(f'  n{op.guid} [label="{label}"];')
        for ref in node.input_refs:
            if ref[0] == "op" and ref[1] in guids:
                lines.append(f"  n{ref[1]} -> n{op.guid};")
    if search_info:
        t = search_info.get("predicted_time")
        if t:
            lines.append(
                f'  info [shape=note, label="predicted {t * 1e3:.3f} ms"];')
    lines.append("}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")

"""Preemption-aware elastic resume planning.

The PCG + strategy decode make resume onto a DIFFERENT topology cheap
for this framework: the checkpoint stores logically-global arrays (a
shard index over the saving mesh) plus the searched strategy it ran
under, and ``FFModel.compile`` already knows how to search a strategy
for whatever devices survived. Resume is therefore a strategy decision,
not a crash:

* same device count → reuse the recorded strategy verbatim (write it to
  a strategy file and compile with ``import_strategy_file`` — zero
  search cost, identical shardings, bit-identical continuation);
* different device count → compile with a search budget for the
  surviving topology; ``load_sharded`` then reassembles each global
  array from the shard index and re-places it onto the NEW strategy's
  NamedShardings.

``plan_resume`` encodes that decision; the multihost dryrun's
kill-and-resume legs and scripts/ckpt_inspect.py consume it.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from flexflow_tpu.ckpt import manifest as mf


def load_manifest(path: str) -> Dict[str, Any]:
    """Manifest of the newest complete checkpoint under ``path`` (or of
    the specific step dir). Raises FileNotFoundError when none exists —
    never returns a partial checkpoint's view."""
    step_dir = mf.resolve_step_dir(path)
    if step_dir is None:
        raise FileNotFoundError(
            f"no complete checkpoint under '{path}' (a checkpoint is "
            f"complete only once its {mf.MANIFEST_NAME} exists)")
    manifest = mf.read_json(os.path.join(step_dir, mf.MANIFEST_NAME))
    if manifest is None:
        raise FileNotFoundError(f"unreadable manifest in {step_dir}")
    return manifest


def plan_resume(manifest: Dict[str, Any],
                num_devices: int) -> Dict[str, Any]:
    """Decide how the surviving topology resumes from ``manifest``.

    Returns ``{action, saved_mesh, saved_devices, num_devices}`` with
    ``action`` one of:

    * ``"reuse"``    — device count matches the saving mesh: the
      recorded strategy applies verbatim (``write_saved_strategy`` +
      ``FFConfig.import_strategy_file``);
    * ``"research"`` — topology changed: compile with a search budget
      so the native search picks a strategy for what survived, then
      load re-shards from the checkpointed shard index.

    When the saving mesh carried a ``slice`` axis (multi-slice
    training) and the lost devices are a whole number of slices, the
    plan additionally classifies the topology change as
    ``topology="slice_loss"`` with ``lost_slices`` /
    ``surviving_slices`` counts: the surviving fleet is an intact
    (smaller) multi-slice deployment — or a single slice, which
    resumes WITHOUT ``--slices`` — so the re-search runs on the
    surviving slice topology rather than an arbitrary device count.
    Any other mismatch classifies as ``topology="device_change"``.
    """
    saved_mesh = {k: int(v) for k, v in (manifest.get("mesh") or {}).items()}
    saved_devices = int(manifest.get("num_devices") or
                        _prod(saved_mesh.values()))
    action = "reuse" if saved_devices == int(num_devices) else "research"
    plan = dict(action=action, saved_mesh=saved_mesh,
                saved_devices=saved_devices, num_devices=int(num_devices))
    saved_slices = int(saved_mesh.get("slice", 1))
    if action == "research" and saved_slices > 1:
        per_slice = saved_devices // saved_slices
        n = int(num_devices)
        if 0 < n < saved_devices and per_slice > 0 and n % per_slice == 0:
            plan["topology"] = "slice_loss"
            plan["surviving_slices"] = n // per_slice
            plan["lost_slices"] = saved_slices - n // per_slice
            plan["slices"] = n // per_slice  # the resume's --slices value
            return plan
    if action == "research":
        plan["topology"] = "device_change"
    return plan


def write_saved_strategy(manifest: Dict[str, Any], path: str) -> str:
    """Materialize the checkpoint's recorded strategy as a strategy
    file (the ``--import-strategy`` format) for the same-topology
    fast path. Returns ``path``."""
    import json
    strategy = manifest.get("strategy")
    if not strategy:
        raise ValueError("checkpoint manifest carries no strategy record")
    with open(path, "w") as f:
        json.dump(strategy, f, indent=1)
    return path


def strategy_matches_mesh(manifest: Dict[str, Any], mesh) -> bool:
    """Whether the live mesh equals the saving mesh (axes and extents).
    False just means the elastic re-shard path engages — not an error
    (the FFL804 INFO diagnostic)."""
    saved = {k: int(v) for k, v in (manifest.get("mesh") or {}).items()}
    live = {k: int(v) for k, v in zip(mesh.axis_names, mesh.devices.shape)}
    return saved == live


def _prod(vals) -> int:
    out = 1
    for v in vals:
        out *= int(v)
    return out

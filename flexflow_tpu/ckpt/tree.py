"""Pytree <-> flat-key plumbing shared by both checkpoint formats.

One flatten/skeleton/rebuild/place implementation serves the legacy v1
single-file path (flexflow_tpu/checkpoint.py) and the v2 per-shard
package (flexflow_tpu/ckpt/sharded.py): '/'-joined key paths over any
nesting of dict/list/tuple with array leaves, a JSON-able structure
skeleton, and re-placement of restored arrays onto the live values'
NamedShardings.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np


def flatten_tree(tree, prefix="") -> List[Tuple[str, Any]]:
    if isinstance(tree, dict):
        out = []
        for k in sorted(tree):
            out += flatten_tree(tree[k], f"{prefix}{k}/")
        return out
    if isinstance(tree, (list, tuple)):
        out = []
        for i, v in enumerate(tree):
            out += flatten_tree(v, f"{prefix}{i}/")
        return out
    return [(prefix[:-1], tree)]


def tree_structure(tree):
    """JSON-able skeleton used to rebuild nesting on load."""
    if isinstance(tree, dict):
        return {"__kind__": "dict",
                "items": {k: tree_structure(v) for k, v in tree.items()}}
    if isinstance(tree, tuple):
        return {"__kind__": "tuple",
                "items": [tree_structure(v) for v in tree]}
    if isinstance(tree, list):
        return {"__kind__": "list",
                "items": [tree_structure(v) for v in tree]}
    return {"__kind__": "leaf"}


def rebuild_tree(skel, flat: Dict[str, Any], prefix=""):
    kind = skel["__kind__"]
    if kind == "dict":
        return {k: rebuild_tree(v, flat, f"{prefix}{k}/")
                for k, v in skel["items"].items()}
    if kind in ("list", "tuple"):
        seq = [rebuild_tree(v, flat, f"{prefix}{i}/")
               for i, v in enumerate(skel["items"])]
        return tuple(seq) if kind == "tuple" else seq
    return flat[prefix[:-1]]


def _same_shifted_names(live: Dict[str, Any], new: Dict[str, Any]) -> bool:
    """True when two key sets agree after stripping trailing _<guid>
    counters from auto-generated op names — the build-a-second-model-
    in-one-process footgun, worth its own diagnosis."""
    def stem(k: str) -> str:
        base, _, tail = k.rpartition("_")
        return base if base and tail.isdigit() else k

    return (len(live) == len(new)
            and sorted(map(stem, live)) == sorted(map(stem, new)))


def place_tree(live, new):
    """Re-place a restored tree onto the shardings of the live values.

    Structure and per-leaf global shapes must match; shardings may
    differ — each array lands on the LIVE leaf's NamedSharding (this is
    what makes resume onto a re-searched strategy / different mesh a
    plain load). Restored leaves are cast to the live dtype.
    """
    import jax

    if isinstance(live, dict):
        if not isinstance(new, dict) or set(new) != set(live):
            hint = ""
            if isinstance(new, dict) and _same_shifted_names(live, new):
                hint = (
                    " — the op names differ only by their auto-name "
                    "counters: auto-generated names (linear_7, ...) are "
                    "deterministic for a fresh process rebuilding the "
                    "same script (a normal restart), but NOT for a "
                    "second model built in one process; pass explicit "
                    "name= to the ops to make checkpoint keys "
                    "build-order-independent")
            raise ValueError(
                f"checkpoint structure mismatch: expected keys "
                f"{sorted(live)}, found "
                f"{sorted(new) if isinstance(new, dict) else type(new)}"
                f"{hint}")
        return {k: place_tree(live[k], new[k]) for k in live}
    if isinstance(live, (list, tuple)):
        if not isinstance(new, (list, tuple)) or len(new) != len(live):
            raise ValueError(
                f"checkpoint structure mismatch: expected sequence of "
                f"{len(live)}, found {new!r:.80}")
        rebuilt = [place_tree(l, n) for l, n in zip(live, new)]
        return type(live)(rebuilt) if isinstance(live, tuple) else rebuilt
    if hasattr(live, "sharding") and hasattr(new, "shape"):
        if tuple(live.shape) != tuple(np.shape(new)):
            raise ValueError(
                f"checkpoint shape {np.shape(new)} != live {live.shape}")
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        if not isinstance(live.sharding, NamedSharding):
            # a default-placed (uncommitted) leaf, e.g. the optimizer's
            # step counter: re-placing onto its SingleDeviceSharding
            # would COMMIT it to one device and poison the next jitted
            # step's device agreement — hand jit an uncommitted array
            return jnp.asarray(np.asarray(new), live.dtype)
        if jax.process_count() > 1:
            # every host holds the assembled global array; each places
            # only its addressable shards of the (possibly cross-host)
            # sharding. The callback returns numpy so JAX places each
            # shard directly on its device (ml_dtypes covers bf16),
            # with no default-device detour
            arr = np.asarray(new)
            dtype = np.dtype(live.dtype)
            return jax.make_array_from_callback(
                tuple(live.shape), live.sharding,
                lambda idx: arr[idx].astype(dtype))
        return jax.device_put(jnp.asarray(new, live.dtype), live.sharding)
    return new

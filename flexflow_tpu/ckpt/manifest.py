"""v2 checkpoint directory layout, commit protocol, and integrity checks.

Layout (one root directory per run):

    <ckpt_dir>/
      step_00000004/
        shards_host0000.npz    each host's addressable shards (tmp+replace)
        index_host0000.json    that host's shard index + checksums
        MANIFEST.json          the COMMIT RECORD — written last, by rank 0
      step_00000008/ ...
      PROGRESS.json            rank-0 heartbeat (restart-lost-step accounting)

Commit protocol: every host writes its shards file, fsyncs, renames,
then writes its index file (atomic) — shard data is durable before any
index references it. Rank 0 then waits for every host's index file to
appear (a filesystem barrier: works from a background writer thread,
needs no JAX collectives, and on a non-shared filesystem fails with an
actionable timeout instead of deadlocking) and writes ``MANIFEST.json``
last. A checkpoint directory without a readable manifest is by
definition incomplete: a preemption at ANY point during save leaves
either a complete previous checkpoint plus an inert partial directory,
or a complete new checkpoint — never an ambiguous state.

``verify_step_dir`` re-derives completeness from first principles
(manifest present, every indexed shard present, checksums match, shard
boxes tile each leaf's global shape) — the shared engine behind
``scripts/ckpt_inspect.py`` and the fflint FFL8xx pass.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import tempfile
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

MANIFEST_NAME = "MANIFEST.json"
PROGRESS_NAME = "PROGRESS.json"
SUPERVISOR_NAME = "SUPERVISOR.json"
STEP_RE = re.compile(r"^step_(\d{8})$")
CKPT_VERSION = 2


def step_dir_name(step: int) -> str:
    return f"step_{int(step):08d}"


def shards_name(host: int) -> str:
    return f"shards_host{int(host):04d}.npz"


def index_name(host: int) -> str:
    return f"index_host{int(host):04d}.json"


@contextlib.contextmanager
def atomic_replace(path: str, mode: str = "wb"):
    """tmp + fsync + ``os.replace``: the destination either exists
    whole or not at all (the property the manifest-last commit
    protocol rests on). Yields the open tmp file; an exception in the
    body unlinks the tmp and never touches the destination. The ONE
    implementation of the crash-atomicity protocol — the v1 .npz, the
    v2 shard files, and every JSON record go through here."""
    from flexflow_tpu.ckpt import faults
    faults.io_check(path)  # the io_error transient-failure seam
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_",
                               suffix=os.path.basename(path) + ".tmp")
    try:
        with os.fdopen(fd, mode) as f:
            yield f
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, obj: Dict[str, Any]) -> None:
    with atomic_replace(path, "w") as f:
        json.dump(obj, f, indent=1)


def read_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def crc32_bytes(payload: bytes) -> int:
    return zlib.crc32(payload) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# directory enumeration


def list_steps(directory: str) -> List[Tuple[int, str, bool]]:
    """[(step, step_dir_path, complete)] sorted ascending by step.
    ``complete`` means a readable manifest exists (the commit record);
    deep integrity is ``verify_step_dir``'s job."""
    out = []
    try:
        entries = sorted(os.listdir(directory))
    except OSError:
        return []
    for e in entries:
        m = STEP_RE.match(e)
        if not m:
            continue
        path = os.path.join(directory, e)
        if not os.path.isdir(path):
            continue
        manifest = read_json(os.path.join(path, MANIFEST_NAME))
        out.append((int(m.group(1)), path, manifest is not None))
    return out


def latest_complete(directory: str) -> Optional[Tuple[int, str]]:
    """(step, step_dir) of the newest committed checkpoint, or None."""
    steps = [(s, p) for s, p, ok in list_steps(directory) if ok]
    return steps[-1] if steps else None


def resolve_step_dir(path: str) -> Optional[str]:
    """``path`` may be a step directory or a checkpoint root — return
    the step dir of the newest complete checkpoint (None when there is
    none)."""
    if os.path.isfile(os.path.join(path, MANIFEST_NAME)):
        return path
    latest = latest_complete(path)
    return latest[1] if latest else None


# ---------------------------------------------------------------------------
# integrity verification (ckpt_inspect + fflint FFL8xx share this)


def verify_step_dir(step_dir: str, deep: bool = True) -> Dict[str, Any]:
    """Re-derive a checkpoint's integrity from its files.

    Returns ``{complete, errors, step, num_hosts, shard_count,
    payload_bytes, manifest}``. ``deep=True`` additionally re-reads
    every shard and checks its CRC32 against the index (the
    ``corrupt_shard`` fault-injection target); ``deep=False`` checks
    structure only (manifest/index presence, shard-key existence,
    coverage arithmetic).
    """
    import numpy as np

    errors: List[str] = []
    manifest = read_json(os.path.join(step_dir, MANIFEST_NAME))
    if manifest is None:
        return dict(complete=False, step=None, num_hosts=0, shard_count=0,
                    payload_bytes=0, manifest=None,
                    errors=[f"no readable {MANIFEST_NAME} (checkpoint was "
                            f"never committed or is mid-write)"])
    leaves = manifest.get("leaves", {})
    covered = {k: 0 for k in leaves}
    shard_count = 0
    payload_bytes = 0
    for idx_file in manifest.get("index_files", []):
        ipath = os.path.join(step_dir, idx_file)
        index = read_json(ipath)
        if index is None:
            errors.append(f"missing/unreadable shard index {idx_file}")
            continue
        spath = os.path.join(step_dir, index["shards_file"])
        npz = None
        if os.path.exists(spath):
            try:
                npz = np.load(spath)
            except Exception as e:
                errors.append(f"unreadable shards file "
                              f"{index['shards_file']}: {e}")
        else:
            errors.append(f"missing shards file {index['shards_file']}")
        for leaf_key, shards in index.get("shards", {}).items():
            if leaf_key not in leaves:
                errors.append(f"index {idx_file} carries unknown leaf "
                              f"'{leaf_key}'")
                continue
            for sh in shards:
                shard_count += 1
                payload_bytes += int(sh.get("bytes", 0))
                box = sh.get("index", [])
                covered[leaf_key] += int(
                    np.prod([max(0, b[1] - b[0]) for b in box])
                    if box else 1)
                if npz is None:
                    continue
                key = sh["key"]
                # chunked shards (flexflow_tpu/ckpt/sharded.py chunk
                # threshold) store only their chunk entries in the npz;
                # the base key is the row's logical name
                pieces = sh.get("chunks") or [sh]
                missing = [p["key"] for p in pieces
                           if p["key"] not in npz.files]
                if missing:
                    errors.append(
                        f"shard '{key}' pieces {missing} listed in "
                        f"{idx_file} absent from {index['shards_file']}")
                    continue
                if deep:
                    # the shared per-piece CRC check (sharded._crc_check
                    # via verify_shard_row) — same "intact" definition
                    # as restore, but piece-by-piece with NO reassembly:
                    # verifying a multi-GB chunked shard needs O(chunk)
                    # memory, not 2x the shard. Lazy import: sharded
                    # imports this module at top level.
                    from flexflow_tpu.ckpt.sharded import verify_shard_row
                    try:
                        verify_shard_row(npz, sh)
                    except ValueError as e:  # stored-CRC mismatch
                        errors.append(
                            f"{e} on '{leaf_key}' — on-disk corruption")
                    except Exception as e:  # zip CRC / truncation
                        errors.append(
                            f"shard '{key}' of '{leaf_key}' is "
                            f"unreadable ({e}) — on-disk corruption")
    for leaf_key, meta in leaves.items():
        want = int(np.prod(meta["shape"])) if meta["shape"] else 1
        if covered.get(leaf_key, 0) != want:
            errors.append(
                f"leaf '{leaf_key}': shard boxes cover "
                f"{covered.get(leaf_key, 0)}/{want} elements — "
                f"incomplete shard set")
    return dict(complete=not errors, step=manifest.get("step"),
                num_hosts=len(manifest.get("index_files", [])),
                shard_count=shard_count, payload_bytes=payload_bytes,
                manifest=manifest, errors=errors)


# ---------------------------------------------------------------------------
# filesystem barrier + retain-N garbage collection


def wait_for_files(paths: List[str], timeout_s: float,
                   what: str) -> None:
    """Poll until every path exists (the cross-host commit barrier that
    needs no collectives). Raises TimeoutError with an actionable
    message — the non-shared-filesystem failure mode must be a
    diagnosis, not a hang."""
    deadline = time.monotonic() + timeout_s
    missing = [p for p in paths if not os.path.exists(p)]
    while missing:
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"checkpoint barrier: {what} did not appear within "
                f"{timeout_s:.0f}s: {[os.path.basename(p) for p in missing]}"
                f" — is the checkpoint directory on a filesystem shared "
                f"by every host (GCS/NFS)? Per-shard checkpoints require "
                f"one.")
        time.sleep(0.05)
        missing = [p for p in missing if not os.path.exists(p)]


def collect_garbage(directory: str, retain: int) -> List[str]:
    """Delete committed checkpoints beyond the newest ``retain`` plus
    abandoned partial directories older than the newest committed step.
    NEVER deletes the last complete checkpoint (retain floor of 1), and
    never touches partial dirs newer than it (they may be mid-write).
    Returns the deleted paths. Caller gates to rank 0."""
    import shutil

    retain = max(1, int(retain))
    steps = list_steps(directory)
    complete = [(s, p) for s, p, ok in steps if ok]
    if not complete:
        return []
    newest_complete = complete[-1][0]
    doomed = [p for s, p in complete[:-retain]]
    doomed += [p for s, p, ok in steps
               if not ok and s < newest_complete]
    deleted = []
    for p in doomed:
        try:
            shutil.rmtree(p)
            deleted.append(p)
        except OSError:
            pass
    return deleted


def note_progress(directory: str, iteration: int) -> None:
    """Rank-0 heartbeat: the last iteration the (possibly doomed) run
    reached. Resume reads it to account restart-lost steps in the
    goodput metric."""
    atomic_write_json(os.path.join(directory, PROGRESS_NAME),
                      dict(iteration=int(iteration), wall_unix=time.time()))


def read_progress(directory: str) -> int:
    data = read_json(os.path.join(directory, PROGRESS_NAME))
    return int(data["iteration"]) if data and "iteration" in data else -1


def read_supervisor(directory: str) -> Optional[Dict[str, Any]]:
    """The supervisor's state record (scripts/supervise.py), when this
    run lives under one — restart counts and cumulative backoff
    downtime, which ``CheckpointManager.finalize`` folds into
    ``goodput_effective``."""
    return read_json(os.path.join(directory, SUPERVISOR_NAME))

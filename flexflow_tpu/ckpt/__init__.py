"""Elastic fault-tolerant checkpointing (v2, per-shard).

The subsystem the ROADMAP's "elastic, fault-tolerant training at scale"
item asked for, replacing the all-gather-to-rank-0 legacy path
(flexflow_tpu/checkpoint.py, kept for v1 compatibility) with:

* per-shard async checkpointing — each host writes only its
  addressable shards, off the critical path, with tmp+rename atomicity,
  per-shard CRC32s, and a manifest-last commit record
  (``sharded``/``manifest``/``manager``);
* preemption-aware elastic resume — reassemble global arrays from the
  shard index and re-place onto whatever strategy the surviving
  topology (re-)searched (``elastic``);
* a deterministic fault-injection harness (``FFS_FAULT``) exercised by
  the multihost dryrun's kill-and-resume legs (``faults``).

``FFModel.load_checkpoint`` auto-detects both formats; ``fit(
checkpoint_dir=..., checkpoint_every=..., resume=...)`` drives the
manager.
"""

from flexflow_tpu.ckpt.elastic import (load_manifest, plan_resume,
                                       strategy_matches_mesh,
                                       write_saved_strategy)
from flexflow_tpu.ckpt.faults import (FaultPlan, get_plan, io_check,
                                      step_hook)
from flexflow_tpu.ckpt.manager import CheckpointManager
from flexflow_tpu.ckpt.manifest import (collect_garbage, latest_complete,
                                        list_steps, resolve_step_dir,
                                        verify_step_dir)
from flexflow_tpu.ckpt.sharded import (load_sharded, save_sharded, snapshot,
                                       write_snapshot)

__all__ = [
    "CheckpointManager",
    "FaultPlan",
    "collect_garbage",
    "get_plan",
    "io_check",
    "latest_complete",
    "list_steps",
    "load_manifest",
    "load_sharded",
    "plan_resume",
    "resolve_step_dir",
    "save_sharded",
    "snapshot",
    "step_hook",
    "strategy_matches_mesh",
    "verify_step_dir",
    "write_saved_strategy",
    "write_snapshot",
]
